"""ParallelExecutor parity tests over an 8-device virtual CPU mesh.

Mirrors the reference's parallel_executor_test_base.py pattern: train the
same model single-device vs data-parallel and assert per-step loss parity
(test_dist_base.py check_with_place:502 uses the same contract).
"""
import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _build_model(seed=5):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = seed
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        label = layers.data("label", shape=[1])
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        pt.optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def _batches(n=8, bs=32):
    rng = np.random.RandomState(0)
    w = rng.randn(8, 1).astype(np.float32)
    return [(xb, xb @ w) for xb in
            (rng.randn(bs, 8).astype(np.float32) for _ in range(n))]


def test_eight_device_mesh_available():
    assert len(jax.devices()) >= 8


def test_data_parallel_loss_parity(mesh8):
    main, startup, loss = _build_model()
    batches = _batches()

    def train(mesh):
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace(), scope=scope)
        exe.run(startup)
        if mesh is None:
            runner = lambda f: exe.run(main, feed=f, fetch_list=[loss.name])
        else:
            pexe = pt.ParallelExecutor(main_program=main,
                                       loss_name=loss.name, scope=scope,
                                       mesh=mesh, place=pt.CPUPlace())
            runner = lambda f: pexe.run([loss.name], feed=f)
        return [float(np.asarray(runner({"x": xb, "label": yb})[0]))
                for xb, yb in batches]

    single = train(None)
    par = train(mesh8)
    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)


def test_parallel_executor_shards_batch(mesh8):
    """The feed is the global batch; each device must see bs/8 rows.
    Verified via the sharding of an intermediate fetched array."""
    main, startup, loss = _build_model()
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    exe.run(startup)
    pexe = pt.ParallelExecutor(main_program=main, loss_name=loss.name,
                               scope=scope, mesh=mesh8,
                               place=pt.CPUPlace())
    assert pexe.device_count == 8
    xb, yb = _batches(1)[0]
    lv, = pexe.run([loss.name], feed={"x": xb, "label": yb})
    assert np.isfinite(lv).all()
    # params stay replicated across the mesh
    w_name = main.all_parameters()[0].name
    w_val = scope.find_var(w_name)
    assert w_val.sharding.is_fully_replicated


def test_model_parallel_param_sharding(mesh8):
    """Tensor-parallel capability: a Parameter with a sharding spec is laid
    out across the mesh (replaces pserver param sharding,
    transpiler VarBlock:65)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        w_attr = pt.ParamAttr(name="tp_w", sharding=(None, "data"))
        y = layers.fc(x, size=32, param_attr=w_attr, bias_attr=False)
        loss = layers.mean(y)
        pt.optimizer.SGD(0.1).minimize(loss)
    scope = pt.Scope()
    pexe = pt.ParallelExecutor(main_program=main, loss_name=loss.name,
                               scope=scope, mesh=mesh8,
                               place=pt.CPUPlace())
    exe = pt.Executor(pt.CPUPlace(), scope=scope, mesh=mesh8)
    exe.run(startup)
    xb = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    lv, = pexe.run([loss.name], feed={"x": xb})
    assert np.isfinite(lv).all()
    w_val = scope.find_var("tp_w")
    # output-dim sharded over the 8 devices
    assert not w_val.sharding.is_fully_replicated


def test_bert_pretrain_data_parallel_parity():
    """BASELINE config 5: BERT pretraining under data-parallel
    ParallelExecutor on the 8-device mesh, loss parity vs single device
    (the reference's parallel_executor_test_base contract on the
    dist_transformer-class model)."""
    from paddle_tpu import models

    def build():
        pt.reset_default_programs()
        pt.default_startup_program().random_seed = 7
        pt.default_main_program().random_seed = 7
        cfg = models.bert.BertConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
            intermediate_size=64, max_position=32)
        feeds, total_loss, _ = models.bert.build_pretrain_net(
            cfg, seq_len=16)
        pt.optimizer.Adam(learning_rate=1e-3).minimize(total_loss)
        feed = models.bert.make_fake_batch(cfg, 8, 16, max_preds=4, seed=0)
        return total_loss, feed

    loss, feed = build()
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(pt.default_startup_program())
    ref = [float(exe.run(pt.default_main_program(), feed=feed,
                         fetch_list=[loss])[0]) for _ in range(4)]

    loss2, feed2 = build()
    scope = pt.Scope()
    exe2 = pt.Executor(pt.CPUPlace(), scope=scope)
    exe2.run(pt.default_startup_program())
    pexe = pt.ParallelExecutor(use_cuda=False, loss_name=loss2.name,
                               scope=scope, place=pt.CPUPlace())
    par = [float(np.asarray(pexe.run(feed=feed2,
                                     fetch_list=[loss2.name])[0]).mean())
           for _ in range(4)]
    np.testing.assert_allclose(par, ref, rtol=2e-4, atol=1e-5)
    assert par[-1] < par[0]
