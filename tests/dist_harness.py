"""Shared multi-process spawn harness for distributed tests (the
reference's test_dist_base.py:227-291 free-port + subprocess machinery,
extracted so every dist test uses one copy)."""
import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn_workers(script: str, world: int, tmp_path, timeout: int = 300,
                  coordinator: str = None):
    """Run `tests/<script>` in `world` rank processes sharing a fresh
    coordinator port; each rank writes JSON to its own out file.  Returns
    the parsed results sorted by rank.  Asserts every worker exits 0.
    Pass `coordinator` ("host:port") to point workers at a service the
    TEST process owns (e.g. a task master / fleet aggregator) instead of
    a fresh jax.distributed rendezvous port."""
    if coordinator is None:
        coordinator = f"127.0.0.1:{free_port()}"
    procs, outs = [], []
    for rank in range(world):
        out = str(tmp_path / f"{script}.{rank}.json")
        outs.append(out)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)      # one CPU device per process
        env.pop("PYTHONPATH", None)     # axon plugin quirk: never set it
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", script),
             coordinator, str(world), str(rank), out],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout.decode(errors="replace"))
    for rc, log in zip((p.returncode for p in procs), logs):
        assert rc == 0, f"{script} worker failed rc={rc}:\n{log[-3000:]}"
    return sorted((json.load(open(o)) for o in outs),
                  key=lambda r: r["rank"])
