"""Debug plane: per-op NaN localization (ref operator.cc:829 under
FLAGS_check_nan_inf) and the device-trace profiler wiring
(ref platform/device_tracer.cc:41 -> jax.profiler xplane)."""
import glob
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags, profiler
from paddle_tpu.core.enforce import EnforceNotMet


def test_per_op_nan_check_names_offending_op():
    """A deliberately-NaN program (log of a negative) is localized to the
    producing op, not just the fetch."""
    x = layers.data("x", [4], dtype="float32")
    y = layers.log(x)                # NaN for negative inputs
    z = layers.scale(y, scale=2.0)   # NaN propagates
    out = layers.mean(z)
    exe = pt.Executor(pt.CPUPlace())
    flags.set_flag("check_nan_inf_per_op", True)
    try:
        with pytest.raises(EnforceNotMet) as ei:
            exe.run(pt.default_main_program(),
                    feed={"x": np.array([[1., -1., 2., 3.]], "float32")},
                    fetch_list=[out])
        assert "'log'" in str(ei.value)
    finally:
        flags.set_flag("check_nan_inf_per_op", False)


def test_per_op_nan_check_names_chaos_poisoned_producer():
    """An ``executor.var.<name>`` chaos poison is visible to the per-op
    localizer AT the poisoned producer — not first at a downstream
    consumer.  (The poison pokes the executor env; the localizer reads
    the op's outs, so the two views must stay in sync.)"""
    x = layers.data("x", [4], dtype="float32")
    y = layers.scale(x, scale=2.0)
    z = layers.scale(y, scale=3.0)
    out = layers.mean(z)
    exe = pt.Executor(pt.CPUPlace())
    flags.set_flag("check_nan_inf_per_op", True)
    flags.set_flag("chaos_spec", f"executor.var.{y.name}=nan:1.0")
    try:
        with pytest.raises(EnforceNotMet) as ei:
            exe.run(pt.default_main_program(),
                    feed={"x": np.ones((2, 4), "float32")},
                    fetch_list=[out])
        msg = str(ei.value)
        assert repr(y.name) in msg          # the poisoned producer...
        assert repr(z.name) not in msg      # ...not its consumer
    finally:
        flags.set_flag("check_nan_inf_per_op", False)
        flags.set_flag("chaos_spec", "")


def test_per_op_nan_check_passes_clean_program():
    x = layers.data("x", [4], dtype="float32")
    out = layers.mean(layers.exp(x))
    exe = pt.Executor(pt.CPUPlace())
    flags.set_flag("check_nan_inf_per_op", True)
    try:
        v, = exe.run(pt.default_main_program(),
                     feed={"x": np.ones((2, 4), "float32")},
                     fetch_list=[out])
        assert np.isfinite(v).all()
    finally:
        flags.set_flag("check_nan_inf_per_op", False)


def test_fetch_level_nan_check_still_works():
    x = layers.data("x", [4], dtype="float32")
    out = layers.mean(layers.log(x))
    exe = pt.Executor(pt.CPUPlace())
    flags.set_flag("check_nan_inf", True)
    try:
        with pytest.raises(EnforceNotMet):
            exe.run(pt.default_main_program(),
                    feed={"x": -np.ones((2, 4), "float32")},
                    fetch_list=[out])
    finally:
        flags.set_flag("check_nan_inf", False)


def test_device_trace_capture(tmp_path):
    """enable_profiler(trace_dir) captures an xplane trace of device work
    (the CUPTI DeviceTracer capability)."""
    trace_dir = str(tmp_path / "trace")
    x = layers.data("x", [8], dtype="float32")
    out = layers.mean(layers.fc(x, size=8))
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    profiler.enable_profiler(trace_dir)
    try:
        exe.run(pt.default_main_program(),
                feed={"x": np.ones((4, 8), "float32")}, fetch_list=[out])
    finally:
        profiler.disable_profiler(trace_dir_used=True)
    produced = glob.glob(os.path.join(trace_dir, "**", "*"),
                         recursive=True)
    assert any(p.endswith(".xplane.pb") or "trace" in os.path.basename(p)
               for p in produced if os.path.isfile(p)), produced


def test_host_event_summary_and_chrome_trace(tmp_path):
    profiler.reset_profiler()
    profiler.enable_profiler()
    with profiler.RecordEvent("my_scope"):
        pass
    profiler.disable_profiler()
    s = profiler.summary()
    assert "my_scope" in s
    path = str(tmp_path / "trace.json")
    profiler.export_chrome_trace(path)
    import json
    trace = json.load(open(path))
    assert any(e["name"] == "my_scope" for e in trace["traceEvents"])
