"""ContextParallelTranspiler: ring-attention sequence sharding as a
program transformation — loss/grad parity of the SAME Program trained on
one device vs sequence-sharded over the 8-device mesh (the dp analogue
lives in tests/test_dist_transpiler.py, tp in test_tensor_parallel.py;
the reference has no cp at all — SURVEY §5 long-context)."""
import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.core.place import make_mesh

T, D, V, B, HEADS = 64, 32, 128, 4, 4


def build(seed=3):
    pt.reset_default_programs()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    main.random_seed = seed
    startup.random_seed = seed
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T, n_layer=2,
        n_head=HEADS, d_model=D, d_inner=64, dropout=0.0)
    feeds, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=T, fused_attention=True, fused_head=False)
    pt.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    return main, startup, avg_cost


def make_feed():
    rng = np.random.RandomState(0)
    toks = rng.randint(0, V, (B, T)).astype("int64")
    return {"tokens": toks, "labels": np.roll(toks, -1, 1)}


def test_transpile_marks_and_shards():
    main, startup, loss = build()
    t = pt.transpiler.ContextParallelTranspiler()
    assigned = t.transpile(main, cp_degree=8)
    assert main._dist_cp_axis == "cp"
    assert main._dist_feed_shard_dim == 1
    assert main._dist_spmd_axis == "cp"
    # the [T, D] sinusoid table is sequence-sharded
    assert any(spec[0] == "cp" for spec in assigned.values()), assigned
    ops = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in ops
    # markers survive serde (clone/save/load)
    rt = pt.Program.from_dict(main.to_dict())
    assert rt._dist_cp_axis == "cp" and rt._dist_feed_shard_dim == 1
    pos = [v for v in rt.global_block().vars.values()
           if getattr(v, "sharding", None) is not None]
    assert pos, "sharding annotations lost in serde"


def test_context_parallel_matches_single_device():
    feed = make_feed()
    main, startup, loss = build()
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    ref = []
    for _ in range(4):
        out, = exe.run(main, feed=feed, fetch_list=[loss])
        ref.append(float(np.asarray(out).ravel()[0]))

    main2, startup2, loss2 = build()
    t = pt.transpiler.ContextParallelTranspiler()
    t.transpile(main2, cp_degree=8)
    mesh = make_mesh((8,), ("cp",))
    exe2 = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe2.run(startup2)
    cp = []
    for _ in range(4):
        out, = exe2.run(main2, feed=feed, fetch_list=[loss2])
        # per-shard means over equal token counts -> global mean
        assert np.asarray(out).shape[0] == 8
        cp.append(float(np.mean(np.asarray(out))))
    np.testing.assert_allclose(cp, ref, rtol=2e-4, atol=1e-5), (ref, cp)
    assert cp[-1] < cp[0]


def test_indivisible_seq_len_raises():
    main, startup, loss = build()
    t = pt.transpiler.ContextParallelTranspiler()
    with pytest.raises(pt.core.enforce.InvalidArgumentError):
        t.transpile(main, cp_degree=7)
