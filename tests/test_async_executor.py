"""AsyncExecutor: multithread CTR training over sharded MultiSlot text
files (ref framework/async_executor.h:60, data_feed.h:224,
python async_executor.py; test pattern: unittests/test_async_executor.py)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

rng = np.random.RandomState(3)

VOCAB = 50
SLOT_W = 4


def write_shards(d, n_files=4, lines_per_file=64):
    """MultiSlot lines: sparse id slot (width<=4), dense label slot."""
    files = []
    for fi in range(n_files):
        path = os.path.join(d, f"part-{fi}")
        with open(path, "w") as f:
            for _ in range(lines_per_file):
                n_ids = rng.randint(1, SLOT_W + 1)
                ids = rng.randint(0, VOCAB, n_ids)
                # learnable structure: label = parity of first id
                label = ids[0] % 2
                f.write(f"{n_ids} " + " ".join(map(str, ids))
                        + f" 1 {label}\n")
        files.append(path)
    return files


def build_ctr_program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", [SLOT_W], dtype="int64")
        label = layers.data("click", [1], dtype="float32")
        emb = layers.embedding(ids, size=[VOCAB, 8])
        pooled = layers.sequence_pool(emb, "sum")
        predict = layers.fc(pooled, size=1, act="sigmoid")
        cost = layers.log_loss(predict, label)
        avg_cost = layers.mean(cost)
        pt.optimizer.SGD(learning_rate=0.5).minimize(avg_cost)
    return main, startup, avg_cost


def test_data_feed_desc_parses_multislot():
    feed = pt.DataFeedDesc([pt.Slot("ids", "uint64", dim=4),
                            pt.Slot("click", "float", is_dense=True,
                                    dim=1)], batch_size=8)
    row = feed.parse_line("3 7 8 9 1 1.0")
    assert row["ids"].tolist() == [7, 8, 9, 0]
    assert row["click"].tolist() == [1.0]
    # unused slots are skipped but still consumed from the line
    feed.set_use_slots(["click"])
    row = feed.parse_line("3 7 8 9 1 0.0")
    assert set(row) == {"click"}


def test_async_executor_trains_multithreaded():
    with tempfile.TemporaryDirectory() as d:
        files = write_shards(d)
        main, startup, loss = build_ctr_program()
        feed = pt.DataFeedDesc([pt.Slot("ids", "uint64", dim=SLOT_W),
                                pt.Slot("click", "float", is_dense=True,
                                        dim=1)], batch_size=16)
        exe = pt.AsyncExecutor(pt.CPUPlace())
        exe.run_startup_program(startup)
        first = exe.run(main, feed, files, thread_num=4,
                        fetch=[loss.name])
        for _ in range(3):
            last = exe.run(main, feed, files, thread_num=4,
                           fetch=[loss.name])
        assert np.isfinite(first[loss.name])
        assert last[loss.name] < first[loss.name]


def test_async_executor_propagates_parse_errors():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bad")
        with open(path, "w") as f:
            f.write("2 7\n")          # truncated slot
        main, startup, loss = build_ctr_program()
        feed = pt.DataFeedDesc([pt.Slot("ids", "uint64", dim=SLOT_W),
                                pt.Slot("click", "float", is_dense=True,
                                        dim=1)], batch_size=4)
        exe = pt.AsyncExecutor(pt.CPUPlace())
        exe.run_startup_program(startup)
        with pytest.raises(pt.core.enforce.EnforceNotMet):
            exe.run(main, feed, [path], thread_num=2, fetch=[loss.name])


def test_async_executor_missing_file():
    main, startup, loss = build_ctr_program()
    feed = pt.DataFeedDesc([pt.Slot("ids", "uint64", dim=SLOT_W)])
    exe = pt.AsyncExecutor(pt.CPUPlace())
    with pytest.raises(pt.core.enforce.EnforceNotMet):
        exe.run(main, feed, ["/nonexistent/part-0"], thread_num=1,
                fetch=[])
