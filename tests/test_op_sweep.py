"""Whole-registry op sweep — the reference's per-op test contract
(unittests/op_test.py:132) applied to EVERY registered op.

Three tiers, mirroring the reference:
  1. every op is invoked with valid inputs and must produce
     finite, well-shaped outputs (the sweep below);
  2. ops with a `ref` get their outputs checked against numpy;
  3. ops in GRAD_CHECK get analytic-vs-finite-difference gradient
     checks through the program autodiff (OpTest.check_grad).

A coverage gate asserts every registered op is either swept here,
exempted with a reason (structural/collective/covered-elsewhere), or
carries a dedicated test file.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.framework.registry import registered_ops

from op_test import OpTest

rng = np.random.RandomState(1234)


def f32(*shape, scale=1.0, positive=False):
    a = rng.randn(*shape).astype("float32") * scale
    return np.abs(a) + 0.5 if positive else a


def i64(*shape, lo=0, hi=10):
    return rng.randint(lo, hi, shape).astype("int64")


# --------------------------------------------------------------------------
# spec table: op -> dict(inputs, attrs, outs, ref (optional), skip_finite)
# `inputs` values are callables (fresh data per run) or arrays.
# --------------------------------------------------------------------------

def unary(name, ref=None, positive=False, **attrs):
    return {"inputs": {"X": f32(2, 6, positive=positive, scale=0.8)},
            "attrs": attrs, "outs": ["Out"], "ref": ref}


def binary(name, ref=None, **attrs):
    return {"inputs": {"X": f32(2, 6), "Y": f32(2, 6)}, "attrs": attrs,
            "outs": ["Out"], "ref": ref}


def reduce(name, **attrs):
    return {"inputs": {"X": f32(2, 3, 4)}, "attrs": attrs, "outs": ["Out"]}


SPECS = {
    # --- unary math -------------------------------------------------------
    "abs": unary("abs", ref=lambda i: np.abs(i["X"])),
    "ceil": unary("ceil", ref=lambda i: np.ceil(i["X"])),
    "floor": unary("floor", ref=lambda i: np.floor(i["X"])),
    "round": unary("round"),
    "cos": unary("cos", ref=lambda i: np.cos(i["X"])),
    "sin": unary("sin", ref=lambda i: np.sin(i["X"])),
    "exp": unary("exp", ref=lambda i: np.exp(i["X"])),
    "erf": unary("erf"),
    "log": unary("log", positive=True,
                 ref=lambda i: np.log(i["X"])),
    "sqrt": unary("sqrt", positive=True,
                  ref=lambda i: np.sqrt(i["X"])),
    "rsqrt": unary("rsqrt", positive=True,
                   ref=lambda i: 1 / np.sqrt(i["X"])),
    "square": unary("square", ref=lambda i: i["X"] ** 2),
    "reciprocal": unary("reciprocal", positive=True,
                        ref=lambda i: 1 / i["X"]),
    "sign": unary("sign", ref=lambda i: np.sign(i["X"])),
    "sigmoid": unary("sigmoid",
                     ref=lambda i: 1 / (1 + np.exp(-i["X"]))),
    "logsigmoid": unary("logsigmoid"),
    "tanh": unary("tanh", ref=lambda i: np.tanh(i["X"])),
    "tanh_shrink": unary("tanh_shrink",
                         ref=lambda i: i["X"] - np.tanh(i["X"])),
    "softplus": unary("softplus"),
    "softsign": unary("softsign",
                      ref=lambda i: i["X"] / (1 + np.abs(i["X"]))),
    "relu": unary("relu", ref=lambda i: np.maximum(i["X"], 0)),
    "relu6": unary("relu6",
                   ref=lambda i: np.clip(i["X"], 0, 6)),
    "leaky_relu": unary("leaky_relu", alpha=0.1),
    "elu": unary("elu"),
    "selu": unary("selu"),
    "gelu": unary("gelu"),
    "brelu": unary("brelu", t_min=-1.0, t_max=1.0),
    "soft_relu": unary("soft_relu"),
    "hard_shrink": unary("hard_shrink", threshold=0.5),
    "hard_sigmoid": unary("hard_sigmoid"),
    "hard_swish": unary("hard_swish"),
    "swish": unary("swish"),
    "mish": unary("mish"),
    "stanh": unary("stanh"),
    "thresholded_relu": unary("thresholded_relu", threshold=0.3),
    "softshrink": unary("softshrink", **{"lambda": 0.3}),
    "maxout": {"inputs": {"X": f32(2, 8, 3, 3)}, "attrs": {"groups": 2},
               "outs": ["Out"]},
    "prelu": {"inputs": {"X": f32(2, 6), "Alpha": f32(1, scale=0.1)},
              "attrs": {"mode": "all"}, "outs": ["Out"]},
    "pow": unary("pow", factor=2.0),
    "clip": unary("clip", min=-0.5, max=0.5,
                  ref=lambda i: np.clip(i["X"], -0.5, 0.5)),
    "clip_by_norm": unary("clip_by_norm", max_norm=1.0),
    "scale": unary("scale", scale=2.0, bias=1.0,
                   ref=lambda i: i["X"] * 2 + 1),
    "cast": {"inputs": {"X": f32(2, 3)},
             "attrs": {"out_dtype": "float32"}, "outs": ["Out"]},
    "isfinite": {"inputs": {"X": f32(2, 3)}, "attrs": {}, "outs": ["Out"],
                 "skip_finite": True},
    "is_empty": {"inputs": {"X": f32(2, 3)}, "attrs": {}, "outs": ["Out"],
                 "skip_finite": True},
    "logical_not": {"inputs": {"X": i64(2, 3, hi=2).astype(bool)},
                    "attrs": {}, "outs": ["Out"], "skip_finite": True},
    "increment": unary("increment", step=1.0),
    "shape": {"inputs": {"Input": f32(2, 3)}, "attrs": {},
              "outs": ["Out"], "skip_finite": True},

    # --- binary / broadcast ----------------------------------------------
    "elementwise_add": binary("a", ref=lambda i: i["X"] + i["Y"]),
    "elementwise_sub": binary("s", ref=lambda i: i["X"] - i["Y"]),
    "elementwise_mul": binary("m", ref=lambda i: i["X"] * i["Y"]),
    "elementwise_div": {"inputs": {"X": f32(2, 6),
                                   "Y": f32(2, 6, positive=True)},
                        "attrs": {}, "outs": ["Out"],
                        "ref": lambda i: i["X"] / i["Y"]},
    "elementwise_max": binary("x", ref=lambda i: np.maximum(i["X"], i["Y"])),
    "elementwise_min": binary("n", ref=lambda i: np.minimum(i["X"], i["Y"])),
    "elementwise_pow": {"inputs": {"X": f32(2, 6, positive=True),
                                   "Y": f32(2, 6, scale=0.3)},
                        "attrs": {}, "outs": ["Out"]},
    "elementwise_mod": {"inputs": {"X": i64(2, 3, lo=1, hi=20),
                                   "Y": i64(2, 3, lo=1, hi=5)},
                        "attrs": {}, "outs": ["Out"], "skip_finite": True},
    "elementwise_floordiv": {"inputs": {"X": i64(2, 3, lo=1, hi=20),
                                        "Y": i64(2, 3, lo=1, hi=5)},
                             "attrs": {}, "outs": ["Out"],
                             "skip_finite": True},
    "minus": binary("minus", ref=lambda i: i["X"] - i["Y"]),
    "less_than": {**binary("lt"), "skip_finite": True},
    "less_equal": {**binary("le"), "skip_finite": True},
    "greater_than": {**binary("gt"), "skip_finite": True},
    "greater_equal": {**binary("ge"), "skip_finite": True},
    "equal": {**binary("eq"), "skip_finite": True},
    "not_equal": {**binary("ne"), "skip_finite": True},
    "logical_and": {"inputs": {"X": i64(2, 3, hi=2).astype(bool),
                               "Y": i64(2, 3, hi=2).astype(bool)},
                    "attrs": {}, "outs": ["Out"], "skip_finite": True},
    "logical_or": {"inputs": {"X": i64(2, 3, hi=2).astype(bool),
                              "Y": i64(2, 3, hi=2).astype(bool)},
                   "attrs": {}, "outs": ["Out"], "skip_finite": True},
    "logical_xor": {"inputs": {"X": i64(2, 3, hi=2).astype(bool),
                               "Y": i64(2, 3, hi=2).astype(bool)},
                    "attrs": {}, "outs": ["Out"], "skip_finite": True},

    # --- reductions -------------------------------------------------------
    "reduce_sum": {**reduce("rs", dim=[1]),
                   "ref": lambda i: i["X"].sum(1)},
    "reduce_mean": {**reduce("rm", dim=[1]),
                    "ref": lambda i: i["X"].mean(1)},
    "reduce_max": {**reduce("rx", dim=[1]),
                   "ref": lambda i: i["X"].max(1)},
    "reduce_min": {**reduce("rn", dim=[1]),
                   "ref": lambda i: i["X"].min(1)},
    "reduce_prod": reduce("rp", dim=[2]),
    "reduce_all": {"inputs": {"X": i64(2, 3, hi=2).astype(bool)},
                   "attrs": {"reduce_all": True}, "outs": ["Out"],
                   "skip_finite": True},
    "reduce_any": {"inputs": {"X": i64(2, 3, hi=2).astype(bool)},
                   "attrs": {"reduce_all": True}, "outs": ["Out"],
                   "skip_finite": True},
    "mean": {"inputs": {"X": f32(2, 6)}, "attrs": {}, "outs": ["Out"],
             "ref": lambda i: i["X"].mean()},
    "sum": {"inputs": {"X": [f32(2, 3), f32(2, 3)]}, "attrs": {},
            "outs": ["Out"]},
    "cumsum": {"inputs": {"X": f32(2, 5)}, "attrs": {"axis": 1},
               "outs": ["Out"], "ref": lambda i: i["X"].cumsum(1)},
    "norm": {"inputs": {"X": f32(2, 6)}, "attrs": {"axis": 1},
             "outs": ["Out", "Norm"]},
    "l1_norm": {"inputs": {"X": f32(2, 6)}, "attrs": {}, "outs": ["Out"],
                "ref": lambda i: np.abs(i["X"]).sum()},
    "squared_l2_norm": {"inputs": {"X": f32(2, 6)}, "attrs": {},
                        "outs": ["Out"],
                        "ref": lambda i: (i["X"] ** 2).sum()},
    "squared_l2_distance": {"inputs": {"X": f32(4, 6), "Y": f32(4, 6)},
                            "attrs": {}, "outs": ["Out", "sub_result"]},
    "dot":
        {"inputs": {"X": f32(3, 4), "Y": f32(3, 4)}, "attrs": {},
         "outs": ["Out"],
         "ref": lambda i: (i["X"] * i["Y"]).sum(-1, keepdims=True)},

    # --- matmul family ----------------------------------------------------
    "mul": {"inputs": {"X": f32(3, 4), "Y": f32(4, 5)}, "attrs": {},
            "outs": ["Out"], "ref": lambda i: i["X"] @ i["Y"]},
    "matmul": {"inputs": {"X": f32(2, 3, 4), "Y": f32(2, 4, 5)},
               "attrs": {}, "outs": ["Out"],
               "ref": lambda i: i["X"] @ i["Y"]},
    "bmm": {"inputs": {"X": f32(2, 3, 4), "Y": f32(2, 4, 5)},
            "attrs": {}, "outs": ["Out"], "ref": lambda i: i["X"] @ i["Y"]},
    "bilinear_tensor_product": {
        "inputs": {"X": f32(2, 3), "Y": f32(2, 4),
                   "Weight": f32(5, 3, 4)},
        "attrs": {}, "outs": ["Out"]},

    # --- shape / indexing -------------------------------------------------
    "reshape": {"inputs": {"X": f32(2, 6)}, "attrs": {"shape": [3, 4]},
                "outs": ["Out"], "ref": lambda i: i["X"].reshape(3, 4)},
    "reshape2": {"inputs": {"X": f32(2, 6)}, "attrs": {"shape": [3, 4]},
                 "outs": ["Out"]},
    "transpose": {"inputs": {"X": f32(2, 3, 4)},
                  "attrs": {"axis": [0, 2, 1]}, "outs": ["Out"],
                  "ref": lambda i: i["X"].transpose(0, 2, 1)},
    "transpose2": {"inputs": {"X": f32(2, 3, 4)},
                   "attrs": {"axis": [0, 2, 1]}, "outs": ["Out"]},
    "flatten": {"inputs": {"X": f32(2, 3, 4)}, "attrs": {"axis": 1},
                "outs": ["Out"]},
    "flatten2": {"inputs": {"X": f32(2, 3, 4)}, "attrs": {"axis": 1},
                 "outs": ["Out"]},
    "flatten_contiguous_range": {"inputs": {"X": f32(2, 3, 4)},
                                 "attrs": {"start_axis": 1,
                                           "stop_axis": 2},
                                 "outs": ["Out"]},
    "squeeze": {"inputs": {"X": f32(2, 1, 4)}, "attrs": {"axes": [1]},
                "outs": ["Out"]},
    "squeeze2": {"inputs": {"X": f32(2, 1, 4)}, "attrs": {"axes": [1]},
                 "outs": ["Out"]},
    "unsqueeze": {"inputs": {"X": f32(2, 4)}, "attrs": {"axes": [1]},
                  "outs": ["Out"]},
    "unsqueeze2": {"inputs": {"X": f32(2, 4)}, "attrs": {"axes": [1]},
                   "outs": ["Out"]},
    "stack": {"inputs": {"X": [f32(2, 3), f32(2, 3)]},
              "attrs": {"axis": 0}, "outs": ["Y"]},
    "unstack": {"inputs": {"X": f32(2, 3)}, "attrs": {"axis": 0},
                "outs": ["Y", "Y"]},
    "unbind": {"inputs": {"X": f32(2, 3)}, "attrs": {"axis": 0},
               "outs": ["Y", "Y"]},
    "concat": {"inputs": {"X": [f32(2, 3), f32(2, 3)]},
               "attrs": {"axis": 0}, "outs": ["Out"]},
    "split": {"inputs": {"X": f32(4, 3)}, "attrs": {"num": 2, "axis": 0,
                                                    "sections": []},
              "outs": ["Out", "Out"]},
    "slice": {"inputs": {"Input": f32(4, 5)},
              "attrs": {"axes": [1], "starts": [1], "ends": [3]},
              "outs": ["Out"], "ref": lambda i: i["Input"][:, 1:3]},
    "strided_slice": {"inputs": {"Input": f32(4, 6)},
                      "attrs": {"axes": [1], "starts": [0], "ends": [6],
                                "strides": [2]},
                      "outs": ["Out"]},
    "expand": {"inputs": {"X": f32(1, 3)},
               "attrs": {"expand_times": [2, 1]}, "outs": ["Out"]},
    "expand_as": {"inputs": {"X": f32(1, 3), "Y": f32(4, 3)},
                  "attrs": {}, "outs": ["Out"]},
    "tile": {"inputs": {"X": f32(1, 3)},
             "attrs": {"repeat_times": [2, 2]}, "outs": ["Out"]},
    "gather": {"inputs": {"X": f32(5, 3),
                          "Index": i64(3, hi=5)},
               "attrs": {}, "outs": ["Out"],
               "ref": lambda i: i["X"][i["Index"]]},
    "gather_nd": {"inputs": {"X": f32(3, 4),
                             "Index": i64(2, 2, hi=3)},
                  "attrs": {}, "outs": ["Out"]},
    "scatter": {"inputs": {"X": f32(5, 3), "Ids": i64(2, hi=5),
                           "Updates": f32(2, 3)},
                "attrs": {}, "outs": ["Out"]},
    "scatter_nd_add": {"inputs": {"X": f32(5, 3),
                                  "Index": i64(2, 1, hi=5),
                                  "Updates": f32(2, 3)},
                       "attrs": {}, "outs": ["Out"]},
    "multiplex": {"inputs": {"Ids": i64(3, 1, hi=2),
                             "X": [f32(3, 4), f32(3, 4)]},
                  "attrs": {}, "outs": ["Out"]},
    "where": {"inputs": {"Condition": i64(2, 3, hi=2).astype(bool),
                         "X": f32(2, 3), "Y": f32(2, 3)},
              "attrs": {}, "outs": ["Out"]},
    "where_index": {"inputs": {"Condition": i64(4, hi=2).astype(bool)},
                    "attrs": {}, "outs": ["Out"], "skip_finite": True},
    "arg_max": {"inputs": {"X": f32(3, 5)}, "attrs": {"axis": 1},
                "outs": ["Out"], "skip_finite": True,
                "ref": lambda i: i["X"].argmax(1)},
    "arg_min": {"inputs": {"X": f32(3, 5)}, "attrs": {"axis": 1},
                "outs": ["Out"], "skip_finite": True},
    "argsort": {"inputs": {"X": f32(3, 5)}, "attrs": {"axis": 1},
                "outs": ["Out", "Indices"], "skip_finite": True},
    "top_k": {"inputs": {"X": f32(3, 6)}, "attrs": {"k": 2},
              "outs": ["Out", "Indices"], "skip_finite": True},
    "one_hot": {"inputs": {"X": i64(4, 1, hi=5)}, "attrs": {"depth": 5},
                "outs": ["Out"]},
    "roll": {"inputs": {"X": f32(3, 4)},
             "attrs": {"shifts": [1], "axis": [1]}, "outs": ["Out"]},
    "flip": {"inputs": {"X": f32(3, 4)}, "attrs": {"axis": [1]},
             "outs": ["Out"], "ref": lambda i: i["X"][:, ::-1]},
    "reverse": {"inputs": {"X": f32(3, 4)}, "attrs": {"axis": [0]},
                "outs": ["Out"]},
    "crop": {"inputs": {"X": f32(4, 5)},
             "attrs": {"offsets": [1, 1], "shape": [2, 3]},
             "outs": ["Out"]},
    "pad": {"inputs": {"X": f32(2, 3)},
            "attrs": {"paddings": [1, 1, 0, 0], "pad_value": 0.0},
            "outs": ["Out"]},
    "pad2d": {"inputs": {"X": f32(1, 2, 3, 3)},
              "attrs": {"paddings": [1, 1, 1, 1]}, "outs": ["Out"]},
    "pad3d": {"inputs": {"X": f32(1, 2, 3, 3, 3)},
              "attrs": {"paddings": [1, 1, 1, 1, 1, 1]}, "outs": ["Out"]},
    "pad_constant_like": {"inputs": {"X": f32(4, 5), "Y": f32(2, 3)},
                          "attrs": {"pad_value": 0.0}, "outs": ["Out"]},
    "space_to_depth": {"inputs": {"X": f32(1, 2, 4, 4)},
                       "attrs": {"blocksize": 2}, "outs": ["Out"]},
    "pixel_shuffle": {"inputs": {"X": f32(1, 8, 3, 3)},
                      "attrs": {"upscale_factor": 2}, "outs": ["Out"]},
    "shard_index": {"inputs": {"X": i64(4, 1, hi=16)},
                    "attrs": {"index_num": 16, "nshards": 2,
                              "shard_id": 0, "ignore_value": -1},
                    "outs": ["Out"], "skip_finite": True},

    # --- creation ---------------------------------------------------------
    "fill_constant": {"inputs": {},
                      "attrs": {"shape": [2, 3], "dtype": "float32",
                                "value": 1.5},
                      "outs": ["Out"],
                      "ref": lambda i: np.full((2, 3), 1.5, "float32")},
    "fill_constant_batch_size_like": {
        "inputs": {"Input": f32(4, 2)},
        "attrs": {"shape": [-1, 3], "dtype": "float32", "value": 2.0},
        "outs": ["Out"]},
    "fill_zeros_like": {"inputs": {"X": f32(2, 3)}, "attrs": {},
                        "outs": ["Out"]},
    "fill_any_like": {"inputs": {"X": f32(2, 3)}, "attrs": {"value": 3.0},
                      "outs": ["Out"]},
    "fill": {"inputs": {},
             "attrs": {"shape": [2, 2], "dtype": "float32",
                       "value": [1.0, 2.0, 3.0, 4.0]},
             "outs": ["Out"]},
    "assign": {"inputs": {"X": f32(2, 3)}, "attrs": {}, "outs": ["Out"]},
    "assign_value": {"inputs": {},
                     "attrs": {"shape": [2], "dtype": "float32",
                               "values": np.array([1., 2.], "float32")},
                     "outs": ["Out"]},
    "eye": {"inputs": {}, "attrs": {"num_rows": 3, "dtype": "float32"},
            "outs": ["Out"]},
    "linspace": {"inputs": {}, "attrs": {"start": 0.0, "stop": 1.0,
                                         "num": 5, "dtype": "float32"},
                 "outs": ["Out"]},
    "range": {"inputs": {"Start": np.zeros((1,), "float32"),
                         "End": np.full((1,), 5.0, "float32"),
                         "Step": np.ones((1,), "float32")},
              "attrs": {"len": 5}, "outs": ["Out"]},
    "uniform_random": {"inputs": {},
                       "attrs": {"shape": [2, 3], "min": -1.0,
                                 "max": 1.0, "dtype": "float32"},
                       "outs": ["Out"]},
    "gaussian_random": {"inputs": {},
                        "attrs": {"shape": [2, 3], "dtype": "float32"},
                        "outs": ["Out"]},
    "truncated_gaussian_random": {
        "inputs": {}, "attrs": {"shape": [2, 3], "dtype": "float32"},
        "outs": ["Out"]},
    "uniform_random_batch_size_like": {
        "inputs": {"Input": f32(4, 2)},
        "attrs": {"shape": [-1, 3], "dtype": "float32"}, "outs": ["Out"]},
    "gaussian_random_batch_size_like": {
        "inputs": {"Input": f32(4, 2)},
        "attrs": {"shape": [-1, 3], "dtype": "float32"}, "outs": ["Out"]},
    "sampling_id": {"inputs": {"X": np.full((3, 4), 0.25, "float32")},
                    "attrs": {}, "outs": ["Out"], "skip_finite": True},

    # --- nn ---------------------------------------------------------------
    "conv2d": {"inputs": {"Input": f32(1, 2, 6, 6),
                          "Filter": f32(3, 2, 3, 3, scale=0.3)},
               "attrs": {}, "outs": ["Output"]},
    "depthwise_conv2d": {"inputs": {"Input": f32(1, 2, 6, 6),
                                    "Filter": f32(2, 1, 3, 3)},
                         "attrs": {}, "outs": ["Output"]},
    "conv3d": {"inputs": {"Input": f32(1, 2, 4, 4, 4),
                          "Filter": f32(3, 2, 2, 2, 2)},
               "attrs": {}, "outs": ["Output"]},
    "conv2d_transpose": {"inputs": {"Input": f32(1, 2, 4, 4),
                                    "Filter": f32(2, 3, 3, 3)},
                         "attrs": {}, "outs": ["Output"]},
    "conv3d_transpose": {"inputs": {"Input": f32(1, 2, 3, 3, 3),
                                    "Filter": f32(2, 3, 2, 2, 2)},
                         "attrs": {}, "outs": ["Output"]},
    "pool2d": {"inputs": {"X": f32(1, 2, 4, 4)},
               "attrs": {"ksize": [2, 2], "pooling_type": "max"},
               "outs": ["Out"]},
    "pool3d": {"inputs": {"X": f32(1, 2, 4, 4, 4)},
               "attrs": {"ksize": [2, 2, 2], "pooling_type": "avg"},
               "outs": ["Out"]},
    "pool2d_with_index": {"inputs": {"X": f32(1, 2, 4, 4)},
                          "attrs": {"ksize": [2, 2]},
                          "outs": ["Out", "Mask"], "skip_finite": True},
    "unpool": {"inputs": {"X": f32(1, 1, 2, 2, positive=True),
                          "Indices": np.array(
                              [[[[0, 3], [12, 15]]]], "int64")},
               "attrs": {"unpooled_height": 4, "unpooled_width": 4},
               "outs": ["Out"]},
    "batch_norm": {"inputs": {"X": f32(4, 3), "Scale": f32(3),
                              "Bias": f32(3),
                              "Mean": np.zeros(3, "float32"),
                              "Variance": np.ones(3, "float32")},
                   "attrs": {"is_test": True}, "outs": ["Y"]},
    "instance_norm": {"inputs": {"X": f32(2, 3, 4, 4)},
                      "attrs": {}, "outs": ["Y"]},
    "layer_norm": {"inputs": {"X": f32(4, 6), "Scale": f32(6),
                              "Bias": f32(6)},
                   "attrs": {"begin_norm_axis": 1}, "outs": ["Y"]},
    "group_norm": {"inputs": {"X": f32(2, 4, 3, 3), "Scale": f32(4),
                              "Bias": f32(4)},
                   "attrs": {"groups": 2}, "outs": ["Y"]},
    "lrn": {"inputs": {"X": f32(1, 4, 3, 3)}, "attrs": {}, "outs": ["Out"]},
    "softmax": {"inputs": {"X": f32(3, 5)}, "attrs": {}, "outs": ["Out"]},
    "log_softmax": {"inputs": {"X": f32(3, 5)}, "attrs": {},
                    "outs": ["Out"]},
    "sequence_softmax": {"inputs": {"X": f32(3, 5)}, "attrs": {},
                         "outs": ["Out"]},
    "dropout": {"inputs": {"X": f32(3, 5)},
                "attrs": {"dropout_prob": 0.5, "is_test": True},
                "outs": ["Out"]},
    "lookup_table": {"inputs": {"W": f32(10, 4), "Ids": i64(3, 2)},
                     "attrs": {}, "outs": ["Out"]},
    "lookup_table_v2": {"inputs": {"W": f32(10, 4), "Ids": i64(3, 2)},
                        "attrs": {}, "outs": ["Out"]},
    "lookup_sparse_table": {"inputs": {"W": f32(10, 4),
                                       "Ids": i64(3)},
                            "attrs": {}, "outs": ["Out"]},
    "im2sequence": {
        "inputs": {"X": f32(1, 1, 4, 4)},
        "attrs": {"kernels": [2, 2], "strides": [2, 2],
                  "paddings": [0, 0, 0, 0]},
        "outs": ["Out"]},
    "affine_channel": {"inputs": {"X": f32(1, 3, 2, 2),
                                  "Scale": f32(3), "Bias": f32(3)},
                       "attrs": {}, "outs": ["Out"]},
    "affine_grid": {"inputs": {"Theta": f32(2, 2, 3, scale=0.3)},
                    "attrs": {"output_shape": [2, 1, 4, 4]},
                    "outs": ["Output"]},
    "grid_sampler": {"inputs": {"X": f32(1, 2, 4, 4),
                                "Grid": f32(1, 3, 3, 2, scale=0.4)},
                     "attrs": {}, "outs": ["Output"]},
    "interpolate": {"inputs": {"X": f32(1, 2, 4, 4)},
                    "attrs": {"out_h": 8, "out_w": 8,
                              "interp_method": "bilinear"},
                    "outs": ["Out"]},
    "bilinear_interp": {"inputs": {"X": f32(1, 2, 4, 4)},
                        "attrs": {"out_h": 8, "out_w": 8},
                        "outs": ["Out"]},
    "nearest_interp": {"inputs": {"X": f32(1, 2, 4, 4)},
                       "attrs": {"out_h": 8, "out_w": 8},
                       "outs": ["Out"]},
    "row_conv": {"inputs": {"X": f32(2, 5, 3),
                            "Filter": f32(3, 3, scale=0.3)},
                 "attrs": {}, "outs": ["Out"]},
    "add_position_encoding": {"inputs": {"X": f32(2, 5, 4)},
                              "attrs": {}, "outs": ["Out"]},
    "cos_sim": {"inputs": {"X": f32(3, 4), "Y": f32(3, 4)},
                "attrs": {}, "outs": ["Out"]},
    "spp": {"inputs": {"X": f32(1, 2, 4, 4)},
            "attrs": {"pyramid_height": 2}, "outs": ["Out"]},
    "shuffle_channel": {"inputs": {"X": f32(1, 4, 2, 2)},
                        "attrs": {"group": 2}, "outs": ["Out"]},
    "conv_shift": {"inputs": {"X": f32(2, 6), "Y": f32(2, 3)},
                   "attrs": {}, "outs": ["Out"]},
    "similarity_focus": {"inputs": {"X": f32(1, 2, 3, 3)},
                         "attrs": {"axis": 1, "indexes": [0]},
                         "outs": ["Out"]},
    "random_crop": {"inputs": {"X": f32(1, 2, 6, 6)},
                    "attrs": {"shape": [4, 4]}, "outs": ["Out"]},
    "sequence_conv": {"inputs": {"X": f32(2, 5, 3),
                                 "Filter": f32(9, 4)},
                      "attrs": {"contextLength": 3, "contextStart": -1},
                      "outs": ["Out"]},

    # --- RNN --------------------------------------------------------------
    "lstm": {"inputs": {"Input": f32(2, 4, 8), "Weight": f32(2, 8)},
             "attrs": {}, "outs": ["Hidden", "LastH", "LastC"]},
    "gru": {"inputs": {"Input": f32(2, 4, 6), "Weight": f32(2, 6)},
            "attrs": {}, "outs": ["Hidden", "LastH"]},
    "lstm_unit": {"inputs": {"X": f32(3, 8), "C_prev": f32(3, 2)},
                  "attrs": {}, "outs": ["C", "H"]},
    "gru_unit": {"inputs": {"Input": f32(3, 6), "HiddenPrev": f32(3, 2),
                            "Weight": f32(2, 6)},
                 "attrs": {}, "outs": ["Hidden"]},
    "lstmp": {"inputs": {"Input": f32(2, 4, 8), "Weight": f32(3, 8),
                         "ProjWeight": f32(2, 3)},
              "attrs": {}, "outs": ["Projection", "LastH", "LastC"]},
    "cudnn_lstm": {"inputs": {"Input": f32(2, 4, 3),
                              "W": f32(3 * 4 * 5 + 5 * 4 * 5 + 4 * 5)},
                   "attrs": {"hidden_size": 5, "num_layers": 1},
                   "outs": ["Out"]},

    # --- losses / metrics -------------------------------------------------
    "cross_entropy": {"inputs": {
        "X": np.full((3, 4), 0.25, "float32"), "Label": i64(3, 1, hi=4)},
        "attrs": {}, "outs": ["Y"]},
    "softmax_with_cross_entropy": {"inputs": {
        "Logits": f32(3, 5), "Label": i64(3, 1, hi=5)},
        "attrs": {}, "outs": ["Loss"]},
    "sigmoid_cross_entropy_with_logits": {"inputs": {
        "X": f32(3, 4), "Label": i64(3, 4, hi=2).astype("float32")},
        "attrs": {}, "outs": ["Out"]},
    "bpr_loss": {"inputs": {"X": np.abs(f32(3, 4)) + 0.1,
                            "Label": i64(3, 1, hi=4)},
                 "attrs": {}, "outs": ["Y"]},
    "hinge_loss": {"inputs": {"Logits": f32(4, 1),
                              "Labels": i64(4, 1, hi=2).astype("float32")},
                   "attrs": {}, "outs": ["Loss"]},
    "huber_loss": {"inputs": {"X": f32(4, 1), "Y": f32(4, 1)},
                   "attrs": {"delta": 1.0}, "outs": ["Out"]},
    "modified_huber_loss": {"inputs": {
        "X": f32(4, 1), "Y": i64(4, 1, hi=2).astype("float32")},
        "attrs": {}, "outs": ["Out"]},
    "smooth_l1_loss": {"inputs": {"X": f32(4, 3), "Y": f32(4, 3)},
                       "attrs": {}, "outs": ["Out", "Diff"]},
    "log_loss": {"inputs": {
        "Predicted": np.random.RandomState(0).rand(4, 1).astype(
            "float32") * 0.8 + 0.1,
        "Labels": i64(4, 1, hi=2).astype("float32")},
        "attrs": {}, "outs": ["Loss"]},
    "margin_rank_loss": {"inputs": {"X1": f32(4, 1), "X2": f32(4, 1),
                                    "Label": np.sign(f32(4, 1))},
                         "attrs": {}, "outs": ["Out"]},
    "rank_loss": {"inputs": {"Left": f32(4, 1), "Right": f32(4, 1),
                             "Label": i64(4, 1, hi=2).astype("float32")},
                  "attrs": {}, "outs": ["Out"]},
    "mse_loss": {"inputs": {"X": f32(4, 3), "Label": f32(4, 3)},
                 "attrs": {}, "outs": ["Out"]},
    "square_error_cost": {"inputs": {"X": f32(4, 3), "Label": f32(4, 3)},
                          "attrs": {}, "outs": ["Out"]},
    "kldiv_loss": {"inputs": {
        "X": np.log(np.random.RandomState(1).rand(3, 4).astype(
            "float32") + 0.1),
        "Target": np.random.RandomState(2).rand(3, 4).astype("float32")},
        "attrs": {"reduction": "mean"}, "outs": ["Loss"]},
    "npair_loss": {"inputs": {"Anchor": f32(3, 4), "Positive": f32(3, 4),
                              "Labels": i64(3, hi=3).astype("float32")},
                   "attrs": {}, "outs": ["Out"]},
    "label_smooth": {"inputs": {"X": np.eye(3, 4, dtype="float32")},
                     "attrs": {"epsilon": 0.1}, "outs": ["Out"]},
    "teacher_student_sigmoid_loss": {
        "inputs": {"X": f32(4, 1),
                   "Label": np.random.RandomState(3).rand(4, 1).astype(
                       "float32")},
        "attrs": {}, "outs": ["Y"]},
    "accuracy": {"inputs": {"Out": np.full((4, 3), 0.33, "float32"),
                            "Indices": i64(4, 1, hi=3),
                            "Label": i64(4, 1, hi=3)},
                 "attrs": {}, "outs": ["Accuracy"]},
    "auc": {"inputs": {
        "Predict": np.random.RandomState(4).rand(6, 2).astype("float32"),
        "Label": i64(6, 1, hi=2),
        "StatPos": np.zeros((4096,), "float32"),
        "StatNeg": np.zeros((4096,), "float32")},
        "attrs": {}, "outs": ["AUC"]},
    "precision_recall": {"inputs": {
        "MaxProbs": np.random.RandomState(5).rand(4, 1).astype("float32"),
        "Indices": i64(4, 1, hi=2), "Labels": i64(4, 1, hi=2),
        "StatesInfo": np.zeros((2, 3), "float32")},
        "attrs": {"class_number": 2}, "outs": ["BatchMetrics"]},
    "positive_negative_pair": {"inputs": {
        "Score": np.random.RandomState(6).rand(6, 1).astype("float32"),
        "Label": i64(6, 1, hi=2).astype("float32"),
        "QueryID": i64(6, 1, hi=2)},
        "attrs": {}, "outs": ["PositivePair", "NegativePair"]},
    "mean_iou": {"inputs": {"Predictions": i64(8, hi=3),
                            "Labels": i64(8, hi=3)},
                 "attrs": {"num_classes": 3},
                 "outs": ["OutMeanIou"]},
    "edit_distance": {"inputs": {"Hyps": i64(2, 4, hi=5),
                                 "Refs": i64(2, 4, hi=5)},
                      "attrs": {}, "outs": ["Out"]},
    "chunk_eval": {"inputs": {"Inference": i64(1, 6, hi=3),
                              "Label": i64(1, 6, hi=3)},
                   "attrs": {"num_chunk_types": 1},
                   "outs": ["Precision", "Recall"]},
    "nce": {"inputs": {"Input": f32(3, 4), "Weight": f32(8, 4),
                       "Label": i64(3, 1, hi=8)},
            "attrs": {"num_total_classes": 8, "num_neg_samples": 3},
            "outs": ["Cost"]},
    "hierarchical_sigmoid": {"inputs": {"X": f32(3, 4),
                                        "W": f32(7, 4),
                                        "Label": i64(3, hi=8)},
                             "attrs": {"num_classes": 8}, "outs": ["Out"]},
    "linear_chain_crf": {"inputs": {"Emission": f32(2, 4, 3),
                                    "Transition": f32(5, 3),
                                    "Label": i64(2, 4, hi=3)},
                         "attrs": {}, "outs": ["LogLikelihood"]},
    "crf_decoding": {"inputs": {"Emission": f32(2, 4, 3),
                                "Transition": f32(5, 3)},
                     "attrs": {}, "outs": ["ViterbiPath"],
                     "skip_finite": True},
    "warpctc": {"inputs": {"Logits": f32(2, 5, 4),
                           "Label": i64(2, 2, lo=1, hi=4)},
                "attrs": {}, "outs": ["Loss"]},
    "ctc_align": {"inputs": {"Input": i64(2, 6, hi=3)},
                  "attrs": {}, "outs": ["Output"], "skip_finite": True},

    # --- sequence ---------------------------------------------------------
    "sequence_concat": {"inputs": {"X": [f32(2, 3, 4), f32(2, 2, 4)]},
                        "attrs": {}, "outs": ["Out"]},
    "sequence_enumerate": {"inputs": {"X": i64(2, 5, hi=9)},
                           "attrs": {"win_size": 2}, "outs": ["Out"],
                           "skip_finite": True},
    "sequence_erase": {"inputs": {"X": i64(2, 5, hi=5)},
                       "attrs": {"tokens": [0]}, "outs": ["Out"],
                       "skip_finite": True},
    "sequence_expand": {"inputs": {"X": f32(2, 3), "Y": f32(2, 3)},
                        "attrs": {}, "outs": ["Out"]},
    "sequence_expand_as": {"inputs": {"X": f32(2, 3), "Y": f32(2, 3)},
                           "attrs": {}, "outs": ["Out"]},
    "sequence_mask": {"inputs": {"X": i64(3, lo=1, hi=5)},
                      "attrs": {"maxlen": 5}, "outs": ["Y"],
                      "skip_finite": True},
    "sequence_pad": {"inputs": {"X": f32(2, 4, 3),
                                "Length": i64(2, lo=1, hi=4)},
                     "attrs": {"padded_length": 5}, "outs": ["Out"]},
    "sequence_unpad": {"inputs": {"X": f32(2, 5, 3),
                                  "Length": i64(2, lo=1, hi=5)},
                       "attrs": {}, "outs": ["Out"]},
    "sequence_pool": {"inputs": {"X": f32(2, 4, 3)},
                      "attrs": {"pooltype": "SUM"}, "outs": ["Out"]},
    "sequence_reshape": {"inputs": {"X": f32(2, 4, 6)},
                         "attrs": {"new_dim": 3}, "outs": ["Out"]},
    "sequence_reverse": {"inputs": {"X": f32(2, 4, 3)},
                         "attrs": {}, "outs": ["Y"]},
    "sequence_scatter": {"inputs": {"X": f32(2, 6),
                                    "Ids": i64(2, 3, hi=6),
                                    "Updates": f32(2, 3)},
                         "attrs": {}, "outs": ["Out"]},
    "sequence_slice": {"inputs": {"X": f32(3, 5, 2)},
                       "attrs": {"offset": 1, "length": 2},
                       "outs": ["Out"]},
    "lod_reset": {"inputs": {"X": f32(4, 3)}, "attrs": {"target_lod": []},
                  "outs": ["Out"]},
    "lod_rank_table": {"inputs": {"X": np.array(
        [[1, 1, 0], [1, 1, 1]], "float32")},
        "attrs": {}, "outs": ["Out"], "skip_finite": True},
    "max_sequence_len": {"inputs": {"X": np.array(
        [[1, 1, 0], [1, 1, 1]], "float32")},
        "attrs": {}, "outs": ["Out"], "skip_finite": True},
    "reorder_lod_tensor_by_rank": {
        "inputs": {"X": f32(3, 4), "RankTable": i64(3, hi=3) * 0 + np.arange(3)},
        "attrs": {}, "outs": ["Out"]},
    "tensor_array_to_tensor": {"inputs": {"X": [f32(2, 3), f32(2, 3)]},
                               "attrs": {"axis": 0}, "outs": ["Out"]},
    "split_lod_tensor": {"inputs": {
        "X": f32(4, 3), "Mask": i64(4, 1, hi=2).astype(bool)},
        "attrs": {}, "outs": ["OutTrue", "OutFalse"]},
    "merge_lod_tensor": {"inputs": {
        "InTrue": f32(4, 3), "InFalse": f32(4, 3),
        "Mask": i64(4, 1, hi=2).astype(bool)},
        "attrs": {}, "outs": ["Out"]},
    "beam_search": {"inputs": {
        "PreScores": f32(2, 3), "PreIds": i64(2, 3, hi=5),
        "LogProbs": f32(2, 3, 5)},
        "attrs": {"beam_size": 3, "end_id": 1},
        "outs": ["Scores", "Ids", "Parents"], "skip_finite": True},
    "beam_search_decode": {"inputs": {
        "Ids": i64(4, 2, 3, hi=5), "Parents": i64(4, 2, 3, hi=3),
        "Scores": f32(2, 3)},
        "attrs": {}, "outs": ["SentenceIds", "SentenceScores"],
        "skip_finite": True},

    # --- selected-rows / ids plumbing ------------------------------------
    "unique": {"inputs": {"X": i64(6, hi=4)}, "attrs": {},
               "outs": ["Out"], "skip_finite": True},
    "unique_with_counts": {"inputs": {"X": i64(6, hi=4)}, "attrs": {},
                           "outs": ["Out", "Count"],
                           "skip_finite": True},
    "hash": {"inputs": {"X": i64(4, 2, hi=100)},
             "attrs": {"num_hash": 2, "mod_by": 1000}, "outs": ["Out"],
             "skip_finite": True},
    "split_ids": {"inputs": {"Ids": i64(5, hi=20)},
                  "attrs": {"num_shards": 2}, "outs": ["Out", "Out"],
                  "skip_finite": True},
    "merge_ids": {"inputs": {"X": [f32(4, 2), f32(4, 2)]},
                  "attrs": {}, "outs": ["Out"]},
    "merge_selected_rows": {"inputs": {"Ids": i64(4, hi=3),
                                       "Values": f32(4, 2)},
                            "attrs": {}, "outs": ["OutIds", "Out"],
                            "skip_finite": True},
    "split_selected_rows": {"inputs": {"Ids": i64(4, hi=10),
                                       "Values": f32(4, 2)},
                            "attrs": {"height_sections": [5, 5]},
                            "outs": ["OutIds", "Out"],
                            "skip_finite": True},
    "get_tensor_from_selected_rows": {
        "inputs": {"Ids": i64(3, hi=6), "Values": f32(3, 2)},
        "attrs": {"height": 6}, "outs": ["Out"]},

    # --- detection --------------------------------------------------------
    "iou_similarity": {"inputs": {
        "X": np.array([[0., 0., 2., 2.]], "float32"),
        "Y": np.array([[1., 1., 3., 3.]], "float32")},
        "attrs": {}, "outs": ["Out"]},
    "box_coder": {"inputs": {
        "PriorBox": np.array([[0., 0., 2., 2.]], "float32"),
        "TargetBox": np.array([[1., 1., 3., 3.]], "float32")},
        "attrs": {"code_type": "encode_center_size"}, "outs": ["OutputBox"]},
    "box_clip": {"inputs": {
        "Input": f32(1, 4, 4, scale=5),
        "ImInfo": np.array([[8., 8., 1.]], "float32")},
        "attrs": {}, "outs": ["Output"]},
    "prior_box": {"inputs": {"Input": f32(1, 2, 3, 3),
                             "Image": f32(1, 3, 12, 12)},
                  "attrs": {"min_sizes": [4.0], "aspect_ratios": [1.0],
                            "variances": [0.1, 0.1, 0.2, 0.2]},
                  "outs": ["Boxes", "Variances"]},
    "density_prior_box": {"inputs": {"Input": f32(1, 2, 3, 3),
                                     "Image": f32(1, 3, 12, 12)},
                          "attrs": {"fixed_sizes": [4.0],
                                    "fixed_ratios": [1.0],
                                    "densities": [1],
                                    "variances": [0.1, 0.1, 0.2, 0.2]},
                          "outs": ["Boxes", "Variances"]},
    "anchor_generator": {"inputs": {"Input": f32(1, 2, 3, 3)},
                         "attrs": {"anchor_sizes": [16.0],
                                   "aspect_ratios": [1.0],
                                   "stride": [4.0, 4.0]},
                         "outs": ["Anchors", "Variances"]},
    "multiclass_nms": {"inputs": {
        "BBoxes": np.abs(f32(1, 4, 4, scale=3)),
        "Scores": np.random.RandomState(7).rand(1, 2, 4).astype(
            "float32")},
        "attrs": {"keep_top_k": 3}, "outs": ["Out"],
        "skip_finite": True},
    "bipartite_match": {"inputs": {
        "DistMat": np.random.RandomState(8).rand(3, 3).astype("float32")},
        "attrs": {}, "outs": ["ColToRowMatchIndices"],
        "skip_finite": True},
    "polygon_box_transform": {"inputs": {"X": f32(1, 8, 2, 2)},
                              "attrs": {}, "outs": ["Output"]},
    "yolo_box": {"inputs": {"X": f32(1, 7, 2, 2),
                            "ImgSize": np.array([[32, 32]], "int64")},
                 "attrs": {"anchors": [2, 3], "class_num": 2,
                           "conf_thresh": 0.01, "downsample": 16},
                 "outs": ["Boxes", "Scores"]},
    "yolov3_loss": {"inputs": {
        "X": f32(1, 7, 4, 4),
        "GTBox": np.array([[[0.5, 0.5, 0.3, 0.4]]], "float32"),
        "GTLabel": np.array([[1]], "int64")},
        "attrs": {"anchors": [10, 13], "class_num": 2},
        "outs": ["Loss"]},
    "roi_align": {"inputs": {
        "X": f32(1, 2, 8, 8), "ROIs": np.array([[1., 1., 6., 6.]],
                                               "float32")},
        "attrs": {"pooled_height": 2, "pooled_width": 2}, "outs": ["Out"]},
    "roi_pool": {"inputs": {
        "X": f32(1, 2, 8, 8), "ROIs": np.array([[1., 1., 6., 6.]],
                                               "float32")},
        "attrs": {"pooled_height": 2, "pooled_width": 2}, "outs": ["Out"]},
    "psroi_pool": {"inputs": {
        "X": f32(1, 8, 6, 6), "ROIs": np.array([[1., 1., 5., 5.]],
                                               "float32")},
        "attrs": {"output_channels": 2, "pooled_height": 2,
                  "pooled_width": 2}, "outs": ["Out"]},
    "generate_proposals": {"inputs": {
        "Scores": np.random.RandomState(9).rand(1, 2, 3, 3).astype(
            "float32"),
        "BboxDeltas": f32(1, 8, 3, 3, scale=0.1),
        "ImInfo": np.array([[24., 24., 1.]], "float32"),
        "Anchors": np.abs(f32(3, 3, 2, 4, scale=6))},
        "attrs": {"post_nms_topN": 4}, "outs": ["RpnRois"],
        "skip_finite": True},
    "rpn_target_assign": {"inputs": {
        "Anchor": np.abs(f32(6, 4, scale=8)),
        "GtBoxes": np.abs(f32(1, 2, 4, scale=8))},
        "attrs": {}, "outs": ["Labels", "BboxTargets"],
        "skip_finite": True},
    "generate_proposal_labels": {"inputs": {
        "RpnRois": np.abs(f32(1, 6, 4, scale=8)),
        "GtBoxes": np.abs(f32(1, 2, 4, scale=8)),
        "GtClasses": i64(1, 2, lo=1, hi=3)},
        "attrs": {"batch_size_per_im": 4}, "outs": ["Rois"],
        "skip_finite": True},
    "target_assign": {"inputs": {
        "X": f32(1, 3, 4),
        "MatchIndices": np.array([[0, -1, 2]], "int32")},
        "attrs": {}, "outs": ["Out", "OutWeight"]},
    "mine_hard_examples": {"inputs": {
        "ClsLoss": np.abs(f32(1, 6)),
        "MatchIndices": np.array([[0, -1, -1, 1, -1, -1]], "int32")},
        "attrs": {}, "outs": ["NegIndices"], "skip_finite": True},
    "detection_map": {"inputs": {
        "DetectRes": np.array([[1., 0.9, 0., 0., 2., 2.],
                               [1., 0.5, 4., 4., 6., 6.]], "float32"),
        "Label": np.array([[1., 0., 0., 2., 2.]], "float32")},
        "attrs": {}, "outs": ["MAP"]},

    # --- quant / misc -----------------------------------------------------
    "fake_quantize_abs_max": {"inputs": {"X": f32(3, 4)},
                              "attrs": {"bit_length": 8},
                              "outs": ["Out", "OutScale"]},
    "fake_channel_wise_quantize_abs_max": {
        "inputs": {"X": f32(3, 4)},
        "attrs": {"bit_length": 8, "quant_axis": 0},
        "outs": ["Out", "OutScale"]},
    "fake_quantize_moving_average_abs_max": {
        "inputs": {"X": f32(3, 4),
                   "InScale": np.ones((), "float32")},
        "attrs": {"bit_length": 8}, "outs": ["Out", "OutScale"]},
    "fake_dequantize_max_abs": {
        "inputs": {"X": f32(3, 4), "Scale": np.ones((1,), "float32")},
        "attrs": {"max_range": 127.0}, "outs": ["Out"]},
    "print": {
        "inputs": {"X": f32(2, 2)}, "attrs": {"message": "sweep: "},
        "outs": ["Out"]},
    "lr_schedule": {"inputs": {"Step": np.array([3], "int64")},
                    "attrs": {"kind": "exponential", "lr": 0.1,
                              "decay_steps": 2, "decay_rate": 0.5,
                              "staircase": False},
                    "outs": ["Out"]},
    "increment_loop_counter": {"inputs": {"X": np.array([1], "int64")},
                               "attrs": {"step": 1}, "outs": ["Out"],
                               "skip_finite": True},
    # --- LoD / tensor-array plumbing (dense redesigns) -------------------
    "lod_array_length": {"inputs": {"X": [f32(2, 3), f32(2, 3)]},
                         "attrs": {}, "outs": ["Out"]},
    "lod_tensor_to_array": {
        "inputs": {"X": f32(3, 4, 2),
                   "RankTable": np.array([2, 0, 1], "int64")},
        "attrs": {}, "outs": ["Out"] * 4},
    "array_to_lod_tensor": {
        "inputs": {"X": [f32(3, 2) for _ in range(4)],
                   "RankTable": np.array([2, 0, 1], "int64")},
        "attrs": {}, "outs": ["Out"]},
    "shrink_rnn_memory": {
        "inputs": {"X": f32(3, 4),
                   "RankTable": np.array([4, 3, 1], "int64"),
                   "I": np.array([2], "int64")},
        "attrs": {}, "outs": ["Out"]},
    "max_pool2d_with_index": {
        "inputs": {"X": f32(2, 3, 8, 8)},
        "attrs": {"ksize": 2, "strides": 2}, "outs": ["Out", "Mask"]},
    "max_pool3d_with_index": {
        "inputs": {"X": f32(1, 2, 4, 4, 4)},
        "attrs": {"ksize": 2, "strides": 2}, "outs": ["Out", "Mask"]},
    "roi_perspective_transform": {
        "inputs": {"X": f32(2, 3, 16, 16),
                   "ROIs": np.array([[2, 2, 12, 3, 13, 13, 1, 12],
                                     [0, 0, 15, 0, 15, 15, 0, 15]],
                                    "float32"),
                   "BatchIdx": np.array([0, 1], "int64")},
        "attrs": {"transformed_height": 6, "transformed_width": 5},
        "outs": ["Out", "Mask"]},
    # --- fused-op family (ops/fused_ops.py) ------------------------------
    "fc": {"inputs": {"Input": f32(3, 4), "W": f32(4, 5),
                      "Bias": f32(5)},
           "attrs": {"activation_type": "relu"}, "outs": ["Out"]},
    "fused_elemwise_activation": {
        "inputs": {"X": f32(2, 6), "Y": f32(2, 6)},
        "attrs": {"functor_list": ["elementwise_add", "relu"]},
        "outs": ["Out"]},
    "conv2d_fusion": {
        "inputs": {"Input": f32(1, 3, 8, 8), "Filter": f32(4, 3, 3, 3),
                   "Bias": f32(4)},
        "attrs": {"strides": 1, "paddings": 1, "activation": "relu"},
        "outs": ["Output"]},
    "fusion_lstm": {
        "inputs": {"X": f32(2, 5, 6), "WeightX": f32(6, 16),
                   "WeightH": f32(4, 16), "Bias": f32(16)},
        "attrs": {}, "outs": ["Hidden", "Cell"]},
    "fusion_gru": {
        "inputs": {"X": f32(2, 5, 6), "WeightX": f32(6, 12),
                   "WeightH": f32(4, 12), "Bias": f32(12)},
        "attrs": {}, "outs": ["Hidden"]},
    "fused_embedding_fc_lstm": {
        "inputs": {"Ids": i64(2, 5, hi=9), "Embeddings": f32(9, 16),
                   "WeightH": f32(4, 16), "Bias": f32(16)},
        "attrs": {}, "outs": ["Hidden", "Cell"]},
    "attention_lstm": {
        "inputs": {"X": f32(2, 5, 6), "AttentionWeight": f32(10, 1),
                   "LSTMWeight": f32(10, 16), "LSTMBias": f32(16)},
        "attrs": {}, "outs": ["Hidden", "Cell"]},
    "fusion_seqconv_eltadd_relu": {
        "inputs": {"X": f32(2, 5, 4), "Filter": f32(12, 6),
                   "Bias": f32(6)},
        "attrs": {"contextLength": 3}, "outs": ["Out"]},
    "fusion_seqexpand_concat_fc": {
        "inputs": {"X": [f32(2, 5, 4), f32(2, 3)],
                   "FCWeight": f32(7, 6), "FCBias": f32(6)},
        "attrs": {"fc_activation": "relu"}, "outs": ["Out"]},
    "fusion_transpose_flatten_concat": {
        "inputs": {"X": [f32(2, 3, 4), f32(2, 3, 4)]},
        "attrs": {"trans_axis": [0, 2, 1], "flatten_axis": 1,
                  "concat_axis": 1}, "outs": ["Out"]},
    "depthwise_conv2d_transpose": {
        "inputs": {"Input": f32(1, 3, 5, 5), "Filter": f32(3, 1, 2, 2)},
        "attrs": {"strides": 2, "paddings": 0}, "outs": ["Output"]},
    "fake_quantize_range_abs_max": {
        "inputs": {"X": f32(2, 6)}, "attrs": {"bit_length": 8},
        "outs": ["Out", "OutScale"]},
    "fake_init": {"inputs": {}, "attrs": {"shape": [2, 3]},
                  "outs": ["Out"]},
    "get_places": {"inputs": {}, "attrs": {"device_count": 1},
                   "outs": ["Out"], "skip_finite": True},
    "rnn_memory_helper": {"inputs": {"X": f32(2, 3)}, "attrs": {},
                          "outs": ["Out"]},
    "write_to_array": {
        "inputs": {"X": f32(3, 4), "I": np.array([1], "int64")},
        "attrs": {"array_len": 4}, "outs": ["Out"]},
    "read_from_array": {
        "inputs": {"X": f32(4, 3), "I": np.array([2], "int64")},
        "attrs": {}, "outs": ["Out"]},
}

# ops whose execution is validated by dedicated tests / harnesses, or that
# are structural and cannot run standalone
EXEMPT = {
    "feed": "structural (executor implements)",
    "fetch": "structural",
    "data": "structural",
    "autodiff": "structural pseudo-op (framework/backward.py tests)",
    "while": "control flow — tests/test_control_flow.py",
    "conditional_block": "control flow — tests/test_control_flow.py",
    "scan": "control flow engine — tests/test_control_flow.py",
    "static_rnn_scan": "control flow — tests/test_control_flow.py",
    "delete_var": "documented no-op (XLA owns liveness)",
    "fused_attention": "tests/test_pallas_kernels.py",
    "fused_mha": "tests/test_pallas_kernels.py fused_mha parity/cross/train",
    "pipeline_boundary": "tests/test_pipeline_parallel.py (identity + GPipe plane)",
    "moe_ffn": "tests/test_expert_parallel.py (dense-equivalence + ep mesh)",
    "scale_sub_region": "tests/test_v2_mixed_tier.py numeric box check",
    "sequence_context": "tests/test_v2_mixed_tier.py context_projection identity checks",
    "fused_lm_head_loss": "tests/test_models.py fused-vs-unfused parity",
    "fused_transformer_block": "tests/test_fused_block.py (transpiler "
                               "parity + kernel numerics)",
    "quantized_matmul": "tests/test_quantize_exec.py freeze/int8 parity",
    "quantized_conv2d": "tests/test_quantize_exec.py conv numerics",
    "sparse_embedding_lookup": "tests/test_sparse_plane.py (hash-fold "
                               "host/graph parity + trains + infer rule)",
    "sparse_scatter_update": "tests/test_sparse_plane.py duplicate-id "
                             "accumulation + infer rule",
    "save": "io op — tests/test_reader_trainer.py save/load-as-ops",
    "load": "io op — dedicated test",
    "save_combine": "io op — dedicated test",
    "load_combine": "io op — dedicated test",
    "c_allreduce_sum": "mesh collective — tests/test_parallel_executor.py",
    "c_allreduce_max": "mesh collective",
    "c_allreduce_mean": "mesh collective",
    "c_allgather": "mesh collective",
    "c_alltoall": "mesh collective",
    "c_broadcast": "mesh collective",
    "c_ppermute": "mesh collective",
    "c_reducescatter": "mesh collective",
    "c_sync_calc_stream": "mesh collective no-op",
    "sgd": "optimizer — tests/test_models.py training",
    "momentum": "optimizer — exercised via Optimizer tests",
    "lars_momentum": "optimizer",
    "adam": "optimizer — test_adam_state_signature_stable",
    "adamw": "optimizer",
    "adamax": "optimizer",
    "adagrad": "optimizer",
    "decayed_adagrad": "optimizer",
    "adadelta": "optimizer",
    "rmsprop": "optimizer",
    "ftrl": "optimizer",
    "lamb": "optimizer",
    "proximal_gd": "optimizer",
    "proximal_adagrad": "optimizer",
    "average_accumulates": "optimizer (ModelAverage)",
}


def _materialize(v):
    return v() if callable(v) else v


def run_spec(op_type, spec):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        block = main.global_block()
        in_map, feeds = {}, {}
        for slot, val in spec["inputs"].items():
            vals = val if isinstance(val, list) else [val]
            names = []
            for k, arr in enumerate(vals):
                arr = np.asarray(_materialize(arr))
                name = f"in_{slot}_{k}"
                block.create_var(name=name, shape=arr.shape,
                                 dtype=str(arr.dtype), is_data=True)
                feeds[name] = arr
                names.append(name)
            in_map[slot] = names
        out_map, fetch = {}, []
        counts = {}
        for slot in spec["outs"]:
            counts[slot] = counts.get(slot, 0) + 1
        done = {}
        for slot, cnt in counts.items():
            names = []
            for k in range(cnt):
                name = f"out_{slot}_{k}"
                block.create_var(name=name, dtype="float32")
                names.append(name)
                fetch.append(name)
            out_map[slot] = names
        block.append_op(op_type, in_map, out_map, spec.get("attrs", {}))
    exe = pt.Executor(pt.CPUPlace())
    outs = exe.run(main, feed=feeds, fetch_list=fetch)
    return {n: v for n, v in zip(fetch, outs)}, feeds


@pytest.mark.parametrize("op_type", sorted(SPECS))
def test_op_smoke(op_type):
    spec = SPECS[op_type]
    outs, feeds = run_spec(op_type, spec)
    for name, v in outs.items():
        arr = np.asarray(v)
        assert arr.size > 0 or op_type in ("is_empty",), \
            f"{op_type}:{name} empty"
        if not spec.get("skip_finite") and np.issubdtype(
                arr.dtype, np.floating):
            assert np.isfinite(arr).all(), f"{op_type}:{name} not finite"
    ref = spec.get("ref")
    if ref is not None:
        ins = {slot: feeds[f"in_{slot}_0"] for slot in spec["inputs"]}
        expect = np.asarray(ref(ins))
        got = np.asarray(outs[f"out_{spec['outs'][0]}_0"])
        np.testing.assert_allclose(
            got.reshape(expect.shape).astype("float64"),
            expect.astype("float64"), rtol=1e-4, atol=1e-5,
            err_msg=f"{op_type} numpy mismatch")


def test_registry_fully_covered():
    """Every registered op is swept, exempted with a reason, or has a
    dedicated test elsewhere (this is the gate that caught the dead RNN
    family in round 1)."""
    missing = [op for op in registered_ops()
               if op not in SPECS and op not in EXEMPT]
    assert not missing, f"ops with no test coverage: {missing}"


# --------------------------------------------------------------------------
# finite-difference gradient sweep for the differentiable core
# --------------------------------------------------------------------------

GRAD_CHECK = {
    "exp": ("X", "Out"), "tanh": ("X", "Out"), "sigmoid": ("X", "Out"),
    "log": ("X", "Out"), "sqrt": ("X", "Out"), "square": ("X", "Out"),
    "softplus": ("X", "Out"), "gelu": ("X", "Out"),
    "elementwise_add": ("X", "Out"), "elementwise_mul": ("X", "Out"),
    "elementwise_div": ("X", "Out"), "elementwise_sub": ("Y", "Out"),
    "mul": ("X", "Out"), "matmul": ("Y", "Out"), "bmm": ("X", "Out"),
    "reduce_sum": ("X", "Out"), "reduce_mean": ("X", "Out"),
    "softmax": ("X", "Out"), "log_softmax": ("X", "Out"),
    "layer_norm": ("X", "Y"), "scale": ("X", "Out"),
    "conv2d": ("Input", "Output"), "cos_sim": ("X", "Out"),
    "sequence_conv": ("X", "Out"), "row_conv": ("X", "Out"),
    "lstm": ("Input", "Hidden"), "gru": ("Input", "Hidden"),
    "lstmp": ("Input", "Projection"),
    "linear_chain_crf": ("Emission", "LogLikelihood"),
    "warpctc": ("Logits", "Loss"),
    "huber_loss": ("X", "Out"), "mse_loss": ("X", "Out"),
    "smooth_l1_loss": ("X", "Out"),
    "softmax_with_cross_entropy": ("Logits", "Loss"),
    "sigmoid_cross_entropy_with_logits": ("X", "Out"),
    "hierarchical_sigmoid": ("X", "Out"),
    "bilinear_tensor_product": ("X", "Out"),
    "conv_shift": ("X", "Out"), "dot": ("X", "Out"),
    "prelu": ("X", "Out"), "pad": ("X", "Out"),
    "cumsum": ("X", "Out"), "l1_norm": ("X", "Out"),
    "squared_l2_norm": ("X", "Out"),
    # breadth sweep: every differentiable op with a smooth-enough spec
    "sin": ("X", "Out"), "cos": ("X", "Out"),
    "reciprocal": ("X", "Out"), "rsqrt": ("X", "Out"),
    "logsigmoid": ("X", "Out"), "softsign": ("X", "Out"),
    "tanh_shrink": ("X", "Out"), "stanh": ("X", "Out"),
    "swish": ("X", "Out"), "mish": ("X", "Out"),
    "elu": ("X", "Out"), "selu": ("X", "Out"),
    "hard_sigmoid": ("X", "Out"), "soft_relu": ("X", "Out"),
    "leaky_relu": ("X", "Out"), "pow": ("X", "Out"),
    "elementwise_max": ("X", "Out"), "elementwise_min": ("X", "Out"),
    "elementwise_pow": ("X", "Out"),
    "reduce_prod": ("X", "Out"),
    "transpose": ("X", "Out"), "concat": ("X", "Out"),
    "expand": ("X", "Out"), "maxout": ("X", "Out"),
    "group_norm": ("X", "Y"), "lrn": ("X", "Out"),
    "pool2d": ("X", "Out"), "pool3d": ("X", "Out"),
    "im2sequence": ("X", "Out"),
    "log_loss": ("Predicted", "Loss"), "bpr_loss": ("X", "Y"),
    "hinge_loss": ("Logits", "Loss"),
    "rank_loss": ("Left", "Out"), "margin_rank_loss": ("X1", "Out"),
    "cross_entropy": ("X", "Y"), "label_smooth": ("X", "Out"),
    "kldiv_loss": ("X", "Loss"),
    "affine_channel": ("X", "Out"), "grid_sampler": ("X", "Output"),
    "bilinear_interp": ("X", "Out"),
    "fc": ("Input", "Out"), "fused_elemwise_activation": ("X", "Out"),
    "fusion_lstm": ("X", "Hidden"), "fusion_gru": ("X", "Hidden"),
    "attention_lstm": ("X", "Hidden"),
    "cudnn_lstm": ("Input", "Out"),
    "conv2d_transpose": ("Input", "Output"),
    "conv3d": ("Input", "Output"),
    "depthwise_conv2d": ("Input", "Output"),
    # nce: excluded — fresh negative samples per evaluation make
    # finite differences meaningless (stochastic objective)
    "add_position_encoding": ("X", "Out"),
    "squared_l2_distance": ("X", "Out"),
}


@pytest.mark.parametrize("op_type", sorted(GRAD_CHECK))
def test_op_grad(op_type):
    spec = SPECS[op_type]
    in_slot, out_slot = GRAD_CHECK[op_type]

    class T(OpTest):
        pass

    t = T()
    T.op_type = op_type

    def setup(self):
        self.inputs = {k: _materialize(v)
                       for k, v in spec["inputs"].items()}
        self.attrs = dict(spec.get("attrs", {}))
        self.outputs = {s: np.zeros(1, "float32") for s in spec["outs"]}

    T.setup = setup
    t.check_grad([in_slot], out_slot, max_relative_error=0.02)
