"""Timecard (ISSUE 19, observability/goodput.py).

Covers: the per-rank wall-clock state machine's conservation invariant
(non-overlapping segments summing to tracked wall BY CONSTRUCTION,
span clipping, note_step anatomy scaling around a prior compile span),
flag-off inertness, status_doc / metrics-doc row round trips (local +
fleet-merged + the GET /goodput route), the built-in goodput_collapse
Watchtower rule and its alert_context, the offline journal
reconstructor (+--compare and the CLI exit-code contract), the
incident --goodput join, flag-off bitwise invariance through a real
checkpointing run with an interleaved A/B overhead gate, the conftest
controller_*-flag leak regression, and the tier-1 elastic-soak
conservation gate (2->4->1->3 resize + chaos-killed rank 0: live
accounting vs offline journal replay per state).
"""
import json
import os
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.observability import alerts
from paddle_tpu.observability import fleet
from paddle_tpu.observability import goodput
from paddle_tpu.observability import incident
from paddle_tpu.observability import journal as obs_journal
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import server as obs_server
from paddle_tpu.resilience import soak


def _spin(seconds):
    """Busy-wait so perf_counter really advances (sleep can undershoot
    on coarse clocks)."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        pass


def _assert_segments_sane(segments):
    """Non-overlapping and time-ordered — the conservation invariant's
    structural half.  Start/dur are independently rounded to 6 decimal
    places on unix-scale floats, so adjacent boundaries can disagree by
    a few microseconds without any real overlap."""
    for a, b in zip(segments, segments[1:]):
        assert a["start_unix"] + a["dur"] <= b["start_unix"] + 1e-5, \
            (a, b)


# ===================================================================
# the state machine: conservation by construction
# ===================================================================

def test_conservation_and_segments():
    flags.set_flag("goodput", True)
    goodput.note_wait("idle")
    _spin(0.004)
    goodput.note_step(data_wait_s=0.001, host_s=0.002, device_s=0.001,
                      wall_s=0.004)
    _spin(0.002)
    goodput.note_span("checkpoint_save", 0.002)
    _spin(0.002)
    goodput.note_wait("input_wait")
    snap = goodput.snapshot()
    assert snap["tracked_s"] == pytest.approx(snap["wall_s"], rel=1e-6)
    assert snap["states"]["compute"] > 0
    assert snap["states"]["input_wait"] > 0
    assert snap["states"]["checkpoint_save"] > 0
    assert set(snap["states"]) <= set(goodput.STATES)
    _assert_segments_sane(snap["segments"])
    # live registry mirrors the accumulators
    rows = goodput.rows_from_metrics_doc(obs_metrics.REGISTRY.to_json())
    for state, v in snap["states"].items():
        assert rows["states"][state] == pytest.approx(v, abs=1e-5)
    assert rows["goodput_fraction"] == pytest.approx(
        snap["goodput_fraction"], abs=1e-5)


def test_span_overlap_is_clipped_never_double_booked():
    flags.set_flag("goodput", True)
    goodput.note_wait("idle")
    _spin(0.003)
    goodput.note_wait("input_wait")     # claims the 3ms
    # a span claiming 10 WHOLE seconds ending now: only the unclaimed
    # sliver since the last boundary may be booked
    goodput.note_span("compile", 10.0)
    snap = goodput.snapshot()
    assert snap["tracked_s"] == pytest.approx(snap["wall_s"], rel=1e-6)
    assert snap["states"].get("compile", 0.0) < 1.0


def test_note_step_scales_around_prior_compile_span():
    flags.set_flag("goodput", True)
    goodput.note_wait("idle")
    _spin(0.004)
    # a compile span eats half the elapsed step wall; the anatomy that
    # follows must scale into the remainder, not double-book
    goodput.note_span("compile", 0.002)
    goodput.note_step(data_wait_s=0.002, host_s=0.002, device_s=0.0,
                      wall_s=0.004)
    snap = goodput.snapshot()
    assert snap["tracked_s"] == pytest.approx(snap["wall_s"], rel=1e-6)
    assert snap["states"]["compile"] == pytest.approx(0.002, abs=5e-4)
    # input_wait and compute each got a scaled share of the remainder
    assert snap["states"]["input_wait"] > 0
    assert snap["states"]["compute"] > 0


def test_flag_off_is_inert():
    assert not goodput.enabled()
    goodput.note_wait("idle")
    goodput.note_step(data_wait_s=0.1, host_s=0.1, device_s=0.1,
                      wall_s=0.3)
    goodput.note_span("compile", 0.1)
    goodput.note_drain_begin()
    goodput.note_drain_end()
    goodput.flush()
    snap = goodput.snapshot()
    assert snap["states"] == {}
    assert snap["tracked_s"] == 0.0
    assert goodput.fraction() == 0.0
    rows = goodput.rows_from_metrics_doc(obs_metrics.REGISTRY.to_json())
    assert rows["states"] == {}
    assert rows["goodput_fraction"] is None


def test_drain_pair_charges_drain():
    flags.set_flag("goodput", True)
    goodput.note_wait("idle")
    goodput.note_drain_begin()
    _spin(0.003)
    goodput.note_drain_end()
    snap = goodput.snapshot()
    assert snap["states"].get("drain", 0.0) >= 0.002
    assert snap["tracked_s"] == pytest.approx(snap["wall_s"], rel=1e-6)


# ===================================================================
# surfaces: status doc, fleet rows, GET /goodput, alert rule
# ===================================================================

def test_status_doc_and_dominant_badput():
    flags.set_flag("goodput", True)
    goodput.note_wait("idle")
    _spin(0.002)
    goodput.note_span("compute", 0.001)
    _spin(0.003)
    goodput.note_wait("checkpoint_save")
    doc = goodput.status_doc()
    assert doc["schema"] == goodput.SCHEMA
    assert doc["enabled"] is True
    assert doc["states_catalog"] == list(goodput.STATES)
    assert doc["dominant_badput"] in goodput.BADPUT_STATES
    ctx = goodput.alert_context({})
    assert ctx["dominant_badput"] == doc["dominant_badput"]
    assert 0.0 <= ctx["goodput_fraction"] <= 1.0


def test_goodput_route_local_and_fleet():
    flags.set_flag("goodput", True)
    goodput.note_wait("idle")
    _spin(0.002)
    goodput.note_wait("compute")
    srv = obs_server.start_http_server(port=0)
    with urllib.request.urlopen(f"{srv.url}/goodput", timeout=10) as r:
        doc = json.loads(r.read())
    assert doc["schema"] == goodput.SCHEMA
    assert doc["source"] == "local"
    assert doc["states"]["compute"] > 0
    with urllib.request.urlopen(f"{srv.url}/", timeout=10) as r:
        assert b"/goodput" in r.read()
    obs_server.reset()

    agg = fleet.FleetAggregator(stale_after=60.0)
    agg.ingest("report_metrics",
               {"schema": fleet.SCHEMA, "rank": 0,
                "time_unix": time.time(),
                "perf_counter": time.perf_counter(),
                "steps_total": 1.0,
                "metrics": obs_metrics.REGISTRY.to_json()})
    rows = agg.goodput_rows()
    assert set(rows) == {"0"}
    assert rows["0"]["states"]["compute"] > 0
    srv = obs_server.start_http_server(port=0, aggregator=agg)
    with urllib.request.urlopen(f"{srv.url}/goodput", timeout=10) as r:
        doc = json.loads(r.read())
    assert doc["source"] == "fleet"
    assert doc["ranks"]["0"]["states"]["compute"] > 0


def test_goodput_collapse_rule_gated_on_flag():
    names = {r.name for r in alerts.default_rules()}
    assert "goodput_collapse" not in names          # flag off: absent
    flags.set_flag("goodput", True)
    rules = {r.name: r for r in alerts.default_rules()}
    assert "goodput_collapse" in rules
    rule = rules["goodput_collapse"]
    # fires on the published complement (badput_fraction >= 1 - gfrac):
    # a labelless gauge's 0.0 default series means a "goodput low" rule
    # would false-fire on a rank that tracked nothing yet
    assert rule.metric == "badput_fraction"
    assert rule.op == ">="
    assert rule.value == pytest.approx(
        1.0 - flags.get_flag("goodput_collapse_fraction"))
    assert rule.for_seconds == pytest.approx(
        flags.get_flag("goodput_collapse_for_s"))
    # threshold flag <= 0 disables the rule even with the plane on
    flags.set_flag("goodput_collapse_fraction", 0.0)
    assert "goodput_collapse" not in {r.name
                                      for r in alerts.default_rules()}


def test_reset_is_alert_safe():
    """After reset() an untracked rank must read as NO data, not as
    collapsed goodput: chip_seconds_total loses every labeled series,
    badput_fraction (the alerting series) sits at its safe 0.0
    default, and the row reconstruction reports fraction None."""
    flags.set_flag("goodput", True)
    goodput.note_wait("idle")
    _spin(0.002)
    goodput.note_wait("idle")
    # all-badput tracking pushed the alerting gauge to the firing end
    assert obs_metrics.REGISTRY.get("badput_fraction").total() \
        == pytest.approx(1.0)
    goodput.reset()
    fams = (obs_metrics.REGISTRY.to_json() or {}).get("metrics") or {}
    assert not (fams.get("chip_seconds_total") or {}).get("series")
    assert obs_metrics.REGISTRY.get("badput_fraction").total() == 0.0
    rows = goodput.rows_from_metrics_doc(obs_metrics.REGISTRY.to_json())
    assert rows["states"] == {} and rows["goodput_fraction"] is None


# ===================================================================
# offline reconstructor + CLI contract
# ===================================================================

def _emit_run(path, states):
    """Write one rank's goodput final (+ a matching segment stream)
    through the REAL journal writer so read_events round-trips."""
    flags.set_flag("journal_path", str(path))
    t = 1000.0
    for state, dur in states.items():
        obs_journal.emit("goodput", "segment", state=state,
                         seg_start_unix=t, dur=dur)
        t += dur
    obs_journal.emit("goodput", "final", states=dict(states),
                     wall_s=sum(states.values()),
                     fraction=states.get("compute", 0.0)
                     / max(sum(states.values()), 1e-9))
    obs_journal.reset()
    flags.set_flag("journal_path", "")


def test_reconstruct_from_real_journal_and_cli(tmp_path, capsys):
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _emit_run(a, {"compute": 9.0, "idle": 1.0})
    _emit_run(b, {"compute": 1.0, "idle": 9.0})
    doc = goodput.reconstruct([str(a)])
    assert doc["fleet"]["goodput_fraction"] == pytest.approx(0.9)
    rank = list(doc["ranks"].values())[0]
    assert rank["states"]["compute"] == pytest.approx(9.0)
    _assert_segments_sane(rank["segments"])
    # breakdown + timeline render
    assert goodput.main([str(a)]) == 0
    out = capsys.readouterr().out
    assert "goodput breakdown" in out
    assert "timeline" in out
    # --compare: 0.9 -> 0.1 regresses past the 0.1 tolerance -> exit 1
    assert goodput.main([str(a), "--compare", str(b)]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    # self-compare is clean
    assert goodput.main([str(a), "--compare", str(a)]) == 0


def test_cli_exit_codes():
    assert not goodput.enabled()
    assert goodput.main([]) == 2                 # live report, plane off
    assert goodput.main(["/nonexistent/journal.jsonl"]) == 2


def test_cli_self_test(capsys):
    assert goodput.main(["--self-test"]) == 0
    out = capsys.readouterr().out
    assert "GOODPUT_SELF_TEST" in out
    payload = json.loads(out.split("GOODPUT_SELF_TEST ", 1)[1]
                         .splitlines()[0])
    assert payload["ok"] is True
    # the self-test restored the flag family
    assert goodput.enabled() is False


def test_restart_gap_and_park_gap_reconstruction():
    base = 2000.0

    def ev(dt, kind, event, seq, **fields):
        return {"schema": obs_journal.SCHEMA, "kind": kind,
                "event": event, "time_unix": base + dt, "rank": 0,
                "pid": 1, "seq": seq, **fields}

    events = [
        ev(0.0, "supervisor", "spawn", 1, worker=0, incarnation=0),
        ev(1.0, "supervisor", "restart", 2, worker=0, rc=9, attempt=1),
        ev(3.0, "supervisor", "spawn", 3, worker=0, incarnation=1),
        ev(5.0, "supervisor", "park", 4, worker=1, rc=3, target_world=1),
        ev(9.0, "supervisor", "spawn", 5, worker=1, incarnation=1),
        ev(9.5, "master", "resize_applied", 6, old_world=2, new_world=3,
           epoch=2),
    ]
    doc = goodput.reconstruct_events(events)
    r0 = doc["ranks"]["0"]
    r1 = doc["ranks"]["1"]
    assert r0["offline_states"]["restart_gap"] == pytest.approx(2.0)
    assert r1["offline_states"]["resize_barrier"] == pytest.approx(4.0)
    assert [g["why"] for g in doc["restart_gaps"]] == ["restart",
                                                       "park"]
    assert doc["resizes"] == [{"old": 2, "new": 3, "epoch": 2,
                               "time_unix": base + 9.5}]


def test_incident_goodput_join():
    events = incident._fixture_events()
    t0 = events[0]["time_unix"]
    events.append({"schema": obs_journal.SCHEMA, "kind": "goodput",
                   "event": "segment", "rank": 0, "pid": 100, "seq": 99,
                   "state": "restart_gap", "seg_start_unix": t0 + 1.4,
                   "dur": 1.0, "time_unix": t0 + 2.4})
    doc = incident.build_report(events, [], t0, t0 + 10.0,
                                {"mode": "window"}, with_goodput=True)
    gp = doc["goodput"]
    assert gp["spikes"], gp
    spike = gp["spikes"][0]
    assert spike["state"] == "restart_gap"
    # the dead_rank alert fires within +-5s of the badput spike
    assert any("alert" in n for n in spike["nearby"])
    text = incident.render_report(doc)
    assert "goodput:" in text
    assert "restart_gap" in text


# ===================================================================
# conftest isolation (satellite): controller_* flags cannot leak
# ===================================================================

def test_controller_flag_leak_part1_mutates():
    """Deliberately leak tuned controller knobs; the NEXT test proves
    the conftest fixture restored every controller_* flag."""
    flags.set_flag("controller_cooldown_s", 1234.5)
    flags.set_flag("controller_max_world", 77)
    flags.set_flag("controller_state_path", "/tmp/leaked")
    flags.set_flag("controller", True)


def test_controller_flag_leak_part2_restored():
    assert flags.get_flag("controller_cooldown_s") != 1234.5
    assert flags.get_flag("controller_max_world") != 77
    assert flags.get_flag("controller_state_path") == ""
    assert flags.get_flag("controller") is False


def test_goodput_state_does_not_leak():
    """Paired with every test above that charged chip-time: a fresh
    test starts with an empty Timecard and the flag family at
    defaults."""
    assert goodput.enabled() is False
    assert goodput.snapshot()["tracked_s"] == 0.0
    assert flags.get_flag("goodput_collapse_fraction") == \
        pytest.approx(0.3)


# ===================================================================
# flag-off bitwise invariance + interleaved A/B overhead gate
# ===================================================================

def _ab_train_once(ckdir, enable_goodput):
    """One checkpointed training run; returns (weights, losses, wall)."""

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, bias_attr=False, name="fc")
        return layers.mean(layers.square_error_cost(pred, y))

    rng = np.random.RandomState(0)
    batches = [[(rng.randn(4).astype("float32"),
                 rng.randn(1).astype("float32")) for _ in range(4)]
               for _ in range(6)]
    losses = []

    def handler(event):
        if isinstance(event, pt.EndStepEvent):
            losses.append(np.asarray(event.metrics[0]).tobytes())

    pt.reset_default_programs()
    goodput.reset()
    flags.set_flag("goodput", bool(enable_goodput))
    cfg = pt.CheckpointConfig(ckdir, max_num_checkpoints=2,
                              epoch_interval=1, step_interval=2)
    t = pt.Trainer(train_func,
                   lambda: pt.optimizer.SGD(learning_rate=0.05),
                   place=pt.CPUPlace(), checkpoint_config=cfg)
    t0 = time.perf_counter()
    t.train(num_epochs=2, event_handler=handler, reader=lambda:
            iter(batches), feed_order=["x", "y"])
    wall = time.perf_counter() - t0
    w_name, = [n for n in t.scope.var_names() if n.endswith(".w_0")]
    w = np.asarray(t.scope.find_var(w_name)).copy()
    flags.set_flag("goodput", False)
    return w, losses, wall


def test_flag_off_bitwise_invariance_and_overhead(tmp_path):
    """Interleaved A/B (off, on, off, on) through a REAL checkpointed
    training run: identical weight bytes and loss bytes in both modes
    (the flag-off contract extends to flag-ON numerics — the plane only
    reads timings), and the enabled plane costs <= 10% wall overhead
    (min-of-reps, small absolute slack for CI scheduler noise)."""
    runs = []
    for i, on in enumerate((False, True, False, True)):
        runs.append(_ab_train_once(str(tmp_path / f"ck{i}"), on))
    w_off, l_off, _ = runs[0]
    for w, losses, _ in runs[1:]:
        assert np.array_equal(w, w_off)
        assert w.tobytes() == w_off.tobytes()
        assert losses == l_off
    t_off = min(runs[0][2], runs[2][2])
    t_on = min(runs[1][2], runs[3][2])
    assert t_on <= t_off * 1.10 + 0.25, (t_on, t_off)
    # the ON runs actually tracked chip-time through the trainer seams
    snap = goodput.snapshot()
    assert snap["tracked_s"] > 0
    assert snap["states"].get("compute", 0.0) > 0


# ===================================================================
# bench satellite: bench_goodput_fraction row + trend subseries
# ===================================================================

def test_bench_row_publishes_goodput_fraction():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "ptpu_bench_module",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    row = {"metric": "probe_tokens_per_sec", "unit": "tokens/s",
           "value": 1.0, "vs_baseline": 1.0, "goodput_fraction": 0.83}
    bench._record_row_metrics(row)
    fam = obs_metrics.REGISTRY.get("bench_goodput_fraction")
    assert fam is not None
    assert fam.labels(metric="probe_tokens_per_sec").value \
        == pytest.approx(0.83)


def _bench_rec(value, gfrac=None):
    return {"m_tokens_per_sec": {"value": value,
                                 "goodput_fraction": gfrac}}


def test_trend_goodput_fraction_subseries():
    from paddle_tpu.observability import bench_gate
    # higher-is-better: a goodput drop is a NAMED regression even when
    # throughput itself improved
    res = bench_gate.trend([
        ("r01", _bench_rec(100.0, gfrac=0.9)),
        ("r02", _bench_rec(104.0, gfrac=0.88)),
        ("r03", _bench_rec(110.0, gfrac=0.5)),
    ])
    rows = {r["metric"]: r for r in res["rows"]}
    grow = rows["m_tokens_per_sec.goodput_fraction"]
    assert grow["status"] == "regression"
    assert "m_tokens_per_sec.goodput_fraction" in res["regressions"]
    assert rows["m_tokens_per_sec"]["status"] == "ok"
    # first post-Timecard record: not a regression
    res = bench_gate.trend([("r01", _bench_rec(100.0)),
                            ("r02", _bench_rec(101.0, gfrac=0.9))])
    rows = {r["metric"]: r for r in res["rows"]}
    assert rows["m_tokens_per_sec.goodput_fraction"]["status"] == "ok"
    assert res["ok"] is True
    # the newest record dropping the column is flagged missing
    res = bench_gate.trend([("r01", _bench_rec(100.0, gfrac=0.9)),
                            ("r02", _bench_rec(101.0))])
    rows = {r["metric"]: r for r in res["rows"]}
    assert rows["m_tokens_per_sec.goodput_fraction"]["status"] \
        == "missing"
    # records with no goodput anywhere grow no subseries row at all
    res = bench_gate.trend([("r01", _bench_rec(100.0)),
                            ("r02", _bench_rec(101.0))])
    assert not [r for r in res["rows"]
                if r["metric"].endswith(".goodput_fraction")]
    # the runlog summary path round-trips the column
    rec = bench_gate.load_trend_record(
        {"summary": {"m": {"value": 7.0, "goodput_fraction": 0.8}}})
    assert rec["m"]["goodput_fraction"] == 0.8


# ===================================================================
# tier-1 conservation gate: elastic soak with resizes + chaos kill
# ===================================================================

def test_timecard_conservation_elastic_soak(tmp_path, monkeypatch):
    """The ISSUE 19 correctness gate: the 2->4->1->3 resize sweep with
    a chaos-killed rank 0, goodput + journal on for every worker.  Per
    rank: segments non-overlapping, per-state seconds sum to the
    tracked wall (+-5%), and the OFFLINE journal reconstruction agrees
    with the live accounting (+-10% per state); restart gaps appear
    only offline (chip-time no process could self-account)."""
    journal_path = str(tmp_path / "fleet_journal.jsonl")
    monkeypatch.setenv("PTPU_GOODPUT", "1")
    monkeypatch.setenv("PTPU_JOURNAL_PATH", journal_path)
    # the supervisor/master live in THIS process: journal their
    # spawn/restart/park/resize events into the same shared file
    flags.set_flag("journal_path", journal_path)

    rep = soak.run_schedule(str(tmp_path), "resize_soak_chaos", world=2,
                            n_tasks=4, epochs=2, timeout=90)
    assert rep["ok"], rep["problems"]
    assert rep["restarts"][0] >= 1                # chaos kill fired
    assert rep["resizes_applied"] == 3
    # flag-ON invariance through checkpointing + resizes: the fleet
    # lands the EXACT fixed-fleet end state
    assert rep["w_total"] == pytest.approx(rep["expected_w_total"],
                                           abs=1e-9)

    live = {}
    for w in rep["workers"]:
        gp = w.get("goodput")
        assert gp is not None, f"rank {w['rank']} report missing goodput"
        if gp["tracked_s"] == 0:
            # an incarnation spawned by a late grow can retire before
            # charging any chip-time (queue already drained) — that
            # conserves trivially, it must just not invent state time
            assert sum(gp["states"].values()) == 0
            continue
        # conservation: segments non-overlapping, states sum to wall
        _assert_segments_sane(gp["segments"])
        assert gp["tracked_s"] == pytest.approx(gp["wall_s"],
                                                rel=0.05)
        # each state value is independently round(,6)-ed in the
        # snapshot, so the sum can drift from tracked_s by up to
        # ~0.5e-6 per state — absolute tolerance, not relative
        assert sum(gp["states"].values()) == pytest.approx(
            gp["tracked_s"], abs=1e-5)
        live[w["rank"]] = gp
    # the chaos-killed-and-restarted rank always does real work
    assert 0 in live, "rank 0 tracked no chip-time"

    flags.set_flag("journal_path", "")
    obs_journal.reset()
    events = obs_journal.read_events(journal_path)
    recon = goodput.reconstruct_events(events)
    # every live rank reconstructs; restart gaps + all 3 resizes do too
    assert any(g["why"] == "restart" and g["rank"] == 0
               for g in recon["restart_gaps"])
    assert [r["new"] for r in recon["resizes"]] == [4, 1, 3]
    finals = {}
    for e in events:
        if e.get("kind") == "goodput" and e.get("event") == "final":
            finals[e["rank"]] = finals.get(e["rank"], 0) + 1
    for rank, gp in live.items():
        off = recon["ranks"].get(str(rank))
        assert off is not None, f"rank {rank} missing offline"
        for state, v_live in gp["states"].items():
            v_off = off["states"].get(state, 0.0)
            tol = max(0.10 * v_live, 0.05)
            if finals.get(rank, 0) <= 1:
                # single incarnation journaled a final: offline replay
                # must agree with the live accounting +-10% per state
                assert abs(v_off - v_live) <= tol, \
                    (rank, state, v_live, v_off)
            else:
                # a parked-then-revived rank sums finals over ALL its
                # incarnations offline, while the live report covers
                # only the last one: offline is a superset
                assert v_off >= v_live - tol, \
                    (rank, state, v_live, v_off)
        # the offline-only keys carry gap chip-time, never live keys
        assert "restart_gap" not in gp["states"]
    # rank 0's restart gap landed in the offline-only ledger
    assert recon["ranks"]["0"]["offline_states"].get(
        "restart_gap", 0.0) > 0
