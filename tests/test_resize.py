"""True elasticity (ISSUE 14): dynamic world size with checkpoint
resharding.

Three layers of drill:

* **protocol units** — the task master's ``request_resize`` epoch-
  boundary semantics, retire/wait directives, snapshot persistence of a
  pending resize across a master restart, and the supervisor's
  grow/park/revive machinery (incl. the live-world respawn-env bugfix);
* **tier-1 miniature** — the headline soak shrunk to a few seconds:
  a supervised fleet scales 2→4→1→3 mid-training and lands the exact
  fixed-fleet end state with a clean exactly-once ledger and zero
  lost/double-consumed reader examples;
* **dp resume parity** — a REAL training run under a data-parallel
  mesh checkpoints, the checkpoint reshards N→M on disk, and training
  resumes under a DIFFERENT mesh landing the same loss as the
  fixed-mesh run (the promote-from-dryrun lane; dp×tp in the slow
  marker).
"""
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.core.place import make_mesh
from paddle_tpu.distributed.supervisor import Supervisor
from paddle_tpu.distributed.task_queue import (TaskMaster,
                                               TaskMasterClient,
                                               serve_master)
from paddle_tpu.incubate import checkpoint as ckpt
from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import soak
from paddle_tpu.resilience.elastic_worker import RETIRED_RC


def _counter(name):
    m = obs.REGISTRY.get(name)
    return 0.0 if m is None else m.total()


def _drain_epoch(m, rank, n):
    for _ in range(n):
        t = m.get_task(worker=rank)
        assert t is not None
        assert m.task_finished(t.task_id, lease=t.lease,
                               worker=rank) == "ok"


# ------------------------------------------------- resize protocol units

def test_resize_pends_until_epoch_boundary():
    """Mid-epoch request pends; the drained queue is the boundary and
    the recycled epoch runs under the new world."""
    m = TaskMaster(num_epochs=2, world_size=2)
    m.set_dataset(["a", "b", "c"])
    r0 = _counter("fleet_resizes_total")
    rep = m.request_resize(3)
    assert rep["applied"] is False and rep["pending_world_size"] == 3
    assert m.target_world_size == 2
    assert _counter("fleet_resizes_total") == r0
    _drain_epoch(m, 0, 3)                  # epoch 0 drains
    assert m.target_world_size == 3 and m.pending_world_size is None
    assert m.resizes == 1
    assert _counter("fleet_resizes_total") == r0 + 1


def test_resize_applies_immediately_when_idle():
    m = TaskMaster(world_size=2)
    rep = m.request_resize(5)
    assert rep["applied"] is True and m.target_world_size == 5


def test_retire_and_wait_directives():
    """A pending grow makes the joining rank WAIT; an effective shrink
    makes out-of-world ranks RETIRE — and they can no longer lease."""
    m = TaskMaster(num_epochs=3, world_size=2)
    m.set_dataset(["a", "b"])
    m.request_resize(3)
    # rank 2 joins early: no lease, wait directive
    assert m.get_task(worker=2) is None
    assert m.worker_directive(2) == {"wait_resize": True,
                                     "target_world_size": 2}
    _drain_epoch(m, 0, 2)                  # grow applies
    assert m.worker_directive(2) == {}
    m.request_resize(1)
    assert m.worker_directive(1) == {}     # in-world until the boundary
    _drain_epoch(m, 1, 2)                  # shrink applies
    assert m.worker_directive(1) == {"retire": True,
                                     "target_world_size": 1}
    assert m.get_task(worker=1) is None    # no leases outside the world
    assert m.get_task(worker=0) is not None
    # in-world / legacy callers see no directive
    assert m.worker_directive(0) == {}
    assert m.worker_directive(None) == {}


def test_shrink_requeues_in_flight_leases_cleanly():
    """A retiring rank's outstanding lease requeues through the normal
    membership/fence machinery: the re-leased copy completes exactly
    once and the zombie ack fences."""
    m = TaskMaster(num_epochs=2, world_size=2, worker_timeout=0.05)
    m.set_dataset(["a", "b"])
    m.register_worker(1)
    t_held = m.get_task(worker=1)          # rank 1 leases, then dies
    _drain_epoch(m, 0, 1)                  # the other task completes
    m.request_resize(1)                    # shrink pending
    time.sleep(0.08)
    m.tick()                               # rank 1's heartbeat expires
    # the lease requeued; epoch 0 drains via rank 0 -> shrink applies
    t = m.get_task(worker=0)
    assert t is not None and t.task_id == t_held.task_id
    assert m.task_finished(t_held.task_id, lease=t_held.lease) == "fenced"
    assert m.task_finished(t.task_id, lease=t.lease, worker=0) == "ok"
    assert m.target_world_size == 1
    assert soak.check_ledger(
        m.ledger_entries(), 2, 1) == []    # epoch 0 exactly once


def test_pending_resize_survives_master_restart(tmp_path):
    """A resize requested before a master crash still applies at the
    next epoch boundary after recovery."""
    snap = str(tmp_path / "master.json")
    m = TaskMaster(snapshot_path=snap, snapshot_interval=0.0,
                   num_epochs=2, world_size=2)
    m.set_dataset(["a", "b"])
    m.request_resize(4)
    m2 = TaskMaster(snapshot_path=snap, snapshot_interval=0.0,
                    num_epochs=2)
    assert m2.target_world_size == 2
    assert m2.pending_world_size == 4
    _drain_epoch(m2, 0, 2)
    assert m2.target_world_size == 4 and m2.resizes == 1


def test_resize_rpc_roundtrip():
    """request_resize + directives over the TCP transport."""
    m = TaskMaster(num_epochs=2, world_size=1)
    m.set_dataset(["a"])
    srv, (host, port) = serve_master(m)
    try:
        with TaskMasterClient(host, port) as c:
            rep = c.request_resize(2)
            assert rep["pending_world_size"] == 2
            assert c.get_task(worker=1) is None
            assert c.wait_resize and not c.retire
            _drain_epoch(m, 0, 1)          # grow applies
            t = c.get_task(worker=1)
            assert t is not None
            assert c.task_finished(t.task_id, lease=t.lease,
                                   worker=1) == "ok"
            # epoch 1 (the final one) just drained -> job complete,
            # so this resize applies immediately on the idle queue
            m.request_resize(1)
        with TaskMasterClient(host, port) as c2:
            assert c2.get_task(worker=1) is None   # queue drained
            assert m.target_world_size == 1
            assert c2.retire or c2.job_complete
    finally:
        srv.shutdown()

    with pytest.raises(ValueError):
        m.request_resize(0)


def test_stats_and_gauge_track_target_world():
    m = TaskMaster(world_size=3)
    s = m.stats()
    assert s["target_world_size"] == 3 and s["resizes"] == 0
    g = obs.REGISTRY.get("fleet_target_world_size")
    assert g.value == 3


# ----------------------------------------------------- supervisor resize

def _fast_backoff():
    from paddle_tpu.resilience import retry as rretry
    return rretry.RetryPolicy(name="supervisor_restart", max_attempts=1,
                              base_delay=0.01, max_delay=0.05)


def _py(code):
    return [sys.executable, "-c", code]


def test_supervisor_grow_spawns_via_factory(tmp_path):
    """set_world_size past the launch fleet spawns new ranks from
    cmd_factory, with the live world in their env."""
    code = ("import os,sys,pathlib\n"
            "pathlib.Path(sys.argv[1]).write_text("
            "os.environ['PTPU_FLEET_WORLD_SIZE'])\n")

    def cmd(rank):
        return [sys.executable, "-c", code,
                str(tmp_path / f"r{rank}.txt")]

    sup = Supervisor([cmd(0)], cmd_factory=cmd,
                     backoff=_fast_backoff())
    sup.start()
    sup.set_world_size(3)
    assert sup.wait(timeout=30)
    assert (tmp_path / "r2.txt").read_text() == "3"
    # rank 0 was spawned at launch world 1
    assert (tmp_path / "r0.txt").read_text() == "1"
    sup.stop()


def test_supervisor_grow_without_factory_raises():
    sup = Supervisor([_py("pass")])
    with pytest.raises(ValueError, match="cmd_factory"):
        sup.set_world_size(2)


def test_supervisor_parks_retire_rc_and_revives(tmp_path):
    """A worker exiting with retire_rc is PARKED (state retired, run
    still counts as clean); growing back over it revives a new
    incarnation that sees the live world."""
    marker = tmp_path / "mode"
    marker.write_text("retire")
    code = ("import os,sys,pathlib\n"
            "root = pathlib.Path(sys.argv[1])\n"
            "(root / ('seen_' + os.environ['PTPU_WORKER_RESTART_COUNT'])"
            ").write_text(os.environ['PTPU_FLEET_WORLD_SIZE'])\n"
            "sys.exit(7 if (root / 'mode').read_text() == 'retire' "
            "else 0)\n")

    def cmd(rank):
        return [sys.executable, "-c", code, str(tmp_path)]

    sup = Supervisor([cmd(0), cmd(1)], cmd_factory=cmd, retire_rc=7,
                     backoff=_fast_backoff())
    sup.target_world = 1                   # rank 1 retires at launch
    sup.start()
    deadline = time.time() + 30
    while sup.status()[1]["state"] != "retired" \
            and time.time() < deadline:
        time.sleep(0.02)
    assert sup.status()[1]["state"] == "retired"
    assert sup.wait(timeout=30)            # retired counts as clean
    marker.write_text("done")              # revived incarnation exits 0
    sup.set_world_size(2)
    deadline = time.time() + 30
    while sup.status()[1]["state"] != "done" and time.time() < deadline:
        time.sleep(0.02)
    assert sup.status()[1]["state"] == "done"
    # the revived incarnation ran with the LIVE world (2), not the
    # launch-time one — the ISSUE 14 respawn-env bugfix
    assert (tmp_path / "seen_1").read_text() == "2"
    assert sup.spawns[1] == 2
    sup.stop()


def test_supervisor_does_not_respawn_outside_world():
    """A crash of a rank the fleet shrank past parks it instead of
    burning restarts respawning into a world it left."""
    sup = Supervisor([_py("import sys; sys.exit(1)")] * 2,
                     max_restarts=5, backoff=_fast_backoff())
    sup.target_world = 1
    sup.start()
    deadline = time.time() + 30
    while sup.status()[1]["state"] != "retired" \
            and time.time() < deadline:
        time.sleep(0.02)
    assert sup.status()[1]["state"] == "retired"
    assert sup.restarts[1] == 0
    sup.stop()


# ------------------------------------------------- tier-1 headline lane

def test_miniature_soak_grow_shrink_grow(tmp_path):
    """The ISSUE 14 headline, miniature: a supervised fleet scales
    2→4→1→3 mid-training (each resize at an epoch boundary), completes
    hands-off, and lands the EXACT fixed-fleet end state — the ledger
    is exactly-once and the per-rank consumed records cover every
    (shard, epoch) reader example exactly once (nothing lost, nothing
    double-consumed across the resizes)."""
    rep = soak.run_schedule(str(tmp_path), "resize_soak", world=2,
                            n_tasks=4, epochs=2, timeout=90)
    assert rep["ok"], rep["problems"]
    assert rep["resizes_applied"] == 3
    assert rep["stats"]["target_world_size"] == 3
    assert rep["ledger_entries"] == 4 * 4      # 4 tasks x 4 epochs
    assert rep["w_total"] == pytest.approx(rep["expected_w_total"],
                                           abs=1e-9)
    ranks = {w["rank"] for w in rep["workers"]}
    assert ranks == {0, 1, 2, 3}               # the grown fleet existed
    # the master's resize_log is the ground truth for which epoch each
    # world governed (boundaries can outpace the driver): the plan
    # applied in order, and every epoch governed by the shrunk world
    # was worked ONLY by rank 0
    log = rep["stats"]["resize_log"]
    assert [r["new"] for r in log] == [4, 1, 3]
    ledger = _ledger_of(tmp_path)
    for ep in range(log[1]["epoch"], log[2]["epoch"]):
        assert {e["worker"] for e in ledger
                if e["epoch"] == ep} <= {0}, ep


def _ledger_of(workdir):
    """Read the persisted master ledger from the soak's snapshot."""
    import zlib
    with open(os.path.join(str(workdir), "master.json")) as f:
        doc = json.load(f)
    payload = doc["state"]
    assert zlib.crc32(payload.encode()) == doc["crc"]
    return json.loads(payload)["ledger"]


def test_miniature_soak_grow_with_worker_kill(tmp_path):
    """resize_combined: the fleet grows 2→3 while chaos kill-9s rank 0
    mid-task; the supervisor restarts it into the LIVE world and the
    end state still lands exactly."""
    rep = soak.run_schedule(str(tmp_path), "resize_combined", world=2,
                            n_tasks=6, epochs=2, timeout=90)
    assert rep["ok"], rep["problems"]
    assert rep["restarts"][0] >= 1
    w = {r["rank"]: r for r in rep["workers"]}
    # the respawned incarnation reported the live (grown or launch)
    # world, whichever was current at its spawn — never a stale one
    assert w[0]["restart_count"] >= 1
    assert w[0]["world"] in (2, 3)
    assert rep["w_total"] == pytest.approx(rep["expected_w_total"],
                                           abs=1e-9)


# --------------------------------------- dp resize: real training plane

def _build_lm():
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 11
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, size=8, act="relu", name="fc1")
        pred = layers.fc(h, size=1, name="fc2")
        loss = layers.mean(layers.square_error_cost(pred, y))
        pt.optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def _dp_batches(n=6, bs=16):
    rng = np.random.RandomState(3)
    w = rng.randn(8, 1).astype(np.float32)
    return [(xb, xb @ w) for xb in
            (rng.randn(bs, 8).astype(np.float32) for _ in range(n))]


def _param_state(scope, program):
    return {p.name: np.asarray(scope.find_var(p.name))
            for p in program.all_parameters()}


def test_dp_resize_reshard_resume_loss_parity(tmp_path):
    """Elastic dp promoted from dryrun: train 3 steps on a 2-device
    data-parallel mesh, checkpoint, reshard the checkpoint on disk
    (1→4 shard files), resume on a 4-device mesh from the RESHARDED
    manifest, train 3 more steps — the final loss matches a fixed
    2-device run, and the resumed params are bit-identical to an
    unresharded resume."""
    batches = _dp_batches()
    root = str(tmp_path / "ck")

    def run(mesh, scope, lo, hi, main, startup, loss, init=True):
        exe = pt.Executor(pt.CPUPlace(), scope=scope, mesh=mesh)
        if init:
            exe.run(startup)
        out = []
        for xb, yb in batches[lo:hi]:
            out.append(float(np.asarray(exe.run(
                main, feed={"x": xb, "y": yb},
                fetch_list=[loss.name])[0])))
        return out

    # fixed-fleet baseline: 6 steps, one 2-device mesh
    main, startup, loss = _build_lm()
    mesh2 = make_mesh((2,), ("data",))
    scope_fixed = pt.Scope()
    fixed = run(mesh2, scope_fixed, 0, 6, main, startup, loss)

    # elastic: 3 steps on d2, checkpoint, reshard, resume on d4
    scope_a = pt.Scope()
    first = run(mesh2, scope_a, 0, 3, main, startup, loss)
    state = _param_state(scope_a, main)
    ckpt.save_checkpoint(root, state, {"step": 3})
    new_serial = ckpt.reshard_checkpoint(root, 4)
    resharded, meta = ckpt.load_state(
        os.path.join(root, f"checkpoint_{new_serial}"))
    direct, _ = ckpt.load_state(os.path.join(root, "checkpoint_0"))
    for name in state:
        # acceptance: resharded resume is BIT-identical to unresharded
        assert np.array_equal(resharded[name], direct[name]), name
        assert resharded[name].dtype == direct[name].dtype
    assert meta["resharded_from"] == 0

    mesh4 = make_mesh((4,), ("data",))
    scope_b = pt.Scope()
    exe_b = pt.Executor(pt.CPUPlace(), scope=scope_b, mesh=mesh4)
    exe_b.run(startup)                      # allocate, then overwrite
    for name, val in resharded.items():
        scope_b.set_var(name, val)
    second = run(mesh4, scope_b, 3, 6, main, startup, loss, init=False)

    np.testing.assert_allclose(first + second, fixed,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_dp_tp_resize_reshard_resume_parity(tmp_path):
    """dp×tp: a model-sharded weight trains on a ("data",2)×("model",2)
    mesh, checkpoints, reshards along its MODEL axis via the layout
    override, and resumes on a ("data",4)×("model",2) mesh with the
    same loss trajectory as the fixed mesh."""
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 13
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        w_attr = pt.ParamAttr(name="tp_w", sharding=(None, "model"))
        h = layers.fc(x, size=8, act="relu", param_attr=w_attr,
                      bias_attr=False)
        pred = layers.fc(h, size=1, name="head")
        loss = layers.mean(layers.square_error_cost(
            pred, layers.data("y", shape=[1])))
        pt.optimizer.SGD(0.05).minimize(loss)
    batches = _dp_batches()

    def run(mesh, scope, lo, hi, init):
        exe = pt.Executor(pt.CPUPlace(), scope=scope, mesh=mesh)
        if init:
            exe.run(startup)
        return [float(np.asarray(exe.run(
            main, feed={"x": xb, "y": yb},
            fetch_list=[loss.name])[0])) for xb, yb in batches[lo:hi]]

    mesh22 = make_mesh((2, 2), ("data", "model"))
    scope_fixed = pt.Scope()
    fixed = run(mesh22, scope_fixed, 0, 6, True)

    scope_a = pt.Scope()
    first = run(mesh22, scope_a, 0, 3, True)
    root = str(tmp_path / "ck")
    state = _param_state(scope_a, main)
    ckpt.save_checkpoint(root, state, {"step": 3})
    # tp weights split along their sharded (model) axis, dense state
    # along axis 0 — the layout knob
    serial = ckpt.reshard_checkpoint(
        root, 2, layout={"tp_w": 1})
    resharded, _ = ckpt.load_state(
        os.path.join(root, f"checkpoint_{serial}"))
    mesh42 = make_mesh((4, 2), ("data", "model"))
    scope_b = pt.Scope()
    exe_b = pt.Executor(pt.CPUPlace(), scope=scope_b, mesh=mesh42)
    exe_b.run(startup)
    for name, val in resharded.items():
        scope_b.set_var(name, val)
    second = run(mesh42, scope_b, 3, 6, False)
    np.testing.assert_allclose(first + second, fixed,
                               rtol=1e-4, atol=1e-5)


# ------------------------------------------------------ slow: full soak

@pytest.mark.slow
def test_resize_matrix_vs_fixed_fleet(tmp_path):
    """The full resize matrix at default sizes, plus the direct
    fixed-fleet comparison the headline promises: the resize_soak run's
    fleet-summed end state equals an actual fixed-fleet run's."""
    fixed = soak.run_schedule(str(tmp_path / "fixed"), "fixed",
                              world=2, n_tasks=6, epochs=4, timeout=120)
    assert fixed["ok"], fixed["problems"]
    for name in ("resize_grow", "resize_shrink", "resize_combined",
                 "resize_soak"):
        rep = soak.run_schedule(str(tmp_path / name), name, world=2,
                                n_tasks=6, epochs=4, timeout=120)
        assert rep["ok"], (name, rep["problems"])
        # same data, same epochs -> same fleet end state as the fixed
        # run, to the float-sum tolerance
        assert rep["w_total"] == pytest.approx(fixed["w_total"],
                                               abs=1e-9), name


def test_applied_resize_survives_relaunch_with_launch_world(tmp_path):
    """Review regression: a master relaunched with its LAUNCH-time
    world_size must keep the snapshot's APPLIED resize target — the
    snapshot is newer truth, and reverting it would silently direct
    the grown ranks to retire."""
    snap = str(tmp_path / "master.json")
    m = TaskMaster(snapshot_path=snap, snapshot_interval=0.0,
                   num_epochs=2, world_size=2)
    m.set_dataset(["a", "b"])
    m.request_resize(4)
    _drain_epoch(m, 0, 2)                  # grow applies
    assert m.target_world_size == 4
    # relaunch with the ORIGINAL argv world (the deployment-script
    # shape): the persisted target must win
    m2 = TaskMaster(snapshot_path=snap, snapshot_interval=0.0,
                    num_epochs=2, world_size=2)
    assert m2.target_world_size == 4
    assert m2.worker_directive(3) == {}    # rank 3 stays in-world
    assert m2.resize_log[-1]["new"] == 4
