"""Persistent executable cache (ISSUE 12, framework/jit_cache.py).

Covers: raw store/load round trip, in-proc + CROSS-PROCESS warm starts
with zero new XLA compiles (executor step, run_steps device loop,
Predictor grid, serving bucket grid — token-identical outputs), the
corrupt-entry fallback matrix (truncated / bit-flipped / bad magic /
wrong-jaxlib header -> loud warning + jit_cache_errors_total +
recompile, NEVER a failed start), stale-flags = clean miss (no error),
LRU eviction order, the verified-programs-only store gate, supervisor
env propagation, flag-off byte-identical behavior, and the CLI
exit-code contract (the xray/lint idiom).
"""
import json
import os
import struct
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.framework import jit_cache
from paddle_tpu.observability import forensics
from paddle_tpu.observability import metrics as obs_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tot(name):
    m = obs_metrics.REGISTRY.get(name)
    return 0.0 if m is None else m.total()


def _build_fc():
    img = layers.data("img", [8], dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    pred = layers.fc(img, size=4, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, label))
    return loss


def _feed(batch=4):
    rng = np.random.RandomState(0)
    return {"img": rng.rand(batch, 8).astype("float32"),
            "label": rng.randint(0, 4, (batch, 1)).astype("int64")}


def _entries(d):
    return sorted(f for f in os.listdir(d) if f.endswith(".jc"))


# --- raw API ---------------------------------------------------------------

def test_store_load_roundtrip_and_ls(tmp_path):
    import jax
    import jax.numpy as jnp
    flags.set_flag("jit_cache_dir", str(tmp_path))
    fn = jax.jit(lambda x: x * 3.0)
    x = jnp.arange(6, dtype=jnp.float32)
    compiled = fn.lower(x).compile()
    comps = {"probe": "roundtrip"}
    khash = jit_cache.entry_key("executor_step", comps)
    assert jit_cache.store("executor_step", khash, comps, compiled)
    h0 = _tot("jit_cache_hits_total")
    back = jit_cache.load("executor_step", khash, comps)
    assert back is not None
    assert np.array_equal(np.asarray(back(x)), np.arange(6) * 3.0)
    assert _tot("jit_cache_hits_total") == h0 + 1
    rows = jit_cache.ls()
    assert len(rows) == 1
    assert rows[0]["kind"] == "executor_step"
    assert rows[0]["hits"] == 1
    assert rows[0]["components"] == {"probe": "roundtrip"}
    assert rows[0]["bytes"] > 0


def test_entry_key_stable_and_flag_sensitive():
    comps = {"program": "abc", "feeds": [["x", [2, 4], "float32"]]}
    k1 = jit_cache.entry_key("executor_step", comps)
    k2 = jit_cache.entry_key("executor_step", dict(comps))
    assert k1 == k2
    assert jit_cache.entry_key("executor_multi", comps) != k1
    comps2 = dict(comps, flags=jit_cache.numerics_flags())
    old = flags.get_flag("quantize_dtype")
    try:
        flags.set_flag("quantize_dtype", "int8")
        comps3 = dict(comps, flags=jit_cache.numerics_flags())
    finally:
        flags.set_flag("quantize_dtype", old)
    assert jit_cache.entry_key("executor_step", comps2) \
        != jit_cache.entry_key("executor_step", comps3)


# --- executor: in-proc warm start ------------------------------------------

def test_executor_warm_start_inproc(tmp_path):
    flags.set_flag("jit_cache_dir", str(tmp_path))
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = _feed()
    prog = pt.default_main_program()
    out_cold = exe.run(prog, feed=feed, fetch_list=[loss])
    assert len(_entries(tmp_path)) == 2     # startup + main step
    # a second Executor = the restarted-process shape (fresh in-memory
    # jit cache): its miss must resolve from DISK with zero compiles
    # and a silent forensics log
    c0 = _tot("executor_compile_total")
    f0 = len(forensics.compile_log())
    exe2 = pt.Executor(pt.CPUPlace(), scope=exe.scope)
    out_warm = exe2.run(prog, feed=feed, fetch_list=[loss])
    assert _tot("executor_compile_total") == c0
    assert len(forensics.compile_log()) == f0
    assert np.array_equal(out_cold[0], out_warm[0])
    rep = exe2.explain(prog, feed=feed, fetch_list=[loss])
    assert rep["jit_cache"]["source"] == "disk"
    assert rep["jit_cache"]["hits"] >= 1
    # the cold process's compile log marked its misses as cache-bound
    assert forensics.compile_log()[-1]["jit_cache"] == "miss"


def test_donate_feeds_twin_persisted_warm(tmp_path):
    """PR 12 follow-up (ISSUE 15 satellite): the donate-feeds twin
    executable (the trainer ``prefetch_depth`` path) persists under its
    own key — step components + a ``donate_feeds`` marker — so a warm
    prefetch restart deserializes it: compile counters and forensics
    stay FROZEN and outputs are bit-identical."""
    flags.set_flag("jit_cache_dir", str(tmp_path))
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    prog = pt.default_main_program()
    out_cold = exe.run(prog, feed=_feed(), fetch_list=[loss],
                       donate_feeds=True)
    # startup entry + the donate twin; the PLAIN step entry is NOT
    # stored (nothing ever dispatched it — no hidden AOT work)
    assert len(_entries(tmp_path)) == 2
    rows = {r["hash"]: r["components"] for r in jit_cache.ls()}
    donate_rows = [c for c in rows.values()
                   if c.get("donate_feeds") is True]
    assert len(donate_rows) == 1
    c0 = _tot("executor_compile_total")
    f0 = len(forensics.compile_log())
    h0 = _tot("jit_cache_hits_total")
    e0 = _tot("jit_cache_errors_total")
    # the restarted-process shape: fresh in-memory jit cache, donating
    # dispatch resolves the TWIN from disk — zero compile bookings
    exe2 = pt.Executor(pt.CPUPlace(), scope=exe.scope)
    out_warm = exe2.run(prog, feed=_feed(), fetch_list=[loss],
                        donate_feeds=True)
    assert _tot("executor_compile_total") == c0
    assert len(forensics.compile_log()) == f0
    assert _tot("jit_cache_hits_total") == h0 + 1
    assert _tot("jit_cache_errors_total") == e0
    assert np.array_equal(out_cold[0], out_warm[0])
    rep = exe2.explain(prog, feed=_feed(), fetch_list=[loss])
    assert rep["jit_cache"]["source"] == "disk"


def test_donate_twin_and_plain_entries_coexist(tmp_path):
    """Donating and plain dispatches of the SAME program key two
    distinct entries; a warm process serves each path from its own
    artifact with identical outputs."""
    flags.set_flag("jit_cache_dir", str(tmp_path))
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    prog = pt.default_main_program()
    out_plain = exe.run(prog, feed=_feed(), fetch_list=[loss])
    out_donate = exe.run(prog, feed=_feed(), fetch_list=[loss],
                         donate_feeds=True)
    assert np.array_equal(out_plain[0], out_donate[0])
    # startup + plain step + donate twin
    assert len(_entries(tmp_path)) == 3
    comps = [r["components"] for r in jit_cache.ls()]
    assert sum(1 for c in comps
               if c.get("donate_feeds") is True) == 1
    h0 = _tot("jit_cache_hits_total")
    c0 = _tot("executor_compile_total")
    n_entries = len(_entries(tmp_path))
    # warm restart dispatching DONATE first: the twin resolves in
    # _prepare, and the later plain dispatch must resolve its OWN
    # entry from disk too (a hit, not a silent AOT recompile + restore)
    exe2 = pt.Executor(pt.CPUPlace(), scope=exe.scope)
    w_donate = exe2.run(prog, feed=_feed(), fetch_list=[loss],
                        donate_feeds=True)
    w_plain = exe2.run(prog, feed=_feed(), fetch_list=[loss])
    assert _tot("jit_cache_hits_total") == h0 + 2
    assert _tot("executor_compile_total") == c0
    assert len(_entries(tmp_path)) == n_entries
    assert np.array_equal(w_plain[0], out_plain[0])
    assert np.array_equal(w_donate[0], out_donate[0])


def test_run_steps_warm_start_inproc(tmp_path):
    flags.set_flag("jit_cache_dir", str(tmp_path))
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    prog = pt.default_main_program()
    out_cold = exe.run_steps(prog, feed=_feed(), fetch_list=[loss],
                             steps=3)
    multi = obs_metrics.REGISTRY.get("executor_compile_total").labels(
        kind="multi_step")
    c0 = multi.value
    m0 = _tot("executor_multi_cache_miss_total")
    h0 = _tot("jit_cache_hits_total")
    exe2 = pt.Executor(pt.CPUPlace(), scope=exe.scope)
    # the warm executor's device loop deserializes: the multi compile
    # counter and multi-miss counter stay FROZEN.  (The step-kind
    # counter still books its in-memory cache entry — pre-existing
    # semantics: run_steps never dispatches the plain step, so no XLA
    # work hides behind it.)
    out_warm = exe2.run_steps(prog, feed=_feed(), fetch_list=[loss],
                              steps=3)
    assert multi.value == c0
    assert _tot("executor_multi_cache_miss_total") == m0
    assert _tot("jit_cache_hits_total") > h0
    assert out_warm[0].shape == out_cold[0].shape
    assert np.all(np.isfinite(out_warm[0]))
    # the warm loop keeps a lowerable jit twin so multi_cost() is not
    # silently None on warm processes (review finding)
    assert exe2._last_compiled._multi_jit


def test_flag_off_byte_identical(tmp_path):
    """jit_cache_dir unset -> pre-cache behavior: no entries, no
    jit_cache counters, no explain() section, compile-log records
    carry no jit_cache field."""
    assert flags.get_flag("jit_cache_dir") == ""
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    h0, m0 = _tot("jit_cache_hits_total"), _tot("jit_cache_misses_total")
    out = exe.run(pt.default_main_program(), feed=_feed(),
                  fetch_list=[loss])
    assert np.all(np.isfinite(out[0]))
    assert _tot("jit_cache_hits_total") == h0
    assert _tot("jit_cache_misses_total") == m0
    assert _entries(tmp_path) == []
    rep = exe.explain(pt.default_main_program(), feed=_feed(),
                      fetch_list=[loss])
    assert "jit_cache" not in rep
    assert all("jit_cache" not in r for r in forensics.compile_log())


# --- cross-process warm start (the headline) -------------------------------

def _run_probe(cache_dir):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PTPU_JIT_CACHE_DIR"] = str(cache_dir)
    env.pop("PTPU_CHAOS_SPEC", None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.framework.jit_cache",
         "--restart-probe", "lm"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("RESTART_PROBE ")]
    assert proc.returncode == 0 and lines, (proc.stdout, proc.stderr)
    return json.loads(lines[-1][len("RESTART_PROBE "):])


def test_cross_process_warm_start(tmp_path):
    """Compile in subprocess A, load in subprocess B: B records ZERO
    XLA compiles (executor_compile_total frozen at 0 for the whole
    process) and bit-identical losses — the acceptance headline."""
    cold = _run_probe(tmp_path)
    assert cold["executor_compile_total"] > 0
    assert cold["jit_cache_misses_total"] > 0
    assert cold["restart_to_first_step_seconds"] > 0
    warm = _run_probe(tmp_path)
    assert warm["executor_compile_total"] == 0
    assert warm["jit_cache_hits_total"] >= 2        # step + multi/init
    assert warm["jit_cache_errors_total"] == 0
    assert warm["losses"] == cold["losses"]


# --- corrupt-entry fallback matrix -----------------------------------------

def _corrupt_all(d, mode):
    for name in _entries(d):
        path = os.path.join(d, name)
        raw = open(path, "rb").read()
        if mode == "truncated":
            doctored = raw[:len(raw) // 2]
        elif mode == "bit_flip":
            b = bytearray(raw)
            b[-3] ^= 0x40               # inside the pickled body
            doctored = bytes(b)
        elif mode == "bad_magic":
            doctored = b"NOTJCMAG" + raw[8:]
        elif mode == "stale_jaxlib":
            fixed = 8 + 4
            (hlen,) = struct.unpack("<I", raw[8:fixed])
            header = json.loads(raw[fixed:fixed + hlen].decode())
            header["env"]["jaxlib"] = "0.0.0-foreign-build"
            hdr = json.dumps(header, sort_keys=True).encode()
            doctored = (raw[:8] + struct.pack("<I", len(hdr)) + hdr
                        + raw[fixed + hlen:])
        else:
            raise AssertionError(mode)
        with open(path, "wb") as f:
            f.write(doctored)


@pytest.mark.parametrize("mode", ["truncated", "bit_flip", "bad_magic",
                                  "stale_jaxlib"])
def test_corrupt_entry_recompiles_with_warning(tmp_path, mode):
    flags.set_flag("jit_cache_dir", str(tmp_path))
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = _feed()
    prog = pt.default_main_program()
    out_good = exe.run(prog, feed=feed, fetch_list=[loss])
    assert _entries(tmp_path)
    _corrupt_all(tmp_path, mode)
    e0 = _tot("jit_cache_errors_total")
    c0 = _tot("executor_compile_total")
    exe2 = pt.Executor(pt.CPUPlace(), scope=exe.scope)
    with pytest.warns(RuntimeWarning, match="jit_cache"):
        out = exe2.run(prog, feed=feed, fetch_list=[loss])
    # loud counter + a REAL recompile + correct outputs — never a
    # bricked start
    assert _tot("jit_cache_errors_total") > e0
    assert _tot("executor_compile_total") > c0
    assert np.array_equal(out[0], out_good[0])
    # the bad entry was dropped and re-stored by the recompile
    assert _entries(tmp_path)


def test_stale_flags_is_clean_miss_not_error(tmp_path):
    """A numerics-flag flip changes the KEY (fresh entry), it does not
    poison the old one: recompile with NO corruption warning and NO
    error counter movement."""
    flags.set_flag("jit_cache_dir", str(tmp_path))
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = _feed()
    prog = pt.default_main_program()
    exe.run(prog, feed=feed, fetch_list=[loss])
    n_before = len(_entries(tmp_path))
    e0 = _tot("jit_cache_errors_total")
    old = flags.get_flag("amp_bf16")
    try:
        flags.set_flag("amp_bf16", True)
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            exe.run(prog, feed=feed, fetch_list=[loss])
        assert not [w for w in rec
                    if "jit_cache" in str(w.message)]
    finally:
        flags.set_flag("amp_bf16", old)
    assert _tot("jit_cache_errors_total") == e0
    assert len(_entries(tmp_path)) == n_before + 1      # fresh entry


# --- LRU GC ----------------------------------------------------------------

def test_lru_eviction_order(tmp_path):
    import jax
    import jax.numpy as jnp
    flags.set_flag("jit_cache_dir", str(tmp_path))
    hashes = []
    for i, n in enumerate((4, 8, 16)):
        fn = jax.jit(lambda x: x + 1.0)
        compiled = fn.lower(
            jnp.zeros((n,), jnp.float32)).compile()
        comps = {"i": i}
        khash = jit_cache.entry_key("executor_step", comps)
        assert jit_cache.store("executor_step", khash, comps, compiled)
        hashes.append(khash)
    paths = [os.path.join(tmp_path, h + ".jc") for h in hashes]
    sizes = [os.path.getsize(p) for p in paths]
    # explicit LRU stamps: entry 1 oldest, then 0, then 2 newest
    now = 1_700_000_000
    for h, t in zip(hashes, (now + 10, now, now + 20)):
        os.utime(os.path.join(tmp_path, h + ".jc"), (t, t))
    ev0 = _tot("jit_cache_evictions_total")
    # budget for exactly two entries -> the oldest-mtime one (index 1)
    # must go first
    evicted = jit_cache.gc(limit_bytes=sizes[0] + sizes[2] + 1)
    assert evicted == 1
    assert _tot("jit_cache_evictions_total") == ev0 + 1
    left = _entries(tmp_path)
    assert hashes[1] + ".jc" not in left
    assert hashes[0] + ".jc" in left and hashes[2] + ".jc" in left
    # a LOAD refreshes mtime: now 0 is oldest -> next squeeze drops it
    assert jit_cache.load("executor_step", hashes[2],
                          {"i": 2}) is not None
    os.utime(os.path.join(tmp_path, hashes[0] + ".jc"), (now, now))
    assert jit_cache.gc(limit_bytes=sizes[2] + 1) == 1
    assert hashes[0] + ".jc" not in _entries(tmp_path)
    assert hashes[2] + ".jc" in _entries(tmp_path)
    # purge drops everything and zeroes the gauge
    assert jit_cache.purge() == 1
    assert _entries(tmp_path) == []


# --- verified-programs-only store gate -------------------------------------

def test_unverified_program_not_stored(tmp_path, monkeypatch):
    """The PR 10 gate: a program the analysis plane cannot vouch for
    (here: the verifier itself blows up) still RUNS, but nothing is
    persisted and jit_cache_unverified_total counts it."""
    from paddle_tpu import analysis

    def boom(*a, **k):
        raise RuntimeError("verifier exploded")
    monkeypatch.setattr(analysis, "verify_program", boom)
    flags.set_flag("jit_cache_dir", str(tmp_path))
    flags.set_flag("verify_program", "off")   # gate still runs for store
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    u0 = _tot("jit_cache_unverified_total")
    exe.run(pt.default_startup_program())
    out = exe.run(pt.default_main_program(), feed=_feed(),
                  fetch_list=[loss])
    assert np.all(np.isfinite(out[0]))      # the run itself is untouched
    assert _entries(tmp_path) == []         # nothing persisted
    assert _tot("jit_cache_unverified_total") > u0


# --- predictor + serving warm grids ----------------------------------------

def test_predictor_warm_grid(tmp_path):
    from paddle_tpu import inference, io
    flags.set_flag("jit_cache_dir", str(tmp_path / "jc"))
    img = layers.data("img", [8], dtype="float32")
    pred = layers.fc(img, size=4, act="softmax")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    model_dir = tmp_path / "model"
    os.makedirs(model_dir)
    io.save_inference_model(str(model_dir), ["img"], [pred], exe)
    cfg = inference.NativeConfig(model_dir=str(model_dir), use_tpu=False)
    feed = {"img": np.random.RandomState(1).rand(2, 8).astype("f4")}
    h_cold = _tot("jit_cache_hits_total")
    p1 = inference.Predictor(cfg)
    p1.prepare(feed)
    out_cold = p1.run(feed)
    assert _tot("jit_cache_hits_total") == h_cold   # cold: no hit
    # a fresh Predictor (fresh process shape: empty _compiled dict)
    # deserializes the grid — zero compiles, identical outputs
    h0 = _tot("jit_cache_hits_total")
    p2 = inference.Predictor(cfg)
    p2.prepare(feed)
    out_warm = p2.run(feed)
    assert _tot("jit_cache_hits_total") == h0 + 1
    assert np.array_equal(out_cold[0], out_warm[0])


def test_serving_warm_grid_token_identical(tmp_path):
    from paddle_tpu import models, serving
    from paddle_tpu.framework import executor as em
    flags.set_flag("jit_cache_dir", str(tmp_path))
    scope = em.Scope()
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=97, tgt_vocab_size=97, max_length=32,
        n_layer=2, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    models.transformer.build_lm_net(
        cfg, seq_len=24, is_test=True, fused_attention=False,
        fused_head=False)
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    pt.default_startup_program().random_seed = 3
    exe.run(pt.default_startup_program())
    params = serving.extract_lm_params(
        pt.default_main_program(), scope, cfg)

    def decode(engine, prompt, n):
        t0 = engine.start_sequence(0, prompt)
        toks = [int(t0)]
        for _ in range(n):
            toks.append(int(engine.decode_step()[0]))
        return toks

    eng = serving.DecodeEngine(cfg, params, max_batch=2, max_len=32,
                               prompt_buckets=(8,))
    eng.prepare()
    cold_compiles = _tot("serving_compiles_total")
    assert cold_compiles >= 2           # prefill bucket + decode step
    toks_cold = decode(eng, [5, 6, 7], 5)
    # warm replica: same geometry/weights, fresh engine — the WHOLE
    # grid deserializes: serving_compiles_total FROZEN, forensics
    # silent, decode token-identical to the cold path
    f0 = len(forensics.compile_log())
    eng2 = serving.DecodeEngine(cfg, params, max_batch=2, max_len=32,
                                prompt_buckets=(8,))
    eng2.prepare()
    assert _tot("serving_compiles_total") == cold_compiles
    assert len(forensics.compile_log()) == f0
    assert _tot("jit_cache_hits_total") >= 2
    toks_warm = decode(eng2, [5, 6, 7], 5)
    assert toks_warm == toks_cold


# --- supervisor plumbing ----------------------------------------------------

def test_supervisor_propagates_cache_dir(tmp_path):
    from paddle_tpu.distributed.supervisor import Supervisor
    flags.set_flag("jit_cache_dir", str(tmp_path))
    sup = Supervisor([["true"], ["true"]],
                     envs=[None, {"PTPU_JIT_CACHE_DIR": "/rank/own"}])
    env0 = sup._env_for(0, 0)
    assert env0["PTPU_JIT_CACHE_DIR"] == str(tmp_path)
    # a restarted incarnation keeps it too (chaos-stripped env)
    env0r = sup._env_for(0, 1)
    assert env0r["PTPU_JIT_CACHE_DIR"] == str(tmp_path)
    # an explicit per-rank dir wins over the flag
    env1 = sup._env_for(1, 0)
    assert env1["PTPU_JIT_CACHE_DIR"] == "/rank/own"


# --- CLI exit-code contract -------------------------------------------------

def test_warm_two_dir_and_dry_run(tmp_path, capsys):
    """ISSUE 19 satellite: the --warm SRC DST two-dir form needs no
    active cache dir, and --dry-run validates/names candidates without
    writing a byte."""
    src = tmp_path / "src"
    dst = tmp_path / "dst"
    flags.set_flag("jit_cache_dir", str(src))
    loss = _build_fc()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    exe.run(pt.default_main_program(), feed=_feed(), fetch_list=[loss])
    names = _entries(src)
    assert len(names) == 2                  # startup + main step
    flags.set_flag("jit_cache_dir", "")     # two-dir form: no ambient dir
    # dry run: exit 0, candidates named, NOTHING written
    assert jit_cache.main(["--warm", str(src), str(dst),
                           "--dry-run"]) == 0
    out = capsys.readouterr().out
    assert "would copy 2 entr(ies)" in out
    for nm in names:
        assert f"would copy {nm}" in out
    assert not os.path.exists(dst) or _entries(dst) == []
    # three positional dirs is a usage error
    assert jit_cache.main(["--warm", str(src), str(dst), str(dst)]) == 2
    # the real copy lands both entries, byte-identical
    assert jit_cache.main(["--warm", str(src), str(dst)]) == 0
    assert "copied 2 entr(ies)" in capsys.readouterr().out
    assert _entries(dst) == names
    for nm in names:
        assert open(os.path.join(src, nm), "rb").read() \
            == open(os.path.join(dst, nm), "rb").read()
    # re-warm is idempotent: everything already present
    r = jit_cache.warm(str(src), dst_dir=str(dst))
    assert r["copied"] == 0 and r["present"] == 2
    assert r["dry_run"] is False and r["entries"] == []


def test_warmed_fresh_process_records_zero_compiles(tmp_path):
    """ISSUE 19 satellite acceptance: a FRESH process pointed at a dir
    seeded only by the two-dir CLI warm records ZERO XLA compiles and
    bit-identical losses — the warm copy is as good as the original."""
    src = tmp_path / "src"
    dst = tmp_path / "dst"
    cold = _run_probe(src)
    assert cold["executor_compile_total"] > 0
    assert jit_cache.main(["--warm", str(src), str(dst)]) == 0
    warm = _run_probe(dst)
    assert warm["executor_compile_total"] == 0
    assert warm["jit_cache_errors_total"] == 0
    assert warm["losses"] == cold["losses"]


def test_cli_exit_codes(tmp_path, capsys):
    flags.set_flag("jit_cache_dir", "")
    # no dir, no action -> usage error
    assert jit_cache.main([]) == 2
    assert jit_cache.main(["--ls"]) == 2            # no dir configured
    assert jit_cache.main(["--restart-probe", "bogus"]) == 2
    # self-test is self-contained (temp dir) and must pass
    assert jit_cache.main(["--self-test"]) == 0
    # happy paths against an explicit dir
    assert jit_cache.main(["--dir", str(tmp_path), "--ls"]) == 0
    listing = capsys.readouterr().out
    assert '"entries": 0' in listing
    assert jit_cache.main(["--dir", str(tmp_path), "--gc"]) == 0
    assert jit_cache.main(["--dir", str(tmp_path), "--purge"]) == 0
    flags.set_flag("jit_cache_dir", "")


# --- mesh/sharding identity (ISSUE 14 satellite) ---------------------------

def _build_mesh_model():
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 21
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1, bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        pt.optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def _mesh_feed():
    rng = np.random.RandomState(4)
    return {"x": rng.randn(8, 8).astype("float32"),
            "y": rng.randn(8, 1).astype("float32")}


def test_mesh_executor_warm_start_same_mesh(tmp_path):
    """Mesh executors persist too: a fresh same-mesh executor resolves
    its sharded executables from DISK — zero new compiles, silent
    forensics, no cache errors (the resized-incarnation warm start)."""
    from paddle_tpu.core.place import make_mesh
    flags.set_flag("jit_cache_dir", str(tmp_path))
    main, startup, loss = _build_mesh_model()
    feed = _mesh_feed()
    mesh = make_mesh((2,), ("data",))
    scope = pt.Scope()
    e0 = _tot("jit_cache_errors_total")
    with warnings.catch_warnings():
        warnings.simplefilter("error")      # any cache warn = failure
        exe = pt.Executor(pt.CPUPlace(), scope=scope, mesh=mesh)
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss.name])
        assert len(_entries(tmp_path)) == 2     # startup + main step
        c0 = _tot("executor_compile_total")
        f0 = len(forensics.compile_log())
        h0 = _tot("jit_cache_hits_total")
        exe2 = pt.Executor(pt.CPUPlace(), scope=scope,
                           mesh=make_mesh((2,), ("data",)))
        exe2.run(main, feed=feed, fetch_list=[loss.name])
    assert _tot("executor_compile_total") == c0
    assert len(forensics.compile_log()) == f0
    assert _tot("jit_cache_hits_total") > h0
    assert _tot("jit_cache_errors_total") == e0
    rep = exe2.explain(main, feed=feed, fetch_list=[loss.name])
    assert rep["jit_cache"]["source"] == "disk"


def test_mesh_change_is_clean_miss(tmp_path):
    """A resized incarnation under a DIFFERENT mesh must MISS cleanly:
    new entry, no corrupt-entry error, no silent wrong-mesh hit — and
    the key carries the mesh identity (axes/devices/shardings)."""
    from paddle_tpu.core.place import make_mesh
    flags.set_flag("jit_cache_dir", str(tmp_path))
    main, startup, loss = _build_mesh_model()
    feed = _mesh_feed()
    scope = pt.Scope()
    e0 = _tot("jit_cache_errors_total")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        exe = pt.Executor(pt.CPUPlace(), scope=scope,
                          mesh=make_mesh((2,), ("data",)))
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss.name])
        n2 = len(_entries(tmp_path))
        m0 = _tot("jit_cache_misses_total")
        # the grown incarnation: 4-device mesh, same program/scope
        exe4 = pt.Executor(pt.CPUPlace(), scope=scope,
                           mesh=make_mesh((4,), ("data",)))
        out = exe4.run(main, feed=feed, fetch_list=[loss.name])
    assert np.isfinite(np.asarray(out[0])).all()
    assert _tot("jit_cache_misses_total") > m0      # clean MISS
    assert _tot("jit_cache_errors_total") == e0
    assert len(_entries(tmp_path)) > n2             # its own entry
    # single-device keys carry NO mesh component (pre-ISSUE-14 entries
    # stay valid); mesh keys name axes + device assignment
    comps = exe4._mesh_components(main)
    assert comps["axes"] == [["data", 4]]
    assert len(comps["device_ids"]) == 4
