"""Watchtower (ISSUE 15): declarative alerts, the fleet event journal,
and incident reconstruction.

Covers: the rule matrix (threshold / rate / absence / burn_rate x
pending / firing / resolved x `for:` holds), malformed-rules-file
rejection naming the line/field, default-rule set + file override,
journal rotate/merge/clock-normalization round trips, /alerts
fleet-merge semantics with exemplar/flight/rank context, /journal,
X-ray fire/resolve instants, the incident CLI's three selectors and
exit codes, flag-off invariance (bitwise outputs + frozen compile
counters), the healthz_stall_seconds knob, and the headline e2e: a
supervised 2-worker fleet, chaos-killed rank -> dead-rank alert fires
on the coordinator with the victim's exemplar trace id + flight ref,
resolves after supervisor revival, and `incident` reconstructs
kill -> fence -> respawn -> resolve in order.
"""
import json
import math
import os
import sys
import time
import urllib.request
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.distributed import task_queue
from paddle_tpu.distributed.supervisor import Supervisor
from paddle_tpu.observability import alerts, incident
from paddle_tpu.observability import fleet as obs_fleet
from paddle_tpu.observability import flight as obs_flight
from paddle_tpu.observability import journal
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import server as obs_server
from paddle_tpu.observability import tracectx
from paddle_tpu.resilience import retry as rretry
from paddle_tpu.resilience.soak import _seed_where_exit_fires

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


def _gdoc(name, rows):
    """Synthetic metrics doc: one gauge family, rows = [(labels, v)]."""
    return {"schema": "paddle_tpu.metrics.v1", "metrics": {
        name: {"type": "gauge", "help": "",
               "series": [{"labels": dict(l), "value": v}
                          for l, v in rows]}}}


def _cdoc(name, value):
    return {"schema": "paddle_tpu.metrics.v1", "metrics": {
        name: {"type": "counter", "help": "",
               "series": [{"labels": {}, "value": value}]}}}


def _hdoc(name, buckets, count, total=1.0, exemplars=None):
    row = {"labels": {}, "sum": total, "count": count,
           "buckets": dict(buckets),
           "overflow": count - sum(buckets.values())}
    if exemplars:
        row["exemplars"] = exemplars
    return {"schema": "paddle_tpu.metrics.v1", "metrics": {
        name: {"type": "histogram", "help": "", "series": [row]}}}


def _firing_gauge(rule):
    return obs_metrics.REGISTRY.get("alerts_firing").labels(
        rule=rule).value


def _transitions(rule, state):
    return obs_metrics.REGISTRY.get("alerts_transitions_total").labels(
        rule=rule, state=state).value


# ------------------------------------------------------ rule matrix

def test_threshold_pending_firing_resolved_hold():
    rule = alerts.Rule(name="r", metric="m", predicate="threshold",
                       op=">", value=1.0, for_seconds=2.0)
    eng = alerts.AlertEngine([rule])
    hi = _gdoc("m", [({}, 5.0)])
    lo = _gdoc("m", [({}, 0.5)])
    eng.evaluate(hi, now=100.0)
    st = eng.status_doc()
    assert st["active"] and st["active"][0]["state"] == "pending"
    assert st["firing"] == []
    eng.evaluate(hi, now=101.0)            # held 1s < for: 2s
    assert eng.status_doc()["firing"] == []
    eng.evaluate(hi, now=102.5)            # held 2.5s >= 2s -> firing
    st = eng.status_doc()
    assert st["firing"] == ["r"]
    assert _firing_gauge("r") == 1
    assert st["active"][0]["value"] == 5.0
    eng.evaluate(lo, now=103.0)            # breach gone -> resolved
    st = eng.status_doc()
    assert st["firing"] == [] and _firing_gauge("r") == 0
    assert st["recent_resolved"] and \
        st["recent_resolved"][0]["state"] == "resolved"
    states = [h["state"] for h in st["history"] if h["rule"] == "r"]
    assert states == ["pending", "firing", "resolved"]
    assert _transitions("r", "firing") == 1
    assert _transitions("r", "resolved") == 1


def test_threshold_pending_clears_without_resolved_noise():
    rule = alerts.Rule(name="p", metric="m", predicate="threshold",
                       op=">", value=1.0, for_seconds=5.0)
    eng = alerts.AlertEngine([rule])
    eng.evaluate(_gdoc("m", [({}, 9.0)]), now=10.0)
    eng.evaluate(_gdoc("m", [({}, 0.0)]), now=11.0)   # never held 5s
    st = eng.status_doc()
    assert st["active"] == [] and st["recent_resolved"] == []
    states = [h["state"] for h in st["history"] if h["rule"] == "p"]
    assert states == ["pending"]           # no firing/resolved noise
    assert _transitions("p", "resolved") == 0


def test_threshold_histogram_quantile_and_exemplar_context():
    tid = "ab" * 16
    rule = alerts.Rule(name="q", metric="h", predicate="threshold",
                       quantile=0.99, op=">", value=0.5)
    eng = alerts.AlertEngine([rule])
    doc = _hdoc("h", {"0.1": 50, "1.0": 49}, count=100, total=60.0,
                exemplars={"1.0": {"value": 0.9, "trace_id": tid,
                                   "time_unix": 5.0}})
    eng.evaluate(doc, now=1.0)             # for: 0 -> fires immediately
    st = eng.status_doc()
    assert st["firing"] == ["q"]
    act = st["active"][0]
    assert act["value"] == 1.0             # interpolated p99 bucket
    assert act["context"]["exemplar_trace_ids"] == [tid]
    # first fire auto-captured a flight bundle ref
    assert act["context"]["flight"]["dumps"] >= 1
    assert act["context"]["flight_bundle"]
    # below the bar -> resolves
    eng.evaluate(_hdoc("h", {"0.1": 100, "1.0": 0}, count=100),
                 now=2.0)
    assert eng.status_doc()["firing"] == []


def test_rate_predicate_fire_and_decay():
    rule = alerts.Rule(name="rate", metric="c", predicate="rate",
                       op=">", value=1.0, window=10.0)
    eng = alerts.AlertEngine([rule])
    eng.evaluate(_cdoc("c", 0.0), now=0.0)     # no anchor yet
    assert eng.status_doc()["firing"] == []
    eng.evaluate(_cdoc("c", 5.0), now=1.0)     # 5/s > 1/s
    assert eng.status_doc()["firing"] == ["rate"]
    eng.evaluate(_cdoc("c", 5.0), now=2.0)     # 2.5/s, still > 1
    assert eng.status_doc()["firing"] == ["rate"]
    eng.evaluate(_cdoc("c", 5.0), now=20.0)    # anchor aged out -> 0/s
    st = eng.status_doc()
    assert st["firing"] == []
    assert [h["state"] for h in st["history"]][-1] == "resolved"


def test_rate_window_survives_dense_evaluation():
    """A 0.05s evaluation cadence (fast ticker + scrapes) must not
    shrink the configured lookback: samples are time-granulated, so
    the anchor is genuinely ~window old — a raw 128-sample cap would
    have truncated a 10s window to 6.4s and missed the rate."""
    rule = alerts.Rule(name="dense", metric="c", predicate="rate",
                       op=">", value=0.2, window=10.0)
    eng = alerts.AlertEngine([rule])
    t = 0.0
    while t < 9.0:                 # 1/s increments for 3s, then flat
        eng.evaluate(_cdoc("c", min(3.0, t)), now=t)
        t += 0.05
    # 3 increments inside the 10s window = 0.33/s > 0.2/s
    assert eng.status_doc()["firing"] == ["dense"]
    while t < 16.0:                # hot anchor ages out past WINDOW
        eng.evaluate(_cdoc("c", 3.0), now=t)
        t += 0.05
    assert eng.status_doc()["firing"] == []


def test_rate_counter_reset_is_not_negative():
    rule = alerts.Rule(name="rr", metric="c", predicate="rate",
                       op=">", value=0.0, window=60.0)
    eng = alerts.AlertEngine([rule])
    eng.evaluate(_cdoc("c", 100.0), now=0.0)
    eng.evaluate(_cdoc("c", 3.0), now=1.0)     # restarted process
    assert eng.status_doc()["firing"] == []    # clamped to 0, not < 0


def test_absence_predicate():
    rule = alerts.Rule(name="a", metric="gone", predicate="absence",
                       for_seconds=1.0)
    eng = alerts.AlertEngine([rule])
    eng.evaluate({"metrics": {}}, now=0.0)
    assert eng.status_doc()["active"][0]["state"] == "pending"
    eng.evaluate({"metrics": {}}, now=1.5)
    assert eng.status_doc()["firing"] == ["a"]
    eng.evaluate(_gdoc("gone", [({}, 1.0)]), now=2.0)   # it came back
    st = eng.status_doc()
    assert st["firing"] == []
    assert [h["state"] for h in st["history"]][-1] == "resolved"


def test_burn_rate_predicate():
    rule = alerts.Rule(name="burn", metric="h", predicate="burn_rate",
                       bound=0.1, budget=0.1, op=">", value=2.0,
                       window=60.0)
    eng = alerts.AlertEngine([rule])
    eng.evaluate(_hdoc("h", {"0.1": 100}, count=100), now=0.0)
    assert eng.status_doc()["firing"] == []
    # 100 new observations, 80 above the bound: 80% breach vs the 10%
    # budget = 8x burn > 2x bar
    eng.evaluate(_hdoc("h", {"0.1": 120}, count=200), now=1.0)
    assert eng.status_doc()["firing"] == ["burn"]
    act = eng.status_doc()["active"][0]
    assert act["value"] == pytest.approx(8.0)
    # new observations all under the bound: burn decays once the hot
    # anchor ages out of the window
    eng.evaluate(_hdoc("h", {"0.1": 320}, count=400), now=90.0)
    assert eng.status_doc()["firing"] == []


def test_vanished_series_resolves():
    """A gauge series that disappears from the doc (departed worker)
    must resolve its firing state, not latch forever."""
    rule = alerts.Rule(name="v", metric="up", predicate="threshold",
                       op="<", value=1.0)
    eng = alerts.AlertEngine([rule])
    eng.evaluate(_gdoc("up", [({"worker": "0"}, 0.0)]), now=0.0)
    assert eng.status_doc()["firing"] == ["v"]
    eng.evaluate({"metrics": {}}, now=1.0)
    assert eng.status_doc()["firing"] == []


def test_alert_xray_instants_and_journal_transitions(tmp_path):
    flags.set_flag("journal_path", str(tmp_path / "j.jsonl"))
    rule = alerts.Rule(name="x", metric="m", predicate="threshold",
                       op=">", value=1.0)
    eng = alerts.AlertEngine([rule])
    eng.evaluate(_gdoc("m", [({}, 2.0)]), now=float(time.time()))
    ctx = eng.status_doc()["active"][0]["context"]
    tid = ctx["alert_trace_id"]
    assert tid and len(tid) == 32
    eng.evaluate(_gdoc("m", [({}, 0.0)]), now=float(time.time()))
    wf = tracectx.waterfall(tid)
    names = [s["name"] for s in wf["spans"]]
    assert names == ["alert.fire", "alert.resolve"]
    evs = journal.read_events(str(tmp_path / "j.jsonl"))
    alert_evs = [(e["event"], e["rule"]) for e in evs
                 if e["kind"] == "alert"]
    assert alert_evs == [("fire", "x"), ("resolve", "x")]
    assert evs[0]["alert_trace_id"] == tid


# ------------------------------------------------- rules file / CLI

def test_malformed_rules_json_names_line(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text('{"rules": [\n  {"name": "a",]\n}')
    with pytest.raises(alerts.RuleError) as ei:
        alerts.load_rules(str(p))
    assert f"{p}:2:" in str(ei.value)      # the JSON line is named
    assert alerts.main(["--check", str(p)]) == 1


def test_malformed_rule_names_rule_and_field(tmp_path):
    cases = [
        ({"metric": "m"}, "name"),
        ({"name": "a", "predicate": "nope", "metric": "m"},
         "predicate"),
        ({"name": "a", "metric": "m", "op": "~"}, "op"),
        ({"name": "a", "metric": "m", "value": "high"}, "value"),
        ({"name": "a", "metric": "m", "quantile": 2.0}, "quantile"),
        ({"name": "a", "metric": "m", "severity": "panic"},
         "severity"),
        ({"name": "a", "metric": "m", "frobnicate": 1}, "frobnicate"),
        ({"name": "a", "metric": "m", "predicate": "burn_rate"},
         "bound"),
    ]
    for i, (obj, field) in enumerate(cases):
        p = tmp_path / f"r{i}.json"
        p.write_text(json.dumps({"rules": [obj]}))
        with pytest.raises(alerts.RuleError) as ei:
            alerts.load_rules(str(p))
        msg = str(ei.value)
        assert "rule #0" in msg and repr(field) in msg, (obj, msg)
        assert alerts.main(["--check", str(p)]) == 1
    # duplicate names are rejected too
    p = tmp_path / "dup.json"
    p.write_text(json.dumps({"rules": [
        {"name": "a", "metric": "m"}, {"name": "a", "metric": "m2"}]}))
    with pytest.raises(alerts.RuleError, match="duplicates"):
        alerts.load_rules(str(p))


def test_alerts_check_cli_exit_codes(tmp_path):
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"rules": [
        {"name": "slow", "metric": "trainer_step_seconds",
         "predicate": "threshold", "quantile": 0.99, "op": ">",
         "value": 0.5, "for": 2.0, "severity": "critical"}]}))
    assert alerts.main(["--check", str(good)]) == 0
    assert alerts.main(["--check", str(tmp_path / "missing.json")]) == 2
    assert alerts.main([]) == 2
    assert alerts.main(["--self-test"]) == 0
    assert incident.main(["--self-test"]) == 0
    assert incident.main([]) == 2


def test_default_rules_and_file_override(tmp_path):
    flags.set_flag("alert_rules_path", "builtin")
    names = {r.name for r in alerts.effective_rules()}
    assert {"dead_rank", "stalled_rank", "recompile_storm",
            "nan_guard", "jit_cache_errors", "queue_saturation",
            "sparse_push_reject_spike"} <= names
    # the serving p99/burn rules gate on the budget flag
    assert "serving_p99_budget" not in names
    old = flags.get_flag("serving_p99_budget_ms")
    flags.set_flag("serving_p99_budget_ms", 50.0)
    try:
        names = {r.name for r in alerts.effective_rules()}
        assert {"serving_p99_budget", "ttft_burn_rate"} <= names
    finally:
        flags.set_flag("serving_p99_budget_ms", old)
    # the stalled_rank rule shares the healthz knob
    stalled = [r for r in alerts.effective_rules()
               if r.name == "stalled_rank"][0]
    assert stalled.value == float(flags.get_flag(
        "healthz_stall_seconds"))
    # a file rule with a builtin's name overrides it
    p = tmp_path / "rules.json"
    p.write_text(json.dumps({"rules": [
        {"name": "nan_guard", "metric": "trainer_bad_steps_total",
         "predicate": "rate", "op": ">", "value": 42.0}]}))
    flags.set_flag("alert_rules_path", str(p))
    by_name = {r.name: r for r in alerts.effective_rules()}
    assert by_name["nan_guard"].value == 42.0
    assert by_name["nan_guard"].source == "file"
    assert "dead_rank" in by_name          # builtins still there


def test_ensure_started_survives_bad_rules_file(tmp_path):
    p = tmp_path / "broken.json"
    p.write_text("{not json")
    flags.set_flag("alert_rules_path", str(p))
    with pytest.warns(RuntimeWarning, match="rules file rejected"):
        eng = alerts.ensure_started()
    assert eng is not None                  # builtins still watching
    assert {r.name for r in eng.rules} == {
        r.name for r in alerts.default_rules()}


# ------------------------------------------------------ journal

def test_journal_emit_read_roundtrip_and_strict_json(tmp_path):
    p = str(tmp_path / "j.jsonl")
    n0 = obs_metrics.REGISTRY.get("journal_events_total").total()
    flags.set_flag("journal_path", p)
    journal.set_rank(2)
    journal.emit("guard", "nan", loss=float("nan"), step=3)
    journal.emit("master", "generation", generation=np.int64(4))
    evs = journal.read_events(p)
    assert [e["event"] for e in evs] == ["nan", "generation"]
    assert evs[0]["rank"] == 2 and evs[0]["kind"] == "guard"
    assert evs[0]["loss"] == "nan"          # strict JSON, stringified
    assert evs[1]["generation"] == 4        # numpy int stays an int
    assert evs[0]["seq"] < evs[1]["seq"]
    assert {"time_unix", "perf_counter", "pid"} <= set(evs[0])
    # the ambient trace id rides along
    ctx = tracectx.start_trace("t")
    with tracectx.activate(ctx):
        journal.emit("worker", "step", step=9)
    evs = journal.read_events(p)
    assert evs[-1]["trace_id"] == ctx.trace_id
    assert obs_metrics.REGISTRY.get(
        "journal_events_total").total() == n0 + 3


def test_journal_disabled_is_noop(tmp_path):
    assert not journal.enabled()
    assert journal.emit("x", "y") is None
    g, total, tail = journal.events_since(0)
    assert total == 0 and tail == []
    assert list(tmp_path.iterdir()) == []


def test_journal_appends_across_writers_and_rotates_at_cap(tmp_path):
    p = str(tmp_path / "j.jsonl")
    flags.set_flag("journal_path", p)
    journal.emit("a", "one")
    journal.reset()                         # process restart shape
    flags.set_flag("journal_path", p)
    journal.emit("a", "two")
    # append, not rotate: both incarnations share one timeline
    assert [e["event"] for e in journal.read_events(p)] == ["one",
                                                           "two"]
    assert not os.path.exists(p + ".1")
    # an oversized file DOES rotate aside (atomically) on reopen
    flags.set_flag("journal_rotate_bytes", 10)
    journal.reset()
    flags.set_flag("journal_path", p)
    journal.emit("a", "three")
    assert os.path.exists(p + ".1")
    assert [e["event"] for e in journal.read_events(p)] == ["three"]
    assert [e["event"] for e in journal.read_events(p + ".1")] == [
        "one", "two"]
    flags.set_flag("journal_rotate_bytes", 64_000_000)


def test_journal_cursor_and_generation(tmp_path):
    flags.set_flag("journal_path", str(tmp_path / "cursor.jsonl"))
    journal.emit("k", "e1")
    g, total, tail = journal.events_since(0)
    assert total == 1 and [e["event"] for e in tail] == ["e1"]
    journal.emit("k", "e2")
    g2, total2, tail2 = journal.events_since(total, g)
    assert total2 == 2 and [e["event"] for e in tail2] == ["e2"]
    # a generation mismatch replays the whole buffer
    g3, _t, tail3 = journal.events_since(total2, g2 - 1)
    assert len(tail3) == 2


def test_journal_fleet_ship_clock_normalization_and_merge(tmp_path):
    """A worker with a skewed wall clock ships journal events; the
    aggregator lands them on the MASTER clock (perf + offset, the
    PR 11 idiom), appends them to the coordinator's journal file, and
    merge_events dedupes the shipped copy against the rank's own."""
    coord = str(tmp_path / "coord.jsonl")
    flags.set_flag("journal_path", coord)
    journal.emit("master", "generation", generation=1)
    agg = obs_fleet.FleetAggregator(stale_after=60.0)
    now = time.time()
    perf = 5000.0
    skew = 123.0                            # worker clock runs ahead
    w_events = [
        {"schema": journal.SCHEMA, "kind": "worker", "event": "step",
         "time_unix": now + skew + 0.1, "perf_counter": perf + 0.1,
         "rank": 3, "pid": 77, "seq": 1},
        {"schema": journal.SCHEMA, "kind": "chaos", "event": "injected",
         "time_unix": now + skew + 0.3, "perf_counter": perf + 0.3,
         "rank": 3, "pid": 77, "seq": 2},
    ]
    payload = {"schema": obs_fleet.SCHEMA, "rank": 3,
               "time_unix": now + skew, "perf_counter": perf,
               "spans": [], "journal": list(w_events)}
    agg.ingest_events(payload, recv_unix=now)
    evs = agg.journal_events()
    assert [e["event"] for e in evs] == ["step", "injected"]
    # normalized onto the master clock: recv - perf + ev_perf
    assert evs[0]["time_unix"] == pytest.approx(now + 0.1, abs=1e-6)
    assert evs[0]["worker_time_unix"] == pytest.approx(
        now + skew + 0.1, abs=1e-6)
    # ... and durably appended to the coordinator's journal
    disk = journal.read_events(coord)
    assert [e["event"] for e in disk] == ["generation", "step",
                                          "injected"]
    assert disk[1]["rank"] == 3
    # offline merge: the shipped copy dedupes against the rank's own
    # file (same (rank, pid, seq) identity), order is master-clock
    merged = journal.merge_events([disk, w_events])
    assert [e["event"] for e in merged] == ["generation", "step",
                                            "injected"]


def test_journal_http_route(tmp_path):
    flags.set_flag("journal_path", str(tmp_path / "j.jsonl"))
    journal.emit("worker", "step", step=1)
    srv = obs_server.start_http_server(port=0)
    doc = _get_json(srv.url + "/journal")
    assert doc["schema"] == journal.SCHEMA and doc["enabled"]
    assert [e["event"] for e in doc["events"]] == ["step"]


def test_alerts_http_route_disabled_by_default():
    srv = obs_server.start_http_server(port=0)
    doc = _get_json(srv.url + "/alerts")
    assert doc["enabled"] is False and doc["rules"] == []


def test_healthz_stall_seconds_flag():
    obs_server.note_trainer_running(True)
    obs_server.note_trainer_step()
    old = flags.get_flag("healthz_stall_seconds")
    try:
        flags.set_flag("healthz_stall_seconds", 0.05)
        time.sleep(0.12)
        assert obs_server.trainer_liveness()["hung"] is True
        flags.set_flag("healthz_stall_seconds", 100.0)
        assert obs_server.trainer_liveness()["hung"] is False
    finally:
        flags.set_flag("healthz_stall_seconds", old)


# ---------------------------------------------- /alerts fleet merge

def _worker_snapshot_payload(rank, steps, exemplar_tid=None):
    buckets = {"0.1": steps}
    row = {"labels": {}, "sum": 0.5, "count": steps,
           "buckets": buckets, "overflow": 0}
    if exemplar_tid:
        row["exemplars"] = {"0.1": {"value": 0.05,
                                    "trace_id": exemplar_tid,
                                    "time_unix": time.time()}}
    return {"schema": obs_fleet.SCHEMA, "rank": rank, "host": "h",
            "pid": 1000 + rank, "time_unix": time.time(),
            "perf_counter": time.perf_counter(),
            "steps_total": float(steps), "closing": False,
            "model": None,
            "metrics": {"schema": "paddle_tpu.metrics.v1", "metrics": {
                "trainer_step_seconds": {"type": "histogram",
                                         "help": "", "series": [row]},
                "trainer_steps_total": {"type": "counter", "help": "",
                                        "series": [{"labels": {},
                                                    "value": steps}]},
            }}}


def test_alerts_fleet_merge_dead_rank_context_over_http(tmp_path):
    """The /alerts fleet-merge semantics: the coordinator's engine
    evaluates the MERGED document, a membership-dead rank fires
    dead_rank with the victim's rank + exemplar trace id attached
    (pulled from its last snapshot), and membership recovery resolves
    it."""
    tid = "cd" * 16
    agg = obs_fleet.FleetAggregator(stale_after=60.0)
    agg.ingest_metrics(_worker_snapshot_payload(0, 10,
                                                exemplar_tid=tid))
    agg.note_worker(0, "live", host="h", pid=1000)
    flags.set_flag("alert_rules_path", "builtin")
    srv = obs_server.start_http_server(port=0, aggregator=agg)
    doc = _get_json(srv.url + "/alerts")
    assert doc["enabled"] and doc["source"] == "fleet"
    assert "dead_rank" not in doc["firing"]
    # the heartbeat plane declares the rank dead -> fleet_worker_dead 1
    agg.note_worker(0, "dead", host="h", pid=1000)
    doc = _get_json(srv.url + "/alerts")
    assert doc["firing"] == ["dead_rank"]
    act = [a for a in doc["active"] if a["rule"] == "dead_rank"][0]
    ctx = act["context"]
    assert ctx["ranks"] == ["0"]
    assert ctx["exemplar_trace_ids"] == [tid]
    assert ctx["flight"]["dumps"] >= 1      # auto-captured bundle ref
    # the alert's own trace resolves over HTTP (fire instant recorded)
    atid = ctx["alert_trace_id"]
    wf = _get_json(srv.url + f"/trace/{atid}")
    assert [s["name"] for s in wf["spans"]] == ["alert.fire"]
    # revival: membership live again -> resolved
    agg.note_worker(0, "live", host="h", pid=1001)
    doc = _get_json(srv.url + "/alerts")
    assert doc["firing"] == []
    assert any(h["rule"] == "dead_rank" and h["state"] == "resolved"
               for h in doc["history"])
    # a clean goodbye is NOT an alarm: departed ranks leave the
    # fleet_worker_dead AND fleet_worker_report_age_seconds families
    # entirely — neither dead_rank nor stalled_rank (whose age would
    # grow forever) can latch on a scale-down
    agg.note_worker(0, "departed")
    doc = _get_json(srv.url + "/alerts")
    assert "dead_rank" not in doc["firing"], doc["active"]
    mdoc = _get_json(srv.url + "/metrics.json")
    for fam in ("fleet_worker_dead", "fleet_worker_report_age_seconds"):
        rows = mdoc["metrics"].get(fam, {}).get("series", [])
        assert all(r["labels"].get("worker") != "0" for r in rows), fam


# ------------------------------------------------- incident CLI

def _write_journal(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps({"schema": journal.SCHEMA, **e}) + "\n")


def test_incident_selectors_report_and_exit_codes(tmp_path, capsys):
    T = 1700000000.0
    p = str(tmp_path / "j.jsonl")
    _write_journal(p, [
        {"kind": "worker", "event": "step", "time_unix": T + 0.5,
         "rank": 0, "pid": 1, "seq": 1, "trace_id": "ee" * 16},
        {"kind": "chaos", "event": "injected", "time_unix": T + 1.0,
         "rank": 0, "pid": 1, "seq": 2, "site": "trainer.step",
         "fault_kind": "exit"},
        {"kind": "master", "event": "worker_dead", "time_unix": T + 2.0,
         "rank": 0, "pid": 2, "seq": 1, "worker": 0},
        {"kind": "alert", "event": "fire", "time_unix": T + 2.2,
         "rank": 0, "pid": 2, "seq": 2, "rule": "dead_rank"},
        {"kind": "supervisor", "event": "spawn", "time_unix": T + 3.0,
         "rank": 0, "pid": 2, "seq": 3, "worker": 0, "incarnation": 1},
        {"kind": "alert", "event": "resolve", "time_unix": T + 4.0,
         "rank": 0, "pid": 2, "seq": 4, "rule": "dead_rank"},
        {"kind": "worker", "event": "step", "time_unix": T + 60.0,
         "rank": 0, "pid": 3, "seq": 1},
    ])
    # --window
    events, hist = incident.gather_events([p])
    t0, t1, sel = incident.resolve_window(events, hist,
                                          window=f"{T + 0.9}:{T + 3.5}")
    doc = incident.build_report(events, hist, t0, t1, sel)
    assert [e["event"] for e in doc["timeline"]] == [
        "injected", "worker_dead", "fire", "spawn"]
    # --alert: fire .. resolve with padding
    t0, t1, sel = incident.resolve_window(events, hist,
                                          alert="dead_rank", pad=1.5)
    doc = incident.build_report(events, hist, t0, t1, sel)
    names = [e["event"] for e in doc["timeline"]]
    assert names == ["injected", "worker_dead", "fire", "spawn",
                     "resolve"]
    assert sel["fired_unix"] == T + 2.2
    # --trace-id
    t0, t1, sel = incident.resolve_window(events, hist,
                                          trace_id="ee" * 16, pad=0.1)
    doc = incident.build_report(events, hist, t0, t1, sel)
    assert [e["event"] for e in doc["timeline"]] == ["step"]
    assert doc["trace_ids"] == ["ee" * 16]
    # CLI contract
    assert incident.main([p, "--alert", "dead_rank"]) == 0
    out = capsys.readouterr().out
    assert "injected" in out and "worker_dead" in out \
        and "spawn" in out and "resolve" in out
    assert incident.main([p, "--alert", "never_fired"]) == 1
    assert incident.main([p, "--window", "bogus"]) == 1
    assert incident.main([p, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["schema"] == incident.SCHEMA
    assert incident.main(
        [p, "--window", "1:2", "--alert", "x"]) == 2


def test_incident_runlog_join(tmp_path, capsys):
    from paddle_tpu.observability import runlog as obs_runlog
    T = time.time()
    jp = str(tmp_path / "j.jsonl")
    _write_journal(jp, [
        {"kind": "guard", "event": "nan", "time_unix": T + 1.0,
         "rank": 0, "pid": 1, "seq": 1, "first_var": "fc_1.w"}])
    rp = str(tmp_path / "run.jsonl")
    log = obs_runlog.RunLog(rp)
    log._f.write(json.dumps({
        "schema": obs_runlog.SCHEMA, "time_unix": T + 1.05,
        "kind": "step", "step": 1, "loss": 0.5}) + "\n")
    log._f.write(json.dumps({
        "schema": obs_runlog.SCHEMA, "time_unix": T + 1.1,
        "kind": "guard", "verdict": "nan", "step": 2, "loss": "nan",
        "attribution": "fc_1.w"}) + "\n")
    log.close()
    assert incident.main([jp, "--runlog", rp]) == 0
    out = capsys.readouterr().out
    assert "guard_nan" in out and "1 train step" in out


# --------------------------------------------- flag-off invariance

def _tiny_training(ckpt_dir):
    losses = []

    def train_func():
        x = layers.data("x", [6], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                      act="softmax")
        return layers.mean(layers.cross_entropy(p, y))

    rng = np.random.RandomState(0)
    batches = [[(rng.rand(6).astype("float32"),
                 np.array([rng.randint(3)], "int64"))
                for _ in range(4)] for _ in range(4)]

    def handler(event):
        if isinstance(event, pt.EndStepEvent) and event.metrics:
            losses.append(np.asarray(event.metrics[0]).copy())

    trainer = pt.Trainer(
        train_func=train_func,
        optimizer_func=lambda: pt.optimizer.SGD(0.1),
        place=pt.CPUPlace(),
        checkpoint_config=pt.CheckpointConfig(
            checkpoint_dir=ckpt_dir, step_interval=2))
    trainer.train(num_epochs=1, event_handler=handler,
                  reader=lambda: iter(batches), feed_order=["x", "y"])
    trainer.stop()
    return losses


def test_watchtower_flag_off_invariance(tmp_path):
    """alert_rules_path="" + journal off is byte-identical on outputs
    and compile bookkeeping (the PR 7/10/11 idiom): the whole plane is
    a pure observer."""
    from paddle_tpu.observability import forensics

    def _compiles():
        return obs_metrics.REGISTRY.get("executor_compile_total").total()

    assert not journal.enabled() and not alerts.enabled()
    c0 = _compiles()
    f0 = len(forensics.compile_log())
    base = _tiny_training(str(tmp_path / "ck_off"))
    d_compiles = _compiles() - c0
    d_forensics = len(forensics.compile_log()) - f0
    # watched run: journal + builtin alerts + a fast ticker
    flags.set_flag("journal_path", str(tmp_path / "j.jsonl"))
    flags.set_flag("alert_rules_path", "builtin")
    flags.set_flag("alert_eval_interval", 0.05)
    c1 = _compiles()
    f1 = len(forensics.compile_log())
    watched = _tiny_training(str(tmp_path / "ck_on"))
    assert _compiles() - c1 == d_compiles
    assert len(forensics.compile_log()) - f1 == d_forensics
    assert len(watched) == len(base)
    for a, b in zip(base, watched):
        assert np.array_equal(a, b)         # bitwise identical losses
    # the watched run actually journaled (checkpoint commits)
    evs = journal.read_events(str(tmp_path / "j.jsonl"))
    assert any(e["kind"] == "checkpoint" and e["event"] == "commit"
               for e in evs)
    assert alerts.get_engine() is not None


# ------------------------------------------------- headline e2e

def test_watchtower_e2e_chaos_kill_dead_rank_alert(tmp_path):
    """ISSUE 15 headline: supervised 2-worker fleet; chaos kill-9s
    rank 0 mid-loop; the dead-rank alert fires on the coordinator with
    the victim's exemplar trace id + flight ref attached; the
    supervisor revives the rank and the alert resolves; the incident
    CLI over the journals reconstructs kill -> fence (dead) ->
    respawn -> resolve in order."""
    coord_journal = str(tmp_path / "coord.jsonl")
    flags.set_flag("journal_path", coord_journal)
    flags.set_flag("alert_rules_path", "builtin")
    flags.set_flag("alert_eval_interval", 0.1)
    agg = obs_fleet.FleetAggregator(stale_after=5.0)
    master = task_queue.TaskMaster(worker_timeout=1.0)
    srv, (mhost, mport) = task_queue.serve_master(master, port=0,
                                                  aggregator=agg)
    http = obs_server.start_http_server(port=0, aggregator=agg)
    assert alerts.get_engine() is not None  # wired by the server

    stop_file = str(tmp_path / "stop")
    worker_py = os.path.join(REPO, "tests", "watchtower_worker.py")

    def cmd(rank):
        return [sys.executable, worker_py, f"127.0.0.1:{mport}",
                str(rank), stop_file]

    base_env = dict(os.environ, JAX_PLATFORMS="cpu")
    base_env.pop("XLA_FLAGS", None)
    base_env.pop("PYTHONPATH", None)        # axon quirk (conftest)
    base_env["PTPU_WORKER_HEARTBEAT_INTERVAL"] = "0.2"
    base_env["PTPU_FLEET_REPORT_INTERVAL"] = "0.2"
    # kill rank 0 on a step in [20, 40): late enough that several
    # reporter flushes (0.2s) shipped exemplar-carrying snapshots first
    kseed = _seed_where_exit_fires(0.2, 20, 40)
    envs = [
        {"PTPU_JOURNAL_PATH": str(tmp_path / "w0.jsonl"),
         "PTPU_CHAOS_SPEC": "trainer.step=exit:0.2:9",
         "PTPU_CHAOS_SEED": str(kseed)},
        {"PTPU_JOURNAL_PATH": str(tmp_path / "w1.jsonl")},
    ]
    # restart backoff SLOWER than the heartbeat death detector
    # (worker_timeout 1.0s + reaper tick): an instant respawn would
    # re-register the rank before the master ever declares it dead —
    # no death, no alert, nothing to watch.  2.5-3.75s of backoff
    # leaves a ~2s dead window for the 0.1s alert ticker.
    sup = Supervisor(
        cmds=[cmd(0), cmd(1)], env=base_env, envs=envs, cwd=REPO,
        backoff=rretry.RetryPolicy(name="wt_restart", max_attempts=1,
                                   base_delay=2.5, max_delay=4.0))
    sup.start()
    try:
        # --- the dead-rank alert fires on the coordinator -----------
        deadline = time.time() + 60
        fired = None
        while time.time() < deadline:
            doc = _get_json(http.url + "/alerts")
            hits = [a for a in doc["active"]
                    if a["rule"] == "dead_rank"
                    and a["state"] == "firing"]
            if hits:
                fired = hits[0]
                break
            time.sleep(0.1)
        assert fired is not None, f"dead_rank never fired: {doc}"
        ctx = fired["context"]
        assert ctx["ranks"] == ["0"], ctx   # the victim, attributed
        # the victim's exemplar trace id (from its last snapshot)
        assert ctx.get("exemplar_trace_ids"), ctx
        tid = ctx["exemplar_trace_ids"][0]
        assert len(tid) == 32 and int(tid, 16) >= 0
        # flight-bundle ref attached (auto-captured on first fire)
        assert ctx.get("flight_bundle")
        assert ctx["flight"]["dumps"] >= 1
        # --- supervisor revival resolves it -------------------------
        while time.time() < deadline:
            doc = _get_json(http.url + "/alerts")
            if "dead_rank" not in doc["firing"]:
                break
            time.sleep(0.1)
        assert "dead_rank" not in doc["firing"], doc["active"]
        assert any(h["rule"] == "dead_rank" and h["state"] == "resolved"
                   for h in doc["history"]), doc["history"]
        assert sup.restarts[0] >= 1         # the respawn really happened
        alerts_doc = doc
    finally:
        with open(stop_file, "w"):
            pass
        finished = sup.wait(timeout=30)
        status = sup.status()
        sup.stop()
        srv.shutdown()
    assert finished, status
    assert all(s["state"] == "done" for s in status.values()), status

    # --- incident reconstruction over the merged journals -----------
    journals = [coord_journal, str(tmp_path / "w0.jsonl"),
                str(tmp_path / "w1.jsonl")]
    events, hist = incident.gather_events(journals,
                                          alerts_doc=alerts_doc)
    t0, t1, sel = incident.resolve_window(events, hist,
                                          alert="dead_rank", pad=60.0)
    rep = incident.build_report(events, hist, t0, t1, sel)
    tl = rep["timeline"]

    def first_idx(pred):
        for i, e in enumerate(tl):
            if pred(e):
                return i
        raise AssertionError(
            f"missing from timeline: {[(e['kind'], e['event']) for e in tl]}")

    i_kill = first_idx(lambda e: e["kind"] == "chaos"
                       and e["event"] == "injected" and e["rank"] == 0)
    i_dead = first_idx(lambda e: e["kind"] == "master"
                       and e["event"] == "worker_dead"
                       and e.get("detail", {}).get("worker") == 0)
    i_respawn = first_idx(lambda e: e["kind"] == "supervisor"
                          and e["event"] == "spawn"
                          and e.get("detail", {}).get("worker") == 0
                          and e.get("detail", {}).get(
                              "incarnation", 0) >= 1)
    i_fire = first_idx(lambda e: e["kind"] == "alert"
                       and e["event"] == "fire"
                       and e.get("detail", {}).get("rule")
                       == "dead_rank")
    i_resolve = first_idx(lambda e: e["kind"] == "alert"
                          and e["event"] == "resolve"
                          and e.get("detail", {}).get("rule")
                          == "dead_rank")
    assert i_kill < i_dead < i_respawn < i_resolve, \
        [(e["kind"], e["event"]) for e in tl]
    assert i_dead < i_fire < i_resolve
    # the ASCII rendering holds the whole story
    text = incident.render_report(rep)
    for needle in ("chaos", "worker_dead", "spawn", "resolve",
                   "alert dead_rank"):
        assert needle in text, text
