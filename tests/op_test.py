"""OpTest harness: the framework's per-op test contract.

Mirrors /root/reference/python/paddle/fluid/tests/unittests/op_test.py:132:
a test sets op_type/inputs/outputs/attrs; check_output runs the single op
through a scratch Program+Executor and compares against the numpy
reference; check_grad compares analytic (vjp) gradients against numeric
finite differences (ref get_numeric_gradient in testsuite.py) — keeping
exactly the reference's validation contract.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.program import grad_var_name


class OpTest:
    op_type: str = ""

    def setup(self):
        """Subclasses set self.inputs / self.outputs / self.attrs here."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    def _build(self, extra_fetch: Sequence[str] = ()):
        self.attrs = getattr(self, "attrs", {})
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            block = main.global_block()
            in_slots, feeds = {}, {}
            for slot, val in self.inputs.items():
                vals = val if isinstance(val, list) else [val]
                names = []
                for i, arr in enumerate(vals):
                    name = f"{slot}_{i}"
                    arr = np.asarray(arr)
                    block.create_var(name=name, shape=arr.shape,
                                     dtype=str(arr.dtype), is_data=True)
                    feeds[name] = arr
                    names.append(name)
                in_slots[slot] = names
            out_slots = {}
            for slot, val in self.outputs.items():
                vals = val if isinstance(val, list) else [val]
                names = []
                for i, _ in enumerate(vals):
                    name = f"out_{slot}_{i}"
                    block.create_var(name=name, dtype="float32")
                    names.append(name)
                out_slots[slot] = names
            block.append_op(self.op_type, in_slots, out_slots, self.attrs)
        return main, feeds, out_slots

    def check_output(self, atol=1e-5, rtol=1e-5, place=None):
        self.setup()
        main, feeds, out_slots = self._build()
        exe = pt.Executor(place or pt.CPUPlace())
        fetch_names, expected = [], []
        for slot, val in self.outputs.items():
            vals = val if isinstance(val, list) else [val]
            for name, arr in zip(out_slots[slot], vals):
                if arr is None:
                    continue
                fetch_names.append(name)
                expected.append(np.asarray(arr))
        got = exe.run(main, feed=feeds, fetch_list=fetch_names)
        for name, e, g in zip(fetch_names, expected, got):
            np.testing.assert_allclose(
                np.asarray(g, dtype=np.float64),
                np.asarray(e, dtype=np.float64),
                atol=atol, rtol=rtol,
                err_msg=f"{self.op_type}:{name} mismatch")

    # ------------------------------------------------------------------
    def check_grad(self, inputs_to_check: Sequence[str], output_name: str,
                   max_relative_error=0.005, delta=5e-3, place=None,
                   no_grad_set=None):
        """Finite-difference vs analytic gradients of sum(output) w.r.t.
        each checked input (reference semantics: scalar loss = mean? ref
        uses sum via output@GRAD of ones — we use sum)."""
        self.setup()
        for slot in inputs_to_check:
            analytic = self._analytic_grad(slot, output_name, place)
            numeric = self._numeric_grad(slot, output_name, delta, place)
            abs_max = max(np.abs(numeric).max(), np.abs(analytic).max(),
                          1e-3)
            diff = np.abs(analytic - numeric).max() / abs_max
            assert diff <= max_relative_error, (
                f"{self.op_type} grad wrt {slot}: rel err {diff:.4g} > "
                f"{max_relative_error} (analytic {analytic.ravel()[:5]}, "
                f"numeric {numeric.ravel()[:5]})")

    def _scalarize(self, main, out_name):
        """loss = sum(out^2): nonzero grads even for outputs with constant
        sum (softmax rows); both analytic and numeric paths share it."""
        block = main.global_block()
        block.create_var(name="sq__", dtype="float32")
        block.append_op("square", {"X": [out_name]}, {"Out": ["sq__"]}, {})
        block.create_var(name="loss__", dtype="float32")
        block.append_op("reduce_sum", {"X": ["sq__"]},
                        {"Out": ["loss__"]}, {"reduce_all": True})
        return "loss__"

    def _analytic_grad(self, slot, output_name, place):
        main, feeds, out_slots = self._build()
        block = main.global_block()
        # promote the checked input to a Parameter so append_backward sees it
        in_name = f"{slot}_0"
        v = block.vars[in_name]
        from paddle_tpu.framework.program import Parameter
        p = Parameter(block, in_name, shape=v.shape, dtype=v.dtype)
        block.vars[in_name] = p
        out_name = out_slots[output_name][0]
        loss_name = self._scalarize(main, out_name)
        with pt.program_guard(main):
            pt.append_backward(block.var(loss_name), parameter_list=[p])
        exe = pt.Executor(place or pt.CPUPlace())
        feed = dict(feeds)
        param_val = feed.pop(in_name)
        exe.scope.set_var(in_name, param_val)
        g, = exe.run(main, feed=feed,
                     fetch_list=[grad_var_name(in_name)])
        return np.asarray(g, np.float64)

    def _numeric_grad(self, slot, output_name, delta, place):
        main, feeds, out_slots = self._build()
        out_name = out_slots[output_name][0]
        loss_name = self._scalarize(main, out_name)
        exe = pt.Executor(place or pt.CPUPlace())
        in_name = f"{slot}_0"
        base = np.asarray(feeds[in_name], np.float64)
        grad = np.zeros_like(base, np.float64)
        flat = base.ravel()
        gflat = grad.ravel()

        def run_with(x):
            f = dict(feeds)
            f[in_name] = x.reshape(base.shape).astype(feeds[in_name].dtype)
            out, = exe.run(main, feed=f, fetch_list=[loss_name])
            return float(np.asarray(out, np.float64))

        for i in range(flat.size):
            x = flat.copy()
            x[i] += delta
            fp = run_with(x)
            x[i] -= 2 * delta
            fm = run_with(x)
            gflat[i] = (fp - fm) / (2 * delta)
        return grad
