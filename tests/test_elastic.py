"""Elastic fleet: fenced leases, master generations, worker membership,
the crash-restarting supervisor, and master failover (ISSUE 5).

The reference's etcd-backed Go master kept a training fleet making
progress through worker death and master restarts via fenced leases and
recovery (go/master/service.go, EDL era).  These tests drill each
mechanism in-process, then prove the whole story end-to-end: chaos
``kill -9``s a worker mid-epoch AND the master is restarted, and the
run completes with every (task, epoch) pair in the ledger exactly once.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from dist_harness import REPO, free_port

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.distributed.supervisor import Supervisor
from paddle_tpu.distributed.task_queue import (
    Heartbeater, TaskMaster, TaskMasterClient, serve_master)
from paddle_tpu.observability import fleet, metrics as obs
from paddle_tpu.resilience import chaos, retry as rretry, soak


def _counter(name):
    m = obs.REGISTRY.get(name)
    return 0.0 if m is None else m.total()


def _gauge(name, **labels):
    m = obs.REGISTRY.get(name)
    return m.labels(**labels).value if labels else m.value


# ------------------------------------------------------ fenced leases

def test_get_task_mints_lease_tokens():
    m = TaskMaster()
    m.set_dataset(["a", "b"])
    t1, t2 = m.get_task(), m.get_task()
    assert t1.lease and t2.lease and t1.lease != t2.lease
    assert m.task_finished(t1.task_id, lease=t1.lease) == "ok"
    # queued tasks carry no lease
    assert all(t.lease is None for t in m.todo + m.done)


def test_zombie_double_completion_is_fenced():
    """ISSUE 5 satellite regression: expire a lease, re-lease the task
    to a second client, then have the FIRST client ack task_finished.
    Pre-fencing this popped the second client's pending entry and
    marked the task done while the new owner was still working it."""
    m = TaskMaster(lease_timeout=0.05)
    m.set_dataset(["a"])
    t1 = m.get_task()
    time.sleep(0.08)
    m.stats()                              # _requeue_expired runs
    t2 = m.get_task()                      # re-leased to a new owner
    assert t2.task_id == t1.task_id and t2.lease != t1.lease
    f0 = _counter("fenced_rpcs_total")
    assert m.task_finished(t1.task_id, lease=t1.lease) == "fenced"
    # the new owner's lease is untouched: still pending, still its own
    assert m.stats()["pending"] == 1
    assert m.task_finished(t2.task_id, lease=t2.lease) == "ok"
    assert _counter("fenced_rpcs_total") == f0 + 1
    # the ledger records exactly ONE completion, under the live lease
    ledger = m.ledger_entries()
    assert [e["lease"] for e in ledger] == [t2.lease]


def test_stale_ack_before_release_is_fenced():
    """A zombie ack for a task that was requeued but NOT yet re-leased
    must also fence: accepting it would mark done work that is queued
    to run again (a guaranteed duplicate)."""
    m = TaskMaster(lease_timeout=0.05)
    m.set_dataset(["a"])
    t = m.get_task()
    time.sleep(0.08)
    assert m.stats()["todo"] == 1          # expired back to todo
    assert m.task_finished(t.task_id, lease=t.lease) == "fenced"
    assert m.stats()["todo"] == 1 and m.stats()["done"] == 0


def test_task_failed_is_fenced_too():
    m = TaskMaster(lease_timeout=0.05)
    m.set_dataset(["a"])
    t1 = m.get_task()
    time.sleep(0.08)
    m.stats()
    t2 = m.get_task()
    assert m.task_failed(t1.task_id, lease=t1.lease) == "fenced"
    # the zombie's failure report must not burn the new owner's lease
    # or the task's failure budget
    assert m.stats()["pending"] == 1
    assert m.pending[t2.task_id]["task"].failures == 1  # expiry only


def test_duplicate_completion_ack_is_idempotent():
    """At-least-once RPC delivery: a completion the master accepted
    whose reply was lost is re-sent with the same lease — it must
    re-ack "ok" (the ledger proves it landed), NOT fence, or the
    worker rolls back work the ledger counts."""
    m = TaskMaster()
    m.set_dataset(["a"])
    t = m.get_task()
    assert m.task_finished(t.task_id, lease=t.lease) == "ok"
    assert m.task_finished(t.task_id, lease=t.lease) == "ok"   # retry
    assert len(m.ledger_entries()) == 1    # no second entry
    # a DIFFERENT stale lease for the same task still fences
    assert m.task_finished(t.task_id, lease="1-999") == "fenced"


def test_reconcile_in_flight_resolves_against_ledger():
    """Crash between checkpoint and ack: the resumed worker keeps the
    update iff the master's ledger shows the lease committed."""
    import numpy as np

    from paddle_tpu.resilience import elastic_worker as ew

    w = ew._apply(np.zeros(16), "s0", 0)
    meta = {"applied": 1, "in_flight": {
        "task_id": 0, "epoch": 0, "lease": "1-1", "shards": ["s0"]}}
    # ack landed before the crash -> keep the update
    w2, n2 = ew.reconcile_in_flight(
        w.copy(), 1, meta, [{"task_id": 0, "lease": "1-1"}])
    assert (w2 == w).all() and n2 == 1
    # lease never committed (task re-runs elsewhere) -> subtract
    w3, n3 = ew.reconcile_in_flight(w.copy(), 1, meta, [])
    assert (w3 == 0).all() and n3 == 0
    # a completion under a DIFFERENT lease is someone else's -> subtract
    w4, n4 = ew.reconcile_in_flight(
        w.copy(), 1, meta, [{"task_id": 0, "lease": "2-7"}])
    assert (w4 == 0).all() and n4 == 0
    # no in-flight task recorded -> untouched
    w5, n5 = ew.reconcile_in_flight(w.copy(), 1, {"applied": 1}, [])
    assert (w5 == w).all() and n5 == 1


def test_legacy_leaseless_acks_still_work():
    m = TaskMaster()
    m.set_dataset(["a", "b"])
    t = m.get_task()
    assert m.task_finished(t.task_id) == "ok"      # no lease presented
    t2 = m.get_task()
    assert m.task_failed(t2.task_id) == "ok"
    assert m.task_finished(999) == "unknown"


# ------------------------------------------ generations + snapshots

def test_generation_bumps_on_every_restart(tmp_path):
    snap = str(tmp_path / "m.json")
    m1 = TaskMaster(snapshot_path=snap)
    m1.set_dataset(["a"])
    assert m1.generation == 1
    m2 = TaskMaster(snapshot_path=snap)
    assert m2.generation == 2
    m3 = TaskMaster(snapshot_path=snap)
    assert m3.generation == 3
    assert _gauge("master_generation") == 3
    assert m3.stats()["todo"] == 1         # queue state carried over


def test_pre_restart_lease_is_fenced_after_recovery(tmp_path):
    snap = str(tmp_path / "m.json")
    m1 = TaskMaster(snapshot_path=snap, snapshot_interval=0,
                    num_epochs=1)
    m1.set_dataset(["a", "b"])
    t = m1.get_task()
    m2 = TaskMaster(snapshot_path=snap)    # restart: leases void
    assert m2.task_finished(t.task_id, lease=t.lease) == "fenced"
    # the task went back to todo and completes under a NEW lease
    ids = set()
    while True:
        t2 = m2.get_task()
        if t2 is None:
            break
        assert t2.lease.startswith(f"{m2.generation}-")
        assert m2.task_finished(t2.task_id, lease=t2.lease) == "ok"
        ids.add(t2.task_id)
    assert ids == {0, 1}


def test_corrupt_snapshot_truncated_recovers_fresh(tmp_path):
    snap = str(tmp_path / "m.json")
    m1 = TaskMaster(snapshot_path=snap, snapshot_interval=0)
    m1.set_dataset(["a", "b", "c"])
    t = m1.get_task()
    m1.task_finished(t.task_id, lease=t.lease)
    with open(snap, "r+b") as f:           # torn write
        f.truncate(os.path.getsize(snap) // 2)
    c0 = _counter("taskmaster_snapshot_corrupt_total")
    with pytest.warns(RuntimeWarning, match="corrupt"):
        m2 = TaskMaster(snapshot_path=snap)
    assert _counter("taskmaster_snapshot_corrupt_total") == c0 + 1
    s = m2.stats()
    assert s["todo"] == s["done"] == s["pending"] == 0   # fresh state
    # the generation sidecar survived the snapshot tear: stale-lease
    # detection still works on exactly the restart that needed it
    assert m2.generation == 2
    m2.set_dataset(["x"])                  # master is usable again


def test_corrupt_snapshot_bitflip_caught_by_crc(tmp_path):
    snap = str(tmp_path / "m.json")
    m1 = TaskMaster(snapshot_path=snap, snapshot_interval=0)
    m1.set_dataset(["a", "b"])
    raw = bytearray(open(snap, "rb").read())
    # flip one bit inside the CRC-framed payload (past the header)
    raw[len(raw) // 2] ^= 0x08
    open(snap, "wb").write(bytes(raw))
    with pytest.warns(RuntimeWarning, match="corrupt"):
        m2 = TaskMaster(snapshot_path=snap)
    assert m2.stats()["todo"] == 0 and m2.generation == 2


def test_master_restart_same_port_client_redials_and_drains(tmp_path):
    """ISSUE 5 satellite: snapshot -> kill serve_master -> restart on
    the same port -> the same client re-dials and drains the remaining
    queue; generation bumped, no task lost or duplicated."""
    snap = str(tmp_path / "m.json")
    port = free_port()
    m1 = TaskMaster(snapshot_path=snap, snapshot_interval=0,
                    num_epochs=1)
    m1.set_dataset([f"s{i}" for i in range(4)])
    srv, _ = serve_master(m1, port=port)
    m2 = None
    try:
        c = TaskMasterClient("127.0.0.1", port)
        t1 = c.get_task()
        assert c.task_finished(t1.task_id, lease=t1.lease) == "ok"
        t2 = c.get_task()                  # in flight across the restart
        srv.shutdown()
        m2 = TaskMaster(snapshot_path=snap, snapshot_interval=0)
        srv, _ = serve_master(m2, port=port)
        # the in-flight lease died with the old generation
        assert c.task_finished(t2.task_id, lease=t2.lease) == "fenced"
        assert c.generation_changes >= 1
        assert c.master_generation == m2.generation == 2
        done = []
        while True:
            t = c.get_task()
            if t is None:
                assert c.job_complete
                break
            assert c.task_finished(t.task_id, lease=t.lease) == "ok"
            done.append(t.task_id)
        c.close()
    finally:
        srv.shutdown()
    ledger = m2.ledger_entries()
    # exactly once across BOTH generations: 4 tasks, no dup, none lost
    assert sorted(e["task_id"] for e in ledger) == [0, 1, 2, 3]
    assert soak.check_ledger(ledger, n_tasks=4, epochs=1) == []


# -------------------------------------------------- worker membership

def test_membership_register_heartbeat_goodbye_lifecycle():
    m = TaskMaster(worker_timeout=60)
    reg = m.register_worker(0, host="h0", pid=123)
    assert reg["lease"] and reg["worker_timeout"] == 60
    assert m.stats()["workers"] == {"0": "live"}
    assert _gauge("fleet_workers", state="live") == 1
    assert m.heartbeat(0, reg["lease"]) == "ok"
    assert m.heartbeat(0, "bogus") == "fenced"
    assert m.heartbeat(7, "nope") == "fenced"      # unknown rank
    assert m.goodbye(0, reg["lease"]) == "ok"
    assert m.stats()["workers"] == {"0": "departed"}
    assert _gauge("fleet_workers", state="departed") == 1
    assert _gauge("fleet_workers", state="live") == 0


def test_worker_death_requeues_leases_immediately():
    """The membership tentpole: a dead worker's task leases requeue the
    moment its heartbeat lease expires — NOT when each per-task lease
    (here 1000x longer) would eventually time out."""
    m = TaskMaster(lease_timeout=100.0, worker_timeout=0.1)
    m.set_dataset(["a", "b", "c"])
    reg = m.register_worker(0)
    t1 = m.get_task(worker=0)
    t2 = m.get_task(worker=0)
    t3 = m.get_task(worker=1)              # another rank's lease
    d0 = _counter("taskmaster_workers_dead_total")
    time.sleep(0.15)                       # heartbeat lease expires
    s = m.stats()                          # reap runs
    assert s["workers"] == {"0": "dead"}
    assert _counter("taskmaster_workers_dead_total") == d0 + 1
    # rank 0's two leases came straight back; rank 1's still pending
    assert s["todo"] == 2 and s["pending"] == 1
    assert _gauge("fleet_workers", state="dead") == 1
    # the dead incarnation's acks fence from now on
    assert m.task_finished(t1.task_id, lease=t1.lease) == "fenced"
    assert m.heartbeat(0, reg["lease"]) == "fenced"
    # and the rank re-registers (supervisor restarted it) and rejoins
    reg2 = m.register_worker(0)
    assert m.stats()["workers"] == {"0": "live"}
    assert m.heartbeat(0, reg2["lease"]) == "ok"
    assert m.task_finished(t3.task_id, lease=t3.lease) == "ok"


def test_reregistration_supersedes_live_incarnation():
    m = TaskMaster(lease_timeout=100.0, worker_timeout=60)
    m.set_dataset(["a"])
    reg1 = m.register_worker(0)
    t = m.get_task(worker=0)
    reg2 = m.register_worker(0)            # restarted incarnation wins
    assert reg1["lease"] != reg2["lease"]
    assert m.heartbeat(0, reg1["lease"]) == "fenced"
    assert m.heartbeat(0, reg2["lease"]) == "ok"
    # the superseded incarnation's task lease was requeued
    assert m.stats()["pending"] == 0 and m.stats()["todo"] == 1
    assert m.task_finished(t.task_id, lease=t.lease) == "fenced"


def test_goodbye_requeues_without_failure_penalty():
    m = TaskMaster(worker_timeout=60)
    m.set_dataset(["a"])
    reg = m.register_worker(3)
    t = m.get_task(worker=3)
    assert m.goodbye(3, reg["lease"]) == "ok"
    assert m.stats()["todo"] == 1
    assert m.todo[0].failures == 0         # clean departure, no strike


def test_aggregator_gets_membership_truth():
    """serve_master(aggregator=...) wires the master's membership plane
    into the FleetAggregator: /healthz keys on heartbeat truth, not on
    metric-report staleness, and stragglers exclude dead ranks."""
    agg = fleet.FleetAggregator(stale_after=0.15, straggler_factor=2.0,
                                straggler_min_steps=1)
    m = TaskMaster(worker_timeout=0.15)
    srv, (host, port) = serve_master(m, aggregator=agg)
    try:
        with TaskMasterClient(host, port) as c:
            reg = c.register_worker(0)
            deadline = time.time() + 5
            while agg.membership().get(0) != "live" \
                    and time.time() < deadline:
                time.sleep(0.01)
            assert agg.membership()[0] == "live"
            h = agg.health()
            # live heartbeat, zero metric reports: NOT stale/degraded
            assert h["per_worker"]["0"]["membership"] == "live"
            assert not h["degraded"]
            # keep heartbeating while a slow "metric reporter" stays
            # silent past stale_after — membership truth wins
            for _ in range(4):
                assert c.heartbeat(0, reg["lease"]) == "ok"
                time.sleep(0.05)
            assert not agg.health()["degraded"]
        # stop heartbeating: the reaper declares death and tells agg
        deadline = time.time() + 5
        while agg.membership().get(0) != "dead" \
                and time.time() < deadline:
            time.sleep(0.02)
        h = agg.health()
        assert h["dead"] == [0] and h["degraded"]
        # a revived rank clears the alarm
        with TaskMasterClient(host, port) as c2:
            c2.register_worker(0)
        deadline = time.time() + 5
        while agg.health()["degraded"] and time.time() < deadline:
            time.sleep(0.02)
        assert not agg.health()["degraded"]
    finally:
        srv.shutdown()


def test_heartbeater_reregisters_across_master_restart(tmp_path):
    snap = str(tmp_path / "m.json")
    port = free_port()
    m1 = TaskMaster(snapshot_path=snap, worker_timeout=5.0)
    srv, _ = serve_master(m1, port=port)
    hb = None
    try:
        hb = Heartbeater(f"127.0.0.1:{port}", rank=3, interval=0.05)
        hb.start()
        assert m1.stats()["workers"] == {"3": "live"}
        srv.shutdown()
        m2 = TaskMaster(snapshot_path=snap, worker_timeout=5.0)
        srv, _ = serve_master(m2, port=port)
        # membership died with the old generation; the heartbeat fences
        # and the Heartbeater re-enrolls under the SAME rank
        deadline = time.time() + 10
        while m2.stats()["workers"].get("3") != "live" \
                and time.time() < deadline:
            time.sleep(0.02)
        assert m2.stats()["workers"] == {"3": "live"}
        assert hb.re_registrations >= 1
        assert hb.master_generation == 2
    finally:
        if hb is not None:
            hb.stop()
        srv.shutdown()


# ------------------------------------------------------ client failover

def test_client_rotates_to_live_endpoint():
    dead = free_port()                     # nothing listening
    m = TaskMaster()
    m.set_dataset(["a"])
    srv, (host, port) = serve_master(m)
    try:
        c = TaskMasterClient(
            endpoints=[f"127.0.0.1:{dead}", f"127.0.0.1:{port}"])
        assert c.port == port              # rotated past the dead one
        t = c.get_task()
        assert c.task_finished(t.task_id, lease=t.lease) == "ok"
        c.close()
    finally:
        srv.shutdown()


def test_client_fails_over_mid_session(tmp_path):
    """Two masters sharing a snapshot: kill the one the client is
    attached to and the retry layer rotates to the survivor."""
    snap = str(tmp_path / "m.json")
    m1 = TaskMaster(snapshot_path=snap, snapshot_interval=0)
    m1.set_dataset(["a", "b"])
    srv1, (h1, p1) = serve_master(m1)
    m2 = TaskMaster(snapshot_path=snap)    # recovers m1's queue, gen 2
    srv2, (h2, p2) = serve_master(m2)
    try:
        c = TaskMasterClient(endpoints=[f"{h1}:{p1}", f"{h2}:{p2}"])
        t = c.get_task()
        assert c.master_generation == 1
        srv1.shutdown()                    # primary dies mid-session
        done = set()
        while True:
            t = c.get_task()
            if t is None:
                break
            if c.task_finished(t.task_id, lease=t.lease) == "ok":
                done.add(t.task_id)
            if t.epoch > 0:
                break
        assert c.port == p2                # survived via the standby
        assert c.master_generation == 2 and c.generation_changes >= 1
        assert done                        # made progress on gen 2
        c.close()
    finally:
        srv2.shutdown()


# --------------------------------------------------------- chaos kinds

def test_chaos_parse_new_kinds_and_defaults():
    faults = chaos.parse_spec("a=exit;b=refuse;c=exit:0.5:3")
    assert faults["a"].kind == "exit" and faults["a"].arg == 9.0
    assert faults["b"].kind == "refuse" and faults["b"].arg == 0.25
    assert faults["c"].prob == 0.5 and faults["c"].arg == 3.0
    with pytest.raises(ValueError, match="unknown fault kind"):
        chaos.parse_spec("a=explode")


@pytest.mark.chaos
def test_chaos_refuse_window_rides_on_retry():
    """One refuse decision opens a WINDOW: every pass inside raises
    ConnectionRefusedError without burning schedule slots, and the
    client's backoff outlives the window."""
    import zlib

    def _fire(seed, site, n, prob):        # the chaos plane's own hash
        return zlib.crc32(f"{seed}:{site}:{n}".encode()) \
            / 0xFFFFFFFF < prob

    site, prob = "task_queue.rpc", 0.3
    # fire on invocation 0 (open the window immediately), then stay
    # quiet so the post-window attempt goes through.  (prob=0.5 would
    # be unsatisfiable here: same-length messages make crc32 values of
    # adjacent invocations differ by a CONSTANT xor, which pins their
    # threshold bits together across every seed.)
    seed = next(s for s in range(2000)
                if _fire(s, site, 0, prob)
                and not any(_fire(s, site, n, prob) for n in (1, 2)))
    flags.set_flag("chaos_seed", seed)
    flags.set_flag("chaos_spec", f"{site}=refuse:{prob}:0.15")
    flags.set_flag("retry_max_attempts", 8)
    a0 = _counter("retry_attempts_total")
    try:
        m = TaskMaster()
        m.set_dataset(["a"])
        srv, (host, port) = serve_master(m)
        try:
            c = TaskMasterClient(host, port)
            t = c.get_task()               # rode through the window
            assert t is not None
            c.close()
        finally:
            srv.shutdown()
        fires = [f for f in chaos.schedule()
                 if f[0] == site and f[2] == "refuse"]
        # ONE schedule slot opened the window, however many RPC
        # attempts it refused (in-window raises don't advance it)
        assert len(fires) == 1 and fires[0][1] == 0
        assert _counter("retry_attempts_total") > a0
    finally:
        flags.set_flag("chaos_spec", "")
        flags.set_flag("retry_max_attempts", 3)
        chaos.reset()


@pytest.mark.chaos
def test_chaos_exit_kills_the_process():
    code = (
        "from paddle_tpu.core import flags\n"
        "from paddle_tpu.resilience import chaos\n"
        "flags.set_flag('chaos_spec', 'boom=exit:1.0:7')\n"
        "chaos.trigger('boom')\n"
        "print('SURVIVED')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop("PYTHONPATH", None)
    p = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 7               # os._exit(arg): kill -9 like
    assert "SURVIVED" not in p.stdout
    assert "injected hard exit" in p.stderr


# ----------------------------------------------------------- supervisor

def _fast_backoff():
    return rretry.RetryPolicy(name="supervisor_restart", max_attempts=1,
                              base_delay=0.01, max_delay=0.05)


def test_supervisor_restarts_crashed_worker_until_success():
    # exits 3 on the first incarnation, 0 once restarted
    cmd = [sys.executable, "-c",
           "import os, sys; "
           "sys.exit(0 if os.environ.get('PTPU_WORKER_RESTART_COUNT') "
           "== '1' else 3)"]
    r0 = _counter("worker_restarts_total")
    sup = Supervisor([cmd], max_restarts=3, backoff=_fast_backoff())
    sup.start()
    assert sup.wait(timeout=30)
    st = sup.status()[0]
    assert st["state"] == "done" and st["restarts"] == 1
    assert _counter("worker_restarts_total") == r0 + 1
    sup.stop()


def test_supervisor_max_restarts_cap():
    cmd = [sys.executable, "-c", "import sys; sys.exit(5)"]
    sup = Supervisor([cmd], max_restarts=2, backoff=_fast_backoff())
    sup.start()
    assert sup.wait(timeout=30) is False   # terminal, but failed
    st = sup.status()[0]
    assert st["state"] == "failed" and st["restarts"] == 2
    assert st["rc"] == 5
    sup.stop()


def test_supervisor_restart_env_strips_chaos():
    """A restarted incarnation runs with PTPU_CHAOS_SPEC cleared by
    default: the deterministic schedule that killed incarnation 0 would
    kill every identical rerun at the same step forever."""
    cmd = [sys.executable, "-c",
           "import os, sys\n"
           "n = os.environ.get('PTPU_WORKER_RESTART_COUNT')\n"
           "spec = os.environ.get('PTPU_CHAOS_SPEC')\n"
           "sys.exit(2 if n == '0' else (0 if spec == '' else 4))"]
    sup = Supervisor([cmd], env=dict(os.environ,
                                     PTPU_CHAOS_SPEC="x=exit:1.0"),
                     max_restarts=1, backoff=_fast_backoff())
    sup.start()
    assert sup.wait(timeout=30)            # rc 4 would mean spec leaked
    sup.stop()


def test_supervisor_backoff_is_deterministic():
    pol = _fast_backoff()
    assert pol.delay(1) == pol.delay(1)    # crc32 jitter, no RNG
    assert pol.delay(2) >= pol.delay(1) * 0.9


# ----------------------------------------------------- ledger checking

def test_check_ledger_flags_duplicates_and_gaps():
    ok = [{"task_id": t, "epoch": e} for t in range(2) for e in range(2)]
    assert soak.check_ledger(ok, n_tasks=2, epochs=2) == []
    dup = ok + [{"task_id": 0, "epoch": 0}]
    assert any("duplicate" in p
               for p in soak.check_ledger(dup, n_tasks=2, epochs=2))
    assert any("missing" in p
               for p in soak.check_ledger(ok[:-1], n_tasks=2, epochs=2))
    extra = ok + [{"task_id": 9, "epoch": 0}]
    assert any("unexpected" in p
               for p in soak.check_ledger(extra, n_tasks=2, epochs=2))


def test_reset_state_zeroes_membership_gauges():
    from paddle_tpu.distributed import task_queue
    m = TaskMaster(worker_timeout=60)
    m.register_worker(0)
    assert _gauge("fleet_workers", state="live") == 1
    task_queue.reset_state()
    assert _gauge("fleet_workers", state="live") == 0
    assert not list(task_queue._MASTERS)


# -------------------------------------------------- trainer resume mark

def test_trainer_resume_is_counted(tmp_path):
    import numpy as np
    root = str(tmp_path / "ck")

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, bias_attr=False, name="fc")
        return layers.mean(layers.square_error_cost(pred, y))

    def make():
        pt.reset_default_programs()
        cfg = pt.CheckpointConfig(root, max_num_checkpoints=3,
                                  step_interval=1)
        return pt.Trainer(train_func,
                          lambda: pt.optimizer.SGD(learning_rate=0.05),
                          place=pt.CPUPlace(), checkpoint_config=cfg)

    rng = np.random.RandomState(0)
    batches = [[(rng.rand(4).astype("float32"),
                 rng.rand(1).astype("float32")) for _ in range(4)]
               for _ in range(3)]
    r0 = _counter("trainer_resumes_total")
    t1 = make()                            # nothing to resume from
    assert _counter("trainer_resumes_total") == r0
    t1.train(num_epochs=1, event_handler=lambda e: None,
             reader=lambda: iter(batches), feed_order=["x", "y"])
    t1.stop()
    t2 = make()                            # the restarted-worker path
    assert _counter("trainer_resumes_total") == r0 + 1
    t2.stop()


# ------------------------------------------------- end-to-end headline

def test_e2e_worker_kill_and_master_failover_exactly_once(tmp_path):
    """ISSUE 5 headline acceptance: a 2-worker supervised run where a
    deterministic chaos schedule kill-9s rank 0 mid-epoch AND the
    master is restarted from its snapshot on the same port.  Training
    completes hands-off; the persisted ledger shows every (task, epoch)
    processed exactly once (zero fenced acks accepted); the supervisor
    revived rank 0 within its backoff budget and the restarted
    incarnation resumed from its checkpoint."""
    rep = soak.run_schedule(str(tmp_path), "combined", world=2,
                            n_tasks=6, epochs=2, timeout=90)
    assert rep["ok"], rep["problems"]
    assert rep["ledger_entries"] == 12     # 6 tasks x 2 epochs, once
    assert rep["restarts"][0] >= 1         # supervisor revived rank 0
    assert rep["generation"] >= 2          # master restarted + bumped
    assert rep["stats"]["complete"]
    w = {r["rank"]: r for r in rep["workers"]}
    assert w[0]["restart_count"] >= 1 and w[0]["resumed"]
    # the survivor rode across both generations
    assert 2 in w[1]["generations"]
    # fenced acks were REJECTED, never recorded: client-side completion
    # claims agree with the master's exactly-once ledger
    claims = [tuple(c) for r in rep["workers"] for c in r["completed"]]
    assert len(claims) == len(set(claims))


@pytest.mark.slow
def test_soak_matrix_all_schedules(tmp_path):
    """The full chaos matrix (worker kill / master restart / RPC refuse
    / combined, plus the fixed-fleet baseline and the ISSUE 14 resize
    schedules incl. the 2→4→1→3 headline) through the CLI entry point —
    the CI soak lane."""
    rc = soak._main(["--workdir", str(tmp_path), "--timeout", "120",
                     "--out", str(tmp_path / "report.json")])
    assert rc == 0
    rep = json.load(open(tmp_path / "report.json"))
    assert len(rep["reports"]) == len(soak.SCHEDULES)
    assert all(r["ok"] for r in rep["reports"])
