"""Tensor parallelism on the Program plane (VERDICT r2 item #5).

Contract: a user-built Program (the transformer from models/transformer.py)
annotated by TensorParallelTranspiler — or by hand via
ParamAttr(sharding=...) — trains on a (data x model) mesh with per-step
loss parity against the single-device run, the same bar the DP plane
meets in tests/test_parallel_executor.py (and the reference meets in
test_dist_base.py check_with_place:502).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.place import make_mesh
from paddle_tpu.models import transformer
from paddle_tpu.transpiler import TensorParallelTranspiler


def _build_lm(seed=11):
    cfg = transformer.TransformerConfig(
        src_vocab_size=64, tgt_vocab_size=64, max_length=16, n_layer=2,
        n_head=4, d_model=16, d_inner=32, dropout=0.0,
        label_smooth_eps=0.0)
    main, startup = pt.Program(), pt.Program()
    main.random_seed = seed
    with pt.program_guard(main, startup):
        feeds, avg_cost, _ = transformer.build_lm_net(
            cfg, seq_len=12, fused_attention=False)
        pt.optimizer.SGD(0.05).minimize(avg_cost)
    return cfg, main, startup, avg_cost


def _batches(cfg, n=4, bs=8, seq=12):
    rng = np.random.RandomState(3)
    out = []
    for _ in range(n):
        toks = rng.randint(1, cfg.src_vocab_size, (bs, seq)).astype("int64")
        out.append({"tokens": toks, "labels": np.roll(toks, -1, 1)})
    return out


def test_transpiler_assigns_megatron_recipe():
    cfg, main, startup, loss = _build_lm()
    specs = TensorParallelTranspiler("model").transpile(main,
                                                       num_partitions=4)
    vals = list(specs.values())
    # vocab-parallel embedding, and the column->row alternation visible
    assert ("model", None) in vals and (None, "model") in vals
    emb = [n for n in specs if "word_emb" in n]
    assert emb and specs[emb[0]] == ("model", None)
    col = sum(1 for v in vals if v == (None, "model"))
    row = sum(1 for v in vals if v == ("model", None))
    assert col >= cfg.n_layer * 2      # qkv projections + ffn1 (+ head)
    assert row >= cfg.n_layer * 2      # out-proj + ffn2 (+ embedding)


def test_transpiler_divisibility_enforced():
    cfg, main, startup, loss = _build_lm()
    with pytest.raises(Exception):
        TensorParallelTranspiler("model").transpile(main, num_partitions=7)


def _train(main, startup, loss, batches, mesh=None, batch_axis="data"):
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace(), scope=scope, mesh=mesh,
                      batch_axis=batch_axis)
    exe.run(startup)
    return scope, [float(np.asarray(
        exe.run(main, feed=f, fetch_list=[loss.name])[0]))
        for f in batches]


def test_tensor_parallel_loss_parity():
    """Program-built transformer LM: single device vs 2x4 (data x model)
    mesh after the tp transpile — per-step losses must match."""
    cfg, main, startup, loss = _build_lm()
    batches = _batches(cfg)
    _, single = _train(main, startup, loss, batches)

    cfg2, main2, startup2, loss2 = _build_lm()   # same seed -> same init
    TensorParallelTranspiler("model").transpile(main2, num_partitions=4)
    mesh = make_mesh((2, 4), ("data", "model"))
    scope, par = _train(main2, startup2, loss2, batches, mesh=mesh)
    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)

    # the weights really live sharded over the model axis
    sharded = [n for n in scope.var_names()
               if main2.global_block().has_var(n)
               and getattr(main2.global_block().var(n), "sharding", None)]
    w = scope.find_var(sharded[0])
    assert not w.sharding.is_fully_replicated


def test_manual_param_attr_sharding_parity():
    """The ParamAttr(sharding=...) spelling — no transpiler — reaches the
    same plane: hand-annotated fc pair (column then row parallel)."""
    def build(seed=9, shard=False):
        col = pt.ParamAttr(sharding=(None, "model")) if shard else None
        row = pt.ParamAttr(sharding=("model", None)) if shard else None
        main, startup = pt.Program(), pt.Program()
        main.random_seed = seed
        with pt.program_guard(main, startup):
            x = layers.data("x", [16])
            y = layers.data("y", [1])
            h = layers.fc(x, size=32, act="relu", param_attr=col,
                          bias_attr=False)
            p = layers.fc(h, size=1, param_attr=row, bias_attr=False)
            loss = layers.mean(layers.square_error_cost(p, y))
            pt.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(1)
    w = rng.randn(16, 1).astype("float32")
    batches = [{"x": (xb := rng.randn(16, 16).astype("float32")),
                "y": xb @ w} for _ in range(4)]
    main, startup, loss = build()
    _, single = _train(main, startup, loss, batches)
    main2, startup2, loss2 = build(shard=True)
    mesh = make_mesh((2, 4), ("data", "model"))
    _, par = _train(main2, startup2, loss2, batches, mesh=mesh)
    np.testing.assert_allclose(single, par, rtol=1e-4, atol=1e-5)


def test_tp_with_fused_mha_is_correct_but_attention_replicated():
    """GSPMD cannot see inside the fused_mha pallas_call, so a
    tp-transpiled fused-attention model runs the attention op
    replicated while FFN/embedding shard — numerically identical to
    the single-device run (the capability guard: correct, not fast;
    fully tensor-parallel attention lives on the unfused path or
    parallel/hybrid.py)."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core.place import make_mesh

    def build():
        pt.reset_default_programs()
        main, startup = (pt.default_main_program(),
                         pt.default_startup_program())
        main.random_seed = startup.random_seed = 5
        cfg = models.transformer.TransformerConfig(
            src_vocab_size=64, tgt_vocab_size=64, max_length=16,
            n_layer=2, n_head=2, d_model=16, d_inner=32, dropout=0.0)
        _, cost, _ = models.transformer.build_lm_net(
            cfg, seq_len=16, fused_attention=True, fused_head=False)
        pt.optimizer.SGD(learning_rate=0.1).minimize(cost)
        return main, startup, cost

    rng = np.random.RandomState(0)
    toks = rng.randint(0, 64, (8, 16)).astype("int64")
    feed = {"tokens": toks, "labels": np.roll(toks, -1, 1)}

    main, startup, cost = build()
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    ref = [float(np.asarray(exe.run(main, feed=feed,
                                    fetch_list=[cost])[0]).ravel()[0])
           for _ in range(3)]

    main2, startup2, cost2 = build()
    specs = pt.transpiler.TensorParallelTranspiler(
        axis_name="model").transpile(main2, num_partitions=4)
    assert specs                      # ffn/embedding params sharded
    mesh = make_mesh((2, 4), ("data", "model"))
    exe2 = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe2.run(startup2)
    got = [float(np.mean(np.asarray(
        exe2.run(main2, feed=feed, fetch_list=[cost2])[0])))
        for _ in range(3)]
    np.testing.assert_allclose(got, ref, rtol=5e-4)
