"""DistributeTranspiler: structural assertions on the rewritten program
(the reference's test_dist_transpiler.py pattern) + loss parity of the
transpiled program on an 8-device mesh vs single-device training."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.place import make_mesh

rng = np.random.RandomState(0)
X = rng.randn(16, 8).astype("f4")
Y = (X[:, :1] > 0).astype("i8")


def build():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1], dtype="int64")
        h = layers.fc(x, size=4, act="tanh")
        p = layers.fc(h, size=2, act="softmax")
        loss = layers.mean(layers.cross_entropy(p, y))
        pt.optimizer.SGD(learning_rate=0.5).minimize(loss)
    return main, startup, loss


def test_transpile_inserts_allreduce_scale_pairs():
    main, startup, loss = build()
    before = [op.type for op in main.global_block().ops]
    t = pt.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, trainers=4)
    prog = t.get_trainer_program()
    ops = [op.type for op in prog.global_block().ops]
    n_grads = len(main.global_block().ops[
        [o.type for o in main.global_block().ops].index("autodiff")]
        .attrs["grads"])
    # one (c_allreduce_sum, scale) pair per gradient, inserted after
    # the autodiff op and before the optimizer ops
    assert ops.count("c_allreduce_sum") == n_grads
    assert ops.count("scale") == before.count("scale") + n_grads
    ad = ops.index("autodiff")
    first_opt = ops.index("sgd")
    ar_positions = [i for i, o in enumerate(ops) if o == "c_allreduce_sum"]
    assert all(ad < i < first_opt for i in ar_positions)
    # scale factor is 1/trainers, writing back to the grad var
    block = prog.global_block()
    scale_ops = [op for op in block.ops if op.type == "scale"
                 and op.inputs["X"][0].endswith("@ALLREDUCE")]
    assert all(abs(op.attrs["scale"] - 0.25) < 1e-9 for op in scale_ops)
    assert prog._dist_spmd_axis == "data"
    assert prog._dist_trainers == 4


def test_transpile_single_trainer_is_identity():
    main, startup, loss = build()
    t = pt.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, trainers=1)
    ops = [op.type for op in t.get_trainer_program().global_block().ops]
    assert "c_allreduce_sum" not in ops
    assert getattr(t.get_trainer_program(), "_dist_spmd_axis", None) is None


def test_transpiled_program_matches_single_device():
    main, startup, loss = build()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    ref = []
    for _ in range(5):
        out, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        ref.append(float(np.asarray(out).ravel()[0]))

    main2, startup2, loss2 = build()
    t = pt.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main2, trainers=8)
    prog = t.get_trainer_program()
    mesh = make_mesh((8,), ("data",))
    exe2 = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe2.run(startup2)
    dist = []
    for _ in range(5):
        out, = exe2.run(prog, feed={"x": X, "y": Y}, fetch_list=[loss2])
        # per-shard losses come back stacked along the shard axis
        assert np.asarray(out).shape[0] == 8
        dist.append(float(np.mean(np.asarray(out))))
    assert all(abs(a - b) < 1e-4 for a, b in zip(ref, dist)), (ref, dist)


def test_mesh_size_mismatch_raises():
    main, startup, loss = build()
    t = pt.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, trainers=4)
    mesh = make_mesh((8,), ("data",))
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup)
    with pytest.raises(pt.core.enforce.EnforceNotMet):
        exe.run(t.get_trainer_program(), feed={"x": X, "y": Y},
                fetch_list=[loss])


def test_pserver_program_still_guides():
    main, startup, loss = build()
    t = pt.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, trainers=2)
    with pytest.raises(NotImplementedError):
        t.get_pserver_program("127.0.0.1:6174")


def test_markers_survive_clone_and_serde():
    main, startup, loss = build()
    t = pt.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, trainers=8)
    prog = t.get_trainer_program()
    rt = pt.Program.from_dict(prog.to_dict())
    assert rt._dist_spmd_axis == "data" and rt._dist_trainers == 8
    cl = prog.clone()
    assert getattr(cl, "_dist_spmd_axis", None) == "data"


def test_transpiled_without_mesh_raises_clearly():
    main, startup, loss = build()
    t = pt.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, trainers=8)
    exe = pt.Executor(pt.CPUPlace())          # no mesh
    exe.run(startup)
    with pytest.raises(pt.core.enforce.EnforceNotMet,
                       match="DistributeTranspiler"):
        exe.run(t.get_trainer_program(), feed={"x": X, "y": Y},
                fetch_list=[loss])
