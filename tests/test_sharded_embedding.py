"""Sharded sparse-embedding capability (the reference's pserver
distributed-lookup-table, transpiler/distribute_transpiler.py:1010,1274 +
parameter_prefetch.cc): shard_map row-sharded lookup + sparse scatter
updates, and the declarative Program-path equivalent on DeepFM."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.core import jax_compat
from paddle_tpu.core.place import make_mesh
from paddle_tpu.parallel import sharded_embedding as se


def _mesh(dp, mp):
    return make_mesh((dp, mp), ("data", "model"))


def test_row_sharded_lookup_matches_take():
    mesh = _mesh(2, 4)
    V, D, B, F = 32, 4, 6, 3
    rng = np.random.RandomState(0)
    table = rng.randn(V, D).astype("float32")
    ids = rng.randint(0, V, (B, F)).astype("int32")

    def f(table, ids):
        return se.row_sharded_lookup(table, ids)

    out = jax.jit(jax_compat.shard_map(
        f, mesh=mesh,
        in_specs=(jax.sharding.PartitionSpec("model", None),
                  jax.sharding.PartitionSpec("data", None)),
        out_specs=jax.sharding.PartitionSpec("data", None, None),
        check_rep=False))(table, ids)
    np.testing.assert_allclose(np.asarray(out), table[ids], rtol=1e-6)


def test_ctr_step_parity_vs_reference():
    """One sharded step == one dense single-device step (params + loss)."""
    cfg = se.ShardedCTRConfig(vocab_size=64, num_field=5, embed_dim=4,
                              fc_sizes=(8,), learning_rate=0.1)
    mesh = _mesh(4, 2)
    params = se.init_ctr_params(mesh, cfg, seed=3)
    host = {k: np.asarray(v) for k, v in params.items()}
    ids, vals, label = se.make_fake_ctr_batch(cfg, batch=8, seed=1)

    step = se.build_ctr_train_step(mesh, cfg)
    new_params, loss = step(params, ids, vals, label)

    ref_params, ref_loss = se.reference_ctr_step(host, cfg, ids, vals,
                                                 label)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in ref_params:
        np.testing.assert_allclose(
            np.asarray(new_params[k]), np.asarray(ref_params[k]),
            rtol=2e-4, atol=1e-6, err_msg=f"param {k} diverged")


def test_ctr_million_row_table_trains():
    """BASELINE config 4 scale: a 1M-row table trains sharded; loss
    decreases over steps on a repeated batch."""
    cfg = se.ShardedCTRConfig(vocab_size=1_000_000, num_field=10,
                              embed_dim=8, fc_sizes=(32,),
                              learning_rate=0.5)
    mesh = _mesh(2, 4)
    params = se.init_ctr_params(mesh, cfg, seed=0)
    step = se.build_ctr_train_step(mesh, cfg)
    ids, vals, label = se.make_fake_ctr_batch(cfg, batch=16, seed=0)
    losses = []
    for _ in range(4):
        params, loss = step(params, ids, vals, label)
        losses.append(float(jax.block_until_ready(loss)))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_sparse_update_touches_only_looked_up_rows():
    cfg = se.ShardedCTRConfig(vocab_size=64, num_field=2, embed_dim=4,
                              fc_sizes=(8,), learning_rate=0.1)
    mesh = _mesh(2, 2)
    params = se.init_ctr_params(mesh, cfg, seed=0)
    before = np.asarray(params["emb"]).copy()
    ids = np.array([[3, 17], [3, 40], [9, 60], [61, 5]], dtype="int32")
    vals = np.ones((4, 2), "float32")
    label = np.ones((4, 1), "float32")
    step = se.build_ctr_train_step(mesh, cfg)
    new_params, _ = step(params, ids, vals, label)
    after = np.asarray(new_params["emb"])
    touched = sorted(set(ids.ravel().tolist()))
    untouched = [i for i in range(64) if i not in touched]
    np.testing.assert_array_equal(after[untouched], before[untouched])
    assert np.abs(after[touched] - before[touched]).max() > 0


def test_deepfm_program_path_sharded_parity():
    """DeepFM (config 4) through the Program/Executor path with the table
    Parameter row-sharded over 'model': loss parity vs replicated run —
    XLA SPMD supplies the collectives the transpiler's pserver split
    provided (distribute_transpiler.py:1010)."""
    losses = {}
    for axis in (None, "model"):
        pt.reset_default_programs()
        from paddle_tpu.framework import executor as em
        em._global_scope = em.Scope()
        cfg = models.deepfm.DeepFMConfig(
            num_field=6, vocab_size=80, embed_dim=4, fc_sizes=(16,),
            sparse_shard_axis=axis)
        feeds, avg_cost, prob = models.deepfm.build_train_net(cfg)
        pt.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
        pt.default_startup_program().random_seed = 11
        feed = models.deepfm.make_fake_batch(cfg, 8)
        if axis is None:
            exe = pt.Executor(pt.CPUPlace())
            exe.run(pt.default_startup_program())
        else:
            mesh = _mesh(4, 2)
            exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
            exe.run(pt.default_startup_program())
        run = []
        for _ in range(3):
            out, = exe.run(pt.default_main_program(), feed=feed,
                           fetch_list=[avg_cost])
            run.append(float(out))
        losses[axis] = run
    np.testing.assert_allclose(losses[None], losses["model"],
                               rtol=1e-4, atol=1e-6)
    assert losses["model"][-1] < losses["model"][0]


def test_embedding_is_sparse_attr_recorded():
    x = layers.data("x", [4], dtype="int64")
    layers.embedding(x, size=[10, 4], is_sparse=True)
    op = [o for o in pt.default_main_program().global_block().ops
          if o.type == "lookup_table"][0]
    assert op.attrs["is_sparse"] is True


def test_sharded_table_across_two_processes(tmp_path):
    """The distributed-lookup-table capability at PROCESS scope, on the
    PROGRAM plane (parameter_prefetch.cc:1): 2 spawned processes build
    the DeepFM Program with ParamAttr(sharding=("model", None)) and
    train via Executor(mesh=...) — loss parity vs a single-process run
    of the identical program, and the ranks' disjoint table shards add
    up to the single-process table."""
    import dist_emb_worker
    from dist_harness import spawn_workers

    results = spawn_workers("dist_emb_worker.py", world=2,
                            tmp_path=tmp_path)

    # single-process ground truth: the identical seeded program
    import paddle_tpu as pt
    from paddle_tpu import models
    main, startup, loss, cfg = dist_emb_worker.build_program(pt, models)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    ref_losses = dist_emb_worker.train_steps(models, exe, main, loss,
                                             cfg)

    for r in results:
        np.testing.assert_allclose(r["losses"], ref_losses,
                                   rtol=1e-4, atol=1e-5)
    # reassemble BOTH row-sharded tables (fm_w1 [V,1] and fm_emb [V,K])
    # from the ranks' disjoint shards and compare elementwise
    for wname in dist_emb_worker.sharded_param_names(main):
        ref_table = np.asarray(exe.scope.find_var(wname))
        rebuilt = np.concatenate(
            [np.asarray(r["shards"][wname], "f4") for r in results],
            axis=0)
        assert rebuilt.shape == ref_table.shape
        np.testing.assert_allclose(rebuilt, ref_table, rtol=1e-4,
                                   atol=1e-5, err_msg=wname)


def test_ctr_step_duplicate_id_batches_match_dense_reference():
    """The scatter-add-vs-overwrite bug class (ISSUE 13 satellite):
    batches BUILT from duplicate ids — the same id repeated within a
    row, across rows, and across data-parallel ranks — must produce
    the dense reference's accumulated update, not a last-writer-wins
    row."""
    cfg = se.ShardedCTRConfig(vocab_size=32, num_field=4, embed_dim=4,
                              fc_sizes=(8,), learning_rate=0.2)
    mesh = _mesh(4, 2)
    params = se.init_ctr_params(mesh, cfg, seed=7)
    host = {k: np.asarray(v) for k, v in params.items()}
    # 8 samples, every field drawing from THREE ids: id 5 appears in
    # every sample (and twice in some rows), so its row accumulates
    # 8+ cotangents across all four data ranks
    ids = np.array([[5, 5, 9, 13], [5, 9, 5, 13], [5, 13, 9, 5],
                    [5, 5, 5, 5], [9, 5, 13, 5], [13, 5, 9, 5],
                    [5, 9, 13, 5], [5, 5, 13, 9]], dtype="int32")
    rng = np.random.RandomState(3)
    vals = rng.rand(8, 4).astype("float32")
    label = rng.randint(0, 2, (8, 1)).astype("float32")

    step = se.build_ctr_train_step(mesh, cfg)
    new_params, loss = step(params, ids, vals, label)
    ref_params, ref_loss = se.reference_ctr_step(host, cfg, ids, vals,
                                                 label)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in ref_params:
        np.testing.assert_allclose(
            np.asarray(new_params[k]), np.asarray(ref_params[k]),
            rtol=2e-4, atol=1e-6, err_msg=f"param {k} diverged")
    # the shared row really moved (an overwrite bug would still move
    # it — the allclose above is the accumulation proof; this guards
    # against a silently-zero gradient instead)
    assert np.abs(np.asarray(new_params["emb"])[5]
                  - host["emb"][5]).max() > 0


def test_sparse_scatter_update_shard_map_lane_duplicate_ids():
    """sparse_scatter_update in isolation on the multi-device
    shard_map lane (core/jax_compat.py): duplicate ids within AND
    across data ranks scatter-ADD into the owning model shard, and
    rows nobody touched stay byte-identical."""
    import jax
    from jax.sharding import PartitionSpec as P

    V, D, B, F = 16, 4, 8, 2
    mesh = _mesh(4, 2)
    rng = np.random.RandomState(0)
    table = rng.randn(V, D).astype("float32")
    # ids concentrated on rows {2, 3, 11}: row 2 appears 9 times
    ids = np.array([[2, 2], [2, 3], [3, 2], [2, 11], [11, 2],
                    [2, 3], [3, 11], [2, 2]], dtype="int32")
    grads = rng.randn(B, F, D).astype("float32")
    lr = 0.1

    def f(tbl, ids, g):
        return se.sparse_scatter_update(tbl, ids, g, lr)

    out = jax.jit(jax_compat.shard_map(
        f, mesh=mesh,
        in_specs=(P("model", None), P("data", None),
                  P("data", None, None)),
        out_specs=P("model", None), check_rep=False))(table, ids, grads)
    # dense reference: scatter-add every (id, grad) pair
    ref = table.copy()
    np.add.at(ref, ids.reshape(-1), -lr * grads.reshape(-1, D))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                               atol=1e-6)
    untouched = [i for i in range(V) if i not in (2, 3, 11)]
    np.testing.assert_array_equal(np.asarray(out)[untouched],
                                  table[untouched])
