"""Beam search ops (ref operators/beam_search_op.cc,
beam_search_decode_op.cc) + the machine-translation book example
(ref tests/book/test_machine_translation.py): train seq2seq+attention,
then beam-decode with finite scores."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models

from op_test import OpTest


def np_beam_step(pre_scores, pre_ids, log_probs, end_id):
    B, K, V = log_probs.shape
    lp = log_probs.copy()
    for b in range(B):
        for k in range(K):
            if pre_ids[b, k] == end_id:
                lp[b, k, :] = -1e9
                lp[b, k, end_id] = 0.0
    total = pre_scores[..., None] + lp
    flat = total.reshape(B, K * V)
    idx = np.argsort(-flat, axis=1)[:, :K]
    scores = np.take_along_axis(flat, idx, axis=1)
    return scores, (idx % V).astype("int32"), (idx // V).astype("int32")


class TestBeamSearch(OpTest):
    op_type = "beam_search"

    def setup(self):
        rng = np.random.RandomState(0)
        B, K, V = 2, 3, 7
        pre_scores = rng.randn(B, K).astype("float32")
        pre_ids = rng.randint(2, V, (B, K)).astype("int32")
        pre_ids[0, 1] = 1                       # one finished beam
        log_probs = np.log(
            rng.dirichlet(np.ones(V), size=(B, K)).astype("float32"))
        scores, ids, parents = np_beam_step(
            pre_scores.astype("float64"), pre_ids,
            log_probs.astype("float64"), end_id=1)
        self.inputs = {"PreScores": pre_scores, "PreIds": pre_ids,
                       "LogProbs": log_probs}
        self.attrs = {"beam_size": K, "end_id": 1}
        self.outputs = {"Scores": scores, "Ids": ids, "Parents": parents}

    def test_output(self):
        self.check_output(atol=1e-5)


def test_beam_search_decode_backtracks():
    """Hand-built 2-step trellis: backtracking recovers the right paths."""
    # T=2, B=1, K=2
    ids = np.array([[[5, 6]], [[7, 8]]], dtype="int32")      # [T,B,K]
    parents = np.array([[[0, 0]], [[1, 0]]], dtype="int32")
    scores = np.array([[-0.5, -1.0]], dtype="float32")
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        b = main.global_block()
        for n, a in (("ids", ids), ("parents", parents), ("sc", scores)):
            b.create_var(name=n, shape=a.shape, dtype=str(a.dtype),
                         is_data=True)
        b.create_var(name="sent", dtype="int32")
        b.create_var(name="sent_sc", dtype="float32")
        b.append_op("beam_search_decode",
                    {"Ids": ["ids"], "Parents": ["parents"],
                     "Scores": ["sc"]},
                    {"SentenceIds": ["sent"], "SentenceScores": ["sent_sc"]},
                    {})
    exe = pt.Executor(pt.CPUPlace())
    sent, sc = exe.run(main, feed={"ids": ids, "parents": parents,
                                   "sc": scores},
                       fetch_list=["sent", "sent_sc"])
    # beam 0 at t=1 came from parent 1 (token 6), then token 7
    np.testing.assert_array_equal(sent[0, 0], [6, 7])
    np.testing.assert_array_equal(sent[0, 1], [5, 8])
    np.testing.assert_allclose(sc, scores)


def test_machine_translation_trains_and_decodes():
    """Book-example contract: loss decreases; beam decode then yields
    finite, sorted scores and in-vocab tokens."""
    V, Ts = 20, 5
    feeds, avg_cost = models.machine_translation.build_train_net(
        src_vocab=V, tgt_vocab=V, src_len=Ts, tgt_len=Ts,
        emb_dim=16, hidden_dim=16)
    pt.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = models.machine_translation.make_copy_task_batch(8, Ts, V)
    losses = []
    for _ in range(8):
        out, = exe.run(pt.default_main_program(), feed=feed,
                       fetch_list=[avg_cost])
        losses.append(float(out))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

    # decode program shares the trained parameters through the scope
    decode_prog, startup2 = pt.Program(), pt.Program()
    with pt.program_guard(decode_prog, startup2):
        dfeeds, sent, sent_scores = \
            models.machine_translation.build_decode_net(
                src_vocab=V, tgt_vocab=V, src_len=Ts, beam_size=3,
                max_len=6, emb_dim=16, hidden_dim=16)
    ids, scores = exe.run(decode_prog, feed={"src": feed["src"]},
                          fetch_list=[sent, sent_scores])
    B = feed["src"].shape[0]
    assert ids.shape == (B, 3, 6)
    assert scores.shape == (B, 3)
    assert np.isfinite(scores).all()
    assert (ids >= 0).all() and (ids < V).all()
    # beams are returned best-first
    assert (np.diff(scores, axis=1) <= 1e-5).all()


def test_machine_translation_with_wmt14_reader():
    """The reference book flow end-to-end with the dataset module:
    wmt14 reader -> padded batches -> train -> beam decode (ref
    tests/book/test_machine_translation.py trains from
    paddle.dataset.wmt14)."""
    import itertools

    from paddle_tpu import dataset

    V, Ts, Tt, B = 100, 8, 9, 16
    samples = list(itertools.islice(dataset.wmt14.train(V)(), 64))

    def pad(seq, n, val=0):
        seq = list(seq)[:n]
        return seq + [val] * (n - len(seq))

    src = np.array([pad(s, Ts) for s, t, tn in samples], "int64")
    trg = np.array([pad(t, Tt) for s, t, tn in samples], "int64")
    lbl = np.array([pad(tn, Tt) for s, t, tn in samples], "int64")

    feeds, avg_cost = models.machine_translation.build_train_net(
        src_vocab=V, tgt_vocab=V, src_len=Ts, tgt_len=Tt,
        emb_dim=16, hidden_dim=32)
    pt.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for epoch in range(6):
        for i in range(0, len(samples), B):
            out, = exe.run(pt.default_main_program(),
                           feed={"src": src[i:i + B], "tgt": trg[i:i + B],
                                 "lbl": lbl[i:i + B]},
                           fetch_list=[avg_cost])
            losses.append(float(np.asarray(out).ravel()[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])

    decode_prog = pt.Program()
    with pt.program_guard(decode_prog, pt.Program()):
        dfeeds, sent, scores = models.machine_translation.build_decode_net(
            src_vocab=V, tgt_vocab=V, src_len=Ts, beam_size=3,
            max_len=Tt, emb_dim=16, hidden_dim=32)
    ids, sc = exe.run(decode_prog, feed={"src": src[:4]},
                      fetch_list=[sent, scores])
    assert np.asarray(ids).shape == (4, 3, Tt)
    assert np.isfinite(np.asarray(sc)).all()
    assert (np.asarray(ids) < V).all() and (np.asarray(ids) >= 0).all()
