"""C inference ABI: build libpaddle_tpu_capi.so + the pure-C++ demo, save
a model from Python, serve it from the C++ process (ref
inference/api/paddle_api.h:134 PaddlePredictor ABI; test pattern:
inference/tests/book C++ round trips)."""
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import book

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "paddle_tpu", "fast", "predictor_demo")


def _build():
    r = subprocess.run(["make", "capi", "demo"],
                       cwd=os.path.join(REPO, "native"),
                       capture_output=True, text=True)
    return r.returncode == 0, r.stderr


@pytest.mark.skipif(shutil.which("g++") is None
                    or shutil.which("python3-config") is None,
                    reason="native toolchain unavailable")
def test_c_abi_serves_saved_model(tmp_path):
    ok, err = _build()
    assert ok, f"native build failed:\n{err}"

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        feeds, loss, pred = book.fit_a_line(x_dim=13)
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    x = np.random.RandomState(0).randn(4, 13).astype("f4")
    exe.run(main, feed={"x": x, "y": np.zeros((4, 1), "f4")},
            fetch_list=[loss])
    model_dir = str(tmp_path / "model")
    pt.io.save_inference_model(model_dir, ["x"], [pred], exe,
                               main_program=main)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)
    site_pkgs = next(p for p in sys.path if p.endswith("site-packages"))
    r = subprocess.run([DEMO, model_dir, f"{site_pkgs}:{REPO}", "x", "13"],
                       capture_output=True, text=True, env=env, cwd=REPO,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "C-ABI OK: 1 outputs" in r.stdout
    assert "shape=[2,1]" in r.stdout


TRAIN_DEMO = os.path.join(REPO, "paddle_tpu", "fast", "train_demo")


@pytest.mark.skipif(shutil.which("g++") is None
                    or shutil.which("python3-config") is None,
                    reason="native toolchain unavailable")
def test_c_abi_trains_saved_program(tmp_path):
    """Pure-C++ TRAINING through the C ABI (the reference's
    train/demo/demo_trainer.cc capability): save the fit_a_line TRAIN
    program pair, the C++ demo loads it, steps 10 times, and its loss
    decreases."""
    r = subprocess.run(["make", "capi", "traindemo"],
                       cwd=os.path.join(REPO, "native"),
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]

    pt.reset_default_programs()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        feeds, loss, pred = book.fit_a_line(x_dim=13)
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    model_dir = str(tmp_path / "train_model")
    pt.io.save_train_program(model_dir, main_program=main,
                             startup_program=startup)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)
    site_pkgs = next(p for p in sys.path if p.endswith("site-packages"))
    r = subprocess.run([TRAIN_DEMO, model_dir, f"{site_pkgs}:{REPO}"],
                       capture_output=True, text=True, env=env, cwd=REPO,
                       timeout=600)
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-2000:])
    assert "TRAIN_DEMO_OK" in r.stdout
    lines = [l for l in r.stdout.splitlines() if l.startswith("step:")]
    assert len(lines) == 10
    losses = [float(l.split("loss:")[1]) for l in lines]
    assert losses[-1] < losses[0]
