"""RecordIO format: python/native interop, crash tolerance, async loader
(ref test tiers: recordio C++ tests + reader op tests)."""
import os

import numpy as np
import pytest

from paddle_tpu import fast, recordio


def _records(n, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.bytes(rng.randint(1, 2000)) for _ in range(n)]


def test_python_roundtrip(tmp_path):
    path = str(tmp_path / "a.rio")
    recs = _records(500)
    recordio.write_records(path, recs)
    assert list(recordio.scan(path)) == recs


def test_crash_tolerant_scan(tmp_path):
    path = str(tmp_path / "b.rio")
    recs = _records(100)
    with recordio.RecordIOWriter(path, max_chunk_records=20) as w:
        for r in recs:
            w.write(r)
    size = os.path.getsize(path)
    # truncate mid-file: earlier full chunks must still scan
    with open(path, "r+b") as f:
        f.truncate(size - 37)
    got = list(recordio.scan(path))
    assert 0 < len(got) <= len(recs)
    assert got == recs[:len(got)]


@pytest.mark.skipif(not fast.available(), reason="native lib not built")
def test_native_python_interop(tmp_path):
    p1 = str(tmp_path / "n.rio")
    p2 = str(tmp_path / "p.rio")
    recs = _records(300, seed=1)
    # native write -> python scan
    with fast.NativeRecordIOWriter(p1) as w:
        for r in recs:
            w.write(r)
    assert list(recordio.scan(p1)) == recs
    # python write -> native scan
    recordio.write_records(p2, recs)
    assert list(fast.native_scan(p2)) == recs


@pytest.mark.skipif(not fast.available(), reason="native lib not built")
def test_async_loader_reads_all_shards(tmp_path):
    shards = []
    all_recs = set()
    for i in range(4):
        p = str(tmp_path / f"shard{i}.rio")
        recs = [bytes([i]) + r for r in _records(200, seed=i)]
        recordio.write_records(p, recs)
        shards.append(p)
        all_recs.update(recs)
    with fast.AsyncDataLoader(shards, num_threads=3,
                              queue_capacity=64) as dl:
        got = set(dl)
    assert got == all_recs


@pytest.mark.skipif(not fast.available(), reason="native lib not built")
def test_async_loader_large_records(tmp_path):
    p = str(tmp_path / "big.rio")
    recs = [os.urandom(3 << 20)]  # bigger than the 1MB initial buffer
    recordio.write_records(p, recs)
    with fast.AsyncDataLoader([p], num_threads=1) as dl:
        got = list(dl)
    assert got == recs
