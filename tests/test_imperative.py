"""Imperative (dygraph) mode: eager ops, tape autograd, Layer/PyLayer —
eager-vs-graph parity in the reference's test_imperative.py pattern."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import imperative
from paddle_tpu.imperative import FC, Layer, PyLayer, to_variable, trace_op


def test_guard_and_eager_ops():
    assert not imperative.enabled()
    with imperative.guard():
        assert imperative.enabled()
        x = to_variable(np.array([[1.0, -2.0]], "f4"))
        y = trace_op("relu", {"X": [x]}, {})[0]
        assert y.numpy().tolist() == [[1.0, 0.0]]
    assert not imperative.enabled()


def test_backward_matches_analytic():
    with imperative.guard():
        x = to_variable(np.array([2.0, 3.0], "f4"))
        y = x * x            # d/dx = 2x
        s = trace_op("reduce_sum", {"X": [y]}, {"dim": [0]})[0]
        s.backward()
        assert np.allclose(x.grad, [4.0, 6.0])


def test_stop_gradient_respected():
    with imperative.guard():
        x = to_variable(np.ones(3, "f4"), stop_gradient=True)
        w = to_variable(np.full(3, 2.0, "f4"))
        out = trace_op("reduce_sum",
                       {"X": [x * w]}, {"dim": [0]})[0]
        out.backward()
        assert x.grad is None
        assert np.allclose(w.grad, [1.0, 1.0, 1.0])


def test_fc_layer_trains_eagerly():
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 4).astype("f4")
    true_w = np.array([[1.0], [-2.0], [0.5], [3.0]], "f4")
    ys = xs @ true_w
    with imperative.guard():
        fc = FC(1)
        losses = []
        for _ in range(30):
            pred = fc(xs)
            err = pred - to_variable(ys, stop_gradient=True)
            sq = err * err
            loss = trace_op("reduce_mean", {"X": [sq]}, {"dim": [0, 1]})[0]
            for p in fc.parameters():
                p.clear_gradient()
            loss.backward()
            for p in fc.parameters():
                p.value = p.value - 0.1 * p.grad
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.1


def test_eager_graph_parity():
    """Same MLP, same init: imperative loss == Program/Executor loss."""
    rng = np.random.RandomState(1)
    x = rng.randn(4, 3).astype("f4")
    w = rng.randn(3, 2).astype("f4")
    # graph mode
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        xv = pt.layers.data("x", [3])
        wv = pt.layers.data("w", [3, 2], append_batch_size=False)
        out = pt.layers.matmul(xv, wv)
        loss = pt.layers.reduce_mean(pt.layers.tanh(out))
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    g, = exe.run(main, feed={"x": x, "w": w}, fetch_list=[loss])
    # eager mode
    with imperative.guard():
        xe = to_variable(x, stop_gradient=True)
        we = to_variable(w)
        oe = trace_op("matmul", {"X": [xe], "Y": [we]}, {})[0]
        te = trace_op("tanh", {"X": [oe]}, {})[0]
        le = trace_op("reduce_mean", {"X": [te]}, {"dim": [0, 1]})[0]
    assert np.allclose(float(g), float(le.numpy()), atol=1e-6)


def test_pylayer_custom_forward():
    class Square(PyLayer):
        def forward(self, x):
            return x * x

    with imperative.guard():
        sq = Square()
        out = sq(np.array([3.0], "f4"))
        s = trace_op("reduce_sum", {"X": [out]}, {"dim": [0]})[0]
        s.backward()
    assert np.allclose(out.numpy(), [9.0])


def test_dropout_backward_replays_same_mask():
    with imperative.guard():
        x = to_variable(np.ones((4, 64), "f4"))
        d = trace_op("dropout", {"X": [x]},
                     {"dropout_prob": 0.5}, out_slots=["Out"])[0]
        s = trace_op("reduce_sum", {"X": [d]}, {"dim": [0, 1]})[0]
        s.backward()
        # grad is the same mask the forward drew (scaled), so grad != 0
        # exactly where the output was kept
        kept = np.asarray(d.numpy()) != 0
        grad_nonzero = np.asarray(x.grad) != 0
        assert (kept == grad_nonzero).all()
