"""Real quantized execution (ISSUE 6 tentpole a + satellites).

STE gradient round-trips for every fake_quantize_* variant, the
freeze_program rewrite to genuine int8/fp8 programs (including the
never-trained rejection), the quantize_dtype training path and its
fake-quant numerical equivalence, the executor compile-key wiring, and
the bench_gate --smoke lane.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.framework.registry import LowerContext, get_op_def
from paddle_tpu.observability import bench_gate
from paddle_tpu.transpiler import QuantizeTranspiler


def _lower(op_type, ins, attrs):
    ctx = LowerContext(jax.random.PRNGKey(0))
    return get_op_def(op_type).lower(ctx, ins, attrs)


# --- satellite: STE gradient round-trips for the fake quant ops ----------

def _np_quant(x, scale, qmax=127.0):
    return np.clip(np.round(x / scale * qmax), -qmax, qmax) * scale / qmax


def test_fake_quantize_abs_max_ste_roundtrip():
    """Forward quantizes onto the int8 grid; backward passes the
    cotangent through unchanged (exactly 1.0 for every non-argmax
    entry — the scale depends only on the absmax element)."""
    x = jnp.asarray([[0.31, -0.77], [0.505, -1.9]], jnp.float32)

    def f(xv):
        return _lower("fake_quantize_abs_max", {"X": [xv]},
                      {"bit_length": 8})["Out"][0]

    out = np.asarray(f(x))
    np.testing.assert_allclose(out, _np_quant(np.asarray(x), 1.9),
                               atol=1e-6)
    g = np.asarray(jax.grad(lambda xv: jnp.sum(f(xv)))(x))
    mask = np.ones_like(g, bool)
    mask[1, 1] = False          # the absmax entry carries scale grads
    np.testing.assert_allclose(g[mask], 1.0, atol=1e-5)


def test_fake_quantize_moving_average_ste_roundtrip():
    x = jnp.asarray([[0.4, -0.2, 0.9, -0.55]], jnp.float32)
    in_scale = jnp.asarray(0.8, jnp.float32)
    attrs = {"bit_length": 8, "moving_rate": 0.9, "is_test": False}

    def f(xv):
        return _lower("fake_quantize_moving_average_abs_max",
                      {"X": [xv], "InScale": [in_scale]}, attrs)["Out"][0]

    scale = 0.9 * 0.8 + 0.1 * 0.9
    np.testing.assert_allclose(np.asarray(f(x)),
                               _np_quant(np.asarray(x), scale), atol=1e-6)
    g = np.asarray(jax.grad(lambda xv: jnp.sum(f(xv)))(x))
    mask = np.ones_like(g, bool)
    mask[0, 2] = False          # absmax entry
    np.testing.assert_allclose(g[mask], 1.0, atol=1e-5)
    # is_test freezes the scale: gradient is identity EVERYWHERE and
    # the forward uses in_scale alone
    attrs_t = dict(attrs, is_test=True)

    def ft(xv):
        return _lower("fake_quantize_moving_average_abs_max",
                      {"X": [xv], "InScale": [in_scale]},
                      attrs_t)["Out"][0]

    np.testing.assert_allclose(np.asarray(ft(x)),
                               _np_quant(np.asarray(x), 0.8), atol=1e-6)
    gt = np.asarray(jax.grad(lambda xv: jnp.sum(ft(xv)))(x))
    # clipped entries (|x| > in_scale) have zero STE gradient
    expect = (np.abs(np.asarray(x)) <= 0.8).astype("f4")
    np.testing.assert_allclose(gt, expect, atol=1e-5)


@pytest.mark.parametrize("axis", [0, 1])
def test_fake_channel_wise_quantize_ste_roundtrip(axis):
    """Per-channel variant, checked per axis: each channel quantizes
    against its OWN absmax, and gradients are 1.0 for every entry that
    is not its channel's absmax."""
    x = jnp.asarray([[0.5, -2.0, 0.25], [-1.0, 0.4, 0.75]], jnp.float32)

    def f(xv):
        return _lower("fake_channel_wise_quantize_abs_max", {"X": [xv]},
                      {"bit_length": 8, "quant_axis": axis})["Out"][0]

    xn = np.asarray(x)
    scales = np.abs(xn).max(axis=1 - axis, keepdims=True)
    np.testing.assert_allclose(np.asarray(f(x)), _np_quant(xn, scales),
                               atol=1e-6)
    out_scale = np.asarray(
        _lower("fake_channel_wise_quantize_abs_max", {"X": [x]},
               {"bit_length": 8, "quant_axis": axis})["OutScale"][0])
    np.testing.assert_allclose(out_scale, scales.reshape(-1), atol=1e-6)
    g = np.asarray(jax.grad(lambda xv: jnp.sum(f(xv)))(x))
    mask = np.abs(xn) != scales     # non-argmax entries per channel
    np.testing.assert_allclose(g[mask], 1.0, atol=1e-5)


# --- tentpole: freeze_program emits real int8 ----------------------------

def _qat_net():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
    return main, startup, pred, loss


def _reg_feed():
    rng = np.random.RandomState(0)
    w = rng.randn(16, 1).astype("float32")
    feed = {"x": rng.randn(64, 16).astype("float32")}
    feed["y"] = feed["x"] @ w
    return feed


def test_freeze_program_rejects_untrained_scales():
    """Satellite regression: freezing a moving-average QAT program whose
    scales were never trained must raise a clear error instead of
    silently folding garbage scales."""
    main, startup, pred, loss = _qat_net()
    qt = QuantizeTranspiler(
        activation_quantize_type="moving_average_abs_max")
    qt.training_transpile(main, startup)
    exe = pt.Executor(pt.CPUPlace())
    # startup never ran at all: weights/scales missing from the scope
    with pytest.raises(Exception, match="no recorded value"):
        qt.freeze_program(main.clone(for_test=True), scope=exe.scope)
    # startup ran but training never did: scale still at the 1.0 init
    exe.run(startup)
    with pytest.raises(Exception, match="never trained"):
        qt.freeze_program(main.clone(for_test=True), scope=exe.scope)


def test_qat_scale_state_shared_with_test_clone():
    """Regression (found by the e2e drive): transpiling the train
    program and its for_test clone SEPARATELY must reuse the same
    moving-average scale vars — deterministic names, no unique suffix —
    so scales trained through one program are seen by the other and
    the test clone can be frozen."""
    main, startup, pred, loss = _qat_net()
    test_prog = main.clone(for_test=True)
    qt = QuantizeTranspiler(
        activation_quantize_type="moving_average_abs_max")
    qt.training_transpile(main, startup)
    qt.training_transpile(test_prog, startup)

    def scale_vars(p):
        return sorted(v.name for v in p.list_vars()
                      if v.persistable and "quant_in_scale" in v.name)

    assert scale_vars(main) == scale_vars(test_prog)
    with pt.program_guard(main, startup):
        pt.optimizer.SGD(0.05).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    feed = _reg_feed()
    for _ in range(4):
        exe.run(main, feed=feed, fetch_list=[loss])
    # the scales trained via `main` unlock freezing the TEST clone
    frozen = qt.freeze_program(test_prog, scope=exe.scope,
                               quantize_dtype="int8")
    kinds = {op.type for op in frozen.global_block().ops}
    assert "quantized_matmul" in kinds
    ref, = exe.run(test_prog, feed=feed, fetch_list=[pred])
    got, = exe.run(frozen, feed=feed, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=0.1, atol=0.1)


def test_freeze_program_emits_real_int8():
    main, startup, pred, loss = _qat_net()
    qt = QuantizeTranspiler(
        activation_quantize_type="moving_average_abs_max")
    qt.training_transpile(main, startup)
    infer = main.clone(for_test=True)
    with pt.program_guard(main, startup):
        pt.optimizer.SGD(0.05).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    feed = _reg_feed()
    for _ in range(8):
        exe.run(main, feed=feed, fetch_list=[loss])
    ref, = exe.run(infer, feed=feed, fetch_list=[pred.name])
    frozen = qt.freeze_program(infer, scope=exe.scope)
    kinds = [op.type for op in frozen.global_block().ops]
    assert kinds.count("quantized_matmul") == 2, kinds
    assert not any(k.startswith("fake_") for k in kinds), kinds
    # the folded weights are genuinely int8 in the scope, with
    # per-channel scale vectors beside them
    qnames = [op.inputs["W"][0] for op in frozen.global_block().ops
              if op.type == "quantized_matmul"]
    for qn in qnames:
        assert exe.scope.find_var(qn).dtype == jnp.int8
    w_scale = exe.scope.find_var(
        [op.inputs["WScale"][0] for op in frozen.global_block().ops
         if op.type == "quantized_matmul"][0])
    assert w_scale.shape == (16,)   # quant_axis 1 of the [16, 16] fc
    got, = exe.run(frozen, feed=feed, fetch_list=[pred.name])
    # int8 with the trained scales reproduces the fake-quant reference
    tol = 0.02 * max(1.0, float(np.max(np.abs(ref))))
    assert float(np.max(np.abs(got - ref))) <= tol


def test_freeze_program_fp8_path():
    main, startup, pred, loss = _qat_net()
    qt = QuantizeTranspiler(
        activation_quantize_type="moving_average_abs_max")
    qt.training_transpile(main, startup)
    with pt.program_guard(main, startup):
        pt.optimizer.SGD(0.05).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    feed = _reg_feed()
    for _ in range(6):
        exe.run(main, feed=feed, fetch_list=[loss])
    infer = main.clone(for_test=True)
    ref, = exe.run(infer, feed=feed, fetch_list=[pred.name])
    frozen = qt.freeze_program(infer, scope=exe.scope,
                               quantize_dtype="e4m3")
    got, = exe.run(frozen, feed=feed, fetch_list=[pred.name])
    rel = float(np.max(np.abs(got - ref)) / max(1.0, np.max(np.abs(ref))))
    assert np.isfinite(got).all()
    assert rel < 0.15, rel          # e4m3 has a ~2^-3 mantissa


def test_quantized_conv2d_matches_f32_conv():
    from paddle_tpu.ops.quantize_ops import channel_scales, quantize_array
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 8, 8).astype("float32")
    w = rng.randn(4, 3, 3, 3).astype("float32") * 0.2
    scales = channel_scales(w, 0)
    wq = quantize_array(jnp.asarray(w),
                        jnp.asarray(scales).reshape(-1, 1, 1, 1), "int8")
    out = _lower("quantized_conv2d",
                 {"Input": [jnp.asarray(x)], "Filter": [wq],
                  "FilterScale": [jnp.asarray(scales)]},
                 {"quantize_dtype": "int8", "strides": [1, 1],
                  "paddings": [1, 1], "dilations": [1, 1],
                  "groups": 1})["Output"][0]
    ref = jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    err = float(jnp.max(jnp.abs(out - ref)))
    assert err < 0.05 * float(jnp.max(jnp.abs(ref))), err


# --- tentpole: quantize_dtype training path ------------------------------

def test_low_precision_matmul_matches_fake_quant_composition():
    """Acceptance: the real int8 forward equals the fake-quant
    simulation of the same matmul (per-tensor activation, per-channel
    weight) up to f32 rounding — same grid, same scales, the contraction
    just actually runs in int8."""
    from paddle_tpu.ops.quantize_ops import low_precision_matmul
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 16).astype("float32"))
    w = jnp.asarray(rng.randn(16, 4).astype("float32"))
    real = low_precision_matmul(x, w, "int8", jnp.float32)
    x_fake = _lower("fake_quantize_abs_max", {"X": [x]},
                    {"bit_length": 8})["Out"][0]
    w_fake = _lower("fake_channel_wise_quantize_abs_max", {"X": [w]},
                    {"bit_length": 8, "quant_axis": 1})["Out"][0]
    fake = jnp.matmul(x_fake, w_fake)
    np.testing.assert_allclose(np.asarray(real), np.asarray(fake),
                               rtol=1e-5, atol=1e-5)


def test_quantize_dtype_flag_trains_and_keys_compiles():
    """int8 execution during training: loss decreases under STE
    gradients, and flipping quantize_dtype compiles a FRESH executable
    (flags are part of the jit cache key, so dtype churn is 'flags'
    drift — not an aliased executable, not a storm)."""
    x = layers.data("x", [16], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(layers.fc(x, size=16, act="relu"), size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(0.05).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = _reg_feed()
    prog = pt.default_main_program()
    base, = exe.run(prog, feed=feed, fetch_list=[loss])
    n_before = len(exe._cache)
    old = flags.get_flag("quantize_dtype")
    flags.set_flag("quantize_dtype", "int8")
    try:
        losses = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
                  for _ in range(15)]
    finally:
        flags.set_flag("quantize_dtype", old)
    assert len(exe._cache) == n_before + 1   # fresh executable, cached
    assert losses[-1] < losses[0] * 0.5
    assert np.isfinite(losses).all()


@pytest.mark.parametrize("qd", ["e4m3", "e5m2"])
def test_fp8_training_matmul_runs(qd):
    from paddle_tpu.ops.quantize_ops import low_precision_matmul
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 8).astype("float32"))
    w = jnp.asarray(rng.randn(8, 4).astype("float32"))
    out = low_precision_matmul(x, w, qd, jnp.float32)
    ref = jnp.matmul(x, w)
    assert np.isfinite(np.asarray(out)).all()
    # fp8 is coarse; just bound the relative error
    rel = float(jnp.max(jnp.abs(out - ref))
                / jnp.maximum(jnp.max(jnp.abs(ref)), 1e-6))
    assert rel < (0.1 if qd == "e4m3" else 0.3)
    # STE backward: gradient of sum(x@w) wrt x is row-sums of w
    g = jax.grad(lambda a: jnp.sum(
        low_precision_matmul(a, w, qd, jnp.float32)))(x)
    np.testing.assert_allclose(np.asarray(g),
                               np.broadcast_to(np.asarray(w).sum(1),
                                               (8, 8)), rtol=1e-5)


def test_int8_lm_compiles_and_trains_on_cpu():
    """CPU-CI acceptance leg of the new bench row: a (tiny) transformer
    LM under quantize_dtype=int8 compiles and its loss stays finite and
    comparable to the fp32 run's."""
    from paddle_tpu import models
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=128, tgt_vocab_size=128, max_length=32,
        n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    feeds, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=16, fused_attention=False)
    pt.optimizer.SGD(0.1).minimize(avg_cost)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = models.transformer.make_fake_lm_batch(cfg, 2, 16)
    prog = pt.default_main_program()
    ref = float(exe.run(prog, feed=feed, fetch_list=[avg_cost])[0])
    old = flags.get_flag("quantize_dtype")
    flags.set_flag("quantize_dtype", "int8")
    try:
        got = float(exe.run(prog, feed=feed, fetch_list=[avg_cost])[0])
    finally:
        flags.set_flag("quantize_dtype", old)
    assert np.isfinite(got)
    assert abs(got - ref) < 0.25 * ref + 0.1


# --- satellite: the tier-1 perf-path smoke lane --------------------------

def test_bench_gate_smoke_mode():
    assert bench_gate.main(["--smoke"]) == 0
