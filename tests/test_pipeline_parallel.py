"""PipelineTranspiler: GPipe pipeline parallelism as a program
transformation — loss parity of the SAME Program trained on one device
vs pipelined over a mesh "pipe" axis, alone and composed with data
parallelism (dp x pp).  The 2018 reference has no pipeline parallelism
at all (SURVEY §2.2); the dp analogue lives in
tests/test_dist_transpiler.py, tp in test_tensor_parallel.py, cp in
test_context_parallel.py."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.core.place import make_mesh

V, T, D, B, L = 64, 16, 16, 8, 4


def build(pp_stages=1, seed=5):
    pt.reset_default_programs()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    main.random_seed = seed
    startup.random_seed = seed
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T, n_layer=L,
        n_head=2, d_model=D, d_inner=32, dropout=0.0)
    feeds, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=T, fused_attention=False, pp_stages=pp_stages)
    pt.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    return main, startup, avg_cost


def make_feed():
    rng = np.random.RandomState(0)
    toks = rng.randint(0, V, (B, T)).astype("int64")
    return {"tokens": toks, "labels": np.roll(toks, -1, 1)}


def _reference_losses(steps=4):
    feed = make_feed()
    main, startup, loss = build(pp_stages=1)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    out = []
    for _ in range(steps):
        l, = exe.run(main, feed=feed, fetch_list=[loss])
        out.append(float(np.asarray(l).ravel()[0]))
    return out


def test_markers_are_identity_untranspiled():
    """A pipeline-ready build (markers present) trains identically to
    the plain build when NOT transpiled."""
    feed = make_feed()
    main, startup, loss = build(pp_stages=4)
    ops = [op.type for op in main.global_block().ops]
    assert ops.count("pipeline_boundary") == 3
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    got = [float(np.asarray(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0]).ravel()[0])
           for _ in range(4)]
    np.testing.assert_allclose(got, _reference_losses(), rtol=1e-5)


def test_transpile_marks_and_validates():
    main, startup, loss = build(pp_stages=4)
    t = pt.transpiler.PipelineTranspiler()
    t.transpile(main, pp_degree=4, n_microbatches=4)
    assert main._dist_pp_axis == "pipe"
    assert main._pp_degree == 4 and main._pp_microbatches == 4
    ops = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in ops
    # markers survive serde
    rt = pt.Program.from_dict(main.to_dict())
    assert rt._dist_pp_axis == "pipe" and rt._pp_degree == 4
    # wrong marker count is rejected
    main2, _, _ = build(pp_stages=2)
    with pytest.raises(Exception, match="pipeline_boundary"):
        pt.transpiler.PipelineTranspiler().transpile(main2, pp_degree=4)


def test_pipeline_matches_single_device():
    """pp=4 over a 4-device "pipe" mesh: per-step losses match the
    un-transpiled single-device run."""
    feed = make_feed()
    ref = _reference_losses()
    main, startup, loss = build(pp_stages=4)
    t = pt.transpiler.PipelineTranspiler()
    t.transpile(main, pp_degree=4, n_microbatches=4)
    mesh = make_mesh((4,), ("pipe",))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(startup)
    got = []
    for _ in range(4):
        out, = exe.run(main, feed=feed, fetch_list=[loss])
        a = np.asarray(out)
        assert a.shape[0] == 4           # one (identical) copy per rank
        np.testing.assert_allclose(a, a[0], rtol=1e-6)
        got.append(float(np.mean(a)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)
    assert got[-1] < got[0]


def test_dp_x_pp_matches_single_device():
    """dp=2 x pp=4 over a (2, 4) mesh: PipelineTranspiler composed with
    DistributeTranspiler, global batch sharded over "data", stages over
    "pipe"."""
    feed = make_feed()
    ref = _reference_losses()
    main, startup, loss = build(pp_stages=4)
    pt.transpiler.PipelineTranspiler().transpile(
        main, pp_degree=4, n_microbatches=2)
    pt.transpiler.DistributeTranspiler().transpile(
        trainer_id=0, program=main, trainers=2, axis_name="data")
    mesh = make_mesh((2, 4), ("data", "pipe"))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(startup)
    got = []
    for _ in range(4):
        out, = exe.run(main, feed=feed, fetch_list=[loss])
        a = np.asarray(out)
        assert a.shape[0] == 2           # one fetch row per dp shard
        got.append(float(np.mean(a)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)
    assert got[-1] < got[0]


def test_pipeline_with_dropout_runs():
    """Dropout under the GPipe scan: per-tick RNG roots (each microbatch
    draws its own mask) — smoke: trains finite, loss moves."""
    pt.reset_default_programs()
    main = pt.default_main_program()
    main.random_seed = pt.default_startup_program().random_seed = 3
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T, n_layer=2,
        n_head=2, d_model=D, d_inner=32, dropout=0.2)
    feeds, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=T, fused_attention=False, pp_stages=2)
    pt.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    pt.transpiler.PipelineTranspiler().transpile(main, pp_degree=2,
                                                 n_microbatches=2)
    mesh = make_mesh((2,), ("pipe",))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(pt.default_startup_program())
    feed = make_feed()
    ls = [float(np.mean(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[avg_cost])[0])))
          for _ in range(3)]
    assert all(np.isfinite(ls)) and ls[-1] != ls[0]


def test_double_transpile_rejected():
    """Re-transpiling would stack duplicate gradient allreduces (P x
    grads, silently); both transpilers refuse."""
    main, startup, loss = build(pp_stages=4)
    pt.transpiler.PipelineTranspiler().transpile(main, pp_degree=4)
    with pytest.raises(Exception, match="already pipeline-transpiled"):
        pt.transpiler.PipelineTranspiler().transpile(main, pp_degree=4)
    pt.transpiler.DistributeTranspiler().transpile(
        trainer_id=0, program=main, trainers=2, axis_name="data")
    with pytest.raises(Exception, match="already carries collective"):
        pt.transpiler.DistributeTranspiler().transpile(
            trainer_id=0, program=main, trainers=2, axis_name="data")


def _build_pytree_net(pp=2, seed=9):
    """Two-stage MLP whose cut carries a PYTREE payload: (hidden,
    residual) — the residual branch re-joins after the boundary."""
    pt.reset_default_programs()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    main.random_seed = startup.random_seed = seed
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[D], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=D, act="relu")
        if pp > 1:
            h, res = layers.pipeline_boundary([h, x])
        else:
            res = x
        h2 = layers.fc(layers.elementwise_add(h, res), size=D,
                       act="relu")
        pred = layers.fc(h2, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def test_pytree_boundary_payload_parity():
    """A (hidden, residual) tuple rides the ppermute ring: pipelined
    losses match the single-device run step for step."""
    rng = np.random.RandomState(3)
    x = rng.randn(B, D).astype("f4")
    feed = {"x": x, "y": x.sum(-1, keepdims=True).astype("f4") * 0.1}

    main, startup, loss = _build_pytree_net(pp=1)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    ref = [float(np.asarray(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0]).ravel()[0])
           for _ in range(4)]

    main2, startup2, loss2 = _build_pytree_net(pp=2)
    pt.transpiler.PipelineTranspiler().transpile(
        main2, pp_degree=2, n_microbatches=4)
    mesh = make_mesh((2,), ("pipe",))
    exe2 = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe2.run(startup2)
    got = [float(np.asarray(exe2.run(main2, feed=feed,
                                     fetch_list=[loss2])[0]).ravel()[0])
           for _ in range(4)]
    np.testing.assert_allclose(got, ref, rtol=2e-4)


def test_pytree_boundary_mismatched_payloads_rejected():
    pt.reset_default_programs()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[D], dtype="float32")
        h = layers.fc(x, size=D)
        h, r = layers.pipeline_boundary([h, x])
        h2 = layers.fc(layers.elementwise_add(h, r), size=4)
        h2 = layers.pipeline_boundary(h2)      # different payload sig
        pred = layers.fc(h2, size=1)
        loss = layers.reduce_mean(layers.square(pred))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    with pytest.raises(Exception, match="share one tuple"):
        pt.transpiler.PipelineTranspiler().transpile(main, pp_degree=3)


def test_pp_fetch_of_stage_internal_rejected_up_front():
    """Fetching a stage-internal var under the pipeline plane raises a
    clear error instead of a KeyError deep inside tracing."""
    feed = make_feed()
    main, startup, loss = build(pp_stages=2)
    pt.transpiler.PipelineTranspiler().transpile(main, pp_degree=2)
    mesh = make_mesh((2,), ("pipe",))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(startup)
    internal = next(
        op.outputs["Out"][0] for op in main.global_block().ops
        if op.type == "pipeline_boundary")
    with pytest.raises(Exception, match="pipeline plane"):
        exe.run(main, feed=feed, fetch_list=[internal])


def test_1f1b_schedule_matches_gpipe_and_single_device():
    """1F1B (explicit per-tick backward, bounded boundary buffer) is
    the same computation as GPipe: losses match the single-device run
    step for step at equal microbatches."""
    feed = make_feed()
    ref = _reference_losses()

    main, startup, loss = build(pp_stages=4)
    pt.transpiler.PipelineTranspiler().transpile(
        main, pp_degree=4, n_microbatches=4, schedule="1f1b")
    assert main._pp_schedule == "1f1b"
    rt = pt.Program.from_dict(main.to_dict())
    assert rt._pp_schedule == "1f1b"          # survives serde
    mesh = make_mesh((4,), ("pipe",))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(startup)
    got = [float(np.asarray(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0]).ravel()[0])
           for _ in range(4)]
    np.testing.assert_allclose(got, ref, rtol=2e-4)


def test_1f1b_supports_dropout_deterministically():
    """Dropout inside a pipeline stage: the GPipe plane cannot
    differentiate through the stage switch with RNG ops in one branch
    (jax cond partial-eval limitation — branches get different
    known-residual sets), but 1F1B's backward is an explicit jax.vjp
    INSIDE each branch, so it works.  Two identical runs must produce
    identical (deterministic, per-microbatch-keyed) loss curves, and
    the loss must decrease."""
    rng = np.random.RandomState(7)
    x = rng.randn(B, D).astype("f4")
    feed = {"x": x, "y": x.sum(-1, keepdims=True).astype("f4") * 0.1}

    def build_do():
        pt.reset_default_programs()
        main, startup = (pt.default_main_program(),
                         pt.default_startup_program())
        main.random_seed = startup.random_seed = 13
        with pt.program_guard(main, startup):
            xv = layers.data("x", shape=[D], dtype="float32")
            yv = layers.data("y", shape=[1], dtype="float32")
            h = layers.fc(xv, size=D, act="relu")
            h = layers.dropout(h, dropout_prob=0.25)
            h, res = layers.pipeline_boundary([h, xv])
            h2 = layers.fc(layers.elementwise_add(h, res), size=D,
                           act="relu")
            pred = layers.fc(h2, size=1)
            loss = layers.reduce_mean(layers.square(pred - yv))
        pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
        pt.transpiler.PipelineTranspiler().transpile(
            main, pp_degree=2, n_microbatches=4, schedule="1f1b")
        return main, startup, loss

    runs = []
    for _ in range(2):
        main, startup, loss = build_do()
        mesh = make_mesh((2,), ("pipe",))
        exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
        exe.run(startup)
        runs.append([
            float(np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0]).ravel()[0])
            for _ in range(4)])
    np.testing.assert_array_equal(runs[0], runs[1])
    assert runs[0][-1] < runs[0][0]


def test_1f1b_more_microbatches_than_stages():
    """M > P exercises the steady-state interleave and the ring-buffer
    wraparound (BUF = 2P slots, M = 8 microbatches over 2 stages)."""
    feed = make_feed()
    ref = _reference_losses()
    main, startup, loss = build(pp_stages=2)
    pt.transpiler.PipelineTranspiler().transpile(
        main, pp_degree=2, n_microbatches=8, schedule="1f1b")
    mesh = make_mesh((2,), ("pipe",))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(startup)
    got = [float(np.asarray(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0]).ravel()[0])
           for _ in range(4)]
    np.testing.assert_allclose(got, ref, rtol=2e-4)


def test_dp_x_1f1b_matches_single_device():
    """dp=2 x pp=2 with the 1F1B schedule: the explicit-vjp grads flow
    through the same dp c_allreduce + pipe-allreduce rewrite chain."""
    feed = make_feed()
    ref = _reference_losses()
    main, startup, loss = build(pp_stages=2)
    pt.transpiler.PipelineTranspiler().transpile(
        main, pp_degree=2, n_microbatches=2, schedule="1f1b")
    pt.transpiler.DistributeTranspiler().transpile(
        trainer_id=0, program=main, trainers=2, axis_name="data")
    mesh = make_mesh((2, 2), ("data", "pipe"))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(startup)
    got = []
    for _ in range(4):
        out, = exe.run(main, feed=feed, fetch_list=[loss])
        got.append(float(np.mean(np.asarray(out))))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)


def test_1f1b_integer_payload_leaf():
    """An int leaf (token ids) riding the boundary: its cotangent is
    float0 and must not break the scan carry/ppermute plumbing."""
    pt.reset_default_programs()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    main.random_seed = startup.random_seed = 11
    with pt.program_guard(main, startup):
        toks = layers.data("toks", shape=[-1], dtype="int64")
        emb = layers.embedding(toks, size=[V, D])
        h = layers.fc(emb, size=D, act="relu", num_flatten_dims=2)
        h, toks2 = layers.pipeline_boundary([h, toks])
        emb2 = layers.embedding(toks2, size=[V, D],
                                param_attr=pt.ParamAttr(name="emb2"))
        h2 = layers.fc(layers.elementwise_add(h, emb2), size=D,
                       num_flatten_dims=2)
        loss = layers.reduce_mean(layers.square(h2))
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    pt.transpiler.PipelineTranspiler().transpile(
        main, pp_degree=2, n_microbatches=2, schedule="1f1b")
    mesh = make_mesh((2,), ("pipe",))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(startup)
    rng = np.random.RandomState(2)
    feed = {"toks": rng.randint(0, V, (B, T)).astype("int64")}
    seen = [float(np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0]).ravel()[0])
            for _ in range(3)]
    assert np.isfinite(seen).all() and seen[-1] < seen[0]


def test_1f1b_nonfinite_jacobian_at_zero_warmup():
    """A stage that opens with sqrt(payload): its Jacobian is inf at
    the zero warm-up buffer, so unmasked 0*inf seeds would poison every
    gradient with NaN — the validity mask on cotangents/grads must keep
    training finite."""
    pt.reset_default_programs()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    main.random_seed = startup.random_seed = 21
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[D], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.square(layers.fc(x, size=D))     # >= 0 payload
        h = layers.pipeline_boundary(h)
        h2 = layers.fc(layers.sqrt(h), size=D, act="relu")
        pred = layers.fc(h2, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
    pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    pt.transpiler.PipelineTranspiler().transpile(
        main, pp_degree=2, n_microbatches=2, schedule="1f1b")
    mesh = make_mesh((2,), ("pipe",))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(startup)
    rng = np.random.RandomState(4)
    x_np = rng.rand(B, D).astype("f4") + 0.5
    feed = {"x": x_np, "y": x_np.sum(-1, keepdims=True) * 0.1}
    seen = [float(np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0]).ravel()[0])
            for _ in range(4)]
    assert np.isfinite(seen).all(), seen
    assert seen[-1] < seen[0]
