"""Predictor, quantize/inference transpilers, task master
(ref test tiers: inference/tests/api analyzers, test_quantize_transpiler,
go master service tests)."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed import TaskMaster, TaskMasterClient, serve_master
from paddle_tpu.inference import AnalysisConfig, create_predictor
from paddle_tpu.transpiler import (DistributeTranspiler, InferenceTranspiler,
                                   QuantizeTranspiler, memory_optimize)


def _train_lenet_and_save(tmp_path, steps=2):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = layers.data("img", [1, 28, 28], dtype="float32")
        label = layers.data("label", [1], dtype="int64")
        from paddle_tpu.models.lenet import lenet
        pred = lenet(img)
        loss = layers.mean(layers.cross_entropy(pred, label))
        pt.optimizer.SGD(0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(4, 1, 28, 28).astype("float32"),
            "label": rng.randint(0, 10, (4, 1)).astype("int64")}
    for _ in range(steps):
        exe.run(main, feed=feed, fetch_list=[loss])
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["img"], [pred], exe, main_program=main)
    return d, feed, exe, main, pred


def test_predictor_end_to_end(tmp_path):
    d, feed, exe, main, pred = _train_lenet_and_save(tmp_path)
    cfg = AnalysisConfig(model_dir=d, use_tpu=False)
    p = create_predictor(cfg)
    assert p.get_input_names() == ["img"]
    p.prepare({"img": feed["img"]})           # AOT
    out, = p.run({"img": feed["img"]})
    assert out.shape == (4, 10)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)
    # parity with the test-mode clone of the training program (the saved
    # model is clone(for_test=True): BN uses global stats, dropout off)
    test_prog = main.clone(for_test=True).prune(["img"], [pred.name])
    ref, = exe.run(test_prog, feed={"img": feed["img"]},
                   fetch_list=[pred.name])
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    # clone shares compiled state
    p2 = p.clone()
    out2, = p2.run({"img": feed["img"]})
    np.testing.assert_allclose(out2, out)
    with pytest.raises(Exception):
        p.run({})


def test_inference_transpiler_folds_conv_bn(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = layers.data("img", [3, 16, 16], dtype="float32")
        conv = layers.conv2d(img, 8, 3, bias_attr=False)
        bn = layers.batch_norm(conv, is_test=True)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(2, 3, 16, 16).astype("float32")}
    before, = exe.run(main, feed=feed, fetch_list=[bn])

    test_prog = main.clone(for_test=True)
    n_ops_before = len(test_prog.global_block().ops)
    InferenceTranspiler().transpile(test_prog, scope=exe.scope)
    # conv+bn becomes conv+assign (the assign aliases the BN output name
    # so external fetches of either var keep working); BN math is gone
    assert len(test_prog.global_block().ops) <= n_ops_before
    assert not any(op.type == "batch_norm"
                   for op in test_prog.global_block().ops)
    after, = exe.run(test_prog, feed=feed, fetch_list=[bn.name])
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)
    # the pre-BN conv output name is still fetchable post-fold
    conv_out = [op for op in test_prog.global_block().ops
                if op.type == "conv2d"][0].outputs["Output"][0]
    via_conv, = exe.run(test_prog, feed=feed, fetch_list=[conv_out])
    np.testing.assert_allclose(via_conv, before, rtol=1e-4, atol=1e-5)


def test_quantize_transpiler_qat_trains():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
    QuantizeTranspiler().training_transpile(main, startup)
    quant_ops = [op.type for op in main.global_block().ops
                 if op.type.startswith("fake_")]
    assert len(quant_ops) >= 4   # act+weight per fc
    with pt.program_guard(main, startup):
        pt.optimizer.SGD(0.05).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    w = rng.randn(16, 1).astype("float32")
    feed = {"x": rng.randn(64, 16).astype("float32")}
    feed["y"] = feed["x"] @ w
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
              for _ in range(15)]
    assert losses[-1] < losses[0] * 0.5


def test_memory_optimize_api_parity():
    p = pt.Program()
    assert memory_optimize(p) is p


def test_distribute_transpiler_contract():
    t = DistributeTranspiler()
    prog = pt.Program()
    t.transpile(trainer_id=0, program=prog, trainers=2)
    assert t.get_trainer_program() is prog
    with pytest.raises(NotImplementedError):
        t.get_pserver_program("127.0.0.1:6174")


def test_task_master_lease_retry_snapshot(tmp_path):
    snap = str(tmp_path / "master.json")
    m = TaskMaster(snapshot_path=snap, lease_timeout=0.2)
    m.set_dataset([f"shard{i}" for i in range(6)], shards_per_task=2)
    srv, (host, port) = serve_master(m)
    try:
        c = TaskMasterClient(host, port)
        t1 = c.get_task()
        t2 = c.get_task()
        assert t1.task_id != t2.task_id
        c.task_finished(t1.task_id)
        c.task_failed(t2.task_id)          # requeued
        t2b = c.get_task()
        ids = {t2.task_id}
        # lease timeout requeues the un-acked task
        t3 = c.get_task()
        assert t3 is not None
        time.sleep(0.3)
        stats = c.stats()
        assert stats["todo"] >= 1          # t3 expired back to todo
        c.close()
    finally:
        srv.shutdown()

    # master restart recovers state from snapshot
    m2 = TaskMaster(snapshot_path=snap)
    s = m2.stats()
    assert s["todo"] + s["pending"] + s["done"] == 3


def test_task_master_epoch_rollover():
    m = TaskMaster()
    m.set_dataset(["a", "b"])
    t1, t2 = m.get_task(), m.get_task()
    m.task_finished(t1.task_id)
    m.task_finished(t2.task_id)
    t = m.get_task()
    assert t is not None and t.epoch == 1


def test_moving_average_scale_state_advances():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8], dtype="float32")
        h = layers.fc(x, size=4)
        loss = layers.mean(h)
    QuantizeTranspiler(
        activation_quantize_type="moving_average_abs_max"
    ).training_transpile(main, startup)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    scale_names = [v.name for v in main.list_vars()
                   if v.persistable and "in_scale" in v.name]
    assert scale_names
    rng = np.random.RandomState(0)
    feed = {"x": (rng.randn(32, 8) * 50).astype("float32")}
    exe.run(main, feed=feed, fetch_list=[loss])
    s1 = float(np.asarray(exe.scope.find_var(scale_names[0])))
    exe.run(main, feed=feed, fetch_list=[loss])
    s2 = float(np.asarray(exe.scope.find_var(scale_names[0])))
    assert s1 != 1.0, "scale must move after step 1"
    assert s2 != s1, "scale must keep moving"


def test_conv_bn_fold_skipped_when_conv_output_reused():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = layers.data("img", [3, 8, 8], dtype="float32")
        conv = layers.conv2d(img, 4, 3, padding=1, bias_attr=False)
        bn = layers.batch_norm(conv, is_test=True)
        both = layers.elementwise_add(bn, conv)   # skip reads pre-BN var
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    test_prog = main.clone(for_test=True)
    InferenceTranspiler().transpile(test_prog, scope=exe.scope)
    assert any(op.type == "batch_norm"
               for op in test_prog.global_block().ops)


def test_fake_quantize_range_abs_max_windowed():
    """ADVICE r2: the windowed (Iter/InScales) form must track the scale
    over the last window_size steps — a shrunk activation range drops the
    scale once the old max rotates out of the window."""
    import numpy as np
    from op_test import OpTest

    class _T(OpTest):
        op_type = "fake_quantize_range_abs_max"

        def setup(self):
            self.inputs = {
                "X": np.array([[0.5, -0.25]], "float32"),
                "Iter": np.array([3], "int64"),       # buffer already full
                "InScales": np.array([4.0, 2.0, 1.0], "float32"),
            }
            self.attrs = {"bit_length": 8, "window_size": 3}
            qmax = 127.0
            # slot 3 % 3 = 0 overwritten by cur=0.5 -> window [0.5, 2, 1]
            scale = 2.0
            x = self.inputs["X"]
            q = np.clip(np.round(x / scale * qmax), -qmax, qmax)
            self.outputs = {
                "Out": q * scale / qmax,
                "OutScale": np.array([scale], "float32"),
                "OutScales": np.array([0.5, 2.0, 1.0], "float32"),
                "OutIter": np.array([4], "float32"),
            }

    _T().check_output(atol=1e-6)
