"""Cross-process context-parallel worker: 2 localhost processes train
the SAME fused-attention LM Program with its SEQUENCE dim sharded over
the process mesh (ContextParallelTranspiler -> Executor(mesh)).  Feeds
are globalized along dim 1 (`_dist_feed_shard_dim`), and batch B=1 <
cp_degree=2 proves the feed is NOT batch-sharded (an uneven dim-0 shard
would be unbuildable).

Run:  python tests/dist_cp_worker.py <coordinator> <world> <rank> <out>
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np

SEED = 77
B, T, D, V, HEADS = 1, 32, 16, 64, 2


def build_program(pt, models):
    pt.reset_default_programs()
    main = pt.default_main_program()
    startup = pt.default_startup_program()
    main.random_seed = SEED
    startup.random_seed = SEED
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T, n_layer=1,
        n_head=HEADS, d_model=D, d_inner=32, dropout=0.0)
    _, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=T, fused_attention=True, fused_head=False)
    pt.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    return main, startup, avg_cost


def make_feed():
    rng = np.random.RandomState(5)
    toks = rng.randint(0, V, (B, T)).astype("int64")
    return {"tokens": toks, "labels": np.roll(toks, -1, 1)}


def train_steps(exe, prog, loss, steps=4):
    feed = make_feed()
    losses = []
    for _ in range(steps):
        out, = exe.run(prog, feed=feed, fetch_list=[loss])
        losses.append(float(np.mean(np.asarray(out))))
    return losses


def main():
    coordinator, world, rank, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.parallel import env as penv

    ok = penv.init_distributed_env(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
    assert ok, "init_distributed_env returned False"
    assert jax.process_count() == world

    main_p, startup, loss = build_program(pt, models)
    t = pt.transpiler.ContextParallelTranspiler()
    t.transpile(main_p, cp_degree=world)
    assert main_p._dist_feed_shard_dim == 1

    mesh = Mesh(np.array(jax.devices()[:world]), ("cp",))
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup)
    losses = train_steps(exe, main_p, loss)

    wname = main_p.all_parameters()[0].name
    w = exe.scope.find_var(wname)
    w_host = np.asarray(w.addressable_data(0))   # replicated param shard
    result = {"rank": rank, "losses": losses,
              "w_sum": float(np.abs(w_host).sum())}
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("WORKER_OK", rank)


if __name__ == "__main__":
    main()
