"""Armada router tests (ISSUE 20): routing, retry-elsewhere, per-
replica circuit breakers, drain propagation, Helmsman replica-scale
actions, and the 2-replica chaos-kill soak headline.

Unit tests drive the Router through its injectable seams (transport,
clock, sleep) — deterministic, no sockets, no real sleeps.  The soak
lanes stand up real supervised worker processes via ServingFleet.

NOTE the first test asserts the router module is NOT imported by plain
serving use — keep router/fleet_worker imports inside test bodies so
this file cannot break that invariant itself.
"""
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
import zlib

import pytest

from paddle_tpu import serving
from paddle_tpu.core import flags
from paddle_tpu.observability import alerts as obs_alerts
from paddle_tpu.observability import controller as ctrl_mod
from paddle_tpu.observability import journal as obs_journal
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import server as obs_server
from paddle_tpu.serving import loadgen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cval(name, **labels):
    m = obs_metrics.REGISTRY.get(name)
    if m is None:
        return 0.0
    return m.labels(**labels).value if labels else m.total()


def _router_events():
    return [e for e in obs_journal.tail(4000) if e["kind"] == "router"]


# --- router-off invariance (MUST run first in this module) -----------------

def test_router_off_is_invisible():
    """Flag-off idiom, router edition: a process that never configures
    a router keeps a byte-identical serving surface — the module is
    not even imported (so no router_* metric families exist and no
    route changes), get_router() resolves lazily to None, and
    /serving/generate handling is the seed single-replica path."""
    assert "paddle_tpu.serving.router" not in sys.modules
    assert serving.get_router() is None
    assert "router" not in serving.status_doc()
    for fam in ("router_requests_total", "router_dispatches_total",
                "router_retries_total", "router_breaker_state",
                "router_healthy_replicas", "router_request_seconds"):
        assert obs_metrics.REGISTRY.get(fam) is None, fam
    # no batcher AND no router: the exact seed answer
    srv = obs_server.ObservabilityServer.__new__(
        obs_server.ObservabilityServer)
    srv.aggregator = None
    code, doc = obs_server.ObservabilityServer.serving_generate(
        srv, {"prompt": [1, 2]})
    assert (code, doc["error"]) == (503, "no serving batcher attached")
    # and the lazy lookup itself did not import the module
    assert "paddle_tpu.serving.router" not in sys.modules


# --- fakes -----------------------------------------------------------------

class FakeSrv:
    """Scriptable replica endpoint for the transport seam."""

    def __init__(self):
        self.state = "running"       # healthz serving.state
        self.queue_depth = 0
        self.down = False            # unreachable (refused)
        self.exc = None              # exception CLASS raised on dispatch
        self.reply = None            # (code, doc) override for dispatch
        self.dispatches = []         # (body, headers) per generate POST
        self.drains = []             # bodies of /serving/drain POSTs

    def ok_doc(self):
        return {"status": "ok", "tokens": [7], "n_tokens": 1,
                "ttft_s": 0.01, "latency_s": 0.02}


class FakeTransport:
    def __init__(self, servers):
        self.servers = dict(servers)   # url -> FakeSrv

    def get_json(self, url, path, timeout):
        s = self.servers[url]
        if s.down:
            raise ConnectionError(f"{url} refused")
        assert path == "/healthz"
        code = 200 if s.state == "running" else 503
        return code, {"status": "ok" if code == 200 else s.state,
                      "serving": {"state": s.state,
                                  "queue_depth": s.queue_depth,
                                  "replica": None}}

    def post_json(self, url, path, body, timeout, headers=None):
        s = self.servers[url]
        if path == "/serving/drain":
            s.drains.append(dict(body))
            s.state = "draining"
            return 200, {"status": "draining"}
        if s.down:
            raise ConnectionError(f"{url} refused")
        s.dispatches.append((dict(body), dict(headers or {})))
        if s.exc is not None:
            raise s.exc(f"{url} dispatch failed")
        if s.reply is not None:
            code, doc = s.reply
            return code, dict(doc)
        return 200, s.ok_doc()


def _mk_router(n=2, clock=None, **kw):
    from paddle_tpu.serving.router import Router
    servers = {f"http://fake{i}": FakeSrv() for i in range(n)}
    defaults = dict(
        transport=FakeTransport(servers),
        retry_budget=4, probe_interval=0.05, breaker_threshold=3,
        breaker_reset_s=10.0, backoff_s=0.001, default_deadline_s=30.0)
    if clock is not None:
        defaults["now_fn"] = lambda: clock[0]
        defaults["sleep_fn"] = lambda s: None
    defaults.update(kw)
    r = Router([(str(i), f"http://fake{i}") for i in range(n)],
               **defaults)
    return r, [servers[f"http://fake{i}"] for i in range(n)]


# --- routing + retry-elsewhere ---------------------------------------------

def test_routes_by_health_and_load_and_balances_ties():
    router, (s0, s1) = _mk_router(2, clock=[1000.0])
    assert router.probe_all() == 2
    for _ in range(8):
        code, doc = router.handle({"prompt": [1, 2]})
        assert code == 200 and doc["status"] == "ok"
        assert doc["hops"] == 1
    # round-robin among load ties: both replicas served
    assert len(s0.dispatches) == 4 and len(s1.dispatches) == 4
    # load-aware: a deep queue on s1 steers traffic to s0
    s1.queue_depth = 50
    assert router.probe_all() == 2
    s0.dispatches.clear(), s1.dispatches.clear()
    for _ in range(4):
        router.handle({"prompt": [1]})
    assert len(s0.dispatches) == 4 and not s1.dispatches


def test_drained_replica_retried_elsewhere_without_breaker_strike():
    flags.set_flag("journal_path", "/tmp/_ptpu_router_j1.jsonl")
    clock = [1000.0]
    router, (s0, s1) = _mk_router(2, clock=clock)
    router.probe_all()
    s0.reply = (503, {"status": "drained", "error": "draining"})
    oks = 0
    for _ in range(6):
        code, doc = router.handle({"prompt": [1, 2]})
        assert code == 200, doc      # failover is invisible to clients
        oks += 1
        assert doc["replica"] == "1"
    assert oks == 6
    # the drained answer marked the replica draining (no more picks)
    assert router.status_doc()["replicas"][0]["state"] == "draining"
    # clean signal, not an error: breaker stays closed
    assert router.status_doc()["replicas"][0]["breaker"] == "closed"
    ev = _router_events()
    assert any(e["event"] == "route_away"
               and e.get("reason") == "drained" for e in ev)
    assert _cval("router_retries_total", reason="drained") >= 1


def test_connection_refused_retries_elsewhere_and_trips_breaker():
    clock = [1000.0]
    router, (s0, s1) = _mk_router(2, clock=clock)
    router.probe_all()
    s0.down = True
    for _ in range(8):
        code, doc = router.handle({"prompt": [3]})
        assert code == 200 and doc["replica"] == "1"
    rep0 = router.status_doc()["replicas"][0]
    assert rep0["breaker"] == "open"         # tripped after 3 strikes
    # open breaker = never picked: no more dispatch attempts at s0
    n = _cval("router_dispatches_total", replica="0")
    for _ in range(4):
        router.handle({"prompt": [3]})
    assert _cval("router_dispatches_total", replica="0") == n


def test_deadline_exceeded_is_explicit_504():
    clock = [1000.0]
    router, (s0, s1) = _mk_router(2, clock=clock)
    router.probe_all()

    class _Late(FakeTransport):
        def post_json(self, url, path, body, timeout, headers=None):
            clock[0] += 10.0                 # each hop eats the budget
            raise TimeoutError(f"{url} deadline")

    router.transport = _Late(router.transport.servers)
    code, doc = router.handle({"prompt": [1], "timeout_s": 15.0})
    assert code == 504 and doc["status"] == "timeout"
    # remaining budget rode to the replica: hop 1 carried <= 15s
    # (checked via the recorded fake clock: 2 hops max inside 15s)
    assert _cval("router_retries_total", reason="timeout") >= 1


def test_no_healthy_replica_is_explicit_503():
    router, (s0, s1) = _mk_router(2, clock=[1000.0])
    s0.state = s1.state = "draining"
    router.probe_all()
    code, doc = router.handle({"prompt": [1]})
    assert code == 503
    assert doc["status"] in ("drained", "error")
    assert _cval("router_requests_total", replica="none",
                 status=doc["status"]) >= 1


# --- circuit-breaker state-machine matrix ----------------------------------

def test_breaker_matrix_trip_open_halfopen_recover():
    """trip -> open -> half-open trial -> recover, single replica so
    the dispatch path (not the probe) drives every transition; fake
    clock + fake sleep = deterministic, no real waiting."""
    flags.set_flag("journal_path", "/tmp/_ptpu_router_j2.jsonl")
    clock = [1000.0]
    router, (s0,) = _mk_router(1, clock=clock, breaker_threshold=3,
                               breaker_reset_s=10.0, retry_budget=2)
    router.probe_all()
    s0.exc = ConnectionError

    def breaker():
        return router.status_doc()["replicas"][0]["breaker"]

    # closed -> 3 consecutive errors -> open (one handle: budget 2 =
    # 3 dispatches, all striking the only replica)
    code, doc = router.handle({"prompt": [1]})
    assert code == 503 and breaker() == "open"
    assert any(e["event"] == "breaker_open" for e in _router_events())
    # while open and inside the window: never dispatched, fast 503
    n = len(s0.dispatches)
    assert router.handle({"prompt": [1]})[0] == 503
    assert len(s0.dispatches) == n
    # past the window: half-open admits ONE trial; it fails -> re-open
    clock[0] += 10.5
    assert breaker() == "half_open"
    code, _ = router.handle({"prompt": [1]})
    assert code == 503
    assert breaker() == "open"               # re-armed, window reset
    assert len(s0.dispatches) == n + 1       # exactly one trial hop
    # past the NEXT window with a healed replica: trial succeeds,
    # breaker closes, traffic resumes
    clock[0] += 10.5
    s0.exc = None
    code, doc = router.handle({"prompt": [1]})
    assert code == 200 and breaker() == "closed"
    assert any(e["event"] == "breaker_close" for e in _router_events())
    assert _cval("router_breaker_state", replica="0") == 0.0


def test_probe_recovers_breaker_and_journals_resume():
    """The other recovery path: an open breaker on a revived replica
    is closed by the health probe (no client request risked), and the
    dead->ready transition journals as resume."""
    flags.set_flag("journal_path", "/tmp/_ptpu_router_j3.jsonl")
    clock = [1000.0]
    router, (s0, s1) = _mk_router(2, clock=clock, breaker_threshold=2,
                                  breaker_reset_s=5.0)
    router.probe_all()
    s0.down = True
    for _ in range(4):                       # strikes via dispatch
        router.handle({"prompt": [1]})
    assert router.status_doc()["replicas"][0]["breaker"] == "open"
    router.probe_all()                       # probe sees it dead too
    assert router.status_doc()["replicas"][0]["state"] == "dead"
    # replica comes back; window passes; the probe closes the breaker
    s0.down = False
    clock[0] += 6.0
    router.probe_all()
    rep0 = router.status_doc()["replicas"][0]
    assert rep0["state"] == "ready" and rep0["breaker"] == "closed"
    ev = _router_events()
    assert any(e["event"] == "resume" and e.get("replica") == "0"
               for e in ev)
    # and it takes traffic again
    s0.dispatches.clear()
    for _ in range(4):
        assert router.handle({"prompt": [1]})[0] == 200
    assert s0.dispatches


# --- drain semantics -------------------------------------------------------

def test_drain_replica_propagates_before_rpc_no_route_after_event():
    """Satellite: after drain_replica returns (and the drain journal
    event exists), NO dispatch ever starts against the draining
    replica — the mark is synchronous under the router lock."""
    flags.set_flag("journal_path", "/tmp/_ptpu_router_j4.jsonl")
    router, (s0, s1, s2) = _mk_router(3, clock=[1000.0])
    router.probe_all()
    rid = router.drain_replica("1", stop=False)
    assert rid == "1"
    assert s1.drains and s1.drains[0]["stop"] is False
    ev = _router_events()
    assert any(e["event"] == "drain" and e.get("replica") == "1"
               for e in ev)
    n1 = len(s1.dispatches)
    for _ in range(12):
        code, doc = router.handle({"prompt": [1]})
        assert code == 200 and doc["replica"] in ("0", "2")
    assert len(s1.dispatches) == n1          # zero post-drain routes
    # unnamed drain picks the least-loaded READY replica
    s0.queue_depth = 9
    router.probe_all()
    assert router.drain_replica() == "2"


def test_sigterm_drains_all_replicas_then_exits():
    """Router-wide SIGTERM semantics: the (async-signal-safe) drain
    flag is honored by the probe loop — every replica gets a stopping
    drain, in-flight finishes, running goes False, new requests get an
    explicit drained 503."""
    flags.set_flag("journal_path", "/tmp/_ptpu_router_j5.jsonl")
    router, srvs = _mk_router(3, probe_interval=0.02)
    router.probe_all()
    router.start()
    try:
        router.request_drain()               # what the handler does
        deadline = time.time() + 5
        while router.running and time.time() < deadline:
            time.sleep(0.02)
        assert not router.running
        for s in srvs:
            assert s.drains and s.drains[0]["stop"] is True
        ev = _router_events()
        assert any(e["event"] == "drain_begin" for e in ev)
        assert any(e["event"] == "drain_complete" for e in ev)
        code, doc = router.handle({"prompt": [1]})
        assert (code, doc["status"]) == (503, "drained")
    finally:
        router.stop()


# --- worker healthz satellite ----------------------------------------------

class _StubBatcher:
    def __init__(self):
        self.running, self.draining, self.queue_depth = True, False, 3

    def stop(self, timeout=10.0):
        pass


def test_healthz_reports_batcher_state(monkeypatch):
    """Satellite: GET /healthz on a serving worker is the one truth the
    router probes — running/draining state, queue depth, replica id;
    draining answers 503 (readiness semantics)."""
    monkeypatch.setenv("PTPU_REPLICA_ID", "4")
    stub = _StubBatcher()
    serving.attach(stub)
    srv = obs_server.start_http_server(port=0)
    doc = json.loads(urllib.request.urlopen(
        srv.url + "/healthz", timeout=5).read())
    assert doc["serving"] == {"state": "running", "queue_depth": 3,
                              "replica": "4"}
    assert doc["status"] == "ok"
    stub.draining = True
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(srv.url + "/healthz", timeout=5)
    assert ei.value.code == 503
    doc = json.loads(ei.value.read())
    assert doc["serving"]["state"] == "draining"
    assert doc["status"] == "draining"


# --- loadgen satellites ----------------------------------------------------

def test_loadgen_round_robin_per_target_and_retried_counts():
    calls = {"b": 0}

    def sub_a(p, m, t):
        return {"status": "ok", "n_tokens": 2}

    def sub_b(p, m, t):
        calls["b"] += 1
        if calls["b"] == 1:
            raise serving.ShedError("busy", 9)
        return {"status": "ok", "n_tokens": 2}

    submit = loadgen.round_robin_submit([("a", sub_a), ("b", sub_b)])
    rep = loadgen.run_loadgen(submit, streams=1, requests_per_stream=4,
                              max_new_tokens=2, max_attempts=5,
                              retry_sleep_s=0.0, p99_budget_ms=0.0)
    assert rep["accounted"]
    assert rep["counts"]["ok"] == 4
    assert rep["counts"]["shed"] == 1
    assert rep["counts"]["retried_ok"] == 1   # the shed one succeeded
    assert rep["per_target"]["a"]["ok"] >= 2  # on a retry elsewhere
    assert rep["per_target"]["b"]["shed"] == 1
    assert rep["per_target"]["a"]["ok"] \
        + rep["per_target"]["b"]["ok"] == 4


def test_loadgen_multi_url_builds_per_target_ledger():
    # the repeated --url CLI path builds this: one ledger row per
    # endpoint, round-robin — no sockets needed to assert the wiring
    submit = loadgen.http_submit_multi(
        ["http://h1:1", "http://h2:2/"])
    assert set(submit.per_target) == {"http://h1:1", "http://h2:2/"}
    assert all(v == {"ok": 0, "shed": 0, "error": 0}
               for v in submit.per_target.values())
    with pytest.raises(SystemExit):          # --url is still required
        loadgen._main([])


# --- Helmsman replica-scale actions ----------------------------------------

def test_parse_action_accepts_replica_kinds_rejects_resize_fields():
    act = obs_alerts.parse_action({"kind": "spawn_replica"},
                                  "r", "threshold")
    assert act == {"kind": "spawn_replica"}
    act = obs_alerts.parse_action(
        {"kind": "drain_replica", "cooldown": 5}, "r", "threshold")
    assert act["cooldown"] == 5.0
    with pytest.raises(obs_alerts.RuleError, match="direction"):
        obs_alerts.parse_action(
            {"kind": "spawn_replica", "direction": "grow"},
            "r", "threshold")


class _StubRouter:
    def __init__(self):
        self.drained = []

    def drain_replica(self, rid=None, stop=False):
        self.drained.append(rid)
        return "9"


def _replica_rule(kind, value=1.0):
    return obs_alerts.Rule(
        name=f"r_{kind}", metric="m", predicate="threshold", op=">",
        value=value, severity="critical", source="builtin",
        action=obs_alerts.parse_action({"kind": kind}, "t",
                                       "threshold"))


def test_controller_spawn_and_drain_replica_policy(tmp_path):
    """Acceptance: spawn_replica/drain_replica actuate through the
    SAME fenced single-flight policy layer — cooldown per class,
    grow/shrink hysteresis across the pair, decisions journaled as
    controller.decision."""
    flags.set_flag("journal_path", str(tmp_path / "j.jsonl"))
    flags.set_flag("controller", True)
    flags.set_flag("alert_rules_path", "builtin")
    stub = _StubRouter()
    spawned = []
    ctrl = ctrl_mod.wire_router(stub,
                                spawn_replica=lambda: spawned.append(1))
    assert ctrl is not None
    assert set(ctrl.actuators) >= {"spawn_replica", "drain_replica"}
    ent = {"rule": _replica_rule("spawn_replica"), "value": 10.0,
           "labels": {}, "context": {}}
    decs = ctrl.consider([ent], now=100.0)
    assert [d["outcome"] for d in decs] == ["applied"]
    assert decs[0]["action"] == "spawn_replica"
    assert decs[0]["direction"] == "grow"
    assert spawned == [1]
    # cooldown: a second spawn inside the class cooldown is skipped
    assert ctrl.consider([ent], now=101.0) == []
    # hysteresis: a drain (shrink) chasing the spawn (grow) inside the
    # reversal window is suppressed — no flap
    ent2 = {"rule": _replica_rule("drain_replica"), "value": 10.0,
            "labels": {}, "context": {}}
    assert ctrl.consider([ent2], now=102.0) == []
    assert stub.drained == []
    # past the window: the drain applies through the router actuator
    decs = ctrl.consider([ent2], now=200.0)
    assert [d["outcome"] for d in decs] == ["applied"]
    assert stub.drained == [None]
    # every decision journaled as controller.decision
    ev = [e for e in obs_journal.tail(500)
          if e["kind"] == "controller" and e["event"] == "decision"]
    assert {e["action"] for e in ev} >= {"spawn_replica",
                                         "drain_replica"}


# --- soak lanes ------------------------------------------------------------

def _seed_where_exit_fires(prob, lo, hi, site="serving.decode_step"):
    for seed in range(10_000):
        fires = [n for n in range(hi)
                 if zlib.crc32(f"{seed}:{site}:{n}".encode())
                 / 0xFFFFFFFF < prob]
        if fires and lo <= fires[0] < hi:
            return seed
    raise RuntimeError("no seed found")


def _mini_fleet(n, tmp_path, replica_envs=None, seed=7):
    from paddle_tpu.serving import fleet_worker
    return fleet_worker.ServingFleet(
        n, seed=seed,
        env=fleet_worker.default_worker_env({
            "PTPU_SERVING_WORKER_BUCKETS": "8",
            "PTPU_SERVING_WORKER_BATCH": "2",
            "PTPU_SERVING_WORKER_MAXLEN": "32"}),
        replica_envs=replica_envs, cwd=REPO, log_dir=str(tmp_path),
        router_kwargs=dict(probe_interval=0.25, breaker_threshold=2,
                           breaker_reset_s=0.5, retry_budget=4,
                           backoff_s=0.05, default_deadline_s=30.0))


@pytest.mark.chaos
def test_router_soak_2replica_chaos_kill_zero_lost(tmp_path):
    """Tier-1 headline (miniature lane): a 2-replica fleet under a
    closed-loop storm, replica 1 chaos-killed mid-decode.  Every
    request terminates exactly once (ledger clean, zero lost), retried
    requests succeed on the survivor, the supervisor revives the
    victim chaos-stripped on the same port, and the router journals
    route-away + resume and routes to it again."""
    flags.set_flag("journal_path", str(tmp_path / "journal.jsonl"))
    kseed = _seed_where_exit_fires(0.2, 5, 18)
    fleet = _mini_fleet(2, tmp_path, replica_envs={
        1: {"PTPU_CHAOS_SPEC": "serving.decode_step=exit:0.2:9",
            "PTPU_CHAOS_SEED": str(kseed)}})
    fleet.start()
    try:
        fleet.wait_ready(timeout=180)
        rep = loadgen.run_loadgen(
            loadgen.router_submit(fleet.router, timeout=30),
            streams=4, requests_per_stream=5, max_new_tokens=5,
            prompt_len_range=(3, 7), vocab_size=97,
            p99_budget_ms=0.0, max_attempts=400, retry_sleep_s=0.15)
        # zero lost: every issued attempt accounted, every request ok
        assert rep["accounted"], rep
        assert rep["counts"]["gave_up"] == 0, rep
        assert rep["counts"]["ok"] == 4 * 5, rep
        # the kill happened and the supervisor revived the victim
        assert fleet.supervisor.restarts[1] >= 1, \
            fleet.supervisor.status()
        # the router saw it and routed away (journal + retry counter)
        ev = _router_events()
        assert any(e["event"] == "route_away" for e in ev), ev[-20:]
        assert _cval("router_retries_total") >= 1
        # ...and resumes routing to the revived replica
        deadline = time.time() + 90
        while time.time() < deadline:
            if fleet.router.probe_all() >= 2:
                break
            time.sleep(0.3)
        doc = fleet.router.status_doc()
        assert doc["healthy"] == 2, doc
        assert any(e["event"] == "resume" and e.get("replica") == "1"
                   for e in _router_events())
        s1_before = _cval("router_dispatches_total", replica="1")
        for _ in range(4):
            code, d = fleet.router.handle(
                {"prompt": [5, 6, 7], "max_new_tokens": 3,
                 "timeout_s": 30})
            assert code == 200, d
        assert _cval("router_dispatches_total",
                     replica="1") > s1_before
    finally:
        fleet.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_router_storm_3replica_drain_mid_storm(tmp_path):
    """Slow lane: 3 replicas, one DRAINED (not killed) mid-storm —
    zero lost, p99 within budget, and no dispatch ever starts against
    the draining replica after drain_replica returns."""
    flags.set_flag("journal_path", str(tmp_path / "journal.jsonl"))
    fleet = _mini_fleet(3, tmp_path)
    fleet.start()
    try:
        fleet.wait_ready(timeout=240)
        report = {}
        submit = loadgen.router_submit(fleet.router, timeout=30)

        def _storm():
            report.update(loadgen.run_loadgen(
                submit, streams=6, requests_per_stream=6,
                max_new_tokens=6, prompt_len_range=(3, 7),
                vocab_size=97, p99_budget_ms=2000.0,
                max_attempts=200, retry_sleep_s=0.1))

        th = threading.Thread(target=_storm)
        th.start()
        time.sleep(1.0)                      # storm underway
        assert fleet.router.drain_replica("1", stop=False) == "1"
        disp_at_drain = _cval("router_dispatches_total", replica="1")
        th.join(timeout=240)
        assert not th.is_alive()
        assert report["accounted"], report
        assert report["counts"]["gave_up"] == 0, report
        assert report["counts"]["ok"] == 6 * 6, report
        assert report["budget_ok"], report
        # drain propagation at fleet scale: the counter froze the
        # moment drain_replica returned
        assert _cval("router_dispatches_total",
                     replica="1") == disp_at_drain
        ev = _router_events()
        assert any(e["event"] == "drain" and e.get("replica") == "1"
                   for e in ev)
        doc = fleet.router.status_doc()
        states = {r["replica"]: r["state"] for r in doc["replicas"]}
        assert states["1"] == "draining"
        assert states["0"] == states["2"] == "ready"
    finally:
        fleet.stop()
