"""Control-flow layer DSL: While / Switch / StaticRNN lowering to
lax.while_loop / lax.cond / lax.scan (ref python/paddle/fluid/layers/
control_flow.py:504,1139,278 and operators/controlflow/)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def test_while_loop_accumulates():
    """i = 0; acc = 0; while i < 5: acc += i; i += 1  ->  acc == 10."""
    i = layers.fill_constant([1], "float32", 0.0, name="i")
    n = layers.fill_constant([1], "float32", 5.0, name="n")
    acc = layers.fill_constant([1], "float32", 0.0, name="acc")
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        acc2 = layers.elementwise_add(acc, i)
        layers.assign(acc2, acc)
        i2 = layers.increment(i, value=1.0, in_place=False)
        layers.assign(i2, i)
        layers.less_than(i, n, cond=cond)
    exe = pt.Executor(pt.CPUPlace())
    out_acc, out_i = exe.run(pt.default_main_program(),
                             fetch_list=[acc, i])
    assert float(np.asarray(out_acc).ravel()[0]) == 10.0
    assert float(np.asarray(out_i).ravel()[0]) == 5.0


def test_switch_picks_branch():
    """Switch writes different lr values depending on a step counter."""
    step = layers.fill_constant([1], "float32", 7.0, name="step")
    thresh = layers.fill_constant([1], "float32", 5.0, name="thresh")
    lr = layers.fill_constant([1], "float32", 0.0, name="lr")
    with layers.Switch() as switch:
        with switch.case(layers.less_than(step, thresh)):
            v = layers.fill_constant([1], "float32", 0.1)
            layers.assign(v, lr)
        with switch.default():
            v = layers.fill_constant([1], "float32", 0.01)
            layers.assign(v, lr)
    exe = pt.Executor(pt.CPUPlace())
    out, = exe.run(pt.default_main_program(), fetch_list=[lr])
    assert abs(float(np.asarray(out).ravel()[0]) - 0.01) < 1e-7


def test_switch_first_case():
    step = layers.fill_constant([1], "float32", 2.0, name="step")
    thresh = layers.fill_constant([1], "float32", 5.0, name="thresh")
    lr = layers.fill_constant([1], "float32", 0.0, name="lr")
    with layers.Switch() as switch:
        with switch.case(layers.less_than(step, thresh)):
            v = layers.fill_constant([1], "float32", 0.1)
            layers.assign(v, lr)
        with switch.default():
            v = layers.fill_constant([1], "float32", 0.01)
            layers.assign(v, lr)
    exe = pt.Executor(pt.CPUPlace())
    out, = exe.run(pt.default_main_program(), fetch_list=[lr])
    assert abs(float(np.asarray(out).ravel()[0]) - 0.1) < 1e-7


def test_static_rnn_matches_numpy():
    """StaticRNN with h_new = tanh(x_t @ W + h_prev @ U) vs numpy."""
    B, T, D, H = 2, 4, 3, 3
    rng = np.random.RandomState(0)
    x_np = rng.randn(B, T, D).astype("float32") * 0.5
    h0_np = np.zeros((B, H), "float32")

    x = layers.data("x", [T, D], dtype="float32")
    h0 = layers.data("h0", [H], dtype="float32")
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h_prev = rnn.memory(init=h0)
        cat = layers.concat([x_t, h_prev], axis=1)
        h = layers.fc(cat, size=H, act="tanh", bias_attr=False)
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    w_name, = [n for n in exe.scope.var_names() if n.endswith(".w_0")]
    w = np.asarray(exe.scope.find_var(w_name))
    got, = exe.run(pt.default_main_program(),
                   feed={"x": x_np, "h0": h0_np}, fetch_list=[out])

    h = h0_np.astype("float64")
    expect = np.zeros((B, T, H))
    for t in range(T):
        h = np.tanh(np.concatenate([x_np[:, t], h], -1) @ w)
        expect[:, t] = h
    np.testing.assert_allclose(got, expect, atol=1e-5)
    assert got.shape == (B, T, H)


def test_while_inside_training_program():
    """A while loop can coexist with autodiff in one program (the loop here
    post-processes a trained value; the reference pattern is program-level
    mixing of control flow and backward ops)."""
    x = layers.data("x", [4], dtype="float32")
    w_out = layers.fc(x, size=1, bias_attr=False)
    loss = layers.mean(layers.square(w_out))
    opt = pt.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(3, 4).astype("float32")}
    l0, = exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
    l1, = exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
    assert float(l1) < float(l0)
