"""v2 mixed_layer/projection plane + recurrent-unit tier + breadth
tier 2 (ref trainer_config_helpers/layers.py:869 mixed_layer, :430
full_matrix_projection; networks.py:836 lstmemory_group, :940 gru_unit,
:547 vgg_16_network, :1498 dot_product_attention)."""
import numpy as np
import pytest

import paddle_tpu.v2 as paddle


# ---------------------------------------------------------------- mixed


def test_mixed_identity_projection_is_identity():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(6))
    m = paddle.layer.mixed(size=6,
                           input=[paddle.layer.identity_projection(x)])
    arr = np.arange(6, dtype="f4")
    out = paddle.infer(output_layer=m,
                       parameters=paddle.parameters.create(m),
                       input=[(arr,)])
    np.testing.assert_allclose(np.asarray(out)[0], arr)


def test_mixed_identity_offset_slices_columns():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(6))
    m = paddle.layer.mixed(
        size=2, input=[paddle.layer.identity_projection(x, offset=2,
                                                        size=2)])
    arr = np.arange(6, dtype="f4")
    out = paddle.infer(output_layer=m,
                       parameters=paddle.parameters.create(m),
                       input=[(arr,)])
    np.testing.assert_allclose(np.asarray(out)[0], arr[2:4])


def test_mixed_sums_projections_and_applies_bias_act():
    """two identity projections + bias + relu: out = relu(2x + b)."""
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    m = paddle.layer.mixed(
        size=4,
        input=[paddle.layer.identity_projection(x),
               paddle.layer.identity_projection(x)],
        bias_attr=True, act=paddle.activation.Relu())
    params = paddle.parameters.create(m)
    arr = np.array([1.0, -1.0, 2.0, -2.0], "f4")
    out = np.asarray(paddle.infer(output_layer=m, parameters=params,
                                  input=[(arr,)]))[0]
    np.testing.assert_allclose(out, np.maximum(2 * arr, 0), atol=1e-6)


def test_mixed_context_manager_iadd_form():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    with paddle.layer.mixed(size=8) as m:
        m += paddle.layer.full_matrix_projection(x, size=8)
        m += paddle.layer.full_matrix_projection(x, size=8)
    out = paddle.infer(output_layer=m,
                       parameters=paddle.parameters.create(m),
                       input=[(np.ones(4, "f4"),)])
    assert np.asarray(out).shape == (1, 8)


def test_mixed_rejects_plain_layer_and_empty():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    with pytest.raises(ValueError, match="projection"):
        paddle.layer.mixed(size=4, input=[x])
    m = paddle.layer.mixed(size=4)
    with pytest.raises(ValueError, match="no projections"):
        paddle.parameters.create(m)


def test_trans_full_matrix_projection_shares_transposed_param():
    """W [size, in] with matmul(x, W^T): check shape via param names."""
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    m = paddle.layer.mixed(
        size=3, input=[paddle.layer.trans_full_matrix_projection(
            x, size=3, param_attr=paddle.attr.Param(name="wt"))])
    params = paddle.parameters.create(m)
    assert params.get("wt").shape == (3, 4)
    out = paddle.infer(output_layer=m, parameters=params,
                       input=[(np.ones(4, "f4"),)])
    w = params.get("wt")
    np.testing.assert_allclose(np.asarray(out)[0], w.sum(1), rtol=1e-5)


def test_table_projection_is_embedding_lookup():
    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(11))
    m = paddle.layer.mixed(
        size=5, input=[paddle.layer.table_projection(
            words, size=5, param_attr=paddle.attr.Param(name="tbl"))])
    pooled = paddle.layer.pooling_layer(
        input=m, pooling_type=paddle.pooling.Sum())
    params = paddle.parameters.create(pooled)
    out = np.asarray(paddle.infer(output_layer=pooled, parameters=params,
                                  input=[([3, 7],)]))
    tbl = params.get("tbl")
    np.testing.assert_allclose(out[0], tbl[3] + tbl[7], rtol=1e-5)


def test_dotmul_scaling_slice_context_projections_build_and_run():
    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(9))
    emb = paddle.layer.embedding(input=words, size=6)
    ctxp = paddle.layer.mixed(
        size=18, input=[paddle.layer.context_projection(
            emb, context_len=3)])
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(6))
    dm = paddle.layer.mixed(size=6,
                            input=[paddle.layer.dotmul_projection(x)])
    sc = paddle.layer.mixed(size=6,
                            input=[paddle.layer.scaling_projection(x)])
    sl = paddle.layer.mixed(
        size=4, input=[paddle.layer.slice_projection(
            x, slices=[(0, 2), (4, 6)])])
    head = paddle.layer.fc(
        input=[paddle.layer.pooling_layer(
            input=ctxp, pooling_type=paddle.pooling.Max()), dm, sc, sl],
        size=3, act=paddle.activation.Softmax())
    out = paddle.infer(output_layer=head,
                       parameters=paddle.parameters.create(head),
                       input=[([1, 2, 3], np.ones(6, "f4"))])
    assert np.asarray(out).shape == (1, 3)
    assert np.allclose(np.asarray(out).sum(-1), 1.0, atol=1e-4)


def test_context_projection_zero_pads_edges():
    """identity check: with context_len=3, the first timestep's left
    block is zeros and its centre block equals emb[t=0]."""
    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(7))
    emb = paddle.layer.embedding(
        input=words, size=4,
        param_attr=paddle.attr.Param(name="emb_tbl"))
    ctxp = paddle.layer.mixed(
        size=12, input=[paddle.layer.context_projection(
            emb, context_len=3)])
    params = paddle.parameters.create(ctxp)
    out = np.asarray(paddle.infer(output_layer=ctxp, parameters=params,
                                  input=[([2, 5],)]))
    tbl = params.get("emb_tbl")
    np.testing.assert_allclose(out[0, 0, :4], np.zeros(4), atol=0)
    np.testing.assert_allclose(out[0, 0, 4:8], tbl[2], rtol=1e-6)
    np.testing.assert_allclose(out[0, 0, 8:], tbl[5], rtol=1e-6)
    np.testing.assert_allclose(out[0, 1, 8:], np.zeros(4), atol=0)


def test_dotmul_operator_multiplies_two_layers():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(4))
    m = paddle.layer.mixed(
        size=4, input=[paddle.layer.dotmul_operator(a=x, b=y, scale=2.0)])
    xa = np.array([1, 2, 3, 4], "f4")
    ya = np.array([2, 2, 0.5, 1], "f4")
    out = paddle.infer(output_layer=m,
                       parameters=paddle.parameters.create(m),
                       input=[(xa, ya)])
    np.testing.assert_allclose(np.asarray(out)[0], 2 * xa * ya)


# ------------------------------------------------- recurrent unit tier


def _train_seq_model(pred_fn, n_cls=2, vocab=30):
    """mirror of test_v2_api._train_seq_model: tiny synthetic
    sequence-classification run asserting the loss decreases."""
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(48):
            n = rng.randint(2, 8)
            cls = rng.randint(n_cls)
            lo, hi = (1, vocab // 2) if cls == 0 else (vocab // 2, vocab)
            yield [int(w) for w in rng.randint(lo, hi, n)], cls

    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(n_cls))
    feat = pred_fn(words)
    out = paddle.layer.fc(input=feat, size=n_cls,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))
    costs = []
    trainer.train(
        reader=paddle.batch(reader, batch_size=16), num_passes=8,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_lstmemory_group_classifier_trains():
    def pred(words):
        emb = paddle.layer.embedding(input=words, size=12)
        proj = paddle.layer.mixed(
            size=32, input=[paddle.layer.full_matrix_projection(
                emb, size=32)])
        lstm = paddle.networks.lstmemory_group(input=proj, size=8)
        return paddle.layer.last_seq(input=lstm)

    _train_seq_model(pred)


def test_gru_group_and_simple_gru_train():
    def pred(words):
        emb = paddle.layer.embedding(input=words, size=12)
        return paddle.layer.last_seq(
            input=paddle.networks.simple_gru(input=emb, size=8))

    _train_seq_model(pred)


def test_simple_gru2_and_bidirectional_gru_train():
    def pred(words):
        emb = paddle.layer.embedding(input=words, size=12)
        return paddle.networks.bidirectional_gru(input=emb, size=6)

    _train_seq_model(pred)


def test_recurrent_layer_classifier_trains():
    def pred(words):
        emb = paddle.layer.embedding(input=words, size=10)
        rec = paddle.layer.recurrent(input=emb)
        return paddle.layer.last_seq(input=rec)

    _train_seq_model(pred)


def test_static_input_visible_every_step():
    """recurrent_group with a StaticInput: step output = x_t + static
    query; verify the static vector is added at EVERY timestep."""
    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(9))
    q = paddle.layer.data(name="q", type=paddle.data_type.dense_vector(4))
    emb = paddle.layer.embedding(
        input=words, size=4, param_attr=paddle.attr.Param(name="etbl"))

    def step(x_t, q_t):
        return paddle.layer.addto(input=[x_t, q_t], name="st_out")

    grp = paddle.layer.recurrent_group(
        step=step, input=[emb, paddle.layer.StaticInput(q)])
    params = paddle.parameters.create(grp)
    qa = np.array([1.0, 2.0, 3.0, 4.0], "f4")
    out = np.asarray(paddle.infer(output_layer=grp, parameters=params,
                                  input=[([3, 6], qa)]))
    tbl = params.get("etbl")
    np.testing.assert_allclose(out[0, 0], tbl[3] + qa, rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], tbl[6] + qa, rtol=1e-6)


def test_dot_product_attention_decoder():
    """dot_product_attention inside a decoder recurrent_group."""
    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(20))
    emb = paddle.layer.embedding(input=words, size=8)
    enc = paddle.networks.simple_gru(input=emb, size=8)

    def step(trg, enc_seq):
        state = paddle.layer.memory(name="dec", size=8)
        ctxv = paddle.networks.dot_product_attention(
            encoded_sequence=enc_seq, attended_sequence=enc_seq,
            transformed_state=state)
        return paddle.layer.fc(input=[trg, ctxv], size=8,
                               act=paddle.activation.Tanh(), name="dec")

    dec = paddle.layer.recurrent_group(
        step=step, input=[emb, paddle.layer.StaticInput(enc)])
    out = paddle.layer.fc(input=paddle.layer.last_seq(input=dec), size=2,
                          act=paddle.activation.Softmax())
    probs = paddle.infer(output_layer=out,
                         parameters=paddle.parameters.create(out),
                         input=[([1, 2, 3],), ([4, 5],)])
    assert np.asarray(probs).shape == (2, 2)


# --------------------------------------------------- breadth tier 2


def test_breadth2_vector_ops_numeric():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(4))
    w = paddle.layer.data(name="wt", type=paddle.data_type.dense_vector(1))
    outs = {
        "power": paddle.layer.power(input=x, weight=w),
        "repeat_row": paddle.layer.repeat(input=x, num_repeats=2),
        "out_prod": paddle.layer.out_prod(x, y),
        "scale_shift": paddle.layer.scale_shift(input=x),
        "linear_comb": paddle.layer.linear_comb(weights=w, vectors=x,
                                                size=4),
    }
    # one infer per op keeps failures attributable
    xa = np.array([1.0, 2.0, 3.0, 4.0], "f4")
    ya = np.array([2.0, 1.0, 0.5, 1.0], "f4")
    wa = np.array([2.0], "f4")
    feed = [(xa, ya, wa)]
    feeding = {"x": 0, "y": 1, "wt": 2}

    got = np.asarray(paddle.infer(
        output_layer=outs["power"],
        parameters=paddle.parameters.create(outs["power"]),
        input=[(xa, wa)], feeding={"x": 0, "wt": 1}))
    np.testing.assert_allclose(got[0], xa ** 2, rtol=1e-5)

    got = np.asarray(paddle.infer(
        output_layer=outs["repeat_row"],
        parameters=paddle.parameters.create(outs["repeat_row"]),
        input=[(xa,)]))
    np.testing.assert_allclose(got[0], np.tile(xa, 2))

    got = np.asarray(paddle.infer(
        output_layer=outs["out_prod"],
        parameters=paddle.parameters.create(outs["out_prod"]),
        input=[(xa, ya)], feeding={"x": 0, "y": 1}))
    np.testing.assert_allclose(got[0], np.outer(xa, ya).ravel(),
                               rtol=1e-6)

    got = np.asarray(paddle.infer(
        output_layer=outs["linear_comb"],
        parameters=paddle.parameters.create(outs["linear_comb"]),
        input=[(xa, wa)], feeding={"x": 0, "wt": 1}))
    np.testing.assert_allclose(got[0], 2.0 * xa, rtol=1e-6)


def test_breadth2_conv_shift_circular():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(5))
    k = paddle.layer.data(name="k", type=paddle.data_type.dense_vector(3))
    out = paddle.layer.conv_shift(x, k)
    xa = np.array([1, 2, 3, 4, 5], "f4")
    ka = np.array([1, 0, 0], "f4")   # kernel peaked at j=0 => shift -1
    got = np.asarray(paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[(xa, ka)], feeding={"x": 0, "k": 1}))
    np.testing.assert_allclose(got[0], np.roll(xa, 1), rtol=1e-6)


def test_breadth2_feature_layers_build_and_train():
    """tensor/gated_unit/fm/dotmul heads train end-to-end."""
    rng = np.random.RandomState(1)

    def reader():
        for _ in range(32):
            x = rng.randn(6).astype("f4")
            yield x, int(x.sum() > 0)

    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(6))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(2))
    feats = [
        paddle.layer.tensor(x, x, size=4),
        paddle.layer.gated_unit(input=x, size=4),
        paddle.layer.factorization_machine(input=x, factor_size=3),
        paddle.layer.mixed(size=6,
                           input=[paddle.layer.dotmul_projection(x)]),
    ]
    out = paddle.layer.fc(input=paddle.layer.concat(input=feats), size=2,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))
    costs = []
    trainer.train(reader=paddle.batch(reader, batch_size=16),
                  num_passes=6,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0]


def test_breadth2_image_tier_builds():
    """pad/crop/spp/img_cmrnorm/cross_channel_norm/bilinear/upsample/
    block_expand/switch_order/rotate over a [2, 8, 8] image."""
    img = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(2 * 8 * 8),
        height=8, width=8)
    padded = paddle.layer.pad(input=img, pad_c=(1, 1), pad_h=(0, 0),
                              pad_w=(0, 0))
    cropped = paddle.layer.crop(input=padded, axis=1, offset=[1, 0, 0],
                                shape=[2, 8, 8])
    feats = [
        paddle.layer.spp(input=cropped, pyramid_height=2),
        paddle.layer.img_cmrnorm(input=cropped, size=3),
        paddle.layer.cross_channel_norm(input=cropped),
        paddle.layer.bilinear_interp(input=cropped, out_size_x=4,
                                     out_size_y=4),
        paddle.layer.upsample(input=cropped, scale=2),
        paddle.layer.switch_order(input=cropped),
        paddle.layer.rotate(input=cropped, height=8, width=8),
        paddle.layer.pooling_layer(
            input=paddle.layer.block_expand(
                input=cropped, block_x=4, block_y=4, stride_x=4,
                stride_y=4),
            pooling_type=paddle.pooling.Max()),
    ]
    pooled = [paddle.layer.fc(input=f, size=3) for f in feats]
    out = paddle.layer.fc(input=paddle.layer.concat(input=pooled),
                          size=2, act=paddle.activation.Softmax())
    got = paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[(np.random.RandomState(0).rand(128).astype("f4"),)])
    assert np.asarray(got).shape == (1, 2)


def test_breadth2_sequence_tier_builds():
    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(12))
    emb = paddle.layer.embedding(input=words, size=6)
    feats = [
        paddle.layer.seq_reshape(input=emb, reshape_size=3),
        paddle.layer.seq_concat(emb, emb),
        paddle.layer.seq_slice(input=emb, starts=0, ends=2),
        paddle.layer.sub_seq(input=emb, offsets=1, sizes=1),
        paddle.layer.row_conv(input=emb, context_len=2),
    ]
    pooled = [paddle.layer.pooling_layer(
        input=f, pooling_type=paddle.pooling.Max()) for f in feats]
    out = paddle.layer.fc(input=paddle.layer.concat(input=pooled),
                          size=2, act=paddle.activation.Softmax())
    got = paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[([1, 2, 3, 4],)])
    assert np.asarray(got).shape == (1, 2)


def test_breadth2_cost_layers_train():
    rng = np.random.RandomState(3)
    data = [(rng.randn(5).astype("f4"),) for _ in range(32)]
    data = [(x, int(x.sum() > 0)) for (x,) in data]

    def reader():
        yield from data

    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(5))
    ilabel = paddle.layer.data(name="label",
                               type=paddle.data_type.integer_value(2))
    probs = paddle.layer.fc(input=x, size=2,
                            act=paddle.activation.Softmax())
    score = paddle.layer.fc(input=x, size=1)
    binlab = paddle.layer.mixed(
        size=1, input=[paddle.layer.identity_projection(
            paddle.layer.data(name="ylab",
                              type=paddle.data_type.dense_vector(1)))])
    costs = [
        paddle.layer.cross_entropy(input=probs, label=ilabel),
        paddle.layer.cross_entropy_with_selfnorm(input=probs,
                                                 label=ilabel),
        paddle.layer.nce(input=x, label=ilabel, num_classes=2,
                         num_neg_samples=1),
        paddle.layer.hsigmoid(input=x, label=ilabel, num_classes=2),
        paddle.layer.huber_classification_cost(input=score,
                                               label=ilabel),
        paddle.layer.multi_binary_label_cross_entropy(
            input=paddle.layer.fc(input=x, size=1,
                                  act=paddle.activation.Sigmoid()),
            label=binlab),
    ]
    total = paddle.layer.addto(input=costs)
    params = paddle.parameters.create(total)
    trainer = paddle.trainer.SGD(
        cost=total, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))
    seen = []

    def rd():
        for xv, c in reader():
            yield xv, c, np.array([float(c)], "f4")

    trainer.train(reader=paddle.batch(rd, batch_size=16), num_passes=10,
                  event_handler=lambda e: seen.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None,
                  feeding={"x": 0, "label": 1, "ylab": 2})
    # nce resamples noise each step: compare pass means, not endpoints
    assert np.mean(seen[-2:]) < np.mean(seen[:2]), seen


def test_breadth2_ctc_cost_trains():
    rng = np.random.RandomState(4)
    V = 5          # classes incl. blank at index 4

    def reader():
        for _ in range(24):
            n = rng.randint(3, 6)
            lab = [int(v) for v in rng.randint(0, V - 1, 2)]
            yield [int(w) for w in rng.randint(0, 9, n)], lab

    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(9))
    lab = paddle.layer.data(
        name="lab", type=paddle.data_type.integer_value_sequence(V))
    emb = paddle.layer.embedding(input=words, size=8)
    logits = paddle.layer.fc(input=emb, size=V)
    cost = paddle.layer.ctc(input=logits, label=lab, blank=V - 1)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))
    seen = []
    trainer.train(reader=paddle.batch(reader, batch_size=8),
                  num_passes=4,
                  event_handler=lambda e: seen.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert np.isfinite(seen).all() and seen[-1] < seen[0]


def test_breadth2_misc_infer_layers():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(6))
    probs = paddle.layer.fc(input=x, size=4,
                            act=paddle.activation.Softmax())
    sid = paddle.layer.sampling_id(input=probs)
    got = np.asarray(paddle.infer(
        output_layer=sid, parameters=paddle.parameters.create(sid),
        input=[(np.ones(6, "f4"),)]))
    assert got.shape[0] == 1 and 0 <= int(got.ravel()[0]) < 4

    res = paddle.layer.resize(input=x, size=3)
    got = np.asarray(paddle.infer(
        output_layer=res, parameters=paddle.parameters.create(res),
        input=[(np.arange(6).astype("f4"),)]))
    assert got.shape == (2, 3)

    sel = paddle.layer.data(name="sel",
                            type=paddle.data_type.integer_value(2))
    a = paddle.layer.fc(input=x, size=3)
    b = paddle.layer.fc(input=x, size=3)
    mux = paddle.layer.multiplex(input=[sel, a, b])
    got = np.asarray(paddle.infer(
        output_layer=mux, parameters=paddle.parameters.create(mux),
        input=[(np.ones(6, "f4"), 1)], feeding={"x": 0, "sel": 1}))
    assert got.shape == (1, 3)

    pr = paddle.layer.prelu(input=x)
    got = np.asarray(paddle.infer(
        output_layer=pr, parameters=paddle.parameters.create(pr),
        input=[(np.arange(-3, 3).astype("f4"),)]))
    assert got.shape == (1, 6)


def test_vgg_16_network_builds_and_infers():
    img = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(3 * 32 * 32),
        height=32, width=32)
    out = paddle.networks.vgg_16_network(img, num_channels=3,
                                         num_classes=4)
    got = np.asarray(paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[(np.random.RandomState(0).rand(3072).astype("f4"),)]))
    assert got.shape == (1, 4)
    assert np.allclose(got.sum(-1), 1.0, atol=1e-3)


def test_remaining_aliases_and_conv_projection():
    """conv_projection in mixed; gru_step_naive group; warp_ctc and
    convex_comb delegate correctly."""
    img = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(1 * 6 * 6),
        height=6, width=6)
    m = paddle.layer.mixed(
        size=0, input=[
            paddle.layer.conv_projection(img, filter_size=3,
                                         num_filters=2, padding=1),
            paddle.layer.conv_projection(img, filter_size=3,
                                         num_filters=2, padding=1)])
    out = paddle.layer.fc(input=m, size=2,
                          act=paddle.activation.Softmax())
    got = np.asarray(paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[(np.ones(36, "f4"),)]))
    assert got.shape == (1, 2)

    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    w = paddle.layer.data(name="wt", type=paddle.data_type.dense_vector(1))
    cc = paddle.layer.convex_comb(weights=w, vectors=x, size=4)
    got = np.asarray(paddle.infer(
        output_layer=cc, parameters=paddle.parameters.create(cc),
        input=[(np.arange(4).astype("f4"), np.array([3.0], "f4"))],
        feeding={"x": 0, "wt": 1}))
    np.testing.assert_allclose(got[0], 3.0 * np.arange(4), rtol=1e-6)

    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(9))
    lab = paddle.layer.data(
        name="lab", type=paddle.data_type.integer_value_sequence(4))
    emb = paddle.layer.embedding(input=words, size=6)
    logits = paddle.layer.fc(input=emb, size=4)
    wc = paddle.layer.warp_ctc(input=logits, label=lab, blank=3)

    def _step(ipt):
        return paddle.layer.gru_step_naive(
            ipt, paddle.layer.memory(name="gn", size=2), name="gn")

    proj = paddle.layer.fc(input=emb, size=6, bias_attr=False)
    gn = paddle.layer.recurrent_group(step=_step, input=proj)
    pooled = paddle.layer.pooling_layer(input=gn,
                                        pooling_type=paddle.pooling.Max())
    head = paddle.layer.fc(input=pooled, size=2,
                           act=paddle.activation.Softmax())
    total = paddle.layer.addto(
        input=[wc, paddle.layer.sum_cost(input=head)])
    params = paddle.parameters.create(total)
    trainer = paddle.trainer.SGD(
        cost=total, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))
    seen = []
    rng = np.random.RandomState(5)

    def reader():
        for _ in range(16):
            n = rng.randint(3, 6)
            yield ([int(v) for v in rng.randint(0, 9, n)],
                   [int(v) for v in rng.randint(0, 3, 2)])

    trainer.train(reader=paddle.batch(reader, batch_size=8),
                  num_passes=3,
                  event_handler=lambda e: seen.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert np.isfinite(seen).all()


def test_3d_and_roi_tier_builds():
    from paddle_tpu.v2.config_base import Layer as Node

    flat = paddle.layer.data(
        name="vol", type=paddle.data_type.dense_vector(1 * 4 * 8 * 8))

    def to_vol(ctx):
        from paddle_tpu import layers as fl
        return fl.reshape(flat.to_var(ctx), [-1, 1, 4, 8, 8])

    vol = Node(to_vol, [flat])
    c3 = paddle.layer.img_conv3d(input=vol, filter_size=3,
                                 num_filters=2, padding=1)
    p3 = paddle.layer.img_pool3d(input=c3, pool_size=2)
    head3 = paddle.layer.fc(input=p3, size=2,
                            act=paddle.activation.Softmax())
    got3 = np.asarray(paddle.infer(
        output_layer=head3, parameters=paddle.parameters.create(head3),
        input=[(np.random.RandomState(1).rand(256).astype("f4"),)]))
    assert got3.shape == (1, 2)
    assert np.allclose(got3.sum(-1), 1.0, atol=1e-3)

    x = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(2 * 8 * 8),
        height=8, width=8)
    rois = paddle.layer.data(name="rois",
                             type=paddle.data_type.dense_vector(4))
    rp = paddle.layer.roi_pool(input=x, rois=rois, pooled_width=2,
                               pooled_height=2)
    out = paddle.layer.fc(input=rp, size=3,
                          act=paddle.activation.Softmax())
    got = np.asarray(paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[(np.random.RandomState(0).rand(128).astype("f4"),
                np.array([0, 0, 7, 7], "f4"))],
        feeding={"img": 0, "rois": 1}))
    assert got.shape == (1, 3)


def test_kmax_seq_score_and_scale_sub_region():
    words = paddle.layer.data(
        name="w", type=paddle.data_type.integer_value_sequence(9))
    emb = paddle.layer.embedding(input=words, size=4)
    scores = paddle.layer.fc(input=emb, size=1, bias_attr=False)
    kmax = paddle.layer.kmax_seq_score(input=scores, beam_size=2)
    got = np.asarray(paddle.infer(
        output_layer=kmax, parameters=paddle.parameters.create(kmax),
        input=[([1, 2, 3, 4],)]))
    assert got.shape == (1, 2)
    assert set(got.ravel().tolist()) <= set(range(8))  # padded T

    img = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(2 * 4 * 4),
        height=4, width=4)
    idx = paddle.layer.data(name="idx",
                            type=paddle.data_type.dense_vector(6))
    ssr = paddle.layer.scale_sub_region(input=img, indices=idx,
                                        value=3.0)
    x = np.ones(32, "f4")
    box = np.array([1, 1, 1, 2, 1, 2], "f4")   # C=1, H=1..2, W=1..2
    got = np.asarray(paddle.infer(
        output_layer=ssr, parameters=paddle.parameters.create(ssr),
        input=[(x, box)], feeding={"img": 0, "idx": 1}))
    assert got.shape == (1, 2, 4, 4)
    assert got[0, 0, :2, :2].ravel().tolist() == [3.0] * 4
    assert got[0, 1].sum() == 16.0              # channel 2 untouched
    assert got[0, 0, 2:, :].sum() == 8.0        # rows 3-4 untouched
