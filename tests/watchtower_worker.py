"""Watchtower fleet worker — one rank of the ISSUE 15 headline e2e
(tests/test_watchtower.py).

Run:  python tests/watchtower_worker.py <host:port> <rank> <stop_file>

The process registers + heartbeats with the coordinator the TEST owns,
runs a synthetic traced step loop (each step observes
``trainer_step_seconds`` under its own X-ray trace — so the shipped
metric snapshots carry exemplar trace ids — and journals a ``worker
step`` event), and reports via a background FleetReporter.  A
``trainer.step`` chaos fault point fires every step: the e2e arms an
``exit`` schedule on rank 0 so the process hard-dies mid-loop, the
master's heartbeat reaper declares it dead (dead-rank alert fires on
the coordinator), the supervisor respawns it clean (restart_env strips
chaos) and the alert resolves.  The loop exits 0 once `stop_file`
appears.
"""
import json
import os
import sys
import time

# repo root on sys.path (PYTHONPATH must stay unset — axon plugin
# quirk, tests/conftest.py)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    endpoints, rank, stop_file = (sys.argv[1], int(sys.argv[2]),
                                  sys.argv[3])
    host, port = endpoints.rsplit(":", 1)

    from paddle_tpu.distributed.task_queue import Heartbeater
    from paddle_tpu.observability import fleet, journal, tracectx
    from paddle_tpu.observability import metrics as obs_metrics
    from paddle_tpu.resilience import chaos

    journal.set_rank(rank)
    tracectx.set_rank(rank)
    restart_count = int(os.environ.get("PTPU_WORKER_RESTART_COUNT",
                                       "0"))
    journal.emit("worker", "start", restart_count=restart_count)

    steps = obs_metrics.counter(
        "trainer_steps_total", "Optimizer steps taken by Trainer.train.")
    step_h = obs_metrics.histogram(
        "trainer_step_seconds", "Wall time of one train step.")

    hb = Heartbeater(f"{host}:{port}", rank)
    hb.start()
    reporter = fleet.FleetReporter(host, int(port), rank=rank)
    reporter.start()

    i = 0
    try:
        while not os.path.exists(stop_file):
            t0 = time.perf_counter()
            # the kill site: an armed exit schedule hard-dies HERE,
            # journal already carrying the chaos event (flushed line)
            chaos.trigger("trainer.step")
            time.sleep(0.02)
            ctx = tracectx.start_trace("worker.step")
            with tracectx.activate(ctx):
                # observed under an active trace -> the histogram
                # bucket gains a (value, trace_id) exemplar, shipped in
                # the next metrics snapshot — the dead-rank alert's
                # "what was the victim doing" context
                step_h.observe(time.perf_counter() - t0)
            steps.inc()
            journal.emit("worker", "step", step=i)
            i += 1
    finally:
        journal.emit("worker", "stopping", steps_done=i)
        try:
            reporter.stop()
        except Exception:
            pass
        hb.stop(goodbye=True)
    print(json.dumps({"rank": rank, "steps": i,
                      "restart_count": restart_count}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
