"""Hybrid dp/pp/tp(+sp)/ep training-step tests on the 8-device CPU mesh.

The contract mirrors the reference's parallel tests
(test_parallel_executor_*: train same model single vs parallel, assert loss
parity — /root/reference/python/paddle/fluid/tests/unittests/
parallel_executor_test_base.py:127): the hybrid sharded loss must match a
single-device reference implementation of the same math to float tolerance.
"""
import jax
import numpy as np
import pytest

from paddle_tpu.parallel import hybrid, topology


def tiny_cfg(**kw):
    base = dict(vocab_size=64, seq_len=16, d_model=32, n_heads=4,
                n_layers=4, d_ff=64, n_microbatches=2, remat=False,
                learning_rate=1e-2)
    base.update(kw)
    return hybrid.HybridConfig(**base)


def test_hybrid_dp_pp_tp_loss_matches_reference():
    cfg = tiny_cfg()
    mesh = topology.make_hybrid_mesh(dp=2, pp=2, tp=2)
    params = hybrid.init_params(mesh, cfg, seed=0)
    opt = hybrid.init_opt_state(params)
    step = hybrid.build_train_step(mesh, cfg)
    tokens, labels = hybrid.make_fake_lm_batch(cfg, global_batch=8)

    host_params = {k: np.asarray(v) for k, v in params.items()}
    ref = float(hybrid.reference_loss(host_params, cfg, tokens, labels))

    params, opt, loss = step(params, opt, tokens, labels)
    assert np.isfinite(float(loss))
    np.testing.assert_allclose(float(loss), ref, rtol=2e-4, atol=2e-4)


def test_hybrid_training_reduces_loss():
    cfg = tiny_cfg()
    mesh = topology.make_hybrid_mesh(dp=2, pp=2, tp=2)
    params = hybrid.init_params(mesh, cfg, seed=0)
    opt = hybrid.init_opt_state(params)
    step = hybrid.build_train_step(mesh, cfg)
    tokens, labels = hybrid.make_fake_lm_batch(cfg, global_batch=8)
    losses = []
    for _ in range(8):
        params, opt, loss = step(params, opt, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_hybrid_moe_expert_parallel_runs():
    cfg = tiny_cfg(moe_experts=4)
    mesh = topology.make_hybrid_mesh(dp=2, pp=2, tp=2)
    params = hybrid.init_params(mesh, cfg, seed=0)
    opt = hybrid.init_opt_state(params)
    step = hybrid.build_train_step(mesh, cfg)
    tokens, labels = hybrid.make_fake_lm_batch(cfg, global_batch=8)
    losses = []
    for _ in range(6):
        params, opt, loss = step(params, opt, tokens, labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_hybrid_pure_dp_matches_reference():
    """dp=8 only (the reference's ParallelExecutor capability)."""
    cfg = tiny_cfg(n_microbatches=1)
    mesh = topology.make_hybrid_mesh(dp=8, pp=1, tp=1)
    params = hybrid.init_params(mesh, cfg, seed=0)
    opt = hybrid.init_opt_state(params)
    step = hybrid.build_train_step(mesh, cfg)
    tokens, labels = hybrid.make_fake_lm_batch(cfg, global_batch=16)
    host_params = {k: np.asarray(v) for k, v in params.items()}
    ref = float(hybrid.reference_loss(host_params, cfg, tokens, labels))
    params, opt, loss = step(params, opt, tokens, labels)
    np.testing.assert_allclose(float(loss), ref, rtol=2e-4, atol=2e-4)
