"""Request X-ray plane (ISSUE 11): trace context propagation, histogram
exemplars, waterfall assembly (local + fleet, incl. worker churn), the
serving soak headline (every request -> retrievable trace), on-demand
device profiling, flag-off invariance and the decode-loop overhead A/B.
"""
import json
import os
import subprocess
import sys
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import models, serving
from paddle_tpu.core import flags
from paddle_tpu.observability import fleet as obs_fleet
from paddle_tpu.observability import forensics
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import server as obs_server
from paddle_tpu.observability import tracectx
from paddle_tpu.observability import xray
from paddle_tpu.serving import loadgen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter_total(name):
    m = obs_metrics.REGISTRY.get(name)
    return 0.0 if m is None else m.total()


# --- context + store unit layer -------------------------------------------

def test_traceparent_parse_and_roundtrip():
    ctx = tracectx.start_trace("t")
    assert ctx is not None
    parsed = tracectx.parse_traceparent(ctx.traceparent())
    assert parsed.trace_id == ctx.trace_id
    assert parsed.span_id == ctx.span_id
    # continuation: an upstream header keeps the trace id, new span id
    cont = tracectx.start_trace("t2", parent=parsed)
    assert cont.trace_id == ctx.trace_id
    assert cont.span_id != ctx.span_id
    for bad in (None, "", "garbage", "00-zz-ff-01",
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # zero trace
                "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # zero span
                "00-" + "a" * 31 + "-" + "b" * 16 + "-01"):  # short
        assert tracectx.parse_traceparent(bad) is None, bad


def test_span_store_waterfall_tree_and_orphans():
    ctx = tracectx.start_trace("req")
    with tracectx.activate(ctx):
        with tracectx.span("phase_a", kind="work", n=1):
            with tracectx.span("inner", kind="work"):
                pass
        tracectx.instant("marker", note="x")
    wf = tracectx.waterfall(ctx.trace_id)
    assert wf["schema"] == "paddle_tpu.xray.v1"
    names = {s["name"] for s in wf["spans"]}
    assert {"phase_a", "inner", "marker"} <= names
    by_name = {s["name"]: s for s in wf["spans"]}
    # inner's parent is phase_a's span (present -> not orphan)
    assert by_name["inner"]["parent_id"] == by_name["phase_a"]["span_id"]
    assert not by_name["inner"]["orphan"]
    # phase_a's parent is the root request span, which was never
    # closed/recorded here -> flagged orphan, not silently rootified
    assert by_name["phase_a"]["orphan"]
    # renders without raising, and the CLI fixture round-trips
    text = xray.render_waterfall(wf)
    assert ctx.trace_id in text and "phase_a" in text


def test_tracing_off_is_inert():
    flags.set_flag("request_tracing", False)
    try:
        assert tracectx.start_trace("t") is None
        assert tracectx.current() is None
        with tracectx.span("s") as child:
            assert child is None
        assert tracectx.trace_ids() == []
    finally:
        flags.set_flag("request_tracing", True)


def test_histogram_exemplars_recorded_merged_and_rendered():
    h = obs_metrics.histogram("xray_test_hist", "t",
                              buckets=(0.1, 1.0))
    ctx = tracectx.start_trace("exemplar")
    with tracectx.activate(ctx):
        h.observe(0.5)           # lands in the le=1.0 bucket
    h.observe(0.05)              # no ambient trace: no exemplar
    doc = obs_metrics.REGISTRY.to_json()
    row = doc["metrics"]["xray_test_hist"]["series"][0]
    assert row["exemplars"] == {
        "1.0": {"value": 0.5, "trace_id": ctx.trace_id,
                "time_unix": pytest.approx(time.time(), abs=30)}}
    # exemplar clauses are OpenMetrics-only: the default (v0.0.4) text
    # must NOT carry them — a mid-line '#' would fail the whole scrape
    plain = obs_metrics.REGISTRY.prometheus_text()
    assert "trace_id=" not in plain
    text = obs_metrics.REGISTRY.prometheus_text(exemplars=True)
    assert f'# {{trace_id="{ctx.trace_id}"}} 0.5' in text
    # fleet merge carries the exemplar through (newest per bucket wins)
    merged = obs_fleet.merge_metric_docs({0: doc, 1: doc})
    fam = merged["xray_test_hist"]
    ent = next(iter(fam["series"].values()))
    assert ent["exemplars"]["1.0"]["trace_id"] == ctx.trace_id
    # and the merged family still renders
    assert "xray_test_hist_bucket" in obs_fleet.render_prometheus(merged)


def test_xray_cli_self_test_smoke():
    """Tier-1 gate: the bundled fixture parses and renders (the
    analysis.lint --self-test idiom)."""
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.observability.xray",
         "--self-test"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={k: v for k, v in os.environ.items() if k != "PYTHONPATH"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


# --- flag-off invariance (the PR 7/10 idiom) ------------------------------

def _tiny_program():
    pt.reset_default_programs()
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 5
    with pt.program_guard(main, startup):
        x = layers.data("x", [8])
        y = layers.data("y", [1])
        p = layers.fc(x, size=1, bias_attr=False)
        loss = layers.mean(layers.square_error_cost(p, y))
        pt.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def test_flag_off_invariance_outputs_keys_and_explain():
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(4, 8).astype("f4"),
            "y": rng.randn(4, 1).astype("f4")}

    def run(tracing):
        flags.set_flag("request_tracing", tracing)
        try:
            main, startup, loss = _tiny_program()
            scope = pt.Scope()
            exe = pt.Executor(pt.CPUPlace(), scope=scope)
            exe.run(startup)
            outs = [exe.run(main, feed=feed, fetch_list=[loss.name])[0]
                    for _ in range(3)]
            explain = exe.explain(main, feed=feed,
                                  fetch_list=[loss.name])
            return outs, explain, _counter_total("executor_compile_total")
        finally:
            flags.set_flag("request_tracing", True)

    outs_on, explain_on, _ = run(True)
    compiles_before = _counter_total("executor_compile_total")
    outs_off, explain_off, _ = run(False)
    # tracing does not touch the compile key: same program shape, same
    # number of fresh compiles either way
    assert _counter_total("executor_compile_total") - compiles_before \
        == 2  # startup + train step, exactly as with tracing on
    for a, b in zip(outs_on, outs_off):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    # explain() reports byte-identical (program uids differ per build;
    # normalize them out)
    for doc in (explain_on, explain_off):
        doc["program"]["uid"] = 0
        doc["cache"] = {}
        if doc.get("cost"):
            doc["cost"]["label"] = ""     # embeds the program uid too
    assert json.dumps(explain_on, sort_keys=True, default=str) \
        == json.dumps(explain_off, sort_keys=True, default=str)


# --- trainer per-step traces + cold-start metric --------------------------

def test_trainer_step_traces_runlog_and_cold_start(tmp_path):
    runlog_path = str(tmp_path / "run.jsonl")
    flags.set_flag("runlog_path", runlog_path)
    try:
        def train_func():
            from paddle_tpu import layers
            x = layers.data("x", [4])
            y = layers.data("y", [1])
            p = layers.fc(x, size=1)
            return layers.mean(layers.square_error_cost(p, y))

        t = pt.Trainer(train_func=train_func,
                       optimizer_func=lambda: pt.optimizer.SGD(0.1),
                       place=pt.CPUPlace())
        rng = np.random.RandomState(0)
        data = [(rng.randn(4).astype("f4"), rng.randn(1).astype("f4"))
                for _ in range(6)]
        batches = [data[i:i + 2] for i in range(0, len(data), 2)]
        t.train(num_epochs=1, event_handler=lambda e: None,
                reader=lambda: iter(batches), feed_order=["x", "y"])
    finally:
        flags.set_flag("runlog_path", "")
    # cold-start metric: set once, positive, plausibly > pure step time
    g = obs_metrics.REGISTRY.get("restart_to_first_step_seconds")
    assert g is not None and g.value > 0
    # every step record carries its trace id, and the trace resolves to
    # a waterfall with the step anatomy as child spans
    steps = [json.loads(l) for l in open(runlog_path)
             if json.loads(l).get("kind") == "step"]
    assert steps and all(s.get("trace_id") for s in steps)
    wf = tracectx.waterfall(steps[-1]["trace_id"])
    assert wf is not None
    names = [s["name"] for s in wf["spans"]]
    assert "trainer.step" in names
    for child in ("trainer.data_wait", "trainer.host", "trainer.device",
                  "executor.step"):
        assert child in names, names
    root = next(s for s in wf["spans"] if s["name"] == "trainer.step")
    assert root["parent_id"] is None
    # the FIRST step's trace contains the compile marker — a step that
    # triggered a recompile says so in its own timeline
    wf0 = tracectx.waterfall(steps[0]["trace_id"])
    assert "executor.compile" in [s["name"] for s in wf0["spans"]]
    # ... and the forensics compile log names the trace right back
    tagged = [r for r in forensics.compile_log()
              if r.get("trace_id") == steps[0]["trace_id"]]
    assert tagged


# --- serving headline: soak -> every request has a retrievable trace ------

@pytest.fixture(scope="module")
def lm():
    """Tiny LM + ONE AOT-prepared engine for the whole module (the
    test_serving compile-once idiom — prepare() is the expensive
    part; per-test state is wiped via engine.reset())."""
    pt.reset_default_programs()
    from paddle_tpu.framework import executor as em
    scope = em.Scope()
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=97, tgt_vocab_size=97, max_length=32,
        n_layer=2, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    feeds, cost, logits = models.transformer.build_lm_net(
        cfg, seq_len=24, is_test=True, fused_attention=False,
        fused_head=False)
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    pt.default_startup_program().random_seed = 3
    exe.run(pt.default_startup_program())
    params = serving.extract_lm_params(pt.default_main_program(), scope,
                                       cfg)
    engine = serving.DecodeEngine(cfg, params, max_batch=4,
                                  max_len=32, prompt_buckets=(8, 16))
    engine.prepare()
    return SimpleNamespace(cfg=cfg, params=params, engine=engine)


@pytest.fixture
def batcher(lm):
    lm.engine.reset()
    b = serving.ContinuousBatcher(lm.engine, queue_limit=32)
    b.start()
    serving.attach(b)
    yield b
    serving.reset()


def _http_json(url, body=None, headers=None):
    if body is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, dict(r.headers), json.loads(r.read().decode())


def test_soak_every_request_yields_a_complete_trace(batcher):
    """The headline: an 8-stream loadgen soak where EVERY issued
    request resolves to a well-formed waterfall via GET /trace/<id>,
    a deliberately slow request's TTFT exemplar links to a trace whose
    waterfall names the slow span, and a request-triggered (lazy
    bucket) recompile appears inside that request's own timeline."""
    srv = obs_server.start_http_server(port=0)
    rep = loadgen.run_loadgen(loadgen.inproc_submit(batcher), streams=8,
                              requests_per_stream=3, max_new_tokens=6,
                              prompt_len_range=(4, 14))
    assert rep["ok"], rep
    issued_ok = rep["counts"]["ok"]
    assert issued_ok == 8 * 3
    # EVERY request carried a trace id ...
    assert len(rep["trace_ids"]) == issued_ok
    assert len(set(rep["trace_ids"])) == issued_ok
    # ... and each resolves over HTTP to a complete span tree:
    # queue -> prefill -> decode -> retire under one root
    for tid in rep["trace_ids"]:
        code, _hdrs, wf = _http_json(f"{srv.url}/trace/{tid}")
        assert code == 200
        assert wf["schema"] == "paddle_tpu.xray.v1"
        names = [s["name"] for s in wf["spans"]]
        for want in ("serving.request", "serving.queue_wait",
                     "serving.prefill", "serving.decode",
                     "serving.retire"):
            assert want in names, (tid, names)
        root = [s for s in wf["spans"]
                if s["name"] == "serving.request"]
        assert len(root) == 1 and root[0]["parent_id"] is None
        ids = {s["span_id"] for s in wf["spans"]}
        for s in wf["spans"]:
            if s is not root[0]:
                assert s["parent_id"] in ids     # well-formed tree
        pf = next(s for s in wf["spans"]
                  if s["name"] == "serving.prefill")
        assert pf["attrs"]["bucket"] in (8, 16)

    # --- deliberately slow request: SLO breach -> capture + exemplar --
    flags.set_flag("serving_p99_budget_ms", 0.0001)   # everything slow
    try:
        req = batcher.submit([1, 2, 3, 4], max_new_tokens=8)
        doc = req.result(timeout=30)
    finally:
        flags.set_flag("serving_p99_budget_ms", 0.0)
    slow_tid = doc["trace_id"]
    # its TTFT histogram bucket carries an exemplar pointing back at a
    # retrievable trace (this one or a soak request that landed in the
    # same bucket — either way, the exemplar's id must resolve)
    mdoc = obs_metrics.REGISTRY.to_json()
    ttft_rows = mdoc["metrics"]["serving_ttft_seconds"]["series"]
    exemplars = {b: e for row in ttft_rows
                 for b, e in (row.get("exemplars") or {}).items()}
    assert exemplars, "TTFT histogram carries no exemplars"
    ex_tids = {e["trace_id"] for e in exemplars.values()}
    assert slow_tid in ex_tids
    code, _h, wf = _http_json(f"{srv.url}/trace/{slow_tid}")
    assert code == 200
    # the breach capture rode along, naming the budget and the numbers
    assert wf["capture"]["reason"] == "slo_breach"
    assert wf["capture"]["detail"]["budget_ms"] == 0.0001
    # ... and the waterfall names its slowest span
    assert "<-- slowest" in xray.render_waterfall(wf)

    # --- exemplars over HTTP: v0.0.4 scrape stays parseable, an
    # OpenMetrics-negotiating scraper gets the clauses -----------------
    plain = urllib.request.urlopen(f"{srv.url}/metrics",
                                   timeout=10).read().decode()
    assert "trace_id=" not in plain
    om_req = urllib.request.Request(
        f"{srv.url}/metrics",
        headers={"Accept": "application/openmetrics-text"})
    with urllib.request.urlopen(om_req, timeout=10) as r:
        assert "openmetrics-text" in r.headers.get("Content-Type", "")
        om = r.read().decode()
    assert "trace_id=" in om and om.endswith("# EOF\n")

    # --- request-triggered recompile inside the request's timeline ---
    # an unprepared bucket is rejected AT SUBMIT while lazy compile is
    # off (it must not become a mid-prefill failure that nukes every
    # in-flight request via the donated-cache recovery)
    batcher.engine.add_bucket(24)
    with pytest.raises(ValueError, match="not prepared"):
        batcher.submit(list(range(1, 20)), max_new_tokens=2)
    flags.set_flag("serving_lazy_bucket_compile", True)
    lazy_before = obs_metrics.REGISTRY.get(
        "serving_compiles_total").labels(kind="prefill_lazy").value
    try:
        req = batcher.submit(list(range(1, 20)), max_new_tokens=2)
        doc = req.result(timeout=60)
    finally:
        flags.set_flag("serving_lazy_bucket_compile", False)
    assert doc["status"] == "ok"
    code, _h, wf = _http_json(f"{srv.url}/trace/{doc['trace_id']}")
    assert code == 200
    compile_spans = [s for s in wf["spans"]
                     if s["name"] == "serving.compile_bucket"]
    assert len(compile_spans) == 1
    assert compile_spans[0]["attrs"]["bucket"] == 24
    assert compile_spans[0]["attrs"]["lazy"] is True
    assert obs_metrics.REGISTRY.get("serving_compiles_total").labels(
        kind="prefill_lazy").value == lazy_before + 1
    # unknown trace -> 404, not 500
    try:
        urllib.request.urlopen(f"{srv.url}/trace/deadbeef", timeout=10)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_http_traceparent_roundtrip(batcher):
    """POST /serving/generate honors an upstream traceparent and echoes
    it: the request's spans land under the CLIENT'S trace id."""
    srv = obs_server.start_http_server(port=0)
    upstream = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    code, hdrs, doc = _http_json(
        f"{srv.url}/serving/generate",
        body={"prompt": [1, 2, 3], "max_new_tokens": 3},
        headers={"traceparent": upstream})
    assert code == 200
    assert doc["trace_id"] == "ab" * 16
    assert hdrs.get("traceparent", "").startswith("00-" + "ab" * 16)
    wf = tracectx.waterfall("ab" * 16)
    assert wf is not None
    assert "serving.prefill" in [s["name"] for s in wf["spans"]]


import urllib.error  # noqa: E402  (used above)


# --- fleet churn assembly -------------------------------------------------

def _xray_payload(rank, spans, *, perf_epoch, wall, schema_rank=None):
    """An events payload as a worker incarnation would send it: spans
    carry that incarnation's perf_counter clock; the payload's clock
    pair lets the aggregator map them onto the master wall clock."""
    return {
        "schema": obs_fleet.SCHEMA,
        "rank": rank if schema_rank is None else schema_rank,
        "time_unix": wall,
        "perf_counter": perf_epoch,
        "spans": [],
        "flight": None,
        "xray": spans,
    }


def test_fleet_churn_merges_one_waterfall_no_duplicates():
    """A supervisor-restarted worker's spans (new incarnation: fresh
    perf_counter epoch, re-shipped at-least-once window) merge into the
    SAME request waterfall with no clock-skew or duplicate-span
    artifacts."""
    agg = obs_fleet.FleetAggregator()
    tid = "f" * 32
    wall0 = 1_700_000_000.0

    def span(sid, name, perf_start, dur, rank, parent=None):
        return {"name": name, "trace_id": tid, "span_id": sid,
                "parent_id": parent, "kind": "work", "rank": rank,
                "start_unix": 12345.0,       # sender-local wall clock:
                "start_perf": perf_start,    # deliberately bogus — the
                "dur": dur}                  # aggregator must NOT use it

    # incarnation 1 of rank 1: perf epoch ~1000s, router span + prefill
    gen1 = [span("a" * 16, "serving.request", 1000.0, 0.5, 0),
            span("b" * 16, "serving.prefill", 1000.1, 0.1, 1,
                 parent="a" * 16)]
    agg.ingest_events(_xray_payload(1, gen1, perf_epoch=1001.0,
                                    wall=wall0 + 1.0),
                      recv_unix=wall0 + 1.01)
    # worker killed + respawned: incarnation 2's perf_counter restarts
    # near ZERO, it re-ships the prefill span (at-least-once) plus the
    # decode/retire spans it produced after the restart
    gen2 = [span("b" * 16, "serving.prefill", 1000.1, 0.1, 1,
                 parent="a" * 16),           # duplicate (old clock!)
            span("c" * 16, "serving.decode", 2.0, 0.2, 1,
                 parent="a" * 16),
            span("d" * 16, "serving.retire", 2.3, 0.0, 1,
                 parent="a" * 16)]
    # CAVEAT: the duplicate re-shipped span carries incarnation-1 perf
    # times but rides incarnation-2's clock pair; its absolute position
    # is garbage either way — what matters is it DEDUPES, not where it
    # lands.  Ship it in its own payload with the old clock pair first
    # (the reporter's cursor semantics re-send whole windows).
    agg.ingest_events(_xray_payload(1, gen2[:1], perf_epoch=1001.0,
                                    wall=wall0 + 1.2),
                      recv_unix=wall0 + 1.21)
    agg.ingest_events(_xray_payload(1, gen2[1:], perf_epoch=2.5,
                                    wall=wall0 + 2.0),
                      recv_unix=wall0 + 2.01)

    wf = agg.xray_waterfall(tid)
    assert wf is not None
    assert wf["span_count"] == 4              # b deduped, not doubled
    sids = [s["span_id"] for s in wf["spans"]]
    assert len(sids) == len(set(sids))
    names = [s["name"] for s in wf["spans"]]
    assert names.count("serving.prefill") == 1
    # clock sanity: every span lands within seconds of the master wall
    # clock window, despite incarnation 2's perf epoch restarting at ~0
    # and the bogus sender-local start_unix
    for s in wf["spans"]:
        assert abs(s["start_unix"] - wall0) < 60.0, s
    # and the whole request reads as ONE ordered waterfall
    assert wf["duration_s"] < 60.0
    order = [s["name"] for s in sorted(wf["spans"],
                                       key=lambda s: s["start_unix"])]
    assert order.index("serving.request") == 0
    # malformed spans are dropped, never a 500
    agg.ingest_events(_xray_payload(
        1, [{"name": "bad"}], perf_epoch=3.0, wall=wall0 + 3.0),
        recv_unix=wall0 + 3.01)
    assert agg.xray_waterfall(tid)["span_count"] == 4

    # a worker-shipped SLO capture attaches to the coordinator's
    # waterfall — and survives alone when the spans are gone
    cap = {"reason": "slo_breach", "time_unix": wall0 + 2.5,
           "detail": {"budget_ms": 1.0, "ttft_ms": 7.7},
           "waterfall": {"schema": "paddle_tpu.xray.v1",
                         "trace_id": tid, "span_count": 4,
                         "duration_s": 2.3, "start_unix": wall0,
                         "spans": []}}
    payload = _xray_payload(1, [], perf_epoch=3.0, wall=wall0 + 3.0)
    payload["xray_captures"] = {tid: cap}
    agg.ingest_events(payload, recv_unix=wall0 + 3.01)
    wf2 = agg.xray_waterfall(tid)
    assert wf2["capture"]["reason"] == "slo_breach"
    assert "waterfall" not in wf2["capture"]
    ghost = "e" * 32
    payload2 = _xray_payload(1, [], perf_epoch=3.0, wall=wall0 + 3.0)
    payload2["xray_captures"] = {ghost: cap}
    agg.ingest_events(payload2, recv_unix=wall0 + 3.02)
    # no spans for this trace at the aggregator: the capture's own
    # frozen waterfall serves
    assert agg.xray_waterfall(ghost)["trace_id"] == tid or \
        agg.xray_waterfall(ghost)["schema"] == "paddle_tpu.xray.v1"


# --- on-demand device profiling -------------------------------------------

def test_profile_endpoint_bounded_capture(tmp_path, batcher):
    srv = obs_server.start_http_server(port=0)
    logdir = str(tmp_path / "xprof")
    code, _h, doc = _http_json(f"{srv.url}/profile",
                               body={"duration_s": 0.4,
                                     "logdir": logdir})
    assert code == 200
    assert doc["status"] in ("started", "unavailable")
    if doc["status"] == "started":
        assert doc["duration_s"] == pytest.approx(0.4)
        # a second capture while one runs: busy, never a crash
        code2, _h2, doc2 = _http_json(f"{srv.url}/profile",
                                      body={"duration_s": 0.4})
        assert code2 == 200 and doc2["status"] == "busy"
        deadline = time.time() + 10
        while time.time() < deadline:
            _c, _h3, st = _http_json(f"{srv.url}/profile")
            if not st["running"]:
                break
            time.sleep(0.1)
        assert not st["running"]
        assert st["last"]["done"] is True
        assert os.path.isdir(logdir)
    # malformed duration -> 400
    try:
        _http_json(f"{srv.url}/profile", body={"duration_s": "soon"})
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


# --- overhead: interleaved A/B on the decode loop -------------------------

def test_tracing_overhead_interleaved_ab(batcher):
    """Tracing ON vs OFF, alternating per round so machine drift hits
    both arms equally: the serving decode loop may not slow by more
    than 10% (plus a floor for sub-ms CPU noise)."""
    def one_round():
        t0 = time.perf_counter()
        reqs = [batcher.submit([1 + i, 2, 3], max_new_tokens=6)
                for i in range(3)]
        for r in reqs:
            r.result(timeout=30)
        return time.perf_counter() - t0

    one_round()                              # warm both paths
    on, off = [], []
    for i in range(8):
        flags.set_flag("request_tracing", i % 2 == 0)
        try:
            (on if i % 2 == 0 else off).append(one_round())
        finally:
            flags.set_flag("request_tracing", True)
    med_on, med_off = np.median(on), np.median(off)
    # 10% bound with an absolute floor: a 40ms round varying by 2ms of
    # scheduler noise must not flake the gate (median: one descheduled
    # round can't poison either arm)
    assert med_on <= med_off * 1.10 + 0.005, (med_on, med_off)
