"""Fleet-telemetry worker — one rank of the 2-rank end-to-end test in
tests/test_fleet.py.

Run:  python tests/dist_fleet_worker.py <master_host:port> <world> <rank> <out.json>

Each process trains a tiny seeded classifier for 3 steps through the
REAL Trainer loop (so trainer_steps_total, the step-anatomy histograms
and trace spans are all produced by the instrumented path, not faked),
then pushes one FleetReporter flush to the coordinator the TEST process
owns (TaskMaster + FleetAggregator + HTTP endpoint), dumps its own
per-rank chrome trace for the offline-merge check, and exits.  The test
then makes ONE urllib scrape of the coordinator's /metrics and asserts
the fleet-summed counters.
"""
import json
import os
import sys

# repo root on sys.path (PYTHONPATH must stay unset — axon plugin quirk,
# tests/conftest.py)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np

STEPS = 3
N, D_IN, CLS = 8, 6, 3


def main():
    master, world, rank, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    host, port = master.rsplit(":", 1)

    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import profiler
    from paddle_tpu.observability import fleet, metrics as obs_metrics

    def train_func():
        x = layers.data("x", [D_IN], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        p = layers.fc(layers.fc(x, size=8, act="relu"), size=CLS,
                      act="softmax")
        return layers.mean(layers.cross_entropy(p, y))

    def reader():
        rng = np.random.RandomState(rank)
        for _ in range(STEPS):
            yield [(rng.rand(D_IN).astype("float32"),
                    np.array([rng.randint(CLS)], "int64"))
                   for _ in range(N)]

    profiler.reset_profiler()
    profiler.enable_profiler()
    trainer = pt.Trainer(train_func=train_func,
                         optimizer_func=lambda: pt.optimizer.SGD(0.1),
                         place=pt.CPUPlace())
    trainer.train(num_epochs=1, event_handler=lambda e: None,
                  reader=reader, feed_order=["x", "y"])
    trainer.stop()
    profiler.disable_profiler()

    # per-rank chrome dump for the offline --merge-traces path (same
    # files a profiled dist run would leave behind)
    trace_path = os.path.join(os.path.dirname(out_path),
                              f"trace_rank{rank}.json")
    profiler.export_chrome_trace(trace_path)

    # one synchronous report (metrics snapshot + every recorded span),
    # then the closing report stop() sends so the coordinator retires
    # this rank instead of flagging it stale after we exit
    reporter = fleet.FleetReporter(host, int(port), rank=rank)
    reporter.flush()
    reporter.stop()

    steps = obs_metrics.REGISTRY.get("trainer_steps_total").value
    anatomy = {
        name: {"sum": obs_metrics.REGISTRY.get(name).sum,
               "count": obs_metrics.REGISTRY.get(name).count}
        for name in ("trainer_step_seconds", "trainer_data_wait_seconds",
                     "trainer_host_seconds", "trainer_device_seconds")}
    with open(out_path, "w") as f:
        json.dump({"rank": rank, "steps": steps, "anatomy": anatomy,
                   "trace_path": trace_path}, f)
    print("FLEET_WORKER_OK", rank)


if __name__ == "__main__":
    main()
