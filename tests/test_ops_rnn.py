"""RNN op family: numpy-forward + finite-difference grad checks (the
reference's per-op contract, unittests/op_test.py:132, applied to
operators/lstm_op.cc / gru_op.cc / lstm_unit_op.cc / gru_unit_op.cc), plus
the stacked-LSTM model (benchmark/fluid/stacked_dynamic_lstm.py)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models

from op_test import OpTest


def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_lstm(x, w, h0=None, c0=None, mask=None, reverse=False):
    """x [B,T,4H] pre-projected, w [H,4H]; gate order i,f,g,o."""
    B, T, H4 = x.shape
    H = H4 // 4
    h = np.zeros((B, H)) if h0 is None else h0.copy()
    c = np.zeros((B, H)) if c0 is None else c0.copy()
    hs = np.zeros((B, T, H))
    ts = range(T - 1, -1, -1) if reverse else range(T)
    for t in ts:
        gates = x[:, t] + h @ w
        i, f, g, o = np.split(gates, 4, axis=-1)
        i, f, o = _sig(i), _sig(f), _sig(o)
        g = np.tanh(g)
        c_new = f * c + i * g
        h_new = o * np.tanh(c_new)
        if mask is not None:
            m = mask[:, t:t + 1]
            h_new = m * h_new + (1 - m) * h
            c_new = m * c_new + (1 - m) * c
        h, c = h_new, c_new
        hs[:, t] = h
    return hs, h, c


def np_gru(x, w, h0=None, mask=None, reverse=False):
    """x [B,T,3H], w [H,3H] = [u|r blocks, c block]."""
    B, T, H3 = x.shape
    H = H3 // 3
    h = np.zeros((B, H)) if h0 is None else h0.copy()
    hs = np.zeros((B, T, H))
    w_g, w_c = w[:, :2 * H], w[:, 2 * H:]
    ts = range(T - 1, -1, -1) if reverse else range(T)
    for t in ts:
        xg, xc = x[:, t, :2 * H], x[:, t, 2 * H:]
        ur = _sig(xg + h @ w_g)
        u, r = ur[:, :H], ur[:, H:]
        cand = np.tanh(xc + (r * h) @ w_c)
        h_new = u * h + (1 - u) * cand
        if mask is not None:
            m = mask[:, t:t + 1]
            h_new = m * h_new + (1 - m) * h
        h = h_new
        hs[:, t] = h
    return hs, h


class TestLSTM(OpTest):
    op_type = "lstm"
    reverse = False

    def setup(self):
        rng = np.random.RandomState(7)
        B, T, H = 2, 3, 2
        x = rng.randn(B, T, 4 * H).astype("float32") * 0.5
        w = rng.randn(H, 4 * H).astype("float32") * 0.5
        hs, h, c = np_lstm(x.astype("float64"), w.astype("float64"),
                           reverse=self.reverse)
        self.inputs = {"Input": x, "Weight": w}
        self.attrs = {"is_reverse": self.reverse}
        self.outputs = {"Hidden": hs, "LastH": h, "LastC": c}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Input", "Weight"], "Hidden",
                        max_relative_error=0.01)


class TestLSTMReverse(TestLSTM):
    reverse = True

    def test_grad(self):
        pass  # same math reversed; forward covers the flip


class TestLSTMMasked(OpTest):
    op_type = "lstm"

    def setup(self):
        rng = np.random.RandomState(3)
        B, T, H = 2, 4, 2
        x = rng.randn(B, T, 4 * H).astype("float32") * 0.5
        w = rng.randn(H, 4 * H).astype("float32") * 0.5
        mask = np.array([[1, 1, 1, 0], [1, 1, 0, 0]], dtype="float32")
        h0 = rng.randn(B, H).astype("float32") * 0.1
        c0 = rng.randn(B, H).astype("float32") * 0.1
        hs, h, c = np_lstm(x.astype("float64"), w.astype("float64"),
                           h0.astype("float64"), c0.astype("float64"), mask)
        self.inputs = {"Input": x, "Weight": w, "H0": h0, "C0": c0,
                       "Mask": mask}
        self.outputs = {"Hidden": hs, "LastH": h, "LastC": c}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestGRU(OpTest):
    op_type = "gru"

    def setup(self):
        rng = np.random.RandomState(11)
        B, T, H = 2, 3, 2
        x = rng.randn(B, T, 3 * H).astype("float32") * 0.5
        w = rng.randn(H, 3 * H).astype("float32") * 0.5
        hs, h = np_gru(x.astype("float64"), w.astype("float64"))
        self.inputs = {"Input": x, "Weight": w}
        self.outputs = {"Hidden": hs, "LastH": h}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Input", "Weight"], "Hidden",
                        max_relative_error=0.01)


class TestGRUMasked(OpTest):
    op_type = "gru"

    def setup(self):
        rng = np.random.RandomState(5)
        B, T, H = 2, 4, 2
        x = rng.randn(B, T, 3 * H).astype("float32") * 0.5
        w = rng.randn(H, 3 * H).astype("float32") * 0.5
        mask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]], dtype="float32")
        hs, h = np_gru(x.astype("float64"), w.astype("float64"), mask=mask)
        self.inputs = {"Input": x, "Weight": w, "Mask": mask}
        self.outputs = {"Hidden": hs, "LastH": h}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestLSTMUnit(OpTest):
    op_type = "lstm_unit"

    def setup(self):
        rng = np.random.RandomState(13)
        B, H = 3, 4
        x = rng.randn(B, 4 * H).astype("float32")
        c_prev = rng.randn(B, H).astype("float32")
        i, f, g, o = np.split(x.astype("float64"), 4, axis=-1)
        c = _sig(f + 0.5) * c_prev + _sig(i) * np.tanh(g)
        h = _sig(o) * np.tanh(c)
        self.inputs = {"X": x, "C_prev": c_prev}
        self.attrs = {"forget_bias": 0.5}
        self.outputs = {"C": c, "H": h}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["X"], "H", max_relative_error=0.01)


class TestGRUUnit(OpTest):
    op_type = "gru_unit"

    def setup(self):
        rng = np.random.RandomState(17)
        B, H = 3, 4
        x = rng.randn(B, 3 * H).astype("float32")
        h = rng.randn(B, H).astype("float32")
        w = rng.randn(H, 3 * H).astype("float32") * 0.5
        xf, hf, wf = (a.astype("float64") for a in (x, h, w))
        ur = _sig(xf[:, :2 * H] + hf @ wf[:, :2 * H])
        u, r = ur[:, :H], ur[:, H:]
        cand = np.tanh(xf[:, 2 * H:] + (r * hf) @ wf[:, 2 * H:])
        h_new = u * hf + (1 - u) * cand
        self.inputs = {"Input": x, "HiddenPrev": h, "Weight": w}
        self.outputs = {"Hidden": h_new, "Gate": np.concatenate([u, r], -1),
                        "ResetHiddenPrev": r * hf}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Input"], "Hidden", max_relative_error=0.01)


# ---------------------------------------------------------------------------
# layer + model tier
# ---------------------------------------------------------------------------

def test_dynamic_lstm_layer_runs():
    words = layers.data("x", [5, 16], dtype="float32")
    hidden, last_c = layers.dynamic_lstm(words, size=16)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    h, c = exe.run(pt.default_main_program(),
                   feed={"x": rng.randn(2, 5, 16).astype("float32")},
                   fetch_list=[hidden, last_c])
    assert h.shape == (2, 5, 4)
    assert c.shape == (2, 4)
    assert np.isfinite(h).all()


def test_dynamic_gru_layer_runs():
    x = layers.data("x", [5, 12], dtype="float32")
    hidden = layers.dynamic_gru(x, size=4)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    h, = exe.run(pt.default_main_program(),
                 feed={"x": rng.randn(2, 5, 12).astype("float32")},
                 fetch_list=[hidden])
    assert h.shape == (2, 5, 4)
    assert np.isfinite(h).all()


def test_stacked_lstm_model_trains():
    """The LSTM benchmark config (reference benchmark/README.md:103-119):
    loss must decrease on a separable synthetic batch."""
    feeds, avg_loss, acc, pred = models.stacked_lstm.build_train_net(
        dict_dim=200, seq_len=12, emb_dim=16, hidden_dim=16, num_layers=2)
    opt = pt.optimizer.Adam(learning_rate=1e-2)
    opt.minimize(avg_loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = models.stacked_lstm.make_fake_batch(8, dict_dim=200, seq_len=12)
    losses = []
    for _ in range(6):
        out, = exe.run(pt.default_main_program(), feed=feed,
                       fetch_list=[avg_loss])
        losses.append(float(out))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_dynamic_lstm_gru_under_amp():
    """AMP regression: a f32 mask used to promote the bf16 scan carry
    and break tracing (scan carry dtype mismatch); the mask is now cast
    to the activation dtype."""
    from paddle_tpu.core import flags
    flags.set_flag("amp_bf16", True)
    try:
        pt.reset_default_programs()
        words = layers.data("words", [8], dtype="int64")
        mask = layers.data("mask", [8], dtype="float32")
        emb = layers.embedding(words, size=[30, 8])
        proj = layers.fc(emb, size=32, num_flatten_dims=2,
                         bias_attr=False)
        h, _ = layers.dynamic_lstm(proj, size=32, mask=mask)
        assert h.shape is not None          # shape inference survived
        proj2 = layers.fc(emb, size=24, num_flatten_dims=2,
                          bias_attr=False)
        g = layers.dynamic_gru(proj2, size=8, mask=mask)
        loss = layers.mean(h) + layers.mean(g)
        exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
        exe.run(pt.default_startup_program())
        rng = np.random.RandomState(0)
        out, = exe.run(pt.default_main_program(),
                       feed={"words": rng.randint(0, 30, (2, 8)),
                             "mask": np.ones((2, 8), "f4")},
                       fetch_list=[loss])
        assert np.isfinite(float(np.asarray(out).ravel()[0]))
    finally:
        flags.set_flag("amp_bf16", False)
