"""Multi-host trainer worker — the reference's dist_mnist contract
(/root/reference/python/paddle/fluid/tests/unittests/test_dist_base.py:212
spawns localhost trainer subprocesses running dist_*.py models through the
REAL framework stack; :502 checks per-step loss parity vs a local run).

Run:  python tests/dist_worker.py <coordinator> <world> <rank> <out.json>

Each process:
  * joins the jax.distributed world via parallel/env.init_distributed_env
    (the gen_nccl_id-equivalent rendezvous, contributing 1 CPU device),
  * builds the SAME seeded classifier Program via the layers DSL,
  * applies DistributeTranspiler(trainers=world) — the nccl2-mode rewrite
    inserting (c_allreduce_sum, 1/N scale) per gradient,
  * trains it with Executor(mesh=<global 2-device mesh>) — shard_map
    executes the collectives over the cross-process axis,
  * reports per-step losses plus the final fc weight.

tests/test_dist_env.py asserts loss parity against a single-process run
of the identical program and bit-equality of weights across ranks.
"""
import json
import os
import sys

# repo root on sys.path (PYTHONPATH must stay unset — axon plugin quirk,
# tests/conftest.py)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np

SEED = 1234
N, D_IN, HID, CLS = 16, 20, 32, 4


def make_batch():
    rng = np.random.RandomState(0)
    x = rng.randn(N, D_IN).astype("float32")
    y = rng.randint(0, CLS, (N, 1)).astype("int64")
    return x, y


def build_program(pt, layers):
    main, startup = pt.Program(), pt.Program()
    main.random_seed = SEED
    startup.random_seed = SEED
    with pt.program_guard(main, startup):
        x = layers.data("x", [D_IN])
        y = layers.data("y", [1], dtype="int64")
        h = layers.fc(x, size=HID, act="relu", name="fc1")
        p = layers.fc(h, size=CLS, act="softmax", name="fc2")
        loss = layers.mean(layers.cross_entropy(p, y))
        pt.optimizer.SGD(learning_rate=0.5).minimize(loss)
    return main, startup, loss


def train_steps(exe, prog, loss, steps=5):
    x, y = make_batch()
    losses = []
    for _ in range(steps):
        out, = exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss])
        losses.append(float(np.mean(np.asarray(out))))
    return losses


def main():
    coordinator, world, rank, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.parallel import env as penv

    ok = penv.init_distributed_env(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
    assert ok, "init_distributed_env returned False"
    assert jax.process_count() == world
    devices = jax.devices()
    assert len(devices) >= world, devices

    main_p, startup, loss = build_program(pt, layers)
    t = pt.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=rank, program=main_p, trainers=world)
    prog = t.get_trainer_program()

    mesh = Mesh(np.array(devices[:world]), ("data",))
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup)
    losses = train_steps(exe, prog, loss)

    wname = main_p.all_parameters()[0].name
    w = exe.scope.find_var(wname)
    assert w is not None, exe.scope.var_names()
    w_host = np.asarray(w.addressable_data(0))   # replicated param
    result = {"rank": rank, "losses": losses,
              "w_sum": float(np.abs(w_host).sum()),
              "w_head": w_host.ravel()[:8].tolist()}
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("WORKER_OK", rank)


if __name__ == "__main__":
    main()
