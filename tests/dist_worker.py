"""Worker for the multi-host harness test (the reference's pattern:
unittests/test_dist_base.py:212 spawns localhost trainer subprocesses).

Run:  python tests/dist_worker.py <coordinator> <world> <rank> <out.json>

Each process contributes its local CPU device to the global mesh via
parallel/env.init_distributed_env (the gen_nccl_id-equivalent rendezvous),
then trains a tiny DP linear model with an explicit grad psum and reports
per-step losses + final weights.
"""
import json
import os
import sys

# repo root on sys.path (PYTHONPATH must stay unset — axon plugin quirk,
# tests/conftest.py)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np


def main():
    coordinator, world, rank, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    import jax
    from paddle_tpu.parallel import env as penv

    ok = penv.init_distributed_env(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
    assert ok, "init_distributed_env returned False"
    assert jax.process_count() == world
    devices = jax.devices()
    assert len(devices) >= world, devices

    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devices[:world]), ("data",))
    B_loc, D = 4, 3
    rng = np.random.RandomState(0)
    # deterministic GLOBAL batch; this process feeds its slice
    x_all = rng.randn(world * B_loc, D).astype("float32")
    y_all = (x_all @ np.array([[1.0], [-2.0], [0.5]], "float32")
             ).astype("float32")
    sl = slice(rank * B_loc, (rank + 1) * B_loc)
    xs = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data", None)), x_all[sl])
    ys = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data", None)), y_all[sl])

    def device_step(w, x, y):
        def loss_fn(w):
            pred = x @ w
            return jnp.sum((pred - y) ** 2) / (world * B_loc)

        lp, g = jax.value_and_grad(loss_fn)(w)
        g = lax.psum(g, "data")
        return w - 0.1 * g, lax.psum(lp, "data")

    step = jax.jit(jax.shard_map(
        device_step, mesh=mesh,
        in_specs=(P(), P("data", None), P("data", None)),
        out_specs=(P(), P()), check_vma=False))

    w = jnp.zeros((D, 1), jnp.float32)
    losses = []
    for _ in range(5):
        w, loss = step(w, xs, ys)
        losses.append(float(jax.block_until_ready(loss)))
    result = {"rank": rank, "losses": losses,
              "w": np.asarray(w).ravel().tolist()}
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("WORKER_OK", rank)


if __name__ == "__main__":
    main()
