"""Perfscope (ISSUE 16, observability/perfscope.py): roofline
attribution, collective-bubble accounting, and the perf-regression
watch.

Covers the acceptance matrix: a comms-heavy 2-device dp step
classified comms-bound with perf_bubble_fraction naming grad_psum, a
matmul-dominated executor step classified compute-bound, an
artificially slowed phase firing the built-in perf_regression rule
with phase + exemplar trace id in the alert context, flag-off
byte-identical outputs with zero step-path compiles, the CLI
exit-code contract and --self-test smoke, the GET /perf route, the
fleet doc-row reconstruction, plus the satellites that ride along:
histogram_quantiles edge cases, the bench_gate --trend roofline-bound
column (flip = named regression), and jit_cache --warm (validation
matrix + cross-process zero-compile warm start).
"""
import json
import os
import struct
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.framework import jit_cache
from paddle_tpu.observability import alerts
from paddle_tpu.observability import bench_gate
from paddle_tpu.observability import forensics
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import perfscope
from paddle_tpu.observability import server as obs_server
from paddle_tpu.parallel import hybrid, topology

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BOUNDS = {"compute", "memory", "comms", "input", "host"}


def _tot(name):
    m = obs_metrics.REGISTRY.get(name)
    return 0.0 if m is None else m.total()


def _gauge(name, **labels):
    m = obs_metrics.REGISTRY.get(name)
    assert m is not None, f"gauge {name} not registered"
    return m.labels(**labels).value


def _fc_program(size=512, batch=512):
    """A matmul-dominated forward program: batch x size @ size x size
    puts the arithmetic intensity far above any CPU ridge point."""
    pt.reset_default_programs()
    x = layers.data("x", [size], dtype="float32")
    h = layers.fc(x, size=size, bias_attr=False)
    loss = layers.mean(h)
    feed = {"x": np.ones((batch, size), "float32")}
    return pt.default_main_program(), loss, feed


def _dp_cfg():
    """The comms-heavy workload: pure dp=2, one microbatch, d_model
    large enough that grad_psum dominates the step's communication."""
    return hybrid.HybridConfig(vocab_size=64, seq_len=8, d_model=256,
                               n_heads=4, n_layers=2, d_ff=512,
                               n_microbatches=1, remat=False)


# =========================================================================
# tentpole: roofline verdicts
# =========================================================================

def test_comms_bound_dp_step_names_grad_psum():
    """A 2-device dp step on a slow modeled interconnect is classified
    comms-bound; the bubble accounting names grad_psum from the
    collective:* scopes; building the model is an abstract jaxpr trace
    — no executor compile and no forensics record."""
    flags.set_flag("perfscope", True)
    # CPU default priors make this tiny model memory-bound; a 0.5 GB/s
    # interconnect models the regime the acceptance targets (on real
    # hardware the measured ICI prior plays this role)
    flags.set_flag("perf_ici_gbps", 0.5)
    cfg = _dp_cfg()
    mesh = topology.make_hybrid_mesh(dp=2, pp=1, tp=1)
    params = hybrid.init_params(mesh, cfg, seed=0)
    opt = hybrid.init_opt_state(params)
    step = hybrid.build_train_step(mesh, cfg)
    tokens, labels_ = hybrid.make_fake_lm_batch(cfg, global_batch=4)

    compiles = _tot("executor_compile_total")
    nrec = len(forensics.compile_log())
    params, opt, loss = step(params, opt, tokens, labels_)
    assert np.isfinite(float(loss))
    assert _tot("executor_compile_total") == compiles
    assert len(forensics.compile_log()) == nrec

    doc = perfscope.status_doc()
    ph = doc["phases"]["hybrid.step"]
    assert ph["bound"] == "comms"
    assert ph["exposed_comm_seconds"] > 0
    assert ph["comm_share"] > 0
    # the dominant collective is the dp gradient all-reduce, named
    # from its collective:grad_psum scope
    assert "grad_psum" in doc["collectives"]
    col = doc["collectives"]["grad_psum"]
    assert col["bytes"] > 0 and col["bubble_fraction"] > 0
    assert col["bytes"] == max(
        c["bytes"] for c in doc["collectives"].values())
    assert _gauge("perf_bubble_fraction", collective="grad_psum") > 0
    assert obs_metrics.REGISTRY.get(
        "perf_comm_exposed_seconds").value > 0
    assert _gauge("perf_bound", phase="hybrid.step",
                  bound="comms") == 1.0


def test_matmul_step_is_compute_bound():
    """The matmul-dominated executor program lands above the ridge
    point -> compute-bound, and explain(perf=True) renders the same
    verdict as a section."""
    flags.set_flag("perfscope", True)
    main, loss, feed = _fc_program()
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(pt.default_startup_program())
    exe.run(main, feed=feed, fetch_list=[loss])

    progs = perfscope.status_doc()["programs"]
    assert progs, "note_dispatch must record executor programs"
    verdicts = [d for d in progs.values() if d.get("bound")]
    assert any(d["bound"] == "compute" for d in verdicts)
    best = max(verdicts, key=lambda d: d.get("arith_intensity", 0.0))
    assert best["bound"] == "compute"
    assert best["arith_intensity"] > best_ridge()
    assert "fuse" in best["recommend"] or "mfu" in best["recommend"]

    rep = exe.explain(main, feed=feed, fetch_list=[loss], perf=True)
    assert rep["perf"]["bound"] == "compute"
    assert rep["perf"]["ridge_intensity"] > 0
    assert rep["perf"]["device"]["platform"] == "cpu"


def best_ridge():
    return perfscope.device_params()["ridge_intensity"]


def test_report_and_top_sinks():
    flags.set_flag("perfscope", True)
    perfscope.note_phase("trainer.step", 0.020)
    perfscope.note_phase("serving.decode", 0.005)
    lines = perfscope.report(top=5)
    assert lines[0].startswith("perfscope: platform=")
    body = "\n".join(lines)
    assert "trainer.step" in body and "serving.decode" in body
    # top=1 keeps only the biggest sink
    one = "\n".join(perfscope.report(top=1))
    assert "trainer.step" in one and "serving.decode" not in one


# =========================================================================
# tentpole: regression watch -> built-in perf_regression rule
# =========================================================================

def _slow_phase(phase="trainer.step"):
    """4 fast samples freeze the baseline, 4 slow ones trip the x5
    ratio past the x2 factor."""
    flags.set_flag("perfscope", True)
    flags.set_flag("perf_baseline_window", 4)
    flags.set_flag("perf_regression_factor", 2.0)
    for _ in range(4):
        perfscope.note_phase(phase, 0.010, trace_id="t-fast")
    for _ in range(4):
        perfscope.note_phase(phase, 0.050, trace_id="t-slow")


def test_regression_watch_ratio_and_status():
    _slow_phase()
    doc = perfscope.status_doc()
    rec = doc["phases"]["trainer.step"]
    assert rec["regressed"] is True
    assert rec["regression_ratio"] == pytest.approx(5.0)
    assert rec["last_trace_id"] == "t-slow"
    assert rec["baseline_s"] == pytest.approx(0.010)
    assert doc["regression"]["last"]["phase"] == "trainer.step"
    assert doc["regression"]["last"]["trace_id"] == "t-slow"
    assert _gauge("perf_regression_ratio",
                  phase="trainer.step") == pytest.approx(5.0)


def test_perf_regression_rule_fires_with_phase_and_exemplar():
    """The built-in perf_regression Watchtower rule fires on the
    breaching perf_regression_ratio series and its context names the
    offending phase + an exemplar trace id of a slow step."""
    _slow_phase()
    rules = [r for r in alerts.default_rules()
             if r.name == "perf_regression"]
    assert rules, "perfscope on -> default rules include perf_regression"
    eng = alerts.AlertEngine(rules)
    eng.evaluate(obs_metrics.REGISTRY.to_json(), now=100.0)
    st = eng.status_doc()
    assert "perf_regression" in st["firing"]
    act = [a for a in st["active"] if a["rule"] == "perf_regression"
           and a["state"] == "firing"]
    assert act and act[0]["labels"].get("phase") == "trainer.step"
    ctx = act[0]["context"]
    assert ctx["phase"] == "trainer.step"
    assert ctx["regression_ratio"] >= 2.0
    assert ctx["exemplar_trace_ids"] == ["t-slow"]
    assert ctx["baseline_seconds"] == pytest.approx(0.010)
    assert ctx["recent_seconds"] == pytest.approx(0.050)


def test_perf_regression_rule_absent_when_flag_off():
    assert flags.get_flag("perfscope") is False
    assert not [r for r in alerts.default_rules()
                if r.name == "perf_regression"]


# =========================================================================
# tentpole: flag-off invariance + zero step-path compiles
# =========================================================================

def test_flag_off_byte_identical_and_no_new_compiles():
    """Flipping perfscope ON does not perturb outputs, does not enter
    the compile key (the warm program is re-used: zero new compiles)
    and the default explain() report carries no perf section."""
    assert flags.get_flag("perfscope") is False
    main, loss, feed = _fc_program(size=16, batch=8)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(pt.default_startup_program())
    out_off = exe.run(main, feed=feed, fetch_list=[loss])[0]
    rep_off = exe.explain(main, feed=feed, fetch_list=[loss],
                          perf=True)
    assert "perf" not in rep_off          # flag off: no section at all
    compiles = _tot("executor_compile_total")
    nrec = len(forensics.compile_log())

    flags.set_flag("perfscope", True)
    out_on = exe.run(main, feed=feed, fetch_list=[loss])[0]
    assert np.array_equal(out_off, out_on)
    assert _tot("executor_compile_total") == compiles
    assert len(forensics.compile_log()) == nrec
    # and explain without perf=True stays perf-free even when enabled
    rep_on = exe.explain(main, feed=feed, fetch_list=[loss])
    assert "perf" not in rep_on


# =========================================================================
# tentpole: CLI + /perf route + fleet doc rows
# =========================================================================

def test_cli_exit_codes_and_self_test(capsys):
    assert perfscope.main(["--self-test"]) == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines()
            if ln.startswith("PERFSCOPE_SELF_TEST ")][-1]
    doc = json.loads(line[len("PERFSCOPE_SELF_TEST "):])
    assert doc["ok"] is True and all(doc["checks"].values())
    # self-test restores flag state: still disabled -> rc 2
    assert flags.get_flag("perfscope") is False
    assert perfscope.main([]) == 2
    flags.set_flag("perfscope", True)
    perfscope.note_phase("trainer.step", 0.01)
    assert perfscope.main([]) == 0
    assert perfscope.main(["--doc"]) == 0
    out = capsys.readouterr().out
    assert "trainer.step" in out
    assert '"schema": "paddle_tpu.perf.v1"' in out


def test_http_perf_route():
    flags.set_flag("perfscope", True)
    perfscope.note_phase("trainer.step", 0.01)
    srv = obs_server.start_http_server(port=0)
    with urllib.request.urlopen(f"{srv.url}/perf", timeout=10) as r:
        doc = json.loads(r.read())
    assert doc["schema"] == "paddle_tpu.perf.v1"
    assert doc["source"] == "local"       # no aggregator: local half
    assert "trainer.step" in doc["phases"]
    assert doc["enabled"] is True


def test_rows_from_metrics_doc_reconstructs_rooflines():
    """fleet.perf_rows() rebuilds per-rank roofline rows from shipped
    metrics documents — the gauges alone carry enough to recover
    bound/mfu/intensity and the bubble fractions."""
    flags.set_flag("perfscope", True)
    perfscope.note_step("trainer.step", device_s=0.01,
                        model={"flops": 2 * 512.0 ** 3,
                               "bytes_accessed": 3 * 512.0 * 512 * 4,
                               "comm": {"grad_psum": 1e5}})
    rows = perfscope.rows_from_metrics_doc(
        obs_metrics.REGISTRY.to_json())
    ph = rows["phases"]["trainer.step"]
    assert ph["bound"] == "compute"
    assert ph["mfu"] > 0 and ph["achieved_flops"] > 0
    assert ph["arith_intensity"] > 10
    assert rows["bubble_fraction"]["grad_psum"] > 0
    assert rows["comm_exposed_seconds"] >= 0
    # empty / absent documents degrade to empty rows, not a crash
    assert perfscope.rows_from_metrics_doc(None) == {
        "phases": {}, "comm_exposed_seconds": 0.0,
        "bubble_fraction": {}}


# =========================================================================
# satellite: histogram_quantiles edge cases
# =========================================================================

def test_histogram_quantiles_missing_and_empty():
    assert obs_metrics.histogram_quantiles("no_such_hist",
                                           [0.5]) is None
    obs_metrics.histogram("edge_empty_seconds", "edge",
                          buckets=[0.1, 1.0])
    # registered but zero observations -> None, not zeros
    assert obs_metrics.histogram_quantiles("edge_empty_seconds",
                                           [0.5, 0.99]) is None
    # a counter is not a histogram
    obs_metrics.counter("edge_not_a_hist_total", "edge").inc()
    assert obs_metrics.histogram_quantiles("edge_not_a_hist_total",
                                           [0.5]) is None


def test_histogram_quantiles_single_bucket():
    h = obs_metrics.histogram("edge_single_seconds", "edge",
                              buckets=[0.25])
    for _ in range(10):
        h.observe(0.1)
    q = obs_metrics.histogram_quantiles("edge_single_seconds",
                                        [0.5, 0.99])
    assert q["p50"] == 0.25 and q["p99"] == 0.25
    assert q["count"] == 10
    assert q["mean"] == pytest.approx(0.1)


def test_histogram_row_quantiles_all_mass_in_overflow():
    """Every observation past the largest bound: the quantile clamps
    to the largest finite bucket bound (the honest lower estimate)
    rather than inventing +Inf."""
    row = {"buckets": {"0.1": 0, "1.0": 0}, "overflow": 5,
           "count": 5, "sum": 50.0}
    q = obs_metrics.histogram_row_quantiles(row, [0.5, 0.99])
    assert q["p50"] == 1.0 and q["p99"] == 1.0
    assert q["mean"] == pytest.approx(10.0)
    # no observations in the row -> None (the fleet-merged doc path)
    assert obs_metrics.histogram_row_quantiles(
        {"buckets": {"0.1": 0}, "count": 0, "sum": 0.0},
        [0.5]) is None
    # bucketless degenerate row clamps to 0.0 instead of raising
    q0 = obs_metrics.histogram_row_quantiles(
        {"buckets": {}, "count": 3, "sum": 3.0}, [0.5])
    assert q0["p50"] == 0.0


def test_histogram_row_quantiles_matches_registry_path():
    """One interpolation implementation: the registry helper and the
    raw doc-row helper agree on the same data."""
    h = obs_metrics.histogram("edge_agree_seconds", "edge",
                              buckets=[0.05, 0.1, 0.5])
    for v in (0.01, 0.02, 0.07, 0.2, 0.4):
        h.observe(v)
    via_name = obs_metrics.histogram_quantiles("edge_agree_seconds",
                                               [0.5, 0.9])
    fam = obs_metrics.REGISTRY.to_json()["metrics"][
        "edge_agree_seconds"]
    row = fam["series"][0]
    via_row = obs_metrics.histogram_row_quantiles(row, [0.5, 0.9])
    assert via_name == via_row


# =========================================================================
# satellite: bench_gate --trend roofline-bound column
# =========================================================================

def _trend_rec(value, mfu=None, bound=None):
    return {"lm_tokens_per_sec": {"value": value, "mfu": mfu,
                                  "bound": bound}}


def test_trend_bound_flip_is_named_regression():
    res = bench_gate.trend([
        ("r01", _trend_rec(100.0, mfu=0.30, bound="compute")),
        ("r02", _trend_rec(104.0, mfu=0.31, bound="compute")),
        ("r03", _trend_rec(105.0, mfu=0.31, bound="comms")),
    ])
    rows = {r["metric"]: r for r in res["rows"]}
    brow = rows["lm_tokens_per_sec.bound"]
    assert brow["status"] == "regression"
    assert brow["flip"] == "compute->comms"
    assert brow["newest"] == "comms"
    assert "lm_tokens_per_sec.bound" in res["regressions"]
    assert res["ok"] is False
    # throughput itself improved: the value row stays ok — the flip
    # alone fails the gate
    assert rows["lm_tokens_per_sec"]["status"] == "ok"
    assert rows["lm_tokens_per_sec.mfu"]["status"] == "ok"


def test_trend_bound_first_appearance_is_ok():
    """A bound appearing for the first time in the newest record (the
    first post-perfscope release) is not a flip."""
    res = bench_gate.trend([
        ("r01", _trend_rec(100.0)),
        ("r02", _trend_rec(101.0, bound="compute")),
    ])
    rows = {r["metric"]: r for r in res["rows"]}
    assert rows["lm_tokens_per_sec.bound"]["status"] == "ok"
    assert res["ok"] is True
    # and a bound that disappears (perfscope off for one run) is not
    # a flip either — None never participates
    res2 = bench_gate.trend([
        ("r01", _trend_rec(100.0, bound="compute")),
        ("r02", _trend_rec(101.0)),
    ])
    rows2 = {r["metric"]: r for r in res2["rows"]}
    assert rows2["lm_tokens_per_sec.bound"]["status"] == "ok"
    # records with no bound anywhere grow no .bound row at all
    res3 = bench_gate.trend([("r01", _trend_rec(100.0)),
                             ("r02", _trend_rec(101.0))])
    assert not [r for r in res3["rows"]
                if r["metric"].endswith(".bound")]


def test_trend_load_record_bound_variants():
    # driver summary rows carry bound through
    rec = bench_gate.load_trend_record(
        {"summary": {"m": {"value": 7.0, "mfu": 0.2,
                           "bound": "memory"}}})
    assert rec["m"] == {"value": 7.0, "mfu": 0.2, "bound": "memory"}
    # bare pre-summary record (the BENCH_r01 layout)
    rec = bench_gate.load_trend_record({"metric": "m", "value": 3.0})
    assert rec["m"]["bound"] is None
    # plain {metric: value} maps never carry a bound
    rec = bench_gate.load_trend_record({"m": 5.0})
    assert rec["m"]["bound"] is None


def test_trend_cli_over_committed_records():
    """The committed BENCH_r01..r05 records predate perfscope: the
    trend CLI must stay green over them (bound None everywhere, no
    .bound rows, rc 0)."""
    paths = [os.path.join(REPO, f"BENCH_r0{i}.json")
             for i in range(1, 6)]
    records = []
    for p in paths:
        with open(p) as f:
            records.append((os.path.basename(p)[:-len(".json")],
                            bench_gate.load_trend_record(json.load(f))))
    res = bench_gate.trend(records, allow_missing=True)
    assert res["ok"] is True
    assert not [r for r in res["rows"]
                if r["metric"].endswith(".bound")]
    assert bench_gate.main(
        ["--trend", *paths, "--allow-missing"]) == 0


# =========================================================================
# satellite: jit_cache --warm
# =========================================================================

def _seed_entries(src, n=3):
    """Compile + store n distinct tiny executables into src."""
    import jax
    import jax.numpy as jnp
    flags.set_flag("jit_cache_dir", str(src))
    x = jnp.arange(4, dtype=jnp.float32)
    names = []
    for i in range(n):
        fn = jax.jit(lambda v, k=float(i + 1): v * k)
        compiled = fn.lower(x).compile()
        comps = {"probe": f"warm-{i}"}
        khash = jit_cache.entry_key("executor_step", comps)
        assert jit_cache.store("executor_step", khash, comps, compiled)
        names.append(khash)
    return names


def _entry_paths(d):
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.endswith(".jc"))


def test_warm_validation_matrix(tmp_path):
    """warm() copies only entries that pass the full load() validation:
    a bit-flipped body is counted corrupt, a foreign-build header is
    counted stale, and neither lands in the destination; re-warming
    counts the survivor as already present."""
    src, dst = tmp_path / "src", tmp_path / "dst"
    _seed_entries(src, n=3)
    paths = _entry_paths(str(src))
    assert len(paths) == 3
    # corrupt: flip a bit inside the pickled body of entry 0
    raw = bytearray(open(paths[0], "rb").read())
    raw[-3] ^= 0x40
    open(paths[0], "wb").write(bytes(raw))
    # stale: rewrite entry 1's header with a foreign env fingerprint
    raw = open(paths[1], "rb").read()
    fixed = len(jit_cache._MAGIC) + 4
    (hlen,) = struct.unpack("<I", raw[fixed - 4:fixed])
    header = json.loads(raw[fixed:fixed + hlen].decode())
    header["env"] = "foreign-build"
    hb = json.dumps(header).encode()
    open(paths[1], "wb").write(
        raw[:fixed - 4] + struct.pack("<I", len(hb)) + hb
        + raw[fixed + hlen:])

    res = jit_cache.warm(str(src), str(dst))
    assert (res["copied"], res["stale"], res["corrupt"],
            res["present"]) == (1, 1, 1, 0)
    assert res["bytes"] > 0
    assert len(_entry_paths(str(dst))) == 1
    # skipped entries are never deleted from the source
    assert len(_entry_paths(str(src))) == 3
    # idempotent: the survivor is now present, nothing re-copies
    res2 = jit_cache.warm(str(src), str(dst))
    assert res2["copied"] == 0 and res2["present"] == 1
    # the warmed entry actually loads and runs in the destination
    flags.set_flag("jit_cache_dir", str(dst))
    rows = jit_cache.ls()
    assert len(rows) == 1
    comps = rows[0]["components"]
    back = jit_cache.load("executor_step",
                          jit_cache.entry_key("executor_step", comps),
                          comps)
    assert back is not None


def test_warm_cli_exit_and_counts(tmp_path, capsys):
    src, dst = tmp_path / "src", tmp_path / "dst"
    _seed_entries(src, n=2)
    assert jit_cache.main(["--dir", str(dst),
                           "--warm", str(src)]) == 0
    out = capsys.readouterr().out
    assert "copied 2 entr" in out
    assert len(_entry_paths(str(dst))) == 2
    # warming an empty/missing source copies nothing but exits 0
    assert jit_cache.main(["--dir", str(dst),
                           "--warm", str(tmp_path / "nope")]) == 0


def _run_probe(cache_dir):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PTPU_JIT_CACHE_DIR"] = str(cache_dir)
    env.pop("PTPU_CHAOS_SPEC", None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.framework.jit_cache",
         "--restart-probe", "lm"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("RESTART_PROBE ")]
    assert proc.returncode == 0 and lines, (proc.stdout, proc.stderr)
    return json.loads(lines[-1][len("RESTART_PROBE "):])


def test_warm_cross_process_zero_compile(tmp_path):
    """The fleet warmup story: rank 0 compiles into a shared dir, a
    new replica warms its own dir from it BEFORE first use and then
    records ZERO XLA compiles — with bit-identical losses."""
    shared, local = tmp_path / "shared", tmp_path / "local"
    cold = _run_probe(shared)
    assert cold["executor_compile_total"] > 0
    assert jit_cache.main(["--dir", str(local),
                           "--warm", str(shared)]) == 0
    warm = _run_probe(local)
    assert warm["executor_compile_total"] == 0
    assert warm["jit_cache_hits_total"] >= 2
    assert warm["jit_cache_errors_total"] == 0
    assert warm["losses"] == cold["losses"]


# =========================================================================
# satellite: conftest isolation
# =========================================================================

def test_state_isolated_between_tests():
    """conftest resets perfscope state + flag around every test: no
    phases/programs survive from the earlier tests in this module."""
    assert flags.get_flag("perfscope") is False
    doc = perfscope.status_doc()
    assert doc["phases"] == {} and doc["programs"] == {}
    assert doc["collectives"] == {}
    assert doc["regression"]["last"] is None
