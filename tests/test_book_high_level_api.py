"""High-level-api book tier: book examples driven through the Trainer
event loop with dataset readers (ref tests/book/high-level-api/ — the
same examples re-expressed via fluid.Trainer)."""
import itertools

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dataset, layers
from paddle_tpu.models import book
from paddle_tpu.reader import decorator


def run_trainer(train_func, feed_order, reader, epochs=2, lr=0.01):
    losses = []

    def handler(event):
        if isinstance(event, pt.EndStepEvent) and event.metrics:
            losses.append(float(np.asarray(event.metrics[0]).ravel()[0]))

    trainer = pt.Trainer(train_func,
                         lambda: pt.optimizer.SGD(learning_rate=lr),
                         place=pt.CPUPlace())
    trainer.train(num_epochs=epochs, event_handler=handler,
                  reader=reader, feed_order=feed_order)
    assert losses and np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses[:6]
    return trainer


def test_fit_a_line_via_trainer_uci_reader():
    """ref high-level-api/fit_a_line: Trainer + uci_housing reader."""
    def train_func():
        feeds, avg_cost, pred = book.fit_a_line(x_dim=13)
        return avg_cost

    reader = decorator.batch(
        lambda: itertools.islice(dataset.uci_housing.train()(), 128), 16)
    run_trainer(train_func, ["x", "y"], reader, epochs=3, lr=0.05)


def test_word2vec_via_trainer_imikolov_reader():
    """ref high-level-api/word2vec: Trainer + imikolov N-gram reader."""
    word_dict = dataset.imikolov.build_dict()
    dict_size = len(word_dict)

    def train_func():
        feeds, avg_cost, pred = book.word2vec(dict_size=dict_size,
                                              embed_size=16,
                                              hidden_size=32)
        return avg_cost

    def samples():
        for s in itertools.islice(
                dataset.imikolov.train(word_dict, 5)(), 256):
            yield ([s[0]], [s[1]], [s[2]], [s[3]], [s[4]])

    reader = decorator.batch(samples, 32)
    run_trainer(train_func,
                ["word_0", "word_1", "word_2", "word_3", "next_word"],
                reader, epochs=2, lr=0.1)


def test_recognize_digits_via_trainer_mnist_reader():
    """ref high-level-api/recognize_digits: Trainer + mnist reader +
    save/load inference round trip."""
    from paddle_tpu import models

    def train_func():
        feeds, avg_loss, acc, pred = models.lenet.build_train_net(
            net_fn=models.lenet.multilayer_perceptron)
        return [avg_loss, acc]

    def samples():
        for img, lbl in itertools.islice(dataset.mnist.train()(), 256):
            yield (np.asarray(img, "float32").reshape(1, 28, 28),
                   [int(lbl)])

    reader = decorator.batch(samples, 32)
    trainer = run_trainer(train_func, ["img", "label"], reader,
                          epochs=2, lr=0.1)
    # params survive a save/load round trip
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        trainer.save_params(d)
        t2 = pt.Trainer(train_func,
                        lambda: pt.optimizer.SGD(learning_rate=0.1),
                        place=pt.CPUPlace(), param_path=d)
        m = t2.test(reader=reader, feed_order=["img", "label"])
        assert np.isfinite(np.asarray(m[0])).all()


def test_image_classification_via_trainer_cifar_reader():
    """ref book/image_classification (test_image_classification_train):
    conv net + cifar-10 reader through the Trainer loop."""
    from paddle_tpu import models

    def train_func():
        img = layers.data("img", [3, 32, 32])
        label = layers.data("label", [1], dtype="int64")
        pred = models.resnet.resnet_cifar10(img, class_dim=10, depth=20)
        avg_loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        return [avg_loss, acc]

    def samples():
        for img, lbl in itertools.islice(dataset.cifar.train10()(), 128):
            yield (np.asarray(img, "float32").reshape(3, 32, 32) / 255.0,
                   [int(lbl)])

    reader = decorator.batch(samples, 32)
    run_trainer(train_func, ["img", "label"], reader, epochs=3, lr=0.05)


def test_understand_sentiment_via_trainer_imdb_reader():
    """ref book/understand_sentiment: stacked LSTM + imdb reader
    (dense+mask sequence plane)."""
    from paddle_tpu import models

    T = 64

    def train_func():
        feeds, avg_loss, acc, pred = \
            models.stacked_lstm.build_train_net(
                dict_dim=5000, seq_len=T, emb_dim=32, hidden_dim=32,
                num_layers=2)
        return [avg_loss, acc]

    word_idx = dataset.imdb.word_dict()

    def samples():
        for sent, lbl in itertools.islice(
                dataset.imdb.train(word_idx)(), 192):
            ids = np.zeros(T, "int64")
            mask = np.zeros(T, "float32")
            n = min(len(sent), T)
            ids[:n] = sent[:n]
            mask[:n] = 1.0
            yield (ids, mask, [int(lbl)])

    reader = decorator.batch(samples, 32)
    run_trainer(train_func, ["words", "mask", "label"], reader,
                epochs=3, lr=0.05)
