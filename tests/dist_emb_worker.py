"""Cross-process sharded-embedding worker ON THE PROGRAM PLANE — the
reference's distributed lookup table (parameter_prefetch.cc:1: trainers
fetch rows from the pserver owning them; sparse grads pushed back) as a
user-facing Program: DeepFM built with its embedding Parameter carrying
``ParamAttr(sharding=("model", None))``, trained via
``Executor(mesh=...)`` over a cross-process "model" axis.  XLA GSPMD
serves the rows and routes the scatter-add gradients across processes —
no direct shard_map/collective calls in user code.

Run:  python tests/dist_emb_worker.py <coordinator> <world> <rank> <out>

Each rank reports per-step losses and the |.|-sum of its LOCAL table
shard; the test checks loss parity against a single-process run of the
identical program and that the disjoint shard sums add up to the
single-process table's total.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np

SEED = 11
STEPS = 3
BATCH = 8


def build_program(pt, models):
    pt.reset_default_programs()
    main = pt.default_main_program()
    startup = pt.default_startup_program()
    main.random_seed = SEED
    startup.random_seed = SEED
    cfg = models.deepfm.DeepFMConfig(
        num_field=6, vocab_size=80, embed_dim=4, fc_sizes=(16,),
        sparse_shard_axis="model")
    feeds, avg_cost, prob = models.deepfm.build_train_net(cfg)
    pt.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    return main, startup, avg_cost, cfg


def sharded_param_names(main):
    """ALL row-sharded tables (DeepFM has two: the [V,1] first-order
    weights fm_w1 and the [V,K] embedding fm_emb)."""
    names = [p.name for p in main.all_parameters()
             if getattr(p, "sharding", None)
             and p.sharding[0] == "model"]
    assert len(names) == 2, names
    return names


def train_steps(models, exe, main, loss, cfg):
    feed = models.deepfm.make_fake_batch(cfg, BATCH)
    losses = []
    for _ in range(STEPS):
        out, = exe.run(main, feed=feed, fetch_list=[loss])
        losses.append(float(np.mean(np.asarray(out))))
    return losses


def main():
    coordinator, world, rank, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.parallel import env as penv

    ok = penv.init_distributed_env(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
    assert ok and jax.process_count() == world

    main_p, startup, loss, cfg = build_program(pt, models)
    devices = np.array(jax.devices()[:world]).reshape(1, world)
    mesh = Mesh(devices, ("data", "model"))
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup)
    losses = train_steps(models, exe, main_p, loss, cfg)

    shards = {}
    for wname in sharded_param_names(main_p):
        table = exe.scope.find_var(wname)
        # THIS rank's rows — the test reassembles the full tables
        shards[wname] = np.asarray(table.addressable_data(0)).tolist()
    result = {"rank": rank, "losses": losses, "shards": shards}
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("EMB_WORKER_OK", rank)


if __name__ == "__main__":
    main()
