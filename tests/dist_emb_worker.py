"""Cross-process sharded-embedding worker — the reference's distributed
lookup table at PROCESS scope (parameter_prefetch.cc:1: trainers fetch
rows from the pserver owning them; sparse grads pushed back).

Run:  python tests/dist_emb_worker.py <coordinator> <world> <rank> <out>

The [V, D] table is row-sharded over a cross-process "model" mesh axis
(world processes x 1 CPU device).  Every step: masked-gather + psum
lookup (rows served by their owning rank over the collective fabric, the
RPC-prefetch equivalent), then a SelectedRows-style sparse scatter-add
update of each rank's own shard.  The worker reports its LOCAL shard
after 3 steps; the test reassembles the table and checks it against a
host numpy reference.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np

V, D, B, F = 16, 4, 4, 2
LR, STEPS = 0.1, 3


def make_ids(step):
    rng = np.random.RandomState(100 + step)
    return rng.randint(0, V, (B, F)).astype("int32")


def init_table():
    rng = np.random.RandomState(7)
    return rng.randn(V, D).astype("float32")


def reference():
    """Host numpy ground truth of the training loop."""
    table = init_table()
    losses = []
    for s in range(STEPS):
        ids = make_ids(s)
        rows = table[ids]                        # [B, F, D]
        losses.append(float(0.5 * np.sum(rows ** 2)))
        np.add.at(table, ids.reshape(-1),
                  -LR * rows.reshape(-1, D))     # duplicate ids accumulate
    return table, losses


def main():
    coordinator, world, rank, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel import env as penv
    from paddle_tpu.parallel.sharded_embedding import (
        row_sharded_lookup, sparse_scatter_update)

    ok = penv.init_distributed_env(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
    assert ok and jax.process_count() == world
    devices = np.array(jax.devices()[:world]).reshape(1, world)
    mesh = Mesh(devices, ("data", "model"))

    table_np = init_table()
    table = jax.make_array_from_callback(
        (V, D), NamedSharding(mesh, P("model", None)),
        lambda idx: table_np[idx])

    def device_step(local_table, ids):
        rows = row_sharded_lookup(local_table, ids, "model")
        loss = 0.5 * jnp.sum(rows ** 2)          # d(loss)/d(rows) = rows
        new_table = sparse_scatter_update(
            local_table, ids, rows, LR, axis_name="model",
            data_axis="data")
        return new_table, lax.psum(loss, "data")

    step = jax.jit(jax.shard_map(
        device_step, mesh=mesh,
        in_specs=(P("model", None), P("data", None)),
        out_specs=(P("model", None), P()), check_vma=False))

    losses = []
    for s in range(STEPS):
        ids_np = make_ids(s)
        ids = jax.make_array_from_callback(
            ids_np.shape, NamedSharding(mesh, P("data", None)),
            lambda idx: ids_np[idx])
        table, loss = step(table, ids)
        losses.append(float(jax.block_until_ready(loss)))

    shard = np.asarray(table.addressable_data(0))
    result = {"rank": rank, "losses": losses,
              "shard": shard.tolist(),
              "rows_per_rank": V // world}
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("EMB_WORKER_OK", rank)


if __name__ == "__main__":
    main()
