"""Memscope (ISSUE 18, observability/memscope.py): live HBM
attribution, OOM forensics, and KV-cache occupancy accounting.

Covers the acceptance matrix: flag-off bitwise invariance through a
real checkpointing Trainer run (losses AND final weights byte-equal,
frozen compile counters/forensics), the census planes/owners over the
executor scope with the legacy device_memory_* gauges riding the same
path, predicted-vs-measured peak reconciliation on CPU (verdict inside
the documented factor-8 band, surfaced by explain(memory=True)), the
KV reserved-vs-written ledger under direct slot math / mid-decode
retire+backfill / the 8-stream loadgen soak, the chaos memory.alloc
site -> flight bundle + firing hbm_pressure alert joined by
``incident``, plus the satellites: the memory_usage_calc cross-check
against the cost model for the bundled transformer-LM and resnet,
bench.py's peak-HBM row + the bench_gate lower-is-better *_bytes
direction and --trend subseries, the CLI/--self-test contract, the
GET /memory route (local and fleet-merged), and conftest isolation.
"""
import importlib.util
import json
import os
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models, observability, serving
from paddle_tpu.contrib import memory_usage_calc
from paddle_tpu.core import flags
from paddle_tpu.framework import executor as executor_mod
from paddle_tpu.observability import alerts
from paddle_tpu.observability import bench_gate
from paddle_tpu.observability import fleet
from paddle_tpu.observability import flight
from paddle_tpu.observability import forensics
from paddle_tpu.observability import incident
from paddle_tpu.observability import memscope
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import server as obs_server
from paddle_tpu.resilience import chaos
from paddle_tpu.serving import loadgen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tot(name):
    m = obs_metrics.REGISTRY.get(name)
    return 0.0 if m is None else m.total()


def _val(name):
    m = obs_metrics.REGISTRY.get(name)
    assert m is not None, f"gauge {name} not registered"
    return m.value


def _gauge(name, **labels):
    m = obs_metrics.REGISTRY.get(name)
    assert m is not None, f"gauge {name} not registered"
    return m.labels(**labels).value


def _train_program(opt="adam"):
    """Tiny fc regression step in the GLOBAL scope (what the census
    attributes): Adam so the accumulator-naming split has both a
    params and an optimizer_state plane to find."""
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, bias_attr=False, name="fc")
    loss = layers.mean(layers.square_error_cost(pred, y))
    if opt == "adam":
        pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
    else:
        pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(4, 4).astype("float32"),
            "y": rng.randn(4, 1).astype("float32")}
    return pt.default_main_program(), loss, feed


def _batches(n, bs=4, seed=0):
    rng = np.random.RandomState(seed)
    return [[(rng.randn(4).astype("float32"),
              rng.randn(1).astype("float32")) for _ in range(bs)]
            for _ in range(n)]


# --- shared tiny LM + decode engine (compiled ONCE per module), the
# --- test_serving construction verbatim so KV slab shapes are real ----

@pytest.fixture(scope="module")
def lm():
    pt.reset_default_programs()
    from paddle_tpu.framework import executor as em
    scope = em.Scope()
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=97, tgt_vocab_size=97, max_length=32,
        n_layer=2, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    feeds, cost, logits = models.transformer.build_lm_net(
        cfg, seq_len=24, is_test=True, fused_attention=False,
        fused_head=False)
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    pt.default_startup_program().random_seed = 3
    exe.run(pt.default_startup_program())
    params = serving.extract_lm_params(pt.default_main_program(),
                                       scope, cfg)
    engine = serving.DecodeEngine(cfg, params, max_batch=4, max_len=32,
                                  prompt_buckets=(8, 16))
    engine.prepare()
    return SimpleNamespace(cfg=cfg, engine=engine)


@pytest.fixture
def fresh_engine(lm):
    lm.engine.reset()
    return lm.engine


@pytest.fixture
def batcher(fresh_engine):
    b = serving.ContinuousBatcher(fresh_engine, queue_limit=64)
    b.start()
    serving.attach(b)
    yield b
    serving.reset()


# =========================================================================
# tentpole: flag-off bitwise invariance (checkpointing Trainer run)
# =========================================================================

def _trainer_run(ckroot):
    """One checkpointing Trainer run from scratch: fresh programs +
    fresh global scope, fixed data/seeds.  Returns (loss_bytes,
    weight_bytes, compile_delta, forensics_delta) — everything the
    invariance contract compares bitwise."""
    pt.reset_default_programs()
    executor_mod._global_scope = executor_mod.Scope()

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, bias_attr=False, name="fc")
        return layers.mean(layers.square_error_cost(pred, y))

    cfg = pt.CheckpointConfig(ckroot, max_num_checkpoints=2,
                              epoch_interval=1, step_interval=2)
    t = pt.Trainer(train_func,
                   lambda: pt.optimizer.SGD(learning_rate=0.05),
                   place=pt.CPUPlace(), checkpoint_config=cfg)
    data = _batches(6)
    losses = []

    def handler(e):
        if type(e).__name__ == "EndStepEvent" and e.metrics:
            losses.append(np.asarray(e.metrics[0]).tobytes())

    compiles = _tot("executor_compile_total")
    nrec = len(forensics.compile_log())
    for _ in range(2):
        t.train(num_epochs=1, event_handler=handler,
                reader=lambda: iter(data), feed_order=["x", "y"])
    wname, = [n for n in t.scope.var_names() if n.endswith(".w_0")]
    w = np.asarray(t.scope.find_var(wname)).tobytes()
    return (b"".join(losses), w,
            _tot("executor_compile_total") - compiles,
            len(forensics.compile_log()) - nrec)


def test_flag_off_bitwise_invariance_checkpointing_trainer(tmp_path):
    """Flipping memscope ON must not perturb a real checkpointing
    Trainer run: losses and final weights stay BYTE-identical and the
    compile counter / forensics log grow by exactly the same amount
    (nothing entered a compile key)."""
    assert flags.get_flag("memscope") is False
    base = _trainer_run(str(tmp_path / "a"))
    again = _trainer_run(str(tmp_path / "b"))
    assert again == base, "trainer run must be deterministic off->off"

    flags.set_flag("memscope", True)
    on = _trainer_run(str(tmp_path / "c"))
    assert on == base, "memscope=True must be byte-identical"
    # and the flag-on run actually measured: the trainer's
    # record_device_memory boundary + the executor dispatch hook both
    # route through sample(), so the census saw the training state
    doc = memscope.status_doc()
    assert doc["planes"].get("params", 0) > 0
    assert doc["last_sample"] is not None


def test_explain_has_no_memory_section_unless_asked():
    main, loss, feed = _train_program(opt="sgd")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    # flag off: no section even when explicitly requested
    rep = exe.explain(main, feed=feed, fetch_list=[loss], memory=True)
    assert "memory" not in rep
    flags.set_flag("memscope", True)
    # enabled but not asked: default explain stays memory-free
    rep = exe.explain(main, feed=feed, fetch_list=[loss])
    assert "memory" not in rep


# =========================================================================
# tentpole: census planes/owners + legacy gauge unification
# =========================================================================

def test_census_attributes_params_and_optimizer_state():
    flags.set_flag("memscope", True)
    flags.set_flag("memscope_topk", 64)
    main, loss, feed = _train_program(opt="adam")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    exe.run(main, feed=feed, fetch_list=[loss])

    doc = memscope.status_doc()
    planes = doc["planes"]
    assert planes.get("params", 0) > 0
    assert planes.get("optimizer_state", 0) > 0
    assert planes.get("executor_feeds", 0) > 0
    # Adam keeps two moments (+ scalar power terms) per param: the
    # optimizer plane outweighs the params plane
    assert planes["optimizer_state"] > planes["params"]
    # owners are named: the fc weight and an adam accumulator both
    # resolve through the scope claims
    names = [o["name"] for o in doc["owners"] if o["name"]]
    assert any(n.endswith(".w_0") for n in names)
    assert any("moment" in n for n in names)
    by_plane = {o["name"]: o["plane"] for o in doc["owners"]
                if o["name"]}
    assert all(p == "optimizer_state" for n, p in by_plane.items()
               if "moment" in n or "_pow" in n)
    # gauges mirror the doc
    assert _gauge("mem_resident_bytes",
                  plane="params") == planes["params"]
    assert _gauge("mem_resident_bytes",
                  plane="optimizer_state") == planes["optimizer_state"]
    assert _val("device_memory_live_bytes") == doc["live_bytes"]


def test_record_device_memory_is_the_same_path():
    """The PR 1 trainer watermark entrypoint delegates to sample():
    one call refreshes BOTH the legacy device_memory_* gauges and,
    when enabled, the census."""
    import jax.numpy as jnp
    keep = jnp.ones((32, 32), jnp.float32)   # noqa: F841 — stays live
    live = observability.record_device_memory()
    assert live >= keep.nbytes
    assert _val("device_memory_live_bytes") == live
    assert _val("device_memory_peak_bytes") >= live
    # flag off: no census happened
    assert memscope.status_doc()["planes"] == {}
    flags.set_flag("memscope", True)
    live2 = observability.record_device_memory()
    doc = memscope.status_doc()
    assert doc["live_bytes"] == live2
    assert doc["last_sample"]["reason"] == "boundary"
    assert doc["planes"]


# =========================================================================
# tentpole: predicted-vs-measured reconciliation
# =========================================================================

def test_peak_ratio_within_documented_band_on_cpu():
    """A megabyte-scale matmul step: its own state dominates the live
    set, so measured-vs-predicted lands inside the documented factor-8
    band regardless of what small arrays earlier tests left alive."""
    flags.set_flag("memscope", True)
    pt.reset_default_programs()
    x = layers.data("x", [512], dtype="float32")
    h = layers.fc(x, size=512, bias_attr=False)
    loss = layers.mean(h)
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    feed = {"x": np.ones((256, 512), "float32")}
    main = pt.default_main_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    exe.run(main, feed=feed, fetch_list=[loss])

    factor = float(flags.get_flag("memscope_ratio_factor"))
    assert factor == 8.0          # the documented tolerance
    rep = exe.explain(main, feed=feed, fetch_list=[loss], memory=True)
    mem = rep["memory"]
    assert mem["predicted_peak_bytes"] > (1 << 20)
    assert mem["ratio"] is not None
    assert mem["verdict"] == "ok"
    assert 1.0 / factor <= mem["ratio"] <= factor
    assert mem["measured_high_water_bytes"] > 0
    assert mem["ratio_factor"] == factor
    assert mem["components"]["argument"] is not None
    assert mem["planes"].get("params", 0) > 0
    # the dispatch record behind the section, and its gauge series
    recs = {k: r for k, r in memscope.status_doc()["programs"].items()
            if r.get("ratio") == mem["ratio"]}
    assert recs, "explain must surface the train step's own record"
    label, rec = next(iter(recs.items()))
    assert rec["dispatches"] == 1
    # dispatch reconciles against the analytic view while explain may
    # surface the XLA cost model's peak — same order of magnitude
    assert rec["predicted_peak_bytes"] == pytest.approx(
        mem["predicted_peak_bytes"], rel=0.5)
    assert _gauge("mem_peak_ratio",
                  program=label) == pytest.approx(rec["ratio"])


def test_verdict_band_edges():
    import jax.numpy as jnp
    flags.set_flag("memscope", True)
    flags.set_flag("memscope_ratio_factor", 2.0)
    keep = jnp.ones((64, 64), jnp.float32)  # noqa: F841 — stays live
    memscope.sample()
    live = float(memscope.status_doc()["live_bytes"])
    assert live >= keep.nbytes
    # predicted far above measured -> over_predicted; far below ->
    # under_predicted (the drift verdicts explain() surfaces)
    memscope.note_dispatch("edge.over", cost=SimpleNamespace(
        label="edge.over", peak_hbm_bytes=live * 100.0))
    memscope.note_dispatch("edge.under", cost=SimpleNamespace(
        label="edge.under", peak_hbm_bytes=live / 100.0))
    progs = memscope.status_doc()["programs"]
    assert progs["edge.over"]["verdict"] == "over_predicted"
    assert progs["edge.under"]["verdict"] == "under_predicted"
    # no cost model at all -> the record stays honest about it
    memscope.note_dispatch("edge.none", cost=None)
    assert memscope.status_doc()["programs"]["edge.none"][
        "verdict"] == "unpredicted"


# =========================================================================
# tentpole: KV occupancy ledger
# =========================================================================

def test_kv_occupancy_direct_slot_math(lm, fresh_engine):
    flags.set_flag("memscope", True)
    eng = fresh_engine
    cfg = lm.cfg
    # bytes per written position: K and V planes, n_layer x n_head x
    # head_dim float32 each
    bpp = cfg.n_layer * cfg.n_head * (cfg.d_model // cfg.n_head) * 4 * 2
    eng.start_sequence(0, [1, 2, 3, 4])
    eng.start_sequence(1, [5, 6, 7, 8, 9, 10])
    doc = memscope.status_doc()["kv"]
    assert doc["bytes_per_position"] == bpp
    assert doc["active_slots"] == 2 and doc["slots"] == 4
    per_slot = doc["slab_bytes"] // 4
    assert doc["reserved_bytes"] == 2 * per_slot == 2 * 32 * bpp
    assert doc["written_bytes"] == (4 + 6) * bpp
    assert doc["waste_fraction"] == pytest.approx(1.0 - 10 / 64)
    # both prompts landed in the 8-token bucket
    assert set(doc["buckets"]) == {"8"}
    assert doc["buckets"]["8"]["slots"] == 2
    # the gauges carry the same ledger
    assert _val("serving_kv_reserved_bytes") == doc["reserved_bytes"]
    assert _val("serving_kv_written_bytes") == doc["written_bytes"]
    assert _val("serving_kv_waste_fraction") == pytest.approx(
        doc["waste_fraction"])
    assert _gauge("serving_kv_bucket_waste_fraction",
                  bucket="8") == pytest.approx(doc["waste_fraction"])
    # one decode step writes one more position per active slot
    eng.decode_step()
    doc = memscope.status_doc()["kv"]
    assert doc["written_bytes"] == (4 + 6 + 2) * bpp
    # the census claims the slabs as the serving_kv plane
    memscope.sample()
    planes = memscope.status_doc()["planes"]
    assert planes.get("serving_kv") == doc["slab_bytes"]


def test_kv_mid_decode_retire_and_backfill(fresh_engine):
    flags.set_flag("memscope", True)
    eng = fresh_engine
    eng.start_sequence(0, [1, 2, 3, 4])
    eng.start_sequence(1, [5, 6, 7, 8, 9, 10])
    eng.decode_step()
    # retire mid-decode and backfill the freed slot with a prompt long
    # enough to land in the OTHER bucket
    eng.retire_slot(0)
    doc = memscope.status_doc()["kv"]
    assert doc["active_slots"] == 1
    eng.start_sequence(0, list(range(1, 13)))
    doc = memscope.status_doc()["kv"]
    assert doc["active_slots"] == 2
    assert set(doc["buckets"]) == {"8", "16"}
    b8, b16 = doc["buckets"]["8"], doc["buckets"]["16"]
    assert b8["slots"] == 1 and b16["slots"] == 1
    bpp = doc["bytes_per_position"]
    assert b16["written_bytes"] == 12 * bpp
    assert b8["written_bytes"] == 7 * bpp
    # per-bucket gauges: the longer prompt wastes less of its slot
    assert b16["waste_fraction"] < b8["waste_fraction"]
    assert _gauge("serving_kv_bucket_waste_fraction",
                  bucket="16") == pytest.approx(b16["waste_fraction"])
    # retiring everything zeroes the ledger but keeps the peak
    eng.retire_slot(0)
    eng.retire_slot(1)
    doc = memscope.status_doc()
    assert doc["kv"]["reserved_bytes"] == 0
    assert doc["kv"]["waste_fraction"] == 0.0
    assert doc["kv_peak_waste_fraction"] > 0.5


def test_kv_waste_under_loadgen_soak(lm, batcher):
    """The acceptance soak: 8 concurrent streams through the
    continuous batcher leave a nonzero peak waste fraction consistent
    with the slot math (prompts >= 4 tokens into 32-position slots
    bound the waste at 1 - 4/32)."""
    flags.set_flag("memscope", True)
    rep = loadgen.run_loadgen(loadgen.inproc_submit(batcher),
                              streams=8, requests_per_stream=3,
                              prompt_len_range=(4, 14),
                              max_new_tokens=8, temperature=0.0,
                              vocab_size=64)
    assert rep["ok"] and rep["counts"]["ok"] == 24
    peak = memscope.status_doc()["kv_peak_waste_fraction"]
    assert peak is not None and peak > 0.0
    assert 0.3 <= peak <= 1.0 - 4.0 / lm.cfg.max_length
    # the final ledger is internally consistent
    doc = memscope.status_doc()["kv"]
    assert doc["written_bytes"] <= doc["reserved_bytes"] or \
        doc["reserved_bytes"] == 0


# =========================================================================
# tentpole: OOM forensics -> flight + hbm_pressure -> incident join
# =========================================================================

def test_hbm_pressure_rule_absent_when_flag_off():
    assert flags.get_flag("memscope") is False
    assert not [r for r in alerts.default_rules()
                if r.name == "hbm_pressure"]
    # and disabled by the threshold knob even when memscope is on
    flags.set_flag("memscope", True)
    flags.set_flag("memscope_pressure_fraction", 0.0)
    assert not [r for r in alerts.default_rules()
                if r.name == "hbm_pressure"]
    flags.set_flag("memscope_pressure_fraction", 0.9)
    assert [r for r in alerts.default_rules()
            if r.name == "hbm_pressure"]


@pytest.mark.chaos
def test_chaos_alloc_failure_flight_alert_incident(tmp_path):
    """The kill chain: a chaos-injected allocation failure at the
    executor dispatch freezes the census into a flight bundle, the
    1-byte HBM budget drives mem_pressure_fraction past the built-in
    hbm_pressure rule (context naming the fattest plane), and
    ``incident`` joins the journal + alert history into one
    timeline."""
    jp = str(tmp_path / "journal.jsonl")
    flags.set_flag("journal_path", jp)
    flags.set_flag("memscope", True)
    flags.set_flag("memscope_hbm_limit_bytes", 1)
    main, loss, feed = _train_program(opt="adam")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    exe.run(main, feed=feed, fetch_list=[loss])

    flags.set_flag("chaos_spec", "memory.alloc=raise:1.0")
    chaos.reset()
    with pytest.raises(chaos.InjectedFault):
        exe.run(main, feed=feed, fetch_list=[loss])
    flags.set_flag("chaos_spec", "")
    chaos.reset()

    # -- flight bundle: census + top owners + the program's cost row
    b = flight.last_bundle()
    assert b is not None and b["reason"] == "memory_alloc_failure"
    mem = b["extra"]["memory"]
    assert mem["where"] == "executor.run"
    assert mem["program"]
    assert mem["cost"] and mem["cost"]["peak_hbm_bytes"] > 0
    census = mem["census"]
    assert census["planes"].get("params", 0) > 0
    assert census["owners"]
    assert census["pressure_fraction"] >= 1.0
    doc = memscope.status_doc()
    assert doc["alloc_failures"] == 1
    assert doc["last_alloc_failure"]["where"] == "executor.run"

    # -- the built-in rule fires and names the fattest plane
    rules = [r for r in alerts.default_rules()
             if r.name == "hbm_pressure"]
    assert rules
    eng = alerts.AlertEngine(rules)
    t0 = time.time()
    eng.evaluate(obs_metrics.REGISTRY.to_json(), now=t0)
    eng.evaluate(obs_metrics.REGISTRY.to_json(), now=t0 + 1.5)
    st = eng.status_doc()
    assert "hbm_pressure" in st["firing"]
    act = [a for a in st["active"] if a["rule"] == "hbm_pressure"
           and a["state"] == "firing"]
    ctx = act[0]["context"]
    assert ctx["pressure_fraction"] >= 1.0
    fattest = max(census["planes"], key=census["planes"].get)
    assert ctx["fattest_plane"] == fattest
    assert ctx["fattest_plane_bytes"] > 0
    assert ctx["top_owner"]["bytes"] > 0
    assert ctx["last_alloc_failure"]["where"] == "executor.run"

    # -- incident joins journal events with the alert history
    events, hist = incident.gather_events([jp], alerts_doc=st)
    w0, w1, sel = incident.resolve_window(events, hist,
                                          alert="hbm_pressure",
                                          pad=30.0)
    rep = incident.build_report(events, hist, w0, w1, sel)
    tl = rep["timeline"]
    kinds = [(e["kind"], e["event"]) for e in tl]
    assert ("memory", "pressure") in kinds
    assert ("memory", "alloc_failure") in kinds
    assert ("chaos", "injected") in kinds
    assert ("alert", "fire") in kinds
    assert kinds.index(("memory", "alloc_failure")) \
        < kinds.index(("alert", "fire"))


@pytest.mark.chaos
def test_chaos_alloc_failure_at_serving_decode(fresh_engine):
    flags.set_flag("memscope", True)
    eng = fresh_engine
    eng.start_sequence(0, [1, 2, 3, 4])
    flags.set_flag("chaos_spec", "memory.alloc=raise:1.0")
    chaos.reset()
    with pytest.raises(chaos.InjectedFault):
        eng.decode_step()
    flags.set_flag("chaos_spec", "")
    chaos.reset()
    b = flight.last_bundle()
    assert b is not None and b["reason"] == "memory_alloc_failure"
    assert b["extra"]["memory"]["where"] == "serving.decode_step"
    assert memscope.status_doc()["alloc_failures"] == 1


# =========================================================================
# tentpole: CLI + /memory route + fleet doc rows
# =========================================================================

def test_cli_exit_codes_and_self_test(capsys):
    assert memscope.main(["--self-test"]) == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines()
            if ln.startswith("MEMSCOPE_SELF_TEST ")][-1]
    doc = json.loads(line[len("MEMSCOPE_SELF_TEST "):])
    assert doc["ok"] is True
    assert doc["kv_waste"] == pytest.approx(0.625)
    # self-test restores flag state: still disabled -> rc 2
    assert flags.get_flag("memscope") is False
    assert memscope.main([]) == 2
    flags.set_flag("memscope", True)
    assert memscope.main([]) == 0
    assert memscope.main(["--doc"]) == 0
    out = capsys.readouterr().out
    assert "memscope census" in out
    assert '"schema": "paddle_tpu.mem.v1"' in out


def test_http_memory_route_local():
    flags.set_flag("memscope", True)
    memscope.sample()
    srv = obs_server.start_http_server(port=0)
    with urllib.request.urlopen(f"{srv.url}/memory", timeout=10) as r:
        doc = json.loads(r.read())
    assert doc["schema"] == "paddle_tpu.mem.v1"
    assert doc["source"] == "local"
    assert doc["enabled"] is True
    assert doc["planes"]
    with urllib.request.urlopen(f"{srv.url}/", timeout=10) as r:
        assert b"/memory" in r.read()


def test_fleet_merged_memory_route():
    flags.set_flag("memscope", True)
    main, loss, feed = _train_program(opt="sgd")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    exe.run(main, feed=feed, fetch_list=[loss])
    local = memscope.status_doc()

    agg = fleet.FleetAggregator(stale_after=60.0)
    agg.ingest("report_metrics",
               {"schema": fleet.SCHEMA, "rank": 0,
                "time_unix": time.time(),
                "perf_counter": time.perf_counter(),
                "steps_total": 1.0,
                "metrics": obs_metrics.REGISTRY.to_json()})
    rows = agg.mem_rows()
    assert set(rows) == {"0"}
    for plane, b in local["planes"].items():
        assert rows["0"]["planes"][plane] == pytest.approx(b)
    assert rows["0"]["live_bytes"] == pytest.approx(local["live_bytes"])

    srv = obs_server.start_http_server(port=0, aggregator=agg)
    with urllib.request.urlopen(f"{srv.url}/memory", timeout=10) as r:
        doc = json.loads(r.read())
    assert doc["source"] == "fleet"
    assert doc["ranks"]["0"]["planes"]


def test_rows_from_metrics_doc_reconstructs_census():
    flags.set_flag("memscope", True)
    flags.set_flag("memscope_hbm_limit_bytes", 1 << 40)
    main, loss, feed = _train_program(opt="sgd")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    exe.run(main, feed=feed, fetch_list=[loss])
    local = memscope.status_doc()

    rows = memscope.rows_from_metrics_doc(obs_metrics.REGISTRY.to_json())
    for plane, b in local["planes"].items():
        assert rows["planes"][plane] == pytest.approx(b)
    assert rows["pressure_fraction"] == pytest.approx(
        local["pressure"]["fraction"])
    assert rows["device"]["host"]["used_bytes"] == pytest.approx(
        local["device"]["host"]["used_bytes"])
    assert rows["peak_ratio"]          # the dispatch published a ratio
    # empty / absent documents degrade to empty rows, not a crash
    assert memscope.rows_from_metrics_doc(None) == {
        "planes": {}, "device": {}, "pressure_fraction": None,
        "peak_ratio": {}, "kv": {}, "live_bytes": None}


# =========================================================================
# satellite: memory_usage_calc cross-check vs the cost model
# =========================================================================

def _explain_cost(loss, feed):
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(pt.default_startup_program())
    rep = exe.explain(pt.default_main_program(), feed=feed,
                      fetch_list=[loss])
    return rep["cost"]


def test_cross_check_transformer_lm():
    """The flagship LM train program: the static walk and the cost
    model agree within the documented factor-8 tolerance on both the
    persistable floor and the activation ceiling."""
    pt.reset_default_programs()
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=97, tgt_vocab_size=97, max_length=32,
        n_layer=2, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    feeds, avg_cost, logits = models.transformer.build_lm_net(
        cfg, seq_len=16, fused_attention=False, fused_head=False)
    pt.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    B = 4
    feed = {"tokens": np.ones((B, 16), "int64"),
            "labels": np.ones((B, 16), "int64")}
    cost = _explain_cost(avg_cost, feed)
    res = memory_usage_calc.cross_check(pt.default_main_program(), B,
                                        cost)
    assert res["tolerance"] == 8.0
    assert res["ok"] is True, res["diverging"]
    assert res["diverging"] == []
    by = {r["component"]: r for r in res["rows"]}
    assert set(by) == {"persistable_vs_argument", "ceiling_vs_peak"}
    for r in by.values():
        assert r["ratio"] is not None
        assert 1 / 8.0 <= r["ratio"] <= 8.0


def test_cross_check_resnet():
    pt.reset_default_programs()
    img = layers.data("img", [3, 32, 32], dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    pred = models.resnet.resnet_cifar10(img, class_dim=10, depth=8)
    loss = layers.mean(layers.cross_entropy(input=pred, label=label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    B = 4
    feed = {"img": np.zeros((B, 3, 32, 32), "float32"),
            "label": np.zeros((B, 1), "int64")}
    cost = _explain_cost(loss, feed)
    res = memory_usage_calc.cross_check(pt.default_main_program(), B,
                                        cost)
    assert res["ok"] is True, res["diverging"]
    # a too-tight tolerance names the diverging component instead of
    # failing silently
    tight = memory_usage_calc.cross_check(pt.default_main_program(), B,
                                          cost, tolerance=1.01)
    assert tight["ok"] is False
    assert tight["diverging"]
    assert all(c in ("persistable_vs_argument", "ceiling_vs_peak")
               for c in tight["diverging"])


def test_cross_check_degenerate_and_errors():
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    loss = layers.mean(x)
    # no cost model at all: no signal, no verdict, never a failure
    res = memory_usage_calc.cross_check(pt.default_main_program(), 2,
                                        None)
    assert res["ok"] is True
    assert all(r["ratio"] is None for r in res["rows"])
    with pytest.raises(ValueError):
        memory_usage_calc.memory_usage_bytes(pt.default_main_program(),
                                             0)
    lo, hi, unit = memory_usage_calc.memory_usage(
        pt.default_main_program(), 2)
    assert hi >= lo >= 0 and unit in ("B", "KB", "MB", "GB")


# =========================================================================
# satellite: bench peak-HBM row + bench_gate *_bytes direction
# =========================================================================

def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "ptpu_bench_module", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_row_carries_peak_hbm_bytes():
    bench = _load_bench()
    main, loss, feed = _train_program(opt="sgd")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    row = {"metric": "probe_tokens_per_sec", "unit": "tokens/s",
           "value": 1.0, "vs_baseline": 1.0}
    bench._attach_cost(row, exe, main, feed, loss, dt=0.01)
    assert row["peak_hbm_bytes"] > 0
    bench._record_row_metrics(row)
    assert _gauge("bench_peak_hbm_bytes",
                  metric="probe_tokens_per_sec") == row["peak_hbm_bytes"]


def test_bytes_metrics_are_lower_is_better():
    assert bench_gate.lower_is_better("peak_hbm_bytes") is True
    assert bench_gate.lower_is_better("bench_peak_hbm_bytes") is True
    assert bench_gate.lower_is_better("lm_tokens_per_sec") is False
    # direction end to end: a fatter candidate is a named regression
    res = bench_gate.gate({"m_bytes": 100.0}, {"m_bytes": 200.0},
                          tolerance=0.15)
    assert res["ok"] is False and res["regressions"] == ["m_bytes"]
    # and a slimmer one is an improvement, not a regression
    res = bench_gate.gate({"m_bytes": 200.0}, {"m_bytes": 100.0},
                          tolerance=0.15)
    assert res["ok"] is True


def _hbm_rec(value, peak=None):
    return {"m_tokens_per_sec": {"value": value,
                                 "peak_hbm_bytes": peak}}


def test_trend_peak_hbm_regression_is_named():
    res = bench_gate.trend([
        ("r01", _hbm_rec(100.0, peak=1.0e6)),
        ("r02", _hbm_rec(104.0, peak=1.1e6)),
        ("r03", _hbm_rec(110.0, peak=2.0e6)),
    ])
    rows = {r["metric"]: r for r in res["rows"]}
    hrow = rows["m_tokens_per_sec.peak_hbm_bytes"]
    assert hrow["status"] == "regression"
    assert hrow["best"] == 1.0e6 and hrow["newest"] == 2.0e6
    assert "m_tokens_per_sec.peak_hbm_bytes" in res["regressions"]
    assert res["ok"] is False
    # throughput itself improved: memory alone fails the gate
    assert rows["m_tokens_per_sec"]["status"] == "ok"


def test_trend_peak_hbm_first_appearance_and_missing():
    # first post-memscope record: not a regression
    res = bench_gate.trend([("r01", _hbm_rec(100.0)),
                            ("r02", _hbm_rec(101.0, peak=1.0e6))])
    rows = {r["metric"]: r for r in res["rows"]}
    assert rows["m_tokens_per_sec.peak_hbm_bytes"]["status"] == "ok"
    assert res["ok"] is True
    # the newest record dropping the column is flagged missing
    res = bench_gate.trend([("r01", _hbm_rec(100.0, peak=1.0e6)),
                            ("r02", _hbm_rec(101.0))])
    rows = {r["metric"]: r for r in res["rows"]}
    assert rows["m_tokens_per_sec.peak_hbm_bytes"]["status"] == "missing"
    assert res["ok"] is False
    assert bench_gate.trend(
        [("r01", _hbm_rec(100.0, peak=1.0e6)),
         ("r02", _hbm_rec(101.0))], allow_missing=True)["ok"] is True
    # records with no peaks anywhere grow no subseries row at all
    res = bench_gate.trend([("r01", _hbm_rec(100.0)),
                            ("r02", _hbm_rec(101.0))])
    assert not [r for r in res["rows"]
                if r["metric"].endswith(".peak_hbm_bytes")]


def test_trend_load_record_peak_variants():
    rec = bench_gate.load_trend_record(
        {"summary": {"m": {"value": 7.0, "peak_hbm_bytes": 5.0e5}}})
    assert rec["m"]["peak_hbm_bytes"] == 5.0e5
    rec = bench_gate.load_trend_record({"metric": "m", "value": 3.0})
    assert rec["m"]["peak_hbm_bytes"] is None
    rec = bench_gate.load_trend_record({"m": 5.0})
    assert rec["m"]["peak_hbm_bytes"] is None


# =========================================================================
# satellite: conftest isolation
# =========================================================================

def test_state_isolated_between_tests():
    """conftest resets memscope state + the flag family around every
    test: no census, programs, KV ledger or alloc forensics survive
    from the earlier tests in this module."""
    assert flags.get_flag("memscope") is False
    assert flags.get_flag("memscope_hbm_limit_bytes") == 0
    assert flags.get_flag("memscope_ratio_factor") == 8.0
    doc = memscope.status_doc()
    assert doc["planes"] == {} and doc["programs"] == {}
    assert doc["kv"] is None
    assert doc["alloc_failures"] == 0
    assert doc["last_alloc_failure"] is None
