"""Gradient accumulation (ref framework/ir/multi_batch_merge_pass.cc) and
ModelAverage (ref python/paddle/fluid/optimizer.py:1373).

Contract under test: K micro-batch steps with accumulate_steps=K must
equal ONE optimizer step on the K×-size batch (within fp tolerance), for
both a stateless (SGD) and a stateful (Adam) optimizer; ModelAverage's
apply/restore context swaps params for their running average and back.
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, optimizer

rng = np.random.RandomState(7)


def _build_linear(opt, accumulate_steps=1):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [3])
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1,
                         param_attr=pt.ParamAttr(
                             name="w",
                             initializer=pt.initializer.ConstantInitializer(
                                 0.5)),
                         bias_attr=False)
        loss = layers.reduce_mean(layers.square(pred - y))
        opt.minimize(loss, accumulate_steps=accumulate_steps)
    return main, startup, loss


def _data(n):
    x = rng.randn(n, 3).astype("float32")
    y = (x @ np.array([[1.0], [-2.0], [0.5]], "float32")).astype("float32")
    return x, y


def _run_steps(opt_fn, accumulate_steps, batches, fetch_w=True):
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    main, startup, loss = _build_linear(opt_fn(), accumulate_steps)
    exe.run(startup)
    for bx, by in batches:
        exe.run(main, feed={"x": bx, "y": by}, fetch_list=[loss])
    return np.asarray(scope.find_var("w"))


def _check_parity(opt_fn, k=4, tol=1e-5):
    x, y = _data(8)
    micro = [(x[i::k], y[i::k]) for i in range(k)]
    w_acc = _run_steps(opt_fn, k, micro)
    w_big = _run_steps(opt_fn, 1, [(x_, y_) for x_, y_ in [(
        np.concatenate([m[0] for m in micro]),
        np.concatenate([m[1] for m in micro]))]])
    assert np.allclose(w_acc, w_big, atol=tol), (w_acc, w_big)


def test_sgd_accumulation_matches_big_batch():
    _check_parity(lambda: optimizer.SGD(learning_rate=0.1))


def test_adam_accumulation_matches_big_batch():
    """Stateful optimizer: moments/beta pows must freeze on non-boundary
    steps — gating every written var, not just the param."""
    _check_parity(lambda: optimizer.Adam(learning_rate=0.05))


def test_params_frozen_until_boundary():
    x, y = _data(8)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    main, startup, loss = _build_linear(optimizer.SGD(0.1),
                                        accumulate_steps=4)
    exe.run(startup)
    w0 = np.asarray(scope.find_var("w")).copy()
    for i in range(3):
        exe.run(main, feed={"x": x[i::4], "y": y[i::4]},
                fetch_list=[loss])
        assert np.allclose(np.asarray(scope.find_var("w")), w0), \
            f"param moved on non-boundary micro-step {i}"
    exe.run(main, feed={"x": x[3::4], "y": y[3::4]}, fetch_list=[loss])
    assert not np.allclose(np.asarray(scope.find_var("w")), w0), \
        "param did not move on the boundary step"


def test_proximal_optimizers_train():
    x, y = _data(8)
    for opt in (optimizer.ProximalGD(0.05, l1=1e-4, l2=1e-4),
                optimizer.ProximalAdagrad(0.1, l1=1e-4, l2=1e-4)):
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace(), scope=scope)
        main, startup, loss = _build_linear(opt)
        exe.run(startup)
        losses = [float(exe.run(main, feed={"x": x, "y": y},
                                fetch_list=[loss])[0]) for _ in range(15)]
        assert losses[-1] < losses[0] * 0.7, losses


def test_model_average_apply_restore():
    x, y = _data(8)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        xv = layers.data("x", [3])
        yv = layers.data("y", [1])
        pred = layers.fc(xv, size=1,
                         param_attr=pt.ParamAttr(
                             name="w",
                             initializer=pt.initializer.ConstantInitializer(
                                 0.5)),
                         bias_attr=False)
        loss = layers.reduce_mean(layers.square(pred - yv))
        optimizer.SGD(0.1).minimize(loss)
        ma = optimizer.ModelAverage(0.15, program=main)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    exe.run(startup)
    snaps = []
    for _ in range(5):
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
        snaps.append(np.asarray(scope.find_var("w")).copy())
    trained = snaps[-1]
    expect_avg = np.mean(snaps, axis=0)
    with ma.apply(exe):
        inside = np.asarray(scope.find_var("w")).copy()
        assert np.allclose(inside, expect_avg, atol=1e-5), (inside,
                                                            expect_avg)
    restored = np.asarray(scope.find_var("w"))
    assert np.allclose(restored, trained, atol=1e-6)


def test_model_average_outside_guard_and_before_training():
    """Review r3: ModelAverage built outside the program_guard must route
    accumulator init to the caller's startup program, and apply() before
    any training step must keep the live params (not swap in zeros)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        xv = layers.data("x", [3])
        yv = layers.data("y", [1])
        pred = layers.fc(xv, size=1,
                         param_attr=pt.ParamAttr(
                             name="w",
                             initializer=pt.initializer.ConstantInitializer(
                                 0.5)),
                         bias_attr=False)
        loss = layers.reduce_mean(layers.square(pred - yv))
        optimizer.SGD(0.1).minimize(loss)
    ma = optimizer.ModelAverage(0.15, program=main,
                                startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    exe.run(startup)
    w0 = np.asarray(scope.find_var("w")).copy()
    with ma.apply(exe):
        assert np.allclose(np.asarray(scope.find_var("w")), w0), \
            "apply() with zero accumulates must be a no-op"
    x, y = _data(4)
    exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
    w1 = np.asarray(scope.find_var("w")).copy()
    with ma.apply(exe):
        assert np.allclose(np.asarray(scope.find_var("w")), w1, atol=1e-6)
    assert np.allclose(np.asarray(scope.find_var("w")), w1)


def test_accumulation_counter_wraps():
    """The boundary counter must stay bounded (no fp32 saturation): after
    many steps the gate still fires every k-th run."""
    x, y = _data(8)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    main, startup, loss = _build_linear(optimizer.SGD(0.05),
                                        accumulate_steps=2)
    exe.run(startup)
    for i in range(10):
        exe.run(main, feed={"x": x[i % 2::2], "y": y[i % 2::2]},
                fetch_list=[loss])
    counters = [v for v in scope.var_names() if v.endswith("acc_counter")]
    assert counters, "accumulation counter var missing"
    c = float(np.asarray(scope.find_var(counters[0])).reshape(()))
    assert 0.0 <= c < 2.0, f"counter not wrapped: {c}"
