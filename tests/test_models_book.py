"""Book-example tier: the five remaining reference book models train
(loss decreases) and round-trip through save/load_inference_model —
the reference's tests/book contract (train -> save -> load -> infer)."""
import os
import tempfile

import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import book

rng = np.random.RandomState(7)
B = 4


def train(build, feeds, steps=4, lr=0.01):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        fs, loss, pred = build()
        pt.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    losses = []
    for _ in range(steps):
        out, = exe.run(main, feed=feeds, fetch_list=[loss])
        losses.append(float(out))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    return main, exe, fs, pred


def test_fit_a_line_trains_and_roundtrips():
    feeds = {"x": rng.randn(B, 13).astype("f4"),
             "y": rng.randn(B, 1).astype("f4")}
    main, exe, fs, pred = train(book.fit_a_line, feeds)
    with tempfile.TemporaryDirectory() as d:
        pt.io.save_inference_model(d, ["x"], [pred], exe,
                                   main_program=main)
        prog, feed_names, fetch_vars = pt.io.load_inference_model(d, exe)
        out, = exe.run(prog, feed={"x": feeds["x"]},
                       fetch_list=list(fetch_vars))
        assert np.asarray(out).shape == (B, 1)


def test_word2vec_trains():
    feeds = {**{f"word_{i}": rng.randint(0, 50, (B, 1)).astype("i8")
                for i in range(4)},
             "next_word": rng.randint(0, 50, (B, 1)).astype("i8")}
    train(lambda: book.word2vec(dict_size=50), feeds)


def test_recommender_system_trains():
    feeds = {"user_id": rng.randint(0, 100, (B, 1)).astype("i8"),
             "gender_id": rng.randint(0, 2, (B, 1)).astype("i8"),
             "age_id": rng.randint(0, 7, (B, 1)).astype("i8"),
             "job_id": rng.randint(0, 21, (B, 1)).astype("i8"),
             "movie_id": rng.randint(0, 200, (B, 1)).astype("i8"),
             "category_id": rng.randint(0, 10, (B, 3)).astype("i8"),
             "movie_title": rng.randint(0, 500, (B, 8)).astype("i8"),
             "score": rng.uniform(1, 5, (B, 1)).astype("f4")}
    train(book.recommender_system, feeds)


def test_rnn_encoder_decoder_trains():
    feeds = {"src_word": rng.randint(0, 100, (B, 8)).astype("i8"),
             "tgt_word": rng.randint(0, 100, (B, 8)).astype("i8"),
             "label": rng.randint(0, 100, (B, 8)).astype("i8")}
    train(book.rnn_encoder_decoder, feeds, lr=0.1)


def test_db_lstm_srl_trains_and_decodes():
    feeds = {**{f"{s}_data": rng.randint(0, 100, (B, 8)).astype("i8")
                for s in ["word", "ctx_n2", "ctx_n1", "ctx_0",
                          "ctx_p1", "ctx_p2"]},
             "verb_data": rng.randint(0, 50, (B, 8)).astype("i8"),
             "mark_data": rng.randint(0, 2, (B, 8)).astype("i8"),
             "target": rng.randint(0, 10, (B, 8)).astype("i8")}
    main, exe, fs, decode = train(lambda: book.db_lstm(depth=2), feeds,
                                  lr=0.05)
    path, = exe.run(main, feed=feeds, fetch_list=[decode])
    path = np.asarray(path)
    assert path.shape == (B, 8)
    assert (path >= 0).all() and (path < 10).all()
