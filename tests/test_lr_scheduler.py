"""In-graph LR schedules vs closed-form references (ref python/paddle/
fluid/layers/learning_rate_scheduler.py), plus end-to-end use as an
optimizer's learning rate."""
import math

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def _run_schedule(build_fn, steps):
    """Build the schedule in a fresh program and run `steps` times,
    returning the lr seen at each run (global step increments per run)."""
    lr = build_fn()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    vals = []
    for _ in range(steps):
        out, = exe.run(pt.default_main_program(), fetch_list=[lr])
        vals.append(float(np.asarray(out).ravel()[0]))
    return vals


def test_exponential_decay():
    vals = _run_schedule(
        lambda: layers.exponential_decay(0.1, decay_steps=2,
                                         decay_rate=0.5), 5)
    # step counter increments before the lr read: steps seen are 1..5
    expect = [0.1 * 0.5 ** (s / 2.0) for s in range(1, 6)]
    np.testing.assert_allclose(vals, expect, rtol=1e-6)


def test_piecewise_decay():
    vals = _run_schedule(
        lambda: layers.piecewise_decay([3, 6], [0.1, 0.05, 0.01]), 8)
    expect = [0.1, 0.1, 0.05, 0.05, 0.05, 0.01, 0.01, 0.01]
    np.testing.assert_allclose(vals, expect, rtol=1e-6)


def test_noam_decay():
    d, warm = 64, 4
    vals = _run_schedule(lambda: layers.noam_decay(d, warm), 8)
    expect = [d ** -0.5 * min(s ** -0.5, s * warm ** -1.5)
              for s in range(1, 9)]
    np.testing.assert_allclose(vals, expect, rtol=1e-5)


def test_polynomial_decay():
    vals = _run_schedule(
        lambda: layers.polynomial_decay(0.1, decay_steps=4,
                                        end_learning_rate=0.01, power=1.0),
        6)
    expect = []
    for s in range(1, 7):
        ss = min(s, 4)
        expect.append((0.1 - 0.01) * (1 - ss / 4.0) + 0.01)
    np.testing.assert_allclose(vals, expect, rtol=1e-5)


def test_cosine_decay():
    vals = _run_schedule(
        lambda: layers.cosine_decay(0.1, step_each_epoch=2, epochs=4), 4)
    expect = [0.1 / 2 * (math.cos(math.floor(s / 2) * math.pi / 4) + 1)
              for s in range(1, 5)]
    np.testing.assert_allclose(vals, expect, rtol=1e-5)


def test_linear_warmup_then_constant():
    vals = _run_schedule(
        lambda: layers.linear_lr_warmup(0.1, warmup_steps=4, start_lr=0.0,
                                        end_lr=0.1), 6)
    expect = [0.1 * min(s / 4.0, 1.0) for s in range(1, 7)]
    np.testing.assert_allclose(vals, expect, rtol=1e-5)


def test_scheduler_drives_optimizer():
    """lr Variable feeds SGD; training still reduces loss and the schedule
    value changes across steps (the reference wiring: optimizer takes the
    schedule var as learning_rate)."""
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, bias_attr=False)
    loss = layers.mean(layers.square_error_cost(pred, y))
    lr = layers.exponential_decay(0.1, decay_steps=2, decay_rate=0.9)
    opt = pt.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 4).astype("float32"),
            "y": rng.randn(8, 1).astype("float32")}
    losses, lrs = [], []
    for _ in range(4):
        lo, lv = exe.run(pt.default_main_program(), feed=feed,
                         fetch_list=[loss, lr])
        losses.append(float(lo))
        lrs.append(float(np.asarray(lv).ravel()[0]))
    assert losses[-1] < losses[0]
    assert lrs[0] != lrs[-1]          # schedule actually advanced
