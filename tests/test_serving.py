"""Serving plane (ISSUE 8): KV-cache decode correctness, continuous
batching, admission control / SLO metrics / drain, the tier-1 loadgen
soak headline, the slow chaos soak, and the Predictor satellites.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import zlib
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import models, serving
from paddle_tpu.core import flags
from paddle_tpu.observability import forensics
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import server as obs_server
from paddle_tpu.serving import loadgen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter_total(name):
    m = obs_metrics.REGISTRY.get(name)
    return 0.0 if m is None else m.total()


# --- shared tiny LM + decode engine (compiled ONCE per module) -------------

@pytest.fixture(scope="module")
def lm():
    """Tiny trained-init LM, its executor reference path, and a
    prepared DecodeEngine over the SAME weights."""
    pt.reset_default_programs()
    from paddle_tpu.framework import executor as em
    scope = em.Scope()
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=97, tgt_vocab_size=97, max_length=32,
        n_layer=2, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    T = 24
    feeds, cost, logits = models.transformer.build_lm_net(
        cfg, seq_len=T, is_test=True, fused_attention=False,
        fused_head=False)
    exe = pt.Executor(pt.CPUPlace(), scope=scope)
    pt.default_startup_program().random_seed = 3
    exe.run(pt.default_startup_program())
    prog = pt.default_main_program()
    params = serving.extract_lm_params(prog, scope, cfg)
    engine = serving.DecodeEngine(cfg, params, max_batch=4, max_len=32,
                                  prompt_buckets=(8, 16))
    engine.prepare()

    def ref_greedy(prompt, n_new):
        """Full-recompute forward per token — the correctness oracle."""
        toks = list(prompt)
        out = []
        for _ in range(n_new):
            pad = np.zeros((1, T), np.int64)
            pad[0, :len(toks)] = toks
            lg, = exe.run(prog, feed={"tokens": pad,
                                      "labels": np.zeros((1, T), "i8")},
                          fetch_list=[logits])
            tok = int(np.argmax(lg[0, len(toks) - 1]))
            toks.append(tok)
            out.append(tok)
        return out

    return SimpleNamespace(cfg=cfg, engine=engine, ref_greedy=ref_greedy)


@pytest.fixture
def fresh_engine(lm):
    lm.engine.reset()
    return lm.engine


@pytest.fixture
def batcher(fresh_engine):
    b = serving.ContinuousBatcher(fresh_engine, queue_limit=16)
    b.start()
    serving.attach(b)
    yield b
    serving.reset()


def _greedy_via_engine(engine, prompts, n_new):
    """Start all prompts in parallel slots; step until each has n_new
    tokens; returns per-prompt token lists."""
    gen = {}
    for s, p in enumerate(prompts):
        gen[s] = [engine.start_sequence(s, p, temperature=0.0)]
    for _ in range(n_new - 1):
        for s, t in engine.decode_step().items():
            gen[s].append(t)
    return [gen[s] for s in range(len(prompts))]


# --- KV-cache decode correctness -------------------------------------------

def test_kv_decode_token_identical_to_full_forward(lm, fresh_engine):
    """Acceptance bar: batched incremental decode == full-recompute
    forward, token for token, across bucketed prompt lengths in ONE
    ragged batch."""
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 97, n).tolist() for n in (5, 8, 13, 16)]
    got = _greedy_via_engine(fresh_engine, prompts, 6)
    for p, g in zip(prompts, got):
        assert g == lm.ref_greedy(p, 6)


def test_kv_decode_retire_backfill_mid_decode(lm, fresh_engine):
    """A retired slot backfilled MID-DECODE (the continuous-batching
    move) decodes its new sequence token-identically while the
    neighbours keep their caches."""
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 97, n).tolist() for n in (6, 9, 12)]
    gen = {s: [fresh_engine.start_sequence(s, p)]
           for s, p in enumerate(prompts)}
    for _ in range(2):
        for s, t in fresh_engine.decode_step().items():
            gen[s].append(t)
    # retire slot 1 mid-flight, backfill a fresh prompt into its slot
    fresh_engine.retire_slot(1)
    p_new = rng.randint(1, 97, 11).tolist()
    g_new = [fresh_engine.start_sequence(1, p_new)]
    for _ in range(3):
        out = fresh_engine.decode_step()
        g_new.append(out[1])
        for s in (0, 2):
            gen[s].append(out[s])
    assert g_new == lm.ref_greedy(p_new, 4)
    for s in (0, 2):
        assert gen[s] == lm.ref_greedy(prompts[s], 6)


def test_temperature_sampling_and_greedy_mix(lm, fresh_engine):
    """Greedy and temperature slots coexist in one decode step; the
    sampled slot stays in-vocab and the greedy slot stays reference-
    exact."""
    rng = np.random.RandomState(2)
    p0, p1 = rng.randint(1, 97, 7).tolist(), rng.randint(1, 97, 7).tolist()
    g0 = [fresh_engine.start_sequence(0, p0, temperature=0.0)]
    g1 = [fresh_engine.start_sequence(1, p1, temperature=1.0)]
    for _ in range(4):
        out = fresh_engine.decode_step()
        g0.append(out[0])
        g1.append(out[1])
    assert g0 == lm.ref_greedy(p0, 5)
    assert all(0 <= t < 97 for t in g1)


def test_cache_capacity_boundary_uses_every_position(lm, fresh_engine,
                                                     batcher):
    """A slot may emit exactly max_len - prompt_len tokens after the
    prefill token: the decode step at lengths == max_len - 1 writes
    the LAST cache position and its emitted token is still valid (its
    K/V is never needed)."""
    prompt = list(range(1, 15))            # len 14, bucket 16
    cap = fresh_engine.max_len - len(prompt) + 1        # incl. prefill
    req = batcher.submit(prompt, max_new_tokens=10_000)
    doc = req.result(timeout=60)
    assert doc["status"] == "ok"
    assert doc["n_tokens"] == cap          # 32 - 14 + 1 = 19
    # reference-exact as far as the T=24 oracle program can see
    n_ref = 24 - len(prompt)
    assert doc["tokens"][:n_ref] == lm.ref_greedy(prompt, n_ref)


def test_lm_program_spec_rejects_fused_build():
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=50, tgt_vocab_size=50, max_length=16,
        n_layer=1, n_head=2, d_model=8, d_inner=16, dropout=0.0)
    models.transformer.build_lm_net(cfg, seq_len=8, is_test=True,
                                    fused_attention=True)
    with pytest.raises(ValueError, match="unfused"):
        models.transformer.lm_program_spec(pt.default_main_program())


def test_prompt_too_long_rejected_at_the_door(lm, fresh_engine, batcher):
    with pytest.raises(ValueError, match="bucket"):
        batcher.submit(list(range(1, 20)))   # > largest bucket (16)


# --- continuous batcher: headline soak, admission, drain -------------------

def test_loadgen_soak_zero_request_path_compiles(lm, batcher):
    """Tier-1 headline: >= 8 concurrent closed-loop streams against the
    batcher-fronted LM complete with ZERO compiles on the request path
    (serving_compiles_total frozen, forensics compile log silent) and
    p99 per-token latency under budget."""
    compiles_before = _counter_total("serving_compiles_total")
    forensics_before = len(forensics.compile_log())
    rep = loadgen.run_loadgen(
        loadgen.inproc_submit(batcher), streams=8,
        requests_per_stream=3, max_new_tokens=6,
        prompt_len_range=(3, 14), vocab_size=97,
        p99_budget_ms=2000.0)
    assert rep["ok"], rep
    assert rep["counts"]["ok"] == 24
    assert rep["accounted"]
    assert rep["per_token_ms"]["p99"] is not None
    assert rep["per_token_ms"]["p99"] <= 2000.0
    assert _counter_total("serving_compiles_total") == compiles_before
    assert len(forensics.compile_log()) == forensics_before
    assert _counter_total("serving_tokens_generated_total") >= 24 * 6


def test_eos_stops_generation(lm, batcher):
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, 97, 5).tolist()
    ref = lm.ref_greedy(prompt, 8)
    # pick an eos that does not occur earlier in the greedy tail, so
    # the stop point is unambiguous
    idx = next(i for i in range(1, len(ref)) if ref[i] not in ref[:i])
    req = batcher.submit(prompt, max_new_tokens=8, eos_id=ref[idx])
    doc = req.result(timeout=30)
    assert doc["status"] == "ok"
    assert doc["tokens"] == ref[:idx + 1]    # stops AT the eos token


def test_admission_control_sheds_past_queue_limit(lm, fresh_engine):
    """Bounded queue: past serving_queue_limit submit() raises
    ShedError and the shed counter moves — the 429 contract."""
    b = serving.ContinuousBatcher(fresh_engine, queue_limit=0)
    b.start()
    serving.attach(b)
    shed_before = obs_metrics.REGISTRY.get(
        "serving_requests_total").labels(status="shed").value
    with pytest.raises(serving.ShedError):
        b.submit([1, 2, 3])
    assert obs_metrics.REGISTRY.get(
        "serving_requests_total").labels(status="shed").value \
        == shed_before + 1
    serving.reset()
    assert not b.running


def test_http_shed_is_429_and_generate_roundtrip(lm, batcher):
    srv = obs_server.start_http_server(port=0)
    url = srv.url
    body = json.dumps({"prompt": [4, 5, 6], "max_new_tokens": 4}).encode()
    doc = json.loads(urllib.request.urlopen(urllib.request.Request(
        url + "/serving/generate", data=body,
        headers={"Content-Type": "application/json"}), timeout=30).read())
    assert doc["status"] == "ok" and len(doc["tokens"]) == 4
    assert doc["ttft_s"] is not None and doc["latency_s"] is not None
    # flip to a zero queue: every admission sheds -> HTTP 429
    batcher.queue_limit = 0
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(urllib.request.Request(
            url + "/serving/generate", data=body,
            headers={"Content-Type": "application/json"}), timeout=30)
    assert ei.value.code == 429
    assert json.loads(ei.value.read())["status"] == "shed"
    # draining is NOT a 429 (retry here) — it's a 503 (fail over)
    batcher.begin_drain(stop=False)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(urllib.request.Request(
            url + "/serving/generate", data=body,
            headers={"Content-Type": "application/json"}), timeout=30)
    assert ei.value.code == 503
    assert json.loads(ei.value.read())["status"] == "drained"
    obs_server.stop_http_server()


def test_serving_route_and_metrics_local_and_fleet_merged(lm, batcher):
    """Acceptance bar: /serving + serving_* series on BOTH the local
    and the fleet-merged /metrics expositions."""
    req = batcher.submit([3, 4, 5], max_new_tokens=4)
    assert req.result(timeout=30)["status"] == "ok"
    srv = obs_server.start_http_server(port=0)
    doc = json.loads(urllib.request.urlopen(
        srv.url + "/serving", timeout=10).read())
    assert doc["schema"] == "paddle_tpu.serving.v1"
    assert doc["attached"] and doc["max_batch"] == 4
    assert doc["requests"]["ok"] >= 1
    assert doc["ttft_s"]["count"] >= 1
    assert doc["per_token_s"]["p99"] is not None
    local_prom = urllib.request.urlopen(
        srv.url + "/metrics", timeout=10).read().decode()
    for name in ("serving_queue_depth", "serving_batch_occupancy",
                 "serving_tokens_generated_total",
                 "serving_requests_total",
                 "serving_ttft_seconds_bucket",
                 "serving_token_seconds_bucket"):
        assert name in local_prom, name
    obs_server.stop_http_server()
    # fleet-merged: a worker snapshot carrying serving_* series merges
    # into the coordinator's exposition
    from paddle_tpu.observability import fleet
    agg = fleet.FleetAggregator(stale_after=3600)
    agg.ingest("report_metrics", fleet.snapshot_payload(rank=1))
    merged = agg.prometheus_text(local=obs_metrics.REGISTRY.to_json())
    for name in ("serving_tokens_generated_total",
                 "serving_ttft_seconds_bucket",
                 "serving_requests_total"):
        assert name in merged, name


def test_drain_finishes_in_flight_and_sheds_queue(lm, batcher):
    """Drain contract: in-flight sequences finish, queued/new requests
    get EXPLICIT drained/shed responses, nothing hangs."""
    reqs = [batcher.submit(list(range(1, 6)), max_new_tokens=12)
            for _ in range(6)]
    batcher.begin_drain(stop=True)
    docs = [r.result(timeout=30) for r in reqs]
    statuses = {d["status"] for d in docs}
    assert statuses <= {"ok", "drained"}
    assert all(d["status"] is not None for d in docs)
    # drained requests answered instantly with no tokens lost silently
    for d in docs:
        if d["status"] == "ok":
            assert len(d["tokens"]) == 12
    deadline = time.time() + 10
    while batcher.running and time.time() < deadline:
        time.sleep(0.05)
    assert not batcher.running
    with pytest.raises((serving.ShedError, RuntimeError)):
        batcher.submit([1, 2, 3])


def test_sigterm_begins_drain_and_chains_handler(lm, batcher):
    """SIGTERM (the PR 2 preemption signal) drains the serving plane
    AND still reaches a previously-installed handler."""
    seen = []
    old = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        batcher.install_signal_handlers()
        req = batcher.submit([2, 3, 4], max_new_tokens=5)
        os.kill(os.getpid(), signal.SIGTERM)
        doc = req.result(timeout=30)
        assert doc["status"] in ("ok", "drained")
        deadline = time.time() + 10
        while batcher.running and time.time() < deadline:
            time.sleep(0.05)
        assert not batcher.running
        assert batcher.draining
        assert seen == [signal.SIGTERM]      # chained, not swallowed
    finally:
        batcher.restore_signal_handlers()
        signal.signal(signal.SIGTERM, old)


def test_submit_after_sigterm_flag_is_drained_synchronously(lm, batcher):
    """ISSUE 20 bugfix regression: a submit racing begin_drain — after
    the SIGTERM handler set ``_drain_requested`` but BEFORE the decode
    loop honors it at the next step boundary — must answer `drained`
    SYNCHRONOUSLY, not queue-then-shed.  A failing-over router (or
    client) must never wait on a dying replica's queue."""
    # simulate exactly what the signal handler does, mid-race
    batcher._drain_requested = True
    depth_before = batcher.queue_depth
    t0 = time.perf_counter()
    with pytest.raises(serving.ShedError) as ei:
        batcher.submit([1, 2, 3], max_new_tokens=4)
    assert ei.value.draining          # 503-drained, not 429-shed
    assert time.perf_counter() - t0 < 1.0      # synchronous, no wait
    assert batcher.queue_depth == depth_before  # never entered queue
    # the real race: many submits against a begin_drain in flight —
    # every one terminates exactly once as ok|drained|shed, none hang
    batcher._drain_requested = False
    results = []

    def _spam():
        for _ in range(8):
            try:
                r = batcher.submit([4, 5, 6], max_new_tokens=3)
                results.append(r.result(timeout=30)["status"])
            except serving.ShedError as e:
                results.append("drained" if e.draining else "shed")
            except RuntimeError:
                results.append("drained")      # stopped mid-race
    th = threading.Thread(target=_spam)
    th.start()
    batcher.begin_drain(stop=True)
    th.join(timeout=30)
    assert not th.is_alive()
    assert len(results) == 8
    assert set(results) <= {"ok", "drained", "shed"}


@pytest.mark.chaos
def test_decode_chaos_fails_requests_explicitly_and_recovers(lm, batcher):
    """A chaos fault mid-decode fails the in-flight requests with an
    explicit error response; the loop keeps serving afterwards."""
    flags.set_flag("chaos_spec", "serving.decode_step=raise:1.0")
    req = batcher.submit([5, 6, 7], max_new_tokens=6)
    doc = req.result(timeout=30)
    assert doc["status"] == "error"
    assert "decode step failed" in doc["error"]
    flags.set_flag("chaos_spec", "")
    req2 = batcher.submit([5, 6, 7], max_new_tokens=4)
    assert req2.result(timeout=30)["status"] == "ok"
    assert batcher.running


# --- Predictor satellites --------------------------------------------------

def _save_tiny_model(tmp_path, with_seq=False):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        if with_seq:
            tokens = pt.layers.data("tokens", [8], dtype="int64")
            emb = pt.layers.embedding(tokens, size=[50, 8])
            pooled = pt.layers.reduce_sum(emb, dim=1)
            pred = pt.layers.fc(pooled, size=3)
            feed_names = ["tokens"]
        else:
            x = pt.layers.data("x", [4], dtype="float32")
            pred = pt.layers.fc(x, size=3)
            feed_names = ["x"]
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, feed_names, [pred], exe,
                               main_program=main)
    from paddle_tpu.inference.predictor import (AnalysisConfig,
                                                create_predictor)
    cfg = AnalysisConfig(d, use_tpu=False)
    return create_predictor(cfg)


def test_predictor_rejects_unknown_feed_names(tmp_path):
    """Satellite: an extra feed name must be a ValueError, NOT a fresh
    executable (it used to silently change _sig and recompile per
    request)."""
    p = _save_tiny_model(tmp_path)
    x = np.ones((2, 4), "f4")
    p.run({"x": x})
    n = len(p._compiled)
    with pytest.raises(ValueError, match="unknown feed names"):
        p.run({"x": x, "bogus": x})
    assert len(p._compiled) == n        # no second executable
    with pytest.raises(ValueError, match="unknown feed names"):
        p.prepare({"x": x, "bogus": x})
    with pytest.raises(ValueError, match="missing feeds"):
        p.run({})


def test_predictor_prepare_buckets_grid(tmp_path):
    """Satellite: prepare_buckets AOT-compiles the whole (batch, seq)
    grid up front; running any bucket shape afterwards never adds an
    executable."""
    p = _save_tiny_model(tmp_path, with_seq=True)
    rep = p.prepare_buckets({"tokens": np.zeros((1, 8), "i8")},
                            batch_sizes=(1, 2), seq_lens=(4, 8))
    assert rep["executables"] == 4
    assert rep["total_seconds"] >= 0
    n = len(p._compiled)
    rng = np.random.RandomState(0)
    for bs in (1, 2):
        for sl in (4, 8):
            out, = p.run({"tokens": rng.randint(0, 50, (bs, sl))
                          .astype("i8")})
            assert out.shape == (bs, 3)
    assert len(p._compiled) == n        # request path: zero compiles


def test_predictor_clone_concurrent_matches_serial(tmp_path):
    """Satellite: M threads over cloned predictors sharing one
    compiled executable reproduce the serial outputs exactly (the
    'sharing is free' claim in clone()'s docstring)."""
    p = _save_tiny_model(tmp_path)
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.randn(2, 4).astype("f4")} for _ in range(8)]
    p.prepare(feeds[0])
    serial = [p.run(f)[0] for f in feeds]
    clones = [p.clone() for _ in range(4)]
    results = [[None] * len(feeds) for _ in clones]
    errors = []

    def worker(ci):
        try:
            for fi, f in enumerate(feeds):
                results[ci][fi] = clones[ci].run(f)[0]
        except Exception as e:             # pragma: no cover
            errors.append(repr(e))

    threads = [threading.Thread(target=worker, args=(ci,))
               for ci in range(len(clones))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for ci in range(len(clones)):
        for fi in range(len(feeds)):
            np.testing.assert_array_equal(results[ci][fi], serial[fi])
    # clones shared the executable cache: no extra compiles
    assert all(c._compiled is p._compiled for c in clones)


# --- chaos soak (slow lane): supervised worker killed under load -----------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _seed_where_exit_fires(prob, lo, hi, site="serving.decode_step"):
    for seed in range(10_000):
        fires = [n for n in range(hi)
                 if zlib.crc32(f"{seed}:{site}:{n}".encode())
                 / 0xFFFFFFFF < prob]
        if fires and lo <= fires[0] < hi:
            return seed
    raise RuntimeError("no seed found")


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_soak_worker_kill_supervisor_restores_capacity(tmp_path):
    """Slow headline: loadgen streams drive a SUPERVISED serving worker
    over HTTP while chaos hard-kills it mid-decode; the supervisor
    restarts it (chaos-stripped) on the same port, capacity returns,
    and every request ends in an explicit ok/shed/error — none lost."""
    from paddle_tpu.distributed.supervisor import Supervisor
    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    # prob 0.2: the crc32 schedule correlation (PR 5 gotcha)
    # leaves no seed with an 8-step skip run at higher probabilities
    kseed = _seed_where_exit_fires(0.2, 8, 30)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop("PYTHONPATH", None)
    sup = Supervisor(
        cmds=[[sys.executable, "-m", "paddle_tpu.serving.worker",
               str(port)]],
        env=env,
        envs=[{"PTPU_CHAOS_SPEC": "serving.decode_step=exit:0.2:9",
               "PTPU_CHAOS_SEED": str(kseed)}],
        cwd=REPO, log_dir=str(tmp_path))
    sup.start()
    try:
        deadline = time.time() + 90
        up = False
        while time.time() < deadline:
            try:
                doc = json.loads(urllib.request.urlopen(
                    url + "/serving", timeout=1).read())
                if doc.get("attached"):
                    up = True
                    break
            except Exception:
                time.sleep(0.3)
        assert up, "worker never became ready"
        rep = loadgen.run_loadgen(
            loadgen.http_submit(url, timeout=30), streams=4,
            requests_per_stream=6, max_new_tokens=6,
            prompt_len_range=(3, 14), vocab_size=97,
            p99_budget_ms=0.0, max_attempts=400, retry_sleep_s=0.15)
        assert rep["accounted"], rep
        assert rep["counts"]["gave_up"] == 0, rep
        assert rep["counts"]["ok"] == 4 * 6, rep
        # the kill actually happened and the supervisor restored it
        assert sup.restarts[0] >= 1, (rep, sup.status())
        assert rep["counts"]["error"] >= 1, rep   # someone saw the gap
        # capacity restored: a fresh request against the restarted
        # incarnation succeeds
        body = json.dumps({"prompt": [9, 8, 7],
                           "max_new_tokens": 3}).encode()
        doc = json.loads(urllib.request.urlopen(urllib.request.Request(
            url + "/serving/generate", data=body,
            headers={"Content-Type": "application/json"}),
            timeout=30).read())
        assert doc["status"] == "ok"
    finally:
        sup.stop()
