"""Async (stale-gradient) update mode — the reference's async pserver
loop (listen_and_serv_op.cc:217) + DC-ASGD compensation
(distribute_transpiler.py:1593) as a host plane over device grad steps."""
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed import AsyncParameterServer, run_async_workers

rng = np.random.RandomState(0)
X = rng.randn(64, 8).astype("f4")
W_TRUE = rng.randn(8, 1).astype("f4")
Y = X @ W_TRUE + 0.01 * rng.randn(64, 1).astype("f4")


@jax.jit
def _grad(w, xb, yb):
    def loss(w):
        return jnp.mean((xb @ w - yb) ** 2)
    return jax.grad(loss)(w)


def _grad_fn(params, step):
    i = (step * 16) % 64
    xb, yb = X[i:i + 16], Y[i:i + 16]
    return {"w": np.asarray(_grad(jnp.asarray(params["w"]), xb, yb))}


def _sync_optimum_loss(lr=0.05, steps=200):
    w = np.zeros((8, 1), np.float32)
    for s in range(steps):
        w -= lr * np.asarray(_grad_fn({"w": w}, s)["w"])
    return float(np.mean((X @ w - Y) ** 2))


def test_async_sgd_converges_near_sync():
    """Barrier-free workers pushing stale grads still reach the convex
    optimum (the async pserver contract)."""
    server = AsyncParameterServer({"w": np.zeros((8, 1))}, lr=0.05)
    params = run_async_workers(server, _grad_fn, n_workers=4,
                               steps_per_worker=50)
    final = float(np.mean((X @ params["w"] - Y) ** 2))
    ref = _sync_optimum_loss()
    assert final < ref * 3 + 1e-3, (final, ref)
    # async really happened: every push bumped the version, and the
    # worker count makes some pushes stale
    assert server.version == 200
    assert max(server.staleness_histogram()) >= 1


def test_dc_asgd_compensation_beats_plain_async_under_staleness():
    """Forced staleness: every gradient is computed against params K
    pushes old.  DC-ASGD's g + lam*g*g*(w - w_stale) term recovers most
    of the loss of accuracy (the reference's _append_dc_asgd_ops)."""
    # lr/staleness chosen where plain async measurably drifts but still
    # converges (lr=0.08, K=6 on this problem: plain 3.4e-3 vs dc 1.4e-3;
    # at K=8 plain diverges outright while dc stays near the optimum)
    K, lr, steps = 6, 0.08, 150

    def run(rule):
        server = AsyncParameterServer({"w": np.zeros((8, 1))}, lr=lr,
                                      rule=rule, dc_lambda=0.5)
        history = [server.pull()]
        for s in range(steps):
            stale_params, stale_ver = history[max(0, len(history) - K)]
            grads = _grad_fn(stale_params, s)
            server.push(grads, stale_params=stale_params,
                        stale_version=stale_ver)
            history.append(server.pull())
        w = server.get()["w"]
        return float(np.mean((X @ w - Y) ** 2))

    plain = run("sgd")
    dc = run("dc_asgd")
    assert np.isfinite(dc) and np.isfinite(plain)
    assert dc < plain * 0.9, (dc, plain)


def test_push_applies_immediately_no_barrier():
    server = AsyncParameterServer({"w": np.ones((2, 2))}, lr=1.0)
    v0 = server.version
    server.push({"w": np.full((2, 2), 0.5)})
    assert server.version == v0 + 1
    np.testing.assert_allclose(server.get()["w"], 0.5 * np.ones((2, 2)))
