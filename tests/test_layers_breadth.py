"""Every layer wrapper in paddle_tpu/layers/sequence.py builds a program
and runs through the Executor (the reference's layer-function contract:
each fn in layers/nn.py has a unittest building + running it)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

rng = np.random.RandomState(42)


def run_net(build, feeds):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        outs = build()
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        fetch = [o.name for o in outs]
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feeds, fetch_list=fetch)


def seq_data(b=2, t=6, d=4):
    return rng.randn(b, t, d).astype("float32")


def test_sequence_conv_pool_softmax():
    x = seq_data()

    def build():
        v = layers.data("x", [6, 4])
        c = layers.sequence_conv(v, num_filters=5, filter_size=3)
        p = layers.sequence_pool(c, "max")
        s = layers.sequence_softmax(c)
        f = layers.sequence_first_step(c)
        l = layers.sequence_last_step(c)
        return c, p, s, f, l

    c, p, s, f, l = run_net(build, {"x": x})
    assert c.shape == (2, 6, 5) and p.shape == (2, 5)
    assert np.allclose(np.asarray(s).sum(1), 1.0, atol=1e-5)
    assert np.allclose(f, np.asarray(c)[:, 0])
    assert np.allclose(l, np.asarray(c)[:, -1])


def test_sequence_manipulation():
    x = seq_data()

    def build():
        v = layers.data("x", [6, 4])
        sl = layers.sequence_slice(v, offset=1, length=3)
        rv = layers.sequence_reverse(v)
        rs = layers.sequence_reshape(v, new_dim=8)
        cc = layers.sequence_concat([v, v])
        return sl, rv, rs, cc

    sl, rv, rs, cc = run_net(build, {"x": x})
    assert sl.shape == (2, 3, 4)
    assert np.allclose(rv, x[:, ::-1])
    assert rs.shape == (2, 3, 8)
    assert cc.shape == (2, 12, 4)


def test_sequence_pad_unpad_expand():
    x = seq_data(2, 4, 3)

    def build():
        v = layers.data("x", [4, 3])
        ln = layers.data("len", [], dtype="int64")
        padded, out_len = layers.sequence_pad(v, pad_value=0.0, maxlen=6,
                                              length=ln)
        unp = layers.sequence_unpad(padded, ln)
        row = layers.sequence_pool(v, "first")
        ex = layers.sequence_expand(row, v)
        exa = layers.sequence_expand_as(row, v)
        return padded, unp, ex, exa

    feeds = {"x": x, "len": np.array([4, 2], "int64")}
    padded, unp, ex, exa = run_net(build, feeds)
    assert padded.shape == (2, 6, 3)
    assert np.all(padded[:, 4:] == 0)
    assert ex.shape == (2, 4, 3) and exa.shape == (2, 4, 3)


def test_sequence_enumerate_scatter():
    ids = rng.randint(0, 9, (2, 5)).astype("int64")

    def build():
        v = layers.data("ids", [5], dtype="int64")
        en = layers.sequence_enumerate(v, win_size=2)
        return (en,)

    en, = run_net(build, {"ids": ids})
    assert en.shape == (2, 5, 2)


def test_crf_layers_train_and_decode():
    B, T, N = 2, 5, 3
    em = rng.randn(B, T, N).astype("float32")
    lab = rng.randint(0, N, (B, T)).astype("int64")
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        e = layers.data("em", [T, N])
        l = layers.data("lab", [T], dtype="int64")
        ll = layers.linear_chain_crf(e, l, param_attr=pt.ParamAttr("crfw"))
        loss = layers.mean(layers.scale(ll, scale=-1.0))
        path = layers.crf_decoding(e, param_attr=pt.ParamAttr("crfw"))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    losses = []
    for _ in range(4):
        out, p = exe.run(main, feed={"em": em, "lab": lab},
                         fetch_list=[loss, path])
        losses.append(float(out))
    assert losses[-1] < losses[0]
    assert np.asarray(p).shape == (B, T)


def test_warpctc_and_greedy_decoder():
    B, T, C = 2, 8, 5
    logits = rng.randn(B, T, C).astype("float32")
    lab = rng.randint(1, C, (B, 3)).astype("int64")

    def build():
        lg = layers.data("lg", [T, C])
        lb = layers.data("lb", [3], dtype="int64")
        loss = layers.warpctc(lg, lb, blank=0)
        dec = layers.ctc_greedy_decoder(lg, blank=0)
        return loss, dec

    loss, dec = run_net(build, {"lg": logits, "lb": lab})
    assert loss.shape == (B, 1) and np.all(np.asarray(loss) > 0)
    assert dec.shape == (B, T)


def test_edit_distance_layer():
    h = np.array([[1, 2, 3, 0]], "int64")
    r = np.array([[1, 3, 3, 0]], "int64")

    def build():
        a = layers.data("h", [4], dtype="int64")
        b = layers.data("r", [4], dtype="int64")
        d, n = layers.edit_distance(a, b, normalized=False)
        return d, n

    d, n = run_net(build, {"h": h, "r": r})
    assert float(np.asarray(d[0]).ravel()[0]) == 1.0


def test_nce_hsigmoid_sampling():
    B, D, N = 4, 6, 10
    x = rng.randn(B, D).astype("float32")
    lab = rng.randint(0, N, (B, 1)).astype("int64")

    def build():
        v = layers.data("x", [D])
        l = layers.data("lab", [1], dtype="int64")
        c1 = layers.mean(layers.nce(v, l, num_total_classes=N,
                                    num_neg_samples=3))
        c2 = layers.mean(layers.hsigmoid(v, l, num_classes=N))
        probs = layers.softmax(layers.fc(v, size=N))
        sid = layers.sampling_id(probs)
        return c1, c2, sid

    c1, c2, sid = run_net(build, {"x": x, "lab": lab})
    assert np.isfinite(c1) and np.isfinite(c2)
    assert sid.shape == (B,) and (sid >= 0).all() and (sid < N).all()


def test_vision_extras():
    img = rng.randn(2, 3, 8, 8).astype("float32")
    vol = rng.randn(1, 2, 4, 4, 4).astype("float32")
    rois = np.array([[0, 0, 7, 7], [2, 2, 6, 6]], "float32")

    def build():
        v = layers.data("img", [3, 8, 8])
        w = layers.data("vol", [2, 4, 4, 4])
        r = layers.data("rois", [4], append_batch_size=False)
        c3 = layers.conv3d(w, num_filters=4, filter_size=3, padding=1)
        p3 = layers.pool3d(w, pool_size=2, pool_stride=2)
        a3 = layers.adaptive_pool3d(w, pool_size=2)
        t3 = layers.conv3d_transpose(w, num_filters=2, filter_size=2,
                                     stride=2)
        rp = layers.roi_pool(v, r, pooled_height=2, pooled_width=2)
        ra = layers.roi_align(v, r, pooled_height=2, pooled_width=2)
        sd = layers.space_to_depth(v, blocksize=2)
        cr = layers.crop(v, shape=[2, 3, 4, 4], offsets=[0, 0, 1, 1])
        i2s = layers.im2sequence(v, filter_size=2, stride=2)
        return c3, p3, a3, t3, rp, ra, sd, cr, i2s

    c3, p3, a3, t3, rp, ra, sd, cr, i2s = run_net(
        build, {"img": img, "vol": vol, "rois": rois})
    assert c3.shape == (1, 4, 4, 4, 4)
    assert p3.shape == (1, 2, 2, 2, 2)
    assert a3.shape == (1, 2, 2, 2, 2)
    assert t3.shape == (1, 2, 8, 8, 8)
    assert rp.shape == (2, 3, 2, 2) and ra.shape == (2, 3, 2, 2)
    assert sd.shape == (2, 12, 4, 4)
    assert cr.shape == (2, 3, 4, 4)


def test_grid_and_affine():
    img = rng.randn(2, 3, 5, 5).astype("float32")
    theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], "float32"),
                    (2, 1, 1))

    def build():
        v = layers.data("img", [3, 5, 5])
        t = layers.data("theta", [2, 3])
        g = layers.affine_grid(t, out_shape=[2, 3, 5, 5])
        s = layers.grid_sampler(v, g)
        ac = layers.affine_channel(v)
        return g, s, ac

    g, s, ac = run_net(build, {"img": img, "theta": theta})
    assert g.shape == (2, 5, 5, 2)
    # identity theta -> identity sampling
    assert np.allclose(s, img, atol=1e-4)


def test_loss_extras():
    B = 4
    pred = (rng.rand(B, 1) * 0.8 + 0.1).astype("float32")
    lab01 = rng.randint(0, 2, (B, 1)).astype("float32")
    left = rng.randn(B, 1).astype("float32")
    right = rng.randn(B, 1).astype("float32")
    seg_pred = rng.rand(B, 8).astype("float32")
    seg_lab = rng.randint(0, 2, (B, 8)).astype("int64")

    def build():
        p = layers.data("p", [1])
        l = layers.data("l", [1])
        lf = layers.data("lf", [1])
        rt = layers.data("rt", [1])
        sp = layers.data("sp", [8])
        sl = layers.data("sl", [8], dtype="int64")
        ll = layers.log_loss(p, l)
        rl = layers.rank_loss(l, lf, rt)
        ml = layers.margin_rank_loss(l, lf, rt)
        dl = layers.dice_loss(sp, sl)
        bl = layers.bpr_loss(layers.softmax(layers.fc(sp, size=5)),
                             layers.cast(l, "int64"))
        return ll, rl, ml, dl, bl

    outs = run_net(build, {"p": pred, "l": lab01, "lf": left, "rt": right,
                           "sp": seg_pred, "sl": seg_lab})
    for o in outs:
        assert np.isfinite(np.asarray(o)).all()


def test_metric_and_misc():
    pred = rng.randint(0, 4, (8,)).astype("int64")
    lab = rng.randint(0, 4, (8,)).astype("int64")
    x1 = rng.randn(2, 3).astype("float32")

    def build():
        p = layers.data("p", [], dtype="int64")
        l = layers.data("l", [], dtype="int64")
        v = layers.data("v", [3])
        miou, wrong, correct = layers.mean_iou(p, l, num_classes=4)
        mx = layers.multiplex([v, v], layers.cast(
            layers.zeros([2, 1], "float32"), "int32"))
        sh = layers.sequence.shape(v)
        sm = layers.sequence.sum([v, v])
        h = layers.sequence.hash(layers.reshape(
            layers.cast(l, "int64"), [-1, 1]), hash_size=100)
        return miou, mx, sh, sm, h

    miou, mx, sh, sm, h = run_net(build, {"p": pred, "l": lab, "v": x1})
    assert 0.0 <= float(miou) <= 1.0
    assert np.allclose(sm, 2 * x1)
    assert (h < 100).all()


def test_rowconv_bilinear_posenc():
    x = seq_data(2, 5, 4)
    y = rng.randn(2, 3).astype("float32")

    def build():
        v = layers.data("x", [5, 4])
        u = layers.data("y", [3])
        rc = layers.row_conv(v, future_context_size=2)
        first = layers.sequence_first_step(v)
        bt = layers.bilinear_tensor_product(first, u, size=6)
        pe = layers.add_position_encoding(v)
        return rc, bt, pe

    rc, bt, pe = run_net(build, {"x": x, "y": y})
    assert rc.shape == x.shape and bt.shape == (2, 6)
    assert pe.shape == x.shape


def test_beam_search_layers():
    B, K, V = 2, 3, 7
    lp = np.log(rng.dirichlet(np.ones(V), (B, K)).astype("float32"))
    pre_ids = np.full((B, K), 2, "int64")
    pre_scores = np.zeros((B, K), "float32")

    def build():
        pi = layers.data("pi", [K], dtype="int64")
        ps = layers.data("ps", [K])
        l = layers.data("lp", [K, V])
        ids, scores, parents = layers.beam_search(
            pi, ps, l, beam_size=K, end_id=1)
        return ids, scores, parents

    ids, scores, parents = run_net(
        build, {"pi": pre_ids, "ps": pre_scores, "lp": lp})
    assert ids.shape == (B, K)
    assert (np.diff(np.asarray(scores), axis=1) <= 1e-6).all()


def test_selected_rows_layers():
    ids = np.array([3, 1, 3, 0], "int64")
    vals = rng.randn(4, 2).astype("float32")

    def build():
        i = layers.data("ids", [], dtype="int64")
        v = layers.data("vals", [2])
        oi, ov = layers.merge_selected_rows(i, v)
        dense = layers.get_tensor_from_selected_rows(i, v, height=5)
        return oi, ov, dense

    oi, ov, dense = run_net(build, {"ids": ids, "vals": vals})
    assert dense.shape == (5, 2)
    # row 3 accumulated twice
    assert np.allclose(dense[3], vals[0] + vals[2], atol=1e-6)


def test_lstm_fused_and_lstmp():
    B, T, D, H = 2, 5, 4, 6
    x = rng.randn(B, T, D).astype("float32")

    def build():
        v = layers.data("x", [T, D])
        h0 = layers.zeros([1, B, H], "float32")
        out, lh, lc = layers.lstm(v, h0, h0, max_len=T, hidden_size=H)
        proj_in = layers.fc(v, size=4 * H, num_flatten_dims=2)
        proj, cell = layers.dynamic_lstmp(proj_in, size=4 * H,
                                          proj_size=3)
        return out, lh, lc, proj, cell

    out, lh, lc, proj, cell = run_net(build, {"x": x})
    assert out.shape == (B, T, H)
    assert lh.shape == (1, B, H) and lc.shape == (1, B, H)
    assert proj.shape == (B, T, 3)
    # second return is the per-step cell sequence (reference contract)
    assert cell.shape == (B, T, H)


def test_misc_random_and_counter():
    x = rng.randn(3, 4).astype("float32")

    def build():
        v = layers.data("x", [4])
        g = layers.gaussian_random_batch_size_like(v, shape=[-1, 5])
        rc = layers.random_crop(v, shape=[2])
        ctr = layers.autoincreased_step_counter()
        sf = layers.similarity_focus(
            layers.reshape(v, [3, 2, 2, 1]), axis=1, indexes=[0])
        return g, rc, ctr, sf

    g, rc, ctr, sf = run_net(build, {"x": x})
    assert g.shape == (3, 5) and rc.shape == (3, 2)


def test_pad_constant_like_and_concat_first():
    big = rng.randn(2, 5).astype("float32")
    small = rng.randn(2, 3).astype("float32")

    def build():
        b = layers.data("b", [5])
        s = layers.data("s", [3])
        return (layers.pad_constant_like(b, s, pad_value=9.0),)

    out, = run_net(build, {"b": big, "s": small})
    assert out.shape == (2, 5)
    assert np.allclose(out[:, 3:], 9.0)


def test_sequence_pool_softmax_masked():
    """ADVICE r2 (high): the Mask input must actually gate pooling and
    softmax — padding steps contribute nothing."""
    x = seq_data()
    lens = np.array([4, 2])
    m = (np.arange(6)[None, :] < lens[:, None]).astype("float32")

    def build():
        v = layers.data("x", [6, 4])
        mk = layers.data("m", [6])
        return (layers.sequence_pool(v, "sum", mask=mk),
                layers.sequence_pool(v, "average", mask=mk),
                layers.sequence_pool(v, "max", mask=mk),
                layers.sequence_last_step(v, mask=mk),
                layers.sequence_softmax(v, mask=mk))

    s, a, mx, last, sm = run_net(build, {"x": x, "m": m})
    for b, n in enumerate(lens):
        assert np.allclose(s[b], x[b, :n].sum(0), atol=1e-5)
        assert np.allclose(a[b], x[b, :n].mean(0), atol=1e-5)
        assert np.allclose(mx[b], x[b, :n].max(0), atol=1e-5)
        assert np.allclose(last[b], x[b, n - 1], atol=1e-6)
        # softmax mass lives entirely on valid steps
        assert np.allclose(np.asarray(sm)[b, :n].sum(0), 1.0, atol=1e-5)
        assert np.allclose(np.asarray(sm)[b, n:], 0.0, atol=1e-6)
