"""Durable sharded checkpoint (incubate/checkpoint.py): CRC + atomic
rename semantics of the reference Go pserver (go/pserver/service.go:346),
rotation + resume of contrib/trainer.py:663,763, and shard reassembly."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.incubate import checkpoint as ckpt


def _state():
    rng = np.random.RandomState(0)
    return {
        "w": rng.randn(8, 4).astype("float32"),
        "b": rng.randn(4).astype("float32"),
        "step": np.asarray([3], dtype="int32"),
        "half": jnp.asarray(rng.randn(4, 4), dtype=jnp.bfloat16),
    }


def test_save_load_round_trip(tmp_path):
    d = str(tmp_path / "c0")
    state = _state()
    ckpt.save_state(d, state, meta={"epoch": 2})
    assert ckpt.is_valid(d)
    out, meta = ckpt.load_state(d)
    assert meta == {"epoch": 2}
    for k in state:
        np.testing.assert_array_equal(
            np.asarray(out[k]).astype("float32"),
            np.asarray(state[k]).astype("float32"))
    assert str(out["half"].dtype) == "bfloat16"


def test_corrupt_shard_detected(tmp_path):
    d = str(tmp_path / "c0")
    ckpt.save_state(d, _state())
    shard = [n for n in os.listdir(d) if n.startswith("shard_")][0]
    path = os.path.join(d, shard)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    assert not ckpt.is_valid(d)
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load_state(d)


def test_missing_manifest_is_invalid(tmp_path):
    """A crash before the manifest commit leaves an invalid checkpoint."""
    d = str(tmp_path / "c0")
    ckpt.save_state(d, _state())
    os.remove(os.path.join(d, ckpt.MANIFEST))
    assert not ckpt.is_valid(d)


def test_rotation_and_corrupt_fallback(tmp_path):
    root = str(tmp_path)
    for i in range(5):
        ckpt.save_checkpoint(root, {"x": np.full((2,), i, "float32")},
                             meta={"i": i}, max_keep=3)
    names = sorted(os.listdir(root))
    assert names == ["checkpoint_2", "checkpoint_3", "checkpoint_4"]
    # corrupt the newest -> latest_checkpoint falls back to serial 3
    d4 = os.path.join(root, "checkpoint_4")
    shard = [n for n in os.listdir(d4) if n.startswith("shard_")][0]
    open(os.path.join(d4, shard), "ab").write(b"garbage")
    assert ckpt.latest_checkpoint(root) == 3
    state, meta, serial = ckpt.load_checkpoint(root)
    assert serial == 3 and meta["i"] == 3
    np.testing.assert_array_equal(state["x"], np.full((2,), 3, "float32"))


def test_sharded_array_reassembly():
    """jax.Arrays sharded over the 8-device mesh save as per-shard pieces
    and reassemble to the full array."""
    from paddle_tpu.core.place import make_mesh
    import tempfile
    mesh = make_mesh((8,), ("data",))
    x = np.arange(8 * 6, dtype="float32").reshape(8, 6)
    xs = jax.device_put(x, jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data", None)))
    with tempfile.TemporaryDirectory() as d:
        ckpt.save_state(d, {"x": xs})
        manifest = json.load(open(os.path.join(d, ckpt.MANIFEST)))
        assert len(manifest["entries"]["x"]["pieces"]) == 8
        out, _ = ckpt.load_state(d)
    np.testing.assert_array_equal(out["x"], x)


def test_trainer_kill_mid_epoch_resume(tmp_path):
    """Train, 'crash', reconstruct: resumes from the newest VALID
    checkpoint; a corrupted newest checkpoint falls back to the previous
    one instead of crashing or loading garbage."""
    ckdir = str(tmp_path / "ck")

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, bias_attr=False, name="fc")
        return layers.mean(layers.square_error_cost(pred, y))

    def opt_func():
        return pt.optimizer.SGD(learning_rate=0.05)

    rng = np.random.RandomState(0)
    batches = [([(rng.randn(4).astype("float32"),
                  rng.randn(1).astype("float32")) for _ in range(4)])
               for _ in range(6)]

    def reader():
        return iter(batches)

    cfg = pt.CheckpointConfig(ckdir, max_num_checkpoints=2,
                              epoch_interval=1, step_interval=2)
    t1 = pt.Trainer(train_func, opt_func, place=pt.CPUPlace(),
                    checkpoint_config=cfg)
    t1.train(num_epochs=2, event_handler=lambda e: None, reader=reader,
             feed_order=["x", "y"])
    w_name, = [n for n in t1.scope.var_names() if n.endswith(".w_0")]
    w_after = np.asarray(t1.scope.find_var(w_name)).copy()
    assert ckpt.latest_checkpoint(ckdir) >= 0

    # crash + resume: a new Trainer picks up the state and epoch offset
    t2 = pt.Trainer(train_func, opt_func, place=pt.CPUPlace(),
                    checkpoint_config=cfg)
    np.testing.assert_allclose(np.asarray(t2.scope.find_var(w_name)),
                               w_after, rtol=1e-6)
    assert t2.epoch_offset == 2

    # corrupt the newest checkpoint: resume falls back to the previous
    root = ckdir
    newest = os.path.join(root, f"checkpoint_{ckpt.latest_checkpoint(root, require_valid=False)}")
    shard = [n for n in os.listdir(newest) if n.startswith("shard_")][0]
    open(os.path.join(newest, shard), "ab").write(b"x")
    t3 = pt.Trainer(train_func, opt_func, place=pt.CPUPlace(),
                    checkpoint_config=cfg)
    assert t3.epoch_offset <= 2   # resumed from an earlier valid serial
    assert t3.scope.find_var(w_name) is not None
