"""Durable sharded checkpoint (incubate/checkpoint.py): CRC + atomic
rename semantics of the reference Go pserver (go/pserver/service.go:346),
rotation + resume of contrib/trainer.py:663,763, and shard reassembly."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.incubate import checkpoint as ckpt


def _state():
    rng = np.random.RandomState(0)
    return {
        "w": rng.randn(8, 4).astype("float32"),
        "b": rng.randn(4).astype("float32"),
        "step": np.asarray([3], dtype="int32"),
        "half": jnp.asarray(rng.randn(4, 4), dtype=jnp.bfloat16),
    }


def test_save_load_round_trip(tmp_path):
    d = str(tmp_path / "c0")
    state = _state()
    ckpt.save_state(d, state, meta={"epoch": 2})
    assert ckpt.is_valid(d)
    out, meta = ckpt.load_state(d)
    assert meta == {"epoch": 2}
    for k in state:
        np.testing.assert_array_equal(
            np.asarray(out[k]).astype("float32"),
            np.asarray(state[k]).astype("float32"))
    assert str(out["half"].dtype) == "bfloat16"


def test_corrupt_shard_detected(tmp_path):
    d = str(tmp_path / "c0")
    ckpt.save_state(d, _state())
    shard = [n for n in os.listdir(d) if n.startswith("shard_")][0]
    path = os.path.join(d, shard)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    assert not ckpt.is_valid(d)
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load_state(d)


def test_missing_manifest_is_invalid(tmp_path):
    """A crash before the manifest commit leaves an invalid checkpoint."""
    d = str(tmp_path / "c0")
    ckpt.save_state(d, _state())
    os.remove(os.path.join(d, ckpt.MANIFEST))
    assert not ckpt.is_valid(d)


def test_rotation_and_corrupt_fallback(tmp_path):
    root = str(tmp_path)
    for i in range(5):
        ckpt.save_checkpoint(root, {"x": np.full((2,), i, "float32")},
                             meta={"i": i}, max_keep=3)
    names = sorted(os.listdir(root))
    assert names == ["checkpoint_2", "checkpoint_3", "checkpoint_4"]
    # corrupt the newest -> latest_checkpoint falls back to serial 3
    d4 = os.path.join(root, "checkpoint_4")
    shard = [n for n in os.listdir(d4) if n.startswith("shard_")][0]
    open(os.path.join(d4, shard), "ab").write(b"garbage")
    assert ckpt.latest_checkpoint(root) == 3
    state, meta, serial = ckpt.load_checkpoint(root)
    assert serial == 3 and meta["i"] == 3
    np.testing.assert_array_equal(state["x"], np.full((2,), 3, "float32"))


def test_sharded_array_reassembly():
    """jax.Arrays sharded over the 8-device mesh save as per-shard pieces
    and reassemble to the full array."""
    from paddle_tpu.core.place import make_mesh
    import tempfile
    mesh = make_mesh((8,), ("data",))
    x = np.arange(8 * 6, dtype="float32").reshape(8, 6)
    xs = jax.device_put(x, jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data", None)))
    with tempfile.TemporaryDirectory() as d:
        ckpt.save_state(d, {"x": xs})
        manifest = json.load(open(os.path.join(d, ckpt.MANIFEST)))
        assert len(manifest["entries"]["x"]["pieces"]) == 8
        out, _ = ckpt.load_state(d)
    np.testing.assert_array_equal(out["x"], x)


def test_trainer_kill_mid_epoch_resume(tmp_path):
    """Train, 'crash', reconstruct: resumes from the newest VALID
    checkpoint; a corrupted newest checkpoint falls back to the previous
    one instead of crashing or loading garbage."""
    ckdir = str(tmp_path / "ck")

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, bias_attr=False, name="fc")
        return layers.mean(layers.square_error_cost(pred, y))

    def opt_func():
        return pt.optimizer.SGD(learning_rate=0.05)

    rng = np.random.RandomState(0)
    batches = [([(rng.randn(4).astype("float32"),
                  rng.randn(1).astype("float32")) for _ in range(4)])
               for _ in range(6)]

    def reader():
        return iter(batches)

    cfg = pt.CheckpointConfig(ckdir, max_num_checkpoints=2,
                              epoch_interval=1, step_interval=2)
    t1 = pt.Trainer(train_func, opt_func, place=pt.CPUPlace(),
                    checkpoint_config=cfg)
    t1.train(num_epochs=2, event_handler=lambda e: None, reader=reader,
             feed_order=["x", "y"])
    w_name, = [n for n in t1.scope.var_names() if n.endswith(".w_0")]
    w_after = np.asarray(t1.scope.find_var(w_name)).copy()
    assert ckpt.latest_checkpoint(ckdir) >= 0

    # crash + resume: a new Trainer picks up the state and epoch offset
    t2 = pt.Trainer(train_func, opt_func, place=pt.CPUPlace(),
                    checkpoint_config=cfg)
    np.testing.assert_allclose(np.asarray(t2.scope.find_var(w_name)),
                               w_after, rtol=1e-6)
    assert t2.epoch_offset == 2

    # corrupt the newest checkpoint: resume falls back to the previous
    root = ckdir
    newest = os.path.join(root, f"checkpoint_{ckpt.latest_checkpoint(root, require_valid=False)}")
    shard = [n for n in os.listdir(newest) if n.startswith("shard_")][0]
    open(os.path.join(newest, shard), "ab").write(b"x")
    t3 = pt.Trainer(train_func, opt_func, place=pt.CPUPlace(),
                    checkpoint_config=cfg)
    assert t3.epoch_offset <= 2   # resumed from an earlier valid serial
    assert t3.scope.find_var(w_name) is not None


# ---------------------------------------------- elastic resharding (ISSUE 14)

def _opt_state():
    """Dense params + adagrad/momentum-style optimizer state, the
    shapes a resize must carry across."""
    rng = np.random.RandomState(7)
    return {
        "fc.w_0": rng.randn(12, 6).astype("float32"),
        "fc.b_0": rng.randn(6).astype("float32"),
        "fc.w_0@ADAGRAD": (rng.rand(12, 6) * 3).astype("float32"),
        "fc.w_0@VELOCITY": rng.randn(12, 6).astype("float32"),
        "lr": np.float32(0.05),
        "step": np.asarray(17, dtype="int64"),
    }


@pytest.mark.parametrize("n_from,n_to", [(1, 2), (2, 4), (4, 2),
                                         (2, 3), (3, 1), (1, 7)])
def test_reshard_round_trip_bit_parity(tmp_path, n_from, n_to):
    """Property matrix: N→M→N over dense params + optimizer state is
    BIT-identical to the original — every dtype preserved, every value
    equal, for splits both finer and coarser than the array extents."""
    root = str(tmp_path / "ck")
    state = _opt_state()
    ckpt.save_checkpoint(root, state, {"step": 17})
    s1 = ckpt.reshard_checkpoint(root, n_from)
    s2 = ckpt.reshard_checkpoint(root, n_to, serial=s1)
    s3 = ckpt.reshard_checkpoint(root, n_from, serial=s2)
    for serial, n in ((s1, n_from), (s2, n_to), (s3, n_from)):
        d = os.path.join(root, f"checkpoint_{serial}")
        out, _ = ckpt.load_state(d)
        man = json.load(open(os.path.join(d, ckpt.MANIFEST)))
        assert man["num_processes"] == n
        for name, val in state.items():
            assert np.array_equal(np.asarray(val), out[name]), name
            assert np.asarray(val).dtype == out[name].dtype, name
    # deterministic splits: N→M→N reproduces the N-manifest's piece
    # layout exactly
    m1 = json.load(open(os.path.join(root, f"checkpoint_{s1}",
                                     ckpt.MANIFEST)))
    m3 = json.load(open(os.path.join(root, f"checkpoint_{s3}",
                                     ckpt.MANIFEST)))
    assert m1["entries"] == m3["entries"]


def test_reshard_from_multiprocess_checkpoint(tmp_path):
    """A checkpoint written by N processes (N shard files, per-process
    pieces) gathers and reshards to M files with identical values —
    the N→M resume path of a fleet resize."""
    d = str(tmp_path / "c0")
    rng = np.random.RandomState(1)
    full_w = rng.randn(8, 4).astype("float32")
    full_a = (rng.rand(8, 4) * 2).astype("float32")
    for p in (1, 0):   # two "processes" write halves; p0 merges LAST
        ckpt.save_state(d, {"w": full_w[p * 4:(p + 1) * 4],
                            "acc": full_a[p * 4:(p + 1) * 4]},
                        meta={"step": 5},
                        process_index=p, num_processes=2)
    # stitch the piece indices to their global slices (save_state wrote
    # per-process local arrays; a real mesh save records global slices
    # via jax shard indices — emulate by patching the manifest)
    man_path = os.path.join(d, ckpt.MANIFEST)
    man = json.load(open(man_path))
    for name in ("w", "acc"):
        man["entries"][name]["shape"] = [8, 4]
        for i, pc in enumerate(man["entries"][name]["pieces"]):
            pc["index"] = [[i * 4, (i + 1) * 4], [0, 4]]
    json.dump(man, open(man_path, "w"))
    state, _ = ckpt.load_state(d)
    np.testing.assert_array_equal(state["w"], full_w)
    new = ckpt.reshard({"entries": man["entries"], "meta": {}}, 4)
    assert sorted({pc["shard"] for e in new["entries"].values()
                   for pc in e["pieces"]}) == [
        f"shard_{q:05d}-of-00004.npz" for q in range(4)]
    ckpt.reshard_state(str(tmp_path / "c1"), state, {"step": 5}, 4)
    out, _ = ckpt.load_state(str(tmp_path / "c1"))
    np.testing.assert_array_equal(out["w"], full_w)
    np.testing.assert_array_equal(out["acc"], full_a)


def test_reshard_layout_override_splits_chosen_axis(tmp_path):
    """The layout knob: a tensor-parallel weight splits along its
    MODEL axis (axis 1) while everything else stays axis-0 — and a
    callable layout works too."""
    state = {"tp_w": np.arange(24, dtype="float32").reshape(4, 6),
             "dense": np.arange(8, dtype="float32").reshape(8, 1)}
    d1 = str(tmp_path / "a")
    ckpt.reshard_state(d1, state, {}, 3, layout={"tp_w": 1})
    man = json.load(open(os.path.join(d1, ckpt.MANIFEST)))
    idx = [pc["index"] for pc in man["entries"]["tp_w"]["pieces"]]
    assert idx == [[[0, 4], [0, 2]], [[0, 4], [2, 4]], [[0, 4], [4, 6]]]
    out, _ = ckpt.load_state(d1)
    np.testing.assert_array_equal(out["tp_w"], state["tp_w"])
    d2 = str(tmp_path / "b")
    ckpt.reshard_state(d2, state, {}, 2,
                       layout=lambda name, shape: len(shape) - 1)
    out2, _ = ckpt.load_state(d2)
    np.testing.assert_array_equal(out2["dense"], state["dense"])
    with pytest.raises(ValueError, match="layout"):
        ckpt.reshard_state(str(tmp_path / "c"), state, {}, 2,
                           layout="bogus")


def test_torn_reshard_falls_back_to_source(tmp_path):
    """The PR 2 torn-write idiom on the reshard commit: a truncate
    fault tears a resharded shard file mid-commit — the new serial
    fails CRC, latest_checkpoint warns and falls back to the source
    checkpoint, and a clean re-reshard then succeeds."""
    from paddle_tpu.core import flags
    from paddle_tpu.resilience import chaos
    root = str(tmp_path / "ck")
    state = _opt_state()
    ckpt.save_checkpoint(root, state, {"step": 17})
    flags.set_flag("chaos_spec",
                   "checkpoint.reshard_write=truncate:1.0:0.4")
    try:
        torn = ckpt.reshard_checkpoint(root, 3)
    finally:
        flags.set_flag("chaos_spec", "")
        chaos.reset()
    assert not ckpt.is_valid(os.path.join(root, f"checkpoint_{torn}"))
    with pytest.warns(RuntimeWarning, match="torn or corrupt"):
        assert ckpt.latest_checkpoint(root) == 0       # fell back
    state_back, meta, serial = ckpt.load_checkpoint(root)
    assert serial == 0
    for name, val in state.items():
        assert np.array_equal(np.asarray(val), state_back[name])
    # the retry reshards from the intact source
    ok = ckpt.reshard_checkpoint(root, 3)
    assert ckpt.is_valid(os.path.join(root, f"checkpoint_{ok}"))
    out, _ = ckpt.load_state(os.path.join(root, f"checkpoint_{ok}"))
    assert np.array_equal(out["fc.w_0"], state["fc.w_0"])


def test_reshard_refuses_without_valid_source(tmp_path):
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.reshard_checkpoint(str(tmp_path / "empty"), 2)
    with pytest.raises(ValueError):
        ckpt.reshard({"entries": {}}, 0)


def test_reshard_bfloat16_round_trips(tmp_path):
    """bf16 params store as f32 pieces (the save_state convention) and
    come back as bf16, resharded or not."""
    x = jnp.asarray(np.random.RandomState(2).randn(6, 3),
                    dtype=jnp.bfloat16)
    root = str(tmp_path / "ck")
    ckpt.save_checkpoint(root, {"h": x})
    s = ckpt.reshard_checkpoint(root, 2)
    out, _ = ckpt.load_state(os.path.join(root, f"checkpoint_{s}"))
    assert out["h"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out["h"], np.float32),
                                  np.asarray(x, np.float32))
