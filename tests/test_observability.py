"""Observability plane: the metrics registry (counter/gauge/histogram
semantics, exposition), executor compile/cache-hit counters, the unified
chrome-trace export, plus the debug tail (ref debugger.py:118
draw_block_graphviz, contrib/memory_usage_calc.py, contrib/op_frequence.py)
and the x32 plane staying warning-free."""
import json
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, observability
from paddle_tpu.core import flags, profiler
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import trace as obs_trace


def _small_program():
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    h = layers.fc(x, size=8, act="relu")
    p = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(p, y))
    pt.optimizer.SGD(0.1).minimize(loss)
    return pt.default_main_program(), loss


def test_draw_block_graphviz(tmp_path):
    main, _ = _small_program()
    path = str(tmp_path / "g.dot")
    out = pt.debugger.draw_block_graphviz(main.global_block(), path=path)
    dot = open(out).read()
    assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
    assert "shape=box" in dot and "shape=ellipse" in dot
    assert "cross_entropy" in dot
    # parameters are shaded; backward hidden by default
    assert "fillcolor" in dot
    assert "@GRAD" not in dot
    full = pt.debugger.draw_block_graphviz(
        main.global_block(), path=str(tmp_path / "g2.dot"),
        show_backward=True)
    assert "autodiff" in open(full).read()


def test_pprint_program_codes():
    main, _ = _small_program()
    txt = pt.debugger.pprint_program_codes(main)
    assert "// block 0" in txt
    assert "mul(" in txt and "cross_entropy(" in txt
    assert "@GRAD" not in txt
    assert "@GRAD" in pt.debugger.pprint_program_codes(
        main, show_backward=True)


def test_memory_usage():
    main, _ = _small_program()
    lo8, hi8, unit8 = pt.contrib.memory_usage(main, batch_size=8)
    lo64, hi64, unit64 = pt.contrib.memory_usage(main, batch_size=64)
    assert 0 < lo8 <= hi8
    # persistable floor is batch-independent; activations grow with B
    scale = {"B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}
    assert lo8 * scale[unit8] == lo64 * scale[unit64]
    assert hi64 * scale[unit64] > hi8 * scale[unit8]
    with pytest.raises(ValueError):
        pt.contrib.memory_usage(main, batch_size=0)


def test_op_freq_statistic():
    main, _ = _small_program()
    uni, adj = pt.contrib.op_freq_statistic(main)
    assert uni["mul"] >= 2 and uni["sgd"] >= 4
    counts = list(uni.values())
    assert counts == sorted(counts, reverse=True)
    assert any("->" in k for k in adj)
    with pytest.raises(TypeError):
        pt.contrib.op_freq_statistic("not a program")


def test_x32_plane_emits_no_truncation_warnings():
    """int64 program dtypes lower to int32 at the dtype plane (x32);
    jax must not warn on every op (round-3 Weak #8)."""
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        pt.reset_default_programs()
        ids = layers.data("ids", [4], dtype="int64")
        emb = layers.embedding(ids, size=[16, 4])
        loss = layers.mean(emb)
        exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
        exe.run(pt.default_startup_program())
        out, = exe.run(pt.default_main_program(),
                       feed={"ids": np.zeros((2, 4), "int64")},
                       fetch_list=[loss])
        assert np.isfinite(float(out))


# --- metrics registry semantics ------------------------------------------

def test_counter_semantics():
    c = obs_metrics.counter("t_counter_total", "test counter")
    v0 = c.value
    c.inc()
    c.inc(2.5)
    assert c.value == pytest.approx(v0 + 3.5)
    with pytest.raises(ValueError):
        c.inc(-1)
    # idempotent get-or-create returns the SAME metric
    assert obs_metrics.counter("t_counter_total") is c
    # re-registration with a different shape is an error
    with pytest.raises(ValueError):
        obs_metrics.gauge("t_counter_total")
    with pytest.raises(ValueError):
        obs_metrics.counter("t_counter_total", labelnames=("x",))


def test_gauge_and_labels():
    g = obs_metrics.gauge("t_gauge", "test gauge", ("shard",))
    g.labels(shard="a").set(4.0)
    g.labels(shard="b").inc(2.0)
    g.labels(shard="b").dec(0.5)
    assert g.labels(shard="a").value == 4.0
    assert g.labels(shard="b").value == 1.5
    assert g.total() == pytest.approx(5.5)
    with pytest.raises(ValueError):
        g.labels(wrong="a")
    with pytest.raises(ValueError):
        g.set(1.0)          # labeled metric needs .labels(...)


def test_histogram_semantics():
    h = obs_metrics.histogram("t_hist_seconds", "test hist",
                              buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(55.55)
    s = h.series()[()]
    assert s.bucket_counts == [1, 1, 1, 1]   # one obs past the last edge
    with h.time():
        pass
    assert h.count == 5


def test_metrics_disabled_flag_noops():
    c = obs_metrics.counter("t_gated_total", "gated")
    v0 = c.value
    flags.set_flag("metrics", False)
    try:
        c.inc()
        assert c.value == v0
    finally:
        flags.set_flag("metrics", True)
    c.inc()
    assert c.value == v0 + 1


def test_prometheus_text_and_json_exposition():
    c = obs_metrics.counter("t_expo_total", "expo test", ("kind",))
    c.labels(kind="x").inc(2)
    h = obs_metrics.histogram("t_expo_seconds", "expo hist",
                              buckets=(1.0, 2.0))
    h.observe(1.5)
    text = obs_metrics.REGISTRY.prometheus_text()
    assert '# TYPE t_expo_total counter' in text
    assert 't_expo_total{kind="x"} 2.0' in text
    assert '# TYPE t_expo_seconds histogram' in text
    assert 't_expo_seconds_bucket' in text and 'le="+Inf"' in text
    assert 't_expo_seconds_count 1' in text
    doc = obs_metrics.REGISTRY.to_json()
    assert doc["schema"] == "paddle_tpu.metrics.v1"
    row = doc["metrics"]["t_expo_total"]
    assert row["type"] == "counter"
    assert row["series"][0] == {"labels": {"kind": "x"}, "value": 2.0}
    json.dumps(doc)      # whole document must be JSON-serializable


# --- executor instrumentation --------------------------------------------

def _compile_counters():
    reg = obs_metrics.REGISTRY
    return (reg.get("executor_compile_total").labels(kind="step").value,
            reg.get("executor_cache_hit_total").value)


def test_executor_cache_hit_and_compile_counters():
    """Acceptance: two identical Executor.run calls -> exactly one
    compile and at least one cache hit, visible via the registry."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    c0, h0 = _compile_counters()
    exe.run(main, feed=feed, fetch_list=[loss])
    exe.run(main, feed=feed, fetch_list=[loss])
    c1, h1 = _compile_counters()
    assert c1 - c0 == 1, "identical runs must compile exactly once"
    assert h1 - h0 >= 1, "second identical run must hit the jit cache"
    assert obs_metrics.REGISTRY.get("executor_step_seconds").total_count() > 0


def test_recompile_storm_warning():
    """Feeding a new batch size every run defeats the jit cache; past the
    threshold the executor warns once (and counts the storm)."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    old = flags.get_flag("recompile_warn_threshold")
    flags.set_flag("recompile_warn_threshold", 2)
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for b in range(1, 6):       # 5 distinct feed shapes
                exe.run(main,
                        feed={"x": np.ones((b, 4), "float32"),
                              "y": np.zeros((b, 1), "int64")},
                        fetch_list=[loss])
        storms = [x for x in w if "recompile storm" in str(x.message)]
        assert len(storms) == 1, "must warn exactly once per fetch key"
    finally:
        flags.set_flag("recompile_warn_threshold", old)


def test_profile_ops_records_per_op_timings():
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    h = obs_metrics.REGISTRY.get("executor_op_seconds")
    n0 = h.total_count()
    flags.set_flag("profile_ops", True)
    profiler.reset_profiler()
    profiler.enable_profiler()
    try:
        exe.run(main, feed={"x": np.ones((2, 4), "float32"),
                            "y": np.zeros((2, 1), "int64")},
                fetch_list=[loss])
    finally:
        flags.set_flag("profile_ops", False)
        profiler.disable_profiler()
    assert h.total_count() > n0
    op_names = {k[0] for k in h.series()}
    assert "mul" in op_names and "cross_entropy" in op_names
    spans = obs_trace.events(cat="op")
    assert any(e["name"] == "op:mul" for e in spans)


# --- unified chrome-trace export -----------------------------------------

def test_unified_chrome_trace_export(tmp_path):
    """Acceptance: a profiled 3-step run exports ONE chrome-trace JSON
    holding both host RecordEvent scopes and executor step spans, with
    schema-valid ph/ts/dur/pid/tid fields sorted by ts."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    profiler.reset_profiler()
    profiler.enable_profiler()
    try:
        with profiler.RecordEvent("my_host_scope"):
            pass
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
    finally:
        profiler.disable_profiler()
    path = str(tmp_path / "trace.json")
    profiler.export_chrome_trace(path)
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    assert spans, "trace must contain complete events"
    for e in spans:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert isinstance(e["name"], str) and e["name"]
    ts = [e["ts"] for e in events if e["ph"] != "M"]
    assert ts == sorted(ts), "events must be sorted by ts"
    names = {e["name"] for e in events}
    assert "my_host_scope" in names              # host RecordEvent scope
    assert any(n.startswith("executor.run") for n in names)
    assert sum(1 for e in spans if e["name"] == "executor.step") == 3
    # lane metadata makes perfetto group the tracks
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in events)


def test_trace_disabled_records_nothing():
    obs_trace.reset()
    obs_trace.disable()
    obs_trace.add_span("ghost", 0.0, 1.0)
    assert obs_trace.events() == []


# --- trainer / memory telemetry ------------------------------------------

def test_telemetry_smoke_train_loop():
    """CI smoke (tier-1, not slow): a 3-step profiled training loop must
    produce zero warnings and a non-empty metrics exposition."""
    def reader():
        rng = np.random.RandomState(0)
        for _ in range(3):
            yield [(rng.rand(4).astype("float32"),
                    np.array([1], "int64")) for _ in range(4)]

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                      act="softmax")
        return layers.mean(layers.cross_entropy(p, y))

    steps0 = obs_metrics.REGISTRY.get("trainer_steps_total").value
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        profiler.reset_profiler()
        profiler.enable_profiler()
        try:
            trainer = pt.Trainer(
                train_func=train_func,
                optimizer_func=lambda: pt.optimizer.SGD(0.1),
                place=pt.CPUPlace())
            trainer.train(num_epochs=1, event_handler=lambda e: None,
                          reader=reader, feed_order=["x", "y"])
            trainer.stop()
        finally:
            profiler.disable_profiler()
    assert caught == [], [str(w.message) for w in caught]
    reg = obs_metrics.REGISTRY
    assert reg.get("trainer_steps_total").value - steps0 == 3
    assert reg.get("trainer_loss_ema").value > 0
    assert reg.get("trainer_examples_per_sec").value > 0
    assert reg.get("device_memory_live_bytes").value > 0
    assert reg.get("device_memory_peak_bytes").value >= \
        reg.get("device_memory_live_bytes").value
    expo = reg.prometheus_text()
    assert expo.strip(), "metrics exposition must be non-empty"
    assert "executor_step_seconds" in expo
    assert "trainer_steps_total" in expo
    markers = [e for e in obs_trace.events()
               if e["name"] == "trainer.step"]
    assert len(markers) == 3


# --- graphviz escaping regression ----------------------------------------

def test_draw_block_graphviz_escapes_special_names(tmp_path):
    """Regression: op/var names with quotes or <> (e.g. `fetch<0>`) must
    not break the emitted DOT syntax."""
    main, _ = _small_program()
    block = main.global_block()
    block.create_var(name='fetch<0>', shape=[1], dtype="float32")
    block.create_var(name='evil"name', shape=[1], dtype="float32")
    block.append_op(type="scale", inputs={"X": ['fetch<0>']},
                    outputs={"Out": ['evil"name']},
                    attrs={"scale": 1.0})
    path = str(tmp_path / "esc.dot")
    dot = open(pt.debugger.draw_block_graphviz(block, path=path)).read()
    assert '\\<' in dot and '\\>' in dot     # angle brackets escaped
    assert '\\"' in dot                      # quote escaped
    assert 'fetch<0>' not in dot             # no raw metacharacters leak
    # every label stays a single balanced quoted string: unescaped-quote
    # count must be even
    unescaped = 0
    prev = ""
    for ch in dot:
        if ch == '"' and prev != "\\":
            unescaped += 1
        prev = ch if not (prev == "\\" and ch == "\\") else ""
    assert unescaped % 2 == 0


# =========================================================================
# ISSUE 3: compiled-program introspection plane — cost model, recompile
# forensics, flight recorder, bench gate, exposition escaping.
# =========================================================================

import dataclasses
import os
import subprocess
import sys

from paddle_tpu.observability import bench_gate, costmodel, flight, forensics
from paddle_tpu.resilience import guard as rguard
from paddle_tpu.resilience import retry as rretry


# --- prometheus exposition escaping (satellite) ---------------------------

def test_prometheus_escaping_help_and_label_values():
    """HELP must escape backslash/newline; label values must escape
    backslash/newline/double-quote — raw, they corrupt the scrape."""
    c = obs_metrics.counter(
        "t_esc_total", 'help with "quotes", a \\ and a\nnewline',
        ("path",))
    c.labels(path='C:\\tmp\n"quoted"').inc()
    text = obs_metrics.REGISTRY.prometheus_text()
    assert ('# HELP t_esc_total help with "quotes", a \\\\ '
            'and a\\nnewline') in text
    assert 'path="C:\\\\tmp\\n\\"quoted\\""' in text
    # the escaped forms must be the ONLY occurrences: no raw newline may
    # survive inside a HELP line or a label value
    for line in text.splitlines():
        if "t_esc_total" in line:
            assert "\n" not in line


# --- cost model (tentpole part 1) -----------------------------------------

def _run_small(exe=None):
    main, loss = _small_program()
    exe = exe or pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    exe.run(main, feed=feed, fetch_list=[loss])
    return exe, main, loss, feed


def test_explain_reports_cost_on_cpu_backend():
    """Acceptance: Executor.explain() returns per-program FLOPs / bytes
    accessed / peak HBM on the CPU backend, plus the schema the docs
    promise."""
    exe, main, loss, feed = _run_small()
    rep = exe.explain(main, feed=feed, fetch_list=[loss])
    assert rep["schema"] == "paddle_tpu.explain.v1"
    assert set(rep) >= {"program", "feeds", "fetches", "state", "cost",
                        "cache", "flags"}
    cost = rep["cost"]
    assert cost is not None and cost["source"] in ("xla", "analytic")
    assert cost["flops"] > 0
    assert cost["bytes_accessed"] > 0
    assert cost["peak_hbm_bytes"] > 0
    assert cost["argument_bytes"] > 0
    assert rep["program"]["op_histogram"].get("mul", 0) >= 2
    assert rep["feeds"]["x"] == {"shape": [4, 4], "dtype": "float32"}
    assert rep["fetches"] == [loss.name]
    assert rep["cache"]["compiles_for_key"] >= 1
    json.dumps(rep)          # the whole report must be JSON-clean
    # the registry carries the same numbers as gauges
    g = obs_metrics.REGISTRY.get("program_cost_flops")
    assert any(s.value == cost["flops"] for s in g.series().values())
    assert obs_metrics.REGISTRY.get(
        "program_cost_peak_hbm_bytes").total() > 0


def test_explain_does_not_consume_rng_or_recompile():
    """explain() must be a pure observer: same executable cache, same
    RNG sequence for subsequent runs."""
    exe, main, loss, feed = _run_small()
    before = exe._run_counter
    c0 = obs_metrics.REGISTRY.get("executor_compile_total").labels(
        kind="step").value
    exe.explain(main, feed=feed, fetch_list=[loss])
    assert exe._run_counter == before
    c1 = obs_metrics.REGISTRY.get("executor_compile_total").labels(
        kind="step").value
    assert c1 == c0, "explain on a cached key must not compile a new step"


def test_cost_model_covers_run_steps_device_loop():
    """A run_steps _multi_cache entry gets its own cost row in the
    cache explorer."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((3, 4, 4), "float32"),
            "y": np.zeros((3, 4, 1), "int64")}
    exe.run_steps(main, feed=feed, fetch_list=[loss], steps=3,
                  per_step_feeds=["x", "y"])
    rep = exe.cache_report()
    assert rep["schema"] == "paddle_tpu.cache_report.v1"
    multi = [m for p in rep["programs"] for m in p["multi"]]
    assert multi, "run_steps must appear in the cache explorer"
    assert multi[0]["steps"] == 3
    assert multi[0]["cost"] is not None
    assert multi[0]["cost"]["flops"] > 0
    json.dumps(rep)
    # and the registry gained a multi-labelled program cost series
    g = obs_metrics.REGISTRY.get("program_cost_flops")
    assert any("multi3" in key[0] for key in g.series())


def test_cost_model_flag_gates_analysis():
    exe, main, loss, feed = _run_small()
    flags.set_flag("cost_model", False)
    try:
        rep = exe.explain(main, feed=feed, fetch_list=[loss])
        assert rep["cost"] is None
    finally:
        flags.set_flag("cost_model", True)


def test_cost_model_matches_analytic_transformer_within_10pct():
    """Acceptance (the bench.py cross-check): XLA's FLOPs for the
    transformer-LM train step agree with the old hand-rolled analytic
    formula within 10% — the contract that let bench.py drop the
    formula."""
    from paddle_tpu import models
    D, F, L, V, T, B = 128, 512, 2, 2000, 64, 2
    pt.reset_default_programs()
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T,
        n_layer=L, n_head=8, d_model=D, d_inner=F, dropout=0.0)
    _, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=T, fused_attention=False, fused_head=False)
    pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = models.transformer.make_fake_lm_batch(cfg, B, T)
    rep = exe.explain(pt.default_main_program(), feed=feed,
                      fetch_list=[avg_cost])
    assert rep["cost"] is not None and rep["cost"]["source"] == "xla"
    flops = rep["cost"]["flops"]
    analytic = 3 * (L * (8 * D * D + 4 * D * F) + L * 2 * T * D
                    + 2 * D * V) * B * T
    assert 0.9 < flops / analytic < 1.1, (flops, analytic)


def test_trainer_exports_cost_model_mfu_gauge():
    """Acceptance: the trainer's MFU/TFLOPs gauges are cost-model
    derived (model-agnostic) and agree with the analytic transformer
    number within 10%."""
    from paddle_tpu import models
    D, F, L, V, T = 128, 512, 2, 2000, 64
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T,
        n_layer=L, n_head=8, d_model=D, d_inner=F, dropout=0.0)

    def train_func():
        _, avg_cost, _ = models.transformer.build_lm_net(
            cfg, seq_len=T, fused_attention=False, fused_head=False)
        return avg_cost

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(2):
            batch = []
            for _ in range(2):
                toks = rng.randint(1, V, (T,)).astype("int64")
                batch.append((toks, np.roll(toks, -1)))
            yield batch

    flags.set_flag("device_peak_flops", 1e12)
    try:
        trainer = pt.Trainer(train_func,
                             lambda: pt.optimizer.SGD(0.1),
                             place=pt.CPUPlace())
        trainer.train(num_epochs=1, event_handler=lambda e: None,
                      reader=reader, feed_order=["tokens", "labels"])
        trainer.stop()
    finally:
        flags.set_flag("device_peak_flops", 0.0)
    reg = obs_metrics.REGISTRY
    flops = reg.get("trainer_flops_per_step").value
    analytic = 3 * (L * (8 * D * D + 4 * D * F) + L * 2 * T * D
                    + 2 * D * V) * 2 * T
    assert flops > 0
    assert 0.9 < flops / analytic < 1.1, (flops, analytic)
    assert reg.get("trainer_tflops").value > 0
    # mfu = (flops/dt) / peak with the peak pinned by the flag
    assert reg.get("trainer_mfu").value > 0


# --- recompile forensics (tentpole part 2) --------------------------------

def test_recompile_cause_feed_shape_drift():
    exe, main, loss, feed = _run_small()
    exe.run(main, feed={"x": np.ones((2, 4), "float32"),
                        "y": np.zeros((2, 1), "int64")},
            fetch_list=[loss])
    rec = exe.compile_log(main)[-1]
    assert rec["causes"] == ["feed_shapes"]
    assert any("x: (4, 4)->(2, 4)" in d for d in rec["details"])


def test_recompile_cause_fetch_program_and_flags_drift():
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    h = layers.fc(x, size=8, act="relu")
    out = layers.mean(h)
    main = pt.default_main_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32")}
    exe.run(main, feed=feed, fetch_list=[out])
    # 1. new fetch set on a known program -> fetch_names
    exe.run(main, feed=feed, fetch_list=[out, h])
    assert exe.compile_log(main)[-1]["causes"] == ["fetch_names"]
    # 2. program mutation -> program_version
    block = main.global_block()
    block.create_var(name="t_extra", shape=[1], dtype="float32")
    block.append_op(type="scale", inputs={"X": [out.name]},
                    outputs={"Out": ["t_extra"]}, attrs={"scale": 2.0})
    exe.run(main, feed=feed, fetch_list=[out])
    assert "program_version" in exe.compile_log(main)[-1]["causes"]
    # 3. numerics flag toggle -> flags
    flags.set_flag("amp_bf16", True)
    try:
        exe.run(main, feed=feed, fetch_list=[out])
    finally:
        flags.set_flag("amp_bf16", False)
    rec = exe.compile_log(main)[-1]
    assert "flags" in rec["causes"]
    assert any("amp_bf16" in d for d in rec["details"])


def test_forensics_scopes_retention_per_executor():
    """A second Executor compiling the same (program, fetch-list) with
    identical feeds is a first compile in ITS cache — not a phantom
    drift against the first executor's retained key."""
    main, loss = _small_program()
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    exe1 = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe1.run(pt.default_startup_program())
    exe1.run(main, feed=feed, fetch_list=[loss])
    exe2 = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe2.run(pt.default_startup_program())
    exe2.run(main, feed=feed, fetch_list=[loss])
    recs = [r for r in exe1.compile_log(main)
            if r["fetches"] == [loss.name]]
    assert [r["causes"] for r in recs[-2:]] == \
        [["first_compile"], ["first_compile"]]


def test_forensics_diff_keys_unit():
    """Component-wise diff vocabulary: drift each component of a
    synthetic cache key and assert the named cause."""
    base = forensics.KeyParts(
        program_uid=7, program_version=3,
        feeds=(("x", (4, 4), "float32"),),
        fetch_names=("loss",),
        state=(("w", (4, 8), "float32"),),
        flags=(("amp_bf16", False),))

    def causes(**kw):
        return [c for c, _ in forensics.diff_keys(
            base, dataclasses.replace(base, **kw))]

    assert causes() == []
    assert causes(feeds=(("x", (8, 4), "float32"),)) == ["feed_shapes"]
    assert causes(feeds=(("x", (4, 4), "float64"),)) == ["feed_dtypes"]
    assert causes(feeds=(("x", (4, 4), "float32"),
                         ("z", (1,), "int64"))) == ["feed_set"]
    assert causes(state=(("w", (4, 16), "float32"),)) == \
        ["state_signature"]
    assert causes(state=(("w", (4, 8), "bfloat16"),)) == \
        ["state_signature"]
    assert causes(program_version=4) == ["program_version"]
    assert causes(fetch_names=("loss", "acc")) == ["fetch_names"]
    assert causes(flags=(("amp_bf16", True),)) == ["flags"]
    # compound drift names every component, shapes first
    got = causes(feeds=(("x", (8, 4), "float64"),), program_version=9)
    assert set(got) == {"feed_shapes", "feed_dtypes", "program_version"}


def test_recompile_storm_warning_names_cause():
    """The storm warning (satellite): names the drifting component and
    the cause-labelled counter increments."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    old = flags.get_flag("recompile_warn_threshold")
    flags.set_flag("recompile_warn_threshold", 2)
    storm = obs_metrics.REGISTRY.get("executor_recompile_storm_total")
    s0 = storm.total()
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for b in range(1, 5):
                exe.run(main,
                        feed={"x": np.ones((b, 4), "float32"),
                              "y": np.zeros((b, 1), "int64")},
                        fetch_list=[loss])
    finally:
        flags.set_flag("recompile_warn_threshold", old)
    storms = [str(x.message) for x in w
              if "recompile storm" in str(x.message)]
    assert len(storms) == 1
    assert "feed_shapes" in storms[0], storms[0]
    assert "x:" in storms[0]          # the latest drift detail is named
    assert storm.total() - s0 == 1
    assert ("feed_shapes",) in storm.series()
    assert obs_metrics.REGISTRY.get(
        "executor_recompile_cause_total").labels(
            cause="feed_shapes").value >= 3


# --- flight recorder (tentpole part 3) ------------------------------------

def _flight_trainer():
    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, bias_attr=False)
        return layers.mean(layers.square_error_cost(pred, y))

    return pt.Trainer(train_func, lambda: pt.optimizer.SGD(0.05),
                      place=pt.CPUPlace())


def _flight_batches(n, bs=4):
    rng = np.random.RandomState(0)
    return [[(rng.randn(4).astype("float32"),
              rng.randn(1).astype("float32")) for _ in range(bs)]
            for _ in range(n)]


def test_flight_recorder_bundle_on_numeric_guard_trip(tmp_path):
    """Acceptance: a forced NumericGuard trip emits one JSON diagnostic
    bundle — bounded, valid, carrying the event ring + metrics +
    cost summaries + flag state."""
    path = str(tmp_path / "flight.json")
    flags.set_flag("flight_recorder_path", path)
    flags.set_flag("chaos_seed", 0)
    flags.set_flag("chaos_spec", "trainer.step=nan:1.0")
    try:
        t = _flight_trainer()
        with pytest.raises(rguard.BadStepError):
            t.train(num_epochs=1, event_handler=lambda e: None,
                    reader=lambda: iter(_flight_batches(4)),
                    feed_order=["x", "y"])
    finally:
        flags.set_flag("flight_recorder_path", "")
        flags.set_flag("chaos_spec", "")
    with open(path) as f:
        doc = json.load(f)                 # must be valid JSON
    # STRICT json: the trigger is a NaN loss, and a bare NaN token
    # would corrupt the bundle for every non-Python consumer
    json.dumps(doc, allow_nan=False)
    assert doc["schema"] == "paddle_tpu.flight.v1"
    assert doc["reason"] == "numeric_guard"
    assert doc["extra"]["verdict"] == "nan"
    assert doc["extra"]["loss"] == "nan"   # stringified, not NaN
    kinds = {e["kind"] for e in doc["events"]}
    assert {"guard", "chaos", "span", "compile"} <= kinds
    cap = int(flags.get_flag("flight_recorder_events"))
    assert len(doc["events"]) <= cap
    assert len(json.dumps(doc)) < (1 << 20)     # bounded bundle
    assert doc["counter_deltas"].get("trainer_bad_steps_total", 0) >= 1
    assert "flags" in doc and "program_costs" in doc \
        and "compile_log" in doc and "metrics" in doc
    assert flight.last_bundle()["reason"] == "numeric_guard"
    assert flight.dump_count() >= 1


def test_flight_recorder_bundle_on_retry_exhaustion(tmp_path):
    path = str(tmp_path / "flight_retry.json")
    flags.set_flag("flight_recorder_path", path)
    pol = rretry.RetryPolicy(name="t_flight", max_attempts=2,
                             base_delay=0.001, jitter=0.0,
                             retry_on=(OSError,))
    try:
        with pytest.raises(OSError):
            rretry.call_with_retry(
                lambda: (_ for _ in ()).throw(OSError("disk gone")), pol)
    finally:
        flags.set_flag("flight_recorder_path", "")
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "retry_exhausted"
    assert doc["extra"]["policy"] == "t_flight"
    assert doc["extra"]["attempts"] == 2
    assert any(e["kind"] == "retry" and e["name"] == "t_flight"
               for e in doc["events"])


def test_flight_recorder_ring_is_bounded_and_gateable():
    flight.reset()
    old = flags.get_flag("flight_recorder_events")
    flags.set_flag("flight_recorder_events", 8)
    try:
        for i in range(50):
            flight.record("span", f"e{i}", i=i)
        evs = flight.events()
        assert len(evs) == 8
        assert evs[-1]["name"] == "e49"     # newest kept, oldest dropped
        flags.set_flag("flight_recorder_events", 0)
        flight.record("span", "ghost")
        assert len(flight.events()) == 8    # capacity 0: recording off
    finally:
        flags.set_flag("flight_recorder_events", old)
    # in-memory dump works without a configured path (no file side
    # effect) and never raises
    assert flight.dump("unit_test") is None
    assert flight.last_bundle()["reason"] == "unit_test"


# --- bench gate (satellite) -----------------------------------------------

def _gate_inputs():
    base = {"parsed": {"summary": {
        "a_tokens_per_sec": {"value": 100.0, "vs_baseline": 2.0},
        "b_ms_per_batch": {"value": 10.0},
        "c_gone_metric": {"value": 5.0}}}}
    cand = {"schema": "paddle_tpu.metrics.v1", "metrics": {
        "bench_value": {"type": "gauge", "help": "", "series": [
            {"labels": {"metric": "a_tokens_per_sec",
                        "unit": "tokens/s"}, "value": 90.0},
            {"labels": {"metric": "b_ms_per_batch",
                        "unit": "ms/batch"}, "value": 10.5},
            {"labels": {"metric": "d_new_metric", "unit": "x"},
             "value": 1.0}]}}}
    return base, cand


def test_bench_gate_formats_directions_and_verdicts():
    base, cand = _gate_inputs()
    bvals = bench_gate.load_metric_values(base)
    cvals = bench_gate.load_metric_values(cand)
    assert bvals == {"a_tokens_per_sec": 100.0, "b_ms_per_batch": 10.0,
                     "c_gone_metric": 5.0}
    assert cvals["a_tokens_per_sec"] == 90.0
    res = bench_gate.gate(bvals, cvals, tolerance=0.15)
    statuses = {r["metric"]: r["status"] for r in res["rows"]}
    assert statuses == {"a_tokens_per_sec": "ok",
                        "b_ms_per_batch": "ok",
                        "c_gone_metric": "missing",
                        "d_new_metric": "new"}
    assert not res["ok"]                        # missing fails by default
    assert bench_gate.gate(bvals, cvals, 0.15, allow_missing=True)["ok"]
    # higher-is-better regression: tokens/s drop past tolerance
    r2 = bench_gate.gate(bvals, dict(cvals, a_tokens_per_sec=80.0),
                         0.15, allow_missing=True)
    assert r2["regressions"] == ["a_tokens_per_sec"]
    # lower-is-better regression: ms/batch INCREASE past tolerance
    r3 = bench_gate.gate(bvals, dict(cvals, b_ms_per_batch=20.0),
                         0.15, allow_missing=True)
    assert r3["regressions"] == ["b_ms_per_batch"]
    # improvement in a lower-is-better metric is never a regression
    r4 = bench_gate.gate(bvals, dict(cvals, b_ms_per_batch=1.0),
                         0.15, allow_missing=True)
    assert r4["ok"]


def test_bench_gate_cli_exit_codes(tmp_path, capsys):
    base, cand = _gate_inputs()
    bp, cp = str(tmp_path / "base.json"), str(tmp_path / "cand.json")
    with open(bp, "w") as f:
        json.dump(base, f)
    with open(cp, "w") as f:
        json.dump(cand, f)
    assert bench_gate.main(["--baseline", bp, "--candidate", cp,
                            "--allow-missing"]) == 0
    assert bench_gate.main(["--baseline", bp, "--candidate", cp]) == 1
    out = capsys.readouterr().out
    assert "[MISS] c_gone_metric" in out
    assert "[  ok] a_tokens_per_sec" in out
    assert bench_gate.main(["--baseline", str(tmp_path / "nope.json"),
                            "--candidate", cp]) == 2
    # a JSON file whose top level is not an object is bad input (rc 2),
    # not a traceback
    lp = str(tmp_path / "list.json")
    with open(lp, "w") as f:
        json.dump([1, 2], f)
    assert bench_gate.main(["--baseline", lp, "--candidate", cp]) == 2


@pytest.mark.slow
def test_bench_metrics_feed_the_gate_end_to_end(tmp_path):
    """Full pipeline: bench.py -> bench_metrics.json -> bench_gate
    self-compare (rc 0).  Slow: runs the real benchmarks on CPU."""
    mpath = str(tmp_path / "bench_metrics.json")
    rpath = str(tmp_path / "bench_runlog.jsonl")
    env = dict(os.environ, PTPU_BENCH_METRICS_PATH=mpath,
               PTPU_BENCH_RUNLOG_PATH=rpath,
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "bench.py"], env=env,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))),
                          capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(mpath) as f:
        doc = json.load(f)
    vals = bench_gate.load_metric_values(doc)
    assert vals, "bench must publish bench_value rows"
    # per-benchmark flops_per_step rides the same dump
    assert "bench_flops_per_step" in doc["metrics"]
    assert bench_gate.main(["--baseline", mpath,
                            "--candidate", mpath]) == 0
    # the bench runlog carries one record per completed row, and
    # round-trips through the CLI parser
    recs = obs_runlog.read_records(rpath)
    bench_rows = [r for r in recs if r["kind"] == "bench"]
    assert len(bench_rows) == len(vals)
    assert {r["metric"] for r in bench_rows} == set(vals)
    assert recs[0]["event"] == "bench_start"
    assert recs[-1]["event"] == "bench_end"


def test_parallel_executor_explain_covers_pjit_program():
    """The tentpole covers the parallel plane too: the mesh executor's
    pjit program yields a cost report through ParallelExecutor.explain."""
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                  act="softmax")
    loss = layers.mean(layers.cross_entropy(p, y))
    pt.optimizer.SGD(0.1).minimize(loss)
    pexe = pt.ParallelExecutor(loss_name=loss.name)
    pexe._exe.run(pt.default_startup_program())
    feed = {"x": np.ones((8, 4), "float32"),
            "y": np.zeros((8, 1), "int64")}
    pexe.run(fetch_list=[loss], feed=feed)
    rep = pexe.explain([loss], feed=feed)
    assert rep["schema"] == "paddle_tpu.explain.v1"
    assert rep["cost"] is not None
    assert rep["cost"]["flops"] > 0
    assert rep["cost"]["peak_hbm_bytes"] > 0
    assert pexe.cache_report()["cached_programs"] >= 1


# =========================================================================
# ISSUE 7: model-health telemetry — in-graph tensor statistics, first-bad-
# layer NaN attribution, run-history log, bench trend gate.
# =========================================================================

from paddle_tpu.observability import runlog as obs_runlog
from paddle_tpu.observability import tensorstats as obs_tensorstats


def _ts_trainer(hidden=8):
    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        h = layers.fc(x, size=hidden, act="relu")
        pred = layers.fc(h, size=1, bias_attr=False)
        return layers.mean(layers.square_error_cost(pred, y))

    return pt.Trainer(train_func, lambda: pt.optimizer.SGD(0.05),
                      place=pt.CPUPlace())


def _ts_batches(n, bs=4):
    rng = np.random.RandomState(0)
    return [[(rng.randn(4).astype("float32"),
              rng.randn(1).astype("float32")) for _ in range(bs)]
            for _ in range(n)]


# --- stats-off invariance (satellite) -------------------------------------

def test_tensorstats_off_explain_and_outputs_invariant():
    """With tensor_stats=False (default) the compile key, explain()
    flags section and step outputs are byte-identical to the stats-less
    executor — and flipping the flag ON does not perturb the step's
    numeric outputs either (the stats fetch rides a separate reserved
    name)."""
    assert flags.get_flag("tensor_stats") is False
    main, loss = _small_program()
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    exe_off = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe_off.run(pt.default_startup_program())
    rep = exe_off.explain(main, feed=feed, fetch_list=[loss])
    # the stats-off report must not even mention the new flags — byte-
    # identical to the pre-tensorstats explain() contract
    assert set(rep["flags"]) == {"amp_bf16", "use_pallas_kernels",
                                 "cost_model", "quantize_dtype",
                                 "fuse_block"}
    off1, = exe_off.run(main, feed=feed, fetch_list=[loss])
    # same program under a stats-sampling executor: identical numerics
    exe_on = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe_on.run(pt.default_startup_program())
    flags.set_flag("tensor_stats", True)
    flags.set_flag("tensor_stats_interval", 1)
    try:
        on1, = exe_on.run(main, feed=feed, fetch_list=[loss])
        rep_on = exe_on.explain(main, feed=feed, fetch_list=[loss])
        assert "tensor_stats" in rep_on["flags"]       # reported when ON
        assert obs_tensorstats.sample_count() == 1
    finally:
        flags.set_flag("tensor_stats", False)
        flags.set_flag("tensor_stats_interval", 10)
    assert np.asarray(off1).tobytes() == np.asarray(on1).tobytes()


def test_tensorstats_off_costs_zero_extra_compiles():
    """Flag off: repeated runs hit the cache exactly as before (one
    compile), and the OFF key is the same key a pre-tensorstats
    executor would build — toggling the flag off->off never drifts."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    c0, h0 = _compile_counters()
    for _ in range(3):
        exe.run(main, feed=feed, fetch_list=[loss])
    c1, h1 = _compile_counters()
    assert c1 - c0 == 1
    assert h1 - h0 == 2


def test_tensorstats_mesh_executor_warns_once_not_silent():
    """tensor_stats=True under a mesh executor cannot sample in-graph
    (feeds/fetches are sharded; the stats fetch is not wired through
    pjit) — the executor must say so loudly, exactly once, instead of
    leaving the flag silently inert in the data-parallel deployment
    the grad-divergence check was built for."""
    from paddle_tpu.core.place import make_mesh
    main, loss = _small_program()
    feed = {"x": np.ones((8, 4), "float32"),
            "y": np.zeros((8, 1), "int64")}
    mesh = make_mesh((8,), ("data",))
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope(), mesh=mesh)
    exe.run(pt.default_startup_program())
    flags.set_flag("tensor_stats", True)
    try:
        with pytest.warns(RuntimeWarning, match="single-device only"):
            exe.run(main, feed=feed, fetch_list=[loss])
        assert obs_tensorstats.sample_count() == 0   # nothing sampled
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            exe.run(main, feed=feed, fetch_list=[loss])
        assert not [w for w in caught
                    if "tensor_stats" in str(w.message)]  # once only
    finally:
        flags.set_flag("tensor_stats", False)


# --- sampling: exactly one extra executable, no storm (acceptance) --------

def test_tensorstats_sampling_two_executables_no_storm():
    """Acceptance: a 50-step run with tensor_stats on at interval 10
    compiles exactly TWO step executables (stats + no-stats variants),
    forensics diagnoses the pair as 'flags' drift, no recompile storm
    warns, and 5 samples land in the model_* gauges."""
    t = _ts_trainer()
    flags.set_flag("tensor_stats", True)
    flags.set_flag("tensor_stats_interval", 10)
    c0, _ = _compile_counters()
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            t.train(num_epochs=1, event_handler=lambda e: None,
                    reader=lambda: iter(_ts_batches(50)),
                    feed_order=["x", "y"])
    finally:
        flags.set_flag("tensor_stats", False)
        flags.set_flag("tensor_stats_interval", 10)
        t.stop()
    c1, _ = _compile_counters()
    assert c1 - c0 == 2, "stats + no-stats variants, nothing else"
    storms = [x for x in w if "recompile storm" in str(x.message)]
    assert storms == [], [str(x.message) for x in storms]
    assert obs_tensorstats.sample_count() == 5      # steps 0,10,..,40
    # the second compile of the train-step key diagnoses as flags drift
    recs = t.exe.compile_log(t.train_program)
    step_recs = [r for r in recs if r["causes"] != ["first_compile"]]
    assert step_recs and step_recs[-1]["causes"] == ["flags"]
    assert any("tensor_stats" in d for d in step_recs[-1]["details"])
    # bounded per-var gauges: top-K + the __all__ aggregate row
    g = obs_metrics.REGISTRY.get("model_grad_norm")
    series = g.series()
    assert ("__all__",) in series
    topk = int(flags.get_flag("tensor_stats_topk"))
    assert 2 <= len(series) <= topk + 1
    assert series[("__all__",)].value > 0
    assert obs_metrics.REGISTRY.get("model_nan_vars").labels(
        var="__all__").value == 0


def test_tensorstats_non_sampled_steps_within_10pct():
    """Acceptance (overhead): at interval 10 the NON-sampled steps run
    the ORIGINAL executable — their median step time stays within 10%
    of the stats-off baseline.  Off/on dispatches are interleaved so
    machine drift between two sequential measurement windows cannot
    masquerade as overhead on these ~1 ms micro-steps."""
    import time as _time
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}

    def one_step():
        t0 = _time.perf_counter()
        exe.run(main, feed=feed, fetch_list=[loss])
        return _time.perf_counter() - t0

    flags.set_flag("tensor_stats_interval", 10)
    try:
        for _ in range(3):              # compile + warm the plain path
            one_step()
        flags.set_flag("tensor_stats", True)
        one_step()                      # compile the stats variant
        base, plain, sampled = [], [], []
        n_on = 1                        # stats-path dispatches so far
        for i in range(100):
            on = i % 2 == 1
            flags.set_flag("tensor_stats", on)
            dt = one_step()
            if not on:
                base.append(dt)
            elif n_on % 10 == 0:
                sampled.append(dt)
                n_on += 1
            else:
                plain.append(dt)
                n_on += 1
    finally:
        flags.set_flag("tensor_stats", False)
        flags.set_flag("tensor_stats_interval", 10)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    assert len(base) == 50 and len(sampled) == 5 and len(plain) == 45
    assert med(plain) <= 1.10 * med(base), (med(plain), med(base))


# --- first-bad-layer attribution (acceptance e2e) -------------------------

@pytest.mark.chaos
def test_first_bad_layer_attribution_e2e():
    """Acceptance: a chaos-injected NaN in a named MID-network variable
    trips the guard, and the guard's raise line,
    trainer_bad_steps_total{first_var=...} and the flight bundle all
    name that variable — first in final-write order, not just any NaN
    var (everything downstream of it is NaN too)."""
    t = _ts_trainer()
    ops = t.train_program.global_block().ops
    fc_tmps = [n for op in ops for ns in op.outputs.values()
               for n in ns if n.startswith("fc") and ".tmp_" in n]
    target = fc_tmps[2]          # the SECOND fc layer's matmul output
    flags.set_flag("tensor_stats", True)
    flags.set_flag("tensor_stats_interval", 1)
    flags.set_flag("chaos_spec", f"executor.var.{target}=nan:1.0")
    bad = obs_metrics.REGISTRY.get("trainer_bad_steps_total")
    b0 = bad.total()
    try:
        with pytest.raises(rguard.BadStepError) as ei:
            t.train(num_epochs=1, event_handler=lambda e: None,
                    reader=lambda: iter(_ts_batches(3)),
                    feed_order=["x", "y"])
    finally:
        flags.set_flag("tensor_stats", False)
        flags.set_flag("tensor_stats_interval", 10)
        flags.set_flag("chaos_spec", "")
        t.stop()
    # 1. the raise log line names the poisoned variable
    assert target in str(ei.value)
    # 2. the metric carries the bounded first_var label
    assert bad.labels(reason="nan", first_var=target).value >= 1
    assert bad.total() - b0 >= 1
    # 3. the flight bundle embeds the full stats snapshot, first_bad
    #    naming the same variable
    doc = flight.last_bundle()
    assert doc["reason"] == "numeric_guard"
    assert doc["tensor_stats"]["first_bad"] == target
    assert doc["extra"]["attribution"].startswith(
        f"first bad var {target!r}")
    json.dumps(doc, allow_nan=False)     # bundle stays strict JSON
    # the poison propagated: MORE than one var went NaN, and the
    # earliest producer won the attribution (not e.g. the loss)
    names = doc["tensor_stats"]["names"]
    stats = doc["tensor_stats"]["stats"]
    nan_col = doc["tensor_stats"]["columns"].index("nan_count")
    bad_vars = [n for n, row in zip(names, stats)
                if float(row[nan_col]) > 0]
    assert len(bad_vars) > 1 and bad_vars[0] == target
    assert obs_metrics.REGISTRY.get("model_nan_vars").labels(
        var="__all__").value == len(bad_vars)


def test_guard_attribution_fallback_when_stats_off():
    """Satellite: with tensor_stats sampling off the guard still
    answers — first_var='unattributed' on the metric and the log line
    says what to enable."""
    assert flags.get_flag("tensor_stats") is False
    t = _ts_trainer()
    flags.set_flag("chaos_spec", "trainer.step=nan:1.0")
    flags.set_flag("chaos_seed", 0)
    bad = obs_metrics.REGISTRY.get("trainer_bad_steps_total")
    v0 = bad.labels(reason="nan", first_var="unattributed").value
    try:
        with pytest.raises(rguard.BadStepError) as ei:
            t.train(num_epochs=1, event_handler=lambda e: None,
                    reader=lambda: iter(_ts_batches(2)),
                    feed_order=["x", "y"])
    finally:
        flags.set_flag("chaos_spec", "")
        t.stop()
    assert "unattributed(enable tensor_stats)" in str(ei.value)
    assert bad.labels(reason="nan",
                      first_var="unattributed").value == v0 + 1


def test_guard_spike_not_attributed_to_stale_nan_sample():
    """A finite loss spike must not be pinned on the first-bad var of
    an EARLIER sample's NaN: attribution is for NaN verdicts only —
    a stale sample from a recovered bad step would name an unrelated
    layer on the spike's metric row and log line."""
    flags.set_flag("tensor_stats", True)
    try:
        # plant a stale poisoned snapshot, as if step 40 sampled a NaN
        stats = np.zeros((1, len(obs_tensorstats.COLUMNS)), "float64")
        stats[0, obs_tensorstats.COLUMNS.index("nan_count")] = 3
        obs_tensorstats._state["snapshot"] = {
            "step": 40, "names": ["fc_1.tmp_0"], "stats": stats,
            "first_bad": "fc_1.tmp_0", "time_unix": 0.0}
        g = rguard.NumericGuard(policy="skip_step", spike_factor=3.0,
                                warmup_steps=2)
        for _ in range(4):
            assert g.observe(1.0) == "ok"
        assert g.observe(100.0) == "spike"           # finite spike
        assert g.last_attribution.startswith("unattributed")
        assert "no NaN to attribute" in g.last_attribution
        bad = obs_metrics.REGISTRY.get("trainer_bad_steps_total")
        assert bad.labels(reason="spike",
                          first_var="unattributed").value >= 1
        # a real NaN verdict still uses the sample
        assert g.observe(float("nan")) == "nan"
        assert "fc_1.tmp_0" in g.last_attribution
    finally:
        flags.set_flag("tensor_stats", False)


def test_runlog_failed_rotate_warns_instead_of_interleaving_silently(
        tmp_path, monkeypatch):
    """When the rotate rename fails but append would succeed (read-only
    directory, writable file), RunLog warns and counts the failure
    instead of silently interleaving two runs in one JSONL."""
    p = str(tmp_path / "run.jsonl")
    with obs_runlog.RunLog(p) as rl:
        rl.write(kind="step", step=0, loss=1.0)

    def deny_replace(src, dst):
        raise PermissionError(13, "Permission denied", src)

    monkeypatch.setattr(obs_runlog.os, "replace", deny_replace)
    fails = obs_metrics.REGISTRY.get("runlog_write_failures_total")
    v0 = fails.value
    with pytest.warns(RuntimeWarning, match="could not rotate"):
        rl2 = obs_runlog.RunLog(p)
    rl2.close()
    assert fails.value == v0 + 1
    # a simply-missing previous run stays silent
    monkeypatch.undo()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        rl3 = obs_runlog.RunLog(str(tmp_path / "fresh.jsonl"))
    rl3.close()


# --- run-history log (tentpole part 2) ------------------------------------

def test_runlog_rotate_write_and_roundtrip(tmp_path):
    """Writer semantics: atomic rotate of a previous run to <path>.1,
    strict-JSON lines (NaN stringified), schema round-trip through the
    CLI parser."""
    p = str(tmp_path / "run.jsonl")
    with obs_runlog.RunLog(p, meta={"run": 1}) as rl:
        rl.write(kind="step", step=0, loss=1.5)
        rl.write(kind="step", step=1, loss=float("nan"))
    with obs_runlog.RunLog(p, meta={"run": 2}) as rl:
        rl.write(kind="step", step=0, loss=1.4)
    assert os.path.exists(p + ".1"), "previous run rotated aside"
    old = obs_runlog.read_records(p + ".1")
    assert [r["kind"] for r in old] == ["meta", "step", "step"]
    assert old[2]["loss"] == "nan"       # stringified, strict JSON
    assert obs_runlog._value(old[2], "loss") != obs_runlog._value(
        old[1], "loss")                   # parses back as float('nan')
    new = obs_runlog.read_records(p)
    assert all(r["schema"] == "paddle_tpu.runlog.v1" for r in new)
    assert new[0]["run"] == 2
    # a non-runlog file is a loud schema error, not garbage records
    q = str(tmp_path / "not_runlog.jsonl")
    with open(q, "w") as f:
        f.write('{"foo": 1}\n')
    with pytest.raises(ValueError, match="schema"):
        obs_runlog.read_records(q)


def test_runlog_numpy_int_step_survives_alignment(tmp_path):
    """A numpy-integer step (np.int64 from a trainer counter) must
    serialize as a JSON int: a float-coerced step (3.0) fails the CLI's
    strict-int step alignment and the record silently vanishes from
    --compare/--plot."""
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    with obs_runlog.RunLog(a) as rl:
        for i in range(3):
            rl.write(kind="step", step=np.int64(i), loss=1.0 / (i + 1))
    with obs_runlog.RunLog(b) as rl:
        for i in range(3):
            rl.write(kind="step", step=i, loss=1.0 / (i + 1))
    steps = [r["step"] for r in obs_runlog.step_records(
        obs_runlog.read_records(a))]
    assert steps == [0, 1, 2]
    assert all(type(s) is int for s in steps)
    doc = obs_runlog.compare(obs_runlog.read_records(a),
                             obs_runlog.read_records(b))
    assert doc["steps_compared"] == 3 and doc["diverged"] is False
    # non-integral numpy scalars still take the float path
    assert obs_runlog._strict(np.float32(1.5)) == 1.5


def _write_run(path, n, spike_at=None, spike=50.0):
    with obs_runlog.RunLog(path) as rl:
        for i in range(n):
            loss = spike if i == spike_at else 1.0 / (i + 1)
            rl.write(kind="step", step=i, global_step=i, loss=loss,
                     lr=0.1)


def test_runlog_compare_cli_finds_first_divergence(tmp_path, capsys):
    """Acceptance: --compare on two 20-step runs (one with an injected
    loss spike) exits nonzero and prints the first diverging step; the
    identical pair exits 0; bad input exits 2."""
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _write_run(a, 20)
    _write_run(b, 20, spike_at=12)
    rc = obs_runlog._main(["--compare", a, b, "--metric", "loss",
                           "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "DIVERGED at step 12" in out
    doc = json.loads(out.splitlines()[0])
    assert doc["schema"] == "paddle_tpu.runlog_compare.v1"
    assert doc["first_divergence"]["step"] == 12
    assert doc["steps_compared"] == 20
    # same trajectory within tolerance -> 0
    assert obs_runlog._main(["--compare", a, a]) == 0
    # a missing file is bad input (rc 2), not a traceback
    assert obs_runlog._main(
        ["--compare", a, str(tmp_path / "nope.jsonl")]) == 2
    # one side NaN at an aligned step is a divergence even at huge
    # tolerance
    c = str(tmp_path / "c.jsonl")
    with obs_runlog.RunLog(c) as rl:
        for i in range(20):
            rl.write(kind="step", step=i,
                     loss=float("nan") if i == 7 else 1.0 / (i + 1))
    assert obs_runlog._main(["--compare", a, c,
                             "--tolerance", "1e9"]) == 1


def test_runlog_tail_and_ascii_trend(tmp_path, capsys):
    p = str(tmp_path / "t.jsonl")
    _write_run(p, 30, spike_at=25)
    assert obs_runlog._main([p, "--tail", "3"]) == 0
    out = capsys.readouterr().out
    assert out.count("\n") == 3 and "step=29" in out
    assert obs_runlog._main([p, "--plot", "loss"]) == 0
    plot = capsys.readouterr().out
    assert "step 0 .. 29" in plot and "(loss" in plot
    assert "*" in plot
    lines = [ln for ln in plot.splitlines() if "|" in ln]
    assert len(lines) == 10              # default height
    # a metric with no samples renders a message, not a crash
    txt = obs_runlog.render_trend(obs_runlog.read_records(p), "zz")
    assert "no finite" in txt


def test_runlog_trainer_writes_step_history(tmp_path):
    """The Trainer's runlog: meta open/close, one step record per step
    carrying loss/lr/throughput, tensorstats rows only on sampled
    steps, guard trips as their own records."""
    p = str(tmp_path / "train.jsonl")
    flags.set_flag("runlog_path", p)
    flags.set_flag("tensor_stats", True)
    flags.set_flag("tensor_stats_interval", 3)
    try:
        t = _ts_trainer()
        t.train(num_epochs=1, event_handler=lambda e: None,
                reader=lambda: iter(_ts_batches(6)),
                feed_order=["x", "y"])
        t.stop()
    finally:
        flags.set_flag("runlog_path", "")
        flags.set_flag("tensor_stats", False)
        flags.set_flag("tensor_stats_interval", 10)
    recs = obs_runlog.read_records(p)
    steps = obs_runlog.step_records(recs)
    assert len(steps) == 6
    assert recs[0]["kind"] == "meta" and recs[0]["event"] == "train_start"
    assert recs[-1]["kind"] == "meta" and recs[-1]["event"] == "train_end"
    for i, r in enumerate(steps):
        assert r["global_step"] == i
        assert r["loss"] > 0 and r["lr"] == 0.05
        assert r["examples_per_sec"] > 0
    with_stats = [r for r in steps if "stats" in r]
    assert [r["global_step"] for r in with_stats] == [0, 3]
    assert with_stats[0]["stats"]["grad_norm"] > 0
    # guard trip -> a guard record with the attribution, before the raise
    p2 = str(tmp_path / "guarded.jsonl")
    flags.set_flag("runlog_path", p2)
    flags.set_flag("chaos_spec", "trainer.step=nan:1.0")
    flags.set_flag("chaos_seed", 0)
    try:
        t2 = _ts_trainer()
        with pytest.raises(rguard.BadStepError):
            t2.train(num_epochs=1, event_handler=lambda e: None,
                     reader=lambda: iter(_ts_batches(2)),
                     feed_order=["x", "y"])
        t2.stop()
    finally:
        flags.set_flag("runlog_path", "")
        flags.set_flag("chaos_spec", "")
    recs2 = obs_runlog.read_records(p2)
    guard_recs = [r for r in recs2 if r["kind"] == "guard"]
    assert len(guard_recs) == 1
    assert guard_recs[0]["verdict"] == "nan"
    assert guard_recs[0]["loss"] == "nan"
    assert "unattributed" in guard_recs[0]["attribution"]
    assert recs2[-1]["event"] == "train_end"   # closed even on raise


# --- bench trend gate (satellite) -----------------------------------------

def _trend_files(tmp_path, newest_tokps, newest_mfu=0.5):
    paths = []
    rows = [("BENCH_r01.json", 100.0, 0.2, 50.0),
            ("BENCH_r02.json", 300.0, 0.4, 20.0),
            ("BENCH_r03.json", newest_tokps, newest_mfu, 18.0)]
    for name, tokps, mfu, ms in rows:
        p = str(tmp_path / name)
        with open(p, "w") as f:
            json.dump({"parsed": {"summary": {
                "lm_tokens_per_sec": {"value": tokps, "mfu": mfu},
                "conv_ms_per_batch": {"value": ms}}}}, f)
        paths.append(p)
    return paths


def test_bench_gate_trend_mode_cli(tmp_path, capsys):
    """Satellite (tier-1 CLI smoke): --trend prints the cross-release
    trajectory and exits 1 when the newest record regresses best-ever
    by > tolerance — per metric AND per MFU series."""
    # improving run: ok
    paths = _trend_files(tmp_path, newest_tokps=400.0, newest_mfu=0.5)
    assert bench_gate.main(["--trend", *paths]) == 0
    out = capsys.readouterr().out
    assert "100 -> 300 -> 400" in out
    assert "lm_tokens_per_sec.mfu" in out
    verdict = json.loads(out.strip().splitlines()[-1])
    assert verdict["ok"] and verdict["newest"] == "BENCH_r03"
    # newest regresses best-ever tokens/s by 50% -> rc 1
    paths = _trend_files(tmp_path, newest_tokps=150.0, newest_mfu=0.5)
    assert bench_gate.main(["--trend", *paths, "--tolerance",
                            "0.15"]) == 1
    out = capsys.readouterr().out
    assert "[FAIL] lm_tokens_per_sec:" in out
    assert json.loads(out.strip().splitlines()[-1])["regressions"] == \
        ["lm_tokens_per_sec"]
    # an MFU-only regression also fails (throughput flat, efficiency
    # collapsed = something is burning flops)
    paths = _trend_files(tmp_path, newest_tokps=310.0, newest_mfu=0.1)
    assert bench_gate.main(["--trend", *paths]) == 1
    out = capsys.readouterr().out
    assert "[FAIL] lm_tokens_per_sec.mfu" in out
    # < 2 records is bad input (rc 2), as is an unreadable file
    assert bench_gate.main(["--trend", paths[0]]) == 2
    assert bench_gate.main(
        ["--trend", paths[0], str(tmp_path / "nope.json")]) == 2
    # the real committed records must load and pass self-consistency
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    real = sorted(
        os.path.join(repo, n) for n in os.listdir(repo)
        if n.startswith("BENCH_r") and n.endswith(".json"))
    if len(real) >= 2:
        capsys.readouterr()
        assert bench_gate.main(["--trend", *real]) in (0, 1)


def test_bench_gate_trend_lower_is_better_direction(tmp_path):
    def write(dirname, r1_ms, r2_ms):
        d = tmp_path / dirname
        d.mkdir()
        paths = []
        for name, ms in (("r1.json", r1_ms), ("r2.json", r2_ms)):
            p = str(d / name)
            with open(p, "w") as f:
                json.dump({"parsed": {"summary": {
                    "m_ms_per_batch": {"value": ms}}}}, f)
            paths.append(p)
        return paths

    # ms/batch GREW in the newest release (r2) -> regression; input
    # order is irrelevant, --trend sorts by filename = release order
    grew = write("grew", 10.0, 20.0)
    assert bench_gate.main(["--trend", *grew]) == 1
    assert bench_gate.main(["--trend", *reversed(grew)]) == 1
    # ms/batch SHRANK in the newest release -> ok
    shrank = write("shrank", 20.0, 10.0)
    assert bench_gate.main(["--trend", *shrank]) == 0


def test_bench_gate_trend_natural_release_order(tmp_path, capsys):
    """Release order is numeric, not lexicographic: BENCH_r10 is newer
    than BENCH_r9, so a regression introduced in r10 must be judged
    against r9's best — a bytewise sort would judge r9 as newest and
    wave the regressed r10 through as 'history'."""
    def write(name, v):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            json.dump({"parsed": {"summary": {
                "m_tokens_per_sec": {"value": v}}}}, f)
        return p
    p9 = write("BENCH_r9.json", 100.0)
    p10 = write("BENCH_r10.json", 40.0)      # newest regressed 60%
    assert bench_gate.main(["--trend", p9, p10]) == 1
    verdict = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["newest"] == "BENCH_r10"
    assert verdict["regressions"] == ["m_tokens_per_sec"]


def test_runlog_compare_aligns_bench_rows(tmp_path, capsys):
    """Two bench runlogs (kind=bench, step = fixed workload index)
    diff with the same CLI as training runs: --compare aligns on the
    workload index and flags the regressed row."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, v1 in ((a, 200.0), (b, 90.0)):
        la = obs_runlog.RunLog(path, meta={"event": "bench_start"})
        la.write(kind="bench", step=0, metric="lm_tokens_per_sec",
                 value=v1)
        la.write(kind="bench", step=2, metric="lstm_ms_per_batch",
                 value=4.0)                  # workload 1 errored out
        la.close()
    rc = obs_runlog._main(["--compare", a, b, "--metric", "value",
                           "--tolerance", "0.05"])
    out = capsys.readouterr().out
    assert rc == 1
    doc = json.loads(out.strip().splitlines()[0])
    assert doc["first_divergence"]["step"] == 0
    assert doc["steps_compared"] == 2        # aligned despite the gap


def test_bench_gate_trend_missing_metric_and_null_parse(tmp_path,
                                                        capsys):
    """A metric that drops out of the newest record (its workload
    errored out of the bench run) fails the trend gate as `missing`
    unless --allow-missing; a release whose driver parse failed
    (parsed: null) contributes NO metrics — its wrapper bookkeeping
    fields (n, rc) must not surface as bogus series."""
    def write(name, doc):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            json.dump(doc, f)
        return p

    p1 = write("r1.json", {"parsed": {"summary": {
        "keep_tokens_per_sec": {"value": 100.0},
        "gone_tokens_per_sec": {"value": 50.0}}}})
    p2 = write("r2.json", {"n": 3, "rc": 0, "tail": "x",
                           "parsed": None})      # failed driver parse
    p3 = write("r3.json", {"parsed": {"summary": {
        "keep_tokens_per_sec": {"value": 110.0}}}})
    assert bench_gate.main(["--trend", p1, p2, p3]) == 1
    out = capsys.readouterr().out
    assert "[miss] gone_tokens_per_sec" in out
    assert "rc" not in json.loads(out.strip().splitlines()[-1])["missing"]
    verdict = json.loads(out.strip().splitlines()[-1])
    assert verdict["missing"] == ["gone_tokens_per_sec"]
    assert verdict["regressions"] == []
    # --allow-missing downgrades the drop to informational
    assert bench_gate.main(["--trend", p1, p2, p3,
                            "--allow-missing"]) == 0
