"""Observability plane: the metrics registry (counter/gauge/histogram
semantics, exposition), executor compile/cache-hit counters, the unified
chrome-trace export, plus the debug tail (ref debugger.py:118
draw_block_graphviz, contrib/memory_usage_calc.py, contrib/op_frequence.py)
and the x32 plane staying warning-free."""
import json
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, observability
from paddle_tpu.core import flags, profiler
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import trace as obs_trace


def _small_program():
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    h = layers.fc(x, size=8, act="relu")
    p = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(p, y))
    pt.optimizer.SGD(0.1).minimize(loss)
    return pt.default_main_program(), loss


def test_draw_block_graphviz(tmp_path):
    main, _ = _small_program()
    path = str(tmp_path / "g.dot")
    out = pt.debugger.draw_block_graphviz(main.global_block(), path=path)
    dot = open(out).read()
    assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
    assert "shape=box" in dot and "shape=ellipse" in dot
    assert "cross_entropy" in dot
    # parameters are shaded; backward hidden by default
    assert "fillcolor" in dot
    assert "@GRAD" not in dot
    full = pt.debugger.draw_block_graphviz(
        main.global_block(), path=str(tmp_path / "g2.dot"),
        show_backward=True)
    assert "autodiff" in open(full).read()


def test_pprint_program_codes():
    main, _ = _small_program()
    txt = pt.debugger.pprint_program_codes(main)
    assert "// block 0" in txt
    assert "mul(" in txt and "cross_entropy(" in txt
    assert "@GRAD" not in txt
    assert "@GRAD" in pt.debugger.pprint_program_codes(
        main, show_backward=True)


def test_memory_usage():
    main, _ = _small_program()
    lo8, hi8, unit8 = pt.contrib.memory_usage(main, batch_size=8)
    lo64, hi64, unit64 = pt.contrib.memory_usage(main, batch_size=64)
    assert 0 < lo8 <= hi8
    # persistable floor is batch-independent; activations grow with B
    scale = {"B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}
    assert lo8 * scale[unit8] == lo64 * scale[unit64]
    assert hi64 * scale[unit64] > hi8 * scale[unit8]
    with pytest.raises(ValueError):
        pt.contrib.memory_usage(main, batch_size=0)


def test_op_freq_statistic():
    main, _ = _small_program()
    uni, adj = pt.contrib.op_freq_statistic(main)
    assert uni["mul"] >= 2 and uni["sgd"] >= 4
    counts = list(uni.values())
    assert counts == sorted(counts, reverse=True)
    assert any("->" in k for k in adj)
    with pytest.raises(TypeError):
        pt.contrib.op_freq_statistic("not a program")


def test_x32_plane_emits_no_truncation_warnings():
    """int64 program dtypes lower to int32 at the dtype plane (x32);
    jax must not warn on every op (round-3 Weak #8)."""
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        pt.reset_default_programs()
        ids = layers.data("ids", [4], dtype="int64")
        emb = layers.embedding(ids, size=[16, 4])
        loss = layers.mean(emb)
        exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
        exe.run(pt.default_startup_program())
        out, = exe.run(pt.default_main_program(),
                       feed={"ids": np.zeros((2, 4), "int64")},
                       fetch_list=[loss])
        assert np.isfinite(float(out))


# --- metrics registry semantics ------------------------------------------

def test_counter_semantics():
    c = obs_metrics.counter("t_counter_total", "test counter")
    v0 = c.value
    c.inc()
    c.inc(2.5)
    assert c.value == pytest.approx(v0 + 3.5)
    with pytest.raises(ValueError):
        c.inc(-1)
    # idempotent get-or-create returns the SAME metric
    assert obs_metrics.counter("t_counter_total") is c
    # re-registration with a different shape is an error
    with pytest.raises(ValueError):
        obs_metrics.gauge("t_counter_total")
    with pytest.raises(ValueError):
        obs_metrics.counter("t_counter_total", labelnames=("x",))


def test_gauge_and_labels():
    g = obs_metrics.gauge("t_gauge", "test gauge", ("shard",))
    g.labels(shard="a").set(4.0)
    g.labels(shard="b").inc(2.0)
    g.labels(shard="b").dec(0.5)
    assert g.labels(shard="a").value == 4.0
    assert g.labels(shard="b").value == 1.5
    assert g.total() == pytest.approx(5.5)
    with pytest.raises(ValueError):
        g.labels(wrong="a")
    with pytest.raises(ValueError):
        g.set(1.0)          # labeled metric needs .labels(...)


def test_histogram_semantics():
    h = obs_metrics.histogram("t_hist_seconds", "test hist",
                              buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(55.55)
    s = h.series()[()]
    assert s.bucket_counts == [1, 1, 1, 1]   # one obs past the last edge
    with h.time():
        pass
    assert h.count == 5


def test_metrics_disabled_flag_noops():
    c = obs_metrics.counter("t_gated_total", "gated")
    v0 = c.value
    flags.set_flag("metrics", False)
    try:
        c.inc()
        assert c.value == v0
    finally:
        flags.set_flag("metrics", True)
    c.inc()
    assert c.value == v0 + 1


def test_prometheus_text_and_json_exposition():
    c = obs_metrics.counter("t_expo_total", "expo test", ("kind",))
    c.labels(kind="x").inc(2)
    h = obs_metrics.histogram("t_expo_seconds", "expo hist",
                              buckets=(1.0, 2.0))
    h.observe(1.5)
    text = obs_metrics.REGISTRY.prometheus_text()
    assert '# TYPE t_expo_total counter' in text
    assert 't_expo_total{kind="x"} 2.0' in text
    assert '# TYPE t_expo_seconds histogram' in text
    assert 't_expo_seconds_bucket' in text and 'le="+Inf"' in text
    assert 't_expo_seconds_count 1' in text
    doc = obs_metrics.REGISTRY.to_json()
    assert doc["schema"] == "paddle_tpu.metrics.v1"
    row = doc["metrics"]["t_expo_total"]
    assert row["type"] == "counter"
    assert row["series"][0] == {"labels": {"kind": "x"}, "value": 2.0}
    json.dumps(doc)      # whole document must be JSON-serializable


# --- executor instrumentation --------------------------------------------

def _compile_counters():
    reg = obs_metrics.REGISTRY
    return (reg.get("executor_compile_total").labels(kind="step").value,
            reg.get("executor_cache_hit_total").value)


def test_executor_cache_hit_and_compile_counters():
    """Acceptance: two identical Executor.run calls -> exactly one
    compile and at least one cache hit, visible via the registry."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    c0, h0 = _compile_counters()
    exe.run(main, feed=feed, fetch_list=[loss])
    exe.run(main, feed=feed, fetch_list=[loss])
    c1, h1 = _compile_counters()
    assert c1 - c0 == 1, "identical runs must compile exactly once"
    assert h1 - h0 >= 1, "second identical run must hit the jit cache"
    assert obs_metrics.REGISTRY.get("executor_step_seconds").total_count() > 0


def test_recompile_storm_warning():
    """Feeding a new batch size every run defeats the jit cache; past the
    threshold the executor warns once (and counts the storm)."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    old = flags.get_flag("recompile_warn_threshold")
    flags.set_flag("recompile_warn_threshold", 2)
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for b in range(1, 6):       # 5 distinct feed shapes
                exe.run(main,
                        feed={"x": np.ones((b, 4), "float32"),
                              "y": np.zeros((b, 1), "int64")},
                        fetch_list=[loss])
        storms = [x for x in w if "recompile storm" in str(x.message)]
        assert len(storms) == 1, "must warn exactly once per fetch key"
    finally:
        flags.set_flag("recompile_warn_threshold", old)


def test_profile_ops_records_per_op_timings():
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    h = obs_metrics.REGISTRY.get("executor_op_seconds")
    n0 = h.total_count()
    flags.set_flag("profile_ops", True)
    profiler.reset_profiler()
    profiler.enable_profiler()
    try:
        exe.run(main, feed={"x": np.ones((2, 4), "float32"),
                            "y": np.zeros((2, 1), "int64")},
                fetch_list=[loss])
    finally:
        flags.set_flag("profile_ops", False)
        profiler.disable_profiler()
    assert h.total_count() > n0
    op_names = {k[0] for k in h.series()}
    assert "mul" in op_names and "cross_entropy" in op_names
    spans = obs_trace.events(cat="op")
    assert any(e["name"] == "op:mul" for e in spans)


# --- unified chrome-trace export -----------------------------------------

def test_unified_chrome_trace_export(tmp_path):
    """Acceptance: a profiled 3-step run exports ONE chrome-trace JSON
    holding both host RecordEvent scopes and executor step spans, with
    schema-valid ph/ts/dur/pid/tid fields sorted by ts."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    profiler.reset_profiler()
    profiler.enable_profiler()
    try:
        with profiler.RecordEvent("my_host_scope"):
            pass
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
    finally:
        profiler.disable_profiler()
    path = str(tmp_path / "trace.json")
    profiler.export_chrome_trace(path)
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    assert spans, "trace must contain complete events"
    for e in spans:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert isinstance(e["name"], str) and e["name"]
    ts = [e["ts"] for e in events if e["ph"] != "M"]
    assert ts == sorted(ts), "events must be sorted by ts"
    names = {e["name"] for e in events}
    assert "my_host_scope" in names              # host RecordEvent scope
    assert any(n.startswith("executor.run") for n in names)
    assert sum(1 for e in spans if e["name"] == "executor.step") == 3
    # lane metadata makes perfetto group the tracks
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in events)


def test_trace_disabled_records_nothing():
    obs_trace.reset()
    obs_trace.disable()
    obs_trace.add_span("ghost", 0.0, 1.0)
    assert obs_trace.events() == []


# --- trainer / memory telemetry ------------------------------------------

def test_telemetry_smoke_train_loop():
    """CI smoke (tier-1, not slow): a 3-step profiled training loop must
    produce zero warnings and a non-empty metrics exposition."""
    def reader():
        rng = np.random.RandomState(0)
        for _ in range(3):
            yield [(rng.rand(4).astype("float32"),
                    np.array([1], "int64")) for _ in range(4)]

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                      act="softmax")
        return layers.mean(layers.cross_entropy(p, y))

    steps0 = obs_metrics.REGISTRY.get("trainer_steps_total").value
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        profiler.reset_profiler()
        profiler.enable_profiler()
        try:
            trainer = pt.Trainer(
                train_func=train_func,
                optimizer_func=lambda: pt.optimizer.SGD(0.1),
                place=pt.CPUPlace())
            trainer.train(num_epochs=1, event_handler=lambda e: None,
                          reader=reader, feed_order=["x", "y"])
            trainer.stop()
        finally:
            profiler.disable_profiler()
    assert caught == [], [str(w.message) for w in caught]
    reg = obs_metrics.REGISTRY
    assert reg.get("trainer_steps_total").value - steps0 == 3
    assert reg.get("trainer_loss_ema").value > 0
    assert reg.get("trainer_examples_per_sec").value > 0
    assert reg.get("device_memory_live_bytes").value > 0
    assert reg.get("device_memory_peak_bytes").value >= \
        reg.get("device_memory_live_bytes").value
    expo = reg.prometheus_text()
    assert expo.strip(), "metrics exposition must be non-empty"
    assert "executor_step_seconds" in expo
    assert "trainer_steps_total" in expo
    markers = [e for e in obs_trace.events()
               if e["name"] == "trainer.step"]
    assert len(markers) == 3


# --- graphviz escaping regression ----------------------------------------

def test_draw_block_graphviz_escapes_special_names(tmp_path):
    """Regression: op/var names with quotes or <> (e.g. `fetch<0>`) must
    not break the emitted DOT syntax."""
    main, _ = _small_program()
    block = main.global_block()
    block.create_var(name='fetch<0>', shape=[1], dtype="float32")
    block.create_var(name='evil"name', shape=[1], dtype="float32")
    block.append_op(type="scale", inputs={"X": ['fetch<0>']},
                    outputs={"Out": ['evil"name']},
                    attrs={"scale": 1.0})
    path = str(tmp_path / "esc.dot")
    dot = open(pt.debugger.draw_block_graphviz(block, path=path)).read()
    assert '\\<' in dot and '\\>' in dot     # angle brackets escaped
    assert '\\"' in dot                      # quote escaped
    assert 'fetch<0>' not in dot             # no raw metacharacters leak
    # every label stays a single balanced quoted string: unescaped-quote
    # count must be even
    unescaped = 0
    prev = ""
    for ch in dot:
        if ch == '"' and prev != "\\":
            unescaped += 1
        prev = ch if not (prev == "\\" and ch == "\\") else ""
    assert unescaped % 2 == 0
