"""Observability tail (ref debugger.py:118 draw_block_graphviz,
contrib/memory_usage_calc.py, contrib/op_frequence.py) + the x32 plane
staying warning-free."""
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _small_program():
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    h = layers.fc(x, size=8, act="relu")
    p = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(p, y))
    pt.optimizer.SGD(0.1).minimize(loss)
    return pt.default_main_program(), loss


def test_draw_block_graphviz(tmp_path):
    main, _ = _small_program()
    path = str(tmp_path / "g.dot")
    out = pt.debugger.draw_block_graphviz(main.global_block(), path=path)
    dot = open(out).read()
    assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
    assert "shape=box" in dot and "shape=ellipse" in dot
    assert "cross_entropy" in dot
    # parameters are shaded; backward hidden by default
    assert "fillcolor" in dot
    assert "@GRAD" not in dot
    full = pt.debugger.draw_block_graphviz(
        main.global_block(), path=str(tmp_path / "g2.dot"),
        show_backward=True)
    assert "autodiff" in open(full).read()


def test_pprint_program_codes():
    main, _ = _small_program()
    txt = pt.debugger.pprint_program_codes(main)
    assert "// block 0" in txt
    assert "mul(" in txt and "cross_entropy(" in txt
    assert "@GRAD" not in txt
    assert "@GRAD" in pt.debugger.pprint_program_codes(
        main, show_backward=True)


def test_memory_usage():
    main, _ = _small_program()
    lo8, hi8, unit8 = pt.contrib.memory_usage(main, batch_size=8)
    lo64, hi64, unit64 = pt.contrib.memory_usage(main, batch_size=64)
    assert 0 < lo8 <= hi8
    # persistable floor is batch-independent; activations grow with B
    scale = {"B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}
    assert lo8 * scale[unit8] == lo64 * scale[unit64]
    assert hi64 * scale[unit64] > hi8 * scale[unit8]
    with pytest.raises(ValueError):
        pt.contrib.memory_usage(main, batch_size=0)


def test_op_freq_statistic():
    main, _ = _small_program()
    uni, adj = pt.contrib.op_freq_statistic(main)
    assert uni["mul"] >= 2 and uni["sgd"] >= 4
    counts = list(uni.values())
    assert counts == sorted(counts, reverse=True)
    assert any("->" in k for k in adj)
    with pytest.raises(TypeError):
        pt.contrib.op_freq_statistic("not a program")


def test_x32_plane_emits_no_truncation_warnings():
    """int64 program dtypes lower to int32 at the dtype plane (x32);
    jax must not warn on every op (round-3 Weak #8)."""
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        pt.reset_default_programs()
        ids = layers.data("ids", [4], dtype="int64")
        emb = layers.embedding(ids, size=[16, 4])
        loss = layers.mean(emb)
        exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
        exe.run(pt.default_startup_program())
        out, = exe.run(pt.default_main_program(),
                       feed={"ids": np.zeros((2, 4), "int64")},
                       fetch_list=[loss])
        assert np.isfinite(float(out))
