"""Observability plane: the metrics registry (counter/gauge/histogram
semantics, exposition), executor compile/cache-hit counters, the unified
chrome-trace export, plus the debug tail (ref debugger.py:118
draw_block_graphviz, contrib/memory_usage_calc.py, contrib/op_frequence.py)
and the x32 plane staying warning-free."""
import json
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, observability
from paddle_tpu.core import flags, profiler
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import trace as obs_trace


def _small_program():
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    h = layers.fc(x, size=8, act="relu")
    p = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(p, y))
    pt.optimizer.SGD(0.1).minimize(loss)
    return pt.default_main_program(), loss


def test_draw_block_graphviz(tmp_path):
    main, _ = _small_program()
    path = str(tmp_path / "g.dot")
    out = pt.debugger.draw_block_graphviz(main.global_block(), path=path)
    dot = open(out).read()
    assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
    assert "shape=box" in dot and "shape=ellipse" in dot
    assert "cross_entropy" in dot
    # parameters are shaded; backward hidden by default
    assert "fillcolor" in dot
    assert "@GRAD" not in dot
    full = pt.debugger.draw_block_graphviz(
        main.global_block(), path=str(tmp_path / "g2.dot"),
        show_backward=True)
    assert "autodiff" in open(full).read()


def test_pprint_program_codes():
    main, _ = _small_program()
    txt = pt.debugger.pprint_program_codes(main)
    assert "// block 0" in txt
    assert "mul(" in txt and "cross_entropy(" in txt
    assert "@GRAD" not in txt
    assert "@GRAD" in pt.debugger.pprint_program_codes(
        main, show_backward=True)


def test_memory_usage():
    main, _ = _small_program()
    lo8, hi8, unit8 = pt.contrib.memory_usage(main, batch_size=8)
    lo64, hi64, unit64 = pt.contrib.memory_usage(main, batch_size=64)
    assert 0 < lo8 <= hi8
    # persistable floor is batch-independent; activations grow with B
    scale = {"B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}
    assert lo8 * scale[unit8] == lo64 * scale[unit64]
    assert hi64 * scale[unit64] > hi8 * scale[unit8]
    with pytest.raises(ValueError):
        pt.contrib.memory_usage(main, batch_size=0)


def test_op_freq_statistic():
    main, _ = _small_program()
    uni, adj = pt.contrib.op_freq_statistic(main)
    assert uni["mul"] >= 2 and uni["sgd"] >= 4
    counts = list(uni.values())
    assert counts == sorted(counts, reverse=True)
    assert any("->" in k for k in adj)
    with pytest.raises(TypeError):
        pt.contrib.op_freq_statistic("not a program")


def test_x32_plane_emits_no_truncation_warnings():
    """int64 program dtypes lower to int32 at the dtype plane (x32);
    jax must not warn on every op (round-3 Weak #8)."""
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        pt.reset_default_programs()
        ids = layers.data("ids", [4], dtype="int64")
        emb = layers.embedding(ids, size=[16, 4])
        loss = layers.mean(emb)
        exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
        exe.run(pt.default_startup_program())
        out, = exe.run(pt.default_main_program(),
                       feed={"ids": np.zeros((2, 4), "int64")},
                       fetch_list=[loss])
        assert np.isfinite(float(out))


# --- metrics registry semantics ------------------------------------------

def test_counter_semantics():
    c = obs_metrics.counter("t_counter_total", "test counter")
    v0 = c.value
    c.inc()
    c.inc(2.5)
    assert c.value == pytest.approx(v0 + 3.5)
    with pytest.raises(ValueError):
        c.inc(-1)
    # idempotent get-or-create returns the SAME metric
    assert obs_metrics.counter("t_counter_total") is c
    # re-registration with a different shape is an error
    with pytest.raises(ValueError):
        obs_metrics.gauge("t_counter_total")
    with pytest.raises(ValueError):
        obs_metrics.counter("t_counter_total", labelnames=("x",))


def test_gauge_and_labels():
    g = obs_metrics.gauge("t_gauge", "test gauge", ("shard",))
    g.labels(shard="a").set(4.0)
    g.labels(shard="b").inc(2.0)
    g.labels(shard="b").dec(0.5)
    assert g.labels(shard="a").value == 4.0
    assert g.labels(shard="b").value == 1.5
    assert g.total() == pytest.approx(5.5)
    with pytest.raises(ValueError):
        g.labels(wrong="a")
    with pytest.raises(ValueError):
        g.set(1.0)          # labeled metric needs .labels(...)


def test_histogram_semantics():
    h = obs_metrics.histogram("t_hist_seconds", "test hist",
                              buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(55.55)
    s = h.series()[()]
    assert s.bucket_counts == [1, 1, 1, 1]   # one obs past the last edge
    with h.time():
        pass
    assert h.count == 5


def test_metrics_disabled_flag_noops():
    c = obs_metrics.counter("t_gated_total", "gated")
    v0 = c.value
    flags.set_flag("metrics", False)
    try:
        c.inc()
        assert c.value == v0
    finally:
        flags.set_flag("metrics", True)
    c.inc()
    assert c.value == v0 + 1


def test_prometheus_text_and_json_exposition():
    c = obs_metrics.counter("t_expo_total", "expo test", ("kind",))
    c.labels(kind="x").inc(2)
    h = obs_metrics.histogram("t_expo_seconds", "expo hist",
                              buckets=(1.0, 2.0))
    h.observe(1.5)
    text = obs_metrics.REGISTRY.prometheus_text()
    assert '# TYPE t_expo_total counter' in text
    assert 't_expo_total{kind="x"} 2.0' in text
    assert '# TYPE t_expo_seconds histogram' in text
    assert 't_expo_seconds_bucket' in text and 'le="+Inf"' in text
    assert 't_expo_seconds_count 1' in text
    doc = obs_metrics.REGISTRY.to_json()
    assert doc["schema"] == "paddle_tpu.metrics.v1"
    row = doc["metrics"]["t_expo_total"]
    assert row["type"] == "counter"
    assert row["series"][0] == {"labels": {"kind": "x"}, "value": 2.0}
    json.dumps(doc)      # whole document must be JSON-serializable


# --- executor instrumentation --------------------------------------------

def _compile_counters():
    reg = obs_metrics.REGISTRY
    return (reg.get("executor_compile_total").labels(kind="step").value,
            reg.get("executor_cache_hit_total").value)


def test_executor_cache_hit_and_compile_counters():
    """Acceptance: two identical Executor.run calls -> exactly one
    compile and at least one cache hit, visible via the registry."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    c0, h0 = _compile_counters()
    exe.run(main, feed=feed, fetch_list=[loss])
    exe.run(main, feed=feed, fetch_list=[loss])
    c1, h1 = _compile_counters()
    assert c1 - c0 == 1, "identical runs must compile exactly once"
    assert h1 - h0 >= 1, "second identical run must hit the jit cache"
    assert obs_metrics.REGISTRY.get("executor_step_seconds").total_count() > 0


def test_recompile_storm_warning():
    """Feeding a new batch size every run defeats the jit cache; past the
    threshold the executor warns once (and counts the storm)."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    old = flags.get_flag("recompile_warn_threshold")
    flags.set_flag("recompile_warn_threshold", 2)
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for b in range(1, 6):       # 5 distinct feed shapes
                exe.run(main,
                        feed={"x": np.ones((b, 4), "float32"),
                              "y": np.zeros((b, 1), "int64")},
                        fetch_list=[loss])
        storms = [x for x in w if "recompile storm" in str(x.message)]
        assert len(storms) == 1, "must warn exactly once per fetch key"
    finally:
        flags.set_flag("recompile_warn_threshold", old)


def test_profile_ops_records_per_op_timings():
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    h = obs_metrics.REGISTRY.get("executor_op_seconds")
    n0 = h.total_count()
    flags.set_flag("profile_ops", True)
    profiler.reset_profiler()
    profiler.enable_profiler()
    try:
        exe.run(main, feed={"x": np.ones((2, 4), "float32"),
                            "y": np.zeros((2, 1), "int64")},
                fetch_list=[loss])
    finally:
        flags.set_flag("profile_ops", False)
        profiler.disable_profiler()
    assert h.total_count() > n0
    op_names = {k[0] for k in h.series()}
    assert "mul" in op_names and "cross_entropy" in op_names
    spans = obs_trace.events(cat="op")
    assert any(e["name"] == "op:mul" for e in spans)


# --- unified chrome-trace export -----------------------------------------

def test_unified_chrome_trace_export(tmp_path):
    """Acceptance: a profiled 3-step run exports ONE chrome-trace JSON
    holding both host RecordEvent scopes and executor step spans, with
    schema-valid ph/ts/dur/pid/tid fields sorted by ts."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    profiler.reset_profiler()
    profiler.enable_profiler()
    try:
        with profiler.RecordEvent("my_host_scope"):
            pass
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
    finally:
        profiler.disable_profiler()
    path = str(tmp_path / "trace.json")
    profiler.export_chrome_trace(path)
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    assert spans, "trace must contain complete events"
    for e in spans:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert isinstance(e["name"], str) and e["name"]
    ts = [e["ts"] for e in events if e["ph"] != "M"]
    assert ts == sorted(ts), "events must be sorted by ts"
    names = {e["name"] for e in events}
    assert "my_host_scope" in names              # host RecordEvent scope
    assert any(n.startswith("executor.run") for n in names)
    assert sum(1 for e in spans if e["name"] == "executor.step") == 3
    # lane metadata makes perfetto group the tracks
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in events)


def test_trace_disabled_records_nothing():
    obs_trace.reset()
    obs_trace.disable()
    obs_trace.add_span("ghost", 0.0, 1.0)
    assert obs_trace.events() == []


# --- trainer / memory telemetry ------------------------------------------

def test_telemetry_smoke_train_loop():
    """CI smoke (tier-1, not slow): a 3-step profiled training loop must
    produce zero warnings and a non-empty metrics exposition."""
    def reader():
        rng = np.random.RandomState(0)
        for _ in range(3):
            yield [(rng.rand(4).astype("float32"),
                    np.array([1], "int64")) for _ in range(4)]

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                      act="softmax")
        return layers.mean(layers.cross_entropy(p, y))

    steps0 = obs_metrics.REGISTRY.get("trainer_steps_total").value
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        profiler.reset_profiler()
        profiler.enable_profiler()
        try:
            trainer = pt.Trainer(
                train_func=train_func,
                optimizer_func=lambda: pt.optimizer.SGD(0.1),
                place=pt.CPUPlace())
            trainer.train(num_epochs=1, event_handler=lambda e: None,
                          reader=reader, feed_order=["x", "y"])
            trainer.stop()
        finally:
            profiler.disable_profiler()
    assert caught == [], [str(w.message) for w in caught]
    reg = obs_metrics.REGISTRY
    assert reg.get("trainer_steps_total").value - steps0 == 3
    assert reg.get("trainer_loss_ema").value > 0
    assert reg.get("trainer_examples_per_sec").value > 0
    assert reg.get("device_memory_live_bytes").value > 0
    assert reg.get("device_memory_peak_bytes").value >= \
        reg.get("device_memory_live_bytes").value
    expo = reg.prometheus_text()
    assert expo.strip(), "metrics exposition must be non-empty"
    assert "executor_step_seconds" in expo
    assert "trainer_steps_total" in expo
    markers = [e for e in obs_trace.events()
               if e["name"] == "trainer.step"]
    assert len(markers) == 3


# --- graphviz escaping regression ----------------------------------------

def test_draw_block_graphviz_escapes_special_names(tmp_path):
    """Regression: op/var names with quotes or <> (e.g. `fetch<0>`) must
    not break the emitted DOT syntax."""
    main, _ = _small_program()
    block = main.global_block()
    block.create_var(name='fetch<0>', shape=[1], dtype="float32")
    block.create_var(name='evil"name', shape=[1], dtype="float32")
    block.append_op(type="scale", inputs={"X": ['fetch<0>']},
                    outputs={"Out": ['evil"name']},
                    attrs={"scale": 1.0})
    path = str(tmp_path / "esc.dot")
    dot = open(pt.debugger.draw_block_graphviz(block, path=path)).read()
    assert '\\<' in dot and '\\>' in dot     # angle brackets escaped
    assert '\\"' in dot                      # quote escaped
    assert 'fetch<0>' not in dot             # no raw metacharacters leak
    # every label stays a single balanced quoted string: unescaped-quote
    # count must be even
    unescaped = 0
    prev = ""
    for ch in dot:
        if ch == '"' and prev != "\\":
            unescaped += 1
        prev = ch if not (prev == "\\" and ch == "\\") else ""
    assert unescaped % 2 == 0


# =========================================================================
# ISSUE 3: compiled-program introspection plane — cost model, recompile
# forensics, flight recorder, bench gate, exposition escaping.
# =========================================================================

import dataclasses
import os
import subprocess
import sys

from paddle_tpu.observability import bench_gate, costmodel, flight, forensics
from paddle_tpu.resilience import guard as rguard
from paddle_tpu.resilience import retry as rretry


# --- prometheus exposition escaping (satellite) ---------------------------

def test_prometheus_escaping_help_and_label_values():
    """HELP must escape backslash/newline; label values must escape
    backslash/newline/double-quote — raw, they corrupt the scrape."""
    c = obs_metrics.counter(
        "t_esc_total", 'help with "quotes", a \\ and a\nnewline',
        ("path",))
    c.labels(path='C:\\tmp\n"quoted"').inc()
    text = obs_metrics.REGISTRY.prometheus_text()
    assert ('# HELP t_esc_total help with "quotes", a \\\\ '
            'and a\\nnewline') in text
    assert 'path="C:\\\\tmp\\n\\"quoted\\""' in text
    # the escaped forms must be the ONLY occurrences: no raw newline may
    # survive inside a HELP line or a label value
    for line in text.splitlines():
        if "t_esc_total" in line:
            assert "\n" not in line


# --- cost model (tentpole part 1) -----------------------------------------

def _run_small(exe=None):
    main, loss = _small_program()
    exe = exe or pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    exe.run(main, feed=feed, fetch_list=[loss])
    return exe, main, loss, feed


def test_explain_reports_cost_on_cpu_backend():
    """Acceptance: Executor.explain() returns per-program FLOPs / bytes
    accessed / peak HBM on the CPU backend, plus the schema the docs
    promise."""
    exe, main, loss, feed = _run_small()
    rep = exe.explain(main, feed=feed, fetch_list=[loss])
    assert rep["schema"] == "paddle_tpu.explain.v1"
    assert set(rep) >= {"program", "feeds", "fetches", "state", "cost",
                        "cache", "flags"}
    cost = rep["cost"]
    assert cost is not None and cost["source"] in ("xla", "analytic")
    assert cost["flops"] > 0
    assert cost["bytes_accessed"] > 0
    assert cost["peak_hbm_bytes"] > 0
    assert cost["argument_bytes"] > 0
    assert rep["program"]["op_histogram"].get("mul", 0) >= 2
    assert rep["feeds"]["x"] == {"shape": [4, 4], "dtype": "float32"}
    assert rep["fetches"] == [loss.name]
    assert rep["cache"]["compiles_for_key"] >= 1
    json.dumps(rep)          # the whole report must be JSON-clean
    # the registry carries the same numbers as gauges
    g = obs_metrics.REGISTRY.get("program_cost_flops")
    assert any(s.value == cost["flops"] for s in g.series().values())
    assert obs_metrics.REGISTRY.get(
        "program_cost_peak_hbm_bytes").total() > 0


def test_explain_does_not_consume_rng_or_recompile():
    """explain() must be a pure observer: same executable cache, same
    RNG sequence for subsequent runs."""
    exe, main, loss, feed = _run_small()
    before = exe._run_counter
    c0 = obs_metrics.REGISTRY.get("executor_compile_total").labels(
        kind="step").value
    exe.explain(main, feed=feed, fetch_list=[loss])
    assert exe._run_counter == before
    c1 = obs_metrics.REGISTRY.get("executor_compile_total").labels(
        kind="step").value
    assert c1 == c0, "explain on a cached key must not compile a new step"


def test_cost_model_covers_run_steps_device_loop():
    """A run_steps _multi_cache entry gets its own cost row in the
    cache explorer."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((3, 4, 4), "float32"),
            "y": np.zeros((3, 4, 1), "int64")}
    exe.run_steps(main, feed=feed, fetch_list=[loss], steps=3,
                  per_step_feeds=["x", "y"])
    rep = exe.cache_report()
    assert rep["schema"] == "paddle_tpu.cache_report.v1"
    multi = [m for p in rep["programs"] for m in p["multi"]]
    assert multi, "run_steps must appear in the cache explorer"
    assert multi[0]["steps"] == 3
    assert multi[0]["cost"] is not None
    assert multi[0]["cost"]["flops"] > 0
    json.dumps(rep)
    # and the registry gained a multi-labelled program cost series
    g = obs_metrics.REGISTRY.get("program_cost_flops")
    assert any("multi3" in key[0] for key in g.series())


def test_cost_model_flag_gates_analysis():
    exe, main, loss, feed = _run_small()
    flags.set_flag("cost_model", False)
    try:
        rep = exe.explain(main, feed=feed, fetch_list=[loss])
        assert rep["cost"] is None
    finally:
        flags.set_flag("cost_model", True)


def test_cost_model_matches_analytic_transformer_within_10pct():
    """Acceptance (the bench.py cross-check): XLA's FLOPs for the
    transformer-LM train step agree with the old hand-rolled analytic
    formula within 10% — the contract that let bench.py drop the
    formula."""
    from paddle_tpu import models
    D, F, L, V, T, B = 128, 512, 2, 2000, 64, 2
    pt.reset_default_programs()
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T,
        n_layer=L, n_head=8, d_model=D, d_inner=F, dropout=0.0)
    _, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=T, fused_attention=False, fused_head=False)
    pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = models.transformer.make_fake_lm_batch(cfg, B, T)
    rep = exe.explain(pt.default_main_program(), feed=feed,
                      fetch_list=[avg_cost])
    assert rep["cost"] is not None and rep["cost"]["source"] == "xla"
    flops = rep["cost"]["flops"]
    analytic = 3 * (L * (8 * D * D + 4 * D * F) + L * 2 * T * D
                    + 2 * D * V) * B * T
    assert 0.9 < flops / analytic < 1.1, (flops, analytic)


def test_trainer_exports_cost_model_mfu_gauge():
    """Acceptance: the trainer's MFU/TFLOPs gauges are cost-model
    derived (model-agnostic) and agree with the analytic transformer
    number within 10%."""
    from paddle_tpu import models
    D, F, L, V, T = 128, 512, 2, 2000, 64
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T,
        n_layer=L, n_head=8, d_model=D, d_inner=F, dropout=0.0)

    def train_func():
        _, avg_cost, _ = models.transformer.build_lm_net(
            cfg, seq_len=T, fused_attention=False, fused_head=False)
        return avg_cost

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(2):
            batch = []
            for _ in range(2):
                toks = rng.randint(1, V, (T,)).astype("int64")
                batch.append((toks, np.roll(toks, -1)))
            yield batch

    flags.set_flag("device_peak_flops", 1e12)
    try:
        trainer = pt.Trainer(train_func,
                             lambda: pt.optimizer.SGD(0.1),
                             place=pt.CPUPlace())
        trainer.train(num_epochs=1, event_handler=lambda e: None,
                      reader=reader, feed_order=["tokens", "labels"])
        trainer.stop()
    finally:
        flags.set_flag("device_peak_flops", 0.0)
    reg = obs_metrics.REGISTRY
    flops = reg.get("trainer_flops_per_step").value
    analytic = 3 * (L * (8 * D * D + 4 * D * F) + L * 2 * T * D
                    + 2 * D * V) * 2 * T
    assert flops > 0
    assert 0.9 < flops / analytic < 1.1, (flops, analytic)
    assert reg.get("trainer_tflops").value > 0
    # mfu = (flops/dt) / peak with the peak pinned by the flag
    assert reg.get("trainer_mfu").value > 0


# --- recompile forensics (tentpole part 2) --------------------------------

def test_recompile_cause_feed_shape_drift():
    exe, main, loss, feed = _run_small()
    exe.run(main, feed={"x": np.ones((2, 4), "float32"),
                        "y": np.zeros((2, 1), "int64")},
            fetch_list=[loss])
    rec = exe.compile_log(main)[-1]
    assert rec["causes"] == ["feed_shapes"]
    assert any("x: (4, 4)->(2, 4)" in d for d in rec["details"])


def test_recompile_cause_fetch_program_and_flags_drift():
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    h = layers.fc(x, size=8, act="relu")
    out = layers.mean(h)
    main = pt.default_main_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((4, 4), "float32")}
    exe.run(main, feed=feed, fetch_list=[out])
    # 1. new fetch set on a known program -> fetch_names
    exe.run(main, feed=feed, fetch_list=[out, h])
    assert exe.compile_log(main)[-1]["causes"] == ["fetch_names"]
    # 2. program mutation -> program_version
    block = main.global_block()
    block.create_var(name="t_extra", shape=[1], dtype="float32")
    block.append_op(type="scale", inputs={"X": [out.name]},
                    outputs={"Out": ["t_extra"]}, attrs={"scale": 2.0})
    exe.run(main, feed=feed, fetch_list=[out])
    assert "program_version" in exe.compile_log(main)[-1]["causes"]
    # 3. numerics flag toggle -> flags
    flags.set_flag("amp_bf16", True)
    try:
        exe.run(main, feed=feed, fetch_list=[out])
    finally:
        flags.set_flag("amp_bf16", False)
    rec = exe.compile_log(main)[-1]
    assert "flags" in rec["causes"]
    assert any("amp_bf16" in d for d in rec["details"])


def test_forensics_scopes_retention_per_executor():
    """A second Executor compiling the same (program, fetch-list) with
    identical feeds is a first compile in ITS cache — not a phantom
    drift against the first executor's retained key."""
    main, loss = _small_program()
    feed = {"x": np.ones((4, 4), "float32"),
            "y": np.zeros((4, 1), "int64")}
    exe1 = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe1.run(pt.default_startup_program())
    exe1.run(main, feed=feed, fetch_list=[loss])
    exe2 = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe2.run(pt.default_startup_program())
    exe2.run(main, feed=feed, fetch_list=[loss])
    recs = [r for r in exe1.compile_log(main)
            if r["fetches"] == [loss.name]]
    assert [r["causes"] for r in recs[-2:]] == \
        [["first_compile"], ["first_compile"]]


def test_forensics_diff_keys_unit():
    """Component-wise diff vocabulary: drift each component of a
    synthetic cache key and assert the named cause."""
    base = forensics.KeyParts(
        program_uid=7, program_version=3,
        feeds=(("x", (4, 4), "float32"),),
        fetch_names=("loss",),
        state=(("w", (4, 8), "float32"),),
        flags=(("amp_bf16", False),))

    def causes(**kw):
        return [c for c, _ in forensics.diff_keys(
            base, dataclasses.replace(base, **kw))]

    assert causes() == []
    assert causes(feeds=(("x", (8, 4), "float32"),)) == ["feed_shapes"]
    assert causes(feeds=(("x", (4, 4), "float64"),)) == ["feed_dtypes"]
    assert causes(feeds=(("x", (4, 4), "float32"),
                         ("z", (1,), "int64"))) == ["feed_set"]
    assert causes(state=(("w", (4, 16), "float32"),)) == \
        ["state_signature"]
    assert causes(state=(("w", (4, 8), "bfloat16"),)) == \
        ["state_signature"]
    assert causes(program_version=4) == ["program_version"]
    assert causes(fetch_names=("loss", "acc")) == ["fetch_names"]
    assert causes(flags=(("amp_bf16", True),)) == ["flags"]
    # compound drift names every component, shapes first
    got = causes(feeds=(("x", (8, 4), "float64"),), program_version=9)
    assert set(got) == {"feed_shapes", "feed_dtypes", "program_version"}


def test_recompile_storm_warning_names_cause():
    """The storm warning (satellite): names the drifting component and
    the cause-labelled counter increments."""
    main, loss = _small_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    old = flags.get_flag("recompile_warn_threshold")
    flags.set_flag("recompile_warn_threshold", 2)
    storm = obs_metrics.REGISTRY.get("executor_recompile_storm_total")
    s0 = storm.total()
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for b in range(1, 5):
                exe.run(main,
                        feed={"x": np.ones((b, 4), "float32"),
                              "y": np.zeros((b, 1), "int64")},
                        fetch_list=[loss])
    finally:
        flags.set_flag("recompile_warn_threshold", old)
    storms = [str(x.message) for x in w
              if "recompile storm" in str(x.message)]
    assert len(storms) == 1
    assert "feed_shapes" in storms[0], storms[0]
    assert "x:" in storms[0]          # the latest drift detail is named
    assert storm.total() - s0 == 1
    assert ("feed_shapes",) in storm.series()
    assert obs_metrics.REGISTRY.get(
        "executor_recompile_cause_total").labels(
            cause="feed_shapes").value >= 3


# --- flight recorder (tentpole part 3) ------------------------------------

def _flight_trainer():
    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, bias_attr=False)
        return layers.mean(layers.square_error_cost(pred, y))

    return pt.Trainer(train_func, lambda: pt.optimizer.SGD(0.05),
                      place=pt.CPUPlace())


def _flight_batches(n, bs=4):
    rng = np.random.RandomState(0)
    return [[(rng.randn(4).astype("float32"),
              rng.randn(1).astype("float32")) for _ in range(bs)]
            for _ in range(n)]


def test_flight_recorder_bundle_on_numeric_guard_trip(tmp_path):
    """Acceptance: a forced NumericGuard trip emits one JSON diagnostic
    bundle — bounded, valid, carrying the event ring + metrics +
    cost summaries + flag state."""
    path = str(tmp_path / "flight.json")
    flags.set_flag("flight_recorder_path", path)
    flags.set_flag("chaos_seed", 0)
    flags.set_flag("chaos_spec", "trainer.step=nan:1.0")
    try:
        t = _flight_trainer()
        with pytest.raises(rguard.BadStepError):
            t.train(num_epochs=1, event_handler=lambda e: None,
                    reader=lambda: iter(_flight_batches(4)),
                    feed_order=["x", "y"])
    finally:
        flags.set_flag("flight_recorder_path", "")
        flags.set_flag("chaos_spec", "")
    with open(path) as f:
        doc = json.load(f)                 # must be valid JSON
    # STRICT json: the trigger is a NaN loss, and a bare NaN token
    # would corrupt the bundle for every non-Python consumer
    json.dumps(doc, allow_nan=False)
    assert doc["schema"] == "paddle_tpu.flight.v1"
    assert doc["reason"] == "numeric_guard"
    assert doc["extra"]["verdict"] == "nan"
    assert doc["extra"]["loss"] == "nan"   # stringified, not NaN
    kinds = {e["kind"] for e in doc["events"]}
    assert {"guard", "chaos", "span", "compile"} <= kinds
    cap = int(flags.get_flag("flight_recorder_events"))
    assert len(doc["events"]) <= cap
    assert len(json.dumps(doc)) < (1 << 20)     # bounded bundle
    assert doc["counter_deltas"].get("trainer_bad_steps_total", 0) >= 1
    assert "flags" in doc and "program_costs" in doc \
        and "compile_log" in doc and "metrics" in doc
    assert flight.last_bundle()["reason"] == "numeric_guard"
    assert flight.dump_count() >= 1


def test_flight_recorder_bundle_on_retry_exhaustion(tmp_path):
    path = str(tmp_path / "flight_retry.json")
    flags.set_flag("flight_recorder_path", path)
    pol = rretry.RetryPolicy(name="t_flight", max_attempts=2,
                             base_delay=0.001, jitter=0.0,
                             retry_on=(OSError,))
    try:
        with pytest.raises(OSError):
            rretry.call_with_retry(
                lambda: (_ for _ in ()).throw(OSError("disk gone")), pol)
    finally:
        flags.set_flag("flight_recorder_path", "")
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "retry_exhausted"
    assert doc["extra"]["policy"] == "t_flight"
    assert doc["extra"]["attempts"] == 2
    assert any(e["kind"] == "retry" and e["name"] == "t_flight"
               for e in doc["events"])


def test_flight_recorder_ring_is_bounded_and_gateable():
    flight.reset()
    old = flags.get_flag("flight_recorder_events")
    flags.set_flag("flight_recorder_events", 8)
    try:
        for i in range(50):
            flight.record("span", f"e{i}", i=i)
        evs = flight.events()
        assert len(evs) == 8
        assert evs[-1]["name"] == "e49"     # newest kept, oldest dropped
        flags.set_flag("flight_recorder_events", 0)
        flight.record("span", "ghost")
        assert len(flight.events()) == 8    # capacity 0: recording off
    finally:
        flags.set_flag("flight_recorder_events", old)
    # in-memory dump works without a configured path (no file side
    # effect) and never raises
    assert flight.dump("unit_test") is None
    assert flight.last_bundle()["reason"] == "unit_test"


# --- bench gate (satellite) -----------------------------------------------

def _gate_inputs():
    base = {"parsed": {"summary": {
        "a_tokens_per_sec": {"value": 100.0, "vs_baseline": 2.0},
        "b_ms_per_batch": {"value": 10.0},
        "c_gone_metric": {"value": 5.0}}}}
    cand = {"schema": "paddle_tpu.metrics.v1", "metrics": {
        "bench_value": {"type": "gauge", "help": "", "series": [
            {"labels": {"metric": "a_tokens_per_sec",
                        "unit": "tokens/s"}, "value": 90.0},
            {"labels": {"metric": "b_ms_per_batch",
                        "unit": "ms/batch"}, "value": 10.5},
            {"labels": {"metric": "d_new_metric", "unit": "x"},
             "value": 1.0}]}}}
    return base, cand


def test_bench_gate_formats_directions_and_verdicts():
    base, cand = _gate_inputs()
    bvals = bench_gate.load_metric_values(base)
    cvals = bench_gate.load_metric_values(cand)
    assert bvals == {"a_tokens_per_sec": 100.0, "b_ms_per_batch": 10.0,
                     "c_gone_metric": 5.0}
    assert cvals["a_tokens_per_sec"] == 90.0
    res = bench_gate.gate(bvals, cvals, tolerance=0.15)
    statuses = {r["metric"]: r["status"] for r in res["rows"]}
    assert statuses == {"a_tokens_per_sec": "ok",
                        "b_ms_per_batch": "ok",
                        "c_gone_metric": "missing",
                        "d_new_metric": "new"}
    assert not res["ok"]                        # missing fails by default
    assert bench_gate.gate(bvals, cvals, 0.15, allow_missing=True)["ok"]
    # higher-is-better regression: tokens/s drop past tolerance
    r2 = bench_gate.gate(bvals, dict(cvals, a_tokens_per_sec=80.0),
                         0.15, allow_missing=True)
    assert r2["regressions"] == ["a_tokens_per_sec"]
    # lower-is-better regression: ms/batch INCREASE past tolerance
    r3 = bench_gate.gate(bvals, dict(cvals, b_ms_per_batch=20.0),
                         0.15, allow_missing=True)
    assert r3["regressions"] == ["b_ms_per_batch"]
    # improvement in a lower-is-better metric is never a regression
    r4 = bench_gate.gate(bvals, dict(cvals, b_ms_per_batch=1.0),
                         0.15, allow_missing=True)
    assert r4["ok"]


def test_bench_gate_cli_exit_codes(tmp_path, capsys):
    base, cand = _gate_inputs()
    bp, cp = str(tmp_path / "base.json"), str(tmp_path / "cand.json")
    with open(bp, "w") as f:
        json.dump(base, f)
    with open(cp, "w") as f:
        json.dump(cand, f)
    assert bench_gate.main(["--baseline", bp, "--candidate", cp,
                            "--allow-missing"]) == 0
    assert bench_gate.main(["--baseline", bp, "--candidate", cp]) == 1
    out = capsys.readouterr().out
    assert "[MISS] c_gone_metric" in out
    assert "[  ok] a_tokens_per_sec" in out
    assert bench_gate.main(["--baseline", str(tmp_path / "nope.json"),
                            "--candidate", cp]) == 2
    # a JSON file whose top level is not an object is bad input (rc 2),
    # not a traceback
    lp = str(tmp_path / "list.json")
    with open(lp, "w") as f:
        json.dump([1, 2], f)
    assert bench_gate.main(["--baseline", lp, "--candidate", cp]) == 2


@pytest.mark.slow
def test_bench_metrics_feed_the_gate_end_to_end(tmp_path):
    """Full pipeline: bench.py -> bench_metrics.json -> bench_gate
    self-compare (rc 0).  Slow: runs the real benchmarks on CPU."""
    mpath = str(tmp_path / "bench_metrics.json")
    env = dict(os.environ, PTPU_BENCH_METRICS_PATH=mpath,
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "bench.py"], env=env,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))),
                          capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(mpath) as f:
        doc = json.load(f)
    vals = bench_gate.load_metric_values(doc)
    assert vals, "bench must publish bench_value rows"
    # per-benchmark flops_per_step rides the same dump
    assert "bench_flops_per_step" in doc["metrics"]
    assert bench_gate.main(["--baseline", mpath,
                            "--candidate", mpath]) == 0


def test_parallel_executor_explain_covers_pjit_program():
    """The tentpole covers the parallel plane too: the mesh executor's
    pjit program yields a cost report through ParallelExecutor.explain."""
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                  act="softmax")
    loss = layers.mean(layers.cross_entropy(p, y))
    pt.optimizer.SGD(0.1).minimize(loss)
    pexe = pt.ParallelExecutor(loss_name=loss.name)
    pexe._exe.run(pt.default_startup_program())
    feed = {"x": np.ones((8, 4), "float32"),
            "y": np.zeros((8, 1), "int64")}
    pexe.run(fetch_list=[loss], feed=feed)
    rep = pexe.explain([loss], feed=feed)
    assert rep["schema"] == "paddle_tpu.explain.v1"
    assert rep["cost"] is not None
    assert rep["cost"]["flops"] > 0
    assert rep["cost"]["peak_hbm_bytes"] > 0
    assert pexe.cache_report()["cached_programs"] >= 1
