"""Model-zoo smoke tests: build each BASELINE config, run train steps, check
the loss is finite and decreases on a fixed batch (the reference's book-test
contract: tests/book/* assert loss decrease)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models


def _train(feeds, loss, feed_dict, steps=3, lr=0.01, opt=None):
    opt = opt or pt.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(steps):
        out, = exe.run(pt.default_main_program(), feed=feed_dict,
                       fetch_list=[loss])
        losses.append(float(out))
    return losses


def test_lenet_mnist_trains():
    feeds, avg_loss, acc, pred = models.lenet.build_train_net()
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(8, 1, 28, 28).astype("float32"),
            "label": rng.randint(0, 10, (8, 1)).astype("int64")}
    losses = _train(feeds, avg_loss, feed, steps=4, lr=0.01)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_resnet18_cifar_builds_and_steps():
    feeds, avg_loss, acc, pred = models.resnet.build_train_net(
        class_dim=10, img_shape=(3, 32, 32), depth=18)
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(4, 3, 32, 32).astype("float32"),
            "label": rng.randint(0, 10, (4, 1)).astype("int64")}
    losses = _train(feeds, avg_loss, feed, steps=2, lr=0.1)
    assert np.isfinite(losses).all()


def test_transformer_tiny_trains():
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=100, tgt_vocab_size=100, max_length=32,
        n_layer=2, n_head=2, d_model=32, d_inner=64, dropout=0.0)
    feeds, avg_cost, logits = models.transformer.build_train_net(
        cfg, src_len=8, tgt_len=8)
    feed = models.transformer.make_fake_batch(cfg, 4, 8, 8)
    losses = _train(feeds, avg_cost, feed, steps=4, lr=0.1,
                    opt=pt.optimizer.Adam(learning_rate=1e-3))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_deepfm_trains():
    cfg = models.deepfm.DeepFMConfig(num_field=10, vocab_size=1000,
                                     embed_dim=8, fc_sizes=(32, 32))
    feeds, avg_cost, prob = models.deepfm.build_train_net(cfg)
    feed = models.deepfm.make_fake_batch(cfg, 16)
    losses = _train(feeds, avg_cost, feed, steps=4, lr=0.1)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_bert_tiny_trains():
    cfg = models.bert.BertConfig(vocab_size=200, hidden_size=32,
                                 num_layers=2, num_heads=2,
                                 intermediate_size=64, max_position=64,
                                 dropout=0.0)
    feeds, total_loss, (mlm, nsp) = models.bert.build_pretrain_net(
        cfg, seq_len=16)
    feed = models.bert.make_fake_batch(cfg, 4, 16, max_preds=4)
    losses = _train(feeds, total_loss, feed, steps=4,
                    opt=pt.optimizer.Adam(learning_rate=1e-3))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_vgg_cifar_builds():
    feeds, avg_loss, acc, pred = models.vgg.build_train_net(
        class_dim=10, img_shape=(3, 32, 32))
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(2, 3, 32, 32).astype("float32"),
            "label": rng.randint(0, 10, (2, 1)).astype("int64")}
    losses = _train(feeds, avg_loss, feed, steps=1, lr=0.01)
    assert np.isfinite(losses).all()
