"""Model-zoo smoke tests: build each BASELINE config, run train steps, check
the loss is finite and decreases on a fixed batch (the reference's book-test
contract: tests/book/* assert loss decrease)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models


def _train(feeds, loss, feed_dict, steps=3, lr=0.01, opt=None):
    opt = opt or pt.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(steps):
        out, = exe.run(pt.default_main_program(), feed=feed_dict,
                       fetch_list=[loss])
        losses.append(float(out))
    return losses


def test_lenet_mnist_trains():
    feeds, avg_loss, acc, pred = models.lenet.build_train_net()
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(8, 1, 28, 28).astype("float32"),
            "label": rng.randint(0, 10, (8, 1)).astype("int64")}
    losses = _train(feeds, avg_loss, feed, steps=4, lr=0.01)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_resnet18_cifar_builds_and_steps():
    feeds, avg_loss, acc, pred = models.resnet.build_train_net(
        class_dim=10, img_shape=(3, 32, 32), depth=18)
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(4, 3, 32, 32).astype("float32"),
            "label": rng.randint(0, 10, (4, 1)).astype("int64")}
    losses = _train(feeds, avg_loss, feed, steps=2, lr=0.1)
    assert np.isfinite(losses).all()


def test_transformer_tiny_trains():
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=100, tgt_vocab_size=100, max_length=32,
        n_layer=2, n_head=2, d_model=32, d_inner=64, dropout=0.0)
    feeds, avg_cost, logits = models.transformer.build_train_net(
        cfg, src_len=8, tgt_len=8)
    feed = models.transformer.make_fake_batch(cfg, 4, 8, 8)
    losses = _train(feeds, avg_cost, feed, steps=4, lr=0.1,
                    opt=pt.optimizer.Adam(learning_rate=1e-3))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_deepfm_trains():
    cfg = models.deepfm.DeepFMConfig(num_field=10, vocab_size=1000,
                                     embed_dim=8, fc_sizes=(32, 32))
    feeds, avg_cost, prob = models.deepfm.build_train_net(cfg)
    feed = models.deepfm.make_fake_batch(cfg, 16)
    losses = _train(feeds, avg_cost, feed, steps=4, lr=0.1)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_bert_tiny_trains():
    cfg = models.bert.BertConfig(vocab_size=200, hidden_size=32,
                                 num_layers=2, num_heads=2,
                                 intermediate_size=64, max_position=64,
                                 dropout=0.0)
    feeds, total_loss, (mlm, nsp) = models.bert.build_pretrain_net(
        cfg, seq_len=16)
    feed = models.bert.make_fake_batch(cfg, 4, 16, max_preds=4)
    losses = _train(feeds, total_loss, feed, steps=4,
                    opt=pt.optimizer.Adam(learning_rate=1e-3))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_vgg_cifar_builds():
    feeds, avg_loss, acc, pred = models.vgg.build_train_net(
        class_dim=10, img_shape=(3, 32, 32))
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(2, 3, 32, 32).astype("float32"),
            "label": rng.randint(0, 10, (2, 1)).astype("int64")}
    losses = _train(feeds, avg_loss, feed, steps=1, lr=0.01)
    assert np.isfinite(losses).all()


def test_lm_fused_attention_trains():
    """Decoder-only LM (the bench config) with the fused flash-attention
    path: loss decreases; parity with the unfused build at init."""
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=200, tgt_vocab_size=200, max_length=16,
        n_layer=2, n_head=2, d_model=32, d_inner=64, dropout=0.0)
    feeds, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=16, fused_attention=True)
    feed = models.transformer.make_fake_lm_batch(cfg, 4, 16)
    losses = _train(feeds, avg_cost, feed, steps=4,
                    opt=pt.optimizer.Adam(learning_rate=1e-3))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_lm_fused_matches_unfused_loss():
    """fused_attention=True/False compute the same MATH: the unfused
    run's weights are mapped onto the fused program (its fused_mha op
    owns Wq/Wk/Wv where the composition has one [D, 3E] qkv fc) and the
    losses must agree."""
    built = {}
    for fused in (True, False):
        pt.reset_default_programs()
        from paddle_tpu.framework import executor as em
        em._global_scope = em.Scope()
        cfg = models.transformer.TransformerConfig(
            src_vocab_size=100, tgt_vocab_size=100, max_length=8,
            n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0)
        feeds, avg_cost, _ = models.transformer.build_lm_net(
            cfg, seq_len=8, fused_attention=fused)
        exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
        pt.default_startup_program().random_seed = 7
        exe.run(pt.default_startup_program())
        built[fused] = (pt.default_main_program(), exe, avg_cost, cfg)

    # map unfused weights -> fused params in creation order; a [D, 3E]
    # qkv weight fans out to the fused op's three [D, E] projections
    uf_main, uf_exe, _, _ = built[False]
    f_main, f_exe, _, _ = built[True]
    uf_arrs = [np.asarray(uf_exe.scope.find_var(p.name))
               for p in uf_main.all_parameters()]
    f_params = f_main.all_parameters()
    ui = 0
    fi = 0
    while fi < len(f_params):
        fp = f_params[fi]
        src = uf_arrs[ui]
        if tuple(src.shape) == tuple(fp.shape):
            f_exe.scope.set_var(fp.name, jnp.asarray(src))
            fi += 1
        elif (len(src.shape) == 2 and len(fp.shape) == 2
              and src.shape[0] == fp.shape[0]
              and src.shape[1] == 3 * fp.shape[1]):
            E = fp.shape[1]
            for j in range(3):
                f_exe.scope.set_var(f_params[fi + j].name,
                                    jnp.asarray(src[:, j*E:(j+1)*E]))
            fi += 3
        else:
            raise AssertionError(
                f"param mismatch: unfused {src.shape} vs fused "
                f"{fp.shape}")
        ui += 1
    assert ui == len(uf_arrs)

    feed = models.transformer.make_fake_lm_batch(built[True][3], 2, 8)
    vals = []
    for fused in (True, False):
        main, exe, avg_cost, _ = built[fused]
        out, = exe.run(main, feed=feed, fetch_list=[avg_cost])
        vals.append(float(out))
    np.testing.assert_allclose(vals[0], vals[1], rtol=1e-4)


def test_amp_bf16_close_to_f32():
    """FLAGS_amp_bf16 keeps the loss within bf16 tolerance of f32."""
    from paddle_tpu.core import flags
    vals = []
    for amp in (False, True):
        pt.reset_default_programs()
        from paddle_tpu.framework import executor as em
        em._global_scope = em.Scope()
        flags.set_flag("amp_bf16", amp)
        try:
            cfg = models.transformer.TransformerConfig(
                src_vocab_size=100, tgt_vocab_size=100, max_length=8,
                n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0)
            feeds, avg_cost, _ = models.transformer.build_lm_net(
                cfg, seq_len=8, fused_attention=False)
            exe = pt.Executor(pt.CPUPlace())
            pt.default_startup_program().random_seed = 7
            exe.run(pt.default_startup_program())
            feed = models.transformer.make_fake_lm_batch(cfg, 2, 8)
            out, = exe.run(pt.default_main_program(), feed=feed,
                           fetch_list=[avg_cost])
            vals.append(float(out))
        finally:
            flags.set_flag("amp_bf16", False)
    np.testing.assert_allclose(vals[0], vals[1], rtol=2e-2)


def test_adam_state_signature_stable():
    """Adam's pow accumulators must keep their shape across steps — a
    changed state signature forces a silent full recompile every run
    (caught live on TPU: 12s/step instead of 70ms)."""
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, bias_attr=False)
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(4, 4).astype("float32"),
            "y": rng.randn(4, 1).astype("float32")}
    for _ in range(3):
        exe.run(pt.default_main_program(), feed=feed, fetch_list=[loss])
    assert len(exe._cache) == 2, (
        f"executor recompiled: {len(exe._cache)} cache entries")


def test_fused_lm_head_matches_unfused():
    """fused_lm_head_loss (chunked remat) == fc + softmax_with_cross_
    entropy + mean, loss AND gradient step."""
    rng = np.random.RandomState(5)
    V, D, N = 97, 16, 24
    x = rng.randn(N, D).astype("float32") * 0.5
    w = rng.randn(D, V).astype("float32") * 0.1
    y = rng.randint(0, V, (N,)).astype("int64")

    def build(fused):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            xv = pt.layers.data("x", [D])
            yv = pt.layers.data("y", [], dtype="int64")
            if fused:
                loss = pt.layers.fused_lm_head_loss(
                    xv, V, yv, param_attr=pt.ParamAttr("head_w"),
                    chunk_size=7)      # deliberately ragged chunks
            else:
                logits = pt.layers.fc(xv, size=V, bias_attr=False,
                                      param_attr=pt.ParamAttr("head_w"))
                y2 = pt.layers.reshape(yv, [-1, 1])
                loss = pt.layers.mean(
                    pt.layers.softmax_with_cross_entropy(logits, y2))
            pt.optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup)
        pt.global_scope().set_var("head_w", w.copy())
        l1, = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
        l2, = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
        return float(np.asarray(l1).ravel()[0]), float(
            np.asarray(l2).ravel()[0])

    f1, f2 = build(True)
    u1, u2 = build(False)
    assert abs(f1 - u1) < 1e-4          # same loss
    assert abs(f2 - u2) < 1e-3          # same post-SGD-step loss (grads)
    assert f2 < f1                       # and it trains


def test_fused_lm_head_unroll_matches_scan():
    """The unroll=True A/B knob computes the identical loss."""
    rng = np.random.RandomState(9)
    V, D, N = 37, 8, 20
    x = rng.randn(N, D).astype("float32")
    y = rng.randint(0, V, (N,)).astype("int64")
    w = rng.randn(D, V).astype("float32") * 0.1

    def run(unroll):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            xv = pt.layers.data("x", [D])
            yv = pt.layers.data("y", [], dtype="int64")
            loss = pt.layers.fused_lm_head_loss(
                xv, V, yv, param_attr=pt.ParamAttr("hw"),
                chunk_size=6, unroll=unroll)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup)
        pt.global_scope().set_var("hw", w.copy())
        out, = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
        return float(np.asarray(out).ravel()[0])

    assert abs(run(False) - run(True)) < 1e-5


def test_resnet_trains_under_amp_bf16():
    """Regression: conv2d's vjp crashed under FLAGS_amp_bf16 (mixed
    bf16/f32 into the conv transpose rule)."""
    from paddle_tpu.core import flags
    flags.set_flag("amp_bf16", True)
    try:
        feeds, avg_loss, acc, pred = models.resnet.build_train_net(
            class_dim=10, img_shape=(3, 32, 32), depth=18)
        rng = np.random.RandomState(0)
        feed = {"img": rng.rand(4, 3, 32, 32).astype("float32"),
                "label": rng.randint(0, 10, (4, 1)).astype("int64")}
        # 8 steps: with bf16 conv activations the bs4 trajectory can
        # bump non-monotonically while BN stats warm up, then collapses
        # to ~0 (memorizes the batch) by step ~4
        losses = _train(feeds, avg_loss, feed, steps=8, lr=0.05)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.1, losses
    finally:
        flags.set_flag("amp_bf16", False)


def test_alexnet_trains():
    feeds, avg_loss, acc, pred = models.alexnet.build_train_net(
        class_dim=10, img_shape=(3, 64, 64))
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(4, 3, 64, 64).astype("float32"),
            "label": rng.randint(0, 10, (4, 1)).astype("int64")}
    losses = _train(feeds, avg_loss, feed, steps=3, lr=0.01)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_googlenet_trains():
    feeds, avg_loss, acc, pred = models.googlenet.build_train_net(
        class_dim=10, img_shape=(3, 96, 96))
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(8, 3, 96, 96).astype("float32"),
            "label": rng.randint(0, 10, (8, 1)).astype("int64")}
    losses = _train(feeds, avg_loss, feed, steps=4, lr=0.002)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_se_resnext_tiny_trains():
    """SE-ResNeXt (ref dist_se_resnext.py): grouped convs + channel
    gating train end-to-end (tiny config for the CPU loop)."""
    feeds, avg_loss, acc, pred = models.se_resnext.build_train_net(
        class_dim=10, img_shape=(3, 32, 32), depth=50,
        stage_blocks=[1, 1])
    feed = models.se_resnext.make_fake_batch(4, (3, 32, 32), 10)
    losses = _train(feeds, avg_loss, feed, steps=3,
                    opt=pt.optimizer.Momentum(learning_rate=0.05,
                                              momentum=0.9))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
