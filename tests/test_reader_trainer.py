"""Reader decorators, datasets, DataFeeder, Trainer event loop +
checkpoint rotation/resume (the reference's contract:
python/paddle/fluid/contrib/trainer.py + reader/decorator.py tests)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, reader
from paddle_tpu.dataset import mnist, uci_housing


def test_reader_decorators_compose():
    r = reader.batch(
        reader.shuffle(lambda: iter(range(100)), buf_size=32, seed=0), 10)
    batches = list(r())
    assert len(batches) == 10
    assert sorted(sum(batches, [])) == list(range(100))

    r2 = reader.chain(lambda: iter([1, 2]), lambda: iter([3]))
    assert list(r2()) == [1, 2, 3]

    r3 = reader.compose(lambda: iter([1, 2]), lambda: iter([(10, 20),
                                                            (30, 40)]))
    assert list(r3()) == [(1, 10, 20), (2, 30, 40)]

    r4 = reader.buffered(lambda: iter(range(7)), 3)
    assert list(r4()) == list(range(7))

    r5 = reader.xmap_readers(lambda x: x * 2, lambda: iter(range(10)),
                             process_num=3, buffer_size=8, order=True)
    assert list(r5()) == [x * 2 for x in range(10)]

    r6 = reader.map_readers(lambda a, b: a + b, lambda: iter([1, 2]),
                            lambda: iter([10, 20]))
    assert list(r6()) == [11, 22]


def test_reader_errors_propagate():
    def bad_reader():
        yield 1
        raise IOError("disk gone")

    with pytest.raises(IOError):
        list(reader.buffered(bad_reader, 4)())

    def bad_mapper(x):
        raise ValueError("boom")

    with pytest.raises(ValueError):
        list(reader.xmap_readers(bad_mapper, lambda: iter(range(5)),
                                 process_num=2, buffer_size=4)())

    # cache: failed first pass leaves nothing cached
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        yield 1
        if calls["n"] == 1:
            raise RuntimeError("flake")
        yield 2

    c = reader.cache(flaky)
    with pytest.raises(RuntimeError):
        list(c())
    assert list(c()) == [1, 2]
    assert list(c()) == [1, 2]


def test_mnist_dataset_schema():
    sample = next(mnist.train()())
    img, lbl = sample
    assert img.shape == (784,) and img.dtype == np.float32
    assert 0 <= lbl < 10
    assert -1.0 <= img.min() and img.max() <= 1.0


def test_data_feeder_pads_ragged():
    main = pt.Program()
    with pt.program_guard(main, pt.Program()):
        ids = layers.data("ids", [16], dtype="int64")
        lbl = layers.data("label", [1], dtype="int64")
    feeder = pt.DataFeeder([ids, lbl], pad_to={"ids": 16}, emit_masks=True)
    batch = [([1, 2, 3], 0), ([4, 5], 1)]
    feed = feeder.feed(batch)
    assert feed["ids"].shape == (2, 16)
    assert feed["ids_mask"].sum() == 5
    assert feed["label"].shape == (2, 1)


def test_trainer_mnist_with_checkpoint_resume(tmp_path):
    def train_func():
        img = layers.data("img", [784], dtype="float32")
        label = layers.data("label", [1], dtype="int64")
        pred = layers.fc(img, size=10, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        return [loss, acc]

    def optimizer_func():
        return pt.optimizer.Adam(learning_rate=1e-3)

    train_reader = reader.batch(
        reader.firstn(mnist.train(), 64), batch_size=16)

    ckpt = pt.CheckpointConfig(str(tmp_path), max_num_checkpoints=2,
                               step_interval=2)
    seen = {"steps": 0, "losses": []}

    def handler(event):
        if isinstance(event, pt.EndStepEvent):
            seen["steps"] += 1
            seen["losses"].append(float(event.metrics[0]))

    trainer = pt.Trainer(train_func, optimizer_func, place=pt.CPUPlace(),
                         checkpoint_config=ckpt)
    trainer.train(num_epochs=2, event_handler=handler,
                  reader=train_reader, feed_order=["img", "label"])
    assert seen["steps"] == 8
    assert seen["losses"][-1] < seen["losses"][0]

    # rotation kept at most 2 checkpoints
    dirs = [d for d in os.listdir(tmp_path) if d.startswith("checkpoint_")]
    assert len(dirs) <= 2

    # test() path
    metrics = trainer.test(reader=train_reader, feed_order=["img", "label"])
    assert np.isfinite(metrics[0])

    # resume: fresh trainer picks up the checkpoint, epoch offset honored
    trainer2 = pt.Trainer(train_func, optimizer_func, place=pt.CPUPlace(),
                          checkpoint_config=ckpt)
    assert trainer2.epoch_offset >= 1
    m2 = trainer2.test(reader=train_reader, feed_order=["img", "label"])
    np.testing.assert_allclose(m2[0], metrics[0], rtol=1e-5)


def test_trainer_uci_housing_linear_regression():
    """The book's fit_a_line example (ref tests/book/test_fit_a_line.py)."""
    def train_func():
        x = layers.data("x", [13], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1, act=None)
        return layers.mean(layers.square_error_cost(pred, y))

    train_reader = reader.batch(
        reader.shuffle(uci_housing.train(), buf_size=256, seed=0), 32)
    losses = []

    def handler(event):
        if isinstance(event, pt.EndStepEvent):
            losses.append(float(event.metrics[0]))

    trainer = pt.Trainer(train_func,
                         lambda: pt.optimizer.SGD(learning_rate=0.05),
                         place=pt.CPUPlace())
    trainer.train(num_epochs=12, event_handler=handler,
                  reader=train_reader, feed_order=["x", "y"])
    assert losses[-1] < losses[0] * 0.2, f"{losses[0]} -> {losses[-1]}"


def test_dataset_breadth_schemas():
    """All 13 reference datasets yield schema-correct samples
    (ref python/paddle/dataset/: 13 modules)."""
    import itertools

    from paddle_tpu import dataset as D

    def take(reader, n=3):
        return list(itertools.islice(reader(), n))

    img, lbl = take(D.flowers.train())[0]
    assert img.shape[0] == 3 and 0 <= lbl < 102
    s = take(D.movielens.train())[0]
    assert len(s) == 8 and isinstance(s[5], list) and isinstance(s[6], list)
    s = take(D.conll05.test())[0]
    assert len(s) == 9 and len(set(map(len, s))) == 1  # parallel lists
    ids, lab = take(D.sentiment.train())[0]
    assert lab in (0, 1) and max(ids) < D.sentiment.VOCAB
    img, mask = take(D.voc2012.train())[0]
    assert mask.shape == img.shape[1:]
    src, trg, trg_next = take(D.wmt14.train(100))[0]
    assert trg[0] == D.wmt14.START and trg_next[-1] == D.wmt14.END
    assert len(trg) == len(trg_next)
    src, trg, _ = take(D.wmt16.train(100, 100))[0]
    assert trg[0] == D.wmt16.START
    hi, lo = take(D.mq2007.train("pairwise"))[0]
    assert hi.shape == (D.mq2007.FEATURE_DIM,)
    qid, rels, feats = take(D.mq2007.train("listwise"))[0]
    assert feats.shape == (len(rels), D.mq2007.FEATURE_DIM)


def test_save_load_as_ops_roundtrip(tmp_path):
    """The reference's checkpoint-as-ops contract (save_op.cc/load_op.cc):
    a program containing save/load ops persists and restores vars during
    execution."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    path = str(tmp_path / "var")
    cpath = str(tmp_path / "combined")
    x = np.arange(6, dtype="float32").reshape(2, 3)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        v = layers.data("x", [3])
        b = main.global_block()
        b.create_var(name="saved_ok", dtype="int32")
        b.create_var(name="csaved_ok", dtype="int32")
        b.append_op("save", {"X": ["x"]}, {"Out": ["saved_ok"]},
                    {"file_path": path})
        b.append_op("save_combine", {"X": ["x", "x"]},
                    {"Out": ["csaved_ok"]},
                    {"file_path": cpath, "var_names": ["a", "b"]})
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    exe.run(main, feed={"x": x}, fetch_list=["saved_ok", "csaved_ok"])

    main2 = pt.Program()
    with pt.program_guard(main2, pt.Program()):
        b = main2.global_block()
        for n in ("loaded", "la", "lb"):
            b.create_var(name=n, dtype="float32")
        b.append_op("load", {}, {"Out": ["loaded"]},
                    {"file_path": path, "shape": [2, 3]})
        b.append_op("load_combine", {}, {"Out": ["la", "lb"]},
                    {"file_path": cpath, "var_names": ["a", "b"],
                     "shapes": [[2, 3], [2, 3]]})
    loaded, la, lb = exe.run(main2, feed={}, fetch_list=["loaded", "la",
                                                         "lb"])
    assert np.allclose(loaded, x)
    assert np.allclose(la, x) and np.allclose(lb, x)


def test_trainer_test_is_side_effect_free():
    """Review r3: Trainer.test() must not touch params or optimizer /
    accumulation state — the for_test clone still contains update ops, so
    the test path has to run the pruned forward slice only."""
    def train_func():
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1)
        return layers.mean(layers.square_error_cost(pred, y))

    def optimizer_func():
        return pt.optimizer.Adam(learning_rate=1e-2)

    rng = np.random.RandomState(0)
    data = [(rng.randn(4).astype("f4"), rng.randn(1).astype("f4"))
            for _ in range(8)]
    r = reader.batch(lambda: iter(data), batch_size=4)

    trainer = pt.Trainer(train_func, optimizer_func, place=pt.CPUPlace(),
                         accumulate_steps=2)
    trainer.train(num_epochs=1, event_handler=lambda e: None, reader=r,
                  feed_order=["x", "y"])
    snap = {n: np.asarray(trainer.scope.find_var(n)).copy()
            for n in trainer.scope.var_names()
            if trainer.scope.find_var(n) is not None}
    trainer.test(reader=r, feed_order=["x", "y"])
    for n, before in snap.items():
        after = np.asarray(trainer.scope.find_var(n))
        assert np.array_equal(before, after), \
            f"test() mutated scope var {n}"


def test_trainer_env_driven_dist_transpile(monkeypatch):
    """ref contrib/trainer.py _dist_transpile_if_necessary: the PADDLE_*
    env contract — TRAINER role with PADDLE_TRAINERS=8 self-transpiles
    the program (c_allreduce per grad) onto the 8-device mesh with loss
    parity vs the plain single-device Trainer."""
    rng = np.random.RandomState(0)
    data = [(rng.randn(4).astype("f4"),
             rng.randn(1).astype("f4")) for _ in range(16)]

    def train_func():
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1)
        return layers.mean(layers.square_error_cost(pred, y))

    def optimizer_func():
        return pt.optimizer.SGD(learning_rate=0.1)

    def run_losses():
        losses = []

        def handler(e):
            if isinstance(e, pt.EndStepEvent) and e.metrics:
                losses.append(float(np.asarray(e.metrics[0]).mean()))

        r = reader.batch(lambda: iter(data), batch_size=16)
        pt.reset_default_programs()
        trainer = pt.Trainer(train_func, optimizer_func,
                             place=pt.CPUPlace())
        trainer.train(num_epochs=3, event_handler=handler, reader=r,
                      feed_order=["x", "y"])
        return losses

    ref = run_losses()

    monkeypatch.setenv("PADDLE_TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINERS", "8")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    dist = run_losses()
    np.testing.assert_allclose(dist, ref, rtol=1e-4, atol=1e-6)

    monkeypatch.setenv("PADDLE_TRAINING_ROLE", "PSERVER")
    with pytest.raises(RuntimeError, match="no parameter servers"):
        run_losses()


# --- async device prefetch (ISSUE 6 tentpole c) ---------------------------

def _prefetch_train_func():
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                  act="softmax")
    return layers.mean(layers.cross_entropy(p, y))


def _slow_reader(n=10, delay=0.03):
    import time

    def r():
        rng = np.random.RandomState(0)
        for _ in range(n):
            time.sleep(delay)
            yield [(rng.rand(4).astype("float32"),
                    np.array([1], "int64")) for _ in range(4)]
    return r


def test_device_prefetch_decorator_stages_feeds():
    import jax

    def raw():
        for i in range(3):
            yield {"a": np.full((2, 4), i, "float32")}

    items = list(reader.device_prefetch(raw, size=2)())
    assert len(items) == 3
    assert all(isinstance(b, reader.DeviceBatch) for b in items)
    assert isinstance(items[0].feed["a"], jax.Array)
    assert items[0].size == 2
    np.testing.assert_array_equal(np.asarray(items[2].feed["a"]), 2.0)
    # producer exceptions reach the consumer, not end-of-data
    def broken():
        yield {"a": np.zeros((1, 1), "float32")}
        raise RuntimeError("decode failed")

    it = reader.device_prefetch(broken, size=2)()
    next(it)
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_trainer_prefetch_overlaps_slow_reader():
    """Acceptance: with device prefetch the measured per-step data wait
    (the NOT-hidden part) collapses vs the unbuffered run of the same
    slow reader — and donated feed buffers / DeviceBatch plumbing
    produce the same healthy training loop."""
    import time

    from paddle_tpu.observability import metrics as obs_metrics

    def hist():
        h = obs_metrics.REGISTRY.get("trainer_data_wait_seconds")
        return h.sum, h.count

    s0, c0 = hist()
    t = pt.Trainer(train_func=_prefetch_train_func,
                   optimizer_func=lambda: pt.optimizer.SGD(0.1),
                   place=pt.CPUPlace())
    t.train(num_epochs=1, event_handler=lambda e: None,
            reader=_slow_reader(), feed_order=["x", "y"])
    t.stop()
    s1, c1 = hist()
    unbuf_mean = (s1 - s0) / (c1 - c0)

    pt.reset_default_programs()
    from paddle_tpu.framework import executor as em
    em._global_scope = em.Scope()

    # the consumer is slower than the producer (EndStep sleep), so the
    # prefetch thread hides the reader's 30ms entirely
    def slow_consumer(e):
        if isinstance(e, pt.EndStepEvent):
            time.sleep(0.04)

    steps = {"n": 0}

    def count_steps(e):
        if isinstance(e, pt.EndStepEvent):
            steps["n"] += 1
            slow_consumer(e)

    s0, c0 = hist()
    t = pt.Trainer(train_func=_prefetch_train_func,
                   optimizer_func=lambda: pt.optimizer.SGD(0.1),
                   place=pt.CPUPlace())
    t.train(num_epochs=1, event_handler=count_steps,
            reader=_slow_reader(), feed_order=["x", "y"],
            prefetch_depth=2)
    t.stop()
    s1, c1 = hist()
    pf_mean = (s1 - s0) / (c1 - c0)
    assert steps["n"] == 10          # every batch trained
    # acceptance (ISSUE 6): >= 5x drop; the 30ms reader sleep is fully
    # hidden so the measured ratio is typically 50x+
    assert unbuf_mean / pf_mean >= 5.0, (unbuf_mean, pf_mean)
    # the prefetch queue depth rides the labeled buffer-depth gauge
    g = obs_metrics.REGISTRY.get("reader_buffer_depth")
    assert ("device_prefetch",) in g._series


def test_input_bound_warning_prefetch_aware():
    """Satellite: a prefetch-enabled run whose reader is fully hidden
    stays quiet; the same slow reader unbuffered warns (and names the
    prefetch knob in its advice)."""
    import time
    import warnings

    from paddle_tpu.core import flags

    old = flags.get_flag("input_bound_warn_fraction")
    flags.set_flag("input_bound_warn_fraction", 0.2)
    try:
        with pytest.warns(RuntimeWarning, match="prefetch_depth"):
            t = pt.Trainer(train_func=_prefetch_train_func,
                           optimizer_func=lambda: pt.optimizer.SGD(0.1),
                           place=pt.CPUPlace())
            t.train(num_epochs=1, event_handler=lambda e: None,
                    reader=_slow_reader(), feed_order=["x", "y"])
            t.stop()

        pt.reset_default_programs()
        from paddle_tpu.framework import executor as em
        em._global_scope = em.Scope()

        def slow_consumer(e):
            if isinstance(e, pt.EndStepEvent):
                time.sleep(0.04)

        t = pt.Trainer(train_func=_prefetch_train_func,
                       optimizer_func=lambda: pt.optimizer.SGD(0.1),
                       place=pt.CPUPlace())
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            t.train(num_epochs=1, event_handler=slow_consumer,
                    reader=_slow_reader(), feed_order=["x", "y"],
                    prefetch_depth=2)
        t.stop()
    finally:
        flags.set_flag("input_bound_warn_fraction", old)


# ------------------- elastic reader re-partition (ISSUE 14 satellite)

def _stream(n=24):
    return lambda: iter(range(n))


def test_elastic_shard_partitions_disjoint_and_complete():
    import paddle_tpu.reader as reader
    parts = [list(reader.elastic_shard(_stream(), 3, r)())
             for r in range(3)]
    assert parts[0] == list(range(0, 24, 3))
    got = sorted(x for p in parts for x in p)
    assert got == list(range(24))              # nothing lost, no dups


def test_elastic_shard_fast_forwards_past_watermark():
    import paddle_tpu.reader as reader
    out = list(reader.elastic_shard(_stream(), 2, 1, start=10)())
    assert out == [11, 13, 15, 17, 19, 21, 23]


def test_elastic_shard_resize_exactly_once():
    """The resize discipline: consume R rounds under world N, resize at
    the rank-aligned boundary (watermark = start + R*N), re-partition
    the remainder under world M — across N→M→K no example is dropped or
    double-consumed, for grow, shrink, and N=1/M=1 edges."""
    import paddle_tpu.reader as reader
    n_examples = 30
    consumed = []
    start = 0
    for world, rounds in ((2, 4), (3, 3), (1, 2), (4, None)):
        phase = []
        for rank in range(world):
            it = reader.elastic_shard(_stream(n_examples), world, rank,
                                      start=start)()
            taken = list(it) if rounds is None else [
                x for _, x in zip(range(rounds), it)]
            phase.append(taken)
        if rounds is not None:
            assert all(len(p) == rounds for p in phase)
        consumed.extend(x for p in phase for x in p)
        start = reader.elastic_watermark(start, rounds, world) \
            if rounds is not None else n_examples
    assert sorted(consumed) == list(range(n_examples))
    assert len(consumed) == len(set(consumed))     # no double-consume


def test_elastic_shard_validates_args():
    import pytest
    import paddle_tpu.reader as reader
    with pytest.raises(ValueError, match="rank"):
        reader.elastic_shard(_stream(), 2, 2)
    with pytest.raises(ValueError, match="start"):
        reader.elastic_shard(_stream(), 2, 0, start=-1)
