"""v2-era API surface (ref python/paddle/v2/ — SURVEY §2.2 "v2 API"):
the canonical quick-start flows run end-to-end through the shim, which
lowers to the same Program/Executor plane as everything else."""
import io
import itertools

import numpy as np

import paddle_tpu.v2 as paddle


def _linreg_reader():
    rng = np.random.RandomState(0)
    w = np.array([1.0, -2.0, 0.5, 3.0], "f4")

    def reader():
        for _ in range(64):
            x = rng.randn(4).astype("f4")
            yield x, np.array([float(x @ w)], "f4")

    return reader


def test_fit_a_line_quickstart():
    """The v2 'fit a line' flow: layer graph -> parameters.create ->
    trainer.SGD -> infer."""
    paddle.init(use_gpu=False, trainer_count=1)
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1,
                           act=paddle.activation.Linear())
    cost = paddle.layer.square_error_cost(input=pred, label=y)

    params = paddle.parameters.create(cost)
    assert params.names()
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.0,
                                                  learning_rate=0.05))
    costs = []
    trainer.train(
        reader=paddle.batch(_linreg_reader(), batch_size=16),
        num_passes=12,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.1, (costs[0], costs[-1])

    test_result = trainer.test(
        reader=paddle.batch(_linreg_reader(), batch_size=16))
    assert test_result.cost < costs[0]

    out = paddle.infer(output_layer=pred, parameters=params,
                       input=[(np.ones(4, "f4"),)],
                       feeding={"x": 0})
    assert out.shape == (1, 1) and np.isfinite(out).all()


def test_recognize_digits_mlp():
    """v2 recognize_digits (MLP variant) on a synthetic separable task:
    classification_cost + Adam + multi-pass training."""
    rng = np.random.RandomState(1)
    centers = rng.randn(3, 8).astype("f4") * 3

    def reader():
        for _ in range(96):
            c = rng.randint(0, 3)
            yield (centers[c] + 0.1 * rng.randn(8).astype("f4"), int(c))

    img = paddle.layer.data(name="img",
                            type=paddle.data_type.dense_vector(8))
    lbl = paddle.layer.data(name="lbl",
                            type=paddle.data_type.integer_value(3))
    h = paddle.layer.fc(input=img, size=16, act=paddle.activation.Relu())
    out = paddle.layer.fc(input=h, size=3,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=lbl)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))
    costs = []
    trainer.train(paddle.batch(reader, 32), num_passes=6,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.5

    probs = paddle.infer(output_layer=out, parameters=params,
                         input=[(centers[c],) for c in range(3)],
                         feeding={"img": 0})
    assert np.argmax(probs, 1).tolist() == [0, 1, 2]


def test_word2vec_style_embedding_concat():
    """v2 word2vec shape: N integer inputs -> shared-ish embeddings ->
    concat -> fc softmax over vocab."""
    V, E = 20, 8
    rng = np.random.RandomState(2)
    data = [(int(a), int(b), int(a)) for a, b in rng.randint(0, V, (64, 2))]

    def reader():
        yield from data

    w1 = paddle.layer.data(name="w1",
                           type=paddle.data_type.integer_value(V))
    w2 = paddle.layer.data(name="w2",
                           type=paddle.data_type.integer_value(V))
    nxt = paddle.layer.data(name="nxt",
                            type=paddle.data_type.integer_value(V))
    e1 = paddle.layer.embedding(input=w1, size=E)
    e2 = paddle.layer.embedding(input=w2, size=E)
    ctx = paddle.layer.concat(input=[e1, e2])
    hid = paddle.layer.fc(input=ctx, size=32,
                          act=paddle.activation.Relu())
    out = paddle.layer.fc(input=hid, size=V,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=nxt)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))
    costs = []
    trainer.train(paddle.batch(reader, 32), num_passes=8,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0]


def test_sequence_embedding_pool_classifier():
    """integer_value_sequence rides the dense+mask plane: embedding ->
    masked sequence_pool -> classifier."""
    V = 12
    rng = np.random.RandomState(3)

    def reader():
        for _ in range(64):
            n = rng.randint(2, 7)
            cls = rng.randint(0, 2)
            lo, hi = (0, V // 2) if cls == 0 else (V // 2, V)
            yield [int(t) for t in rng.randint(lo, hi, n)], int(cls)

    seq = paddle.layer.data(
        name="seq", type=paddle.data_type.integer_value_sequence(V))
    lbl = paddle.layer.data(name="lbl",
                            type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=seq, size=8)
    pooled = paddle.layer.sequence_pool(input=emb,
                                        pool_type=paddle.pooling.Avg())
    out = paddle.layer.fc(input=pooled, size=2,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=lbl)

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    costs = []
    trainer.train(paddle.batch(reader, 32), num_passes=6,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.7


def test_parameters_tar_roundtrip_and_set():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    pred = paddle.layer.fc(input=x, size=2,
                           act=paddle.activation.Linear())
    params = paddle.parameters.create(pred)
    name = params.names()[0]
    params.set(name, np.full_like(params.get(name), 0.25))

    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    restored = paddle.parameters.Parameters.from_tar(buf)
    np.testing.assert_allclose(restored.get(name), params.get(name))
    # init_from_tar merges into an existing Parameters
    buf.seek(0)
    params2 = paddle.parameters.create(pred)
    params2.init_from_tar(buf)
    np.testing.assert_allclose(params2.get(name), 0.25)


def test_dataset_and_reader_are_shared_plane():
    row = next(iter(
        itertools.islice(paddle.dataset.uci_housing.train()(), 1)))
    assert len(row) == 2 and len(row[0]) == 13
    shuffled = paddle.reader.decorator.shuffle(
        _linreg_reader(), buf_size=8)
    assert len(list(shuffled())) == 64


def test_networks_simple_img_conv_pool():
    """v2.networks composite: LeNet-style conv net classifies a
    synthetic 2-class image task."""
    rng = np.random.RandomState(4)

    def reader():
        for _ in range(64):
            cls = rng.randint(0, 2)
            img = np.zeros((1, 8, 8), "f4")
            if cls:
                img[0, :4] = 1.0
            else:
                img[0, 4:] = 1.0
            img += 0.05 * rng.randn(1, 8, 8).astype("f4")
            yield img, int(cls)

    # v2 images feed flat (dense_vector) and the data layer's
    # height/width declare the conv shape; the feed plane reshapes
    img = paddle.layer.data(name="img",
                            type=paddle.data_type.dense_vector(64),
                            height=8, width=8)
    conv = paddle.networks.simple_img_conv_pool(
        input=img, filter_size=3, num_filters=4, pool_size=2,
        pool_stride=2, act=paddle.activation.Relu())
    out = paddle.layer.fc(input=conv, size=2,
                          act=paddle.activation.Softmax())
    lbl = paddle.layer.data(name="lbl",
                            type=paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=out, label=lbl)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))
    costs = []
    trainer.train(paddle.batch(reader, 32), num_passes=4,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.8, (costs[0], costs[-1])


def _seq_cls_reader(rng, vocab=60, n=64, classes=2):
    """Separable task: the class decides which vocab half dominates."""
    band = vocab // classes
    for _ in range(n):
        cls = rng.randint(0, classes)
        length = rng.randint(4, 9)
        words = (rng.randint(0, band, (length,)) + band * cls).tolist()
        yield words, int(cls)


def _train_seq_model(pred_fn, passes=6, lr=0.05):
    rng = np.random.RandomState(9)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(60))
    feat = pred_fn(words)
    out = paddle.layer.fc(input=feat, size=2,
                          act=paddle.activation.Softmax())
    lbl = paddle.layer.data(name="lbl",
                            type=paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=out, label=lbl)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=lr))
    costs = []
    trainer.train(paddle.batch(lambda: _seq_cls_reader(rng), 32),
                  num_passes=passes,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert np.isfinite(costs).all(), costs
    assert costs[-1] < costs[0] * 0.8, (costs[0], costs[-1])
    return costs


def test_v2_simple_lstm_text_classifier():
    """IMDB-style quick start: embedding -> simple_lstm -> last_seq ->
    fc softmax (the understand_sentiment v2 recipe)."""
    def pred(words):
        emb = paddle.layer.embedding(input=words, size=16)
        lstm = paddle.networks.simple_lstm(input=emb, size=16)
        return paddle.layer.last_seq(input=lstm)

    _train_seq_model(pred)


def test_v2_bidirectional_lstm_classifier():
    def pred(words):
        emb = paddle.layer.embedding(input=words, size=12)
        return paddle.networks.bidirectional_lstm(input=emb, size=8)

    _train_seq_model(pred)


def test_v2_bidirectional_lstm_return_seq_shape():
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(30))
    emb = paddle.layer.embedding(input=words, size=10)
    seq = paddle.networks.bidirectional_lstm(input=emb, size=6,
                                             return_seq=True)
    pooled = paddle.layer.sequence_pool(
        input=seq, pool_type=paddle.pooling.Max())
    out = paddle.layer.fc(input=pooled, size=2,
                          act=paddle.activation.Softmax())
    probs = paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[([1, 2, 3, 4],), ([5, 6],)])
    assert np.asarray(probs).shape == (2, 2)


def test_v2_sequence_conv_pool_classifier():
    """Text-CNN quick start (ref networks.py sequence_conv_pool)."""
    def pred(words):
        emb = paddle.layer.embedding(input=words, size=16)
        return paddle.networks.sequence_conv_pool(
            input=emb, context_len=3, hidden_size=24)

    _train_seq_model(pred)


def test_v2_recurrent_group_classifier():
    """recurrent_group + memory: a hand-written simple RNN trains (ref
    layers.py:4161 recurrent_group)."""
    H = 16

    def pred(words):
        emb = paddle.layer.embedding(input=words, size=16)

        def step(y):
            mem = paddle.layer.memory(name="rnn_state", size=H)
            return paddle.layer.fc(input=[y, mem], size=H,
                                   act=paddle.activation.Tanh(),
                                   name="rnn_state")

        rnn = paddle.layer.recurrent_group(step=step, input=emb)
        return paddle.layer.last_seq(input=rnn)

    _train_seq_model(pred)


def test_v2_simple_attention():
    """simple_attention returns a [B, D] context; masked pads get ~0
    weight."""
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(30))
    emb = paddle.layer.embedding(input=words, size=12)
    proj = paddle.layer.fc(input=emb, size=10, bias_attr=False)
    state = paddle.layer.fc(
        input=paddle.layer.sequence_pool(
            input=emb, pool_type=paddle.pooling.Avg()),
        size=8)
    ctxv = paddle.networks.simple_attention(
        encoded_sequence=emb, encoded_proj=proj, decoder_state=state)
    out = paddle.layer.fc(input=ctxv, size=2,
                          act=paddle.activation.Softmax())
    probs = paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[([1, 2, 3],), ([4, 5, 6, 7, 8],)])
    assert np.asarray(probs).shape == (2, 2)
    assert np.allclose(np.asarray(probs).sum(-1), 1.0, atol=1e-3)


def test_v2_recurrent_group_inner_memory_and_reverse():
    """memory(name=X) binds to the like-named step layer even when X is
    NOT the group output; reverse=True is length-aware (pads stay at
    the sequence end, the carry is not contaminated)."""
    H = 8

    def pred(words):
        emb = paddle.layer.embedding(input=words, size=8)

        def step(y):
            mem = paddle.layer.memory(name="state", size=H)
            h = paddle.layer.fc(input=[y, mem], size=H,
                                act=paddle.activation.Tanh(),
                                name="state")
            # group output is a PROJECTION of the state, not the state
            return paddle.layer.fc(input=h, size=H,
                                   act=paddle.activation.Relu())

        rnn = paddle.layer.recurrent_group(step=step, input=emb,
                                           reverse=True)
        return paddle.layer.first_seq(input=rnn)

    _train_seq_model(pred, passes=8)


def test_v2_fc_mixed_rank_rejected():
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(30))
    emb = paddle.layer.embedding(input=words, size=8)     # [B, T, 8]
    pooled = paddle.layer.sequence_pool(
        input=emb, pool_type=paddle.pooling.Avg())        # [B, 8]
    bad = paddle.layer.fc(input=[emb, pooled], size=4)
    import pytest
    with pytest.raises(ValueError, match="share rank"):
        paddle.parameters.create(bad)


def test_v2_lstmemory_size_mismatch_rejected():
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(30))
    emb = paddle.layer.embedding(input=words, size=12)
    bad = paddle.layer.lstmemory(input=emb, size=64)      # 12 != 4*64
    import pytest
    with pytest.raises(ValueError, match="pre-projected"):
        paddle.parameters.create(bad)


def test_v2_breadth_tier_builds_and_runs():
    """The breadth-tier layer fns (grumemory, addto, cos_sim, norms,
    clip, maxout, expand, crf, costs) build and execute through the v2
    plane."""
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(30))
    emb = paddle.layer.embedding(input=words, size=12)
    proj3 = paddle.layer.fc(input=emb, size=24, bias_attr=False)
    gru = paddle.layer.grumemory(input=proj3, size=8)
    pooled = paddle.layer.pooling_layer(input=gru,
                                        pooling_type=paddle.pooling.Max())
    a = paddle.layer.fc(input=pooled, size=6)
    b = paddle.layer.fc(input=pooled, size=6)
    feats = [
        paddle.layer.addto(input=[a, b], act=paddle.activation.Relu()),
        paddle.layer.cos_sim(a, b),
        paddle.layer.dot_prod_layer(a, b),
        paddle.layer.l2_distance_layer(a, b),
        paddle.layer.scaling_layer(input=a,
                                   weight=paddle.layer.dot_prod_layer(a, b)),
        paddle.layer.slope_intercept_layer(input=a, slope=2.0,
                                           intercept=1.0),
        paddle.layer.clip_layer(input=a, min=-1.0, max=1.0),
        paddle.layer.sum_to_one_norm_layer(
            input=paddle.layer.clip_layer(input=a, min=0.1, max=1.0)),
        paddle.layer.row_l2_norm_layer(input=a),
        paddle.layer.maxout_layer(input=a, groups=2),
    ]
    out = paddle.layer.fc(input=paddle.layer.concat(input=feats), size=2,
                          act=paddle.activation.Softmax())
    probs = paddle.infer(
        output_layer=out, parameters=paddle.parameters.create(out),
        input=[([1, 2, 3],), ([4, 5, 6, 7],)])
    assert np.asarray(probs).shape == (2, 2)
    assert np.allclose(np.asarray(probs).sum(-1), 1.0, atol=1e-3)


def test_v2_crf_tagger_trains():
    """SRL-style tagger: emissions -> crf_layer cost; decode with
    crf_decoding_layer sharing the transition param."""
    N_TAGS = 4
    rng = np.random.RandomState(11)

    def reader():
        for _ in range(128):
            n = rng.randint(3, 7)
            words = rng.randint(0, 20, (n,)).tolist()
            tags = [w % N_TAGS for w in words]      # learnable mapping
            yield words, tags

    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(20))
    tags = paddle.layer.data(
        name="tags", type=paddle.data_type.integer_value_sequence(N_TAGS))
    emb = paddle.layer.embedding(input=words, size=8)
    emit = paddle.layer.fc(input=emb, size=N_TAGS)
    crf_attr = paddle.attr.Param(name="crf_trans")
    cost = paddle.layer.crf_layer(input=emit, label=tags,
                                  param_attr=crf_attr)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    costs = []
    trainer.train(paddle.batch(reader, 16), num_passes=8,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert np.isfinite(costs).all()
    # NLL is positive and collapses on this learnable mapping (a sign
    # bug on the likelihood would send it negative-and-decreasing)
    assert costs[0] > 0 and costs[-1] > 0
    assert costs[-1] < costs[0] * 0.2, (costs[0], costs[-1])

    seq = [1, 2, 3, 4, 5, 6, 7]
    decoded = np.asarray(paddle.infer(
        output_layer=paddle.layer.crf_decoding_layer(
            input=emit, param_attr=crf_attr),
        parameters=params, input=[(seq,)]))
    exp = [w % N_TAGS for w in seq]
    assert (decoded.ravel()[:len(seq)] == exp).mean() >= 0.8, (
        decoded.ravel()[:len(seq)], exp)


def test_v2_cost_layers():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(4))
    for cost in (paddle.layer.huber_regression_cost(input=x, label=y),
                 paddle.layer.smooth_l1_cost(input=x, label=y),
                 paddle.layer.sum_cost(input=x),
                 paddle.layer.mse_cost(input=x, label=y)):
        val = paddle.infer(output_layer=cost,
                           parameters=paddle.parameters.create(cost),
                           input=[(np.ones(4, "f4"), np.zeros(4, "f4"))])
        assert np.isfinite(np.asarray(val)).all()


def test_v2_rank_cost_and_interpolation_feed_order():
    """Default feeding follows declared order: rank_cost(left, right,
    label) and interpolation_layer([x, y], weight) consume reader
    columns in signature order (regression: build order once differed)."""
    left = paddle.layer.data(name="l", type=paddle.data_type.dense_vector(1))
    right = paddle.layer.data(name="r", type=paddle.data_type.dense_vector(1))
    lbl = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    cost = paddle.layer.rank_cost(left=left, right=right, label=lbl)
    v = paddle.infer(
        output_layer=cost, parameters=paddle.parameters.create(cost),
        input=[(np.array([5.0], "f4"), np.array([0.0], "f4"),
                np.array([1.0], "f4"))])
    # left >> right with label=1 (left should rank higher): tiny cost
    assert float(np.asarray(v).ravel()[0]) < 0.1

    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(2))
    y2 = paddle.layer.data(name="y2", type=paddle.data_type.dense_vector(2))
    w = paddle.layer.data(name="w", type=paddle.data_type.dense_vector(1))
    interp = paddle.layer.interpolation_layer(input=[x, y2], weight=w)
    v = paddle.infer(
        output_layer=interp,
        parameters=paddle.parameters.create(interp),
        input=[(np.array([1.0, 1.0], "f4"), np.array([3.0, 3.0], "f4"),
                np.array([0.25], "f4"))])
    # out = w*x + (1-w)*y = 0.25*1 + 0.75*3
    np.testing.assert_allclose(np.asarray(v).ravel(), [2.5, 2.5],
                               atol=1e-5)
