"""CRF/CTC family vs brute-force enumeration, sampled losses, and the
misc op-census additions (ref operators/linear_chain_crf_op.cc,
warpctc_op.cc, nce_op.cc, hierarchical_sigmoid_op.cc, ...), plus the
label_semantic_roles-style CRF tagging model."""
import itertools

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _run_op(op_type, inputs, attrs, out_slots, place=None):
    """Build + run a single op; returns dict slot -> np array."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        block = main.global_block()
        in_map, feeds = {}, {}
        for slot, arr in inputs.items():
            arr = np.asarray(arr)
            name = f"in_{slot}"
            block.create_var(name=name, shape=arr.shape,
                             dtype=str(arr.dtype), is_data=True)
            feeds[name] = arr
            in_map[slot] = [name]
        out_map = {}
        for slot in out_slots:
            name = f"out_{slot}"
            block.create_var(name=name, dtype="float32")
            out_map[slot] = [name]
        block.append_op(op_type, in_map, out_map, attrs)
    exe = pt.Executor(place or pt.CPUPlace())
    vals = exe.run(main, feed=feeds,
                   fetch_list=[f"out_{s}" for s in out_slots])
    return dict(zip(out_slots, vals))


# ---------------------------------------------------------------------------
# CRF: brute force over all tag paths
# ---------------------------------------------------------------------------

def _crf_brute(em, trans, label=None):
    """Returns (log_z, best_path, gold_score_fn)."""
    T, N = em.shape
    start, stop, w = trans[0], trans[1], trans[2:]

    def path_score(path):
        s = start[path[0]] + em[0, path[0]]
        for t in range(1, T):
            s += w[path[t - 1], path[t]] + em[t, path[t]]
        return s + stop[path[-1]]

    scores = {p: path_score(p)
              for p in itertools.product(range(N), repeat=T)}
    log_z = np.logaddexp.reduce(list(scores.values()))
    best = max(scores, key=scores.get)
    return log_z, best, path_score


def test_linear_chain_crf_matches_brute_force():
    rng = np.random.RandomState(0)
    B, T, N = 2, 4, 3
    em = rng.randn(B, T, N).astype("float32")
    trans = rng.randn(N + 2, N).astype("float32") * 0.5
    label = rng.randint(0, N, (B, T)).astype("int64")
    out = _run_op("linear_chain_crf",
                  {"Emission": em, "Transition": trans, "Label": label},
                  {}, ["LogLikelihood"])
    for b in range(B):
        log_z, _, path_score = _crf_brute(em[b].astype("float64"),
                                          trans.astype("float64"))
        expect = path_score(tuple(label[b])) - log_z
        np.testing.assert_allclose(out["LogLikelihood"][b, 0], expect,
                                   rtol=1e-4)


def test_crf_decoding_matches_brute_force():
    rng = np.random.RandomState(1)
    B, T, N = 2, 4, 3
    em = rng.randn(B, T, N).astype("float32")
    trans = rng.randn(N + 2, N).astype("float32") * 0.5
    out = _run_op("crf_decoding",
                  {"Emission": em, "Transition": trans}, {},
                  ["ViterbiPath"])
    for b in range(B):
        _, best, _ = _crf_brute(em[b].astype("float64"),
                                trans.astype("float64"))
        np.testing.assert_array_equal(out["ViterbiPath"][b], best)


def test_crf_grad_flows():
    """-mean(llh) trains the transition matrix (finite grads)."""
    rng = np.random.RandomState(2)
    B, T, N = 2, 3, 3
    em_np = rng.randn(B, T, N).astype("float32")
    label_np = rng.randint(0, N, (B, T)).astype("int64")
    em = layers.data("em", [T, N], dtype="float32")
    label = layers.data("lbl", [T], dtype="int64")
    helper_block = pt.default_main_program().global_block()
    from paddle_tpu.framework.layer_helper import LayerHelper
    helper = LayerHelper("crf")
    trans = helper.create_parameter(None, shape=[N + 2, N],
                                    dtype="float32")
    llh = helper.create_variable_for_type_inference("float32")
    helper_block.append_op(
        "linear_chain_crf",
        {"Emission": [em.name], "Transition": [trans.name],
         "Label": [label.name]},
        {"LogLikelihood": [llh.name]}, {})
    loss = layers.mean(layers.scale(llh, scale=-1.0))
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(5):
        out, = exe.run(pt.default_main_program(),
                       feed={"em": em_np, "lbl": label_np},
                       fetch_list=[loss])
        losses.append(float(out))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# CTC: brute force over alignments
# ---------------------------------------------------------------------------

def _ctc_brute(logp, label, blank=0):
    """-log sum over all T-paths collapsing to `label`."""
    T, C = logp.shape

    def collapse(path):
        out, prev = [], -1
        for p in path:
            if p != prev and p != blank:
                out.append(p)
            prev = p
        return tuple(out)

    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == tuple(label):
            total = np.logaddexp(total, sum(logp[t, p]
                                            for t, p in enumerate(path)))
    return -total


def test_warpctc_matches_brute_force():
    rng = np.random.RandomState(3)
    B, T, C, S = 2, 4, 3, 2
    logits = rng.randn(B, T, C).astype("float32")
    label = np.array([[1, 2], [2, 1]], dtype="int64")
    out = _run_op("warpctc", {"Logits": logits, "Label": label}, {},
                  ["Loss"])
    logp = logits.astype("float64")
    logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
    for b in range(B):
        expect = _ctc_brute(logp[b], label[b])
        np.testing.assert_allclose(out["Loss"][b, 0], expect, rtol=1e-4)


def test_warpctc_grad_trains():
    rng = np.random.RandomState(4)
    B, T, C = 2, 5, 4
    x_np = rng.randn(B, T, C).astype("float32")
    lbl_np = np.array([[1, 2, 3], [3, 1, 2]], dtype="int64")
    x = layers.data("x", [T, C], dtype="float32")
    lbl = layers.data("lbl", [3], dtype="int64")
    h = layers.fc(x, size=C, num_flatten_dims=2)
    from paddle_tpu.framework.layer_helper import LayerHelper
    helper = LayerHelper("ctc")
    loss_var = helper.create_variable_for_type_inference("float32")
    pt.default_main_program().global_block().append_op(
        "warpctc", {"Logits": [h.name], "Label": [lbl.name]},
        {"Loss": [loss_var.name]}, {"blank": 0})
    loss = layers.mean(loss_var)
    pt.optimizer.Adam(5e-2).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(6):
        out, = exe.run(pt.default_main_program(),
                       feed={"x": x_np, "lbl": lbl_np}, fetch_list=[loss])
        losses.append(float(out))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_ctc_align():
    x = np.array([[0, 1, 1, 0, 2, 2, 3],
                  [1, 1, 0, 1, 0, 0, 0]], dtype="int32")
    out = _run_op("ctc_align", {"Input": x}, {"blank": 0},
                  ["Output"])["Output"]
    np.testing.assert_array_equal(out[0][:3], [1, 2, 3])
    assert (out[0][3:] == 0).all()
    np.testing.assert_array_equal(out[1][:2], [1, 1])


def test_chunk_eval_counts():
    # IOB with 1 type: B=0, I=1, O=2
    lab = np.array([[0, 1, 2, 0, 1, 1]], dtype="int64")
    inf = np.array([[0, 1, 2, 0, 2, 2]], dtype="int64")  # 2nd chunk wrong
    out = _run_op("chunk_eval", {"Inference": inf, "Label": lab},
                  {"num_chunk_types": 1},
                  ["Precision", "Recall", "F1-Score", "NumInferChunks",
                   "NumLabelChunks", "NumCorrectChunks"])
    assert int(out["NumLabelChunks"][0]) == 2
    assert int(out["NumInferChunks"][0]) == 2
    assert int(out["NumCorrectChunks"][0]) == 1
    np.testing.assert_allclose(out["Precision"][0], 0.5)
    np.testing.assert_allclose(out["Recall"][0], 0.5)


# ---------------------------------------------------------------------------
# sampled losses
# ---------------------------------------------------------------------------

def test_hierarchical_sigmoid_is_distribution():
    """exp(-cost(c)) over all classes sums to 1 (complete binary tree)."""
    rng = np.random.RandomState(5)
    D, num_classes = 6, 8
    x = rng.randn(1, D).astype("float32")
    w = rng.randn(num_classes - 1, D).astype("float32")
    probs = []
    for c in range(num_classes):
        out = _run_op("hierarchical_sigmoid",
                      {"X": x, "W": w,
                       "Label": np.array([c], dtype="int64")},
                      {"num_classes": num_classes}, ["Out"])
        probs.append(np.exp(-out["Out"][0, 0]))
    np.testing.assert_allclose(sum(probs), 1.0, rtol=1e-4)


def test_nce_trains():
    rng = np.random.RandomState(6)
    B, D, N = 8, 16, 50
    x_np = rng.randn(B, D).astype("float32")
    lbl_np = rng.randint(0, N, (B, 1)).astype("int64")
    x = layers.data("x", [D], dtype="float32")
    lbl = layers.data("lbl", [1], dtype="int64")
    h = layers.fc(x, size=D)
    from paddle_tpu.framework.layer_helper import LayerHelper
    helper = LayerHelper("nce")
    w = helper.create_parameter(None, shape=[N, D], dtype="float32")
    cost = helper.create_variable_for_type_inference("float32")
    pt.default_main_program().global_block().append_op(
        "nce", {"Input": [h.name], "Weight": [w.name], "Label": [lbl.name]},
        {"Cost": [cost.name]},
        {"num_total_classes": N, "num_neg_samples": 5})
    loss = layers.mean(cost)
    pt.optimizer.Adam(1e-2).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(8):
        out, = exe.run(pt.default_main_program(),
                       feed={"x": x_np, "lbl": lbl_np}, fetch_list=[loss])
        losses.append(float(out))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# the label_semantic_roles book tier: CRF tagging model
# ---------------------------------------------------------------------------

def test_crf_tagging_model_trains_and_decodes():
    """ref tests/book/test_label_semantic_roles.py contract: BiGRU-class
    encoder + CRF loss trains; Viterbi accuracy on the train batch
    improves over training."""
    rng = np.random.RandomState(7)
    V, T, N, E = 30, 6, 4, 16
    B = 8
    words_np = rng.randint(0, V, (B, T)).astype("int64")
    # synthetic rule: tag = word % N (learnable from embeddings)
    label_np = (words_np % N).astype("int64")

    words = layers.data("words", [T], dtype="int64")
    label = layers.data("label", [T], dtype="int64")
    emb = layers.embedding(words, size=[V, E])
    feat = layers.fc(emb, size=N, num_flatten_dims=2)
    from paddle_tpu.framework.layer_helper import LayerHelper
    helper = LayerHelper("crf")
    trans = helper.create_parameter(pt.ParamAttr(name="crf_trans"),
                                    shape=[N + 2, N], dtype="float32")
    llh = helper.create_variable_for_type_inference("float32")
    block = pt.default_main_program().global_block()
    block.append_op("linear_chain_crf",
                    {"Emission": [feat.name], "Transition": [trans.name],
                     "Label": [label.name]},
                    {"LogLikelihood": [llh.name]}, {})
    loss = layers.mean(layers.scale(llh, scale=-1.0))
    path = helper.create_variable_for_type_inference("int32")
    block.append_op("crf_decoding",
                    {"Emission": [feat.name], "Transition": [trans.name]},
                    {"ViterbiPath": [path.name]}, {})
    pt.optimizer.Adam(5e-2).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    accs, losses = [], []
    for _ in range(15):
        lo, p = exe.run(pt.default_main_program(),
                        feed={"words": words_np, "label": label_np},
                        fetch_list=[loss, path])
        losses.append(float(lo))
        accs.append(float((p == label_np).mean()))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert accs[-1] > accs[0]
    assert accs[-1] > 0.8


# ---------------------------------------------------------------------------
# misc census additions
# ---------------------------------------------------------------------------

def test_unique_and_counts():
    x = np.array([5, 3, 5, 7, 3, 3], dtype="int32")
    out = _run_op("unique_with_counts", {"X": x}, {},
                  ["Out", "Index", "Count", "UniqueCount"])
    assert int(out["UniqueCount"][0]) == 3
    np.testing.assert_array_equal(out["Out"][:3], [3, 5, 7])
    # index maps each element to its unique slot
    np.testing.assert_array_equal(out["Out"][out["Index"].astype(int)], x)


def test_sequence_conv_matches_numpy():
    rng = np.random.RandomState(8)
    B, T, D, M, CL = 2, 5, 3, 4, 3
    x = rng.randn(B, T, D).astype("float32")
    w = rng.randn(CL * D, M).astype("float32")
    out = _run_op("sequence_conv", {"X": x, "Filter": w},
                  {"contextLength": CL, "contextStart": -1}, ["Out"])
    expect = np.zeros((B, T, M))
    xp = np.pad(x, ((0, 0), (1, 1), (0, 0)))
    for t in range(T):
        ctxwin = xp[:, t:t + CL].reshape(B, -1)
        expect[:, t] = ctxwin @ w
    np.testing.assert_allclose(out["Out"], expect, rtol=1e-4, atol=1e-5)


def test_split_merge_ids_round_trip():
    ids = np.array([3, 4, 5, 9, 12], dtype="int64")
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        block = main.global_block()
        block.create_var(name="ids", shape=ids.shape, dtype="int64",
                         is_data=True)
        for i in range(3):
            block.create_var(name=f"s{i}", dtype="int64")
        block.append_op("split_ids", {"Ids": ["ids"]},
                        {"Out": ["s0", "s1", "s2"]}, {"num_shards": 3})
    exe = pt.Executor(pt.CPUPlace())
    s = exe.run(main, feed={"ids": ids}, fetch_list=["s0", "s1", "s2"])
    for i in range(3):
        owned = s[i][s[i] >= 0]
        assert all(v % 3 == i for v in owned)
    # merged positions reconstruct the original ids
    merged = np.maximum.reduce(s)
    np.testing.assert_array_equal(merged, ids)


def test_merge_selected_rows_sums_duplicates():
    ids = np.array([2, 0, 2, 1], dtype="int64")
    vals = np.arange(8, dtype="float32").reshape(4, 2)
    out = _run_op("merge_selected_rows", {"Ids": ids, "Values": vals},
                  {}, ["OutIds", "Out"])
    np.testing.assert_array_equal(out["OutIds"][:3], [0, 1, 2])
    np.testing.assert_allclose(out["Out"][2], vals[0] + vals[2])


def test_get_tensor_from_selected_rows():
    ids = np.array([1, 3], dtype="int64")
    vals = np.array([[1., 2.], [3., 4.]], dtype="float32")
    out = _run_op("get_tensor_from_selected_rows",
                  {"Ids": ids, "Values": vals}, {"height": 5}, ["Out"])
    assert out["Out"].shape == (5, 2)
    np.testing.assert_allclose(out["Out"][1], [1, 2])
    np.testing.assert_allclose(out["Out"][3], [3, 4])
    assert (out["Out"][[0, 2, 4]] == 0).all()


def test_cudnn_lstm_matches_reference_loop():
    rng = np.random.RandomState(9)
    B, T, D, H = 2, 4, 3, 5
    x = rng.randn(B, T, D).astype("float32")
    n_w = D * 4 * H + H * 4 * H + 4 * H
    w = (rng.randn(n_w) * 0.5).astype("float32")
    out = _run_op("cudnn_lstm", {"Input": x, "W": w},
                  {"hidden_size": H, "num_layers": 1}, ["Out"])["Out"]
    # numpy single-layer reference
    wx = w[:D * 4 * H].reshape(D, 4 * H)
    wh = w[D * 4 * H:D * 4 * H + H * 4 * H].reshape(H, 4 * H)
    b = w[D * 4 * H + H * 4 * H:]
    sig = lambda v: 1 / (1 + np.exp(-v))
    h = np.zeros((B, H))
    c = np.zeros((B, H))
    expect = np.zeros((B, T, H))
    for t in range(T):
        g = x[:, t] @ wx + h @ wh + b
        i, f, gg, o = np.split(g, 4, axis=1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        expect[:, t] = h
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_roi_align_shapes_and_center():
    x = np.zeros((1, 1, 8, 8), dtype="float32")
    x[0, 0, 2:6, 2:6] = 1.0
    rois = np.array([[2., 2., 6., 6.]], dtype="float32")
    out = _run_op("roi_align", {"X": x, "ROIs": rois},
                  {"pooled_height": 2, "pooled_width": 2,
                   "spatial_scale": 1.0}, ["Out"])["Out"]
    assert out.shape == (1, 1, 2, 2)
    assert out.min() > 0.5     # entirely inside the bright square


def test_generate_proposals_shapes():
    rng = np.random.RandomState(10)
    N, A, H, W = 1, 3, 4, 4
    scores = rng.rand(N, A, H, W).astype("float32")
    deltas = (rng.randn(N, 4 * A, H, W) * 0.1).astype("float32")
    im_info = np.array([[32., 32., 1.]], dtype="float32")
    anchors = (rng.rand(H, W, A, 4) * 16).astype("float32")
    anchors[..., 2:] += 8
    out = _run_op("generate_proposals",
                  {"Scores": scores, "BboxDeltas": deltas,
                   "ImInfo": im_info, "Anchors": anchors},
                  {"post_nms_topN": 5, "pre_nms_topN": 20},
                  ["RpnRois", "RpnRoiProbs"])
    assert out["RpnRois"].shape == (1, 5, 4)
    assert out["RpnRoiProbs"].shape == (1, 5)
