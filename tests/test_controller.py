"""Helmsman (ISSUE 17): closed-loop self-healing and traffic-driven
autoscaling — the policy layer between firing action-rules and the
fleet's actuators.

Covers: the ``action:`` clause validation matrix + the ``alerts
--check`` exit-code contract, the engine -> action_sink delivery
(criticals first, sink errors isolated), every policy clause on a fake
clock (cooldown, hysteresis, clamps, burn-proportional step,
single-flight + fence rejection, failure backoff -> circuit breaker ->
alert-only degrade -> reset, state persistence across a coordinator
restart incl. the corrupt-file path), flag-off invariance, the
satellites (journal reserved-name collision warning + counter,
supervisor backoff-vs-worker-timeout warning, revive semantics,
request_resize storms coalescing, streaming extend_dataset epoch cap),
the HTTP surface (GET /controller, POST /serving/drain), the
``incident --decision`` selector, and the tier-1 miniature controller
soak where the fleet grows AND shrinks itself with zero human resizes.
"""
import json
import os
import sys
import threading
import urllib.error
import urllib.request
import warnings

import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.supervisor import Supervisor
from paddle_tpu.distributed.task_queue import TaskMaster
from paddle_tpu.observability import alerts, incident
from paddle_tpu.observability import controller as ctrl_mod
from paddle_tpu.observability import journal
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import server as obs_server
from paddle_tpu.resilience import retry as rretry
from paddle_tpu.resilience import soak


def _gdoc(name, rows):
    """Synthetic metrics doc: one gauge family, rows = [(labels, v)]."""
    return {"schema": "paddle_tpu.metrics.v1", "metrics": {
        name: {"type": "gauge", "help": "",
               "series": [{"labels": dict(l), "value": v}
                          for l, v in rows]}}}


def _fleet_doc(world=2, generation=1, resizes=0, pending=None,
               workers=None):
    return {"target_world_size": world, "pending_world_size": pending,
            "generation": generation, "resizes": resizes,
            "workers": workers or {}}


def _grow_rule(value=3.0, **act):
    action = {"kind": "request_resize", "direction": "grow", **act}
    return alerts.Rule(name="backlog", metric="m", predicate="threshold",
                       op=">", value=value, severity="critical",
                       action=alerts.parse_action(action, "t",
                                                  "threshold"))


def _shrink_rule(**act):
    action = {"kind": "request_resize", "direction": "shrink", **act}
    return alerts.Rule(name="idle", metric="m", predicate="threshold",
                       op="<", value=1.0,
                       action=alerts.parse_action(action, "t",
                                                  "threshold"))


def _ent(rule, value=10.0):
    return {"rule": rule, "value": value, "labels": {}, "context": {}}


def _counter(name, **labels):
    fam = obs_metrics.REGISTRY.get(name)
    if fam is None:
        return 0.0
    return fam.labels(**labels).value if labels else fam.total()


# ------------------------------------------------ action clause parsing

def test_parse_action_valid_matrix():
    a = alerts.parse_action(
        {"kind": "request_resize", "direction": "grow", "step": 2,
         "proportional": True, "immediate": True, "cooldown": 5,
         "hysteresis": 10, "max_step": 4, "min_world": 1,
         "max_world": 8}, "t", "threshold")
    assert a["kind"] == "request_resize" and a["direction"] == "grow"
    assert a["step"] == 2 and a["max_step"] == 4
    assert a["proportional"] is True and a["immediate"] is True
    assert a["cooldown"] == 5.0 and a["hysteresis"] == 10.0
    for kind in ("drain", "revive", "log"):
        assert alerts.parse_action({"kind": kind}, "t",
                                   "threshold")["kind"] == kind


@pytest.mark.parametrize("obj,match", [
    ("drain", "must be a JSON object"),
    ({"kind": "reboot"}, "must be one of"),
    ({"kind": "drain", "step": 1}, "only applies to request_resize"),
    ({"kind": "request_resize"}, "'grow' or 'shrink'"),
    ({"kind": "request_resize", "direction": "up"}, "'grow' or 'shrink'"),
    ({"kind": "request_resize", "direction": "grow", "step": 0},
     "must be >= 1"),
    ({"kind": "request_resize", "direction": "grow", "step": 1.5},
     "must be an integer"),
    ({"kind": "request_resize", "direction": "grow",
      "proportional": "yes"}, "must be a boolean"),
    ({"kind": "request_resize", "direction": "grow", "min_world": 5,
      "max_world": 2}, "must be <= max_world"),
    ({"kind": "log", "cooldown": -1}, "must be >= 0"),
    ({"kind": "log", "frobnicate": 1}, "is not an action field"),
])
def test_parse_action_invalid_matrix(obj, match):
    with pytest.raises(alerts.RuleError, match=match):
        alerts.parse_action(obj, "t", "threshold")


def test_parse_action_refuses_absence_rules():
    with pytest.raises(alerts.RuleError, match="absence"):
        alerts.parse_action({"kind": "drain"}, "t", "absence")


def test_alerts_check_cli_action_exit_codes(tmp_path, capsys):
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"rules": [
        {"name": "r", "metric": "m", "predicate": "threshold",
         "op": ">", "value": 1,
         "action": {"kind": "request_resize", "direction": "grow"}}]}))
    assert alerts.main(["--check", str(good)]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"rules": [
        {"name": "r", "metric": "m", "predicate": "threshold",
         "op": ">", "value": 1, "action": {"kind": "reboot"}}]}))
    assert alerts.main(["--check", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "INVALID" in out and "kind" in out
    assert alerts.main(["--check", str(tmp_path / "missing.json")]) == 2


# ------------------------------------------------ engine -> sink wiring

def test_action_sink_gets_criticals_first_and_survives_errors():
    grow = _grow_rule()                        # severity critical
    shrink = _shrink_rule()                    # severity warning
    plain = alerts.Rule(name="noact", metric="m",
                        predicate="threshold", op=">", value=0.0)
    eng = alerts.AlertEngine([shrink, plain, grow])
    got = []
    eng.action_sink = lambda actionable, now: got.append(
        [e["rule"].name for e in actionable])
    eng.evaluate(_gdoc("m", [({}, 0.5)]), now=100.0)
    # 0.5 breaches both "idle" (< 1) and "noact" (> 0) but only rules
    # WITH an action clause reach the sink
    assert got == [["idle"]]
    eng.evaluate(_gdoc("m", [({}, 5.0)]), now=101.0)
    assert got[-1] == ["backlog"]              # critical grow fires
    # a raising sink must never take down the evaluation pass
    eng.action_sink = lambda actionable, now: 1 / 0
    st = eng.evaluate(_gdoc("m", [({}, 5.0)]), now=102.0)
    assert "backlog" in st["firing"]


# ------------------------------------------------ policy, on a fake clock

def _mk(actuators=None, fleet=None, state_path=None, **kw):
    pt.core.flags.set_flag("controller", True)
    holder = fleet if callable(fleet) else (lambda: fleet)
    return ctrl_mod.Controller(fleet_fn=holder,
                               actuators=actuators or {},
                               state_path=state_path or "", **kw)


def test_cooldown_bounds_decision_rate():
    calls = []
    c = _mk({"request_resize": lambda t, f, i: calls.append(t) or {}},
            _fleet_doc(world=2))
    rule = _grow_rule(cooldown=10, max_world=8)
    assert c.consider([_ent(rule)], now=100.0)[0]["outcome"] == "applied"
    for t in (101.0, 105.0, 109.9):            # inside the cooldown
        assert c.consider([_ent(rule)], now=t) == []
    assert c.consider([_ent(rule)], now=110.1)[0]["outcome"] == "applied"
    assert len(calls) == 2
    assert _counter("controller_skips_total", reason="cooldown") == 3


def test_hysteresis_blocks_direction_reversal():
    c = _mk({"request_resize": lambda t, f, i: {}}, _fleet_doc(world=4))
    grow = _grow_rule(cooldown=1, hysteresis=30, max_world=8)
    shrink = _shrink_rule(cooldown=1, hysteresis=30)
    assert c.consider([_ent(grow)], now=100.0)[0]["outcome"] == "applied"
    # reversal inside the hysteresis window: skipped, not clamped
    assert c.consider([_ent(shrink)], now=110.0) == []
    assert _counter("controller_skips_total", reason="hysteresis") == 1
    dec = c.consider([_ent(shrink)], now=131.0)
    assert dec and dec[0]["outcome"] == "applied"
    assert dec[0]["direction"] == "shrink"


def test_clamp_is_a_noop_decision_that_still_charges_cooldown():
    calls = []
    c = _mk({"request_resize": lambda t, f, i: calls.append(t) or {}},
            _fleet_doc(world=1))
    shrink = _shrink_rule(cooldown=10, min_world=1)
    dec = c.consider([_ent(shrink, value=0.0)], now=100.0)
    assert dec[0]["outcome"] == "clamped"
    assert calls == []                          # actuator never ran
    assert dec[0]["target_world"] == 1
    # the clamped decision charged the cooldown: a rule pinned at a
    # bound journals once per cooldown, it does not spam every tick
    assert c.consider([_ent(shrink, value=0.0)], now=105.0) == []
    assert _counter("controller_decisions_total",
                    action="request_resize", outcome="clamped") == 1


def test_proportional_step_scales_with_breach_and_caps():
    seen = []
    c = _mk({"request_resize": lambda t, f, i: seen.append(t) or {}},
            _fleet_doc(world=2))
    rule = _grow_rule(value=3.0, step=1, proportional=True, max_step=4,
                      max_world=32, cooldown=1)
    # observed 9 = 3x threshold -> step 3; world 2 -> target 5
    c.consider([_ent(rule, value=9.0)], now=100.0)
    assert seen[-1] == 5
    # observed 60 = 20x threshold -> step capped at max_step 4
    c.consider([_ent(rule, value=60.0)], now=102.0)
    assert seen[-1] == 2 + 4


def test_fence_rejection_counted_never_cooldown_charged():
    fenced = {"n": 0}

    def _resize(t, fence, i):
        fenced["n"] += 1
        return {"fenced": True}
    c = _mk({"request_resize": _resize}, _fleet_doc(world=2))
    rule = _grow_rule(cooldown=100, max_world=8)
    dec = c.consider([_ent(rule)], now=100.0)
    assert dec[0]["outcome"] == "fenced"
    assert dec[0]["fence"] == {"generation": 1, "resizes": 0}
    assert _counter("controller_fence_rejections_total") == 1
    # a fenced outcome charges NO cooldown: the very next tick retries
    # with a fresh token (the decision was never applied)
    dec = c.consider([_ent(rule)], now=100.5)
    assert dec[0]["outcome"] == "fenced" and fenced["n"] == 2


def test_failure_backoff_breaker_degrade_and_reset():
    def _drain():
        raise RuntimeError("boom")
    c = _mk({"drain": _drain}, _fleet_doc())
    rule = alerts.Rule(name="d", metric="m", predicate="threshold",
                       op=">", value=0.0,
                       action=alerts.parse_action(
                           {"kind": "drain", "cooldown": 1},
                           "t", "threshold"))
    # defaults: controller_backoff_s=5, breaker threshold 3
    assert c.consider([_ent(rule)], now=100.0)[0]["outcome"] == "failed"
    assert c.consider([_ent(rule)], now=101.0) == []   # backoff 5s
    assert _counter("controller_skips_total", reason="backoff") == 1
    assert c.consider([_ent(rule)], now=106.0)[0]["outcome"] == "failed"
    with pytest.warns(RuntimeWarning, match="alert-only"):
        dec = c.consider([_ent(rule)], now=120.0)      # 3rd strike
    assert dec[0]["outcome"] == "failed"
    assert c.degraded
    assert obs_metrics.REGISTRY.get("controller_degraded").value == 1.0
    # degraded = alert-only: NOTHING actuates, grow rules included
    grow = _grow_rule(max_world=8)
    assert c.consider([_ent(grow)], now=130.0) == []
    assert _counter("controller_skips_total", reason="degraded") == 1
    c.reset_breaker()
    assert not c.degraded
    assert c.consider([_ent(grow)], now=131.0)[0]["outcome"] \
        == "no_actuator"


def test_no_actuator_is_visible_not_silent():
    c = _mk({}, _fleet_doc(world=2))
    dec = c.consider([_ent(_grow_rule(max_world=8))], now=100.0)
    assert dec[0]["outcome"] == "no_actuator"
    assert _counter("controller_decisions_total",
                    action="request_resize", outcome="no_actuator") == 1


def test_state_persists_across_controller_restart(tmp_path):
    sp = str(tmp_path / "state.json")
    c = _mk({"request_resize": lambda t, f, i: {}},
            _fleet_doc(world=2), state_path=sp)
    rule = _grow_rule(cooldown=50, max_world=8)
    c.consider([_ent(rule)], now=100.0)
    assert os.path.exists(sp)
    # a restarted coordinator resumes its cooldown clocks instead of
    # instantly re-firing every still-held action
    c2 = ctrl_mod.Controller(fleet_fn=lambda: _fleet_doc(world=3),
                             actuators={"request_resize":
                                        lambda t, f, i: {}},
                             state_path=sp)
    assert c2.consider([_ent(rule)], now=120.0) == []     # still held
    dec = c2.consider([_ent(rule)], now=151.0)
    assert dec and dec[0]["outcome"] == "applied"
    assert dec[0]["decision_id"] == "helm-00002"          # seq resumed


def test_corrupt_state_file_warns_and_starts_fresh(tmp_path):
    sp = str(tmp_path / "state.json")
    with open(sp, "w") as f:
        f.write("{not json")
    with pytest.warns(RuntimeWarning, match="unreadable"):
        c = ctrl_mod.Controller(state_path=sp)
    assert not c.degraded and c.status_doc()["seq"] == 0


def test_single_flight_per_action_class():
    pt.core.flags.set_flag("controller", True)
    release = threading.Event()
    entered = threading.Event()

    def _slow(t, f, i):
        entered.set()
        release.wait(5)
        return {}
    c = _mk({"request_resize": _slow}, _fleet_doc(world=2))
    rule = _grow_rule(cooldown=0, max_world=8)
    out = []
    th = threading.Thread(target=lambda: out.extend(
        c.consider([_ent(rule)], now=100.0)))
    th.start()
    assert entered.wait(5)
    # a second decision for the same class while one is actuating is
    # skipped, not queued behind the lock
    assert c.consider([_ent(rule)], now=100.1) == []
    assert _counter("controller_skips_total", reason="inflight") == 1
    release.set()
    th.join(5)
    assert out and out[0]["outcome"] == "applied"


# ------------------------------------------------ flag-off invariance

def test_flag_off_is_invisible(tmp_path):
    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps({"rules": [
        {"name": "r", "metric": "m", "predicate": "threshold",
         "op": ">", "value": 1,
         "action": {"kind": "request_resize", "direction": "grow"}}]}))
    pt.core.flags.set_flag("alert_rules_path", str(rules))
    jp = tmp_path / "j.jsonl"
    pt.core.flags.set_flag("journal_path", str(jp))
    before = threading.active_count()
    assert ctrl_mod.ensure_started(fleet_fn=lambda: _fleet_doc()) is None
    assert ctrl_mod.get_controller() is None
    doc = ctrl_mod.status_doc()
    assert doc["enabled"] is False and doc["decisions"] == []
    # no sink attaches: an enabled alert plane stays observe-only
    eng = alerts.ensure_started()
    assert eng is not None and eng.action_sink is None
    eng.evaluate(_gdoc("m", [({}, 9.0)]), now=100.0)
    assert _counter("controller_decisions_total") == 0
    assert threading.active_count() <= before + 1   # alert ticker only
    # no controller journal events, ever
    journal_kinds = [json.loads(ln).get("kind")
                     for ln in open(jp)] if jp.exists() else []
    assert "controller" not in journal_kinds


def test_controller_without_sensors_is_refused_loudly():
    pt.core.flags.set_flag("controller", True)
    pt.core.flags.set_flag("alert_rules_path", "")
    with pytest.warns(RuntimeWarning, match="no sensor"):
        assert ctrl_mod.ensure_started() is None


# ------------------------------------------------ satellite: storms

def test_resize_storm_coalesces_to_one_pending_target(tmp_path):
    m = TaskMaster(snapshot_path=str(tmp_path / "s.json"),
                   num_epochs=2, world_size=2)
    m.set_dataset([f"sh-{i}" for i in range(4)])    # mid-epoch: pends
    targets = [3, 4, 5, 6, 7, 8]
    barrier = threading.Barrier(len(targets))

    def _storm(n):
        barrier.wait()
        m.request_resize(n)
    ths = [threading.Thread(target=_storm, args=(n,)) for n in targets]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    st = m.stats()
    # N racing clients coalesce to ONE pending target (last write
    # wins); nothing applied mid-epoch, the log stays empty
    assert st["pending_world_size"] in targets
    assert st["resizes"] == 0 and st["resize_log"] == []


def test_fenced_resize_storm_applies_exactly_once(tmp_path):
    m = TaskMaster(snapshot_path=str(tmp_path / "s.json"),
                   num_epochs=1, world_size=2)
    m.extend_dataset(["sh-0"])                  # non-idle: immediate path
    st = m.stats()
    fence = {"generation": st["generation"], "resizes": st["resizes"]}
    replies = []
    barrier = threading.Barrier(6)

    def _storm(n):
        barrier.wait()
        replies.append(m.request_resize(n, fence=fence, immediate=True))
    ths = [threading.Thread(target=_storm, args=(3 + i,))
           for i in range(6)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    applied = [r for r in replies if r["applied"]]
    fenced = [r for r in replies if r["fenced"]]
    # everyone raced with the SAME fence token: exactly one decision
    # applied, every other one rejected — never coalesced into a
    # second apply
    assert len(applied) == 1 and len(fenced) == 5
    log = m.stats()["resize_log"]
    assert len(log) == 1 and log[0]["old"] == 2
    # monotonic chain under further fenced resizes
    for target in (6, 1, 4):
        st = m.stats()
        m.request_resize(target, fence={"generation": st["generation"],
                                        "resizes": st["resizes"]},
                         immediate=True)
    log = m.stats()["resize_log"]
    assert [e["old"] for e in log[1:]] == [e["new"] for e in log[:-1]]


def test_streaming_extend_after_valley_stays_epoch_zero(tmp_path):
    m = TaskMaster(snapshot_path=str(tmp_path / "s.json"),
                   num_epochs=1, world_size=1)
    m.extend_dataset(["sh-0"])
    t = m.get_task(worker=0)
    assert t.epoch == 0
    m.task_finished(t.task_id, lease=t.lease, worker=0)
    assert not m.complete                       # unsealed: more may come
    # the queue momentarily drained (a traffic valley) — a new arrival
    # must still join epoch 0, not a phantom epoch 1
    m.extend_dataset(["sh-1"])
    t = m.get_task(worker=0)
    assert t.epoch == 0
    m.task_finished(t.task_id, lease=t.lease, worker=0)
    m.extend_dataset([], final=True)            # end of stream
    assert m.complete
    assert sorted(e["task_id"] for e in m.ledger_entries()) == [0, 1]
    assert {e["epoch"] for e in m.ledger_entries()} == {0}


# ------------------------------------------------ satellite: journal

def test_journal_reserved_field_collision_warns_and_counts(tmp_path):
    pt.core.flags.set_flag("journal_path", str(tmp_path / "j.jsonl"))
    with pytest.warns(RuntimeWarning, match="reserved"):
        rec = journal.emit("test", "collide", rank=5, payload=7)
    assert rec["rank"] == 0                     # envelope value kept
    assert rec["payload"] == 7                  # honest field kept
    assert _counter("journal_field_collisions_total", field="rank") == 1
    # warn once per site, count always
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        journal.emit("test", "collide", rank=6)
    assert _counter("journal_field_collisions_total", field="rank") == 2


# ------------------------------------------------ satellite: supervisor

def test_supervisor_warns_when_backoff_outruns_death_declaration():
    with pytest.warns(RuntimeWarning, match="declares it dead"):
        Supervisor(cmds=[["true"]], worker_timeout=1.0)
    # a backoff slower than timeout + reaper tick is fine
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        Supervisor(cmds=[["true"]], worker_timeout=1.0,
                   backoff=rretry.RetryPolicy(name="t", max_attempts=1,
                                              base_delay=2.0,
                                              max_delay=2.0))
        # and the silent default stays silent: nothing consumes death
        # declarations (no explicit timeout, no alert plane/controller)
        Supervisor(cmds=[["true"]])
    # an enabled controller implies a consumer -> the flag-derived
    # timeout is checked too
    pt.core.flags.set_flag("controller", True)
    with pytest.warns(RuntimeWarning, match="revive path"):
        Supervisor(cmds=[["true"]])


def test_supervisor_revive_respawns_parked_ranks_now():
    sup = Supervisor(cmds=[[sys.executable, "-c", "pass"]],
                     backoff=rretry.RetryPolicy(name="t",
                                                max_attempts=1,
                                                base_delay=9.0,
                                                max_delay=9.0))
    try:
        with sup._lock:
            sup._state[0] = "retired"
        assert sup.revive(ranks=[5]) == []      # outside the world
        assert sup.revive() == [0]
        with sup._lock:
            assert sup._state[0] == "restarting"
            assert sup._restart_at[0] == 0.0    # no backoff wait
    finally:
        sup.stop()


# ------------------------------------------------ HTTP surface

def test_http_controller_route_and_drain_503(tmp_path):
    srv = obs_server.start_http_server(port=0)
    try:
        with urllib.request.urlopen(srv.url + "/controller",
                                    timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert doc["schema"] == ctrl_mod.SCHEMA
        assert doc["enabled"] is False and doc["decisions"] == []
        # drain with no serving batcher attached is a 503 — the remote
        # actuator failure the controller's breaker counts
        req = urllib.request.Request(srv.url + "/serving/drain",
                                     data=b"{}", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
    finally:
        obs_server.stop_http_server()


# ------------------------------------------------ incident --decision

def test_incident_decision_selector(tmp_path, capsys):
    T = 1700000000.0
    p = str(tmp_path / "j.jsonl")
    tid = "9f" * 16
    with open(p, "w") as f:
        for e in [
            {"kind": "alert", "event": "fire", "time_unix": T + 1.0,
             "rank": 0, "pid": 1, "seq": 1, "rule": "backlog",
             "trace_id": tid},
            {"kind": "controller", "event": "decision",
             "time_unix": T + 1.5, "rank": 0, "pid": 1, "seq": 2,
             "decision_id": "helm-00007", "rule": "backlog",
             "action": "request_resize", "direction": "grow",
             "outcome": "applied", "alert_trace_id": tid},
            {"kind": "master", "event": "resize_applied",
             "time_unix": T + 1.8, "rank": 0, "pid": 1, "seq": 3,
             "old_world": 2, "new_world": 4, "trace_id": tid},
            {"kind": "worker", "event": "step", "time_unix": T + 900.0,
             "rank": 0, "pid": 1, "seq": 4},
        ]:
            f.write(json.dumps({"schema": journal.SCHEMA, **e}) + "\n")
    events, hist = incident.gather_events([p])
    t0, t1, sel = incident.resolve_window(events, hist,
                                          decision="helm-00007", pad=1.0)
    doc = incident.build_report(events, hist, t0, t1, sel)
    names = [(e["kind"], e["event"]) for e in doc["timeline"]]
    # the decision joins its alert (via alert_trace_id) and the resize
    # it caused into one timeline; the unrelated late event stays out
    assert ("controller", "decision") in names
    assert ("alert", "fire") in names
    assert ("master", "resize_applied") in names
    assert ("worker", "step") not in names
    assert sel["decision_id"] == "helm-00007"
    assert sel["outcome"] == "applied"
    with pytest.raises(ValueError, match="no journal"):
        incident.resolve_window(events, hist, decision="helm-99999")
    # CLI: selector renders, mutual exclusion holds, self-test passes
    assert incident.main([p, "--decision", "helm-00007"]) == 0
    out = capsys.readouterr().out
    assert "helm-00007" in out and "resize_applied" in out
    assert incident.main([p, "--decision", "x", "--alert", "y"]) == 2
    assert incident.main(["--self-test"]) == 0


# ------------------------------------------------ closed loop e2e

def test_schedule_registry_covers_controller_lanes():
    assert {"controller", "controller_ramp",
            "controller_chaos"} <= set(soak.SCHEDULES)
    for name in ("controller", "controller_ramp", "controller_chaos"):
        assert name in soak._CONTROLLER_PROFILES


def test_controller_soak_fleet_scales_itself(tmp_path):
    """Tier-1 miniature of the ISSUE 17 headline: an arrival trace
    oversubscribes a 1-rank fleet; the controller grows it off the
    backlog rule, the valley shrinks it back, every resize in the
    master's log maps 1:1 to an applied controller decision (zero
    human resizes), and the exactly-once ledger holds across the
    controller's own resizes."""
    rep = soak.run_schedule(str(tmp_path), "controller", timeout=90)
    assert rep["ok"], rep["problems"]
    assert rep["grows"] >= 1 and rep["shrinks"] >= 1
    assert rep["resizes_applied"] == len(
        [d for d in rep["decisions"]
         if d["action"] == "request_resize"
         and d["outcome"] == "applied"])
    assert rep["stats"]["complete"]
    # anti-flap: applied+clamped resize decisions respect the cooldown
    charged = [d for d in rep["decisions"]
               if d["outcome"] in ("applied", "clamped")]
    assert len(charged) <= rep["duration_s"] / 1.0 + 2


@pytest.mark.slow
def test_controller_ramp_and_chaos_soaks(tmp_path):
    """The two slow Helmsman lanes end-to-end.  Ramp: two full
    load/valley cycles; SLO holds (p99 sojourn under the serving
    budget) AND the elastic fleet beats the static max-world baseline
    on chip-seconds.  Chaos: the coordinator dies between a decision's
    fence cut and its actuation (fence REJECTED, retried — never
    double-applied), rank 0 is chaos-killed mid-run, and a broken
    drain actuator trips the circuit breaker into alert-only mode."""
    ramp = soak.run_schedule(str(tmp_path / "ramp"), "controller_ramp",
                             timeout=110)
    assert ramp["ok"], ramp["problems"]
    assert ramp["grows"] >= 2 and ramp["shrinks"] >= 2
    assert ramp["p99_sojourn_ms"] < 15000.0
    assert ramp["chip_seconds"] < ramp["chip_seconds_baseline"]
    chaos = soak.run_schedule(str(tmp_path / "chaos"),
                              "controller_chaos", timeout=110)
    assert chaos["ok"], chaos["problems"]
    assert chaos["fence_rejections"] >= 1
    assert chaos["resizes_applied"] == len(
        [d for d in chaos["decisions"]
         if d["action"] == "request_resize"
         and d["outcome"] == "applied"])
    assert chaos["restarts"][0] >= 1 and chaos["generation"] >= 2
    assert chaos["degraded"]
