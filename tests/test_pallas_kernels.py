"""Pallas kernel parity tests (interpret mode on CPU; same code runs
compiled on TPU). Contract mirrors the reference's op tests: outputs vs
reference math, gradients vs jax.grad of reference."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels import flash_attention, fused_layer_norm


def ref_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    w = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("T", [64, 256])
def test_flash_attention_matches_reference(causal, T):
    rng = np.random.RandomState(0)
    B, H, d = 2, 3, 32
    q, k, v = [rng.randn(B, H, T, d).astype("float32") for _ in range(3)]
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=causal)
    ref = ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads_match(causal):
    rng = np.random.RandomState(1)
    B, H, T, d = 1, 2, 64, 16
    q, k, v = [jnp.asarray(rng.randn(B, H, T, d).astype("float32"))
               for _ in range(3)]

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ref_attention(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_fused_layer_norm_matches_reference():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(6, 40, 64).astype("float32"))
    g = jnp.asarray(rng.rand(64).astype("float32") + 0.5)
    b = jnp.asarray(rng.randn(64).astype("float32"))
    y = fused_layer_norm(x, g, b)
    mu = x.mean(-1, keepdims=True)
    ref = (x - mu) / jnp.sqrt(x.var(-1, keepdims=True) + 1e-5) * g + b
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_fused_layer_norm_grads_match():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 32).astype("float32"))
    g = jnp.asarray(rng.rand(32).astype("float32") + 0.5)
    b = jnp.asarray(rng.randn(32).astype("float32"))

    def loss_fused(x, g, b):
        return jnp.sum(fused_layer_norm(x, g, b) ** 3)

    def loss_ref(x, g, b):
        mu = x.mean(-1, keepdims=True)
        y = (x - mu) / jnp.sqrt(x.var(-1, keepdims=True) + 1e-5) * g + b
        return jnp.sum(y ** 3)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, g, b)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_fused_attention_layer_in_program():
    import paddle_tpu as pt
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16, 32], dtype="float32")
        y = layers.fused_multihead_attention(x, x, x, n_head=4, causal=True)
        loss = layers.mean(y)
        pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(2, 16, 32).astype("float32")}
    l1, = exe.run(main, feed=feed, fetch_list=[loss])
    l2, = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(l1) and np.isfinite(l2) and l1 != l2


def test_layer_norm_op_uses_fused_path():
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import flags
    rng = np.random.RandomState(0)
    xv = rng.randn(4, 8, 32).astype("float32")
    outs = []
    for use in (True, False):
        flags.set_flag("use_pallas_kernels", use)
        try:
            pt.reset_default_programs()
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = layers.data("x", [8, 32], dtype="float32")
                y = layers.layer_norm(x, begin_norm_axis=2)
            exe = pt.Executor(pt.CPUPlace())
            exe.run(startup)
            o, = exe.run(main, feed={"x": xv}, fetch_list=[y])
            outs.append(o)
        finally:
            flags.set_flag("use_pallas_kernels", True)
    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-5, atol=2e-5)


def test_flash_attention_odd_seq_lengths():
    """T not divisible by the default big blocks must still be exact."""
    rng = np.random.RandomState(4)
    for T in (768, 1536, 96):
        q, k, v = [jnp.asarray(rng.randn(1, 2, T, 16).astype("float32"))
                   for _ in range(3)]
        out = flash_attention(q, k, v, causal=True)
        ref = ref_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("T", [7, 100, 129])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_nondivisible_seq(T, causal):
    """Prime/odd T takes the internal pad-to-128 path: forward AND grads
    must match the unpadded reference exactly."""
    rng = np.random.RandomState(5)
    q, k, v = [jnp.asarray(rng.randn(1, 2, T, 16).astype("float32"))
               for _ in range(3)]
    out = flash_attention(q, k, v, causal=causal)
    ref = ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ref_attention(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_fused_layer_norm_stats_grads_propagate():
    """Differentiating through the mean/var returned by
    return_stats=True must match the unfused reference (the VJP carries
    the stats cotangents, not silently zeroing them)."""
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(8, 32).astype("float32"))
    g = jnp.asarray(rng.rand(32).astype("float32") + 0.5)
    b = jnp.asarray(rng.randn(32).astype("float32"))

    def loss_fused(x):
        y, mean, var = fused_layer_norm(x, g, b, return_stats=True)
        return jnp.sum(y ** 2) + jnp.sum(mean ** 2) + jnp.sum(var ** 2)

    def loss_ref(x):
        mu = x.mean(-1)
        var = x.var(-1)
        y = ((x - mu[:, None]) / jnp.sqrt(var[:, None] + 1e-5)) * g + b
        return jnp.sum(y ** 2) + jnp.sum(mu ** 2) + jnp.sum(var ** 2)

    gf = jax.grad(loss_fused)(x)
    gr = jax.grad(loss_ref)(x)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=2e-4, atol=2e-4)


def test_fused_mha_named_attr_does_not_alias():
    import paddle_tpu as pt
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8, 16], dtype="float32")
        layers.fused_multihead_attention(
            x, x, x, n_head=2, param_attr=pt.ParamAttr(name="attn"))
    names = [p.name for p in main.all_parameters()]
    assert len(set(names)) == 4, names


def ref_lm_head_loss(x, w, y):
    logits = np.asarray(x, np.float32) @ np.asarray(w, np.float32)
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) \
        + logits.max(-1)
    gold = np.take_along_axis(logits, np.maximum(y, 0)[:, None], 1)[:, 0]
    return (lse - gold) * (y >= 0)


@pytest.mark.parametrize("V", [384, 500])     # divisible + padded-tail
def test_lm_head_xent_matches_reference(V):
    from paddle_tpu.kernels import lm_head_xent
    rng = np.random.RandomState(7)
    N, D = 64, 32
    x = jnp.asarray(rng.randn(N, D).astype("float32"))
    w = jnp.asarray(rng.randn(D, V).astype("float32") * 0.1)
    y = rng.randint(0, V, N).astype("int32")
    y[5] = -1                                  # ignored position
    out = lm_head_xent(x, w, jnp.asarray(y), block_n=32, block_v=128,
                       chunk=32)
    ref = ref_lm_head_loss(x, w, y)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_lm_head_xent_grads_match():
    from paddle_tpu.kernels import lm_head_xent
    rng = np.random.RandomState(8)
    N, D, V = 64, 16, 256
    x = jnp.asarray(rng.randn(N, D).astype("float32"))
    w = jnp.asarray(rng.randn(D, V).astype("float32") * 0.1)
    y = rng.randint(0, V, N).astype("int32")
    y[3] = -1
    yj = jnp.asarray(y)

    def loss_k(x, w):
        # sum + sum**2: the plain sum gives IGNORED tokens a nonzero
        # upstream cotangent, so a kernel that fails to mask their
        # gradient (dlogits = softmax/n instead of 0) is caught
        per_tok = lm_head_xent(x, w, yj, block_n=32, block_v=128,
                               chunk=32)
        return jnp.sum(per_tok ** 2) + jnp.sum(per_tok)

    def loss_ref(x, w):
        logits = x @ w
        lse = jax.scipy.special.logsumexp(logits, -1)
        gold = jnp.take_along_axis(
            logits, jnp.maximum(yj, 0)[:, None], 1)[:, 0]
        per_tok = (lse - gold) * (yj >= 0)
        return jnp.sum(per_tok ** 2) + jnp.sum(per_tok)

    gk = jax.grad(loss_k, argnums=(0, 1))(x, w)
    gr = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_fused_lm_head_op_pallas_vs_scan_path():
    """The op's kernel path and its scan fallback must agree."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import flags
    rng = np.random.RandomState(9)
    xv = rng.randn(2, 128, 32).astype("float32")
    yv = rng.randint(0, 384, (2, 128)).astype("int64")
    outs = []
    for use in (True, False):
        flags.set_flag("use_pallas_kernels", use)
        try:
            pt.reset_default_programs()
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = layers.data("x", [128, 32], dtype="float32")
                yl = layers.data("y", [128], dtype="int64")
                x2 = layers.reshape(x, [-1, 32])
                y2 = layers.reshape(yl, [-1])
                loss = layers.fused_lm_head_loss(x2, 384, y2)
            exe = pt.Executor(pt.CPUPlace())
            exe.run(startup)
            o, = exe.run(main, feed={"x": xv, "y": yv},
                         fetch_list=[loss])
            outs.append(o)
        finally:
            flags.set_flag("use_pallas_kernels", True)
    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-4, atol=2e-4)


@pytest.mark.skipif(jax.devices()[0].platform != "tpu",
                    reason="needs the real TPU chip (run from the default "
                           "env: python -m pytest tests/test_pallas_"
                           "kernels.py -k tpu_hardware)")
def test_flash_attention_odd_T_on_tpu_hardware():
    """VERDICT r02 #10: prime/odd T must be exact ON HARDWARE (not just
    interpret mode) — the internal pad-to-128 path feeds the kernel
    MXU-tileable blocks.  Verified tolerance is TPU default-precision
    matmul noise (~2.5e-3 relative vs a float64 host reference,
    measured IDENTICAL for divisible T=128/256 and odd T=7/129 — the
    pad path adds no error; see _drive_oddt.py)."""
    rng = np.random.RandomState(0)
    for T in (7, 129, 128):
        q, k, v = [rng.randn(1, 2, T, 64).astype("f4") for _ in range(3)]
        out = np.asarray(flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            causal=True, interpret=False))
        qd, kd, vd = (a.astype(np.float64) for a in (q, k, v))
        s = np.einsum("bhqd,bhkd->bhqk", qd, kd) / 8.0
        s = np.where(np.tril(np.ones((T, T), bool))[None, None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, vd)
        rel = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
        assert rel < 6e-3, (T, rel)


def test_fused_mha_op_pallas_matches_unfused():
    """fused_mha (projection-fused, head-major HDT kernel) matches its
    own unfused composition with identical weights, incl. an odd T that
    exercises the internal 128-granule padding; cross-attention (kv= )
    and a training step are exercised through the Program plane."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import flags
    rng = np.random.RandomState(3)
    xv = rng.randn(2, 72, 32).astype("float32")    # T=72: padded to 128
    outs = []
    for use_pallas in (True, False):
        flags.set_flag("use_pallas_kernels", use_pallas)
        try:
            pt.reset_default_programs()
            main, startup = pt.Program(), pt.Program()
            main.random_seed = startup.random_seed = 11
            with pt.program_guard(main, startup):
                x = layers.data("x", [72, 32], dtype="float32")
                y = layers.fused_mha(x, n_head=4, causal=True)
            exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
            exe.run(startup)
            o, = exe.run(main, feed={"x": xv}, fetch_list=[y])
            outs.append(np.asarray(o))
        finally:
            flags.set_flag("use_pallas_kernels", True)
    np.testing.assert_allclose(outs[0], outs[1], rtol=3e-4, atol=3e-4)


def test_fused_mha_cross_attention_and_training():
    import paddle_tpu as pt
    from paddle_tpu import layers
    rng = np.random.RandomState(4)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        xq = layers.data("xq", [16, 32], dtype="float32")
        xkv = layers.data("xkv", [24, 32], dtype="float32")
        y = layers.fused_mha(xq, n_head=2, kv=xkv)
        loss = layers.mean(layers.square(y))
        pt.optimizer.SGD(0.5).minimize(loss)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    feed = {"xq": rng.randn(2, 16, 32).astype("f4"),
            "xkv": rng.randn(2, 24, 32).astype("f4")}
    ls = [float(np.asarray(exe.run(main, feed=feed,
                                   fetch_list=[loss])[0]))
          for _ in range(3)]
    assert np.isfinite(ls).all() and ls[-1] < ls[0], ls


def test_fused_attention_qkv_layer():
    """Pre-projected q/k/v surface (layers.fused_attention_qkv) stays
    alive now that the transformer fused path routes to fused_mha."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("q", [16, 32], dtype="float32")
        q = layers.fc(x, size=32, num_flatten_dims=2, bias_attr=False)
        y = layers.fused_attention_qkv(q, q, q, n_head=4, causal=True)
        loss = layers.mean(layers.square(y))
        pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    rng = np.random.RandomState(1)
    feed = {"q": rng.randn(2, 16, 32).astype("f4")}
    l1 = float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss])[0]))
    l2 = float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss])[0]))
    assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1


def test_hdt_fused_multi_k_backward_matches_two_kernel():
    """The general one-pass HDT backward (2 <= nk <= 16) matches the
    two-kernel fallback (which is itself parity-tested vs the reference
    composition) — incl. causal, non-causal, and kv_len masking."""
    import sys
    famod = sys.modules["paddle_tpu.kernels.flash_attention"]
    fa = famod.flash_attention_hdt
    rng = np.random.RandomState(7)

    def to_hdt(x, B, T, H, d):
        import jax.numpy as jnp
        return jnp.transpose(x, (1, 3, 0, 2)).reshape(H, d, B * T)

    for (B, H, T, d, causal, kvl) in ((2, 2, 512, 64, True, None),
                                      (1, 4, 384, 32, False, 300)):
        import jax.numpy as jnp
        qh = to_hdt(jnp.asarray(rng.randn(B, H, T, d), jnp.float32),
                    B, T, H, d)
        kh = to_hdt(jnp.asarray(rng.randn(B, H, T, d), jnp.float32),
                    B, T, H, d)
        vh = to_hdt(jnp.asarray(rng.randn(B, H, T, d), jnp.float32),
                    B, T, H, d)

        def loss(q, k, v, fused):
            famod._FUSED_BWD_MULTI_K = fused
            famod._make_flash_hdt.cache_clear()
            try:
                return (fa(q, k, v, batch=B, causal=causal,
                           interpret=True, kv_len=kvl, block_q=128,
                           block_k=128) ** 2).sum()
            finally:
                famod._FUSED_BWD_MULTI_K = True
        g1 = jax.grad(lambda q, k, v: loss(q, k, v, True),
                      (0, 1, 2))(qh, kh, vh)
        g2 = jax.grad(lambda q, k, v: loss(q, k, v, False),
                      (0, 1, 2))(qh, kh, vh)
        for a, b, nm in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"{nm} {(B,H,T,causal)}")
