"""Test config: force an 8-device virtual CPU platform.

This validates multi-chip sharding logic without TPU hardware (the
reference's analogue: CPU_NUM faking multi-device,
python/paddle/fluid/parallel_executor.py, and test_dist_base.py).

Environment quirks of this image (documented for future sessions):
  * sitecustomize imports the axon TPU plugin AND jax._src.config at
    interpreter startup, so JAX_PLATFORMS env changes made here are too
    late — but backends initialize lazily, so jax.config.update still
    works as long as it runs before the first jax.devices()/jit call.
  * XLA_FLAGS is read by the CPU client at backend init, which has not
    happened yet when conftest runs, so the env write below is effective.
  * Setting PYTHONPATH (to anything) breaks axon plugin discovery — never
    set it; run pytest from the repo root instead.
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# import-time (pristine) values of every controller_* tuning flag —
# captured before any test body runs, so a test that tunes cooldowns or
# clamps and forgets to restore them cannot leak policy into the next
# case (ISSUE 19 satellite; tests/test_goodput.py has the regression)
_CONTROLLER_FLAG_DEFAULTS = None


def _controller_flag_defaults(flags_mod):
    global _CONTROLLER_FLAG_DEFAULTS
    if _CONTROLLER_FLAG_DEFAULTS is None:
        _CONTROLLER_FLAG_DEFAULTS = {
            k: v for k, v in flags_mod.all_flags().items()
            if k.startswith("controller")}
    return dict(_CONTROLLER_FLAG_DEFAULTS)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long soak/perf tests, excluded from tier-1 "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests "
        "(seeded, tier-1-safe)")


@pytest.fixture(autouse=True)
def fresh_programs():
    """Each test gets fresh default programs + a fresh scope, no armed
    chaos spec leaking across tests, and no observability HTTP server
    or trainer-liveness state surviving a case."""
    import paddle_tpu as pt
    import paddle_tpu.serving as serving
    from paddle_tpu import analysis
    from paddle_tpu.distributed import task_queue
    from paddle_tpu.framework import executor as executor_mod
    from paddle_tpu.observability import alerts as obs_alerts
    from paddle_tpu.observability import controller as obs_controller
    from paddle_tpu.observability import costmodel, flight, forensics
    from paddle_tpu.observability import deviceprof, metrics as obs_metrics
    from paddle_tpu.observability import goodput as obs_goodput
    from paddle_tpu.observability import journal as obs_journal
    from paddle_tpu.observability import memscope as obs_memscope
    from paddle_tpu.observability import perfscope as obs_perfscope
    from paddle_tpu.observability import runlog, tensorstats, tracectx
    from paddle_tpu.observability import server as obs_server
    from paddle_tpu.resilience import chaos
    pt.reset_default_programs()
    executor_mod._global_scope = executor_mod.Scope()
    pt.core.flags.set_flag("chaos_spec", "")
    chaos.reset()
    costmodel.reset()
    forensics.reset()
    flight.reset()
    obs_server.reset()
    # model-health telemetry: zero the sampling counter/snapshot and
    # close any runlog writer a test left open — sampling cadence and
    # file handles must not leak across cases
    tensorstats.reset()
    runlog.reset()
    # Watchtower: stop any alert ticker thread, drop engine state and
    # the firing gauges; close journal writers and wipe the shipping
    # ring — one case's firing alerts / journal events must not leak
    # into the next, and both flags default back to off
    obs_alerts.reset()
    obs_journal.reset()
    pt.core.flags.set_flag("alert_rules_path", "")
    pt.core.flags.set_flag("journal_path", "")
    # Helmsman: drop the controller singleton (decision ring, breaker
    # state, cooldown clocks) and restore EVERY controller_* tuning
    # flag to its import-time value — one case's actuation history or
    # tuned cooldowns/clamps must not charge the next case
    obs_controller.reset()
    for _cf, _cv in _controller_flag_defaults(pt.core.flags).items():
        pt.core.flags.set_flag(_cf, _cv)
    # request X-ray: traces/captures from one case must not resolve in
    # the next (GET /trace, exemplar trace ids), and the device-prof
    # capture latch must not read busy across cases
    tracectx.reset()
    obs_metrics.clear_exemplars()
    deviceprof.reset()
    # static-analysis plane: drop test-registered infer rules, zero the
    # findings metric family, and restore the verify_program default so
    # an error-mode test cannot leak rejection semantics into the next
    analysis.reset()
    pt.core.flags.set_flag("verify_program", "warn")
    # forget the previous test's masters (weakset) and zero the
    # queue/membership gauges: a scrape-time refresh_metrics() must not
    # re-publish a dead master's fleet_workers / taskmaster_tasks series
    task_queue.reset_state()
    # serving plane: no batcher loop thread or HTTP-routed engine may
    # survive a case (queue threads joined, routes detached); this
    # also detaches the Armada router when its module was imported —
    # probe thread joined, per-replica breaker/metric series dropped
    # (ISSUE 20)
    serving.reset()
    # persistent executable cache: tier-1 runs with it OFF — cache
    # tests point jit_cache_dir at tmp_path themselves, and the flag
    # must not leak artifacts (or warm-start semantics) across cases
    # or into the repo
    pt.core.flags.set_flag("jit_cache_dir", "")
    # perfscope: baselines, cached comm models and the perf_* gauges
    # must not leak rooflines (or a regression verdict) across cases,
    # and the flag defaults back to off
    obs_perfscope.reset()
    pt.core.flags.set_flag("perfscope", False)
    for _pf, _pv in (("perf_regression_factor", 2.0),
                     ("perf_baseline_window", 32),
                     ("perf_hbm_gbps", 0.0), ("perf_ici_gbps", 0.0)):
        pt.core.flags.set_flag(_pf, _pv)
    # memscope: join the census ticker, drop the plane/program/KV state
    # and every mem_*/serving_kv_* gauge series, and default the flag
    # family back off — one test's residency census or pressure verdict
    # must not leak into the next
    obs_memscope.reset()
    pt.core.flags.set_flag("memscope", False)
    for _mf, _mv in (("memscope_interval", 0.0), ("memscope_topk", 8),
                     ("memscope_pressure_fraction", 0.9),
                     ("memscope_hbm_limit_bytes", 0),
                     ("memscope_ratio_factor", 8.0)):
        pt.core.flags.set_flag(_mf, _mv)
    # Timecard: drop the accounting clock, accumulators, timeline and
    # chip-time metric families, and default the flag family back off —
    # one case's chip-seconds must not leak into the next
    obs_goodput.reset()
    pt.core.flags.set_flag("goodput", False)
    for _gf, _gv in (("goodput_collapse_fraction", 0.3),
                     ("goodput_collapse_for_s", 3.0)):
        pt.core.flags.set_flag(_gf, _gv)
    yield
    pt.core.flags.set_flag("chaos_spec", "")
    chaos.reset()
    obs_server.reset()
    task_queue.reset_state()
    serving.reset()
    obs_alerts.reset()
    obs_journal.reset()
    obs_controller.reset()
    pt.core.flags.set_flag("alert_rules_path", "")
    pt.core.flags.set_flag("journal_path", "")
    for _cf, _cv in _controller_flag_defaults(pt.core.flags).items():
        pt.core.flags.set_flag(_cf, _cv)
    pt.core.flags.set_flag("jit_cache_dir", "")
    obs_perfscope.reset()
    pt.core.flags.set_flag("perfscope", False)
    for _pf, _pv in (("perf_regression_factor", 2.0),
                     ("perf_baseline_window", 32),
                     ("perf_hbm_gbps", 0.0), ("perf_ici_gbps", 0.0)):
        pt.core.flags.set_flag(_pf, _pv)
    obs_memscope.reset()
    pt.core.flags.set_flag("memscope", False)
    for _mf, _mv in (("memscope_interval", 0.0), ("memscope_topk", 8),
                     ("memscope_pressure_fraction", 0.9),
                     ("memscope_hbm_limit_bytes", 0),
                     ("memscope_ratio_factor", 8.0)):
        pt.core.flags.set_flag(_mf, _mv)
    obs_goodput.reset()
    pt.core.flags.set_flag("goodput", False)
    for _gf, _gv in (("goodput_collapse_fraction", 0.3),
                     ("goodput_collapse_for_s", 3.0)):
        pt.core.flags.set_flag(_gf, _gv)


@pytest.fixture
def mesh8():
    from paddle_tpu.core.place import make_mesh
    assert len(jax.devices()) >= 8, "tests require 8 virtual CPU devices"
    return make_mesh((8,), ("data",))
