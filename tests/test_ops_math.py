"""Op tests: math/elementwise/reduce families (numpy-checked + finite-diff
grads, mirroring the reference's test_elementwise_add_op.py etc.)."""
import numpy as np
import pytest

from op_test import OpTest

rng = np.random.RandomState(42)


class TestMul(OpTest):
    op_type = "mul"

    def setup(self):
        x = rng.randn(4, 5).astype(np.float32)
        y = rng.randn(5, 3).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestMulFlatten(OpTest):
    op_type = "mul"

    def setup(self):
        x = rng.randn(2, 3, 4).astype(np.float32)
        y = rng.randn(12, 5).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1}
        self.outputs = {"Out": (x.reshape(2, 12) @ y)}

    def test(self):
        self.check_output()


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def setup(self):
        x = rng.randn(3, 4, 5).astype(np.float32)
        y = rng.randn(3, 6, 5).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_Y": True}
        self.outputs = {"Out": x @ y.transpose(0, 2, 1)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        x = rng.randn(2, 3, 4).astype(np.float32)
        y = rng.randn(3).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseDiv(OpTest):
    op_type = "elementwise_div"

    def setup(self):
        x = rng.rand(4, 5).astype(np.float32) + 0.5
        y = rng.rand(4, 5).astype(np.float32) + 0.5
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x / y}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


class TestReduceSum(OpTest):
    op_type = "reduce_sum"

    def setup(self):
        x = rng.randn(3, 4, 5).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False}
        self.outputs = {"Out": x.sum(axis=1)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReduceMeanAll(OpTest):
    op_type = "reduce_mean"

    def setup(self):
        x = rng.randn(3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"reduce_all": True}
        self.outputs = {"Out": np.asarray(x.mean())}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestScale(OpTest):
    op_type = "scale"

    def setup(self):
        x = rng.randn(3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.5, "bias": 1.0}
        self.outputs = {"Out": x * 2.5 + 1.0}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSumNary(OpTest):
    op_type = "sum"

    def setup(self):
        xs = [rng.randn(3, 4).astype(np.float32) for _ in range(3)]
        self.inputs = {"X": xs}
        self.outputs = {"Out": xs[0] + xs[1] + xs[2]}

    def test(self):
        self.check_output()


class TestSoftmax(OpTest):
    op_type = "softmax"

    def setup(self):
        x = rng.randn(4, 7).astype(np.float32)
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestTopK(OpTest):
    op_type = "top_k"

    def setup(self):
        x = rng.randn(4, 10).astype(np.float32)
        k = 3
        idx = np.argsort(-x, axis=1)[:, :k]
        self.inputs = {"X": x}
        self.attrs = {"k": k}
        self.outputs = {"Out": np.take_along_axis(x, idx, 1),
                        "Indices": idx.astype(np.int64)}

    def test(self):
        self.check_output()


class TestConcat(OpTest):
    op_type = "concat"

    def setup(self):
        xs = [rng.randn(2, i + 2).astype(np.float32) for i in range(3)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate(xs, axis=1)}

    def test(self):
        self.check_output()


class TestTranspose(OpTest):
    op_type = "transpose"

    def setup(self):
        x = rng.randn(2, 3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"axis": [1, 2, 0]}
        self.outputs = {"Out": x.transpose(1, 2, 0)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReshape(OpTest):
    op_type = "reshape"

    def setup(self):
        x = rng.randn(2, 3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"shape": [0, -1]}
        self.outputs = {"Out": x.reshape(2, 12)}

    def test(self):
        self.check_output()


class TestGather(OpTest):
    op_type = "gather"

    def setup(self):
        x = rng.randn(10, 4).astype(np.float32)
        idx = np.array([1, 3, 5], np.int64)
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestActivations:
    cases = {
        "relu": lambda x: np.maximum(x, 0),
        "sigmoid": lambda x: 1 / (1 + np.exp(-x)),
        "tanh": np.tanh,
        "exp": np.exp,
        "square": np.square,
        "softplus": lambda x: np.log1p(np.exp(x)),
        "leaky_relu": lambda x: np.where(x > 0, x, 0.02 * x),
        "gelu": lambda x: x * 0.5 * (1 + np.vectorize(
            lambda v: float(__import__("math").erf(v / np.sqrt(2))))(x)),
    }

    @pytest.mark.parametrize("name", sorted(cases))
    def test_forward(self, name):
        class T(OpTest):
            op_type = name

            def setup(self):
                x = rng.randn(3, 4).astype(np.float32)
                self.inputs = {"X": x}
                self.outputs = {"Out": TestActivations.cases[name](x)}
        t = T()
        t.check_output(atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("name", ["relu", "sigmoid", "tanh", "square"])
    def test_grad(self, name):
        class T(OpTest):
            op_type = name

            def setup(self):
                # keep away from relu kink
                x = rng.randn(3, 4).astype(np.float32)
                x = np.where(np.abs(x) < 0.1, 0.5, x)
                self.inputs = {"X": x}
                self.outputs = {"Out": TestActivations.cases[name](x)}
        T().check_grad(["X"], "Out", max_relative_error=0.01)


class TestCast(OpTest):
    op_type = "cast"

    def setup(self):
        x = rng.randn(3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"out_dtype": "int32"}
        self.outputs = {"Out": x.astype(np.int32)}

    def test(self):
        self.check_output()


class TestClipByNorm(OpTest):
    op_type = "clip_by_norm"

    def setup(self):
        x = rng.randn(4, 4).astype(np.float32) * 10
        norm = np.sqrt((x ** 2).sum())
        self.inputs = {"X": x}
        self.attrs = {"max_norm": 1.0}
        self.outputs = {"Out": x / norm if norm > 1 else x}

    def test(self):
        self.check_output()
