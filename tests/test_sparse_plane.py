"""Sparse plane (ISSUE 13): SelectedRows gradients, hash-bucketed
adagrad tables with optional int8 rows, the pull_rows/push_grads shard
service on the task-queue transport (bounded staleness + push ledger),
the AsyncExecutor streaming loop, DeepFM over the Program-plane sparse
ops, and the 2-supervised-workers + chaos-kill headline e2e."""
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, sparse
from paddle_tpu.core import flags
from paddle_tpu.distributed.supervisor import Supervisor
from paddle_tpu.distributed.task_queue import TaskMaster, serve_master
from paddle_tpu.framework.async_executor import (AsyncExecutor,
                                                 DataFeedParseError)
from paddle_tpu.models import deepfm as dfm
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.resilience import retry as rretry, soak
from paddle_tpu.sparse import worker as sw

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = sw.CTRJobConfig(num_field=4, vocab_size=64, embed_dim=4,
                       fc_sizes=(16,), learning_rate=0.1,
                       batch_size=16, seed=0)


def _counter(metric_name, **labels):
    m = obs_metrics.REGISTRY.get(metric_name)
    if m is None:
        return 0.0
    if labels:
        return m.labels(**labels).value
    return m.total()


def _serve(svc=None, **master_kw):
    m = TaskMaster(**master_kw)
    srv, (h, p) = serve_master(m, sparse=svc)
    return m, srv, f"{h}:{p}"


# ------------------------------------------------------- SelectedRows

def test_selected_rows_merge_sums_duplicates():
    sr = sparse.SelectedRows([3, 1, 3], [[1, 1], [2, 2], [5, 5]], 8)
    m = sr.merged()
    assert m.rows.tolist() == [1, 3]
    np.testing.assert_allclose(m.values, [[2, 2], [6, 6]])
    # to_dense also scatter-ADDS (the overwrite bug class)
    np.testing.assert_allclose(sr.to_dense()[3], [6, 6])
    # wire roundtrip
    rt = sparse.SelectedRows.from_wire(m.to_wire())
    assert rt.rows.tolist() == [1, 3] and rt.height == 8


def test_selected_rows_bounds_checked():
    with pytest.raises(ValueError):
        sparse.SelectedRows([9], [[1.0]], 8)
    with pytest.raises(ValueError):
        sparse.SelectedRows([0, 1], [[1.0]], 8)   # row/value mismatch


def test_selected_rows_from_dense():
    g = np.zeros((6, 2), "f4")
    g[4] = 2.0
    sr = sparse.SelectedRows.from_dense(g)
    assert sr.rows.tolist() == [4]
    np.testing.assert_allclose(sr.to_dense(), g)


# ------------------------------------------------------------- tables

def test_embedding_shard_sgd_touches_only_live_rows():
    cfg = sparse.TableConfig("t", rows=8, dim=2, seed=1,
                             learning_rate=0.5)
    sh = sparse.EmbeddingShard(cfg)
    before = sh.dense()
    g = sparse.SelectedRows([2, 2, 5], np.ones((3, 2), "f4"), 8)
    n = sh.apply(g)
    assert n == 2                       # unique rows, not occurrences
    after = sh.dense()
    # duplicate id 2 accumulated BOTH contributions (scatter-add)
    np.testing.assert_allclose(after[2], before[2] - 0.5 * 2.0)
    np.testing.assert_allclose(after[5], before[5] - 0.5 * 1.0)
    untouched = [0, 1, 3, 4, 6, 7]
    np.testing.assert_array_equal(after[untouched], before[untouched])


def test_embedding_shard_adagrad_matches_manual():
    cfg = sparse.TableConfig("t", rows=4, dim=2, seed=3,
                             learning_rate=0.5, optimizer="adagrad",
                             adagrad_eps=1e-6)
    sh = sparse.EmbeddingShard(cfg)
    w0 = sh.dense()
    g1 = np.array([[1.0, 2.0]], "f4")
    sh.apply(sparse.SelectedRows([1], g1, 4))
    sh.apply(sparse.SelectedRows([1], g1, 4))
    acc = g1 * g1 + g1 * g1
    w_manual = (w0[1] - 0.5 * g1 / (np.sqrt(g1 * g1) + 1e-6)
                - 0.5 * g1 / (np.sqrt(acc) + 1e-6))
    np.testing.assert_allclose(sh.dense()[1], w_manual[0], rtol=1e-6)


def test_embedding_shard_int8_rows_bounded_error():
    cfg = sparse.TableConfig("t", rows=16, dim=8, seed=2,
                             init_std=0.1, learning_rate=0.1,
                             int8_rows=True)
    f32 = sparse.TableConfig("t", rows=16, dim=8, seed=2,
                             init_std=0.1, learning_rate=0.1)
    q = sparse.EmbeddingShard(cfg)
    d = sparse.EmbeddingShard(f32)
    # int8 storage is ~4x smaller on the row payload
    assert q.state_bytes() < d.state_bytes() / 2
    # quantization error bounded by one code step per row
    err = np.abs(q.dense() - d.dense())
    step = np.abs(d.dense()).max(axis=1, keepdims=True) / 127.0
    assert (err <= step * 0.51 + 1e-9).all()
    # updates keep working (requantize path)
    g = sparse.SelectedRows([3], np.ones((1, 8), "f4"), 16)
    q.apply(g)
    d.apply(g)
    np.testing.assert_allclose(q.dense()[3], d.dense()[3], atol=0.02)


def test_hash_bucket_deterministic_and_spread():
    a = sparse.hash_bucket(np.arange(256), 16)
    b = sparse.hash_bucket(np.arange(256), 16)
    assert (a == b).all() and a.min() >= 0 and a.max() < 16
    # every bucket hit (a degenerate hash concentrates)
    assert len(set(a.tolist())) == 16
    # huge ids fold without overflow errors
    big = sparse.hash_bucket(np.array([2**62, 10**15]), 7)
    assert ((0 <= big) & (big < 7)).all()


def test_partition_rows_mod_ownership():
    parts = sparse.partition_rows(np.array([0, 1, 2, 3, 4, 5]), 2)
    assert parts[0].tolist() == [0, 2, 4]
    assert parts[1].tolist() == [1, 3, 5]


# ----------------------------------------------------- shard service

def test_service_staleness_bound_rejects_and_accounts():
    svc = sparse.SparseShardService(staleness_bound=1)
    svc.init_tables([sparse.TableConfig("t", rows=8, dim=2, seed=0,
                                        learning_rate=0.1)])
    v0 = svc.pull_rows("t", [1])["version"]
    g = sparse.SelectedRows([1], np.ones((1, 2), "f4"), 8)
    r0 = _counter("sparse_push_rejected_total", reason="stale")
    assert svc.push_grads("t", g, v0, "a")["status"] == "ok"
    assert svc.push_grads("t", g, v0, "b")["status"] == "ok"  # st = 1
    out = svc.push_grads("t", g, v0, "c")          # staleness 2 > 1
    assert out["status"] == "stale" and out["rows_applied"] == 0
    assert svc.stale_rejections == 1
    assert _counter("sparse_push_rejected_total",
                    reason="stale") == r0 + 1
    # a fresh pull refreshes the window; the SAME push id then lands
    v1 = svc.pull_rows("t", [1])["version"]
    assert svc.push_grads("t", g, v1, "c")["status"] == "ok"


def test_service_push_ledger_is_exactly_once():
    svc = sparse.SparseShardService()
    svc.init_tables([sparse.TableConfig("t", rows=8, dim=2, seed=0,
                                        learning_rate=1.0)])
    before = svc.state("t")["values"]
    g = sparse.SelectedRows([2], np.ones((1, 2), "f4"), 8)
    v = svc.pull_rows("t", [2])["version"]
    a = svc.push_grads("t", g, v, "push-1")
    b = svc.push_grads("t", g, v, "push-1")        # retried delivery
    assert a["status"] == b["status"] == "ok"
    assert b.get("duplicate") and b["rows_applied"] == 1
    after = np.asarray(svc.state("t")["values"])
    np.testing.assert_allclose(
        after[2], np.asarray(before)[2] - 1.0)     # applied ONCE


def test_service_metrics_move():
    p0 = _counter("sparse_rows_pulled_total", table="m")
    q0 = _counter("sparse_rows_pushed_total", table="m")
    h = obs_metrics.REGISTRY.get("sparse_staleness_steps")
    c0 = h.total_count()
    svc = sparse.SparseShardService()
    svc.init_tables([sparse.TableConfig("m", rows=8, dim=2, seed=0,
                                        learning_rate=0.1)])
    v = svc.pull_rows("m", [1, 2, 3])["version"]
    svc.push_grads("m", sparse.SelectedRows(
        [1, 2], np.ones((2, 2), "f4"), 8), v, "x")
    assert _counter("sparse_rows_pulled_total", table="m") == p0 + 3
    assert _counter("sparse_rows_pushed_total", table="m") == q0 + 2
    assert h.total_count() == c0 + 1


def test_two_shard_partition_reassembles_and_trains():
    """Mod-partitioned tables across two shard services: pulls route by
    ownership, pushes land on the owner, the reassembled table matches
    a single-shard run of the same pushes."""
    specs = [sparse.TableConfig("t", rows=16, dim=2, seed=4,
                                init_std=0.1, learning_rate=0.5)]
    svc0 = sparse.SparseShardService(shard_id=0, num_shards=2)
    svc1 = sparse.SparseShardService(shard_id=1, num_shards=2)
    one = sparse.SparseShardService()
    m0, s0, ep0 = _serve(svc0)
    m1, s1, ep1 = _serve(svc1)
    try:
        c = sparse.SparseShardClient([ep0, ep1])
        c.init_tables(specs)
        one.init_tables(specs)
        ids = np.array([0, 1, 2, 3, 8, 9, 15])
        vals, vers = c.pull_rows("t", ids)
        ref = one.pull_rows("t", ids.tolist())
        np.testing.assert_allclose(vals, np.asarray(ref["values"],
                                                    "f4"))
        g = sparse.SelectedRows(ids, np.ones((7, 2), "f4"), 16)
        out = c.push_grads("t", g, vers, "p")
        assert out["rows_applied"] == 7 and not out["stale"]
        one.push_grads("t", g, ref["version"], "p")
        np.testing.assert_allclose(
            c.table_state("t"),
            np.asarray(one.state("t")["values"], "f4"))
        c.close()
    finally:
        s0.shutdown()
        s1.shutdown()


@pytest.mark.chaos
def test_sparse_rpc_chaos_sites_absorbed_by_retry():
    """sparse.pull / sparse.push fault points (docs/RESILIENCE.md):
    injected ConnectionErrors ride the resilience/retry.py backoff the
    same way a dropped socket would — the call succeeds, the retry
    and fault counters move."""
    svc = sparse.SparseShardService()
    svc.init_tables([sparse.TableConfig("t", rows=8, dim=2, seed=0,
                                        learning_rate=0.1)])
    m, srv, ep = _serve(svc)
    try:
        c = sparse.SparseShardClient(ep)
        f0 = _counter("resilience_faults_injected_total",
                      site="sparse.pull", kind="raise")
        r0 = _counter("retry_attempts_total", name="sparse_rpc")
        flags.set_flag("chaos_spec",
                       "sparse.pull=raise:0.5;sparse.push=raise:0.5")
        pushed = 0
        for i in range(6):
            vals, vers = c.pull_rows("t", [1, 2])
            g = sparse.SelectedRows([1, 2], np.ones((2, 2), "f4"), 8)
            pushed += c.push_grads("t", g, vers, f"p{i}")[
                "rows_applied"]
        flags.set_flag("chaos_spec", "")
        assert pushed == 12                 # nothing lost
        assert _counter("resilience_faults_injected_total",
                        site="sparse.pull", kind="raise") > f0
        assert _counter("retry_attempts_total",
                        name="sparse_rpc") > r0
        c.close()
    finally:
        flags.set_flag("chaos_spec", "")
        srv.shutdown()


def test_sparse_verbs_without_service_named_error():
    m, srv, ep = _serve(None)
    try:
        c = sparse.SparseShardClient(ep)
        with pytest.raises(RuntimeError, match="no SparseShardService"):
            c.pull_rows("t", [0])
        c.close()
    finally:
        srv.shutdown()


# ------------------------------------------- AsyncExecutor streaming

def _write_lines(path, n, start=0):
    """One sample per line with a globally UNIQUE id — the
    exactly-once assertions key on it."""
    with open(path, "w") as f:
        for i in range(start, start + n):
            f.write(f"1 {i} 1 {i % 2}\n")
    return str(path)


def _count_feed():
    return pt.DataFeedDesc([pt.Slot("ids", "uint64", dim=1),
                            pt.Slot("label", "float", is_dense=True,
                                    dim=1)], batch_size=4)


def test_parse_line_names_source_line_and_slot():
    feed = _count_feed()
    r0 = _counter("datafeed_rejected_lines_total")
    with pytest.raises(DataFeedParseError) as ei:
        feed.parse_line("x 7 1 0", lineno=3, source="part-9")
    msg = str(ei.value)
    assert "part-9" in msg and "line 3" in msg and "'ids'" in msg
    # non-numeric id inside a well-framed slot
    with pytest.raises(ValueError) as ei2:
        feed.parse_line("1 seven 1 0", lineno=4, source="part-9")
    assert "non-numeric" in str(ei2.value)
    # truncated slot still raises the legacy EnforceNotMet surface too
    with pytest.raises(pt.core.enforce.EnforceNotMet):
        feed.parse_line("2 7")
    assert _counter("datafeed_rejected_lines_total") == r0 + 3


def test_async_executor_skip_mode_counts_rejected_lines(tmp_path):
    p = tmp_path / "shard"
    with open(p, "w") as f:
        f.write("1 1 1 0\n")
        f.write("BAD LINE\n")
        f.write("1 2 1 1\n")
    seen = []

    def step(feed):
        seen.append(int(feed["ids"].shape[0]))
        return {"n": feed["ids"].shape[0]}

    r0 = _counter("datafeed_rejected_lines_total")
    exe = AsyncExecutor()
    exe.run(None, _count_feed(), [str(p)], thread_num=1, fetch=["n"],
            step_fn=step, on_bad_line="skip")
    assert sum(seen) == 2                  # bad line dropped, counted
    assert _counter("datafeed_rejected_lines_total") == r0 + 1
    # default mode: the same file aborts with the named error
    with pytest.raises(DataFeedParseError, match="line 2"):
        exe.run(None, _count_feed(), [str(p)], thread_num=1,
                fetch=["n"], step_fn=step)


def test_async_executor_propagates_step_failure_and_stops(tmp_path):
    """Satellite regression: a poisoned batch's exception reaches the
    caller as the FIRST error and the pool terminates promptly —
    worker threads must not swallow it and train on."""
    files = [_write_lines(tmp_path / f"s{i}", 40, start=100 * i)
             for i in range(3)]
    calls = []

    class Poison(RuntimeError):
        pass

    def step(feed):
        calls.append(1)
        if len(calls) == 3:
            raise Poison("poisoned batch")
        return {"n": feed["ids"].shape[0]}

    exe = AsyncExecutor()
    t0 = time.time()
    with pytest.raises(Poison, match="poisoned batch"):
        exe.run(None, _count_feed(), files, thread_num=3,
                fetch=["n"], step_fn=step)
    assert time.time() - t0 < 30           # clean stop, no hang
    # the pool stopped near the failure, not after draining 30 batches
    assert len(calls) <= 10


def test_async_executor_checkpoint_resume_exactly_once(tmp_path):
    """file+offset checkpointing: a run killed mid-stream resumes past
    COMMITTED batches; across both runs every line trains exactly
    once."""
    files = [_write_lines(tmp_path / f"s{i}", 24, start=100 * i)
             for i in range(2)]
    ck = str(tmp_path / "stream.json")
    trained = []

    def make_step(fail_after):
        n_seen = [0]

        def step(feed):
            if fail_after is not None and n_seen[0] >= fail_after:
                raise RuntimeError("killed")
            n_seen[0] += 1
            trained.extend(feed["ids"].ravel().tolist())
            return {"n": feed["ids"].shape[0]}
        return step

    exe = AsyncExecutor()
    with pytest.raises(RuntimeError, match="killed"):
        exe.run(None, _count_feed(), files, thread_num=1,
                fetch=["n"], step_fn=make_step(3), checkpoint_path=ck)
    assert 0 < len(trained) <= 16
    doc = json.load(open(ck))
    assert sum(doc["files"].values()) == len(trained)
    # the restarted incarnation fast-forwards and finishes the stream
    exe.run(None, _count_feed(), files, thread_num=1, fetch=["n"],
            step_fn=make_step(None), checkpoint_path=ck)
    assert sorted(trained) == sorted(
        list(range(24)) + list(range(100, 124)))
    # a third run is a no-op (stream fully committed)
    before = len(trained)
    exe.run(None, _count_feed(), files, thread_num=1, fetch=["n"],
            step_fn=make_step(None), checkpoint_path=ck)
    assert len(trained) == before


def test_async_executor_publishes_per_source_buffer_depth(tmp_path):
    f = _write_lines(tmp_path / "depth-src", 16)
    exe = AsyncExecutor()
    exe.run(None, _count_feed(), [f], thread_num=1, fetch=["n"],
            step_fn=lambda feed: {"n": feed["ids"].shape[0]})
    g = obs_metrics.REGISTRY.get("reader_buffer_depth")
    series = {k[0]: s.value for k, s in g.series().items()}
    assert "async_executor:depth-src" in series


# -------------------------------------- Program-plane sparse ops

def test_sparse_embedding_op_trains_and_folds_huge_ids():
    cfg = dfm.DeepFMConfig(num_field=4, vocab_size=32, embed_dim=4,
                           fc_sizes=(8,))
    feeds, cost, prob = dfm.build_sparse_train_net(cfg)
    pt.optimizer.Adagrad(learning_rate=0.2).minimize(cost)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"feat_ids": rng.randint(0, 10**12,
                                    (8, 4)).astype("int64"),
            "feat_vals": rng.rand(8, 4).astype("float32"),
            "label": rng.randint(0, 2, (8, 1)).astype("float32")}
    losses = [float(exe.run(pt.default_main_program(), feed=feed,
                            fetch_list=[cost])[0]) for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_sparse_lookup_hash_matches_host_plane():
    """In-graph hash bucketing and sparse/table.hash_bucket agree on
    every id — the contract that lets a reader fold ids host-side OR
    leave them raw for the graph."""
    V = 50
    ids = np.array([[0, 5, 7, 10**9, 123456789]], dtype="int64")
    x = layers.data("x", [5], dtype="int64")
    table = layers.data("tbl", [V], dtype="float32")
    from paddle_tpu.framework.layer_helper import LayerHelper
    h = LayerHelper("sparse_embedding_lookup")
    out = h.create_variable_for_type_inference("float32")
    h.append_op("sparse_embedding_lookup",
                {"W": [table], "Ids": [x]}, {"Out": [out]},
                {"hash_bucket": True})
    exe = pt.Executor(pt.CPUPlace())
    tbl = np.arange(V, dtype="f4")[:, None] * np.ones((1, 1), "f4")
    got, = exe.run(pt.default_main_program(),
                   feed={"x": ids, "tbl": tbl}, fetch_list=[out])
    want = sparse.hash_bucket(ids, V).astype("f4")[..., None]
    np.testing.assert_allclose(got, want)


def test_sparse_op_shape_infer_rules():
    from paddle_tpu import analysis
    # good program verifies clean
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", [4], dtype="int64")
        emb = layers.sparse_embedding(ids, size=[32, 8])
    res = analysis.verify_program(main, feed=["ids"], fetch_list=[emb])
    assert not res.errors
    assert emb.shape[-1] == 8
    # float ids: provable type error
    pt.reset_default_programs()
    main2, startup2 = pt.Program(), pt.Program()
    with pt.program_guard(main2, startup2):
        bad = layers.data("bad", [4], dtype="float32")
        emb2 = layers.sparse_embedding(bad, size=[32, 8])
    res2 = analysis.verify_program(main2, feed=["bad"],
                                   fetch_list=[emb2])
    assert any("must be integer" in str(f) for f in res2.errors)
    # scatter: transposed grad caught statically
    pt.reset_default_programs()
    main3, startup3 = pt.Program(), pt.Program()
    with pt.program_guard(main3, startup3):
        from paddle_tpu.framework.layer_helper import LayerHelper
        w = layers.data("w", [8], dtype="float32")      # [B, 8] table
        i3 = layers.data("i", [], dtype="int64")
        g3 = layers.data("g", [3], dtype="float32")     # wrong dim
        h = LayerHelper("sparse_scatter_update")
        out3 = h.create_variable_for_type_inference("float32")
        h.append_op("sparse_scatter_update",
                    {"W": [w], "Ids": [i3], "Grad": [g3]},
                    {"Out": [out3]}, {"learning_rate": 0.1})
    res3 = analysis.verify_program(main3, feed=["w", "i", "g"],
                                   fetch_list=[out3])
    assert any("trailing dim" in str(f) for f in res3.errors)


def test_lint_gate_includes_deepfm_sparse():
    from paddle_tpu.analysis import lint as lint_cli
    builders = lint_cli.model_builders()
    assert "deepfm_sparse" in builders
    assert len(builders) >= 19
    e, w = lint_cli.lint_model("deepfm_sparse",
                               builders["deepfm_sparse"])
    assert e == 0


# -------------------------------------- streaming CTR: parity lanes

def _train_stream(cfg, files, thread_num, svc=None, **run_kw):
    """In-process fleet lane: stream `files` through CTRStepper(s)
    against a (fresh) shard service over TCP; returns the final host
    params."""
    svc = svc or sparse.SparseShardService()
    m, srv, ep = _serve(svc)
    try:
        c = sparse.SparseShardClient(ep)
        c.init_tables(sw.table_specs(cfg))
        stepper = sw.CTRStepper(cfg, c, push_tag="inproc")
        exe = AsyncExecutor()
        exe.run(None, dfm.criteo_feed_desc(cfg.num_field,
                                           cfg.batch_size),
                files, thread_num=thread_num, fetch=["loss"],
                step_fn=stepper, **run_kw)
        params = {}
        for spec in sw.table_specs(cfg):
            arr = c.table_state(spec.name)
            params[spec.name] = (arr[0] if spec.name.endswith("_b")
                                 else arr)
        c.close()
        return params, stepper
    finally:
        srv.shutdown()


def test_stream_single_source_matches_dense_reference(tmp_path):
    """Sequential streaming == the dense single-process
    reference_ctr_step run, parameter-for-parameter: the gather/
    compute/scatter path is numerically the dense step."""
    files = dfm.make_criteo_files(tmp_path, 1, 96,
                                  num_field=TINY.num_field,
                                  vocab_size=TINY.vocab_size, seed=5)
    params, stepper = _train_stream(TINY, files, thread_num=1)
    assert stepper.row_count_mismatches == 0
    ids, vals, label = dfm.load_criteo_files(files, TINY.num_field)
    ref = sw.reference_train(TINY, ids, vals, label)
    for k in ref:
        np.testing.assert_allclose(params[k], ref[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_async_multiqueue_converges_to_reference_tolerance(tmp_path):
    """The async-vs-sync convergence parity satellite: multi-source
    round-robin streaming (different batch ORDER than the sequential
    reference, the async part of async SGD) lands within tolerance of
    the sync run's loss/AUC on the full set."""
    files = dfm.make_criteo_files(tmp_path, 4, 48,
                                  num_field=TINY.num_field,
                                  vocab_size=TINY.vocab_size, seed=5)
    params, stepper = _train_stream(TINY, files, thread_num=2)
    assert stepper.row_count_mismatches == 0
    ids, vals, label = dfm.load_criteo_files(files, TINY.num_field)
    ref = sw.reference_train(TINY, ids, vals, label)
    l_ref, a_ref = sw.evaluate_ctr(ref, TINY, ids, vals, label)
    l_got, a_got = sw.evaluate_ctr(params, TINY, ids, vals, label)
    assert abs(l_got - l_ref) < 0.05, (l_got, l_ref)
    assert a_got > 0.75 and a_got > a_ref - 0.05, (a_got, a_ref)


def test_stream_int8_tables_still_learn(tmp_path):
    """int8 row storage (PR 6 convention) through the full streaming
    loop: the model still separates the classes."""
    cfg = sw.CTRJobConfig(**{**TINY.to_wire(), "int8_rows": True})
    files = dfm.make_criteo_files(tmp_path, 2, 64,
                                  num_field=cfg.num_field,
                                  vocab_size=cfg.vocab_size, seed=5)
    params, stepper = _train_stream(cfg, files, thread_num=1)
    ids, vals, label = dfm.load_criteo_files(files, cfg.num_field)
    _, auc = sw.evaluate_ctr(params, cfg, ids, vals, label)
    assert auc > 0.7, auc
    assert stepper.row_count_mismatches == 0


def test_adagrad_tables_beat_flat_sgd_start(tmp_path):
    """Row-wise adagrad accumulators live server-side: loss after one
    pass is finite and falls."""
    cfg = sw.CTRJobConfig(**{**TINY.to_wire(),
                             "table_optimizer": "adagrad",
                             "learning_rate": 0.3})
    files = dfm.make_criteo_files(tmp_path, 2, 64,
                                  num_field=cfg.num_field,
                                  vocab_size=cfg.vocab_size, seed=6)
    params, _ = _train_stream(cfg, files, thread_num=1)
    ids, vals, label = dfm.load_criteo_files(files, cfg.num_field)
    loss, auc = sw.evaluate_ctr(params, cfg, ids, vals, label)
    init_loss, _ = sw.evaluate_ctr(sw.init_host_params(cfg), cfg,
                                   ids, vals, label)
    assert np.isfinite(loss) and loss < init_loss
    assert auc > 0.7


def test_stale_push_refresh_covers_all_shards():
    """Multi-shard stale recovery: when only SOME shards reject a push
    as stale, the refresh must re-pull a row from EACH stale shard and
    MERGE the fresh versions — replacing the dict would zero the other
    shards' versions and wedge the worker forever."""
    specs = [sparse.TableConfig("t", rows=16, dim=2, seed=0,
                                learning_rate=0.1)]
    svc0 = sparse.SparseShardService(shard_id=0, num_shards=2,
                                     staleness_bound=0)
    svc1 = sparse.SparseShardService(shard_id=1, num_shards=2,
                                     staleness_bound=0)
    m0, s0, ep0 = _serve(svc0)
    m1, s1, ep1 = _serve(svc1)
    try:
        c = sparse.SparseShardClient([ep0, ep1])
        c.init_tables(specs)
        _, vers = c.pull_rows("t", np.array([0, 1, 2, 3]))
        # advance shard 1 behind the client's back: its next push is
        # stale (bound 0), shard 0's is fresh
        g1 = sparse.SelectedRows([1], np.ones((1, 2), "f4"), 16)
        v1 = svc1.pull_rows("t", [1])["version"]
        svc1.push_grads("t", g1, v1, "direct")
        stepper = sw.CTRStepper(TINY, c, push_tag="x")
        g = sparse.SelectedRows([0, 1, 2, 3], np.ones((4, 2), "f4"),
                                16)
        out = stepper._push("t", g, vers, "pid")
        # recovered: one stale round-trip, then every row applied
        # (shard 0's re-push deduped by the ledger, not re-applied)
        assert stepper.stale_retries >= 1
        assert out["rows_applied"] == 4 and not out["stale"]
        assert np.asarray(svc0.state("t")["values"]).shape == (8, 2)
        c.close()
    finally:
        s0.shutdown()
        s1.shutdown()


def test_async_executor_multithread_resume_never_skips(tmp_path):
    """With several step workers, completions can land out of order;
    the checkpoint watermark must stay contiguous so a crash-resume
    never SKIPS a line (re-training is allowed only past the
    watermark)."""
    from collections import Counter
    files = [_write_lines(tmp_path / f"s{i}", 24, start=100 * i)
             for i in range(3)]
    ck = str(tmp_path / "stream.json")
    trained = []

    def make_step(fail_after):
        n = [0]

        def step(feed):
            if fail_after is not None and n[0] >= fail_after:
                raise RuntimeError("killed")
            n[0] += 1
            trained.extend(feed["ids"].ravel().tolist())
            return {"n": feed["ids"].shape[0]}
        return step

    exe = AsyncExecutor()
    with pytest.raises(RuntimeError, match="killed"):
        exe.run(None, _count_feed(), files, thread_num=3,
                fetch=["n"], step_fn=make_step(5), checkpoint_path=ck)
    crash_mark = json.load(open(ck))["files"]
    exe.run(None, _count_feed(), files, thread_num=3, fetch=["n"],
            step_fn=make_step(None), checkpoint_path=ck)
    every = set(range(24)) | set(range(100, 124)) | set(
        range(200, 224))
    assert set(trained) == every         # nothing skipped, ever
    # re-trained lines sit strictly PAST their source's crash-time
    # watermark (the bounded in-flight window)
    for line_id, count in Counter(trained).items():
        if count > 1:
            src = str(tmp_path / f"s{line_id // 100}")
            lineno = line_id % 100 + 1
            assert lineno > crash_mark.get(src, 0), (line_id,
                                                     crash_mark)


# ------------------------------------------------- headline e2e

@pytest.mark.chaos
def test_sparse_ctr_e2e_two_workers_chaos_kill(tmp_path):
    """ISSUE 13 headline acceptance: 2 supervised worker processes + a
    parameter-shard service stream a criteo-shaped file set; a chaos
    schedule kill-9s rank 0 mid-stream; the supervisor revives it;
    training completes with exactly-once task-ledger accounting, every
    push applied exactly the batch's unique live ids (no dense
    gradient), and the final AUC/loss lands within tolerance of the
    synchronous single-process reference run."""
    cfg = TINY
    files = dfm.make_criteo_files(tmp_path, 6, 48,
                                  num_field=cfg.num_field,
                                  vocab_size=cfg.vocab_size, seed=5)
    svc = sparse.SparseShardService()
    master = TaskMaster(snapshot_path=str(tmp_path / "master.json"),
                        num_epochs=1, worker_timeout=3.0,
                        lease_timeout=60.0)
    master.set_dataset(files, shards_per_task=1)
    srv, (h, p) = serve_master(master, sparse=svc)
    ep = f"{h}:{p}"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PTPU_SPARSE_CFG=json.dumps(cfg.to_wire()))
    env.pop("XLA_FLAGS", None)
    env.pop("PYTHONPATH", None)
    env.pop("PTPU_CHAOS_SPEC", None)
    cmds, outs = [], []
    for rank in range(2):
        out = str(tmp_path / f"worker{rank}.json")
        outs.append(out)
        cmds.append([sys.executable, "-m", "paddle_tpu.sparse.worker",
                     ep, str(rank), out])
    # rank 0 dies at its trainer.step fault point (hard exit, lease
    # held); the supervisor's restart env strips the spec so the
    # revived incarnation runs clean
    envs = [{"PTPU_CHAOS_SPEC": "trainer.step=exit:0.7:9"}, None]
    sup = Supervisor(cmds, env=env, envs=envs, cwd=REPO,
                     max_restarts=3,
                     backoff=rretry.RetryPolicy(
                         name="supervisor_restart", max_attempts=1,
                         base_delay=0.05, max_delay=0.2),
                     log_dir=str(tmp_path))
    try:
        sup.start()
        ok = sup.wait(timeout=240)
        status = sup.status()
        logs = {r: open(tmp_path / f"worker_r{r}.log",
                        errors="replace").read()[-2000:]
                for r in range(2)
                if (tmp_path / f"worker_r{r}.log").exists()}
        assert ok, (status, logs)
        # exactly-once: every task completed once, none twice/missing
        ledger = master.ledger_entries()
        assert soak.check_ledger(ledger, n_tasks=len(files),
                                 epochs=1) == []
        # the chaos kill really happened and was survived
        assert status[0]["restarts"] >= 1, status
        results = [json.load(open(o)) for o in outs]
        by_rank = {r["rank"]: r for r in results}
        assert by_rank[0]["restart_count"] >= 1
        # no dense-gradient materialization: every push applied exactly
        # the batch's unique live ids, on every incarnation
        for r in results:
            assert r["row_count_mismatches"] == 0, r
            assert r["steps"] == 0 or r["rows_applied"] > 0
        # both workers contributed and all client completion claims are
        # unique (fenced zombie acks never recorded)
        claims = [tuple(c) for r in results for c in r["completed"]]
        assert len(claims) == len(set(claims))
        assert len(claims) == len(files)
        # convergence parity vs the synchronous reference
        ids, vals, label = dfm.load_criteo_files(files, cfg.num_field)
        got = {}
        for spec in sw.table_specs(cfg):
            arr = svc.state(spec.name)["values"]
            arr = np.asarray(arr, "f4")
            got[spec.name] = (arr[0] if spec.name.endswith("_b")
                              else arr)
        ref = sw.reference_train(cfg, ids, vals, label)
        l_ref, a_ref = sw.evaluate_ctr(ref, cfg, ids, vals, label)
        l_got, a_got = sw.evaluate_ctr(got, cfg, ids, vals, label)
        # a killed worker's half-streamed file re-runs under the new
        # lease (pushes are at-least-once ACROSS re-executions), so
        # the bar is convergence tolerance, not bit equality
        assert abs(l_got - l_ref) < 0.08, (l_got, l_ref)
        assert a_got > 0.75 and a_got > a_ref - 0.08, (a_got, a_ref)
    finally:
        sup.stop()
        srv.shutdown()


# ------------------------- push-ledger persistence (ISSUE 14 satellite)

def test_service_snapshot_restart_dedupes_redelivered_push(tmp_path):
    """PR 13 follow-up regression: the push ledger (and the tables it
    guards) survive a SparseShardService restart — a push re-delivered
    across the restart re-acks with the ORIGINAL row count and applies
    NOTHING twice; fresh pushes still land; adagrad accumulators and
    the version carry over."""
    snap = str(tmp_path / "shard.json")
    svc = sparse.SparseShardService(snapshot_path=snap)
    svc.init_tables([sparse.TableConfig("t", rows=8, dim=2, seed=0,
                                        learning_rate=0.5,
                                        optimizer="adagrad")])
    g = sparse.SelectedRows([1, 3, 3], np.ones((3, 2), "f4"), 8)
    v = svc.pull_rows("t", [1, 3])["version"]
    r1 = svc.push_grads("t", g, v, "push-1")
    assert r1["status"] == "ok" and r1["rows_applied"] == 2
    after = svc.state("t")

    # restart mid-stream: a NEW service recovers tables + ledger
    svc2 = sparse.SparseShardService(snapshot_path=snap)
    assert sorted(svc2.tables) == ["t"]
    assert svc2.state("t") == after          # values + version intact
    # at-least-once delivery re-sends the same push id
    r2 = svc2.push_grads("t", g, v, "push-1")
    assert r2["status"] == "ok" and r2.get("duplicate")
    assert r2["rows_applied"] == r1["rows_applied"]
    assert svc2.state("t") == after          # ZERO double-applies
    # the stream continues: a new push lands and re-snapshots
    v2 = svc2.pull_rows("t", [1])["version"]
    g2 = sparse.SelectedRows([1], np.ones((1, 2), "f4"), 8)
    assert svc2.push_grads("t", g2, v2, "push-2")["status"] == "ok"
    svc3 = sparse.SparseShardService(snapshot_path=snap)
    assert svc3.push_grads("t", g2, v2, "push-2").get("duplicate")
    # adagrad accumulators persisted (same grad -> smaller 2nd step)
    t_live = svc2.tables["t"]
    t_back = svc3.tables["t"]
    np.testing.assert_array_equal(t_live._accum, t_back._accum)
    assert t_back.version == t_live.version


def test_service_snapshot_corrupt_falls_back_fresh(tmp_path):
    """The task-master corrupt-snapshot idiom: a torn/bit-flipped shard
    snapshot recovers a FRESH service with a loud warning + counter —
    never a bricked restart."""
    snap = str(tmp_path / "shard.json")
    svc = sparse.SparseShardService(snapshot_path=snap)
    svc.init_tables([sparse.TableConfig("t", rows=4, dim=2, seed=0)])
    with open(snap, "r+b") as f:
        f.seek(25)
        f.write(b"XXXX")
    c0 = _counter("sparse_snapshot_corrupt_total")
    with pytest.warns(RuntimeWarning, match="corrupt"):
        svc2 = sparse.SparseShardService(snapshot_path=snap)
    assert svc2.tables == {}
    assert _counter("sparse_snapshot_corrupt_total") == c0 + 1


def test_service_int8_table_round_trips_snapshot(tmp_path):
    """int8 row storage (codes + scales) survives the snapshot."""
    snap = str(tmp_path / "shard.json")
    svc = sparse.SparseShardService(snapshot_path=snap)
    svc.init_tables([sparse.TableConfig("q", rows=8, dim=4, seed=3,
                                        int8_rows=True)])
    g = sparse.SelectedRows([2], np.full((1, 4), 0.25, "f4"), 8)
    v = svc.pull_rows("q", [2])["version"]
    svc.push_grads("q", g, v, "p")
    svc2 = sparse.SparseShardService(snapshot_path=snap)
    assert svc2.state("q") == svc.state("q")
    t, t2 = svc.tables["q"], svc2.tables["q"]
    np.testing.assert_array_equal(t._codes, t2._codes)
    np.testing.assert_array_equal(t._scales, t2._scales)


def test_service_wal_replay_and_torn_tail(tmp_path):
    """The per-push durability lever is the O(push) WAL: pushes after
    the last full snapshot replay deterministically on restart, and a
    torn tail (crash mid-append) stops replay at the tear with a
    warning instead of bricking the start."""
    snap = str(tmp_path / "shard.json")
    svc = sparse.SparseShardService(snapshot_path=snap)
    svc.init_tables([sparse.TableConfig("t", rows=8, dim=2, seed=0,
                                        learning_rate=0.5,
                                        optimizer="adagrad")])
    v = svc.pull_rows("t", [1, 3])["version"]
    for i in range(3):
        g = sparse.SelectedRows([1, 3], np.full((2, 2), i + 1.0, "f4"),
                                8)
        v = svc.push_grads("t", g, v, f"p{i}")["version"]
    live = svc.state("t")
    wal = snap + ".wal"
    assert os.path.getsize(wal) > 0      # pushes rode the WAL, not
    #                                      full per-push snapshots
    svc2 = sparse.SparseShardService(snapshot_path=snap)
    assert svc2.state("t") == live       # bit-identical replay
    assert all(svc2.push_grads(
        "t", sparse.SelectedRows([1], np.ones((1, 2), "f4"), 8),
        0, f"p{i}").get("duplicate") for i in range(3))
    # tear the last WAL line mid-append
    raw = open(wal, "rb").read()
    open(wal, "wb").write(raw[:-9])
    with pytest.warns(RuntimeWarning, match="torn at line"):
        svc3 = sparse.SparseShardService(snapshot_path=snap)
    # earlier entries replayed; only the torn push is missing
    assert svc3.tables["t"].version == svc2.tables["t"].version - 1


def test_service_corrupt_snapshot_does_not_ledger_wal_pushes(tmp_path):
    """Review regression: when the snapshot is corrupt the WAL must NOT
    replay into the fresh state — its gradients cannot apply (no
    tables), and ledgering their push_ids would dedupe the re-delivered
    pushes whose updates were never applied (silent loss).  After
    re-init, the re-delivered push must land as a REAL apply."""
    snap = str(tmp_path / "shard.json")
    svc = sparse.SparseShardService(snapshot_path=snap)
    cfg = sparse.TableConfig("t", rows=8, dim=2, seed=0,
                             learning_rate=0.5)
    svc.init_tables([cfg])
    g = sparse.SelectedRows([1], np.ones((1, 2), "f4"), 8)
    v = svc.pull_rows("t", [1])["version"]
    assert svc.push_grads("t", g, v, "p1")["status"] == "ok"
    with open(snap, "r+b") as f:
        f.seek(30)
        f.write(b"XXXX")
    with pytest.warns(RuntimeWarning, match="corrupt"):
        svc2 = sparse.SparseShardService(snapshot_path=snap)
    assert svc2.tables == {}
    svc2.init_tables([cfg])                 # the trainer re-inits
    init_dense = svc2.state("t")["values"]
    out = svc2.push_grads("t", g, 0, "p1")  # re-delivery
    assert out["status"] == "ok" and not out.get("duplicate")
    assert svc2.state("t")["values"] != init_dense   # really applied


def test_service_snapshot_runs_off_the_push_path(tmp_path):
    """Review regression: the O(table) full snapshot runs on a
    background thread from a copied view — the push reply does not
    carry it — and the result is restart-equivalent to the live
    state."""
    import time as _time
    snap = str(tmp_path / "shard.json")
    # interval 0 = a full snapshot is DUE on every push (test mode)
    svc = sparse.SparseShardService(snapshot_path=snap,
                                    snapshot_interval=0.0)
    svc.init_tables([sparse.TableConfig("t", rows=8, dim=2, seed=0)])
    g = sparse.SelectedRows([1, 2], np.ones((2, 2), "f4"), 8)
    v = svc.pull_rows("t", [1, 2])["version"]
    assert svc.push_grads("t", g, v, "bg-1")["status"] == "ok"
    deadline = _time.time() + 10
    while svc._snap_pending and _time.time() < deadline:
        _time.sleep(0.01)
    assert not svc._snap_pending            # the bg write completed
    assert not os.path.exists(snap + ".wal.old")   # rotated + dropped
    svc2 = sparse.SparseShardService(snapshot_path=snap)
    assert svc2.state("t") == svc.state("t")
    assert svc2.push_grads("t", g, v, "bg-1").get("duplicate")
