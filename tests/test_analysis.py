"""Static program verifier & lint plane (ISSUE 10): the broken-program
matrix (one deliberately broken program per lint, exact finding
records), the executor's pre-dispatch gate (error mode rejects BEFORE
anything compiles), flag-off invariance, zero-findings passes over
every bundled model + the transpiled variants, the transpiler
post-conditions, the contrib walkers, the graphviz finding overlay,
the lint CLI, and the bench gate."""
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import analysis, layers, models
from paddle_tpu.analysis import lint as lint_cli
from paddle_tpu.core import flags


def _fc_net():
    """x[−1,16] → fc relu → fc → mse loss; returns (loss, pred)."""
    x = layers.data("x", [16], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    return loss, pred


def _feed(batch=4):
    rng = np.random.RandomState(0)
    return {"x": rng.randn(batch, 16).astype("float32"),
            "y": rng.randn(batch, 1).astype("float32")}


def _find_op(block, op_type, nth=0):
    hits = [i for i, op in enumerate(block.ops) if op.type == op_type]
    return hits[nth]


# =====================================================================
# the verifier matrix: one broken program per lint, exact records
# =====================================================================

def test_matrix_undefined_read():
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    i = _find_op(block, "relu")
    block.ops[i].inputs["X"] = ["never_produced"]
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x", "y"], fetch_list=[loss])
    (f,) = res.by_code("undefined_read")
    assert f.severity == analysis.ERROR
    assert f.pass_name == "dataflow"
    assert f.block_idx == 0 and f.op_index == i
    assert f.op_type == "relu"
    assert f.var_names == ("never_produced",)
    assert "no producer" in f.message
    # the op was appended by THIS test file — the layer call site rides
    # the finding
    assert f.callsite and "test_analysis.py" in f.callsite


def test_matrix_shape_mismatch():
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    # a transpiler-style miscompile: rewire the second fc's weight to a
    # parameter with the wrong contraction dim (8 != 16)
    block.create_parameter("bad_w", [8, 4])
    i = _find_op(block, "mul", nth=1)
    block.ops[i].inputs["Y"] = ["bad_w"]
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x", "y"], fetch_list=[loss])
    f = res.by_code("shape_mismatch")[0]
    assert f.severity == analysis.ERROR
    assert f.pass_name == "shape_inference"
    assert f.op_index == i and f.op_type == "mul"
    assert "bad_w" in f.var_names
    assert "contraction mismatch" in f.message
    assert "16" in f.message and "[8, 4]" in f.message


def test_matrix_dead_op():
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    unused = layers.scale(block.var("x"), scale=3.0)
    i = _find_op(block, "scale")
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x", "y"], fetch_list=[loss])
    (f,) = res.by_code("dead_op")
    assert f.severity == analysis.WARN
    assert f.pass_name == "dataflow"
    assert f.op_index == i and f.op_type == "scale"
    assert f.var_names == (unused.name,)
    assert "nothing reads" in f.message
    # fetch-aware: fetching the value makes the op live
    res2 = analysis.verify_program(pt.default_main_program(),
                                   feed=["x", "y"],
                                   fetch_list=[loss, unused])
    assert not res2.by_code("dead_op")


def test_matrix_donated_fetch():
    loss, _ = _fc_net()
    res = analysis.verify_program(
        pt.default_main_program(), feed=["x", "y"],
        fetch_list=["x", loss], donate_feeds=True)
    (f,) = res.by_code("donated_fetch")
    assert f.severity == analysis.ERROR
    assert f.pass_name == "hazards"
    assert f.var_names == ("x",)
    assert "donated" in f.message
    # without donation the same fetch is legal
    res2 = analysis.verify_program(
        pt.default_main_program(), feed=["x", "y"],
        fetch_list=["x", loss], donate_feeds=False)
    assert not res2.by_code("donated_fetch")


def test_matrix_missing_fetch():
    loss, _ = _fc_net()
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x", "y"],
                                  fetch_list=[loss, "no_such_var"])
    (f,) = res.by_code("missing_fetch")
    assert f.severity == analysis.ERROR
    assert f.var_names == ("no_such_var",)
    assert f.op_index == -1


def test_finding_record_schema():
    loss, _ = _fc_net()
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x", "y"],
                                  fetch_list=["nope"])
    d = res.by_code("missing_fetch")[0].to_dict()
    assert d["schema"] == "paddle_tpu.analysis.v1"
    assert set(d) >= {"pass", "code", "severity", "message",
                      "block_idx", "op_index", "op_type", "var_names",
                      "callsite"}
    doc = res.to_dict()
    assert doc["schema"] == "paddle_tpu.analysis.v1"
    assert doc["counts"]["error"] == 1


def test_findings_metric_increments():
    from paddle_tpu.analysis.findings import _m_findings
    before = _m_findings.labels(**{"pass": "dataflow",
                                   "severity": "error"}).value
    loss, _ = _fc_net()
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x", "y"], fetch_list=["nope"])
    after = _m_findings.labels(**{"pass": "dataflow",
                                  "severity": "error"}).value
    assert after == before + len(res.errors) >= before + 1
    assert ("dataflow", "error") in _m_findings.series()


# =====================================================================
# hazard lints
# =====================================================================

def test_hazard_unknown_feed_and_unset_shape():
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    block.create_var("shapeless")        # no shape recorded
    res = analysis.verify_program(
        pt.default_main_program(),
        feed=["x", "y", "shapeless", "not_a_var"], fetch_list=[loss])
    (f,) = res.by_code("unknown_feed")
    assert f.var_names == ("not_a_var",) and f.severity == analysis.WARN
    (g,) = res.by_code("unset_feed_shape")
    assert g.var_names == ("shapeless",)
    assert "feed_shapes" in g.message    # names the forensics cause


def test_hazard_lowp_accum():
    x = layers.data("x", [8, 8], dtype="bfloat16")
    w = pt.default_main_program().global_block().create_parameter(
        "w16", [8, 8], dtype="bfloat16")
    out = layers.matmul(x, pt.default_main_program().global_block()
                        .var("w16"))
    s = layers.reduce_sum(out)
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x"], fetch_list=[s])
    codes = {f.code for f in res.findings}
    assert "lowp_accum" in codes
    f = res.by_code("lowp_accum")[0]
    assert f.severity == analysis.WARN and "amp_bf16" in f.message
    # the amp plane (f32 accumulation) silences the lint
    flags.set_flag("amp_bf16", True)
    try:
        res2 = analysis.verify_program(pt.default_main_program(),
                                       feed=["x"], fetch_list=[s])
        assert not res2.by_code("lowp_accum")
    finally:
        flags.set_flag("amp_bf16", False)


# =====================================================================
# executor pre-dispatch gate
# =====================================================================

def _compile_counters():
    from paddle_tpu.framework.executor import _m_cache_miss, _m_compile
    return _m_compile.total(), _m_cache_miss.total()


def test_executor_error_mode_rejects_before_any_compile():
    """The acceptance bar: a broken program is caught BEFORE any jit
    trace — executor_compile_total unchanged by the rejection."""
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    block.ops[_find_op(block, "relu")].inputs["X"] = ["never_produced"]
    exe = pt.Executor(pt.CPUPlace())
    flags.set_flag("verify_program", "error")
    c0 = _compile_counters()
    with pytest.raises(analysis.ProgramVerificationError) as ei:
        exe.run(pt.default_main_program(), feed=_feed(),
                fetch_list=[loss])
    assert _compile_counters() == c0        # nothing compiled
    assert exe._cache == {}                 # nothing cached
    assert "undefined_read" in str(ei.value)
    assert ei.value.result.errors


def test_executor_error_mode_rejects_run_steps():
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    block.ops[_find_op(block, "relu")].inputs["X"] = ["never_produced"]
    exe = pt.Executor(pt.CPUPlace())
    flags.set_flag("verify_program", "error")
    c0 = _compile_counters()
    feed = {k: np.stack([v, v]) for k, v in _feed().items()}
    with pytest.raises(analysis.ProgramVerificationError):
        exe.run_steps(pt.default_main_program(), feed=feed,
                      fetch_list=[loss], steps=2,
                      per_step_feeds=("x", "y"))
    assert _compile_counters() == c0


def test_executor_error_mode_accepts_valid_run_steps_slabs():
    """Regression (review round): per-step feed slabs carry a leading
    [steps] dim the program never sees — error-mode verification must
    strip it, not reject the valid program as a shape mismatch."""
    loss, _ = _fc_net()
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    flags.set_flag("verify_program", "error")
    f = _feed()
    slabs = {k: np.stack([v, v, v]) for k, v in f.items()}
    outs = exe.run_steps(pt.default_main_program(), feed=slabs,
                         fetch_list=[loss], steps=3,
                         per_step_feeds=("x", "y"))
    assert np.isfinite(outs[0]).all() and outs[0].shape[0] == 3


def test_executor_error_mode_catches_shape_mismatch_statically():
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    block.create_parameter("bad_w", [8, 4])
    block.ops[_find_op(block, "mul", nth=1)].inputs["Y"] = ["bad_w"]
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    flags.set_flag("verify_program", "error")
    c0 = _compile_counters()
    with pytest.raises(analysis.ProgramVerificationError) as ei:
        exe.run(pt.default_main_program(), feed=_feed(),
                fetch_list=[loss])
    assert _compile_counters() == c0
    assert "contraction mismatch" in str(ei.value)


def test_executor_warn_mode_warns_once_and_proceeds_to_trace():
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    block.ops[_find_op(block, "relu")].inputs["X"] = ["never_produced"]
    exe = pt.Executor(pt.CPUPlace())
    assert flags.get_flag("verify_program") == "warn"
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with pytest.raises(Exception, match="not materialised"):
            exe.run(pt.default_main_program(), feed=_feed(),
                    fetch_list=[loss])
    msgs = [str(x.message) for x in w
            if "program verification" in str(x.message)]
    assert len(msgs) == 1 and "undefined_read" in msgs[0]


def test_executor_clean_program_emits_no_warning():
    loss, _ = _fc_net()
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        exe.run(pt.default_startup_program())
        out, = exe.run(pt.default_main_program(), feed=_feed(),
                       fetch_list=[loss])
    assert np.isfinite(out).all()


# =====================================================================
# flag-off invariance (PR 7 idiom)
# =====================================================================

def test_verify_off_is_byte_identical():
    """verify_program=off: compile keys, outputs and explain() match
    the warn-mode (default) executor bit for bit — verification is a
    pure observer; 'off' merely skips it."""
    import json
    feed = _feed()

    def run_mode(mode):
        pt.reset_default_programs()
        from paddle_tpu.framework import executor as em
        em._global_scope = em.Scope()
        flags.set_flag("verify_program", mode)
        loss, _ = _fc_net()
        pt.optimizer.SGD(0.1).minimize(loss)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        outs = [exe.run(pt.default_main_program(), feed=feed,
                        fetch_list=[loss])[0] for _ in range(3)]
        keys = sorted(k[2:] for k in exe._cache)   # drop uid/version
        rep = exe.explain(pt.default_main_program(), feed=feed,
                          fetch_list=[loss])
        rep["program"]["uid"] = 0      # fresh per run; not behavior
        if rep.get("cost"):            # cost label embeds the uid too
            rep["cost"]["label"] = ""
        return outs, keys, rep

    outs_off, keys_off, rep_off = run_mode("off")
    outs_warn, keys_warn, rep_warn = run_mode("warn")
    for a, b in zip(outs_off, outs_warn):
        np.testing.assert_array_equal(a, b)        # bitwise
    assert keys_off == keys_warn
    assert "analysis" not in rep_off               # pre-PR shape
    assert "analysis" in rep_warn
    rep_warn.pop("analysis")
    assert json.dumps(rep_off, sort_keys=True, default=str) \
        == json.dumps(rep_warn, sort_keys=True, default=str)


def test_explain_analysis_section():
    loss, _ = _fc_net()
    # an unfetched dead chain shows up in the explain section's counts
    layers.scale(pt.default_main_program().global_block().var("x"),
                 scale=2.0)
    exe = pt.Executor(pt.CPUPlace())
    rep = exe.explain(pt.default_main_program(), feed=_feed(),
                      fetch_list=[loss])
    sec = rep["analysis"]
    assert sec["mode"] == "warn"
    assert sec["counts"].get("warn", 0) >= 1
    codes = {f["code"] for f in sec["findings"]}
    assert "dead_op" in codes


# =====================================================================
# transpiler post-conditions
# =====================================================================

def test_check_transpiled_raises_named_diagnostic():
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    block.ops[_find_op(block, "relu")].inputs["X"] = ["never_produced"]
    with pytest.raises(analysis.ProgramVerificationError,
                       match="BrokenTranspiler"):
        analysis.check_transpiled(pt.default_main_program(),
                                  "BrokenTranspiler")
    # the escape hatch: off disables post-conditions end to end
    flags.set_flag("verify_program", "off")
    assert analysis.maybe_check_transpiled(
        pt.default_main_program(), "BrokenTranspiler") is None


def test_fuse_transpiler_postcondition_catches_miscompile(monkeypatch):
    """Sabotage FuseBlockTranspiler so its replacement op reads a var
    it just deleted: the post-condition must reject the rewrite."""
    from paddle_tpu.transpiler.fused_block import FuseBlockTranspiler
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=100, tgt_vocab_size=100, max_length=64,
        n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    feeds, cost, _ = models.transformer.build_lm_net(cfg, seq_len=16)
    orig = FuseBlockTranspiler._try_match

    def sabotage(self, block, ops, i, consumers):
        repl, width = orig(self, block, ops, i, consumers)
        if repl is not None:
            repl.inputs["X"] = [repl.inputs["X"][0] + ".GONE"]
        return repl, width

    monkeypatch.setattr(FuseBlockTranspiler, "_try_match", sabotage)
    with pytest.raises(analysis.ProgramVerificationError,
                       match="FuseBlockTranspiler"):
        FuseBlockTranspiler().transpile(pt.default_main_program())


# =====================================================================
# zero-findings passes: every bundled model + transpiled variants
# =====================================================================

@pytest.mark.parametrize("name", ["resnet", "transformer_lm", "bert",
                                  "deepfm", "nmt", "stacked_lstm"])
def test_bundled_model_verifies_clean(name):
    build = lint_cli.model_builders()[name]
    with pt.program_guard(pt.Program(), pt.Program()):
        feeds, fetches = build()
        main = pt.default_main_program()
        res = analysis.verify_program(
            main, feed=[v.name for v in feeds], fetch_list=fetches)
        sres = analysis.verify_program(pt.default_startup_program())
    assert res.findings == [], res.report()
    assert sres.findings == [], sres.report()


def _trained_qat(quantize_dtype="int8"):
    x = layers.data("x", [16], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    from paddle_tpu.transpiler import QuantizeTranspiler
    qt = QuantizeTranspiler(
        activation_quantize_type="moving_average_abs_max")
    qt.training_transpile(pt.default_main_program(),
                          pt.default_startup_program())
    infer = pt.default_main_program().clone(for_test=True)
    pt.optimizer.SGD(0.05).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    for _ in range(4):
        exe.run(pt.default_main_program(), feed=_feed(64),
                fetch_list=[loss])
    frozen = qt.freeze_program(infer, scope=exe.scope,
                               quantize_dtype=quantize_dtype)
    return frozen, pred, loss


def test_quantized_variants_verify_clean():
    frozen, pred, loss = _trained_qat()
    # the QAT train program (verified in-transpile too) and the frozen
    # int8 program both lint clean — zero error findings
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x", "y"], fetch_list=[loss])
    assert res.errors == [], res.report()
    # the clone carries the loss chain too — fetch both heads so the
    # zero-findings bar is meaningful (nothing is dead, no orphans)
    fres = analysis.verify_program(frozen, feed=["x", "y"],
                                   fetch_list=[pred.name, loss.name])
    assert fres.findings == [], fres.report()
    # the frozen program carries no orphaned fp32 weights (they are
    # deleted with their fake-quant producers)
    kinds = {op.type for op in frozen.global_block().ops}
    assert "quantized_matmul" in kinds


def test_freeze_keeps_subblock_only_params():
    """Regression (review round): freeze_program's orphan-Parameter
    sweep must count sub-block reads — a param consumed only inside a
    while/cond sub-block is NOT orphaned."""
    x = layers.data("x", [16], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    main = pt.default_main_program()
    # a parameter read ONLY by an op in a nested block
    main.global_block().create_parameter("sub_only_w", [4, 4])
    sub = main.create_block()
    sub.append_op("scale", {"X": ["sub_only_w"]},
                  {"Out": ["sub_scaled"]}, {"scale": 2.0})
    main.rollback()
    from paddle_tpu.transpiler import QuantizeTranspiler
    qt = QuantizeTranspiler(
        activation_quantize_type="moving_average_abs_max")
    qt.training_transpile(main, pt.default_startup_program())
    infer = main.clone(for_test=True)
    pt.optimizer.SGD(0.05).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    exe.scope.set_var("sub_only_w", np.eye(4, dtype="float32"))
    for _ in range(4):
        exe.run(main, feed=_feed(64), fetch_list=[loss])
    frozen = qt.freeze_program(infer, scope=exe.scope)
    assert "sub_only_w" in frozen.global_block().vars


def test_fused_and_tp_and_pp_variants_verify_clean():
    from paddle_tpu.transpiler import (PipelineTranspiler,
                                       TensorParallelTranspiler)
    from paddle_tpu.transpiler.fused_block import FuseBlockTranspiler

    # fused-block variant (post-condition already ran inside transpile)
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=100, tgt_vocab_size=100, max_length=64,
        n_layer=2, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    feeds, cost, _ = models.transformer.build_lm_net(cfg, seq_len=16)
    pt.optimizer.SGD(0.1).minimize(cost)
    assert FuseBlockTranspiler().transpile(
        pt.default_main_program()) == 2
    res = analysis.verify_program(
        pt.default_main_program(), feed=[v.name for v in feeds],
        fetch_list=[cost])
    assert res.errors == [], res.report()

    # tensor-parallel variant (annotations only; unfused attention)
    pt.reset_default_programs()
    feeds, cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=16, fused_attention=False)
    pt.optimizer.SGD(0.1).minimize(cost)
    TensorParallelTranspiler().transpile(pt.default_main_program(),
                                         num_partitions=2)
    res = analysis.verify_program(
        pt.default_main_program(), feed=[v.name for v in feeds],
        fetch_list=[cost])
    assert res.errors == [], res.report()

    # pipeline variant (boundary markers + spliced allreduce/assign)
    pt.reset_default_programs()
    feeds, cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=16, pp_stages=2)
    pt.optimizer.SGD(0.1).minimize(cost)
    PipelineTranspiler().transpile(pt.default_main_program(),
                                   pp_degree=2)
    res = analysis.verify_program(
        pt.default_main_program(), feed=[v.name for v in feeds],
        fetch_list=[cost])
    assert res.errors == [], res.report()


# =====================================================================
# shape-inference internals
# =====================================================================

def test_infer_rule_registry_alongside_opdef():
    from paddle_tpu.framework import registry
    assert registry.get_shape_infer("mul") is not None
    assert registry.get_op_def("mul") is not None
    # a test-registered rule is visible, then cleaned by conftest's
    # analysis.reset()
    @registry.register_shape_infer("relu")
    def _rule(op, ins, attrs):
        return None
    assert registry.get_shape_infer("relu") is _rule
    analysis.reset()
    assert registry.get_shape_infer("relu") is None


def test_unknown_op_degrades_to_unknown_shape():
    """An op the pass cannot abstract-eval must not crash verification
    (the 'unknown ops degrade, never a crash' contract)."""
    with pt.program_guard(pt.Program(), pt.Program()):
        feeds, sent, scores = \
            models.machine_translation.build_decode_net(
                src_vocab=50, tgt_vocab=50, src_len=8)
        res = analysis.verify_program(
            pt.default_main_program(),
            feed=[v.name for v in feeds], fetch_list=[sent, scores])
    assert res.errors == [], res.report()
    assert "static_rnn_scan" in res.unknown_shape_ops


def test_matmul_infer_rule_transpose_and_batch():
    from paddle_tpu.analysis.infer_rules import (InferError,
                                                 _infer_matmul)

    class _Op:
        inputs = {"X": ["a"], "Y": ["b"]}
    out = _infer_matmul(_Op(), {"X": [((3, 4, 5), "float32")],
                                "Y": [((3, 5, 7), "float32")]}, {})
    assert out["Out"][0][0] == (3, 4, 7)
    out = _infer_matmul(_Op(), {"X": [((4, 5), "float32")],
                                "Y": [((7, 5), "float32")]},
                        {"transpose_Y": True})
    assert out["Out"][0][0] == (4, 7)
    with pytest.raises(InferError, match="contraction mismatch"):
        _infer_matmul(_Op(), {"X": [((4, 5), "float32")],
                              "Y": [((6, 7), "float32")]}, {})
    # dynamic dims are wildcards, not mismatches
    out = _infer_matmul(_Op(), {"X": [((-1, 5), "float32")],
                                "Y": [((5, 7), "float32")]}, {})
    assert out["Out"][0][0] == (-1, 7)
    # batch dims broadcast numpy-style (review-round regression):
    # a size-1 batch dim defers to the other side
    out = _infer_matmul(_Op(), {"X": [((1, 4, 8), "float32")],
                                "Y": [((5, 8, 2), "float32")]}, {})
    assert out["Out"][0][0] == (5, 4, 2)
    out = _infer_matmul(_Op(), {"X": [((5, 4, 8), "float32")],
                                "Y": [((8, 2), "float32")]}, {})
    assert out["Out"][0][0] == (5, 4, 2)


def test_explain_is_a_pure_observer_of_the_findings_metric():
    """Regression (review round): polling explain() must not inflate
    analysis_findings_total — the counter tracks verifier events, not
    report reads."""
    from paddle_tpu.analysis.findings import _m_findings
    loss, _ = _fc_net()
    layers.scale(pt.default_main_program().global_block().var("x"),
                 scale=2.0)          # a warn finding to tempt the counter
    exe = pt.Executor(pt.CPUPlace())
    rep = exe.explain(pt.default_main_program(), feed=_feed(),
                      fetch_list=[loss])
    assert rep["analysis"]["counts"].get("warn", 0) >= 1
    before = _m_findings.total()
    for _ in range(3):
        exe.explain(pt.default_main_program(), feed=_feed(),
                    fetch_list=[loss])
    assert _m_findings.total() == before


# =====================================================================
# satellites: contrib walkers, graphviz overlay, CLI, bench gate
# =====================================================================

def test_contrib_op_frequence_smoke():
    from paddle_tpu.contrib.op_frequence import op_freq_statistic
    loss, _ = _fc_net()
    uni, adj = op_freq_statistic(pt.default_main_program())
    assert uni["mul"] == 2
    assert uni["elementwise_add"] == 2
    assert adj["mul->elementwise_add"] == 2
    # sorted most-frequent-first
    assert list(uni.values()) == sorted(uni.values(), reverse=True)
    with pytest.raises(TypeError, match="should be Program"):
        op_freq_statistic(pt.default_main_program().global_block())


def test_contrib_memory_usage_smoke():
    from paddle_tpu.contrib.memory_usage_calc import memory_usage
    loss, _ = _fc_net()
    lo1, hi1, unit1 = memory_usage(pt.default_main_program(), 16)
    assert 0 < lo1 <= hi1 and unit1 in ("B", "KB", "MB", "GB")
    lo2, hi2, _ = memory_usage(pt.default_main_program(), 256)
    assert hi2 > hi1          # activations scale with the batch dim
    assert lo2 == lo1         # the persistable floor does not
    with pytest.raises(ValueError, match="positive"):
        memory_usage(pt.default_main_program(), 0)


def test_graphviz_highlight_renders_findings(tmp_path):
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    # one dead op + one broken op
    dead = layers.scale(block.var("x"), scale=3.0)
    i_relu = _find_op(block, "relu")
    block.ops[i_relu].inputs["X"] = ["never_produced"]
    res = analysis.verify_program(pt.default_main_program(),
                                  feed=["x", "y"], fetch_list=[loss])
    path = str(tmp_path / "g.dot")
    dot = open(pt.debugger.draw_block_graphviz(
        block, highlight=res, path=path)).read()
    assert f'op_{_find_op(block, "scale")} ' in dot
    assert 'fillcolor="grey80"' in dot          # dead op greyed
    assert 'fillcolor="red"' in dot             # error op red
    assert dot.count("digraph") == 1
    # regression: without highlight the emission is the pre-PR shape
    dot_plain = open(pt.debugger.draw_block_graphviz(
        block, path=str(tmp_path / "p.dot"))).read()
    assert "fillcolor=\"grey80\"" not in dot_plain
    assert "style=rounded]" in dot_plain


def test_analysis_cli_all_models(capsys):
    """Tier-1 CI gate: every bundled model builds and verifies with
    zero errors through the lint CLI."""
    rc = lint_cli.main([])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 errors" in out.splitlines()[-1]
    # every registered model ran
    for name in lint_cli.model_builders():
        assert f"[lint] {name}:" in out


def test_analysis_cli_contract(capsys):
    assert lint_cli.main(["--list"]) == 0
    assert "resnet" in capsys.readouterr().out
    assert lint_cli.main(["--models", "nope"]) == 2
    # the gate CATCHES a broken program: exit 1
    assert lint_cli.main(["--self-test"]) == 1


def test_bench_refuses_unverified_workload():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..",
                              "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    loss, _ = _fc_net()
    block = pt.default_main_program().global_block()
    block.ops[_find_op(block, "relu")].inputs["X"] = ["never_produced"]
    with pytest.raises(RuntimeError,
                       match="failed static verification"):
        bench._verify_gate(pt.default_main_program(), {"x": 0, "y": 0},
                           [loss])
