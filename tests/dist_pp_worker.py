"""Cross-process PIPELINE-parallel worker: 2 localhost processes each
hold one stage of the SAME Program (PipelineTranspiler GPipe schedule)
and exchange boundary activations via ppermute ACROSS the process
boundary — the multi-host story for the Program-plane pipeline, like
dist_worker.py for dp and dist_cp_worker.py for cp.

Run:  python tests/dist_pp_worker.py <coordinator> <world> <rank> <out>
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np

SEED = 21
V, T, D, B, L = 64, 16, 16, 8, 2
STEPS = 4


def build_program(pt, models, pp_stages):
    pt.reset_default_programs()
    main = pt.default_main_program()
    startup = pt.default_startup_program()
    main.random_seed = SEED
    startup.random_seed = SEED
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=V, tgt_vocab_size=V, max_length=T, n_layer=L,
        n_head=2, d_model=D, d_inner=32, dropout=0.0)
    feeds, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=T, fused_attention=False, pp_stages=pp_stages)
    pt.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
    return main, startup, avg_cost


def make_feed():
    rng = np.random.RandomState(2)
    toks = rng.randint(0, V, (B, T)).astype("int64")
    return {"tokens": toks, "labels": np.roll(toks, -1, 1)}


def train_steps(exe, prog, loss):
    feed = make_feed()
    out = []
    for _ in range(STEPS):
        l, = exe.run(prog, feed=feed, fetch_list=[loss])
        out.append(float(np.mean(np.asarray(l))))
    return out


def main():
    coordinator, world, rank, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.parallel import env as penv

    ok = penv.init_distributed_env(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
    assert ok and jax.process_count() == world

    main_p, startup, loss = build_program(pt, models, pp_stages=world)
    pt.transpiler.PipelineTranspiler().transpile(
        main_p, pp_degree=world, n_microbatches=2)

    mesh = Mesh(np.array(jax.devices()[:world]), ("pipe",))
    exe = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe.run(startup)
    losses = train_steps(exe, main_p, loss)

    wname = main_p.all_parameters()[0].name
    w = exe.scope.find_var(wname)
    w_host = np.asarray(w.addressable_data(0))
    result = {"rank": rank, "losses": losses,
              "w_sum": float(np.abs(w_host).sum())}
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("PP_WORKER_OK", rank)


if __name__ == "__main__":
    main()
