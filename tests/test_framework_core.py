"""Core substrate tests: program IR, executor, autodiff, optimizer.

Mirrors the reference's C++-unit tier (framework/*_test.cc) + the
fit_a_line book test (python/paddle/fluid/tests/book/test_fit_a_line.py).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def test_program_build_and_serialize():
    main = pt.Program()
    startup = pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[13])
        y = layers.fc(x, size=1)
    assert main.global_block().has_var(y.name)
    # one mul + one elementwise_add
    types = [op.type for op in main.global_block().ops]
    assert "mul" in types and "elementwise_add" in types
    # round-trip
    s = main.serialize_to_string()
    clone = pt.Program.parse_from_string(s)
    assert [op.type for op in clone.global_block().ops] == types
    assert clone.global_block().var(y.name).dtype == "float32"


def test_shape_inference_through_layers():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[3, 32, 32])
        c = layers.conv2d(x, num_filters=8, filter_size=3, padding=1)
        p = layers.pool2d(c, pool_size=2, pool_stride=2)
        f = layers.fc(p, size=10)
    assert tuple(c.shape) == (-1, 8, 32, 32)
    assert tuple(p.shape) == (-1, 8, 16, 16)
    assert tuple(f.shape) == (-1, 10)


def test_executor_fill_and_fetch():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        a = layers.fill_constant([2, 3], "float32", 5.0)
        b = layers.scale(a, scale=2.0, bias=1.0)
    exe = pt.Executor(pt.CPUPlace())
    b_val, = exe.run(main, fetch_list=[b])
    np.testing.assert_allclose(b_val, np.full((2, 3), 11.0))


def test_startup_initializes_params():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, size=8)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    params = main.all_parameters()
    assert len(params) == 2  # w + b
    for p in params:
        val = exe.scope.find_var(p.name)
        assert val is not None and tuple(val.shape) == tuple(p.shape)


def test_linear_regression_converges():
    """fit_a_line capability: loss must decrease under SGD."""
    rng = np.random.RandomState(0)
    w_true = rng.randn(4, 1).astype(np.float32)
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 42
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = pt.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    losses = []
    for i in range(60):
        xb = rng.randn(16, 4).astype(np.float32)
        yb = xb @ w_true
        lv, = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.05, losses[::10]


def test_adam_converges():
    rng = np.random.RandomState(1)
    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    w = rng.randn(4, 1).astype(np.float32)
    first = last = None
    for i in range(80):
        xb = rng.randn(32, 4).astype(np.float32)
        yb = np.tanh(xb @ w).astype(np.float32)
        lv, = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        first = first if first is not None else float(lv)
        last = float(lv)
    assert last < first * 0.5


def test_grad_vars_materialize():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[3])
        y = layers.fc(x, size=2)
        loss = layers.mean(y)
        params = main.all_parameters()
        grads = pt.append_backward(loss)
    assert len(grads) == 2
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    w = params[0]
    g, = exe.run(main, feed={"x": np.ones((5, 3), np.float32)},
                 fetch_list=[w.name + "@GRAD"])
    assert g.shape == tuple(w.shape)
    # d(mean(xW+b))/dW = x_mean / 2 outputs
    np.testing.assert_allclose(g, np.full(g.shape, 0.5), atol=1e-6)


def test_program_clone_for_test_flips_is_test():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        d = layers.dropout(x, dropout_prob=0.5)
    test_prog = main.clone(for_test=True)
    ops = [op for op in test_prog.global_block().ops
           if op.type == "dropout"]
    assert ops and ops[0].attrs["is_test"] is True


def test_prune_slices_program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
    pruned = main.prune(["x"], [pred.name])
    types = [op.type for op in pruned.global_block().ops]
    assert "square_error_cost" not in types and "mean" not in types
    assert "mul" in types


def test_save_load_persistables(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        pred = layers.fc(x, size=3)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    xb = np.ones((2, 4), np.float32)
    out1, = exe.run(main, feed={"x": xb}, fetch_list=[pred])
    pt.io.save_persistables(exe, str(tmp_path), main_program=main)
    # clobber params, reload, outputs must match
    scope2 = pt.Scope()
    exe2 = pt.Executor(pt.CPUPlace(), scope=scope2)
    pt.io.load_persistables(exe2, str(tmp_path), main_program=main)
    out2, = exe2.run(main, feed={"x": xb}, fetch_list=[pred])
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_save_load_inference_model(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1, act="tanh")
        loss = layers.mean(layers.square_error_cost(pred, y))
        pt.optimizer.SGD(0.01).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    xb = np.random.RandomState(3).randn(5, 4).astype(np.float32)
    # one training step, then reference output via an inference-only slice
    exe.run(main, feed={"x": xb, "y": np.zeros((5, 1), np.float32)},
            fetch_list=[loss])
    infer_prog = main.prune(["x"], [pred.name])
    ref, = exe.run(infer_prog, feed={"x": xb}, fetch_list=[pred.name])
    pt.io.save_inference_model(str(tmp_path), ["x"], [pred], exe,
                               main_program=main)
    scope2 = pt.Scope()
    exe2 = pt.Executor(pt.CPUPlace(), scope=scope2)
    prog, feeds, fetches = pt.io.load_inference_model(str(tmp_path), exe2)
    out, = exe2.run(prog, feed={"x": xb}, fetch_list=fetches)
    np.testing.assert_allclose(ref, out, rtol=1e-5)


def test_regularizer_and_clip():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = pt.optimizer.SGD(
            0.1, regularization=pt.regularizer.L2Decay(0.01))
        opt.minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    lv, = exe.run(main, feed={"x": np.ones((4, 4), np.float32),
                              "y": np.ones((4, 1), np.float32)},
                  fetch_list=[loss])
    assert np.isfinite(lv).all()
