"""Ring-attention (context parallel) tests on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core import jax_compat
from paddle_tpu.parallel import ring_attention as ra
from paddle_tpu.parallel import topology


def test_ring_matches_plain_attention():
    mesh = topology.make_context_mesh(dp=1, cp=8)
    B, T, H, hd = 2, 64, 4, 16
    rng = np.random.RandomState(0)
    q, k, v = [rng.randn(B, T, H, hd).astype("float32") for _ in range(3)]

    ref = np.asarray(ra.plain_attention(jnp.asarray(q), jnp.asarray(k),
                                        jnp.asarray(v), causal=True))

    fn = jax.jit(jax_compat.shard_map(
        lambda q, k, v: ra.ring_attention(q, k, v, "cp", causal=True),
        mesh=mesh,
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
        out_specs=P(None, "cp"), check_rep=False))
    out = np.asarray(fn(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_ring_non_causal_matches():
    mesh = topology.make_context_mesh(dp=2, cp=4)
    B, T, H, hd = 4, 32, 2, 8
    rng = np.random.RandomState(1)
    q, k, v = [rng.randn(B, T, H, hd).astype("float32") for _ in range(3)]
    ref = np.asarray(ra.plain_attention(jnp.asarray(q), jnp.asarray(k),
                                        jnp.asarray(v), causal=False))
    fn = jax.jit(jax_compat.shard_map(
        lambda q, k, v: ra.ring_attention(q, k, v, "cp", causal=False),
        mesh=mesh,
        in_specs=(P("dp", "cp"), P("dp", "cp"), P("dp", "cp")),
        out_specs=P("dp", "cp"), check_rep=False))
    out = np.asarray(fn(q, k, v))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_context_parallel_lm_trains():
    cfg = ra.ContextParallelConfig(vocab_size=128, seq_len=64, d_model=32,
                                   n_heads=4, n_layers=2, d_ff=64,
                                   learning_rate=0.05)
    mesh = topology.make_context_mesh(dp=2, cp=4)
    params = ra.cp_init_params(mesh, cfg, seed=0)
    step = ra.cp_build_train_step(mesh, cfg)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size, (4, cfg.seq_len)).astype("int32")
    labels = np.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(6):
        params, loss = step(params, tokens, labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
