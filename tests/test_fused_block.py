"""Fused transformer block (ISSUE 6 tentpole b): pattern-matching
transpiler, numerics vs the unfused program (forward AND training),
the Pallas kernel in interpret mode (randomized shapes, causal, and
the masked/ragged tail), and the fuse_block executor-key wiring.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.core import flags
from paddle_tpu.kernels import fused_block as fb
from paddle_tpu.transpiler.fused_block import (FuseBlockTranspiler,
                                               maybe_fuse)

# this jax build predates pltpu.CompilerParams; the kernel carries a
# TPUCompilerParams alias, so interpret mode works either way
_HAS_PALLAS = fb._CompilerParams is not None


def _lm(T=32, n_layer=2, dropout=0.0, fused_attention=True):
    cfg = models.transformer.TransformerConfig(
        src_vocab_size=300, tgt_vocab_size=300, max_length=64,
        n_layer=n_layer, n_head=4, d_model=32, d_inner=64,
        dropout=dropout)
    feeds, avg_cost, _ = models.transformer.build_lm_net(
        cfg, seq_len=T, fused_attention=fused_attention)
    return cfg, avg_cost


def _fresh_scope():
    from paddle_tpu.framework import executor as em
    pt.reset_default_programs()
    em._global_scope = em.Scope()


def test_fuse_block_transpiler_matches_unfused_training():
    """The transpiled program (2 fused_transformer_block ops replacing
    20) reproduces the unfused program's loss trajectory — forward and
    gradients — on CPU."""
    # old-jax CPU: keep the unfused baseline off the flash kernels
    old = flags.get_flag("use_pallas_kernels")
    flags.set_flag("use_pallas_kernels", False)
    try:
        cfg, avg_cost = _lm()
        pt.optimizer.SGD(0.1).minimize(avg_cost)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        feed = models.transformer.make_fake_lm_batch(cfg, 2, 32)
        prog = pt.default_main_program()
        ref = [float(exe.run(prog, feed=feed, fetch_list=[avg_cost])[0])
               for _ in range(2)]

        _fresh_scope()
        cfg, avg_cost = _lm()
        pt.optimizer.SGD(0.1).minimize(avg_cost)
        prog = pt.default_main_program()
        n = FuseBlockTranspiler().transpile(prog)
        assert n == 2
        kinds = [op.type for op in prog.global_block().ops]
        assert kinds.count("fused_transformer_block") == 2
        assert "fused_mha" not in kinds and "relu" not in kinds
        exe2 = pt.Executor(pt.CPUPlace())
        exe2.run(pt.default_startup_program())
        fused = [float(exe2.run(prog, feed=feed,
                                fetch_list=[avg_cost])[0])
                 for _ in range(2)]
        np.testing.assert_allclose(fused, ref, rtol=1e-4, atol=1e-4)
    finally:
        flags.set_flag("use_pallas_kernels", old)


def test_fuse_block_skips_foreign_ops_and_external_consumers():
    # a foreign op inside the would-be window (here: a scale between
    # attention and its residual — the slot dropout occupies at rate>0)
    # breaks the contiguous pattern: nothing fuses
    x = layers.data("x", [12, 16], dtype="float32")
    ln1 = layers.layer_norm(x, begin_norm_axis=2)
    attn = layers.fused_mha(ln1, 2, causal=True)
    attn = layers.scale(attn, scale=1.0)
    res1 = layers.elementwise_add(attn, x)
    ln2 = layers.layer_norm(res1, begin_norm_axis=2)
    ffn = layers.fc(layers.fc(ln2, size=32, num_flatten_dims=2,
                              act="relu"), size=16, num_flatten_dims=2)
    layers.elementwise_add(ffn, res1)
    assert FuseBlockTranspiler().transpile(pt.default_main_program()) == 0

    # an intermediate consumed OUTSIDE the block keeps it unfused
    _fresh_scope()
    cfg2 = models.transformer.TransformerConfig(
        src_vocab_size=100, tgt_vocab_size=100, max_length=32,
        n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    tokens = layers.data("tokens", [16], dtype="int64")
    x = models.transformer.prepare_embedding(tokens, 100, 16, 32,
                                             0.0, name="src")
    h = models.transformer.encoder_layer(
        x, None, 2, 8, 8, 16, 32, 0.0, causal=True, fused=True)
    block = pt.default_main_program().global_block()
    mha_out = [op for op in block.ops if op.type == "fused_mha"
               ][0].outputs["Out"][0]
    # read the attention output from outside the would-be fusion window
    layers.mean(block.var(mha_out))
    assert FuseBlockTranspiler().transpile(pt.default_main_program()) == 0


def test_maybe_fuse_is_flag_gated():
    _lm(n_layer=1)
    assert maybe_fuse(pt.default_main_program()) == 0   # flag off
    old = flags.get_flag("fuse_block")
    flags.set_flag("fuse_block", True)
    try:
        assert maybe_fuse(pt.default_main_program()) == 1
    finally:
        flags.set_flag("fuse_block", old)


@pytest.mark.skipif(not _HAS_PALLAS, reason="no pallas compiler params")
@pytest.mark.parametrize("B,T,causal", [(2, 128, True), (2, 80, True),
                                        (1, 200, False)])
def test_block_kernel_interpret_matches_reference(B, T, causal):
    """The Pallas kernel (interpret mode) vs the XLA composition on
    randomized shapes, including ragged tails (T=80/200 pad to the 128
    granule with masked keys)."""
    D, E, F, H = 32, 32, 64, 4
    rng = np.random.RandomState(T)

    def mk(*shape):
        return jnp.asarray(rng.randn(*shape).astype("f4") * 0.3)

    x = mk(B, T, D)
    p = (mk(D) + 1.0, mk(D), mk(D, E), mk(D, E), mk(D, E), mk(E, D),
         mk(D) + 1.0, mk(D), mk(D, F), mk(F), mk(F, D), mk(D))
    ref = fb.block_reference(x, p, H, causal)
    out = fb.transformer_block(x, p, H, causal, use_pallas=True,
                               interpret=True)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-4
    # custom-VJP gradients == the composition's gradients
    g_k = jax.grad(lambda xv: jnp.sum(fb.transformer_block(
        xv, p, H, causal, use_pallas=True, interpret=True)))(x)
    g_r = jax.grad(lambda xv: jnp.sum(
        fb.block_reference(xv, p, H, causal)))(x)
    assert float(jnp.max(jnp.abs(g_k - g_r))) < 1e-4


@pytest.mark.skipif(not _HAS_PALLAS, reason="no pallas compiler params")
def test_block_kernel_bf16_tolerance():
    """Acceptance bound: fused vs unfused within 2e-2 in bf16,
    including a ragged tail."""
    D, E, F, H = 32, 32, 64, 4
    rng = np.random.RandomState(7)

    def mk(*shape):
        return jnp.asarray(rng.randn(*shape).astype("f4") * 0.3,
                           jnp.bfloat16)

    for T in (128, 80):
        x = mk(2, T, D)
        p = (mk(D) + 1.0, mk(D), mk(D, E), mk(D, E), mk(D, E), mk(E, D),
             mk(D) + 1.0, mk(D), mk(D, F), mk(F), mk(F, D), mk(D))
        ref = fb.block_reference(x, p, H, True)
        out = fb.transformer_block(x, p, H, True, use_pallas=True,
                                   interpret=True)
        rel = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                    - ref.astype(jnp.float32)))
                    / jnp.max(jnp.abs(ref.astype(jnp.float32))))
        assert rel < 2e-2, (T, rel)


def test_fuse_block_flag_in_executor_compile_key():
    """Flipping FLAGS_fuse_block must compile a fresh executable (it is
    part of the jit cache key), so a mid-run toggle can never alias the
    fused and unfused programs."""
    x = layers.data("x", [8], dtype="float32")
    loss = layers.mean(layers.fc(x, size=4))
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    feed = {"x": np.ones((2, 8), "float32")}
    prog = pt.default_main_program()
    exe.run(prog, feed=feed, fetch_list=[loss])
    n = len(exe._cache)
    old = flags.get_flag("fuse_block")
    flags.set_flag("fuse_block", True)
    try:
        exe.run(prog, feed=feed, fetch_list=[loss])
    finally:
        flags.set_flag("fuse_block", old)
    assert len(exe._cache) == n + 1
