"""Fleet telemetry plane (ISSUE 4): FleetAggregator merge semantics,
straggler/liveness tracking, the live HTTP endpoint
(observability/server.py), TaskMaster queue metrics, serve_master
lifecycle hardening, trainer step-time anatomy, the reader buffer-depth
gauge, offline trace merge, and the 2-rank end-to-end scrape."""
import json
import os
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags, profiler
from paddle_tpu.distributed import TaskMaster, TaskMasterClient, \
    serve_master
from paddle_tpu.observability import fleet, flight as obs_flight
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import server as obs_server
from paddle_tpu.observability import trace as obs_trace

from dist_harness import free_port, spawn_workers


# --- payload helpers ------------------------------------------------------

def _doc(counters=None, hists=None, gauges=None):
    """A paddle_tpu.metrics.v1 document from plain dicts.  counters /
    gauges: {name: value | [(labels, value), ...]}; hists:
    {name: {"sum", "count", "buckets", "overflow"}}."""
    metrics = {}
    for name, v in (counters or {}).items():
        rows = v if isinstance(v, list) else [({}, v)]
        metrics[name] = {"type": "counter", "help": "",
                         "series": [{"labels": dict(l), "value": x}
                                    for l, x in rows]}
    for name, v in (gauges or {}).items():
        rows = v if isinstance(v, list) else [({}, v)]
        metrics[name] = {"type": "gauge", "help": "",
                         "series": [{"labels": dict(l), "value": x}
                                    for l, x in rows]}
    for name, row in (hists or {}).items():
        metrics[name] = {"type": "histogram", "help": "",
                         "series": [{"labels": {}, **row}]}
    return {"schema": "paddle_tpu.metrics.v1", "metrics": metrics}


def _payload(rank, doc=None, steps=0.0, t=None, perf=None):
    return {"schema": fleet.SCHEMA, "rank": rank, "host": f"h{rank}",
            "pid": 1000 + rank,
            "time_unix": time.time() if t is None else t,
            "perf_counter": (time.perf_counter() if perf is None
                             else perf),
            "steps_total": steps, "metrics": doc or _doc()}


def _events(rank, spans, t=None, perf=None, flight_bundle=None):
    return {"schema": fleet.SCHEMA, "rank": rank,
            "time_unix": time.time() if t is None else t,
            "perf_counter": (time.perf_counter() if perf is None
                             else perf),
            "spans": spans, "flight": flight_bundle}


# --- aggregator merge semantics -------------------------------------------

def test_counters_sum_across_workers():
    agg = fleet.FleetAggregator(stale_after=3600)
    agg.ingest_metrics(_payload(0, _doc(counters={
        "w_steps_total": 3.0,
        "w_labeled_total": [({"kind": "a"}, 2.0), ({"kind": "b"}, 1.0)],
    }), steps=3))
    agg.ingest_metrics(_payload(1, _doc(counters={
        "w_steps_total": 4.0,
        "w_labeled_total": [({"kind": "a"}, 5.0)],
    }), steps=4))
    fams = agg.merged_families()
    series = fams["w_steps_total"]["series"]
    assert [r["value"] for r in series.values()] == [7.0]
    labeled = {tuple(sorted(r["labels"].items())): r["value"]
               for r in fams["w_labeled_total"]["series"].values()}
    assert labeled == {(("kind", "a"),): 7.0, (("kind", "b"),): 1.0}
    txt = agg.prometheus_text()
    assert "w_steps_total 7.0" in txt
    assert 'w_labeled_total{kind="a"} 7.0' in txt


def test_histogram_buckets_merge():
    h0 = {"sum": 1.0, "count": 3, "buckets": {"0.1": 2, "1.0": 1},
          "overflow": 0}
    h1 = {"sum": 9.0, "count": 2, "buckets": {"0.1": 0, "1.0": 1},
          "overflow": 1}
    agg = fleet.FleetAggregator(stale_after=3600)
    agg.ingest_metrics(_payload(0, _doc(hists={"w_lat_seconds": h0})))
    agg.ingest_metrics(_payload(1, _doc(hists={"w_lat_seconds": h1})))
    fam = agg.merged_families()["w_lat_seconds"]
    (row,) = fam["series"].values()
    assert row["sum"] == 10.0 and row["count"] == 5
    assert row["buckets"] == {"0.1": 2, "1.0": 2} and row["overflow"] == 1
    txt = agg.prometheus_text()
    # cumulative buckets: 2 (<=0.1), 4 (<=1.0), 5 (+Inf)
    assert 'w_lat_seconds_bucket{le="0.1"} 2' in txt
    assert 'w_lat_seconds_bucket{le="1.0"} 4' in txt
    assert 'w_lat_seconds_bucket{le="+Inf"} 5' in txt
    assert "w_lat_seconds_count 5" in txt


def test_gauges_keep_worker_label():
    agg = fleet.FleetAggregator(stale_after=3600)
    agg.ingest_metrics(_payload(0, _doc(gauges={"w_throughput": 10.0})))
    agg.ingest_metrics(_payload(1, _doc(gauges={"w_throughput": 30.0})))
    fam = agg.merged_families()["w_throughput"]
    per = {r["labels"]["worker"]: r["value"]
           for r in fam["series"].values()}
    assert per == {"0": 10.0, "1": 30.0}
    txt = agg.prometheus_text()
    assert 'w_throughput{worker="0"} 10.0' in txt
    assert 'w_throughput{worker="1"} 30.0' in txt


def test_empty_fleet_family_does_not_clobber_local():
    """Workers declare taskmaster_tasks at import but never set it; the
    coordinator's populated gauges must survive the overlay (while a
    populated fleet family replaces the local zero-valued one)."""
    agg = fleet.FleetAggregator(stale_after=3600)
    agg.ingest_metrics(_payload(0, _doc(
        counters={"trainer_steps_total": 5.0},
        gauges={"taskmaster_tasks": []})))
    local = _doc(counters={"trainer_steps_total": 0.0},
                 gauges={"taskmaster_tasks": [({"state": "todo"}, 7.0)]})
    fams = agg.merged_families(local=local)
    (tm_row,) = fams["taskmaster_tasks"]["series"].values()
    assert tm_row["value"] == 7.0
    (steps_row,) = fams["trainer_steps_total"]["series"].values()
    assert steps_row["value"] == 5.0


def test_straggler_warning_once():
    flags.set_flag("straggler_factor", 2.0)
    agg = fleet.FleetAggregator(stale_after=3600)
    c0 = obs_metrics.REGISTRY.get(
        "fleet_straggler_warnings_total").total()
    agg.ingest_metrics(_payload(0, steps=20))
    agg.ingest_metrics(_payload(1, steps=22))
    with pytest.warns(RuntimeWarning, match="straggler: rank 2"):
        agg.ingest_metrics(_payload(2, steps=4))
    reg = obs_metrics.REGISTRY.get("fleet_straggler_warnings_total")
    assert reg.total() - c0 == 1
    # warned once: a repeat report from the same laggard is silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        agg.ingest_metrics(_payload(2, steps=5))
    assert agg.health()["stragglers"] == [2]
    assert agg.health()["degraded"]


def test_no_straggler_when_disabled_or_warming_up():
    # a lone worker can't straggle, and factor <= 1 disables the check
    agg = fleet.FleetAggregator(stale_after=3600, straggler_factor=1.0)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        agg.ingest_metrics(_payload(0, steps=100))
        agg.ingest_metrics(_payload(1, steps=1))
    # below straggler_min_steps the fleet is still warming up
    agg2 = fleet.FleetAggregator(stale_after=3600, straggler_factor=2.0,
                                 straggler_min_steps=1000)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        agg2.ingest_metrics(_payload(0, steps=100))
        agg2.ingest_metrics(_payload(1, steps=1))


def test_stale_worker_degrades_health():
    agg = fleet.FleetAggregator(stale_after=0.05)
    agg.ingest_metrics(_payload(0, steps=1))
    assert not agg.health()["degraded"]
    time.sleep(0.1)
    h = agg.health()
    assert h["stale"] == [0] and h["degraded"]
    assert h["per_worker"]["0"]["stale"]


def test_worker_step_rate_tracked():
    agg = fleet.FleetAggregator(stale_after=3600)
    agg.ingest_metrics(_payload(0, steps=0))
    time.sleep(0.05)
    agg.ingest_metrics(_payload(0, steps=10))
    rate = agg.workers()[0]["step_rate"]
    assert rate > 0
    # a restarted worker's counter goes backwards: rate clamps to 0,
    # never exports a large negative spike
    time.sleep(0.02)
    agg.ingest_metrics(_payload(0, steps=2))
    assert agg.workers()[0]["step_rate"] == 0.0


def test_offline_merge_warns_on_rank_collision(tmp_path):
    """Colliding filename ranks are remapped to the next pid — loudly,
    so nobody debugs the wrong rank's timeline."""
    for name, span in (("trace0.json", "a"), ("trace_rank0.json", "b")):
        obs_trace.reset()
        obs_trace.enable()
        obs_trace.add_span(span, time.perf_counter(), 0.01, tid=1)
        obs_trace.disable()
        obs_trace.export_chrome_trace(str(tmp_path / name))
    obs_trace.reset()
    with pytest.warns(RuntimeWarning, match="already taken"):
        merged = fleet.merge_trace_files(
            [str(tmp_path / "trace0.json"),
             str(tmp_path / "trace_rank0.json")])
    body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    assert {e["pid"] for e in body} == {0, 1}


def test_bad_schema_rejected():
    agg = fleet.FleetAggregator(stale_after=3600)
    with pytest.raises(ValueError, match="fleet payload schema"):
        agg.ingest("report_metrics", {"schema": "bogus.v9", "rank": 0})
    with pytest.raises(ValueError, match="unknown fleet verb"):
        agg.ingest("report_bogus", _payload(0))


# --- clock normalization + trace merge ------------------------------------

def test_merged_trace_normalizes_clocks():
    """Two ranks with wildly different perf_counter epochs but the same
    wall clock: concurrent spans must land at the same normalized ts
    under distinct pids."""
    agg = fleet.FleetAggregator(stale_after=3600)
    wall = time.time()
    # rank 0: perf epoch ~1000s; its span starts at perf 1000.5
    agg.ingest_events(_events(
        0, [{"name": "step", "ph": "X", "ts": 1000.5, "dur": 0.25,
             "tid": 1, "cat": "executor"}], t=wall, perf=1001.0))
    # rank 1: perf epoch ~9000s; concurrent span at the same wall time
    agg.ingest_events(_events(
        1, [{"name": "step", "ph": "X", "ts": 9000.5, "dur": 0.25,
             "tid": 1, "cat": "executor"},
            {"name": "mark", "ph": "i", "ts": 9000.9, "tid": 3,
             "cat": "marker"}], t=wall, perf=9001.0))
    tr = agg.merged_trace()
    json.loads(json.dumps(tr, allow_nan=False))   # strict JSON
    spans = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in spans} == {0, 1}
    ts = {e["pid"]: e["ts"] for e in spans}
    # both spans started 0.5s before their report: same normalized ts
    # (within the RTT the skew term absorbs)
    assert abs(ts[0] - ts[1]) < 0.2 * 1e6
    body = [e for e in tr["traceEvents"] if e.get("ph") != "M"]
    assert [e["ts"] for e in body] == sorted(e["ts"] for e in body)
    inst = [e for e in body if e["ph"] == "i"]
    assert inst and inst[0]["s"] == "t"
    # per-rank process metadata for perfetto grouping
    pnames = [e for e in tr["traceEvents"]
              if e.get("ph") == "M" and e["name"] == "process_name"]
    assert {e["pid"] for e in pnames} == {0, 1}


def test_offline_trace_merge_cli(tmp_path):
    """--merge-traces merges per-rank chrome dumps (the files
    export_chrome_trace leaves behind) with clock_sync normalization:
    strict JSON, one pid per rank, events sorted."""
    for rank in (0, 1):
        obs_trace.reset()
        obs_trace.enable()
        t = time.perf_counter()
        obs_trace.add_span(f"work_r{rank}", t, 0.01, tid=1,
                           cat="executor")
        obs_trace.add_instant(f"mark_r{rank}", t + 0.01, tid=3)
        obs_trace.disable()
        obs_trace.export_chrome_trace(
            str(tmp_path / f"trace_rank{rank}.json"))
    obs_trace.reset()
    out = str(tmp_path / "fleet_trace.json")
    rc = fleet._main(["--merge-traces", str(tmp_path), "-o", out])
    assert rc == 0
    with open(out) as f:
        merged = json.load(f)
    body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    assert {e["pid"] for e in body} == {0, 1}
    assert [e["ts"] for e in body] == sorted(e["ts"] for e in body)
    assert merged["metadata"]["fleet_ranks"] == [0, 1]
    names = {e["name"] for e in body}
    assert "work_r0" in names and "work_r1" in names
    # rerun with -o inside the input dir: the previous merged output
    # (and any non-trace json) must be skipped, not re-ingested
    with open(tmp_path / "results.json", "w") as f:
        json.dump({"rank": 0, "steps": 3}, f)
    assert fleet._main(["--merge-traces", str(tmp_path), "-o", out]) == 0
    with open(out) as f:
        merged2 = json.load(f)
    assert merged2["metadata"]["fleet_ranks"] == [0, 1]
    body2 = [e for e in merged2["traceEvents"] if e.get("ph") != "M"]
    assert len(body2) == len(body)


def test_reporter_failed_push_does_not_drop_spans():
    """A flush that dies mid-push must leave the span cursor / flight
    watermark untouched so the next tick re-sends the window."""
    class FlakyClient:
        def __init__(self):
            self.metrics, self.events, self.fail = [], [], True

        def report_metrics(self, p):
            self.metrics.append(p)

        def report_events(self, p):
            if self.fail:
                self.fail = False
                raise ConnectionError("coordinator away")
            self.events.append(p)

        def close(self):
            pass

    obs_trace.reset()
    obs_trace.enable()
    try:
        obs_trace.add_span("s1", 1.0, 0.1, tid=1)
        rep = fleet.FleetReporter("h", 1, rank=0, interval=99,
                                  client=FlakyClient())
        with pytest.raises(ConnectionError):
            rep.flush()                      # push fails AFTER recording
        obs_trace.add_span("s2", 2.0, 0.1, tid=1)
        rep.flush()                          # retries the whole window
        (payload,) = rep._client.events
        assert {e["name"] for e in payload["spans"]} == {"s1", "s2"}
    finally:
        obs_trace.disable()
        obs_trace.reset()


def test_flight_scrape_is_a_pure_observer():
    """GET /flight before any dump must not advance the counter-delta
    baseline a later REAL crash bundle reports against."""
    c = obs_metrics.counter("t_flight_obs_total", "test")
    obs_flight.reset()
    c.inc(1)
    s = obs_server.start_http_server(port=free_port())
    try:
        code, fl = _get(s.url + "/flight")       # on-demand build
        assert json.loads(fl)["reason"] == "http_on_demand"
        c.inc(2)
        obs_flight.dump("real_trip")
        deltas = obs_flight.last_bundle()["counter_deltas"]
        # the full window since reset survives the scrape: 1 + 2
        assert deltas["t_flight_obs_total"] == 3.0
    finally:
        obs_server.stop_http_server()


def test_metrics_json_strict_with_nan_gauge():
    """/metrics.json and /healthz must stay strict JSON even when a
    gauge holds NaN (a poisoned loss is exactly when people scrape)."""
    g = obs_metrics.gauge("t_nan_gauge", "test")
    g.set(float("nan"))
    s = obs_server.start_http_server(port=free_port())
    try:
        code, js = _get(s.url + "/metrics.json")
        doc = json.loads(js)      # the raw token NaN would fail here
        (row,) = doc["metrics"]["t_nan_gauge"]["series"]
        assert row["value"] == "nan"
    finally:
        obs_server.stop_http_server()
        g.set(0.0)


def test_coordinator_enrolls_itself_via_ingest_local():
    """ingest_local folds THIS process's registry into the fleet sums
    with worker attribution — the coordinator-also-trains path."""
    c = obs_metrics.counter("t_coord_steps_total", "test")
    base = c.value
    c.inc(4)
    agg = fleet.FleetAggregator(stale_after=3600)
    agg.ingest_metrics(_payload(1, _doc(counters={
        "t_coord_steps_total": 2.0})))
    agg.ingest_local(rank=0)
    fams = agg.merged_families()
    (row,) = fams["t_coord_steps_total"]["series"].values()
    assert row["value"] == base + 4 + 2.0
    assert set(agg.workers()) == {0, 1}


def test_reporter_stop_skips_closing_flush_when_lock_held():
    """stop() must not stack a second retry cycle behind a loop flush
    stuck on a dead coordinator: bounded wait, then skip."""
    rep = fleet.FleetReporter.__new__(fleet.FleetReporter)
    rep.rank, rep.interval = 0, 0.05
    rep._own_client, rep._client = False, None
    rep._span_cursor, rep._flight_dumps = 0, obs_flight.dump_count()
    rep._trace_gen = obs_trace.generation()
    rep._stop = __import__("threading").Event()
    rep._thread = None
    rep._flush_lock = __import__("threading").Lock()
    f0 = obs_metrics.REGISTRY.get("fleet_report_failures_total").value
    rep._flush_lock.acquire()     # a stuck loop flush holds the lock
    try:
        t0 = time.time()
        rep.stop(flush=True)      # must bound, skip, count a failure
        assert time.time() - t0 < 5.0
    finally:
        rep._flush_lock.release()
    assert obs_metrics.REGISTRY.get(
        "fleet_report_failures_total").value == f0 + 1


def test_start_http_server_conflicts_are_loud():
    s = obs_server.start_http_server(port=free_port())
    try:
        # idempotent no-conflict calls return the running server
        assert obs_server.start_http_server() is s
        assert obs_server.start_http_server(port=s.address[1]) is s
        # an aggregator attaches to an aggregator-less server (the
        # coordinator-also-trains race with Trainer.ensure_started)
        agg = fleet.FleetAggregator(stale_after=3600)
        assert obs_server.start_http_server(aggregator=agg) is s
        assert s.aggregator is agg
        # conflicting requests raise instead of being ignored
        with pytest.raises(RuntimeError, match="different FleetAgg"):
            obs_server.start_http_server(
                aggregator=fleet.FleetAggregator(stale_after=1))
        with pytest.raises(RuntimeError, match="requested port"):
            obs_server.start_http_server(port=s.address[1] + 1)
        # a failed call leaves no side effect: the rogue aggregator of
        # a bad-port request must NOT end up attached
        rogue = fleet.FleetAggregator(stale_after=1)
        with pytest.raises(RuntimeError, match="requested port"):
            obs_server.start_http_server(port=s.address[1] + 1,
                                         aggregator=rogue)
        assert s.aggregator is agg
    finally:
        obs_server.stop_http_server()


def test_offline_merge_mixed_clock_sync(tmp_path):
    """A dump without clock_sync metadata (pre-fleet / foreign) aligns
    at the earliest SYNCED timestamp, not unix zero."""
    obs_trace.reset()
    obs_trace.enable()
    obs_trace.add_span("synced", time.perf_counter(), 0.01, tid=1)
    obs_trace.disable()
    obs_trace.export_chrome_trace(str(tmp_path / "trace_rank0.json"))
    obs_trace.reset()
    foreign = {"traceEvents": [
        {"name": "legacy", "ph": "X", "ts": 5_000_000.0, "dur": 100.0,
         "pid": 0, "tid": 1}]}            # no metadata.clock_sync
    with open(tmp_path / "trace_rank1.json", "w") as f:
        json.dump(foreign, f)
    merged = fleet.merge_trace_files(
        [str(tmp_path / "trace_rank0.json"),
         str(tmp_path / "trace_rank1.json")])
    body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    assert {e["pid"] for e in body} == {0, 1}
    # both ranks share one origin: everything within a second, not
    # epoch-seconds apart
    assert max(e["ts"] for e in body) - min(e["ts"] for e in body) < 1e6


def test_straggler_recovers_and_healthz_unlatches():
    """A diagnosed straggler that catches back up clears the degraded
    state (and may warn again on a fresh lapse) — /healthz must not
    latch at 503 forever."""
    agg = fleet.FleetAggregator(stale_after=3600, straggler_factor=2.0)
    agg.ingest_metrics(_payload(0, steps=20))
    agg.ingest_metrics(_payload(1, steps=22))
    with pytest.warns(RuntimeWarning, match="straggler: rank 2"):
        agg.ingest_metrics(_payload(2, steps=4))
    assert agg.health()["degraded"]
    agg.ingest_metrics(_payload(2, steps=21))    # caught up
    h = agg.health()
    assert h["stragglers"] == [] and not h["degraded"]


def test_straggler_unlatches_when_fleet_shrinks():
    """A straggler diagnosis must not pin /healthz at 503 after the
    rest of the fleet departs and no median comparison exists."""
    agg = fleet.FleetAggregator(stale_after=3600, straggler_factor=2.0)
    agg.ingest_metrics(_payload(0, steps=20))
    agg.ingest_metrics(_payload(1, steps=22))
    with pytest.warns(RuntimeWarning, match="straggler: rank 2"):
        agg.ingest_metrics(_payload(2, steps=4))
    for r in (0, 1):                 # fleet finishes around the laggard
        p = _payload(r, steps=25)
        p["closing"] = True
        agg.ingest_metrics(p)
    agg.ingest_metrics(_payload(2, steps=30))    # lone live worker
    h = agg.health()
    assert h["stragglers"] == [] and not h["degraded"]


def test_departed_worker_keeps_counts_but_never_goes_stale():
    """A closing report retires the rank from liveness alarms while its
    counters stay in the fleet sums."""
    agg = fleet.FleetAggregator(stale_after=0.05)
    agg.ingest_metrics(_payload(0, _doc(counters={"w_done_total": 7.0}),
                                steps=7))
    p = _payload(0, _doc(counters={"w_done_total": 9.0}), steps=9)
    p["closing"] = True
    agg.ingest_metrics(p)
    time.sleep(0.1)                  # well past stale_after
    h = agg.health()
    assert h["per_worker"]["0"]["departed"]
    assert h["stale"] == [] and not h["degraded"]
    fams = agg.merged_families()
    (row,) = fams["w_done_total"]["series"].values()
    assert row["value"] == 9.0
    (up,) = fams["fleet_worker_up"]["series"].values()
    assert up["value"] == 0.0        # departed = not up, just not alarmed


def test_reporter_resends_after_trace_reset():
    """trace.reset() shrinking the buffer restarts the span cursor at 0
    — post-reset spans must reach the coordinator, not be clamped away."""
    class Sink:
        def __init__(self):
            self.events = []

        def report_metrics(self, p):
            pass

        def report_events(self, p):
            self.events.append(p)

        def close(self):
            pass

    obs_trace.reset()
    obs_trace.enable()
    try:
        for i in range(5):
            obs_trace.add_span(f"pre{i}", float(i), 0.1, tid=1)
        rep = fleet.FleetReporter("h", 1, rank=0, interval=99,
                                  client=Sink())
        rep.flush()
        obs_trace.reset()                      # e.g. reset_profiler()
        # regrow PAST the old cursor (5): a length heuristic would
        # silently drop post0..post4 — the generation check must not
        for i in range(7):
            obs_trace.add_span(f"post{i}", float(i), 0.1, tid=1)
        rep.flush()
        names = {e["name"] for e in rep._client.events[-1]["spans"]}
        assert names == {f"post{i}" for i in range(7)}
    finally:
        obs_trace.disable()
        obs_trace.reset()


def test_reporter_flushes_are_serialized():
    """stop()'s closing flush must not interleave with a loop flush on
    the shared client socket: flushes hold one lock."""
    import threading as th

    class SlowClient:
        def __init__(self):
            self.active = 0
            self.max_active = 0
            self._l = th.Lock()

        def report_metrics(self, p):
            with self._l:
                self.active += 1
                self.max_active = max(self.max_active, self.active)
            time.sleep(0.05)
            with self._l:
                self.active -= 1

        def report_events(self, p):
            pass

        def close(self):
            pass

    rep = fleet.FleetReporter("h", 1, rank=0, interval=99,
                              client=SlowClient())
    threads = [th.Thread(target=rep.flush) for _ in range(4)]
    for t in threads:
        t.start()
    rep.stop()                   # closing flush competes with the four
    for t in threads:
        t.join()
    assert rep._client.max_active == 1


def test_local_unlabeled_counter_survives_worker_zero_series():
    """Workers eagerly declare unlabeled counters at 0 (taskmaster_
    lease_expired_total); their zero rows must not clobber the
    coordinator's real count — but real worker counts DO win."""
    agg = fleet.FleetAggregator(stale_after=3600)
    agg.ingest_metrics(_payload(0, _doc(counters={
        "taskmaster_lease_expired_total": 0.0,
        "trainer_steps_total": 3.0})))
    local = _doc(counters={"taskmaster_lease_expired_total": 1.0,
                           "trainer_steps_total": 0.0})
    fams = agg.merged_families(local=local)
    (lease,) = fams["taskmaster_lease_expired_total"]["series"].values()
    assert lease["value"] == 1.0          # local signal kept
    (steps,) = fams["trainer_steps_total"]["series"].values()
    assert steps["value"] == 3.0          # fleet signal wins


def test_ensure_started_bind_failure_warns_not_raises():
    """The Trainer's flag-gated auto-start must never take training
    down: a lost port race warns and continues."""
    s = obs_server.start_http_server(port=free_port())
    taken = s.address[1]
    obs_server.stop_http_server()
    import socket
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", taken))
    blocker.listen(1)
    old = flags.get_flag("obs_http_port")
    flags.set_flag("obs_http_port", taken)
    try:
        with pytest.warns(RuntimeWarning,
                          match="observability endpoint not started"):
            assert obs_server.ensure_started() is None
        assert obs_server.get_server() is None
    finally:
        flags.set_flag("obs_http_port", old)
        blocker.close()


def test_trainer_raises_on_none_batch():
    """A buggy reader yielding None mid-stream must fail loudly at the
    feeder, not silently truncate the epoch."""
    def bad_reader():
        rng = np.random.RandomState(0)
        yield [(rng.rand(4).astype("float32"), np.array([1], "int64"))
               for _ in range(4)]
        yield None

    with pytest.raises(TypeError):
        _tiny_train(bad_reader)


# --- task master: queue metrics + lifecycle -------------------------------

def _tasks_gauge(state):
    return obs_metrics.REGISTRY.get("taskmaster_tasks").labels(
        state=state).value


def test_taskmaster_queue_state_metrics():
    m = TaskMaster(lease_timeout=0.05)
    m.set_dataset([f"s{i}" for i in range(4)])
    assert _tasks_gauge("todo") == 4
    t = m.get_task()
    assert _tasks_gauge("todo") == 3 and _tasks_gauge("pending") == 1
    m.task_finished(t.task_id)
    assert _tasks_gauge("pending") == 0 and _tasks_gauge("done") == 1
    c0 = obs_metrics.REGISTRY.get("taskmaster_lease_expired_total").value
    m.get_task()
    time.sleep(0.1)
    m.stats()                      # _requeue_expired runs here
    c1 = obs_metrics.REGISTRY.get("taskmaster_lease_expired_total").value
    assert c1 - c0 == 1
    assert _tasks_gauge("pending") == 0


def test_serve_master_bind_error_names_endpoint():
    port = free_port()
    m = TaskMaster()
    srv, (host, p) = serve_master(m, port=port)
    try:
        with pytest.raises(OSError, match=f"127.0.0.1:{port}"):
            serve_master(TaskMaster(), port=port)
    finally:
        srv.shutdown()


def test_serve_master_shutdown_joins_thread():
    m = TaskMaster()
    srv, (host, port) = serve_master(m)
    t = srv._serve_thread
    assert t.is_alive()
    srv.shutdown()
    assert not t.is_alive()
    # the socket is released: the same port rebinds immediately
    srv2, addr2 = serve_master(TaskMaster(), port=port)
    assert addr2[1] == port
    srv2.shutdown()


def test_report_rpc_roundtrip():
    agg = fleet.FleetAggregator(stale_after=3600)
    m = TaskMaster()
    srv, (host, port) = serve_master(m, aggregator=agg)
    try:
        with TaskMasterClient(host, port) as c:
            ack = c.report_metrics(_payload(
                0, _doc(counters={"w_rpc_total": 2.0}), steps=2))
            assert ack["ok"] and "server_time_unix" in ack
            c.report_events(_events(
                0, [{"name": "s", "ph": "X", "ts": 1.0, "dur": 0.1,
                     "tid": 1, "cat": "executor"}]))
            # schema violations surface as application errors
            with pytest.raises(RuntimeError, match="fleet payload"):
                c.report_metrics({"schema": "nope", "rank": 0})
    finally:
        srv.shutdown()
    assert agg.workers()[0]["steps_total"] == 2
    assert len(agg.merged_trace()["traceEvents"]) >= 2


def test_report_without_aggregator_is_an_error():
    m = TaskMaster()
    srv, (host, port) = serve_master(m)     # no aggregator
    try:
        with TaskMasterClient(host, port) as c:
            with pytest.raises(RuntimeError, match="no FleetAggregator"):
                c.report_metrics(_payload(0))
    finally:
        srv.shutdown()


def test_reporter_constructs_before_coordinator_listens():
    """Workers and coordinator start concurrently: constructing (and
    stopping) a reporter against a not-yet-bound port must never raise
    — the dial happens lazily at first flush and failures absorb."""
    rep = fleet.FleetReporter("127.0.0.1", 1, rank=0, interval=0.01)
    rep.start()
    f0 = obs_metrics.REGISTRY.get("fleet_report_failures_total").value
    time.sleep(0.1)              # a few loop ticks, all refused
    rep.stop()                   # closing flush refused too — absorbed
    assert obs_metrics.REGISTRY.get(
        "fleet_report_failures_total").value > f0


def test_fleet_reporter_background_push():
    agg = fleet.FleetAggregator(stale_after=3600)
    m = TaskMaster()
    srv, (host, port) = serve_master(m, aggregator=agg)
    try:
        rep = fleet.FleetReporter(host, port, rank=5, interval=0.05)
        rep.start()
        deadline = time.time() + 5.0
        while 5 not in agg.workers() and time.time() < deadline:
            time.sleep(0.02)
        rep.stop()
    finally:
        srv.shutdown()
    assert 5 in agg.workers()


# --- HTTP endpoint --------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_http_endpoints_local_registry():
    m = TaskMaster()
    m.set_dataset(["a", "b"])
    s = obs_server.start_http_server(port=free_port())
    try:
        code, txt = _get(s.url + "/metrics")
        assert code == 200
        assert 'taskmaster_tasks{state="todo"} 2' in txt
        code, js = _get(s.url + "/metrics.json")
        doc = json.loads(js)
        assert doc["schema"] == "paddle_tpu.metrics.v1"
        assert "taskmaster_tasks" in doc["metrics"]
        code, hz = _get(s.url + "/healthz")
        hz = json.loads(hz)
        assert code == 200 and hz["status"] == "ok"
        assert hz["trainer"]["steps"] == 0 and hz["fleet"] is None
        code, fl = _get(s.url + "/flight")
        assert code == 200
        assert json.loads(fl)["schema"] == "paddle_tpu.flight.v1"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(s.url + "/nope")
        assert ei.value.code == 404
    finally:
        obs_server.stop_http_server()


def test_http_healthz_degraded_is_503():
    agg = fleet.FleetAggregator(stale_after=0.01)
    agg.ingest_metrics(_payload(0, steps=1))
    time.sleep(0.05)
    s = obs_server.start_http_server(port=free_port(), aggregator=agg)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(s.url + "/healthz")
        assert ei.value.code == 503
        doc = json.loads(ei.value.read().decode())
        assert doc["status"] == "degraded"
        assert doc["fleet"]["stale"] == [0]
    finally:
        obs_server.stop_http_server()


def test_healthz_degrades_on_hung_trainer():
    """A RUNNING trainer with no step for > the stale window is hung:
    /healthz must 503 so a probe restarts it; a finished trainer (not
    running) with the same old timestamp must stay 200."""
    obs_server.note_trainer_running(True)
    obs_server.note_trainer_step()
    obs_server._liveness["last_step_unix"] -= 120.0   # fake old step
    s = obs_server.start_http_server(port=free_port())
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(s.url + "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["trainer"]["hung"]
        obs_server.note_trainer_running(False)   # clean finish
        obs_server._liveness["last_step_unix"] -= 120.0
        code, hz = _get(s.url + "/healthz")
        assert code == 200 and not json.loads(hz)["trainer"]["hung"]
    finally:
        obs_server.stop_http_server()


def test_http_server_bind_error_names_endpoint():
    s = obs_server.start_http_server(port=free_port())
    try:
        port = s.address[1]
        with pytest.raises(OSError, match=f"127.0.0.1:{port}"):
            obs_server.ObservabilityServer(port=port)
    finally:
        obs_server.stop_http_server()


def test_http_server_flag_gated():
    flags.set_flag("obs_http_port", 0)
    assert obs_server.ensure_started() is None
    assert obs_server.get_server() is None


# --- trainer step anatomy -------------------------------------------------

def _tiny_train(reader, epochs=1):
    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                      act="softmax")
        return layers.mean(layers.cross_entropy(p, y))

    trainer = pt.Trainer(train_func=train_func,
                         optimizer_func=lambda: pt.optimizer.SGD(0.1),
                         place=pt.CPUPlace())
    trainer.train(num_epochs=epochs, event_handler=lambda e: None,
                  reader=reader, feed_order=["x", "y"])
    trainer.stop()


def _hist_sums():
    reg = obs_metrics.REGISTRY
    return {name: (reg.get(name).sum, reg.get(name).count)
            for name in ("trainer_step_seconds",
                         "trainer_data_wait_seconds",
                         "trainer_host_seconds",
                         "trainer_device_seconds")}


def test_step_anatomy_sums_to_step_time():
    """Acceptance: in a profiled 3-step run the summed anatomy
    (data_wait + host + device) is within 20% of trainer_step time,
    and each anatomy histogram saw every step."""
    def reader():
        rng = np.random.RandomState(0)
        for _ in range(3):
            yield [(rng.rand(4).astype("float32"),
                    np.array([1], "int64")) for _ in range(4)]

    before = _hist_sums()
    profiler.reset_profiler()
    profiler.enable_profiler()
    try:
        _tiny_train(reader)
    finally:
        profiler.disable_profiler()
    after = _hist_sums()
    d = {k: (after[k][0] - before[k][0], after[k][1] - before[k][1])
         for k in after}
    assert all(v[1] == 3 for v in d.values()), d
    step = d["trainer_step_seconds"][0]
    parts = (d["trainer_data_wait_seconds"][0]
             + d["trainer_host_seconds"][0]
             + d["trainer_device_seconds"][0])
    assert step > 0
    assert abs(parts - step) <= 0.2 * step, (parts, step)
    # the anatomy rides the unified trace too
    names = [e["name"] for e in obs_trace.events()]
    for n in ("trainer.data_wait", "trainer.host", "trainer.device"):
        assert names.count(n) == 3, names
    # trainer liveness (the /healthz source) advanced with the steps
    assert obs_server.trainer_liveness()["steps"] == 3
    assert obs_server.trainer_liveness()["alive"]


def test_anatomy_excludes_begin_handler_time():
    """A slow BeginStepEvent handler is user code — neither data wait
    nor host/device; trainer_step_seconds must exclude it so the
    anatomy invariant (and the input-bound fraction) stays honest."""
    def reader():
        rng = np.random.RandomState(0)
        for _ in range(3):
            yield [(rng.rand(4).astype("float32"),
                    np.array([1], "int64")) for _ in range(4)]

    def train_func():
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        p = layers.fc(layers.fc(x, size=8, act="relu"), size=3,
                      act="softmax")
        return layers.mean(layers.cross_entropy(p, y))

    def slow_handler(e):
        if isinstance(e, pt.BeginStepEvent):
            time.sleep(0.08)      # would dwarf a sub-ms CPU step

    before = _hist_sums()
    trainer = pt.Trainer(train_func=train_func,
                         optimizer_func=lambda: pt.optimizer.SGD(0.1),
                         place=pt.CPUPlace())
    trainer.train(num_epochs=1, event_handler=slow_handler,
                  reader=reader, feed_order=["x", "y"])
    trainer.stop()
    after = _hist_sums()
    d = {k: after[k][0] - before[k][0] for k in after}
    parts = (d["trainer_data_wait_seconds"] + d["trainer_host_seconds"]
             + d["trainer_device_seconds"])
    step = d["trainer_step_seconds"]
    assert abs(parts - step) <= 0.2 * step, (parts, step)


def test_input_bound_warning_fires_and_flag_disables():
    """A reader that sleeps per batch trips the input-bound diagnosis
    once the data-wait fraction crosses the flag; the unit check below
    covers the flag=0 disable without a second Trainer compile."""
    def slow_reader():
        rng = np.random.RandomState(0)
        for _ in range(10):
            time.sleep(0.03)
            yield [(rng.rand(4).astype("float32"),
                    np.array([1], "int64")) for _ in range(4)]

    old = flags.get_flag("input_bound_warn_fraction")
    flags.set_flag("input_bound_warn_fraction", 0.2)
    try:
        with pytest.warns(RuntimeWarning, match="input-bound"):
            _tiny_train(slow_reader)
        # flag 0 disables: same accumulated evidence, no warning
        flags.set_flag("input_bound_warn_fraction", 0.0)
        anatomy = {"data_wait": 9.0, "step": 10.0, "n": 50,
                   "warned": False}
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            pt.Trainer._note_anatomy(None, anatomy, 0.5, 0.5)
        assert not anatomy["warned"]
    finally:
        flags.set_flag("input_bound_warn_fraction", old)


def test_reader_buffer_depth_gauge():
    from paddle_tpu.reader import buffered

    def src():
        for i in range(10):
            yield i

    it = iter(buffered(src, 5, name="t_outer")())
    assert next(it) == 0
    time.sleep(0.05)         # let the producer fill the queue
    list(it)
    g = obs_metrics.REGISTRY.get("reader_buffer_depth")
    series = g.labels(reader="t_outer")
    assert series.value >= 0   # sampled at every consume
    # a slow consumer observes a filled queue through ITS OWN labeled
    # series — composed pipelines don't race one shared gauge
    it2 = iter(buffered(lambda: iter(range(10)), 5, name="t_inner")())
    next(it2)
    time.sleep(0.05)
    next(it2)
    assert g.labels(reader="t_inner").value > 0
    assert g.labels(reader="t_outer").value == 0   # drained earlier


# --- 2-rank end-to-end (the ISSUE acceptance scenario) --------------------

def test_two_rank_fleet_scrape_end_to_end(tmp_path):
    """Two spawned workers each train 3 real Trainer steps and report to
    the coordinator this test owns; ONE urllib scrape of /metrics shows
    trainer_steps_total summed across ranks next to the coordinator's
    taskmaster_tasks gauges; the merged chrome trace is strict JSON with
    spans under both pids (live AND offline paths)."""
    agg = fleet.FleetAggregator(stale_after=3600)
    master = TaskMaster()
    master.set_dataset(["shard-0", "shard-1", "shard-2"])
    srv, (host, port) = serve_master(master, aggregator=agg)
    web = obs_server.start_http_server(port=free_port(), aggregator=agg)
    try:
        results = spawn_workers("dist_fleet_worker.py", world=2,
                                tmp_path=tmp_path,
                                coordinator=f"127.0.0.1:{port}",
                                timeout=240)
        assert [r["rank"] for r in results] == [0, 1]
        want_steps = sum(r["steps"] for r in results)
        assert want_steps == 6

        code, txt = _get(web.url + "/metrics")
        assert code == 200
        line = [ln for ln in txt.splitlines()
                if ln.startswith("trainer_steps_total ")]
        assert line and float(line[0].split()[-1]) == want_steps, line
        assert 'taskmaster_tasks{state="todo"} 3' in txt
        # merged histograms: 6 fleet-wide steps observed
        cnt = [ln for ln in txt.splitlines()
               if ln.startswith("trainer_step_seconds_count ")]
        assert cnt and float(cnt[0].split()[-1]) == 6, cnt
        # per-worker gauges carry the worker label
        assert 'worker="0"' in txt and 'worker="1"' in txt

        # /healthz: both ranks reported recently -> not degraded
        code, hz = _get(web.url + "/healthz")
        hz = json.loads(hz)
        assert code == 200 and hz["fleet"]["workers"] == 2
        assert not hz["fleet"]["degraded"]

        # live merged trace: strict JSON, spans under two pids
        tr = agg.merged_trace()
        json.loads(json.dumps(tr, allow_nan=False))
        pids = {e["pid"] for e in tr["traceEvents"]
                if e.get("ph") == "X"}
        assert pids == {0, 1}
        names = {e["name"] for e in tr["traceEvents"]}
        assert "executor.step" in names and "trainer.host" in names

        # per-worker anatomy: data_wait + host + device ~= step (20%)
        for r in results:
            a = r["anatomy"]
            parts = (a["trainer_data_wait_seconds"]["sum"]
                     + a["trainer_host_seconds"]["sum"]
                     + a["trainer_device_seconds"]["sum"])
            step = a["trainer_step_seconds"]["sum"]
            assert abs(parts - step) <= 0.2 * step, (r["rank"], a)

        # offline merge of the per-rank dumps matches the live story
        merged = fleet.merge_trace_files(
            [r["trace_path"] for r in results],
            out_path=str(tmp_path / "fleet_trace.json"))
        body = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
        assert {e["pid"] for e in body} == {0, 1}
        assert [e["ts"] for e in body] == sorted(e["ts"] for e in body)
    finally:
        obs_server.stop_http_server()
        srv.shutdown()


# --- model-health fleet path (ISSUE 7) ------------------------------------

def _model_payload(rank, step, norm, steps=10.0, epoch=None):
    p = _payload(rank, steps=steps)
    p["model"] = {"step": step, "epoch": epoch, "sample": 1,
                  "time_unix": time.time(),
                  "grad_norm": norm, "update_ratio": 0.01,
                  "nan_vars": 0, "first_bad": None}
    return p


def test_grad_divergence_warning_once_per_step():
    """Same-step per-rank grad norms differing by > the factor under dp
    warn ONCE per sample step and bump the counter; matched norms and
    repeat reports stay quiet."""
    agg = fleet.FleetAggregator(grad_divergence_factor=10.0)
    c = obs_metrics.REGISTRY.get("fleet_grad_divergence_warnings_total")
    c0 = c.value
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        agg.ingest_metrics(_model_payload(0, 5, 1.0))
        agg.ingest_metrics(_model_payload(1, 5, 1.5))    # in sync: quiet
        agg.ingest_metrics(_model_payload(1, 6, 55.0))
        agg.ingest_metrics(_model_payload(0, 6, 1.0))    # 55x gap: warn
        agg.ingest_metrics(_model_payload(0, 6, 1.0))    # repeat: once
    div = [x for x in w if "grad divergence" in str(x.message)]
    assert len(div) == 1
    msg = str(div[0].message)
    assert "step 6" in msg and "rank 1" in msg and "55" in msg
    assert c.value - c0 == 1
    assert agg.model_rows()[1]["grad_norm"] == 55.0


def test_grad_divergence_respects_disable_and_mismatched_steps():
    agg = fleet.FleetAggregator(grad_divergence_factor=0.0)  # <=1 = off
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        agg.ingest_metrics(_model_payload(0, 3, 1.0))
        agg.ingest_metrics(_model_payload(1, 3, 1e6))
    assert [x for x in w if "divergence" in str(x.message)] == []
    # different sample steps never compare (interval skew is normal)
    agg2 = fleet.FleetAggregator(grad_divergence_factor=10.0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        agg2.ingest_metrics(_model_payload(0, 3, 1.0))
        agg2.ingest_metrics(_model_payload(1, 4, 1e6))
        # non-finite norms are the guard's problem, not a sync verdict
        agg2.ingest_metrics(_model_payload(1, 3, float("nan")))
    assert [x for x in w if "divergence" in str(x.message)] == []


def test_grad_divergence_aligns_on_resumable_epoch_step():
    """Rows align on the trainer's (epoch, step-in-epoch) position:
    a respawned worker whose dispatch counter restarted still compares
    at the right step, and the SAME step-in-epoch in different epochs
    never cross-compares (a restarted rank in epoch 0 vs a survivor in
    epoch 1 is interval skew, not a desync)."""
    agg = fleet.FleetAggregator(grad_divergence_factor=10.0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        # same step number, DIFFERENT epochs: never compared
        agg.ingest_metrics(_model_payload(0, 5, 1.0, epoch=1))
        agg.ingest_metrics(_model_payload(1, 5, 1e6, epoch=0))
    assert [x for x in w if "divergence" in str(x.message)] == []
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        # survivor and respawned rank meet at the same (epoch, step)
        agg.ingest_metrics(_model_payload(1, 7, 99.0, epoch=1))
        agg.ingest_metrics(_model_payload(0, 7, 1.0, epoch=1))
    div = [x for x in w if "grad divergence" in str(x.message)]
    assert len(div) == 1
    assert "epoch 1 step 7" in str(div[0].message)


def test_model_route_serves_local_and_worker_rows():
    """/model: the local snapshot (None when sampling never ran) plus
    every rank's latest compact stats row; per-rank grad norms also
    land on /metrics via the gauge-with-worker-label merge."""
    from paddle_tpu.observability import tensorstats as obs_tensorstats
    agg = fleet.FleetAggregator(grad_divergence_factor=0.0)
    agg.ingest_metrics(_model_payload(0, 7, 2.5))
    p1 = _model_payload(1, 7, 2.6)
    p1["metrics"] = _doc(gauges={"model_grad_norm":
                                 [({"var": "__all__"}, 2.6)]})
    agg.ingest_metrics(p1)
    srv = obs_server.start_http_server(port=0, aggregator=agg)
    try:
        doc = json.load(urllib.request.urlopen(srv.url + "/model"))
        assert doc["schema"] == "paddle_tpu.model.v1"
        assert doc["enabled"] == obs_tensorstats.enabled()
        assert doc["local"] is None        # no local sample this test
        assert doc["workers"]["0"]["step"] == 7
        assert doc["workers"]["1"]["grad_norm"] == 2.6
        # the fleet /metrics view carries rank 1's grad-norm gauge
        # under a worker label
        text = urllib.request.urlopen(srv.url + "/metrics").read()
        assert b'model_grad_norm{var="__all__",worker="1"} 2.6' in text
    finally:
        obs_server.stop_http_server()


def test_snapshot_payload_carries_model_row():
    """FleetReporter's metric payload ships the tensorstats row once a
    sample exists (None before)."""
    from paddle_tpu.observability import tensorstats as obs_tensorstats
    assert fleet.snapshot_payload(0)["model"] is None
    import paddle_tpu.optimizer  # noqa: F401
    pt.reset_default_programs()
    x = layers.data("x", [4], dtype="float32")
    loss = layers.mean(layers.fc(x, size=4))
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    flags.set_flag("tensor_stats", True)
    flags.set_flag("tensor_stats_interval", 1)
    try:
        exe.run(pt.default_main_program(),
                feed={"x": np.ones((2, 4), "float32")},
                fetch_list=[loss])
    finally:
        flags.set_flag("tensor_stats", False)
        flags.set_flag("tensor_stats_interval", 10)
    row = fleet.snapshot_payload(0)["model"]
    assert row is not None and row["grad_norm"] > 0
    assert row["nan_vars"] == 0
