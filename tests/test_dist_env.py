"""Multi-host harness: 2 localhost processes train the SAME framework
Program (layers DSL -> DistributeTranspiler(trainers=2) -> mesh Executor)
with per-step loss AND final-weight parity vs a single-process run — the
reference's test_dist_base.py:212 (spawn localhost trainers running the
real stack) + :502 check_with_place (loss-delta comparison) contract."""
import numpy as np

from dist_harness import spawn_workers


def _single_process_reference():
    """Ground truth: the identical Program trained on one device in THIS
    process (conftest pins an 8-CPU-device pool; plain Executor)."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    import dist_worker

    pt.reset_default_programs()
    main_p, startup, loss = dist_worker.build_program(pt, layers)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    losses = dist_worker.train_steps(exe, main_p, loss)
    wname = main_p.all_parameters()[0].name
    w = np.asarray(exe.scope.find_var(wname))
    return losses, w


def test_two_process_framework_dp_parity(tmp_path):
    results = spawn_workers("dist_worker.py", world=2, tmp_path=tmp_path)
    ref_losses, ref_w = _single_process_reference()
    # the framework stack crossed the process boundary: per-step losses
    # and the trained weights of the 2-process collective run match the
    # local run
    for r in results:
        np.testing.assert_allclose(r["losses"], ref_losses,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(r["w_head"], ref_w.ravel()[:8],
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(r["w_sum"], float(np.abs(ref_w).sum()),
                                   rtol=1e-4)
    # loss decreased and both ranks agree bit-for-bit on the weights
    assert ref_losses[-1] < ref_losses[0]
    assert results[0]["w_sum"] == results[1]["w_sum"]
    np.testing.assert_array_equal(results[0]["w_head"],
                                  results[1]["w_head"])


def _single_process_cp_reference():
    """The identical LM Program trained un-transpiled on one device."""
    import paddle_tpu as pt
    from paddle_tpu import models

    import dist_cp_worker

    main_p, startup, loss = dist_cp_worker.build_program(pt, models)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    losses = dist_cp_worker.train_steps(exe, main_p, loss)
    wname = main_p.all_parameters()[0].name
    w = np.asarray(exe.scope.find_var(wname))
    return losses, float(np.abs(w).sum())


def test_two_process_context_parallel_parity(tmp_path):
    """Sequence-sharded feeds cross the process boundary: B=1 <
    cp_degree=2, so a batch-sharded global feed could not even be built
    — the executor must globalize along _dist_feed_shard_dim."""
    results = spawn_workers("dist_cp_worker.py", world=2,
                            tmp_path=tmp_path)
    ref_losses, ref_w_sum = _single_process_cp_reference()
    for r in results:
        np.testing.assert_allclose(r["losses"], ref_losses,
                                   rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(r["w_sum"], ref_w_sum, rtol=1e-4)
    assert ref_losses[-1] < ref_losses[0]
    assert results[0]["w_sum"] == results[1]["w_sum"]


def _single_process_pp_reference():
    import paddle_tpu as pt
    from paddle_tpu import models

    import dist_pp_worker

    main, startup, loss = dist_pp_worker.build_program(pt, models,
                                                       pp_stages=1)
    exe = pt.Executor(pt.CPUPlace(), scope=pt.Scope())
    exe.run(startup)
    return dist_pp_worker.train_steps(exe, main, loss)


def test_two_process_pipeline_parallel_parity(tmp_path):
    """Stage activations ppermute ACROSS the process boundary: 2
    spawned processes each run one GPipe stage of the same Program;
    per-step losses match the un-transpiled single-process run."""
    results = spawn_workers("dist_pp_worker.py", world=2,
                            tmp_path=tmp_path)
    ref = _single_process_pp_reference()
    for r in results:
        np.testing.assert_allclose(r["losses"], ref, rtol=2e-4,
                                   atol=1e-5)
    assert ref[-1] < ref[0]
    assert results[0]["w_sum"] == results[1]["w_sum"]
