"""Multi-host rendezvous harness: 2 localhost processes train a DP model
through parallel/env.init_distributed_env with loss parity vs a
single-process run (the reference's test_dist_base.py:212,502 contract)."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _reference_losses():
    """Single-process ground truth of the worker's training loop."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 3).astype("float64")
    y = x @ np.array([[1.0], [-2.0], [0.5]])
    w = np.zeros((3, 1))
    losses = []
    for _ in range(5):
        pred = x @ w
        losses.append(float(np.sum((pred - y) ** 2) / 8))
        g = 2 * x.T @ (pred - y) / 8
        w = w - 0.1 * g
    return losses, w.ravel()


def test_two_process_dp_parity(tmp_path):
    world = 2
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    procs, outs = [], []
    for rank in range(world):
        out = str(tmp_path / f"r{rank}.json")
        outs.append(out)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)      # one CPU device per process
        env.pop("PYTHONPATH", None)     # axon plugin quirk: never set it
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "dist_worker.py"),
             coordinator, str(world), str(rank), out],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout.decode(errors="replace"))
    for rc, log in zip((p.returncode for p in procs), logs):
        assert rc == 0, f"worker failed rc={rc}:\n{log[-2000:]}"

    ref_losses, ref_w = _reference_losses()
    results = [json.load(open(o)) for o in outs]
    for r in results:
        np.testing.assert_allclose(r["losses"], ref_losses,
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(r["w"], ref_w, rtol=1e-4, atol=1e-6)
    # both ranks agree bit-for-bit on the replicated weights
    np.testing.assert_array_equal(results[0]["w"], results[1]["w"])
