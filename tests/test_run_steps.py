"""Executor.run_steps: a device-side training loop (lax.scan over the
compiled step) must advance state exactly like N sequential run() calls
— same losses, same final params, same RNG stream — for constant feeds,
per-step feed slabs, and the implicit-SPMD mesh plane.

Reference analogue: repeated exe.run train loops with
num_iteration_per_drop_scope (parallel_executor.cc:191)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _build_net(seed=None):
    pt.reset_default_programs()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    if seed is not None:
        main.random_seed = seed
        startup.random_seed = seed
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        h = layers.dropout(h, dropout_prob=0.3)
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _batch(rng, n=1):
    x = rng.rand(n, 8, 4).astype("float32") if n > 1 else \
        rng.rand(8, 4).astype("float32")
    y = (x.sum(-1, keepdims=True) * 0.5).astype("float32")
    return x, y


def test_run_steps_matches_sequential_runs():
    rng = np.random.RandomState(0)
    main, startup, loss = _build_net()
    x, y = _batch(rng)
    feed = {"x": x, "y": y}

    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    seq_losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
                  for _ in range(4)]
    seq_w = {n: np.asarray(exe.scope.find_var(n))
             for n in exe.scope.var_names()}

    main2, startup2, loss2 = _build_net()
    exe2 = pt.Executor(pt.CPUPlace())
    exe2.run(startup2)
    stacked, = exe2.run_steps(main2, feed=feed, fetch_list=[loss2],
                              steps=4)
    assert stacked.shape[0] == 4
    np.testing.assert_allclose(stacked.ravel(), seq_losses, rtol=1e-6)
    for n, w in seq_w.items():
        np.testing.assert_allclose(
            np.asarray(exe2.scope.find_var(n)), w, rtol=1e-6,
            err_msg=n)


def test_run_steps_per_step_feed_slab():
    rng = np.random.RandomState(1)
    main, startup, loss = _build_net(seed=7)
    xs, ys = _batch(rng, n=3)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    seq = [float(exe.run(main, feed={"x": xs[i], "y": ys[i]},
                         fetch_list=[loss])[0]) for i in range(3)]

    main2, startup2, loss2 = _build_net(seed=7)
    exe2 = pt.Executor(pt.CPUPlace())
    exe2.run(startup2)
    stacked, = exe2.run_steps(
        main2, feed={"x": xs, "y": ys}, fetch_list=[loss2], steps=3,
        per_step_feeds=("x", "y"))
    np.testing.assert_allclose(stacked.ravel(), seq, rtol=1e-6)


def test_run_steps_validates_slab_dim():
    main, startup, loss = _build_net()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    x = np.zeros((2, 8, 4), "float32")
    y = np.zeros((2, 8, 1), "float32")
    with pytest.raises(Exception, match="leading dim"):
        exe.run_steps(main, feed={"x": x, "y": y}, fetch_list=[loss],
                      steps=3, per_step_feeds=("x", "y"))


def test_run_steps_on_mesh_data_parallel():
    from paddle_tpu.core.place import make_mesh
    rng = np.random.RandomState(2)
    x = rng.rand(8, 4).astype("float32")
    y = (x.sum(-1, keepdims=True) * 0.5).astype("float32")
    feed = {"x": x, "y": y}

    main, startup, loss = _build_net(seed=11)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    seq = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
           for _ in range(3)]

    main2, startup2, loss2 = _build_net(seed=11)
    mesh = make_mesh((8,), ("data",))
    exe2 = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe2.run(startup2)
    stacked, = exe2.run_steps(main2, feed=feed, fetch_list=[loss2],
                              steps=3)
    np.testing.assert_allclose(stacked.ravel(), seq, rtol=1e-5)
