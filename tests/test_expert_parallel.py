"""ExpertParallelTranspiler: switch-MoE on the Program plane.

Identity tests pin the semantics: with every expert initialized to the
SAME weights and capacity ample enough to drop nothing, top-1 routing
is equivalent to the dense FFN those weights define — single-device,
AND expert-sharded over the 8-device mesh (all_to_all dispatch).
Training parity: the ep-transpiled program's loss trajectory matches
the single-device run step for step."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.place import make_mesh

E, D, F = 4, 8, 16


def _build_moe_net(cf=64.0):
    pt.reset_default_programs()
    main, startup = pt.default_main_program(), pt.default_startup_program()
    main.random_seed = startup.random_seed = 5
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[D], dtype="float32")
        y = layers.data("y", shape=[D], dtype="float32")
        out, aux = layers.moe(x, num_experts=E, d_hidden=F,
                              capacity_factor=cf,
                              param_attr=pt.ParamAttr(name="moe"))
        mse = layers.reduce_mean(layers.square(out - y))
        loss = layers.elementwise_add(mse, layers.reduce_sum(aux))
    return main, startup, loss, out, mse


def _tie_experts(scope):
    """Make every expert identical so routing cannot change the math."""
    for nm, axis_rows in (("moe.w1", (D, F)), ("moe.w2", (F, D))):
        w = np.array(scope.find_var(nm))
        w[:] = w[0]
        scope.set_var(nm, w)


def _batch(n=16):
    rng = np.random.RandomState(0)
    x = rng.randn(n, D).astype("f4")
    return {"x": x, "y": (x * 0.5 + 0.1).astype("f4")}


def test_moe_with_tied_experts_equals_dense_ffn():
    main, startup, loss, out, _ = _build_moe_net()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    _tie_experts(exe.scope)
    feed = _batch()
    got, = exe.run(main, feed=feed, fetch_list=[out])
    w1 = np.asarray(exe.scope.find_var("moe.w1"))[0]
    w2 = np.asarray(exe.scope.find_var("moe.w2"))[0]
    gate = np.asarray(exe.scope.find_var("moe.gate"))
    probs = np.exp(feed["x"] @ gate)
    probs /= probs.sum(-1, keepdims=True)
    dense = np.maximum(feed["x"] @ w1, 0.0) @ w2
    # top-1 switch scales by the winning gate prob
    np.testing.assert_allclose(got, dense * probs.max(-1)[:, None],
                               rtol=2e-5, atol=1e-6)


def test_moe_ep_mesh_matches_single_device():
    from paddle_tpu.transpiler import ExpertParallelTranspiler
    feed = _batch()

    main, startup, loss, _, mse = _build_moe_net()
    pt.optimizer.SGD(learning_rate=0.1).minimize(
        loss, startup_program=startup)
    exe2 = pt.Executor(pt.CPUPlace())
    exe2.run(startup)
    _tie_experts(exe2.scope)
    single = [exe2.run(main, feed=feed, fetch_list=[loss, mse])
              for _ in range(4)]
    single_mse = [float(s[1]) for s in single]
    single = [float(s[0]) for s in single]

    main2, startup2, loss2, _, mse2 = _build_moe_net()
    pt.optimizer.SGD(learning_rate=0.1).minimize(
        loss2, startup_program=startup2)
    specs = ExpertParallelTranspiler().transpile(main2, ep_degree=4)
    assert set(specs) == {"moe.w1", "moe.w2"}
    mesh = make_mesh((4,), ("expert",))
    exe3 = pt.Executor(pt.CPUPlace(), mesh=mesh)
    exe3.run(startup2)
    _tie_experts(exe3.scope)
    sharded, sharded_mse = [], []
    for _ in range(4):
        lv, mv = exe3.run(main2, feed=feed, fetch_list=[loss2, mse2])
        # each shard reports its LOCAL mean over its batch slice; the
        # global value is their mean (equal shard sizes)
        sharded.append(float(np.asarray(lv).mean()))
        sharded_mse.append(float(np.asarray(mv).mean()))
    # the task loss matches tightly; the aux regularizer is computed
    # over LOCAL token sets (nonlinear in the set — the per-device aux
    # of real Switch training), so the total only matches loosely
    np.testing.assert_allclose(sharded_mse, single_mse, rtol=1e-3)
    np.testing.assert_allclose(sharded, single, rtol=2e-2)


def test_moe_trains_and_balances():
    """untied experts: loss decreases and the aux loss keeps routing
    from collapsing (all experts get traffic by the end)."""
    main, startup, loss, _, _m = _build_moe_net(cf=2.0)
    pt.optimizer.Adam(learning_rate=0.01).minimize(
        loss, startup_program=startup)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    feed = _batch(32)
    seen = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
            for _ in range(30)]
    assert seen[-1] < seen[0] * 0.9, (seen[0], seen[-1])


def test_transpiler_rejects_bad_configs():
    from paddle_tpu.transpiler import ExpertParallelTranspiler
    main, startup, loss, _, mse = _build_moe_net()
    with pytest.raises(Exception, match="not divisible"):
        ExpertParallelTranspiler().transpile(main, ep_degree=3)
    pt.reset_default_programs()
    with pt.program_guard(pt.default_main_program(),
                          pt.default_startup_program()):
        x = layers.data("x", shape=[D], dtype="float32")
        layers.fc(x, size=2)
    with pytest.raises(Exception, match="moe_ffn"):
        ExpertParallelTranspiler().transpile(
            pt.default_main_program(), ep_degree=2)
